"""Runtime sanitizers: buffer-lifetime and lock-discipline checking
(ISSUE 14 tentpoles b/c — the ASan/TSan lineage, sized for this
runtime's two recurring bug classes).

Every generation of this codebase has re-found the same two hazards by
hand: **use-after-donate** on device buffers (the PR 2 donated-husk
flush protocol, PR 8's guard-trip-on-consumed-buffers, PR 10's k-stale
reads racing the optimize block's donated params, PR 11's KV-pool
rebind contract) and **lock-discipline bugs** (the PR 6/13 reentrant-
lock fixes for signal-handler flight dumps).  This module makes both
checked artifacts instead of review-time folklore:

- ``FLAGS_sanitizer=buffers`` (or ``all``): every donation site swaps
  the scope slot that aliased the consumed buffer to a
  :class:`PoisonedHusk` — any host access before the re-bind raises
  :class:`BufferLifetimeError` naming the var, the donating dispatch
  (op), the step, and the site, instead of a bare jax "Array has been
  deleted".  Donation bumps a per-(scope, var) generation epoch;
  re-binding (``scope.set`` / ``sync_scope``) installs the fresh
  buffer over the husk.  :class:`BufferEpochGuard` applies the same
  contract to non-scope state (the serving KV page pool).
- ``FLAGS_sanitizer=locks`` (or ``all``): :func:`make_lock` returns an
  :class:`InstrumentedLock` recording per-thread acquisition order
  into a process lock graph; an order inversion (A->B somewhere,
  B->A elsewhere — a latent deadlock), a non-reentrant re-acquisition
  (a certain deadlock, raised as :class:`LockDisciplineError` instead
  of hanging), and a non-reentrant lock marked signal-handler-
  reachable (the flight.dump invariant) are all recorded and reported
  as one ranked ``lockgraph_<pid>.json`` artifact.

Disabled cost: the hot-path guard is ONE module-attribute read
(``_BUFFERS_ON`` / ``_LOCKS_ON``, mirrored from the flag by a
FLAGS.watch hook) — gated < 2% of a prepared step by
tools/telemetry_overhead.py.  ``make_lock`` with the lock sanitizer
off returns a plain ``threading.Lock``/``RLock``: zero per-acquire
overhead in production.

Every trip increments ``sanitizer_trips_total`` and — when
``FLAGS_telemetry_dump_dir`` is configured — leaves one flight-recorder
dump (the tools/fault_matrix.py 'sanitizer' preset asserts both
artifacts).
"""
from __future__ import annotations

import json
import os
import threading
import weakref

from .flags import FLAGS

__all__ = [
    "BufferEpochGuard", "BufferLifetimeError", "InstrumentedLock",
    "LockDisciplineError", "PoisonedHusk", "buffer_epoch", "buffers_on",
    "disabled_probe", "is_husk", "locks_on", "make_condition",
    "make_event", "make_lock", "poison_donated",
    "probe_signal_reentrancy", "reset_lock_graph", "trip",
    "weaver_on", "weaver_probe", "weaver_yield", "write_lockgraph",
]

# hot-path mirrors of FLAGS_sanitizer — the disabled path reads exactly
# one of these per guarded site (the telemetry_overhead.py contract)
_BUFFERS_ON = False
_LOCKS_ON = False
_WEAVER_ON = False


def _sync_mode(value):
    global _BUFFERS_ON, _LOCKS_ON, _WEAVER_ON
    mode = str(value or "off")
    # weaver implies the buffer checks: the schedule explorer's
    # scenarios rely on use-after-donate / double-free trips being the
    # observable failure
    _BUFFERS_ON = mode in ("buffers", "all", "weaver")
    _LOCKS_ON = mode in ("locks", "all")
    _WEAVER_ON = mode == "weaver"


FLAGS.watch("sanitizer", _sync_mode)


def buffers_on():
    return _BUFFERS_ON


def locks_on():
    return _LOCKS_ON


def weaver_on():
    return _WEAVER_ON


def disabled_probe(iters):
    """Execute exactly the per-site disabled-path work ``iters`` times
    (one module-attribute read + branch) — micro-timed by the
    tools/telemetry_overhead.py sanitizer gate."""
    n = 0
    for _ in range(iters):
        if _BUFFERS_ON:
            n += 1
    return n


def weaver_probe(iters):
    """The weaver hook's disabled-path work ``iters`` times (one
    module-attribute read + branch, identical to :func:`weaver_yield`
    with the mode off) — micro-timed by the telemetry_overhead.py
    weaver gate."""
    n = 0
    for _ in range(iters):
        if _WEAVER_ON:
            n += 1
    return n


def _trips_counter():
    from paddle_tpu.observability import metrics
    return metrics.counter(
        "sanitizer_trips_total",
        "buffer-lifetime and lock-discipline sanitizer trips")


def _note_trip(reason, blocked):
    """Counter + (dump-dir-gated) flight artifact for one trip.  Never
    raises: the diagnostic must not mask the error it annotates."""
    try:
        _trips_counter().inc()
    except Exception:
        pass
    try:
        if FLAGS.telemetry_dump_dir:
            from paddle_tpu.observability import flight
            flight.dump(reason, blocked=blocked)
    except Exception:
        pass


# ---------------------------------------------------------------------------
# Buffer sanitizer
# ---------------------------------------------------------------------------

class BufferLifetimeError(RuntimeError):
    """A host access touched a buffer after its donation and before its
    re-bind.  Names the var, the donating dispatch (op), the step, and
    the dispatch site — the four facts every one of the PR 2/8/10/11
    postmortems had to reconstruct by hand."""

    def __init__(self, var, op=None, step=None, site=None, epoch=None):
        self.var = var
        self.op = op
        self.step = step
        self.site = site
        self.epoch = epoch
        super().__init__(
            "use-after-donate: the buffer of %r was donated to dispatch"
            " %r (step %s, site %s, epoch %s) and has not been re-bound"
            " — read it through Scope.find_var / after sync_scope() or"
            " the apply commits, or copy the value before the step"
            % (var, op, step, site, epoch))


def trip(var, op=None, step=None, site=None, epoch=None):
    """Record one buffer trip (counter + flight dump) and raise the
    named :class:`BufferLifetimeError`."""
    err = BufferLifetimeError(var, op=op, step=step, site=site,
                              epoch=epoch)
    _note_trip("sanitizer:buffer:%s" % var,
               {"var": var, "op": op, "step": step, "site": site,
                "epoch": epoch})
    raise err


class PoisonedHusk:
    """The slot-filler a donation leaves behind: any host read raises
    :class:`BufferLifetimeError` naming the donation that consumed the
    buffer.  ``is_deleted()`` answers True so the executor's existing
    consumed-buffer checks keep their semantics."""

    __slots__ = ("var", "op", "step", "site", "epoch")

    def __init__(self, var, op=None, step=None, site=None, epoch=0):
        object.__setattr__(self, "var", var)
        object.__setattr__(self, "op", op)
        object.__setattr__(self, "step", step)
        object.__setattr__(self, "site", site)
        object.__setattr__(self, "epoch", epoch)

    def is_deleted(self):
        return True

    def _trip(self):
        trip(self.var, op=self.op, step=self.step, site=self.site,
             epoch=self.epoch)

    # every host materialization path lands on one of these
    def __array__(self, dtype=None, copy=None):
        self._trip()

    def __float__(self):
        self._trip()

    def __int__(self):
        self._trip()

    def __len__(self):
        self._trip()

    def __iter__(self):
        self._trip()

    def __getitem__(self, idx):
        self._trip()

    def __getattr__(self, name):
        # duck-typing probes on private/dunder names degrade to the
        # normal AttributeError (hasattr() checks, pickling probes);
        # any public data access is a real read — trip with the story
        if name.startswith("_"):
            raise AttributeError(name)
        self._trip()

    def __repr__(self):
        return ("<PoisonedHusk %r donated by %r step %s site %s>"
                % (self.var, self.op, self.step, self.site))


def is_husk(v):
    return type(v) is PoisonedHusk


def buffer_epoch(scope, name):
    """Donation generation of ``name`` in ``scope``'s chain (0 = never
    donated under the sanitizer)."""
    s = scope.find_scope_of(name) if hasattr(scope, "find_scope_of") \
        else scope
    while s is not None:
        epochs = getattr(s, "_buffer_epochs", None)
        if epochs and name in epochs:
            return epochs[name]
        s = getattr(s, "_parent", None)
    return 0


def poison_donated(scope, consumed, op=None, step=None, site=None,
                   only_dead=False):
    """Swap every scope slot that still aliases a just-donated dispatch
    argument to a :class:`PoisonedHusk` (buffers mode; no-op
    otherwise).  ``consumed`` maps var name -> the argument handed to
    the dispatch.  A slot is poisoned when it holds that same object,
    or already holds a consumed (deleted) jax array — never when a
    fresh value was written over it.  ``only_dead`` restricts the swap
    to provably-consumed buffers (the failed-dispatch path: a TRACE
    failure consumes nothing, and identity alone cannot tell it from a
    failed execute).  The swap deliberately does NOT bump the scope
    write version: a husk is an absence marker, not a write, and must
    not trigger the prepared executor's external-write re-stage."""
    if not _BUFFERS_ON or not consumed:
        return 0
    n = 0
    for name, arg in consumed.items():
        s = scope.find_scope_of(name)
        if s is None:
            continue
        cur = s._vars.get(name)
        if cur is None or type(cur) is PoisonedHusk:
            continue
        if only_dead or cur is not arg:
            fn = getattr(cur, "is_deleted", None)
            try:
                dead = callable(fn) and fn()
            except Exception:
                dead = False
            if not dead:
                continue
        epochs = getattr(s, "_buffer_epochs", None)
        if epochs is None:
            epochs = s._buffer_epochs = {}
        epochs[name] = epochs.get(name, 0) + 1
        s._vars[name] = PoisonedHusk(name, op=op, step=step, site=site,
                                     epoch=epochs[name])
        n += 1
    return n


class BufferEpochGuard:
    """The donation/re-bind contract for device state that lives
    OUTSIDE a Scope (the serving KV page pool, ISSUE 11): the owner
    brackets every donating dispatch with ``begin()``/``rebind()``,
    and readers validate a previously-observed ``epoch`` (or mid-
    dispatch access) through ``check()`` — a stale epoch means the
    pages the reader is holding were donated and re-bound under it."""

    def __init__(self, name):
        self.name = name
        self.epoch = 0
        self._in_flight = None   # (op, step) while a dispatch owns it

    def begin(self, op, step=None):
        if _BUFFERS_ON:
            self._in_flight = (op, step)

    def rebind(self):
        if self._in_flight is not None or _BUFFERS_ON:
            self.epoch += 1
            self._in_flight = None

    def check(self, epoch=None, var=None):
        """Validate a read of the guarded state.  Raises
        :class:`BufferLifetimeError` when a donating dispatch is in
        flight, or when ``epoch`` (from a prior read) is stale."""
        if not _BUFFERS_ON:
            return
        name = var or self.name
        if self._in_flight is not None:
            op, step = self._in_flight
            trip(name, op=op, step=step,
                 site="%s (dispatch in flight)" % self.name,
                 epoch=self.epoch)
        if epoch is not None and epoch != self.epoch:
            trip(name, op="rebind", step=None,
                 site="%s (stale epoch %s, current %s)"
                      % (self.name, epoch, self.epoch),
                 epoch=self.epoch)


# ---------------------------------------------------------------------------
# Lock sanitizer
# ---------------------------------------------------------------------------

class LockDisciplineError(RuntimeError):
    """A lock acquisition that would deadlock (non-reentrant
    re-acquisition by the holding thread) — raised instead of hanging,
    naming the lock and thread."""


class _LockGraph:
    """Process-wide acquisition-order graph.  Edges are (held ->
    acquired) lock-name pairs; an inversion is an (A,B) pair observed
    in both directions.  Guarded by a RAW lock (never instrumented —
    the sanitizer must not sanitize itself)."""

    def __init__(self):
        self._mu = threading.Lock()
        self.reset()

    def reset(self):
        with self._mu:
            self.edges = {}        # (a, b) -> count
            self.inversions = {}   # (a, b) sorted pair -> count
            self.violations = []   # [{kind, lock, thread, note}]
            self.locks = []        # weakrefs of InstrumentedLock

    def register(self, lock):
        with self._mu:
            self.locks = [r for r in self.locks if r() is not None]
            self.locks.append(weakref.ref(lock))

    def live_locks(self):
        with self._mu:
            return [l for l in (r() for r in self.locks)
                    if l is not None]

    def note_edge(self, a, b):
        if a == b:
            return
        first = False
        with self._mu:
            k = (a, b)
            self.edges[k] = self.edges.get(k, 0) + 1
            if (b, a) in self.edges:
                pair = (min(a, b), max(a, b))
                first = pair not in self.inversions
                self.inversions[pair] = self.inversions.get(pair, 0) + 1
        if first:
            self._on_inversion((a, b))

    def note_violation(self, kind, lock, note=""):
        with self._mu:
            self.violations.append({
                "kind": kind, "lock": lock,
                "thread": threading.current_thread().name,
                "note": note})

    def _on_inversion(self, pair):
        _note_trip("sanitizer:lockorder:%s->%s" % pair,
                   {"locks": list(pair), "kind": "order-inversion"})
        try:
            if FLAGS.telemetry_dump_dir:
                write_lockgraph(FLAGS.telemetry_dump_dir)
        except Exception:
            pass

    def cycles(self):
        """Simple cycles in the acquisition graph (length <= 6),
        ranked by weight = the rarest edge on the cycle — the cycle a
        human should look at first is the one every thread keeps
        re-proving."""
        with self._mu:
            edges = dict(self.edges)
        adj = {}
        for (a, b) in edges:
            adj.setdefault(a, []).append(b)
        found, seen = [], set()

        def dfs(root, node, path):
            if len(path) > 6:
                return
            for nxt in adj.get(node, ()):
                if nxt == root:
                    cyc = path[:]
                    key = frozenset(cyc)
                    if key not in seen:
                        seen.add(key)
                        w = min(edges[(cyc[i], cyc[(i + 1) % len(cyc)])]
                                for i in range(len(cyc)))
                        found.append({"locks": cyc, "count": w})
                elif nxt not in path:
                    dfs(root, nxt, path + [nxt])

        for root in sorted(adj):
            dfs(root, root, [root])
        found.sort(key=lambda c: (-c["count"], len(c["locks"])))
        return found

    def report_dict(self):
        with self._mu:
            edges = [{"from": a, "to": b, "count": c}
                     for (a, b), c in sorted(self.edges.items())]
            inversions = [{"locks": list(p), "count": c}
                          for p, c in sorted(self.inversions.items(),
                                             key=lambda kv: -kv[1])]
            violations = list(self.violations)
        return {
            "kind": "lockgraph",
            "pid": os.getpid(),
            "mode": str(FLAGS.sanitizer),
            "edges": edges,
            "cycles": self.cycles(),
            "inversions": inversions,
            "violations": violations,
        }


GRAPH = _LockGraph()

_HELD = threading.local()


def _held_stack():
    st = getattr(_HELD, "stack", None)
    if st is None:
        st = _HELD.stack = []
    return st


class InstrumentedLock:
    """A lock that records its place in the process acquisition order.

    - every acquire with other locks held adds (held -> this) edges;
      an edge pair observed in both directions is an order inversion
      (latent deadlock) — recorded, counted, and written to the
      lockgraph artifact;
    - re-acquiring a NON-reentrant lock on the holding thread is a
      certain deadlock: recorded and raised as
      :class:`LockDisciplineError` instead of hanging;
    - ``signal_safe`` marks locks reachable from signal handlers (the
      metrics/flight/slo invariant from PRs 6 and 13): such a lock
      must be reentrant — a non-reentrant one is a violation at
      creation, before any signal can prove it the hard way."""

    def __init__(self, name, reentrant=False, signal_safe=False):
        self.name = name
        self.reentrant = bool(reentrant)
        self.signal_safe = bool(signal_safe)
        self._inner = threading.RLock() if reentrant else threading.Lock()
        GRAPH.register(self)
        if self.signal_safe and not self.reentrant:
            GRAPH.note_violation(
                "signal-unsafe-lock", name,
                "a signal-handler-reachable lock must be reentrant: a "
                "signal landing on the holding thread would deadlock "
                "inside its own diagnostic (the flight.dump invariant)")
            _note_trip("sanitizer:lock:%s" % name,
                       {"lock": name, "kind": "signal-unsafe-lock"})

    def _is_owned(self):
        # threading.Condition probes this when handed a foreign lock
        return any(h is self for h in _held_stack())

    def acquire(self, blocking=True, timeout=-1):
        st = _held_stack()
        held_here = any(h is self for h in st)
        if held_here and not self.reentrant:
            GRAPH.note_violation(
                "non-reentrant-reacquire", self.name,
                "the holding thread re-acquired a non-reentrant lock — "
                "a certain deadlock, averted by the sanitizer")
            _note_trip("sanitizer:lock:%s" % self.name,
                       {"lock": self.name,
                        "kind": "non-reentrant-reacquire"})
            raise LockDisciplineError(
                "thread %r re-acquired non-reentrant lock %r it already "
                "holds — this deadlocks without the sanitizer; make the "
                "lock reentrant or restructure the call path"
                % (threading.current_thread().name, self.name))
        if not held_here:
            for h in st:
                GRAPH.note_edge(h.name, self.name)
        if blocking:
            ok = self._inner.acquire(True, timeout)
        else:   # threading forbids a timeout on a non-blocking acquire
            ok = self._inner.acquire(False)
        if ok:
            st.append(self)
        return ok

    def release(self):
        st = _held_stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i] is self:
                del st[i]
                break
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return "<InstrumentedLock %r%s%s>" % (
            self.name, " reentrant" if self.reentrant else "",
            " signal_safe" if self.signal_safe else "")


def make_lock(name, reentrant=False, signal_safe=False):
    """The one lock constructor sanitizer-adopting subsystems use
    (observability/, distributed/rpc.py, serving/).  Lock sanitizer
    off: a plain ``threading.Lock``/``RLock`` — zero per-acquire cost.
    On (``FLAGS_sanitizer=locks|all`` at creation time): an
    :class:`InstrumentedLock` feeding the process lock graph.
    ``signal_safe`` documents (and, instrumented, enforces) the
    flight.dump invariant: the lock is taken inside signal handlers
    and must be reentrant.  Under ``FLAGS_sanitizer=weaver`` with a
    schedule-exploration run active (analysis/weaver.py), the lock is
    a WeaverLock: every acquire/release is a scheduling decision."""
    if _WEAVER_ON:
        lk = _weaver().weaver_lock(name, reentrant=reentrant)
        if lk is not None:
            return lk
    if not _LOCKS_ON:
        return threading.RLock() if reentrant else threading.Lock()
    return InstrumentedLock(name, reentrant=reentrant,
                            signal_safe=signal_safe)


def _weaver():
    from paddle_tpu.analysis import weaver
    return weaver


def make_event(name):
    """The event analog of :func:`make_lock`: a plain
    ``threading.Event`` unless a weaver run is active, in which case a
    WeaverEvent whose wait/set are scheduling decisions (a timed wait
    never sleeps — the timeout is virtual)."""
    if _WEAVER_ON:
        ev = _weaver().weaver_event(name)
        if ev is not None:
            return ev
    return threading.Event()


def make_condition(name, lock=None):
    """The condition analog of :func:`make_lock`.  ``lock`` may be a
    lock previously returned by :func:`make_lock` (the
    Condition-over-my-mutex idiom); instrumentation rides whatever
    that lock already is.  Under an active weaver run this returns a
    WeaverCondition whose wait/notify are scheduling decisions."""
    if _WEAVER_ON:
        cv = _weaver().weaver_condition(name, lock)
        if cv is not None:
            return cv
    return threading.Condition(lock)


def weaver_yield(site):
    """A pure scheduling decision at a queue/wire boundary (fastwire
    frame hand-off, request-queue put/get, the pserver apply window).
    Off path: ONE module-attribute read — gated like every sanitizer
    hook by tools/telemetry_overhead.py."""
    if _WEAVER_ON:
        _weaver().maybe_yield(site)


def probe_signal_reentrancy():
    """Actively prove the flight.dump invariant over every live
    instrumented ``signal_safe`` lock: acquire it, then re-acquire
    non-blocking on the same thread (what a signal-handler dump does
    mid-``observe``).  A lock that refuses is recorded as a violation.
    Returns the violations found by this probe."""
    out = []
    for lock in GRAPH.live_locks():
        if not lock.signal_safe:
            continue
        if not lock._inner.acquire(False):
            continue   # contended right now; nothing to prove safely
        try:
            if lock.reentrant:
                ok = lock._inner.acquire(False)
                if ok:
                    lock._inner.release()
                else:   # an RLock never refuses its holder
                    ok = False
            else:
                ok = False
            if not ok:
                v = {"kind": "signal-reentrancy-probe",
                     "lock": lock.name,
                     "thread": threading.current_thread().name,
                     "note": "re-acquisition on the holding thread "
                             "failed: a signal-handler dump here would "
                             "deadlock"}
                GRAPH.note_violation(v["kind"], v["lock"], v["note"])
                out.append(v)
        finally:
            lock._inner.release()
    return out


def write_lockgraph(directory=None):
    """Write the ranked ``lockgraph_<pid>.json`` artifact (cycles
    first, then raw inversions, violations, and the full edge list);
    returns the path, or None when the write failed (best-effort, like
    every diagnostic artifact)."""
    try:
        import tempfile

        directory = (directory or FLAGS.telemetry_dump_dir
                     or tempfile.gettempdir())
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, "lockgraph_%d.json" % os.getpid())
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(GRAPH.report_dict(), f, indent=1)
        os.replace(tmp, path)
        return path
    except Exception:
        return None


def reset_lock_graph():
    """Drop all recorded edges/violations (tests)."""
    GRAPH.reset()
