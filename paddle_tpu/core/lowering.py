"""Block -> JAX function lowering.

This replaces the reference's per-op kernel dispatch loop
(framework/executor.cc:332-345 + operator.cc:605 RunImpl): instead of running
one CUDA kernel per op with a Scope of mutable tensors, an entire BlockDesc is
traced into ONE pure JAX function (reads = arguments, writes = results) and
compiled by XLA for the target backend.  XLA then does the fusion, layout
assignment and scheduling that the reference implements by hand
(operators/math/*, details/threaded_ssa_graph_executor.cc).

The imperative Scope semantics are recovered by functionalization: variables
read before written become function inputs; persistable variables that any op
writes (e.g. sgd's in-place param update) become function outputs that the
executor writes back to the Scope, with input buffers donated so XLA updates
in place.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import get_op_info
from .types import proto_to_np_dtype, VarKind

# Ops the trace skips entirely; the Executor handles them on the host.
# (reference: feed_fetch_method.cc, save/load ops run as normal kernels —
# here they are host-side by construction.)
EMPTY_VAR = ""

# --------------------------------------------------------------------------
# bf16 mixed precision (the TPU-native analog of the reference's
# paddle/contrib/float16/float16_transpiler.py): instead of rewriting the
# desc with cast ops and fp16 weight copies, the lowering autocasts
# MXU-bound ops to bfloat16 at trace time and XLA fuses the casts into the
# matmul/conv kernels.  Params and the desc stay float32 (master weights);
# the vjp of the cast gives fp32 parameter gradients automatically, and
# bf16's fp32-sized exponent means no loss scaling is needed.
# --------------------------------------------------------------------------

# MXU-bound ops: compute in bf16 (inputs cast fp32 -> bf16).
# elementwise_add is here for bias/residual adds: without it the fp32
# bias promotes every post-matmul activation back to fp32 and the
# network's activation traffic loses the bf16 bandwidth win.
AMP_WHITE = frozenset({
    "mul", "matmul", "conv2d", "conv3d", "conv2d_transpose",
    "depthwise_conv2d", "sequence_conv", "elementwise_add",
})
# Numerically sensitive ops: always compute in fp32 (inputs cast back).
# layer_norm is NOT here: its lowering computes statistics in f32
# internally while keeping the normalized output in the input dtype, so
# transformer activation chains stay bf16.  batch_norm does the same
# internally and FLAGS.bn_bf16 opts it out of this list (round-4
# re-measurement, PROFILE_r04.md: bf16-out BN is +0.9% on
# ResNet-50/v5e — the earlier "fp32 BN fuses better" claim was stale);
# it stays listed by default for reference-parity numerics.
AMP_BLACK = frozenset({
    "softmax", "softmax_with_cross_entropy", "cross_entropy", "mean",
    "reduce_mean", "reduce_sum", "sum", "batch_norm",
    "exp", "log", "square_error_cost", "l2_normalize", "norm",
    "sigmoid_cross_entropy_with_logits",
})

# AMP_WHITE plus the fused ops that absorb whitelisted chains (their
# _amp_cast_ins branches take the casts slot-for-slot): the ops whose
# outputs are bf16 activations under AMP.  The ONE definition shared by
# the numerics watch list (observability/numerics.select_watched) and
# the static numerics checker (analysis/checkers.py) — a new fused op
# added here is covered by both at once.
AMP_AUTOCAST_OPS = AMP_WHITE | frozenset({
    "fused_conv2d_bn_act", "fused_matmul_bias_act",
    "fused_qkv_matmul", "fused_add_ln",
})


_OPTIMIZE_ROLE = 0x0002  # framework.OpRole.Optimize


def _amp_cast_ins(op_type, ins, role=0):
    """Autocast an op's inputs per the white/black lists; everything else
    runs in whatever dtype flows in (XLA fuses the casts)."""
    if role & _OPTIMIZE_ROLE:
        # parameter updates / lr arithmetic stay fp32 (master weights)
        return ins
    if op_type == "fused_conv2d_bn_act":
        # MXU operands (Input/Filter/Residual) go bf16; the BN parameter
        # slots (Scale/Bias/Mean/Variance) keep their stored dtype — the
        # lowering computes statistics from f32 partials regardless
        mxu_slots = ("Input", "Filter", "Residual")

        def conv_slot(slot, x):
            if slot in mxu_slots and x is not None and \
                    getattr(x, "dtype", None) == jnp.float32:
                return x.astype(jnp.bfloat16)
            return x

        return Ins({s: [conv_slot(s, v) for v in vs]
                    for s, vs in ins._d.items()})
    if op_type == "fused_add_ln":
        # mirror the unfused chain under AMP: the residual add is
        # whitelisted (activation streams go bf16) while layer_norm
        # passes through — Scale/Bias keep their stored dtype and the
        # lowering computes statistics in f32 / applies the affine in
        # x.dtype, exactly like the layer_norm lowering
        stream_slots = ("X", "Y")

        def ln_slot(slot, x):
            if slot in stream_slots and x is not None and \
                    getattr(x, "dtype", None) == jnp.float32:
                return x.astype(jnp.bfloat16)
            return x

        return Ins({s: [ln_slot(s, v) for v in vs]
                    for s, vs in ins._d.items()})
    if op_type in AMP_WHITE or op_type in (
            "fused_matmul_bias_act", "fused_qkv_matmul"):
        # the fused matmul ops absorb whitelisted chains (mul +
        # elementwise_add bias/residual + act): every f32 operand goes
        # bf16, matching the unfused ops' casts slot for slot
        if op_type == "elementwise_add":
            # only activation-shaped adds (bias/residual): scalar or [1]
            # adds are lr-schedule / counter arithmetic and keep fp32
            x = ins.get("X")
            if x is None or getattr(x, "ndim", 0) < 2:
                return ins

        def conv(x):
            if x is not None and getattr(x, "dtype", None) == jnp.float32:
                return x.astype(jnp.bfloat16)
            return x
    elif op_type in AMP_BLACK:
        if op_type == "batch_norm":
            from paddle_tpu.core.flags import FLAGS
            if FLAGS.bn_bf16:
                # pass-through: the lowering computes statistics in f32
                # and applies the affine in x.dtype, so bf16 stays bf16
                return ins

        def conv(x):
            if x is not None and getattr(x, "dtype", None) == jnp.bfloat16:
                return x.astype(jnp.float32)
            return x
    else:
        return ins
    return Ins({s: [conv(v) for v in vs] for s, vs in ins._d.items()})


class Ins:
    """Read-only view of an op's input slots during lowering.

    ``ins[slot]`` -> the single value of a one-var slot;
    ``ins.list(slot)`` -> list (entries may be None for empty var names);
    ``ins.get(slot)`` -> single value or None.
    """

    __slots__ = ("_d",)

    def __init__(self, d):
        self._d = d

    def __getitem__(self, slot):
        v = self._d[slot]
        if len(v) != 1 or v[0] is None:
            raise ValueError("slot %r expected exactly one value, got %r" %
                             (slot, v))
        return v[0]

    def get(self, slot, default=None):
        v = self._d.get(slot)
        if not v or v[0] is None:
            return default
        return v[0]

    def list(self, slot):
        return self._d.get(slot, [])

    def has(self, slot):
        v = self._d.get(slot)
        return bool(v) and any(x is not None for x in v)

    def slots(self):
        return self._d.keys()


class _Counter:
    __slots__ = ("n",)

    def __init__(self):
        self.n = 0


class LoweringContext:
    """State threaded through the trace of one block (and its sub-blocks)."""

    def __init__(self, program, block_idx, env, base_key, mode="train",
                 counter=None):
        self.program = program
        self.block_idx = block_idx
        self.block = program.blocks[block_idx]
        self.env = env                  # name -> traced value
        self.base_key = base_key        # jax PRNG key (traced)
        self.mode = mode                # 'train' | 'test'
        self.mesh = None                # set by the executor when SPMD
        self.amp = bool(getattr(program, "amp_bf16", False))
        self._counter = counter or _Counter()

    def next_key(self):
        """Deterministic per-op PRNG key (replaces per-op curand states)."""
        self._counter.n += 1
        return jax.random.fold_in(self.base_key, self._counter.n)

    def seq_len_of(self, name):
        """Device-side [N] lengths of a ragged (LoD) value, or None when
        the value is dense.  Lengths enter as '<feed>@LEN' arrays and are
        propagated across shape-preserving ops by run_op."""
        return self.env.get(name + "@LEN")

    def set_seq_len(self, name, lengths):
        self.env[name + "@LEN"] = lengths

    def var_desc(self, name):
        blk = self.block
        while blk is not None:
            if name in blk.vars:
                return blk.vars[name]
            blk = (self.program.blocks[blk.parent_idx]
                   if blk.parent_idx >= 0 else None)
        return None

    def var_np_dtype(self, name):
        vd = self.var_desc(name)
        return np.float32 if vd is None else proto_to_np_dtype(vd.dtype)

    def sub_context(self, block_idx, env):
        """Context for tracing a sub-block (control flow bodies)."""
        sub = LoweringContext(self.program, block_idx, env, self.base_key,
                              self.mode, self._counter)
        sub.mesh = self.mesh
        return sub


def run_ops(ctx):
    """Trace every op of ctx.block in order against ctx.env."""
    for op in ctx.block.ops:
        run_op(ctx, op)


def run_op(ctx, op):
    info = get_op_info(op.type)
    if info.host_op:
        return
    ins = _gather_inputs(ctx.env, op)
    attrs = {k: a.value for k, a in op.attrs.items()}
    if ctx.amp:
        ins = _amp_cast_ins(op.type, ins, getattr(op, "role", 0))
    outs = info.lower(ctx, ins, attrs, op)
    _scatter_outputs(ctx.env, op, outs)
    if not getattr(info, "seq_aware", False):
        _propagate_seq_lens(ctx, op)


def _propagate_seq_lens(ctx, op):
    """Carry '<name>@LEN' across ops that keep the [N, T, ...] leading
    layout (embedding/fc/activation/elementwise chains), the padded-batch
    analog of the reference's ShareLoD in InferShape."""
    lens = None
    nested = []  # ('@LEN@j', value) for every nested level present
    src = None
    for n in op.input_arg_names():
        if n and n + "@LEN" in ctx.env:
            lens = ctx.env[n + "@LEN"]
            j = 1
            while n + "@LEN@%d" % j in ctx.env:
                nested.append(("@LEN@%d" % j, ctx.env[n + "@LEN@%d" % j]))
                j += 1
            src = ctx.env.get(n)
            break
    if lens is None or src is None or getattr(src, "ndim", 0) < 2:
        return
    lead = src.shape[:2]
    for n in op.output_arg_names():
        if not n or n + "@LEN" in ctx.env:
            continue
        val = ctx.env.get(n)
        if getattr(val, "ndim", 0) >= 2 and tuple(val.shape[:2]) == \
                tuple(lead):
            ctx.env[n + "@LEN"] = lens
            # nested levels carry only while the nested dims survive:
            # level j occupies dim j+1 of the padded layout
            for sfx, v in nested:
                j = int(sfx.rsplit("@", 1)[1])
                if getattr(val, "ndim", 0) >= j + 2 and \
                        val.shape[j + 1] == src.shape[j + 1]:
                    ctx.env[n + sfx] = v


def _gather_inputs(env, op):
    d = {}
    for slot, names in op.inputs.items():
        vals = []
        for n in names:
            if n == EMPTY_VAR:
                vals.append(None)
            elif n in env:
                vals.append(env[n])
            else:
                raise KeyError(
                    "op %s input %s/%s not found in environment" %
                    (op.type, slot, n))
        d[slot] = vals
    return Ins(d)


def _scatter_outputs(env, op, outs):
    outs = outs or {}
    for slot, names in op.outputs.items():
        if slot not in outs:
            if names and any(n != EMPTY_VAR for n in names):
                raise ValueError("op %s produced no value for output slot %s"
                                 % (op.type, slot))
            continue
        vals = outs[slot]
        if not isinstance(vals, (list, tuple)):
            vals = [vals]
        if len(vals) != len(names):
            raise ValueError(
                "op %s output slot %s: %d values for %d names" %
                (op.type, slot, len(vals), len(names)))
        for n, v in zip(names, vals):
            if n == EMPTY_VAR or v is None:
                continue
            env[n] = v


# ---------------------------------------------------------------------------
# Generic gradient lowering: jax.vjp of the forward lowering.
# ---------------------------------------------------------------------------

def generic_grad_lower(ctx, ins, attrs, op):
    """Lower ``<fwd>_grad`` by differentiating the forward lowering.

    Replaces hand-written grad kernels (reference operators/*_op.cc grad
    kernels): inside one compiled block XLA fuses the vjp just as well as a
    bespoke kernel, and correctness is guaranteed by construction.
    """
    fwd_type = op.type[: -len("_grad")]
    info = get_op_info(fwd_type)

    out_grad_slots = [s for s in ins.slots() if s.endswith("@GRAD")]
    fwd_output_slots = [s[: -len("@GRAD")] for s in out_grad_slots]
    fwd_input_slots = [s for s in ins.slots()
                       if not s.endswith("@GRAD") and s not in fwd_output_slots]

    # Differentiable leaf positions, read off the grad op's own outputs:
    # slot "X@GRAD" name list parallels the forward slot "X" name list, with
    # "" holes for non-differentiable / pruned entries.
    wrt = []  # [(fwd_slot, index)]
    for gslot, names in op.outputs.items():
        base = gslot[: -len("@GRAD")]
        for i, n in enumerate(names):
            if n != EMPTY_VAR:
                wrt.append((base, i))
    if not wrt:
        return {}

    const_ins = {s: list(ins.list(s)) for s in fwd_input_slots}
    primals = {}
    for slot, i in wrt:
        primals[(slot, i)] = const_ins[slot][i]

    # Forward lowering must be deterministic under re-trace; stateful ops
    # (dropout &c.) register custom grad lowerings instead.
    sub_ctx = ctx  # shares the key counter; deterministic ops ignore it

    # View exposing the forward op's input names (slots the grad op shares)
    # so lowerings that consult names — e.g. sequence ops reading
    # '<input>@LEN' — behave identically under differentiation.
    fwd_op_view = _FwdOpView(
        fwd_type, {s: list(op.inputs.get(s, [])) for s in fwd_input_slots},
        # grad-op inputs named after fwd output slots ARE the fwd outputs;
        # block-ops (conditional_block/recurrent) consult these names
        {s: list(op.inputs.get(s, [])) for s in fwd_output_slots})

    def fwd(p):
        merged = {s: list(v) for s, v in const_ins.items()}
        for (slot, i), val in p.items():
            merged[slot][i] = val
        merged_ins = Ins(merged)
        if ctx.amp:
            # same autocast as the forward trace: backward matmuls/convs
            # also run bf16, and vjp-of-cast returns fp32 param grads
            merged_ins = _amp_cast_ins(fwd_type, merged_ins,
                                       getattr(op, "role", 0))
        outs = info.lower(sub_ctx, merged_ins, dict(attrs), fwd_op_view)
        flat = {}
        for s in fwd_output_slots:
            v = outs.get(s)
            if not isinstance(v, (list, tuple)):
                v = [v]
            flat[s] = [x if _is_float(x) else None for x in v]
        return flat

    out_vals, vjp_fn = jax.vjp(fwd, primals)

    cots = {}
    for s in fwd_output_slots:
        gvals = ins.list(s + "@GRAD")
        cot_list = []
        for i, ov in enumerate(out_vals[s]):
            if ov is None:
                cot_list.append(None)
                continue
            g = gvals[i] if i < len(gvals) else None
            if g is None:
                g = jnp.zeros_like(ov)
            elif ctx.amp and g.dtype != ov.dtype:
                # mixed precision: a cotangent arriving from an op of a
                # different compute dtype (e.g. fp32 from a black-listed
                # consumer into a bf16 forward) — cast; XLA fuses it.
                # Outside AMP a mismatch is a real bug: let jax.vjp raise.
                g = g.astype(ov.dtype)
            cot_list.append(g)
        cots[s] = cot_list
    grads = vjp_fn(cots)[0]

    result = {}
    for gslot, names in op.outputs.items():
        base = gslot[: -len("@GRAD")]
        vals = []
        for i, n in enumerate(names):
            vals.append(grads.get((base, i)) if n != EMPTY_VAR else None)
        result[gslot] = vals
    return result


class _FwdOpView:
    """Minimal OpDesc stand-in handed to forward lowerings during vjp."""

    __slots__ = ("type", "inputs", "outputs")

    def __init__(self, type_, inputs, outputs=None):
        self.type = type_
        self.inputs = inputs
        self.outputs = outputs or {}

    def input_arg_names(self):
        return [n for ns in self.inputs.values() for n in ns]

    def output_arg_names(self):
        return [n for ns in self.outputs.values() for n in ns]


def _is_float(x):
    return x is not None and jnp.issubdtype(jnp.result_type(x), jnp.floating)


# ---------------------------------------------------------------------------
# Build-time shape inference by abstract evaluation.
# ---------------------------------------------------------------------------

# Sentinels for dynamic (-1) dims during eval_shape.  The inference
# runs TWICE, once per sentinel, and an output dim maps back to -1 only
# when it tracks BOTH substitutions — a model whose real dim happens to
# equal one sentinel (e.g. vocab_size=97) stays static because it holds
# its value in the other run (ISSUE 10 satellite; previously any output
# dim equal to 97 was silently declared dynamic).  Both values are
# prime so either run fails the same divisibility asserts, if any.
_FAKE_BATCH = 97
_FAKE_BATCH_ALT = 89


def infer_op_outputs(program, block, op, var_specs=None):
    """Infer output (shape, dtype) per output var via the op's registered
    ``infer_shape`` or, as the general fallback, jax.eval_shape over the
    lowering.

    Replaces reference per-op InferShape (operator.cc:606): abstract
    evaluation of the lowering needs no hand-written shape functions.
    Dynamic dims (-1) are substituted with a sentinel and mapped back;
    disambiguation against real dims that equal the sentinel is by a
    second evaluation under a different sentinel (see _FAKE_BATCH).

    ``var_specs`` ({name: (shape, np dtype)}) overrides the declared
    VarDesc of an input — the verifier's shape checker threads its own
    propagated env through a block this way, so a mismatch introduced
    AFTER build time (a transpiler rename) is still caught.

    A registered ``infer_shape(ins, attrs, op) -> {slot: specs}`` takes
    the same Ins view of jax.ShapeDtypeStruct specs the lowering would
    see and returns output specs without tracing — for host-adjacent or
    data-dependent ops where abstract evaluation is unavailable or wrong
    (see core/registry.py).
    """
    info = get_op_info(op.type)
    attrs = {k: a.value for k, a in op.attrs.items()}

    def build_specs(fake):
        specs = {}
        dynamic = False
        for slot, names in op.inputs.items():
            lst = []
            for n in names:
                if n == EMPTY_VAR:
                    lst.append(None)
                    continue
                override = var_specs.get(n) if var_specs else None
                if override is not None:
                    shape, dtype = override
                else:
                    vd = _find_var(program, block, n)
                    if vd is None:
                        raise KeyError(
                            "var %s not found for shape inference" % n)
                    shape, dtype = vd.shape, proto_to_np_dtype(vd.dtype)
                if any(d == -1 for d in shape):
                    dynamic = True
                shape = tuple(fake if d == -1 else d for d in shape)
                lst.append(jax.ShapeDtypeStruct(shape, dtype))
            specs[slot] = lst
        return specs, dynamic

    def run(specs):
        if callable(info.infer_shape):
            shaped = info.infer_shape(Ins(specs), attrs, op)
            return {slot: (list(v) if isinstance(v, (list, tuple))
                           else [v])
                    for slot, v in (shaped or {}).items()}

        def f(s):
            env = {}
            ctx = LoweringContext(program, block.idx, env,
                                  jax.random.PRNGKey(0), "train")
            outs = info.lower(ctx, Ins(s), attrs, op)
            norm = {}
            for slot, v in (outs or {}).items():
                norm[slot] = list(v) if isinstance(v, (list, tuple)) \
                    else [v]
            return norm

        return jax.eval_shape(f, specs)

    specs, dynamic = build_specs(_FAKE_BATCH)
    shaped = run(specs)
    shaped_alt = None
    if dynamic and any(
            _FAKE_BATCH in getattr(sd, "shape", ())
            for outs in shaped.values() for sd in outs
            if sd is not None):
        # second pass under the alternate sentinel, run ONLY when an
        # output dim actually equals the primary sentinel (for most
        # ops no output dim is 97 and there is nothing to
        # disambiguate): dims that moved 97 -> 89 in lockstep are
        # really the dynamic dim.  Any failure of the alternate
        # evaluation (an op with a genuine size constraint the other
        # sentinel violates) degrades to the single-sentinel mapping
        # rather than losing inference.
        try:
            shaped_alt = run(build_specs(_FAKE_BATCH_ALT)[0])
        except Exception:
            shaped_alt = None

    result = {}
    for slot, names in op.outputs.items():
        if slot not in shaped:
            continue
        alt_slot = shaped_alt.get(slot) if shaped_alt else None
        for i, (n, sd) in enumerate(zip(names, shaped[slot])):
            # non-dense outputs (SelectedRows grads, TensorArrays) have
            # no single (shape, dtype); their consumers validate them
            if n == EMPTY_VAR or sd is None or \
                    not hasattr(sd, "shape") or not hasattr(sd, "dtype"):
                continue
            alt = alt_slot[i] if alt_slot and i < len(alt_slot) else None
            alt_shape = tuple(alt.shape) if alt is not None and \
                hasattr(alt, "shape") and len(alt.shape) == len(sd.shape) \
                else None
            shape = []
            for j, d in enumerate(sd.shape):
                if not dynamic:
                    shape.append(d)       # no -1 inputs: nothing to map
                elif d == _FAKE_BATCH and (
                        alt_shape is None
                        or alt_shape[j] == _FAKE_BATCH_ALT):
                    shape.append(-1)
                else:
                    shape.append(d)
            result[n] = (tuple(shape), sd.dtype)
    return result


def _find_var(program, block, name):
    blk = block
    while blk is not None:
        if name in blk.vars:
            return blk.vars[name]
        blk = program.blocks[blk.parent_idx] if blk.parent_idx >= 0 else None
    return None
