"""Core Executor: runs a block of a ProgramDesc against a Scope.

Parity: reference framework/executor.cc:127 (Executor::Run / Prepare /
RunPreparedContext).  Two paths:

- **Compiled path** (the normal one): the block is functionalized and lowered
  to a single jitted XLA computation (see lowering.py), cached on
  (program uid+version, block, feed specs, fetch list, mode).  Persistable
  inputs that the block writes (optimizer in-place updates) are donated so
  XLA reuses their buffers — the analog of the reference's buddy-allocator
  reuse + in-place optimizer ops.
- **Interpreted path**: if host ops (save/load/print/readers/RPC) appear
  between device ops, ops run one-by-one eagerly — the "graceful fallback"
  for ops XLA cannot express.  Host ops at the head/tail of a block (feed /
  read / fetch) are peeled off and the middle still compiles.
"""
from __future__ import annotations

import sys
import time
import warnings

import jax
import numpy as np

from . import lowering
from . import sanitizer as _san
from .lod import LoDTensor
from .lowering import LoweringContext, run_ops, run_op
from .registry import get_op_info
from .scope import Scope
from .types import proto_to_np_dtype, VarKind

from .flags import FLAGS

from paddle_tpu.observability import metrics as _obs_metrics
from paddle_tpu.observability import numerics as _num
from paddle_tpu.observability.trace import TRACER as _TRC

# always-on metrics (one short lock per step — see
# tools/telemetry_overhead.py for the hot-path overhead gate); span
# tracing below is additionally gated on _TRC.on (FLAGS_telemetry)
_M_STEPS = _obs_metrics.counter(
    "executor_steps_total", "executor steps (run + run_prepared)")
_M_CACHE_HITS = _obs_metrics.counter(
    "compile_cache_hits_total", "compiled-entry cache hits")
_M_CACHE_MISSES = _obs_metrics.counter(
    "compile_cache_misses_total", "compiled-entry cache misses (builds)")
_M_FLUSHES = _obs_metrics.counter(
    "prepared_flushes_total",
    "PreparedProgram.sync_scope write-backs of device state")
_H_STEP_MS = _obs_metrics.histogram(
    "step_wall_ms",
    "per-step wall of traced executor steps (FLAGS_telemetry on)")


def _matmul_precision_ctx():
    """jax.default_matmul_precision(FLAGS.matmul_precision) when set —
    must wrap jit CALLS (the config participates in jax's jit cache and
    applies at (re)lowering time)."""
    import contextlib

    p = FLAGS.matmul_precision
    if p:
        return jax.default_matmul_precision(str(p))
    return contextlib.nullcontext()

class EOFException(Exception):
    """A program-level reader has no next batch (parity: the enforce
    the reference's read op raises at end-of-data — callers catch it
    and reset the reader, reader/read_op.cc)."""


LEN_SUFFIX = "@LEN"
# pad ragged batches' time dim up to a multiple of this so the number of
# distinct compiled shapes stays bounded (bucketing)
LOD_PAD_MULTIPLE = 8
# level-2 feeds also bucket the outer (sentence-count) dim
LOD_SEQ_PAD_MULTIPLE = 4


def _prepare_lod_feeds(feed):
    """LoDTensor feeds -> padded dense array + '<name>@LEN' lengths.
    Level-2 LoD pads to [N, S, W, ...] with '@LEN' = outer sentence
    lengths and '@LEN@1' = [N, S] inner sub-sequence lengths; deeper
    LoD generalizes recursively — one padded dim and one '@LEN@j'
    array per level (reference lod_tensor.h:58 depth-unbounded LoD)."""
    # hot-path fast exit: dense-only feeds (the overwhelmingly common
    # case in a training loop) skip the per-item padding scan entirely
    for v in feed.values():
        if isinstance(v, LoDTensor) and v.lod:
            break
    else:
        return feed

    for name, v in list(feed.items()):
        if not (isinstance(v, LoDTensor) and v.lod):  # dense rides along
            continue
        if len(v.lod) > 2:
            # level-k (k>=3): general recursive pad — outer ragged dims
            # bucket to LOD_SEQ_PAD_MULTIPLE, the innermost time dim to
            # LOD_PAD_MULTIPLE; '@LEN@j' carries level-j lengths
            # (reference lod_tensor.h:58 depth-unbounded LoD)
            k = len(v.lod)
            # padded fan-out per level: max segment length, bucketed
            max_dims = []
            for j in range(k):
                mult = LOD_PAD_MULTIPLE if j == k - 1 \
                    else LOD_SEQ_PAD_MULTIPLE
                mx = max(v.sequence_lengths(j), default=1)
                max_dims.append(-(-max(mx, 1) // mult) * mult)
            padded, lens = v.to_padded_klevel(max_dims=max_dims)
            feed[name] = padded
            feed[name + LEN_SUFFIX] = lens[0].astype(np.int32)
            for j in range(1, k):
                feed[name + LEN_SUFFIX + "@%d" % j] = \
                    lens[j].astype(np.int32)
            continue
        if len(v.lod) == 2:
            # bucket both ragged dims so compiled shapes stay bounded.
            # This is the FEED bridge (pad + expose '@LEN' outer and
            # '@LEN@1' inner lengths); sequence_pool/softmax/conv
            # consume '@LEN@1' and operate at the FINEST level
            # (ops/sequence.py _fold_level2, reference
            # lod_tensor.h:58-110 semantics).
            s_max = max((v.lod[0][i + 1] - v.lod[0][i]
                         for i in range(len(v.lod[0]) - 1)), default=1)
            w_max = max((v.lod[1][j + 1] - v.lod[1][j]
                         for j in range(len(v.lod[1]) - 1)), default=1)
            s_max = -(-max(s_max, 1) // LOD_SEQ_PAD_MULTIPLE) * \
                LOD_SEQ_PAD_MULTIPLE
            w_max = -(-max(w_max, 1) // LOD_PAD_MULTIPLE) * \
                LOD_PAD_MULTIPLE
            padded, outer, inner = v.to_padded_2level(
                max_seq=s_max, max_word=w_max)
            feed[name] = padded
            feed[name + LEN_SUFFIX] = outer.astype(np.int32)
            feed[name + LEN_SUFFIX + "@1"] = inner.astype(np.int32)
            continue
        lens = v.sequence_lengths(0)
        t = max(lens) if lens else 1
        t = -(-max(t, 1) // LOD_PAD_MULTIPLE) * LOD_PAD_MULTIPLE
        padded, lengths = v.to_padded(max_len=t)
        feed[name] = padded
        feed[name + LEN_SUFFIX] = lengths.astype(np.int32)
    return feed


def _tuning_fingerprint():
    try:
        from paddle_tpu import tuning
        return tuning.fingerprint()
    except Exception:
        return ("", 0, 0)


def _cache_key(program, block_id, feed_spec, fetch_list, mode,
               numerics=None):
    """The ONE compiled-entry cache key — shared by run()'s per-feed
    path and prepare(), so a prepared program and run() with the same
    signature reuse a single executable.  Trace-time flag reads are part
    of the key: toggling them must not hit a stale executable.
    ``numerics`` pins the health-fetch variant explicitly (the prepared
    path caches BOTH twins of one signature); None reads the flag."""
    return (program.uid, program.version, block_id, feed_spec,
            tuple(fetch_list), mode,
            bool(getattr(program, "amp_bf16", False)),
            bool(FLAGS.auto_layout),
            # read at trace time (_amp_cast_ins / conv2d lowering)
            bool(FLAGS.bn_bf16), bool(FLAGS.conv_nhwc),
            str(FLAGS.matmul_precision),
            # scheduler-flag experiments must recompile, never reuse a
            # stale executable (ISSUE 5 lever c; see flags.py
            # apply_xla_flags for the process-lifetime caveat)
            bool(FLAGS.xla_latency_hiding_scheduler),
            str(FLAGS.xla_extra_flags),
            # autotune-cache state (ISSUE 7): lowerings consult the
            # cache at trace time, so a re-tuned cache (new file, new
            # dir, or an in-process record()) must recompile
            _tuning_fingerprint(),
            # numerics observatory (ISSUE 8): any mode but 'off' adds
            # the fused health reduction as an extra step output —
            # toggling it must never serve an executable without (or
            # with) the fetch.  The plain twin of a health entry keys
            # identically to the flag-off build, so toggling the
            # observatory never recompiles the common executable.
            _num.trace_enabled() if numerics is None else bool(numerics))


class _CacheEntry:
    __slots__ = ("fn", "input_names", "persist_outs", "fetch_names",
                 "input_shardings", "jit_fn", "watched", "monitor")

    def __init__(self, fn, input_names, persist_outs, fetch_names,
                 input_shardings=None, jit_fn=None, watched=()):
        self.fn = fn
        self.input_names = input_names
        self.persist_outs = persist_outs
        self.fetch_names = fetch_names
        self.input_shardings = input_shardings
        self.jit_fn = jit_fn  # the raw jax.jit object (AOT lower/compile)
        # numerics observatory (ISSUE 8): names whose health stats ride
        # the step as an extra output when FLAGS_check_numerics is on;
        # the monitor owns the read-back cadence + escalation
        self.watched = tuple(watched)
        self.monitor = _num.HealthMonitor(self.watched, "executor.run") \
            if self.watched else None


def flush_prepared(scope, exclude=None):
    """sync_scope() every dirty prepared program registered on ``scope``
    or any ancestor (parity role: reference RunPreparedContext keeps
    scope authoritative between prepared runs; here state lives on
    device and this is the on-demand write-back)."""
    s = scope
    while s is not None:
        if getattr(s, "_prepared_registry", None):
            s.flush_prepared(exclude)
        s = s._parent


def seen_entry(scope, name):
    """(owning scope, write version) snapshot of ``name`` — the shared
    primitive of the external-write-wins protocol (PreparedProgram and
    PipelineProgram): record it when you read or install a value,
    compare later to tell your own writes apart from someone else's."""
    s = scope.find_scope_of(name)
    return (s, s._write_versions.get(name) if s is not None else None)


def seen_changed(scope, name, seen):
    """True when ``name`` was written since ``seen`` was recorded (or
    was never recorded): the scope's value wins over device state."""
    if seen is None:
        return True
    cur = seen_entry(scope, name)
    return cur[0] is not seen[0] or cur[1] != seen[1]


class PreparedShapeMismatch(ValueError):
    """A feed's shape drifted from an AOT (auto-layout) prepared
    signature — the caller should run() this batch or re-prepare."""


class PreparedProgram:
    """Reference Executor::Prepare + RunPreparedContext
    (framework/executor.cc:127): the per-step cost is dispatch, not
    re-analysis.  Owns the compiled entry plus a device-resident state
    map of every non-feed input and written persistable; the state is
    threaded step-to-step so donated parameter/optimizer buffers never
    round-trip through the Scope.  ``run_prepared`` does feed staging +
    one dispatch and returns fetches as UN-CONVERTED device arrays;
    ``sync_scope`` flushes the written persistables back on demand
    (called automatically by every run()/io-save path via
    ``flush_prepared`` and on context exit).

    Interleaving contract: every read path on the same scope — run(),
    the io save programs, and plain ``Scope.find_var`` — flushes this
    state first (Scope.flush_prepared), so readers never observe a
    stale value or a donated (invalidated) buffer; and any scope write
    bumps the scope's version counter, which makes the next
    ``run_prepared`` re-stage its state from the scope.  Per-name write
    versions tell our own sync-backs apart from external writes: a name
    someone else wrote always wins over our device copy.
    """

    def __init__(self, core, program, block_id, entry, scope, mode,
                 feed_specs, entry_health=None):
        self._core = core
        self._program = program
        self._block_id = block_id
        self._entry = entry
        # health-instrumented twin (ISSUE 8): same signature + state
        # contract, plus the packed health output; dispatched instead
        # of the plain entry on numerics cadence steps
        self._entry_health = entry_health
        self._scope = scope
        self._mode = mode
        self._feed_names = frozenset(feed_specs)
        self._program_version = program.version
        # AOT entries (auto-layout) executed for FIXED argument shapes:
        # a shape drift (final partial batch) must fail with guidance,
        # not a deep XLA mismatch.  jit entries are shape-polymorphic
        # (retrace per new shape) so no per-step check is paid there.
        self._fixed_shapes = None
        if entry.jit_fn is None and hasattr(feed_specs, "items"):
            self._fixed_shapes = {
                name: tuple(v.shape)
                for name, v in feed_specs.items() if v is not None}
        block = program.blocks[block_id]
        dev = core.place.jax_device()
        self._targets = []      # per input index: sharding/Format/device
        self._feed_dtypes = {}  # feed name -> np dtype for coercion
        self._state_targets = {}
        for i, name in enumerate(entry.input_names):
            target = (entry.input_shardings[i]
                      if entry.input_shardings is not None else dev)
            if target is None:
                target = dev
            self._targets.append(target)
            if name in self._feed_names:
                vd = block.find_var_recursive(name)
                self._feed_dtypes[name] = (proto_to_np_dtype(vd.dtype)
                                           if vd is not None else None)
            else:
                self._state_targets[name] = target
        self._state = {}
        self._seen = {}  # name -> (owning scope, write version) we read
        self._read_only = [n for n in self._state_targets
                           if n not in set(entry.persist_outs)]
        # numerics observatory (ISSUE 8): own monitor = own read-back
        # cadence per prepared program (the entries may be shared)
        self._monitor = _num.HealthMonitor(entry_health.watched,
                                           "step.prepared") \
            if entry_health is not None and entry_health.watched else None
        # another prepared program/pipeline may hold newer values for
        # the persistables we are about to stage
        flush_prepared(scope)
        self._refresh_from_scope()
        self._dirty = False
        self._scope_epoch = scope.chain_version()
        # register on every scope that OWNS one of our resident names
        # (plus the lookup root): a reader rooted at an ancestor that
        # holds the persistables must hit the registry even though it
        # never walks down to the training scope
        owners = {id(scope): scope}
        for name in list(self._state_targets) + list(entry.persist_outs):
            s = scope.find_scope_of(name)
            if s is not None:
                owners.setdefault(id(s), s)
        for s in owners.values():
            s.attach_prepared(self)

    @property
    def fetch_names(self):
        return self._entry.fetch_names

    @property
    def is_stale(self):
        """True once the program mutated after prepare() (its version
        bumped): the compiled entry no longer matches — sync_scope and
        re-prepare.  run_prepared refuses stale entries loudly."""
        return self._program.version != self._program_version

    def _refresh_from_scope(self):
        """Re-stage resident inputs from the scope (after a run()/load
        wrote new values).  device_put is a no-op for arrays already
        committed to their target.  Values are read via the owning
        scope's raw storage — callers flushed other prepared programs
        already, and the per-name write versions recorded here let
        sync_scope detect external writes later."""
        scope = self._scope
        local = getattr(scope, "_reader_batch_vars", ())
        for name, target in self._state_targets.items():
            s = scope.find_scope_of(name)
            if s is None:
                raise KeyError(name)
            v = s._vars[name]
            if _san.is_husk(v):
                # sanitizer husk: re-raise with the donation's full
                # story (var, op, step, site) instead of the generic
                # consumed-buffer message below
                v._trip()
            if callable(getattr(v, "is_deleted", None)) and \
                    v.is_deleted():
                # the buffer was donated and consumed — by a failed
                # step, or by training that never synced back before
                # this program was dropped: the VALUE is gone
                raise RuntimeError(
                    "persistable %r in the scope is a donated buffer "
                    "whose value was consumed (a failed prepared step, "
                    "or a PreparedProgram dropped without sync_scope); "
                    "restore it (io.load_persistables / a checkpoint) "
                    "before continuing" % name)
            self._state[name] = _put(v, target, local_rows=name in local)
            self._seen[name] = (s, s._write_versions.get(name))
        # write-only persistables are rebuilt by the next step; drop
        # stale copies so sync_scope can't resurrect them, but KEEP a
        # write-version baseline so an external write to them between
        # now and the next sync is still detected (scope wins)
        for name in self._entry.persist_outs:
            if name not in self._state_targets:
                self._state.pop(name, None)
                self._seen[name] = seen_entry(scope, name)

    def run_prepared(self, feed=None):
        """Feed staging + one dispatch.  Returns the fetch list as
        device arrays — host conversion is the CALLER's choice (defer
        np.asarray until the value is actually consumed).

        Telemetry: one step counter per COMPLETED step; with
        FLAGS_telemetry on, a 'step.prepared' span with 'step.feed' /
        'step.dispatch' phases and a step_wall_ms histogram
        observation.  A failed attempt records neither (the
        PreparedShapeMismatch fallback re-runs the step through run(),
        which does its own counting — inc-ing up front would count
        such a step twice).  Disabled cost: the counter inc plus one
        attribute read (the < 2% overhead gate in
        tools/telemetry_overhead.py)."""
        if not _TRC.on:
            out = self._run_prepared_impl(feed, None)
            _M_STEPS.inc()
            return out
        span = _TRC.begin("step.prepared")
        try:
            out = self._run_prepared_impl(feed, _TRC)
        except BaseException:
            # keep the trace evidence, but under a name the phase
            # table won't mix into real step stats
            span.name = "step.prepared.failed"
            raise
        finally:
            _TRC.end(span)
        _M_STEPS.inc()
        _H_STEP_MS.observe((span.t1 - span.t0) / 1e6)
        return out

    def _run_prepared_impl(self, feed, _tr):
        if self.is_stale:
            raise RuntimeError(
                "program mutated since prepare() (version %d -> %d): the "
                "compiled entry is stale — re-prepare" %
                (self._program_version, self._program.version))
        scope = self._scope
        # another prepared program (or pipeline) may hold newer values
        flush_prepared(scope, exclude=self)
        if scope.chain_version() != self._scope_epoch:
            # someone wrote the scope since our last sync.  Flush OUR
            # updates first: our written persistables in the scope are
            # older than the state (and may be donated husks) — syncing
            # makes the scope whole before we re-stage from it.
            if self._dirty:
                self.sync_scope()
            self._refresh_from_scope()
            self._scope_epoch = scope.chain_version()
        sp_feed = _tr.begin("step.feed") if _tr is not None else None
        feed = _prepare_lod_feeds(dict(feed or {}))
        if feed.keys() != self._feed_names:
            self._check_feed_names(feed)
        entry = self._entry
        state = self._state
        fixed = self._fixed_shapes
        args = []
        for i, name in enumerate(entry.input_names):
            # feed precedence for names both fed AND written by the
            # block, exactly like run(): the device copy of such a name
            # exists only for sync_scope, never shadows the feed
            if name in state and name not in self._feed_names:
                args.append(state[name])
                continue
            val = feed[name]
            if fixed is not None:
                exp = fixed.get(name)
                if exp is not None and tuple(np.shape(val)) != exp:
                    raise PreparedShapeMismatch(
                        "feed %r shape %s != prepared signature %s: "
                        "this entry was AOT-compiled for fixed shapes "
                        "(FLAGS.auto_layout) — re-prepare for the new "
                        "batch shape or use run()" %
                        (name, tuple(np.shape(val)), exp))
            dtype = self._feed_dtypes.get(name)
            if dtype is not None and not hasattr(val, "dtype"):
                val = np.asarray(val, dtype=dtype)
            args.append(_put(val, self._targets[i], local_rows=True))
        seed, counter = self._core._rng_counter(self._program, scope)
        if sp_feed is not None:
            _tr.end(sp_feed)
        # numerics (ISSUE 8): pick the health-instrumented twin on
        # cadence steps (bisect: every step), the plain executable
        # otherwise — both share the signature and state contract.
        # Bisect additionally snapshots the resident state BEFORE the
        # dispatch consumes the donated buffers: the forensic re-run of
        # a tripped step must start from the exact pre-step values (the
        # expensive debug tier; metrics/guard pay nothing here).
        snap = None
        use_health = self._monitor is not None and \
            self._monitor.want_health()
        if use_health:
            entry = self._entry_health
            if _num.effective_mode() == "bisect":
                snap = {name: _snapshot_value(v)
                        for name, v in self._state.items()}
        # buffer sanitizer (ISSUE 14): the dispatch donates the
        # device-resident persistables it overwrites.  On step 1 the
        # scope slots still alias these exact arrays; poisoning them
        # after the dispatch turns any host read that bypasses the
        # flush protocol into a named BufferLifetimeError instead of a
        # bare jax deleted-array error.  Later steps find the slots
        # already husked (or externally rewritten) and skip in O(1).
        donated_map = None
        if _san._BUFFERS_ON:
            # donated = resident INPUTS the block overwrites (the
            # _build donate_argnums set); a write-only persist_out is
            # rebuilt, not donated — poisoning it would husk the live
            # value sync_scope installed last flush
            donated_map = {n: state[n] for n in entry.persist_outs
                           if n in state and n in self._state_targets
                           and n not in self._feed_names}
            don_site = "prepared block %d of program %s" % (
                self._block_id, getattr(self._program, "uid", "?"))
        sp_disp = _tr.begin("step.dispatch") if _tr is not None else None
        try:
            out = entry.fn(tuple(args), seed, counter)
            if entry.watched:
                fetches, persists, health = out
            else:
                fetches, persists = out
            if sp_disp is not None:
                _tr.end(sp_disp)
        except Exception:
            if sp_disp is not None:
                _tr.end(sp_disp, args={"failed": True})
            if donated_map:
                # name the scope slots a failed EXECUTE consumed
                # (trace failures consume nothing: only_dead)
                _san.poison_donated(scope, donated_map,
                                    op="run_prepared",
                                    step=int(counter), site=don_site,
                                    only_dead=True)
            # an execute-time failure may have consumed the donated
            # inputs: drop exactly the deleted buffers so a finally/
            # context-exit sync installs only values that survived
            # (trace-time failures consume nothing and lose nothing)
            dead = False
            for name in list(state):
                v = state[name]
                if callable(getattr(v, "is_deleted", None)) \
                        and v.is_deleted():
                    del state[name]
                    self._seen.pop(name, None)
                    dead = True
            if dead:
                self._scope_epoch = None  # re-stage dropped names
            raise
        if donated_map:
            _san.poison_donated(scope, donated_map, op="run_prepared",
                                step=int(counter), site=don_site)
        for name, val in zip(entry.persist_outs, persists):
            state[name] = val
        self._dirty = True
        if self._monitor is not None:
            rerun = None
            if snap is not None:
                def rerun(_snap=snap, _feed=feed, _seed=seed,
                          _counter=counter):
                    self._restore_snapshot(_snap)
                    block = self._program.blocks[self._block_id]
                    return self._core._bisect_rerun(
                        self._program, self._block_id, list(block.ops),
                        self._scope, _feed, _seed, _counter, self._mode)
            self._monitor.observe(health if use_health else None,
                                  rerun=rerun,
                                  checked=True if use_health else None)
        return list(fetches)

    def _restore_snapshot(self, snap):
        """Rewind to the pre-step state (numerics bisect): the tripped
        step's device results are discarded, the scope gets the host
        snapshot back, and the next step (if any) re-stages from it."""
        scope = self._scope
        for name, arr in snap.items():
            (scope.find_scope_of(name) or scope).set(name, arr)
        self._state.clear()
        self._seen.clear()
        self._dirty = False
        self._scope_epoch = None

    def _check_feed_names(self, feed):
        missing = self._feed_names - feed.keys()
        if missing:
            raise KeyError(
                "prepared program expects feed(s) %s (prepared "
                "signature: %s)" % (sorted(missing),
                                    sorted(self._feed_names)))
        resident = feed.keys() & self._state_targets.keys()
        if resident:
            raise ValueError(
                "feed(s) %s are device-resident state of this prepared "
                "program; sync_scope() + run(), or re-prepare with them "
                "in feed_specs" % sorted(resident))
        # extra never-read feeds are ignored, like run()

    def sync_scope(self):
        """Flush written persistables back to the scope.  The scope then
        holds the CURRENT device arrays; a later step donates them
        again, which re-marks this program dirty so the next flush
        rewrites fresh buffers.  A name written EXTERNALLY since we last
        read/installed it (scope.set by user code, a load, another
        executor) wins: the device copy is dropped and re-staged from
        the scope instead of clobbering the newer value."""
        _M_FLUSHES.inc()
        if _TRC.on:
            with _TRC.span("step.sync_scope"):
                return self._sync_scope_impl()
        return self._sync_scope_impl()

    def _sync_scope_impl(self):
        scope = self._scope
        stale = False
        for name in self._entry.persist_outs:
            val = self._state.get(name)
            if val is None:
                continue
            if seen_changed(scope, name, self._seen.get(name)):
                # external write since our last read/install: scope wins
                self._state.pop(name, None)
                self._seen.pop(name, None)
                stale = True
                continue
            s = scope.find_scope_of(name) or scope
            s.set(name, val)
            self._seen[name] = (s, s._write_versions[name])
        # READ-ONLY resident state (e.g. a learning-rate var) can also
        # have been written externally; installing our persist_outs
        # fast-forwards the epoch past that write, so it must be
        # detected HERE or the next step would silently keep the stale
        # device copy
        if not stale:
            for name in self._read_only:
                if seen_changed(scope, name, self._seen.get(name)):
                    stale = True
                    break
        self._dirty = False
        # anything stale must be re-staged before the next step even if
        # nothing else touches the scope: poison the epoch
        self._scope_epoch = None if stale else scope.chain_version()

    # context manager: `with core.prepare(...) as prep:` syncs on exit
    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._dirty:
            self.sync_scope()
        return False


class ExecutorCore:
    """place: target device.  mesh: optional jax.sharding.Mesh — when set,
    the block is compiled as ONE SPMD program: feed (batch-dim) inputs are
    sharded over `dp_axis`, parameters replicated, and XLA's SPMD partitioner
    inserts the gradient all-reduces over ICI that the reference implemented
    as NCCL AllReduceOpHandles (details/multi_devices_graph_builder.cc:232)."""

    def __init__(self, place, mesh=None, dp_axis="dp"):
        self.place = place
        self.mesh = mesh
        self.dp_axis = dp_axis
        self._cache = {}

    # ------------------------------------------------------------------
    def _maybe_verify(self, program):
        """Ahead-of-time verification (paddle_tpu/analysis), paid ONLY
        when this program version has never been verified — the same
        cadence as a compile-cache miss, since the compiled-entry key
        includes program.version.  The verified marker lives on the
        program (not this executor) so nested executors (go routines,
        pserver serve loops) and run()/prepare() share one verification
        per mutation."""
        level = FLAGS.check_program
        if level == "off":
            return
        key = (program.version, level)
        if getattr(program, "_verified_key", None) == key:
            return
        from paddle_tpu import analysis
        try:
            analysis.verify_and_enforce(program, level=level,
                                        source="executor")
        except analysis.ProgramVerificationError:
            raise  # error mode: every run on the bad version re-raises
        except Exception as e:
            # a checker crash must never take down training: report it
            # and keep running (the program may still be fine)
            warnings.warn("program verification itself failed (%s: %s); "
                          "continuing unverified" % (type(e).__name__, e),
                          analysis.ProgramLintWarning)
        program._verified_key = key

    # ------------------------------------------------------------------
    def run(self, program, scope, block_id=0, feed=None, fetch_list=None,
            mode="train", return_numpy=True):
        # step metrics on COMPLETION only, mirroring run_prepared: a
        # raising run is not a step, and its aborted duration must not
        # land in the histogram.  Neither is a sub-block run — a
        # pserver's listen_and_serv applies each shard's optimize block
        # through here (ops/distributed_ops apply_block), and counting
        # those would report shard-apply time as the process's step
        # stats (10 shards x 100 rounds = 1000 phantom "steps").
        is_step = block_id == 0
        if not _TRC.on:
            out = self._run_impl(program, scope, block_id, feed,
                                 fetch_list, mode, return_numpy)
            if is_step:
                _M_STEPS.inc()
            return out
        span = _TRC.begin("executor.run", None, {"block": block_id})
        try:
            out = self._run_impl(program, scope, block_id, feed,
                                 fetch_list, mode, return_numpy)
        except BaseException:
            span.name = "executor.run.failed"
            raise
        finally:
            _TRC.end(span)
        if is_step:
            _M_STEPS.inc()
            # a blocking serve (listen_and_serv) is not a training
            # step either: one minutes-long observation would wreck
            # the step_wall_ms sum/mean/percentiles.  The executor.run
            # span still records it for the trace.
            if not _block_serves(program, block_id):
                _H_STEP_MS.observe((span.t1 - span.t0) / 1e6)
        return out

    def _run_impl(self, program, scope, block_id, feed, fetch_list,
                  mode, return_numpy):
        self._maybe_verify(program)
        # device-resident prepared state (run_prepared) must land in the
        # scope before this unprepared path reads or overwrites it
        flush_prepared(scope)
        feed = _prepare_lod_feeds(dict(feed or {}))
        fetch_list = list(fetch_list or [])
        block = program.blocks[block_id]
        # host ops with sub-block access (listen_and_serv) read this
        self._current_program = program

        t0 = time.perf_counter() if FLAGS.benchmark else None

        prelude, core_ops, postlude, mixed = _segment(block)
        if FLAGS.check_nan_inf:
            # legacy debug mode: run op-by-op eagerly so EVERY op's
            # outputs are validated and the first bad op is named
            # (reference FLAGS_check_nan_inf, operator.cc:590 — checks
            # even transients a downstream op would mask).  The ISSUE 8
            # observatory (FLAGS_check_numerics=bisect) keeps run()
            # compiled instead and re-runs only a TRIPPED step op-by-op;
            # the prepared path uses that machinery for this flag too.
            mixed = True
        if mixed:
            # the interpreted path executes EVERY op of the block itself
            # (host ops included) — running prelude/postlude here too
            # would execute them twice (e.g. double-send to a pserver)
            fetches = self._run_interpreted(program, block, scope, feed,
                                            fetch_list, mode)
        else:
            for op in prelude:
                _run_host_op(self, op, scope, feed)
            # postlude host ops may read non-persistable temps the block
            # computed (e.g. print of an activation): fetch those too and
            # hand them over via env instead of polluting the scope.
            # Conversely, fetches PRODUCED by postlude host ops (e.g. a
            # chunk_eval metric) come out of that env afterwards.
            post_writes = {n for op in postlude
                           for n in op.output_arg_names() if n}
            core_fetch = [n for n in fetch_list if n not in post_writes]
            post_in = [n for op in postlude for n in op.input_arg_names()
                       if n]
            # '@LEN' companions ride along so host ops (chunk_eval &c.)
            # see real sequence lengths, not the padded T
            post_in += [n + LEN_SUFFIX for n in list(post_in)]
            post_reads = sorted({
                n for n in post_in
                if n not in feed and not scope.has_var(n)
                and n not in post_writes})
            if core_ops or core_fetch or post_reads:
                outs = self._run_compiled(program, block_id, core_ops,
                                          scope, feed,
                                          core_fetch + post_reads, mode)
            else:
                outs = []  # all-host program (save/load/...): nothing to
                #            compile — don't jit an empty computation
            by_name = dict(zip(core_fetch, outs[:len(core_fetch)]))
            post_env = dict(zip(post_reads, outs[len(core_fetch):]))
            for op in postlude:
                _run_host_op(self, op, scope, feed,
                             post_env if (post_reads or post_writes)
                             else None)
            fetches = [by_name[n] if n in by_name else post_env.get(n)
                       for n in fetch_list]

        if t0 is not None:
            # reference FLAGS_benchmark (executor.cc): per-run wall time
            print("[benchmark] block %d ran in %.3f ms" %
                  (block_id, (time.perf_counter() - t0) * 1e3),
                  file=sys.stderr)

        if return_numpy:
            fetches = fetches_to_host(fetches)
        return fetches

    # ------------------------------------------------------------------
    def prepare(self, program, feed_specs, fetch_list, mode="train",
                scope=None, block_id=0):
        """Reference Executor::Prepare (executor.cc:127): pay program
        analysis once, get a PreparedProgram whose per-step cost is feed
        staging + one dispatch (RunPreparedContext).

        ``feed_specs`` is either a sample feed dict ({name: array-like /
        LoDTensor}, e.g. the first minibatch — its shapes/dtypes let the
        compiled entry share the run() cache) or a bare iterable of feed
        names.  Raises ValueError for blocks the compiled path cannot
        own whole (host ops) — callers fall back to run()."""
        if scope is None:
            raise ValueError(
                "prepare() requires the scope holding the program's "
                "persistables (run the startup program into it first)")
        self._maybe_verify(program)
        if feed_specs is None:  # zero-feed program (scope-resident data)
            feed_specs = {}
        fetch_list = list(fetch_list or [])
        block = program.blocks[block_id]
        prelude, core_ops, postlude, mixed = _segment(block)
        if mixed or prelude or postlude:
            host = sorted({op.type for op in block.ops
                           if get_op_info(op.type).host_op})
            raise ValueError(
                "block %d has host op(s) %s; the prepared hot path "
                "compiles the whole block — use run()" % (block_id, host))
        # FLAGS.check_nan_inf no longer refuses the prepared path
        # (ISSUE 8): the legacy flag maps onto the numerics guard+bisect
        # machinery — the step stays one dispatch with the fused health
        # fetch, and a trip re-runs THAT step op-by-op to name the first
        # bad op, preserving the reference semantics on both paths
        # (MIGRATION.md "check_nan_inf on the prepared path").
        if hasattr(feed_specs, "keys"):
            sample = _prepare_lod_feeds(dict(feed_specs))
            # the SAME cache key _run_compiled builds from a real feed,
            # so prepare() and run() share one compiled executable
            key_spec = tuple(sorted(
                (name, tuple(np.shape(v)),
                 str(v.dtype) if hasattr(v, "dtype") else
                 str(np.asarray(v).dtype))
                for name, v in sample.items()))
            stub = {
                name: jax.ShapeDtypeStruct(
                    np.shape(v), v.dtype if hasattr(v, "dtype")
                    else np.asarray(v).dtype)
                for name, v in sample.items()}
        else:
            # names-only signature: membership is enough to build; the
            # entry cannot alias run()'s per-shape keys, but repeated
            # prepare() calls (re-prepare after staleness, sibling
            # PreparedPrograms) must not re-trace
            stub = {name: None for name in feed_specs}
            key_spec = ("names-only",) + tuple(sorted(stub))
        key = _cache_key(program, block_id, key_spec, fetch_list, mode)
        entry = self._cache.get(key)
        if entry is None:
            _M_CACHE_MISSES.inc()
            entry = self._build(program, block_id, core_ops, scope,
                                stub, fetch_list, mode)
            self._cache[key] = entry
        else:
            _M_CACHE_HITS.inc()
        # Numerics observatory (ISSUE 8): with a mode on, the entry
        # above carries the health output — also compile the PLAIN twin
        # (keyed exactly like the flag-off build, so it is usually a
        # cache hit) and let run_prepared dispatch the health twin only
        # on cadence steps: the stats pass costs one memory pass over
        # the watched bytes, and amortizing it by 1/every is what keeps
        # metrics mode under tools/telemetry_overhead.py's 2% gate.
        entry_health = None
        if entry.watched:
            entry_health = entry
            key_plain = _cache_key(program, block_id, key_spec,
                                   fetch_list, mode, numerics=False)
            entry = self._cache.get(key_plain)
            if entry is None:
                _M_CACHE_MISSES.inc()
                entry = self._build(program, block_id, core_ops, scope,
                                    stub, fetch_list, mode,
                                    with_health=False)
                self._cache[key_plain] = entry
            else:
                _M_CACHE_HITS.inc()
        return PreparedProgram(self, program, block_id, entry, scope,
                               mode, stub, entry_health=entry_health)

    # ------------------------------------------------------------------
    def _rng_key(self, program, scope):
        seed, counter = self._rng_counter(program, scope)
        return jax.random.fold_in(jax.random.PRNGKey(seed), counter)

    def _rng_counter(self, program, scope):
        """Step counter fed to the compiled fn; the PRNGKey derivation
        happens inside the jitted computation so no eager dispatches are
        paid per step."""
        counter = getattr(scope, "_rng_counter", 0)
        scope._rng_counter = counter + 1
        seed = getattr(program, "random_seed", 0) or 0
        return np.uint32(seed), np.uint32(counter)

    def _run_compiled(self, program, block_id, core_ops, scope, feed,
                      fetch_list, mode):
        block = program.blocks[block_id]
        # NB: use .dtype when present — np.asarray on a jax.Array would be
        # a blocking device-to-host copy in the hot path.
        feed_spec = tuple(sorted(
            (name, tuple(np.shape(v)),
             str(v.dtype) if hasattr(v, "dtype") else
             str(np.asarray(v).dtype))
            for name, v in feed.items()))
        key = _cache_key(program, block_id, feed_spec, fetch_list, mode)
        entry = self._cache.get(key)
        if entry is None:
            _M_CACHE_MISSES.inc()
            entry = self._build(program, block_id, core_ops, scope, feed,
                                fetch_list, mode)
            self._cache[key] = entry
        else:
            _M_CACHE_HITS.inc()

        dev = self.place.jax_device()
        args = []
        for i, name in enumerate(entry.input_names):
            target = (entry.input_shardings[i]
                      if entry.input_shardings is not None else dev)
            if target is None:  # auto-layout path: feeds use the device
                target = dev
            if name in feed:
                val = feed[name]
                vd = block.find_var_recursive(name)
                if vd is not None and not hasattr(val, "dtype"):
                    val = np.asarray(val, dtype=proto_to_np_dtype(vd.dtype))
                args.append(_put(val, target, local_rows=True))
            else:
                # Always commit to the target device: mixing committed and
                # uncommitted arrays across steps would miss jit's C++ cache
                # and recompile (device_put is a no-op when already there).
                # reader-op batches in the scope are per-process LOCAL
                # rows, not global values (see reader_ops._read)
                args.append(_put(
                    scope.find_var(name), target,
                    local_rows=name in getattr(scope,
                                               "_reader_batch_vars", ())))
        seed, counter = self._rng_counter(program, scope)

        # numerics bisect (ISSUE 8): host snapshot of the scope-read
        # inputs BEFORE the dispatch consumes the donated persistable
        # buffers — from step 2 on, the scope's persistables ARE the
        # arrays donated to this dispatch, so the forensic re-run of a
        # tripped step must start from copies taken now (mirrors the
        # prepared path's per-step snapshot; the expensive debug tier)
        snap = None
        if entry.watched and _num.effective_mode() == "bisect":
            snap = {name: _snapshot_value(args[i])
                    for i, name in enumerate(entry.input_names)
                    if name not in feed}
        # buffer sanitizer (ISSUE 14): the dispatch donates the scope-
        # resident persistables it overwrites — the consumed map names
        # var -> the exact argument handed over, so poisoning swaps
        # only slots that still alias the dying buffer
        donated_map = None
        if _san._BUFFERS_ON:
            persist_set = set(entry.persist_outs)
            donated_map = {
                n: args[i] for i, n in enumerate(entry.input_names)
                if n in persist_set and n not in feed}
            don_site = "block %d of program %s" % (
                block_id, getattr(program, "uid", "?"))
        try:
            if _TRC.on:
                sp = _TRC.begin("executor.dispatch")
                try:
                    out = entry.fn(tuple(args), seed, counter)
                finally:
                    _TRC.end(sp)
            else:
                out = entry.fn(tuple(args), seed, counter)
        except Exception:
            # a failed EXECUTE consumed the donated inputs; a failed
            # trace consumed nothing — only_dead tells them apart, so
            # a trace failure never husks a live value
            if donated_map:
                _san.poison_donated(scope, donated_map,
                                    op="executor.run",
                                    step=int(counter), site=don_site,
                                    only_dead=True)
            raise
        if donated_map:
            _san.poison_donated(scope, donated_map, op="executor.run",
                                step=int(counter), site=don_site)
        if entry.watched:
            fetches, persists, health = out
        else:
            fetches, persists = out
        # write-back BEFORE the health check: on a guard trip the scope
        # then holds the post-step (poisoned but LIVE) values, never
        # donated husks — post-mortem reads and skip-batch continuation
        # keep working; bisect restores its pre-step snapshot instead.
        # The scope.set here is also the sanitizer's RE-BIND: it
        # replaces the poisoned husks with the fresh buffers.
        for name, val in zip(entry.persist_outs, persists):
            (scope.find_scope_of(name) or scope).set(name, val)
        if entry.watched:
            def _rerun(_snap=snap):
                if _snap is not None:
                    for name, v in _snap.items():
                        (scope.find_scope_of(name) or scope).set(name, v)
                return self._bisect_rerun(program, block_id, core_ops,
                                          scope, feed, seed, counter,
                                          mode)
            entry.monitor.observe(health, rerun=_rerun)
        return list(fetches)

    def _build(self, program, block_id, core_ops, scope, feed, fetch_list,
               mode, with_health=None):
        block = program.blocks[block_id]
        written = set()
        external = []  # ordered reads satisfied by feed or scope
        seen_ext = set()
        for op in core_ops:
            for name in op.input_arg_names():
                if (name and name not in written and name not in seen_ext):
                    seen_ext.add(name)
                    external.append(name)
            for name in op.output_arg_names():
                if name:
                    written.add(name)
        # fetching an un-written var (e.g. a parameter) reads it too.
        # '@LEN' fetches are env-internal sequence lengths produced by the
        # trace itself (or absent -> fetched as None), never external.
        for name in fetch_list:
            if (name and name not in written and name not in seen_ext
                    and not (name.endswith(LEN_SUFFIX)
                             and not scope.has_var(name)
                             and name not in feed)):
                seen_ext.add(name)
                external.append(name)
        # ragged feeds travel as (padded, lengths) pairs: pull in the
        # device-side length vector of every LoD input (SURVEY §5.7 —
        # ragged->dense bucketing bridge to XLA static shapes)
        for name in list(external):
            suffixes = [LEN_SUFFIX]
            j = 1
            while name + LEN_SUFFIX + "@%d" % j in feed:
                suffixes.append(LEN_SUFFIX + "@%d" % j)
                j += 1
            for suffix in suffixes:
                if name + suffix in feed and name + suffix not in seen_ext:
                    seen_ext.add(name + suffix)
                    external.append(name + suffix)

        input_names = []
        for name in external:
            if name in feed or scope.has_var(name):
                input_names.append(name)
            else:
                raise RuntimeError(
                    "variable %r is read by block %d but is neither fed nor "
                    "initialized in the scope (run the startup program first)"
                    % (name, block_id))

        persist_outs = []
        for name in written:
            vd = block.find_var_recursive(name)
            if vd is not None and vd.persistable:
                persist_outs.append(name)
        persist_outs.sort()

        ops = list(core_ops)

        # numerics observatory (ISSUE 8): the watch list is fixed BEFORE
        # tracing so the packed health rows align with entry.watched;
        # the reduction is part of the jitted step (one dispatch).
        # Sub-block runs (block_id != 0) are NOT watched — a pserver's
        # listen_and_serv applies each shard's optimize block through
        # here, and a guard trip raising mid-apply (lock released
        # around the block) would wedge the serve loop with every
        # trainer stuck in retry; poisoned inbound grads are the wire
        # health check's job (numerics.server_check_grad names the
        # (round, sender) cid), and the trainer's own guard trips on
        # the poisoned params it fetches back.  Mirrors the
        # executor_steps_total sub-block exclusion.
        watched = ()
        if block_id == 0 and (_num.trace_enabled() if with_health is None
                              else with_health):
            watched = _num.select_watched(program, block, ops,
                                          persist_outs, fetch_list)

        def fn(inputs, seed, counter):
            env = dict(zip(input_names, inputs))
            rng = jax.random.fold_in(jax.random.PRNGKey(seed), counter)
            ctx = LoweringContext(program, block_id, env, rng, mode)
            ctx.block = block
            ctx.mesh = self.mesh
            for op in ops:
                run_op(ctx, op)
            fetches = tuple(env.get(n) for n in fetch_list)
            persists = tuple(env[n] for n in persist_outs)
            if watched:
                return fetches, persists, _num.pack_health(env, watched)
            return fetches, persists

        # Donate persistable inputs that the block overwrites: XLA reuses
        # the parameter buffers across steps (in-place optimizer update).
        donate = tuple(
            i for i, n in enumerate(input_names)
            if n in persist_outs and not _in_feed_only(n, feed, scope))

        def fn_flat(*flat_args):
            return fn(tuple(flat_args[:-2]), flat_args[-2], flat_args[-1])

        jit_kwargs = {"donate_argnums": donate}
        input_shardings = None
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            repl = NamedSharding(self.mesh, P())
            annotated = getattr(program, "var_shardings", {})

            axis_names = set(self.mesh.axis_names)

            reader_vars = getattr(scope, "_reader_batch_vars", ())

            def shard_of(name):
                if name in annotated:
                    spec = tuple(a if a in axis_names else None
                                 for a in annotated[name])
                    return NamedSharding(self.mesh, P(*spec))
                vd = block.find_var_recursive(name)
                # batch-dim data shards over dp whether it arrives as a
                # feed or from a program-level reader chain (the read
                # host op tags its outputs in the scope)
                if ((name in feed or name in reader_vars)
                        and vd is not None and len(vd.shape) >= 1
                        and vd.shape[0] == -1 and self.dp_axis in axis_names):
                    return NamedSharding(self.mesh, P(
                        self.dp_axis, *([None] * (len(vd.shape) - 1))))
                return repl

            input_shardings = [shard_of(n) for n in input_names]
            jit_kwargs["in_shardings"] = tuple(input_shardings) + (repl, repl)
            # Fetches come back replicated (they are consumed on host);
            # written persistables keep their annotated placement so e.g.
            # tensor-parallel weights never gather.  The health array is
            # tiny and host-consumed: replicated.
            out_sh = (tuple(repl for _ in fetch_list),
                      tuple(shard_of(n) for n in persist_outs))
            if watched:
                out_sh = out_sh + (repl,)
            jit_kwargs["out_shardings"] = out_sh
        # Scheduler-flag knobs (FLAGS_xla_*): best-effort late application
        # — a no-op once a backend exists; bench.py applies them before
        # backend init, which is the supported path (MIGRATION.md).
        from .flags import apply_xla_flags
        apply_xla_flags()
        # Pin trace/compile/execute to the place's device: with zero inputs
        # (every startup program) nothing else commits the computation, and
        # jit would otherwise compile for the process-default backend — e.g.
        # a CPUPlace startup run landing on the host's TPU.
        pin = None if self.mesh is not None else self.place.jax_device()

        if (pin is not None and pin.platform == "tpu" and FLAGS.auto_layout
                and input_names):
            entry = self._build_auto_layout(
                fn_flat, jit_kwargs, input_names, persist_outs, fetch_list,
                block, feed, scope, pin, watched)
            if entry is not None:
                return entry

        jflat = jax.jit(fn_flat, **jit_kwargs)

        def jfn(inputs, seed, counter):
            with _matmul_precision_ctx():
                if pin is None:
                    return jflat(*inputs, seed, counter)
                with jax.default_device(pin):
                    return jflat(*inputs, seed, counter)

        return _CacheEntry(jfn, input_names, persist_outs, tuple(fetch_list),
                           input_shardings, jit_fn=jflat, watched=watched)

    def _build_auto_layout(self, fn_flat, jit_kwargs, input_names,
                           persist_outs, fetch_list, block, feed, scope,
                           dev, watched=()):
        """Single-chip experiment path: AOT-compile with AUTO argument
        layouts.  AUTO lets XLA's layout assignment pick the parameter
        layouts; donation then aliases input and output buffers in that
        SAME layout, so weights stay in whatever form the compiler
        prefers across steps with no boundary relayouts.  Measured
        NEUTRAL on ResNet-50 and the transformer LM (the profile's
        relayout copies turned out to be internal to conv scheduling,
        not argument-boundary conversions — XLA's default argument
        layouts already matched), hence FLAGS.auto_layout defaults off;
        kept for models whose parameters do want non-default layouts.
        device_put into the chosen Format is a one-time cost (a no-op
        once the scope holds the formatted buffer)."""
        try:
            from jax.experimental.layout import Format, Layout
        except ImportError:
            return None
        try:
            fmt = Format(Layout.AUTO)
            specs = []
            for name in input_names:
                val = feed.get(name)
                if val is None:
                    val = scope.find_var(name)
                if not hasattr(val, "dtype"):
                    vd = block.find_var_recursive(name)
                    val = np.asarray(val, dtype=proto_to_np_dtype(vd.dtype)
                                     if vd is not None else None)
                specs.append(jax.ShapeDtypeStruct(np.shape(val), val.dtype))
            specs += [jax.ShapeDtypeStruct((), np.uint32)] * 2
            kw = dict(jit_kwargs)
            # feeds keep default layouts (host arrays stream in each step);
            # persistables get AUTO
            feed_only = {n for n in input_names
                         if _in_feed_only(n, feed, scope)}
            kw["in_shardings"] = tuple(
                (None if n in feed_only else fmt) for n in input_names
            ) + (None, None)
            # fetches need AUTO too: donated AUTO inputs with a
            # default-layout output subtree is rejected by jax ("Input
            # layout being donated was AUTO while output layout was
            # None"); host reads convert on transfer regardless
            kw["out_shardings"] = ((fmt, fmt, fmt) if watched
                                   else (fmt, fmt))  # (+ health)
            with _matmul_precision_ctx(), jax.default_device(dev):
                compiled = jax.jit(fn_flat, **kw).lower(*specs).compile()
            in_fmts = compiled.input_formats[0]
            input_shardings = [
                (None if n in feed_only else in_fmts[i])
                for i, n in enumerate(input_names)]

            def jfn(inputs, seed, counter):
                with jax.default_device(dev):
                    return compiled(*inputs, seed, counter)

            return _CacheEntry(jfn, input_names, persist_outs,
                               tuple(fetch_list), input_shardings,
                               watched=watched)
        except Exception as e:  # any version/platform mismatch: plain jit
            warnings.warn("auto_layout compile failed (%s); falling back "
                          "to default layouts" % e)
            return None

    def _run_interpreted(self, program, block, scope, feed, fetch_list, mode):
        dev = self.place.jax_device()
        env = _ScopeEnv(scope, dev)
        for name, val in feed.items():
            vd = block.find_var_recursive(name)
            dtype = (proto_to_np_dtype(vd.dtype) if vd is not None else None)
            env[name] = jax.device_put(
                np.asarray(val, dtype=dtype) if dtype else np.asarray(val),
                dev)
        ctx = LoweringContext(program, block.idx, env,
                              self._rng_key(program, scope), mode)
        check_ops = FLAGS.check_nan_inf or \
            _num.effective_mode() == "bisect"
        with jax.default_device(dev):
            for oi, op in enumerate(block.ops):
                info = get_op_info(op.type)
                if info.host_op:
                    _run_host_op(self, op, scope, feed, env)
                else:
                    run_op(ctx, op)
                    if check_ops:
                        _num.check_op_outputs(op, env, block.idx, oi)
        # sync written persistables back
        for name in env.written:
            vd = block.find_var_recursive(name)
            if vd is not None and vd.persistable:
                s = scope.find_scope_of(name) or scope
                s.set(name, env[name])
        return [env.get(n) for n in fetch_list]

    def _bisect_rerun(self, program, block_id, ops, scope, feed, seed,
                      counter, mode):
        """Forensic re-run of ONE already-dispatched step, op by op,
        with per-op output checks (numerics bisect): expected to raise
        NumericsError naming the FIRST offending op, its input stats
        and program location.  The caller guarantees the scope holds
        the step's PRE-dispatch state (both run() and the prepared
        path restore their per-step host snapshot before calling),
        and ``(seed, counter)`` replay the dispatched step's exact RNG
        stream, so stateful ops (dropout) reproduce bit-for-bit.  Host
        ops are skipped — prelude/postlude already ran — and nothing is
        written back: this is evidence collection, not execution."""
        block = program.blocks[block_id]
        dev = self.place.jax_device()
        env = _ScopeEnv(scope, dev)
        for name, val in feed.items():
            vd = block.find_var_recursive(name)
            dtype = (proto_to_np_dtype(vd.dtype) if vd is not None
                     else None)
            env[name] = jax.device_put(
                np.asarray(val, dtype=dtype) if dtype
                else np.asarray(val), dev)
        rng = jax.random.fold_in(jax.random.PRNGKey(seed), counter)
        ctx = LoweringContext(program, block.idx, env, rng, mode)
        ctx.mesh = self.mesh
        with jax.default_device(dev):
            for oi, op in enumerate(ops):
                if get_op_info(op.type).host_op:
                    continue
                run_op(ctx, op)
                _num.check_op_outputs(op, env, block.idx, oi)
        return None  # did not reproduce — the monitor reports that


class _ScopeEnv(dict):
    """dict-like env that falls back to Scope lookups (interpreted path)."""

    def __init__(self, scope, device):
        super().__init__()
        self.scope = scope
        self.device = device
        self.written = set()

    def __contains__(self, name):
        return super().__contains__(name) or self.scope.has_var(name)

    def __missing__(self, name):
        val = self.scope.find_var(name)  # KeyError if absent
        super().__setitem__(name, val)
        return val

    def __setitem__(self, name, val):
        self.written.add(name)
        super().__setitem__(name, val)

    def get(self, name, default=None):
        try:
            return self[name]
        except KeyError:
            return default


def _snapshot_value(v):
    """Host copy of one resident value that survives buffer donation
    (numerics bisect pre-step snapshots).  jax.Arrays copy to host;
    SelectedRows copies its parts — keeping the object by reference
    would hand the restore a consumed values buffer."""
    if hasattr(v, "rows") and hasattr(v, "values"):
        from .selected_rows import SelectedRows
        return SelectedRows(np.array(np.asarray(v.rows), copy=True),
                            np.array(np.asarray(v.values), copy=True),
                            v.height)
    return np.asarray(v)


def _in_feed_only(name, feed, scope):
    return name in feed and not scope.has_var(name)


def fetches_to_host(outs):
    """Fetch-list values -> host numpy (None and list/tuple fetches —
    absent vars, LoD pairs — pass through untouched)."""
    return [_to_host_numpy(v) if v is not None and
            not isinstance(v, (list, tuple)) else v for v in outs]


def _to_host_numpy(v):
    """np.asarray that also handles multi-host global arrays: fetches
    are replicated (out_shardings in _build), so this process's first
    addressable shard IS the value."""
    if isinstance(v, jax.Array) and not v.is_fully_addressable:
        return np.asarray(v.addressable_data(0))
    return np.asarray(v)


def _put(val, target, local_rows=False):
    """device_put that tolerates Format targets and multi-host shardings.

    Multi-host (jax.distributed) shardings span devices this process
    cannot address; host values carry one of two semantics:

    - ``local_rows=True`` (feeds): the value is this process's LOCAL
      batch shard (the reference nccl2 contract: every trainer feeds
      its own batch, parallel_executor.cc:84-95) — assembled with
      ``make_array_from_process_local_data``.
    - ``local_rows=False`` (scope values): the value is the FULL global
      array, identical in every process (deterministic startup); each
      process materializes its addressable shards from it via
      ``make_array_from_callback`` — which is also what makes SHARDED
      (tensor-parallel) parameters work across hosts, where treating
      the full value as a local shard would double the global shape.

    Already-global jax.Arrays (last step's persistables) pass through
    untouched.

    Format targets: the TPU runtime here rejects device_put of a
    jax.Array onto a Format EVEN when the array already has exactly that
    layout (the relayout-by-jit path fails on the backend), so the
    already-formatted steady-state case must be a true no-op, and a
    genuine relayout goes through the host."""
    from jax.sharding import Sharding
    if isinstance(target, Sharding) and not target.is_fully_addressable:
        if isinstance(val, jax.Array):
            if val.sharding == target:
                return val
            if not val.is_fully_addressable:  # global -> global reshard
                return jax.device_put(val, target)
            val = np.asarray(val)  # local array -> rebuild globally
        elif not isinstance(val, np.ndarray):
            val = np.asarray(val)  # scope value / list / scalar
        if local_rows:
            return jax.make_array_from_process_local_data(target, val)
        full = val
        return jax.make_array_from_callback(
            full.shape, target, lambda idx: full[idx])
    fmt_layout = getattr(target, "layout", None)
    if fmt_layout is not None and isinstance(val, jax.Array):
        try:
            if val.format == target:
                return val
        except Exception:
            pass
        try:
            return jax.device_put(val, target)
        except Exception:
            return jax.device_put(np.asarray(val), target)
    return jax.device_put(val, target)


def _block_serves(program, block_id):
    """True when the block contains a blocking serve op
    (listen_and_serv) — cached per (block, version) on the program, so
    the per-step cost after the first call is one dict lookup."""
    cache = getattr(program, "_serve_blocks", None)
    if cache is None:
        cache = program._serve_blocks = {}
    key = (block_id, program.version)
    v = cache.get(key)
    if v is None:
        v = cache[key] = any(op.type == "listen_and_serv"
                             for op in program.blocks[block_id].ops)
    return v


def _segment(block):
    """Split ops into host prelude / device core / host postlude.

    Returns (prelude, core, postlude, mixed): ``mixed`` is True when host ops
    are interleaved with device ops and the block must be interpreted.
    """
    ops = block.ops
    is_host = [get_op_info(op.type).host_op for op in ops]
    i = 0
    while i < len(ops) and is_host[i]:
        i += 1
    j = len(ops)
    while j > i and is_host[j - 1]:
        j -= 1
    mixed = any(is_host[i:j])
    return ops[:i], ops[i:j], ops[j:], mixed


def _run_host_op(executor, op, scope, feed, env=None):
    info = get_op_info(op.type)
    impl = getattr(info, "_host_impl", None) or getattr(info.lower,
                                                        "host_impl", None)
    if impl is None:
        impl = info.lower
    impl(executor, op, scope, feed, env)
