"""Device places.

Parity: reference platform/place.h:75 (CPUPlace:25, CUDAPlace:35).  The GPU
place is replaced by TPUPlace; `CUDAPlace` is kept as a migration alias so
reference user code runs unchanged.  A Place resolves to a jax.Device.
"""
from __future__ import annotations

import jax


class Place:
    device_type = None

    def __init__(self, device_id=0):
        self.device_id = device_id

    def __eq__(self, other):
        return (type(self) is type(other)
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((type(self).__name__, self.device_id))

    def __repr__(self):
        return "%s(%d)" % (type(self).__name__, self.device_id)

    def jax_device(self):
        devs = _devices_for(self.device_type)
        if not devs:
            raise RuntimeError("no %s devices available" % self.device_type)
        return devs[self.device_id % len(devs)]


def _devices_for(kind):
    # local_devices, not devices: under jax.distributed the global list
    # spans every process, and a Place must resolve to a device THIS
    # process can address (a multi-host run would otherwise pin local
    # work to another host's device id and die on a cross-host reshard)
    if kind == "cpu":
        try:
            return jax.local_devices(backend="cpu")
        except RuntimeError:
            return []
    # "accelerator": whatever the default backend exposes, minus pure-host
    devs = jax.local_devices()
    accel = [d for d in devs if d.platform != "cpu"]
    return accel or devs  # fall back to CPU so tests run anywhere


class CPUPlace(Place):
    device_type = "cpu"


class TPUPlace(Place):
    device_type = "accelerator"


# Migration alias for reference user code (platform/place.h:35).
CUDAPlace = TPUPlace


def is_accelerator_available():
    return any(d.platform != "cpu" for d in jax.devices())
