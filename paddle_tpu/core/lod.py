"""LoDTensor: dense data + Level-of-Detail ragged-sequence offsets.

Parity: reference framework/lod_tensor.h:58-110.  The LoD (offset table per
nesting level) stays on the host; the dense concatenated data is the device
tensor.  Sequence ops receive the data plus host-side lengths and lower to
bucketed/masked static-shape XLA code (SURVEY §5.7).
"""
from __future__ import annotations

import numpy as np


class LoDTensor:
    __slots__ = ("data", "lod")

    def __init__(self, data, lod=None):
        self.data = data
        # lod: list of offset lists, e.g. [[0, 2, 5]] = two seqs len 2 and 3
        self.lod = [list(l) for l in (lod or [])]

    @property
    def shape(self):
        return tuple(np.shape(self.data))

    @property
    def dtype(self):
        return np.asarray(self.data).dtype

    def lod_level(self):
        return len(self.lod)

    def sequence_lengths(self, level=-1):
        offs = self.lod[level]
        return [offs[i + 1] - offs[i] for i in range(len(offs) - 1)]

    def num_sequences(self, level=0):
        return len(self.lod[level]) - 1

    def __array__(self, dtype=None):
        arr = np.asarray(self.data)
        return arr.astype(dtype) if dtype is not None else arr

    def __repr__(self):
        return "LoDTensor(shape=%s, lod=%s)" % (self.shape, self.lod)

    def to_padded(self, pad_value=0.0, max_len=None):
        """[sum_T, D...] + lod -> ([N, max_len, D...], [N] lengths).
        The ragged->dense bucketing bridge to XLA static shapes."""
        data = np.asarray(self.data)
        lens = self.sequence_lengths(0)
        n = len(lens)
        t = max_len or (max(lens) if lens else 0)
        out = np.full((n, t) + data.shape[1:], pad_value, dtype=data.dtype)
        offs = self.lod[0]
        for i in range(n):
            seq = data[offs[i]:offs[i + 1]]
            out[i, : len(seq)] = seq[:t]
        return out, np.asarray(lens, dtype=np.int64)

    @staticmethod
    def from_sequences(seqs):
        """Build from a list of [T_i, D...] arrays (level-1 LoD)."""
        seqs = [np.asarray(s) for s in seqs]
        offs = [0]
        for s in seqs:
            offs.append(offs[-1] + len(s))
        data = (np.concatenate(seqs, axis=0) if seqs
                else np.zeros((0,), np.float32))
        return LoDTensor(data, [offs])

    @staticmethod
    def from_padded(padded, lengths):
        padded = np.asarray(padded)
        lengths = [int(l) for l in np.asarray(lengths).reshape(-1)]
        parts = [padded[i, :l] for i, l in enumerate(lengths)]
        data = (np.concatenate(parts, axis=0) if parts
                else padded.reshape((0,) + padded.shape[2:]))
        offs = [0]
        for l in lengths:
            offs.append(offs[-1] + l)
        return LoDTensor(data, [offs])
