"""LoDTensor: dense data + Level-of-Detail ragged-sequence offsets.

Parity: reference framework/lod_tensor.h:58-110.  The LoD (offset table per
nesting level) stays on the host; the dense concatenated data is the device
tensor.  Sequence ops receive the data plus host-side lengths and lower to
bucketed/masked static-shape XLA code (SURVEY §5.7).
"""
from __future__ import annotations

import numpy as np


class LoDTensor:
    __slots__ = ("data", "lod")

    def __init__(self, data, lod=None):
        self.data = data
        # lod: list of offset lists, e.g. [[0, 2, 5]] = two seqs len 2 and 3
        self.lod = [list(l) for l in (lod or [])]

    @property
    def shape(self):
        return tuple(np.shape(self.data))

    @property
    def dtype(self):
        return np.asarray(self.data).dtype

    def lod_level(self):
        return len(self.lod)

    def sequence_lengths(self, level=-1):
        offs = self.lod[level]
        return [offs[i + 1] - offs[i] for i in range(len(offs) - 1)]

    def num_sequences(self, level=0):
        return len(self.lod[level]) - 1

    def __array__(self, dtype=None):
        arr = np.asarray(self.data)
        return arr.astype(dtype) if dtype is not None else arr

    def __repr__(self):
        return "LoDTensor(shape=%s, lod=%s)" % (self.shape, self.lod)

    def to_padded(self, pad_value=0.0, max_len=None):
        """[sum_T, D...] + lod -> ([N, max_len, D...], [N] lengths).
        The ragged->dense bucketing bridge to XLA static shapes."""
        data = np.asarray(self.data)
        lens = self.sequence_lengths(0)
        n = len(lens)
        t = max_len or (max(lens) if lens else 0)
        out = np.full((n, t) + data.shape[1:], pad_value, dtype=data.dtype)
        offs = self.lod[0]
        for i in range(n):
            seq = data[offs[i]:offs[i + 1]]
            out[i, : len(seq)] = seq[:t]
        return out, np.asarray(lens, dtype=np.int64)

    @staticmethod
    def from_sequences(seqs):
        """Build from a list of [T_i, D...] arrays (level-1 LoD)."""
        seqs = [np.asarray(s) for s in seqs]
        offs = [0]
        for s in seqs:
            offs.append(offs[-1] + len(s))
        data = (np.concatenate(seqs, axis=0) if seqs
                else np.zeros((0,), np.float32))
        return LoDTensor(data, [offs])

    def to_padded_2level(self, pad_value=0.0, max_seq=None,
                         max_word=None):
        """Level-2 LoD -> ([N, S, W, D...], outer_lens [N],
        inner_lens [N, S]).  N sentences of up to S sub-sequences of up
        to W tokens — the nested analog of :meth:`to_padded` (reference
        lod_tensor.h:58 hierarchical LoD).  max_seq/max_word truncate
        (lengths report the truncated sizes)."""
        if len(self.lod) != 2:
            raise NotImplementedError(
                "to_padded_2level needs exactly a level-2 LoD, got "
                "%d levels" % len(self.lod))
        data = np.asarray(self.data)
        outer, inner = self.lod[0], self.lod[1]
        n = len(outer) - 1
        outer_lens = [outer[i + 1] - outer[i] for i in range(n)]
        s = max_seq or (max(outer_lens) if outer_lens else 0)
        inner_lens_all = [inner[j + 1] - inner[j]
                          for j in range(len(inner) - 1)]
        w = max_word or (max(inner_lens_all) if inner_lens_all else 0)
        out = np.full((n, s, w) + data.shape[1:], pad_value,
                      dtype=data.dtype)
        inner_lens = np.zeros((n, s), np.int64)
        for i in range(n):
            for si, j in enumerate(range(outer[i], outer[i + 1])):
                if si >= s:
                    break                     # truncated by max_seq
                seq = data[inner[j]:inner[j + 1]][:w]
                out[i, si, : len(seq)] = seq
                inner_lens[i, si] = len(seq)  # post-truncation length
        outer_clipped = np.minimum(np.asarray(outer_lens, np.int64), s)
        return out, outer_clipped, inner_lens

    def to_padded_klevel(self, pad_value=0.0, max_dims=None):
        """Arbitrary-depth LoD -> (padded [N, S1, ..., S_{k-1}, D...],
        [lens_0 [N], lens_1 [N,S1], ..., lens_{k-1} [N,..,S_{k-2}]]).

        The general form of :meth:`to_padded` / :meth:`to_padded_2level`
        — the reference LoD is a vector of levels with no depth cap
        (framework/lod_tensor.h:58-110).  Level j's segments nest inside
        level j-1's; the padded array gains one dense dim per level
        (level 0's fan-out is the batch dim N; the deepest level is the
        time dim).  ``max_dims`` (one entry per level: [cap_1, ...,
        cap_{k-1}, cap_time]) truncates; lengths report post-truncation
        sizes."""
        k = len(self.lod)
        if k == 0:
            raise ValueError("to_padded_klevel needs a LoD")
        data = np.asarray(self.data)
        seg_lens = [self.sequence_lengths(j) for j in range(k)]
        # dims[j] = padded fan-out OF level j (max segment length);
        # dims[0].. dims[k-1] become the S1..S_{k-1},W dims
        dims = [max(l, default=1) or 1 for l in seg_lens]
        if max_dims is not None:
            dims = [md or d for md, d in zip(max_dims, dims)]
        n = len(seg_lens[0])
        out = np.full((n,) + tuple(dims) + data.shape[1:], pad_value,
                      dtype=data.dtype)
        # lens_arrays[j] indexes by the j+1 leading dims of `out`
        lens_arrays = [np.zeros((n,) + tuple(dims[:j]), np.int64)
                       for j in range(k)]

        def fill(level, seg, idx):
            length = seg_lens[level][seg]
            if level == k - 1:      # deepest: segments are data rows
                start = self.lod[level][seg]
                used = min(length, dims[level])
                out[idx + (slice(0, used),)] = data[start:start + used]
                lens_arrays[level][idx] = used
                return
            kids_start = self.lod[level][seg]
            used = min(length, dims[level])
            lens_arrays[level][idx] = used
            for si in range(used):
                fill(level + 1, kids_start + si, idx + (si,))

        for i in range(n):
            fill(0, i, (i,))
        return out, lens_arrays

    @staticmethod
    def from_padded_klevel(padded, lens_arrays):
        """Inverse of :meth:`to_padded_klevel`."""
        padded = np.asarray(padded)
        k = len(lens_arrays)
        lod = [[0] for _ in range(k)]
        parts = []

        def walk(level, idx):
            length = int(np.asarray(lens_arrays[level])[idx])
            lod[level].append(lod[level][-1] + length)
            if level == k - 1:
                parts.append(padded[idx][:length])
                return
            for si in range(length):
                walk(level + 1, idx + (si,))

        for i in range(np.shape(lens_arrays[0])[0]):
            walk(0, (i,))
        # structural dims are [N, S1..S_{k-1}, W] = k+1; features follow
        # (fresh zeros: reshape can't shrink a nonempty padded block)
        data = (np.concatenate(parts, axis=0) if parts
                else np.zeros((0,) + padded.shape[k + 1:], padded.dtype))
        return LoDTensor(data, lod)

    @staticmethod
    def from_padded_2level(padded, outer_lens, inner_lens):
        """Inverse of :meth:`to_padded_2level`."""
        padded = np.asarray(padded)
        outer_lens = np.asarray(outer_lens).reshape(-1)
        inner_lens = np.asarray(inner_lens)
        parts = []
        outer_offs, inner_offs = [0], [0]
        for i, ol in enumerate(outer_lens):
            outer_offs.append(outer_offs[-1] + int(ol))
            for si in range(int(ol)):
                il = int(inner_lens[i, si])
                inner_offs.append(inner_offs[-1] + il)
                parts.append(padded[i, si, :il])
        data = (np.concatenate(parts, axis=0) if parts
                else np.zeros((0,) + padded.shape[3:], padded.dtype))
        return LoDTensor(data, [outer_offs, inner_offs])

    @staticmethod
    def from_padded(padded, lengths):
        padded = np.asarray(padded)
        lengths = [int(l) for l in np.asarray(lengths).reshape(-1)]
        parts = [padded[i, :l] for i, l in enumerate(lengths)]
        data = (np.concatenate(parts, axis=0) if parts
                else np.zeros((0,) + padded.shape[2:], padded.dtype))
        offs = [0]
        for l in lengths:
            offs.append(offs[-1] + l)
        return LoDTensor(data, [offs])
