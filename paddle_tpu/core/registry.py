"""Operator registry.

Parity: reference framework/op_registry.h (REGISTER_OPERATOR + OpInfoMap) and
grad_op_desc_maker.h.  An op here is:

- ``lower(ctx, ins, attrs) -> outs``: a JAX tracing function.  ``ins``/``outs``
  map slot name -> list of jax values (or a single value for convenience —
  normalized by the engine).  This replaces the reference's per-device OpKernel
  table: there is exactly one lowering, and XLA compiles it for the target
  backend (TPU/CPU).
- ``grad_maker(op, block, no_grad_set) -> (grad_op_descs, grad_to_var)``:
  build-time autodiff hook, as in reference GradOpDescMakerBase.  The default
  maker emits ``<type>_grad`` consuming forward ins/outs + output grads; the
  default grad *lowering* evaluates jax.vjp of the forward lowering, so an op
  gets a correct gradient without hand-writing one (XLA fuses it anyway).
- ``infer_shape``: optional ``fn(ins, attrs, op) -> {slot: specs}`` taking the
  same ``Ins`` view the lowering would, holding jax.ShapeDtypeStruct specs
  instead of traced values, and returning output specs per slot.  Register one
  for ops whose output shape abstract evaluation cannot model (data-dependent
  sizes, host-adjacent state); everything else falls back to jax.eval_shape
  over the lowering (abstract evaluation — no FLOPs).  Consumed by build-time
  shape inference (fluid Block.append_op) and the ahead-of-time program
  verifier's shape checker (paddle_tpu/analysis) through
  ``lowering.infer_op_outputs``.
"""
from __future__ import annotations


class OpInfo:
    __slots__ = ("type", "lower", "grad_maker", "grad_lower", "infer_shape",
                 "host_op", "stateful", "wrt", "no_vjp_outputs", "seq_aware")

    def __init__(self, type_, lower=None, grad_maker="default",
                 grad_lower=None, infer_shape=None, host_op=False,
                 stateful=False, wrt=None, no_vjp_outputs=(),
                 seq_aware=False):
        self.type = type_
        self.lower = lower
        # "default" -> generic maker; None -> non-differentiable; callable -> custom
        self.grad_maker = grad_maker
        self.grad_lower = grad_lower
        self.infer_shape = infer_shape
        self.host_op = host_op          # executed on host by the Executor
        self.stateful = stateful        # uses PRNG (dropout/uniform_random/...)
        # slots to differentiate w.r.t.; None = all floating-point inputs
        self.wrt = wrt
        # output slots excluded from vjp (integer/aux outputs)
        self.no_vjp_outputs = tuple(no_vjp_outputs)
        # op manages sequence lengths itself (no automatic @LEN propagation)
        self.seq_aware = seq_aware


_registry = {}


def register_op(type_, **kwargs):
    """Register an op.  Usable directly or as a decorator on the lowering."""

    def _do(lower):
        if type_ in _registry:
            raise ValueError("op %r already registered" % type_)
        _registry[type_] = OpInfo(type_, lower=lower, **kwargs)
        return lower

    if "lower" in kwargs:
        lower = kwargs.pop("lower")
        return _do(lower)
    return _do


def get_op_info(type_):
    info = _registry.get(type_)
    if info is None and type_.endswith("_grad") and \
            type_[: -len("_grad")] in _registry:
        # Synthesize the grad op from the forward lowering's vjp
        # (lowering.generic_grad_lower); registered lazily so explicit
        # custom grad lowerings (e.g. dropout_grad) take precedence.
        from . import lowering  # local import: registry <-> lowering cycle

        info = OpInfo(type_, lower=lowering.generic_grad_lower,
                      grad_maker=None)
        _registry[type_] = info
    if info is None:
        raise KeyError("operator %r is not registered (registered: %d ops)" %
                       (type_, len(_registry)))
    return info


def has_op(type_):
    return type_ in _registry


def registered_ops():
    return sorted(_registry.keys())
