"""Scope: name -> value tree with parent lookup.

Parity: reference framework/scope.h:39 / variable.h:26.  Values are
type-erased Python objects; device tensors are jax.Arrays (committed to a
device), host-side containers (LoDTensor, readers, step scopes) are plain
objects.  Unlike the reference there is no separate Variable wrapper — the
scope maps names directly to values plus a small metadata dict.
"""
from __future__ import annotations


class Scope:
    def __init__(self, parent=None):
        self._parent = parent
        self._vars = {}
        self._kids = []

    # --- tree ---
    @property
    def parent(self):
        return self._parent

    def new_scope(self):
        kid = Scope(self)
        self._kids.append(kid)
        return kid

    def drop_kids(self):
        self._kids = []

    # --- vars ---
    def var(self, name):
        """Find-or-create in THIS scope (reference Scope::Var)."""
        if name not in self._vars:
            self._vars[name] = None
        return self._vars.get(name)

    def set(self, name, value):
        self._vars[name] = value

    def find_var(self, name):
        """Recursive lookup (reference Scope::FindVar). Returns value or
        raises KeyError if the name exists nowhere."""
        s = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s._parent
        raise KeyError(name)

    def has_var(self, name):
        s = self
        while s is not None:
            if name in s._vars:
                return True
            s = s._parent
        return False

    def find_scope_of(self, name):
        s = self
        while s is not None:
            if name in s._vars:
                return s
            s = s._parent
        return None

    def erase(self, names):
        for n in names:
            self._vars.pop(n, None)

    def local_var_names(self):
        return list(self._vars.keys())

    def __contains__(self, name):
        return self.has_var(name)


_global_scope = Scope()


def global_scope():
    return _global_scope


def reset_global_scope():
    global _global_scope
    _global_scope = Scope()
    return _global_scope
