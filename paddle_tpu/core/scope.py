"""Scope: name -> value tree with parent lookup.

Parity: reference framework/scope.h:39 / variable.h:26.  Values are
type-erased Python objects; device tensors are jax.Arrays (committed to a
device), host-side containers (LoDTensor, readers, step scopes) are plain
objects.  Unlike the reference there is no separate Variable wrapper — the
scope maps names directly to values plus a small metadata dict.
"""
from __future__ import annotations


class Scope:
    def __init__(self, parent=None):
        self._parent = parent
        self._vars = {}
        self._kids = []
        # bumped on every write/erase; PreparedProgram (executor_impl)
        # watches the chain sum to know when its device-resident state
        # must be refreshed from the scope, and the per-name write
        # version to tell its OWN sync-backs apart from external writes
        # (an external write to a name always wins over device state)
        self._version = 0
        self._write_versions = {}
        # prepared-execution attachments (weakrefs to objects with
        # ``._dirty`` + ``.sync_scope()``): their device-resident train
        # state is flushed back before any value is read through this
        # scope, so readers never observe stale/donated buffers
        self._prepared_registry = None
        self._in_flush = False

    # --- tree ---
    @property
    def parent(self):
        return self._parent

    def new_scope(self):
        kid = Scope(self)
        self._kids.append(kid)
        return kid

    def drop_kids(self):
        self._kids = []

    # --- vars ---
    def var(self, name):
        """Find-or-create in THIS scope (reference Scope::Var)."""
        if name not in self._vars:
            self._vars[name] = None
        return self._vars.get(name)

    def set(self, name, value):
        self._vars[name] = value
        self._version += 1
        self._write_versions[name] = self._version

    def find_var(self, name):
        """Recursive lookup (reference Scope::FindVar). Returns value or
        raises KeyError if the name exists nowhere.  Flushes attached
        prepared-execution state first so a direct read never observes a
        value the device has moved past (or a donated buffer)."""
        s = self
        while s is not None:
            if s._prepared_registry is not None:
                s.flush_prepared()
            if name in s._vars:
                return s._vars[name]
            s = s._parent
        raise KeyError(name)

    def flush_prepared(self, exclude=None):
        """sync_scope() every dirty prepared attachment of THIS scope
        (see core/executor_impl.PreparedProgram; pipeline joins too).
        Dead weakrefs are pruned; re-entry is a no-op."""
        reg = self._prepared_registry
        if not reg or self._in_flush:
            return
        self._in_flush = True
        try:
            live = []
            for ref in reg:
                p = ref()
                if p is None:
                    continue
                live.append(ref)
                if p is not exclude and getattr(p, "_dirty", False):
                    p.sync_scope()
            if len(live) != len(reg):
                reg[:] = live
        finally:
            self._in_flush = False

    def attach_prepared(self, prep):
        """Register ``prep`` (has ``._dirty`` + ``.sync_scope()``) for
        read-time flushing on this scope."""
        import weakref

        if self._prepared_registry is None:
            self._prepared_registry = []
        self._prepared_registry.append(weakref.ref(prep))

    def has_var(self, name):
        s = self
        while s is not None:
            if name in s._vars:
                return True
            s = s._parent
        return False

    def find_scope_of(self, name):
        s = self
        while s is not None:
            if name in s._vars:
                return s
            s = s._parent
        return None

    def erase(self, names):
        removed = False
        for n in names:
            if n in self._vars:
                del self._vars[n]
                self._write_versions.pop(n, None)
                removed = True
        if removed:  # a no-op erase must not force prepared re-stages
            self._version += 1

    def chain_version(self):
        """Sum of versions up the parent chain: any write visible to a
        lookup from this scope changes the number."""
        v = 0
        s = self
        while s is not None:
            v += s._version
            s = s._parent
        return v

    def local_var_names(self):
        return list(self._vars.keys())

    def __contains__(self, name):
        return self.has_var(name)


_global_scope = Scope()


def global_scope():
    return _global_scope


def reset_global_scope():
    global _global_scope
    _global_scope = Scope()
    return _global_scope
