"""Runtime flag registry — the gflags analog.

Parity: reference platform/enforce + gflags flags (FLAGS_check_nan_inf
in framework/operator.cc:590, FLAGS_benchmark in executor.cc, plus the
env forwarding done by python/paddle/fluid/__init__.py:__bootstrap__,
which passes selected FLAGS_* env vars to InitGflags).  Here flags are
plain Python with the same ``FLAGS_<name>`` environment override.
"""
from __future__ import annotations

import os

__all__ = ["FLAGS", "define_flag"]


def _parse(raw, default):
    if isinstance(default, bool):
        return raw.lower() in ("1", "true", "yes", "on")
    if isinstance(default, int):
        return int(raw)
    if isinstance(default, float):
        return float(raw)
    return raw


class _Flags:
    """Attribute-style access; unknown flags raise AttributeError."""

    def __init__(self):
        object.__setattr__(self, "_defs", {})
        object.__setattr__(self, "_watchers", {})

    def define(self, name, default, help=""):
        raw = os.environ.get("FLAGS_" + name)
        value = _parse(raw, default) if raw is not None else default
        self._defs[name] = {"value": value, "default": default,
                            "help": help}

    def __getattr__(self, name):
        try:
            return self._defs[name]["value"]
        except KeyError:
            raise AttributeError("undefined flag %r (define it with "
                                 "flags.define_flag)" % name)

    def __setattr__(self, name, value):
        if name not in self._defs:
            raise AttributeError("undefined flag %r" % name)
        self._defs[name]["value"] = value
        for fn in self._watchers.get(name, ()):
            fn(value)

    def watch(self, name, fn):
        """Call ``fn(value)`` now and again on every later
        ``FLAGS.<name> = value`` assignment — for flags whose value is
        mirrored into a hot-path attribute (e.g. FLAGS_telemetry ->
        observability TRACER.on: the mirror keeps the per-step check to
        one attribute read, the watcher keeps a runtime flag flip from
        being silently ignored)."""
        self._watchers.setdefault(name, []).append(fn)
        if name in self._defs:
            fn(self._defs[name]["value"])

    def flags(self):
        return {k: v["value"] for k, v in self._defs.items()}


FLAGS = _Flags()


def define_flag(name, default, help=""):
    FLAGS.define(name, default, help)


def apply_xla_flags():
    """Materialize the FLAGS_xla_* scheduler knobs into XLA_FLAGS.

    XLA parses XLA_FLAGS exactly once, at first backend creation, so
    call this BEFORE the first jax device touch (bench.py does; the
    executor calls it defensively at first compile).  Returns the tokens
    applied.  The same values ride the executor compile-cache key, so an
    in-process flag flip can never serve a stale executable — but it
    still needs a fresh process to reach XLA itself (MIGRATION.md)."""
    tokens = []
    if FLAGS.xla_latency_hiding_scheduler:
        tokens.append("--xla_tpu_enable_latency_hiding_scheduler=true")
    if FLAGS.xla_extra_flags:
        tokens.extend(str(FLAGS.xla_extra_flags).split())
    if not tokens:
        return []
    cur = os.environ.get("XLA_FLAGS", "")
    have = set(cur.split())
    missing = [t for t in tokens if t not in have]
    if missing:
        os.environ["XLA_FLAGS"] = (cur + " " + " ".join(missing)).strip()
    return tokens


# core runtime flags (reference analogs cited above)
define_flag("check_nan_inf", False,
            "raise on the first op producing nan/inf, naming it "
            "(reference FLAGS_check_nan_inf).  run() executes op-by-op "
            "like the reference; the prepared hot path instead maps "
            "this onto the ISSUE 8 numerics observatory (fused health "
            "fetch + bisect re-run of a tripped step — same first-bad-"
            "op answer, one-dispatch steps; see FLAGS_check_numerics "
            "in observability/numerics.py and MIGRATION.md)")
define_flag("benchmark", False,
            "print per-run wall time (reference FLAGS_benchmark)")
define_flag("check_program", "warn",
            "ahead-of-time program verification (paddle_tpu/analysis): "
            "'off' never verifies; 'warn' (default) verifies each "
            "program once per (uid, version) — i.e. only on a "
            "compile-cache miss — and warns on error-severity "
            "diagnostics; 'error' raises ProgramVerificationError "
            "instead.  Zero per-step cost: steady-state training never "
            "re-verifies")
define_flag("check_suppress", "",
            "comma-separated checker names the default verification "
            "pipeline skips (e.g. 'lifetime,numerics'): applies to the "
            "executor verify hook and any verify_program call that "
            "does not name explicit checkers.  The escape hatch for "
            "FLAGS_check_program=error users when a new checker lands "
            "— see MIGRATION.md 'Donation-lifetime checker'")
define_flag("sanitizer", "off",
            "runtime sanitizers (core/sanitizer.py): 'off' (default; "
            "the instrumented hot paths pay ONE module-attribute read, "
            "gated < 2% by tools/telemetry_overhead.py), 'buffers' "
            "(use-after-donate checking: every donation swaps the "
            "aliasing scope slot to a poisoned husk that raises "
            "BufferLifetimeError naming var/op/step/site on any host "
            "access before re-bind), 'locks' (lock-discipline "
            "checking: instrumented locks record per-thread "
            "acquisition order, detect order-inversion cycles and "
            "non-reentrant acquisition on signal-handler-reachable "
            "paths, reported as lockgraph_<pid>.json), 'all', or "
            "'weaver' (deterministic-schedule exploration: make_lock/"
            "make_event/make_condition hand out analysis/weaver.py "
            "primitives whose every acquire/release/wait/notify is a "
            "scheduling decision under the active Weaver's virtual "
            "clock; implies buffer checking so scenario invariants "
            "can trip; see tools/weaver.py).  "
            "Lock instrumentation is chosen at lock CREATION time — "
            "set the flag (or FLAGS_sanitizer env) before the "
            "subsystems under test construct their locks.  Every trip "
            "increments sanitizer_trips_total and leaves a flight "
            "dump when FLAGS_telemetry_dump_dir is set")
define_flag("conv_nhwc", False,
            "lower conv2d through NHWC (MXU-preferred layout); the "
            "boundary transposes cancel across conv chains in XLA")
define_flag("bn_bf16", False,
            "under AMP, let batch_norm consume/produce bf16 (statistics "
            "stay f32 internally, like layer_norm) instead of casting "
            "its inputs to f32; halves BN-chain activation bytes on "
            "HBM-bound conv nets")
define_flag("matmul_precision", "",
            "XLA dot/conv precision for f32 operands: '' (backend "
            "default: TPU multiplies f32 in bf16 passes, the fast "
            "mode), 'float32'/'highest' (exact f32, ~3-6x slower "
            "matmuls on TPU).  The TPU analog of the reference's "
            "cuDNN math-mode control; see MIGRATION.md 'float32 "
            "matmul precision on TPU'")
define_flag("conv_layout", "NCHW",
            "convnet pipeline layout: 'NCHW' (reference contract; the "
            "default) or 'NHWC' — models that honor the flag (e.g. "
            "models/resnet.py get_model) run the LayoutTranspiler NHWC "
            "pass: data_format propagated through conv/pool/bn/"
            "elementwise chains, conv weights pinned HWIO at creation, "
            "and conv+BN+act stages fused into the Pallas conv-stage "
            "kernel.  Acts at PROGRAM BUILD time (get_model) — flip it "
            "before building, not on a built program; the NCHW program "
            "stays selectable for bisection")
define_flag("conv_fused_stages", True,
            "with conv_layout=NHWC, also run FuseConvBNActPass "
            "(conv+BN(+residual)(+relu) -> fused_conv2d_bn_act backed "
            "by kernels/conv_fused.py); off = layout pass alone, for "
            "attributing wins between the two levers")
define_flag("transformer_fuse", False,
            "transformer block fusion (ISSUE 7): models that honor the "
            "flag (models/transformer.py get_model) run "
            "FuseTransformerBlockPass before backward generation — the "
            "QKV projections collapse to one wide matmul, "
            "matmul+bias(+gelu/relu)(+dropout)(+residual) chains and "
            "residual-add+layer_norm chains become fused ops backed by "
            "kernels/matmul_fused.py (f32 VMEM accumulator epilogues, "
            "explicit saved-activation grad lowerings, identical-math "
            "XLA fallback off-TPU / over-budget).  Acts at PROGRAM "
            "BUILD time, like conv_layout; the unfused program stays "
            "the default for bisection")
define_flag("autotune_cache_dir", "",
            "persistent shape-keyed autotune cache directory "
            "(paddle_tpu/tuning): sweep tools (conv_tune/flash_tune/"
            "matmul_tune) record their best tile configs per (kernel, "
            "shape, dtype, backend) into autotune_cache.json here, and "
            "kernel lowerings consult it at compile time — every "
            "future model inherits the best tiles instead of "
            "re-sweeping.  Unset (default) = built-in defaults; a "
            "corrupt/missing cache file degrades to defaults without "
            "error.  The cache fingerprint rides the executor "
            "compile-cache key, so re-tuning never serves a stale "
            "executable")
define_flag("xla_latency_hiding_scheduler", False,
            "enable XLA's latency-hiding scheduler "
            "(--xla_tpu_enable_latency_hiding_scheduler): overlaps "
            "async copies/collectives with compute when scheduling "
            "fusions.  Applied to XLA_FLAGS by apply_xla_flags() "
            "(bench.py calls it before backend init; flipping it in a "
            "live process needs a restart — XLA parses XLA_FLAGS once) "
            "and part of the executor compile-cache key")
define_flag("xla_extra_flags", "",
            "extra raw XLA_FLAGS tokens appended by apply_xla_flags() "
            "(e.g. '--xla_tpu_enable_async_collective_fusion=true'); "
            "reproducible-experiment plumbing for scheduler knobs — "
            "part of the executor compile-cache key")
define_flag("telemetry", False,
            "span tracing (paddle_tpu/observability): per-step executor "
            "spans, RPC round spans with (round, sender, seq) "
            "correlation ids, Pallas launch-site spans.  Off (the "
            "default) the instrumented hot paths pay one attribute "
            "read — tools/telemetry_overhead.py gates this at < 2% of "
            "the prepared step.  Metrics (counters/histograms) are "
            "ALWAYS on; this flag gates tracing only")
define_flag("telemetry_ring_size", 4096,
            "completed-span ring capacity of the process tracer; the "
            "same ring is the flight recorder's history (oldest spans "
            "evict first)")
define_flag("telemetry_dump_dir", "",
            "when set: processes with tracing on write "
            "trace_<label>_<pid>.json here at exit (merge them with "
            "tools/trace_report.py), flight-recorder dumps "
            "(flight_<pid>_<n>.json) land here instead of the system "
            "temp dir, and injected faults leave one dump per fault "
            "point (tools/fault_matrix.py asserts it)")
define_flag("moe_metrics", True,
            "MoE routing observability (ISSUE 15 rider): the moe_ffn "
            "routing shard emits per-expert load, dropped-token "
            "fraction and router entropy into the always-on metrics "
            "registry via a host callback (one small transfer per "
            "step; tools/trace_report.py --moe rolls them up).  Off "
            "removes the callback from the traced program entirely")
define_flag("serve_max_batch", 16,
            "serving tier (paddle_tpu/serving): cap of the power-of-2 "
            "shape-bucket ladder (1, 2, 4, ... serve_max_batch).  The "
            "continuous batcher assembles at most this many rows per "
            "dispatch; each bucket is backed by its own pre-compiled "
            "AOT executable (compiled at model load for the warm set, "
            "in the background on a bucket miss)")
define_flag("serve_max_wait_us", 2000,
            "serving tier: continuous-batching deadline, microseconds, "
            "anchored at the FIRST queued request's arrival.  The "
            "scheduler launches a batch the moment it is full OR this "
            "deadline expires — it never waits for a full batch, and a "
            "request that arrived while the device was busy ships on "
            "the very next dispatch (its deadline already passed).  "
            "0 = never coalesce-wait: launch whatever is queued")
define_flag("serve_warm_buckets", "",
            "serving tier: comma-separated bucket sizes to pre-compile "
            "at model load (e.g. '1,8').  Empty (default) warms the "
            "whole ladder up to serve_max_batch.  A cold bucket hit at "
            "runtime falls to the nearest warm bucket while a "
            "background thread compiles the missed one")
define_flag("serve_kv_block_size", 16,
            "generative serving (serving/generative.py): tokens per KV "
            "cache block.  Power of two; every sequence's K/V occupies "
            "ceil(context/block_size) blocks of the tenant's paged "
            "pool, gathered through a per-sequence block table by the "
            "decode-mode flash attention kernel "
            "(kernels/flash_attention.paged_attention)")
define_flag("serve_kv_blocks", 512,
            "generative serving: KV cache blocks in a tenant's "
            "device-resident pool (one is reserved as the padding "
            "scratch block).  Memory = 2 x layers x blocks x "
            "block_size x d_model x 4 bytes.  When admission or "
            "mid-decode growth would exceed the pool, the scheduler "
            "counts serve_kv_alloc_failures_total and preempts the "
            "youngest sequence (serve_kv_preemptions_total) — "
            "recompute-style eviction, requeued at the queue front")
define_flag("serve_prefix_cache", False,
            "generative serving (ISSUE 19): copy-on-write prefix KV "
            "reuse.  On, a tenant keeps a radix index over prompt "
            "token ids at block granularity: admission shares the "
            "cached prefix blocks by refcount (serve_kv_blocks_shared "
            "gauge), prefill computes and stores ONLY the un-cached "
            "suffix (serve_kv_prefix_hits gauge / "
            "serve_prefix_tokens_* counters), a shared block written "
            "mid-block is copied first (COW, "
            "serve_kv_cow_copies_total), and finished prompts' blocks "
            "park in a refcount-zero LRU instead of the free list — "
            "evicted only under allocation pressure.  Per-tenant "
            "override: load_generative(prefix_cache=...)")
define_flag("serve_spec_k", 0,
            "generative serving (ISSUE 19): speculative decoding "
            "draft depth.  k > 0 makes the decode loop propose k "
            "tokens per iteration from the tenant's draft LM (a "
            "load_generative(draft=...) requirement) and verify all "
            "k in ONE batched target dispatch — greedy acceptance "
            "keeps the longest matching prefix plus the target's "
            "correction token, so output stays bit-identical to "
            "non-speculative greedy decode (the certificate in "
            "tools/serve_bench.py).  0 (default) is plain one-token "
            "decode.  Per-tenant override: load_generative(spec_k=...)")
define_flag("dist_compress", "",
            "gradient compression codec for the pserver wire "
            "(distributed/compress.py): '' (raw frames, the default), "
            "'fp16' (half-precision dense grads, bit-exact on fp16-"
            "representable values), 'int8' (per-chunk linear "
            "quantization with a trainer-side error-feedback residual "
            "so the quantization bias cancels across steps), or "
            "'topk' (top-k magnitude sparsification with error "
            "feedback; ratio via FLAGS_dist_topk_ratio).  SelectedRows "
            "grads additionally ship int8 rows + delta-encoded int32 "
            "ids under ANY non-empty mode.  Compressed frames are "
            "wire-format v2: the client negotiates per endpoint "
            "(WireVersion RPC) and falls back to raw frames against an "
            "old server — see MIGRATION.md")
define_flag("dist_topk_ratio", 0.01,
            "fraction of dense-grad elements kept by the 'topk' codec "
            "(indices + values of the largest-|g| entries; the rest "
            "accumulates in the error-feedback residual)")
define_flag("dist_staleness", 0,
            "bounded-staleness sync training: a trainer's barrier for "
            "round r acks once round r-k is applied+durable, so "
            "trainers run up to k rounds ahead of the slowest peer "
            "(param gets accept k-stale values).  0 (default) is "
            "today's fully-synchronous round — bit-exact with the "
            "k-unaware wire.  With k>0 the client retains k+1 rounds "
            "of replay cache; a server crash can lose at most the k "
            "un-acked rounds (bounded loss, like bounded staleness)")
define_flag("dist_hier_local", 0,
            "hierarchical gradient aggregation: number of trainers "
            "per host group (0 disables).  Group leader (lowest "
            "trainer id in the group) pre-reduces the group's grads "
            "locally and makes ONE upload + ONE barrier per round, "
            "cutting pserver ingress and fanin by this factor; "
            "followers talk to the leader over a loopback fastwire "
            "channel (distributed/hierarchy.py) and keep reading "
            "params directly.  Requires PADDLE_TRAINER_ID and "
            "trainers %% dist_hier_local == 0")
define_flag("dist_hier_port", 18970,
            "base TCP port of the host-local aggregation channel; "
            "group g listens on dist_hier_port + g")
define_flag("ledger_sample_ms", 250,
            "resource-ledger sampling interval, milliseconds "
            "(observability/ledger.py): a background collector reads "
            "every registered per-subsystem probe (pserver pending "
            "grads, reply/replay caches, barrier quorum, apply "
            "backlog, hier fan-in buffers, fastwire sockets) at this "
            "rate, exports the values as ledger_* gauges, and appends "
            "them to a bounded time-series ring that rides every "
            "flight-recorder dump.  0 disables the collector (probes "
            "still answer on-demand snapshots).  Overhead gated < 2% "
            "by tools/telemetry_overhead.py")
define_flag("ledger_ring", 2048,
            "samples retained by the resource-ledger time-series ring "
            "(oldest evict first); the flight recorder embeds the "
            "newest slice of it")
define_flag("ledger_watch", "",
            "collapse tripwires: comma-separated 'resource>value' "
            "terms (e.g. 'pserver_pending_grad_bytes>100000000').  "
            "When a sampled ledger value crosses its threshold the "
            "collector writes ONE flight-recorder dump per resource "
            "per process (reason 'ledger:<resource>') carrying the "
            "full ledger series — the scale lab's collapse forensics "
            "(tools/scale_bench.py --collapse)")
define_flag("pserver_reply_cache_mb", 256,
            "byte cap (MB) of the pserver per-shard reply cache "
            "(encoded param frames served to every trainer's get).  "
            "Least-recently-used entries evict past the cap "
            "(pserver_reply_cache_evictions_total counts them) — an "
            "eviction only costs a re-encode on the next get, so a "
            "256-trainer run cannot OOM the server through cached "
            "replies.  0 = unbounded (the pre-ISSUE-12 behavior)")
define_flag("rpc_replay_cache_mb", 512,
            "byte cap (MB) of the trainer-side per-endpoint replay "
            "cache (post-codec grads retained for reconnect replay; "
            "k+1 rounds under bounded staleness).  Oldest non-current "
            "rounds evict first (rpc_replay_cache_evictions_total); "
            "an evicted round is unrecoverable on a server restart "
            "and walks forward as an empty apply, exactly like a "
            "round outside the staleness window — see MIGRATION.md.  "
            "0 = unbounded (the pre-ISSUE-12 behavior)")
define_flag("barrier_rescan", False,
            "legacy O(trainers) barrier-quorum bookkeeping: rescan "
            "the whole sender map on every ack instead of maintaining "
            "the quorum count incrementally.  Exists for the scale "
            "lab's before/after A/B (tools/scale_bench.py "
            "--before-after) — never enable in production")
define_flag("tsdb_dir", "",
            "root directory of the Watchtower time-series store "
            "(observability/tsdb.py).  When set, a background sampler "
            "appends a fixed-interval snapshot of EVERY always-on "
            "metric (counters/gauges + histogram percentiles, with "
            "the resource ledger refreshed into the same row) to a "
            "per-(label, pid) subdirectory of append-only binary "
            "segments — the durable history the SLO engine "
            "(FLAGS_slo_spec), tools/watchtower.py and "
            "tools/perf_sentinel.py read.  Empty disables (the "
            "default: nothing is written)")
define_flag("tsdb_sample_ms", 250,
            "Watchtower sampler interval, milliseconds; 0 disables "
            "the background sampler (explicit "
            "tsdb.sample_registry() calls still work).  Overhead "
            "gated < 2% of the interval by "
            "tools/telemetry_overhead.py")
define_flag("tsdb_segment_bytes", 1 << 20,
            "active tsdb segment seals and rotates at this size; "
            "each sealed segment is one mmap-friendly fixed-width "
            "binary file plus a row in the JSON meta index")
define_flag("tsdb_retention_mb", 64,
            "per-process tsdb byte budget: oldest sealed segments "
            "drop once the store exceeds it (the active segment "
            "always survives).  0 = unbounded")
define_flag("slo_spec", "",
            "SLO specs for the Watchtower burn-rate engine "
            "(observability/slo.py): a .json/.toml file path or an "
            "inline comma-separated objective list "
            "('serve_request_ms.p99<=10,"
            "pserver_rounds_applied_total.rate>=1').  With "
            "FLAGS_tsdb_dir set, a background evaluator checks every "
            "spec against the store on FLAGS_slo_eval_ms cadence; a "
            "window whose burn rate crosses its threshold increments "
            "slo_alerts_total and writes ONE flight dump per "
            "(slo, window) with the offending series embedded")
define_flag("slo_eval_ms", 1000,
            "SLO evaluation cadence, milliseconds; 0 disables the "
            "background evaluator (slo.evaluate_once() still works)")
define_flag("auto_layout", False,
            "single-device accelerator path: AOT-compile with XLA-chosen "
            "(AUTO) parameter layouts and keep persistable buffers in "
            "them across steps.  Experimental knob: measured neutral on "
            "ResNet-50/transformer (XLA's default argument layouts "
            "already match; the profile's relayout copies are internal "
            "to conv scheduling), but it removes boundary copies when a "
            "model's parameters do want non-default layouts")
