"""Data type and variable-kind enums + numpy/jax mappings.

Parity: reference framework/framework.proto VarType (dtype enum) and
framework/data_type.h.  bfloat16 is first-class (TPU native compute type).
"""
import numpy as np

from paddle_tpu.proto import framework_pb2 as pb

try:
    import ml_dtypes

    _BF16 = np.dtype(ml_dtypes.bfloat16)
except Exception:  # pragma: no cover
    import jax.numpy as _jnp

    _BF16 = np.dtype(_jnp.bfloat16)


class DataType:
    """Thin namespace over the proto enum (values are ints)."""

    UNSET = pb.DT_UNSET
    FP32 = pb.DT_FLOAT32
    FP64 = pb.DT_FLOAT64
    INT32 = pb.DT_INT32
    INT64 = pb.DT_INT64
    BOOL = pb.DT_BOOL
    BF16 = pb.DT_BFLOAT16
    FP16 = pb.DT_FLOAT16
    UINT8 = pb.DT_UINT8
    INT8 = pb.DT_INT8
    INT16 = pb.DT_INT16
    UINT32 = pb.DT_UINT32
    UINT64 = pb.DT_UINT64


class VarKind:
    DENSE = pb.VK_DENSE
    LOD_TENSOR = pb.VK_LOD_TENSOR
    SELECTED_ROWS = pb.VK_SELECTED_ROWS
    READER = pb.VK_READER
    STEP_SCOPES = pb.VK_STEP_SCOPES
    LOD_TENSOR_ARRAY = pb.VK_LOD_TENSOR_ARRAY
    FETCH_LIST = pb.VK_FETCH_LIST
    FEED_MINIBATCH = pb.VK_FEED_MINIBATCH
    RAW = pb.VK_RAW
    LOD_RANK_TABLE = pb.VK_LOD_RANK_TABLE


_NP_TO_PROTO = {
    np.dtype(np.float32): DataType.FP32,
    np.dtype(np.float64): DataType.FP64,
    np.dtype(np.int32): DataType.INT32,
    np.dtype(np.int64): DataType.INT64,
    np.dtype(np.bool_): DataType.BOOL,
    _BF16: DataType.BF16,
    np.dtype(np.float16): DataType.FP16,
    np.dtype(np.uint8): DataType.UINT8,
    np.dtype(np.int8): DataType.INT8,
    np.dtype(np.int16): DataType.INT16,
    np.dtype(np.uint32): DataType.UINT32,
    np.dtype(np.uint64): DataType.UINT64,
}
_PROTO_TO_NP = {v: k for k, v in _NP_TO_PROTO.items()}

_STR_TO_PROTO = {
    "float32": DataType.FP32,
    "float64": DataType.FP64,
    "int32": DataType.INT32,
    "int64": DataType.INT64,
    "bool": DataType.BOOL,
    "bfloat16": DataType.BF16,
    "float16": DataType.FP16,
    "uint8": DataType.UINT8,
    "int8": DataType.INT8,
    "int16": DataType.INT16,
    "uint32": DataType.UINT32,
    "uint64": DataType.UINT64,
}


def np_dtype_to_proto(dtype):
    """numpy dtype / dtype-string / proto int -> proto DataType int."""
    if isinstance(dtype, int):
        return dtype
    if isinstance(dtype, str):
        return _STR_TO_PROTO[dtype]
    return _NP_TO_PROTO[np.dtype(dtype)]


def proto_to_np_dtype(proto_dtype):
    return _PROTO_TO_NP[proto_dtype]


def dtype_is_floating(proto_dtype):
    return proto_dtype in (DataType.FP32, DataType.FP64, DataType.BF16,
                           DataType.FP16)


def dtype_name(proto_dtype):
    return str(proto_to_np_dtype(proto_dtype))
