"""Crash-safe filesystem commits.

One implementation of the write-tmp -> fsync -> rename idiom, shared by
every durable writer (master snapshots, checkpoint _SUCCESS markers,
pserver shard markers) so the subtle parts — fsync before rename, the
directory fsync that actually makes the rename survive power loss on
ext4/xfs, optional backup rotation — are fixed in exactly one place.
"""
from __future__ import annotations

import os

__all__ = ["atomic_write"]


def _fsync_dir(dirname):
    """Persist a rename: fsync the containing directory (best-effort —
    not every platform/filesystem allows opening a directory)."""
    try:
        fd = os.open(dirname or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write(path, data, backup_suffix=None):
    """Write ``data`` (str or bytes) to ``path`` atomically.

    tmp file -> flush + fsync -> (optionally rotate the existing file to
    ``path + backup_suffix``) -> rename -> fsync(dir).  A crash at any
    point leaves either the previous complete file (or its backup) or
    the new complete file — never a truncated one.
    """
    tmp = path + ".tmp"
    with open(tmp, "wb" if isinstance(data, bytes) else "w") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    if backup_suffix and os.path.exists(path):
        os.replace(path, path + backup_suffix)
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path))
