#!/usr/bin/env python
"""Headline benchmark: ResNet-50 ImageNet-shape training throughput.

Mirrors the reference harness's metric — examples/sec over timed
iterations (reference benchmark/fluid/fluid_benchmark.py:297-301) — on the
fluid-style ResNet-50 (benchmark/fluid/models/resnet.py) built with
paddle_tpu and compiled by XLA onto whatever accelerator is attached
(one TPU chip under the driver; CPU otherwise).

Prints ONE json line:
  {"metric": ..., "value": N, "unit": "images/sec", "vs_baseline": N}

vs_baseline: the only in-repo published ResNet-50 training number is the
MKL-DNN CPU baseline, 81.69 images/sec at bs=64
(reference benchmark/IntelOptimizedPaddle.md:39-45); value/81.69.
"""
import json
import os
import sys
import time

import numpy as np


def main():
    model_name = os.environ.get("BENCH_MODEL", "resnet50")
    on_accel = False
    try:
        import jax
        on_accel = any(d.platform != "cpu" for d in jax.devices())
    except Exception:
        pass
    # Keep CPU smoke-runs fast; real run uses ImageNet shapes.
    if on_accel:
        batch_size = int(os.environ.get("BENCH_BATCH", "64"))
        data_set = os.environ.get("BENCH_DATASET", "flowers")
        iters = int(os.environ.get("BENCH_ITERS", "20"))
    else:
        batch_size = int(os.environ.get("BENCH_BATCH", "16"))
        data_set = os.environ.get("BENCH_DATASET", "cifar10")
        iters = int(os.environ.get("BENCH_ITERS", "5"))

    import paddle_tpu.fluid as fluid
    from paddle_tpu.models import resnet

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        avg_cost, (data, label), (acc,) = resnet.get_model(
            data_set=data_set, depth=50 if model_name == "resnet50" else 32)

    place = fluid.TPUPlace() if on_accel else fluid.CPUPlace()
    exe = fluid.Executor(place)
    exe.run(startup)

    dshape = [batch_size] + list(data.shape[1:])
    rng = np.random.RandomState(0)
    images = rng.rand(*dshape).astype(np.float32)
    class_dim = 102 if data_set == "flowers" else 10
    labels = rng.randint(0, class_dim, (batch_size, 1)).astype(np.int64)
    feed = {data.name: images, label.name: labels}

    # Pre-stage the batch on device (the reference reads from a
    # double-buffered reader; a constant device-resident batch is the
    # use_fake_data analog) and warm up compile + autotuning.
    try:
        import jax
        dev = place.jax_device()
        feed = {k: jax.device_put(v, dev) for k, v in feed.items()}
    except Exception:
        pass
    for _ in range(2):
        exe.run(main_prog, feed=feed, fetch_list=[avg_cost])

    # Timed loop: steps are dispatched asynchronously (XLA execution is
    # async like the reference's CUDA streams); one sync at the end.
    t0 = time.time()
    loss = None
    for _ in range(iters):
        loss, = exe.run(main_prog, feed=feed, fetch_list=[avg_cost],
                        return_numpy=False)
    loss = np.asarray(loss)  # blocks until the chain has drained
    elapsed = time.time() - t0

    images_per_sec = batch_size * iters / elapsed
    baseline = 81.69  # MKL-DNN CPU ResNet-50 bs64 (IntelOptimizedPaddle.md:41)
    print(json.dumps({
        "metric": "resnet50_%s_train_bs%d" % (data_set, batch_size),
        "value": round(images_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(images_per_sec / baseline, 3),
    }))


if __name__ == "__main__":
    sys.exit(main())
