#!/usr/bin/env python
"""Headline benchmark: ResNet-50 ImageNet-shape training throughput.

Mirrors the reference harness's metric — examples/sec over timed
iterations (reference benchmark/fluid/fluid_benchmark.py:297-301) — on the
fluid-style ResNet-50 (benchmark/fluid/models/resnet.py) built with
paddle_tpu and compiled by XLA onto whatever accelerator is attached
(one TPU chip under the driver; CPU otherwise).

Accelerator runs default to bf16 mixed precision (Float16Transpiler —
the TPU analog of reference paddle/contrib/float16/float16_transpiler.py)
at batch 256; BENCH_AMP=0 / BENCH_BATCH override.

Convnet layout/fusion knobs (ISSUE 5; see README "Convolution layout &
fusion"): BENCH_LAYOUT=NHWC runs the LayoutTranspiler pipeline (NHWC
end-to-end, HWIO-pinned weights, Pallas fused conv stages;
BENCH_FUSED_STAGES=0 for the layout pass alone), BENCH_DEPTH overrides
the ResNet depth, and FLAGS_xla_latency_hiding_scheduler=1 /
FLAGS_xla_extra_flags="..." plumb XLA scheduler experiments — applied
before backend init and recorded in the JSON (xla_flags) plus the
executor compile-cache key.  The headline JSON carries data_format,
fused_stages and (under BENCH_PROFILE) xplane-sourced per_category_ms
so every BENCH_*.json row names the experiment that produced it.

Prints ONE json line:
  {"metric": ..., "value": N, "unit": "images/sec", "vs_baseline": N,
   "tflops": N, "mfu": N, "amp": bool}

vs_baseline: the only in-repo published ResNet-50 training number is the
MKL-DNN CPU baseline, 81.69 images/sec at bs=64
(reference benchmark/IntelOptimizedPaddle.md:39-45); value/81.69.

tflops/mfu: delivered training FLOP/s from the standard analytic count
(~4.1 GFLOPs/image forward at 224x224, x3 for fwd+bwd ~= 12.3e9), against
BENCH_PEAK_TFLOPS (default 197, TPU v5e bf16 peak).  Only reported for
224x224 datasets where the analytic count applies.

The default (accelerator) run also embeds a ``secondary`` metric: the
compute-bound transformer-LM flagship (d1024 L6, flash attention), whose
MFU shows the stack's ceiling when the workload is not HBM-bound the way
ResNet-50 is on v5e (see the roofline fields on the headline metric).
BENCH_SECONDARY=0 skips it.
"""
import contextlib
import json
import os
import signal
import sys
import threading
import time

import numpy as np

TRAIN_FLOPS_PER_IMG_224 = 12.3e9
TRAIN_FLOPS_PER_IMG_VGG16_224 = 46.5e9  # ~15.5 GF fwd x3
DEFAULT_PEAK_TFLOPS = 197.0  # v5e bf16


@contextlib.contextmanager
def _wall_budget(seconds, what):
    """SIGALRM wall-clock budget: a hung device call inside ``what``
    degrades to a TimeoutError the caller turns into an ``*_error``
    JSON field, instead of wedging the whole bench into the driver's
    rc:124 with no artifact at all.  No-op off the main thread or with
    a non-positive budget."""
    if seconds <= 0 or \
            threading.current_thread() is not threading.main_thread():
        yield
        return

    def _handler(signum, frame):
        # flight recorder FIRST: the artifact must exist even if the
        # TimeoutError is swallowed or the process dies during unwind
        path = None
        try:
            from paddle_tpu.observability import flight
            path = flight.dump("wall_budget:%s" % what,
                               blocked={"op": what,
                                        "budget_s": int(seconds)})
        except Exception:
            path = None
        msg = "%s exceeded its %ds wall budget" % (what, int(seconds))
        if path:
            msg += " (flight recorder: %s)" % path
        raise TimeoutError(msg)

    prev = signal.signal(signal.SIGALRM, _handler)
    # never truncate a sub-second budget to alarm(0) == "no alarm"
    signal.alarm(max(1, int(seconds)))
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, prev)


def _probe_backend(timeout):
    """Up-front liveness probe: one tiny jit, watched from the OUTSIDE.
    A dead accelerator tunnel fails HERE, in seconds and explicitly,
    instead of hanging the first 100-layer compile until the driver
    kills the run.  The probe runs in a daemon thread because a wedged
    PJRT call never returns to the interpreter — a SIGALRM handler
    could not interrupt it; the main thread just stops waiting.
    BENCH_FAKE_DEAD=1 simulates the dead tunnel (test hook for the
    error artifact path)."""
    result = {}

    def probe():
        try:
            if os.environ.get("BENCH_FAKE_DEAD") == "1":
                time.sleep(timeout + 30)   # hang like a dead tunnel
            import jax
            import jax.numpy as jnp
            jax.jit(lambda x: x + 1)(
                jnp.zeros((8,), jnp.float32)).block_until_ready()
            result["ok"] = True
        except Exception as e:   # a fast, explicit failure also counts
            result["error"] = str(e)[:200]

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout)
    if result.get("ok"):
        return
    raise TimeoutError(
        result.get("error") or
        "no response from the backend within %ds (liveness probe)"
        % int(timeout))


def _exit_with_error_artifact(metric, err, on_accel):
    """Print the explicit JSON error line and LEAVE — os._exit, because
    a wedged runtime thread would otherwise hang interpreter teardown
    and turn this fast failure back into the driver's rc:124.  A
    flight-recorder dump rides along (who-was-waiting-on-whom instead
    of a bare error string; ISSUE 6 tentpole d)."""
    rec = {
        "metric": metric,
        "error": "backend unreachable: %s" % str(err)[:200],
        "on_accel": on_accel,
    }
    try:
        from paddle_tpu.observability import flight
        path = flight.dump("backend_unreachable",
                           blocked={"op": "liveness_probe",
                                    "error": str(err)[:200]})
        if path:
            rec["flight_recorder"] = path
    except Exception:
        pass
    print(json.dumps(rec), flush=True)
    sys.stdout.flush()
    os._exit(0)


def _ensure_bench_recordio(img_shape, data_set, n=2048):
    """Synthesize (once) an uncompressed recordio of uint8 images +
    int64 labels in the given CHW shape; returns its path.  Record
    format: label:i64le + image bytes (C-order)."""
    import struct

    import paddle_tpu as pt
    from paddle_tpu import recordio as rio

    path = os.path.join(
        os.environ.get("BENCH_DATA_DIR", "/tmp"),
        "paddle_tpu_bench_%s_%s.rio" % (data_set,
                                        "x".join(map(str, img_shape))))
    if os.path.exists(path):
        return path
    if data_set == "cifar10":
        base = pt.dataset.cifar.train10()

        def samples():
            for a, lab in base():
                yield (np.asarray(a, np.float32).reshape(img_shape), lab)
    else:
        samples = pt.dataset.flowers.train()
    tmp = path + ".tmp"
    with rio.Writer(tmp, compressor=rio.NO_COMPRESS) as w:
        k = 0
        for img, lab in samples():
            u8 = np.clip(np.asarray(img) * 255.0, 0, 255).astype(np.uint8)
            w.write(struct.pack("<q", int(lab)) + u8.tobytes())
            k += 1
            if k >= n:
                break
    os.replace(tmp, path)
    return path


def _xplane_categories(profile_dir):
    """xplane-sourced per-category device ms for a bench JSON (ISSUE
    5/7): where the step's bytes actually go.  Table goes to stderr;
    returns the dict (or an error marker — profile parse never sinks a
    bench)."""
    import glob

    from paddle_tpu.utils.xplane import print_category_profile
    pbs = sorted(glob.glob(os.path.join(
        profile_dir, "**", "*.xplane.pb"), recursive=True),
        key=os.path.getmtime)
    if not pbs:
        return None
    stdout, sys.stdout = sys.stdout, sys.stderr
    try:
        print("category profile (%s):" % pbs[-1])
        cats = print_category_profile(pbs[-1])
        return {c["category"]: round(c["time_ps"] / 1e9, 1)
                for c in cats[:8]}
    except Exception as e:
        return {"error": str(e)[:120]}
    finally:
        sys.stdout = stdout


def transformer_bench(on_accel, as_dict=False):
    """BENCH_MODEL=transformer: bf16 LM training tokens/sec (flash
    attention on the TPU path; second headline next to ResNet-50).

    ``as_dict``: run with the compute-bound flagship dims (d1024 L6 —
    0.55 MFU measured on v5e) and return the result instead of printing,
    for embedding as the ``secondary`` metric of the default bench.

    ISSUE 7 knobs: BENCH_FUSED_TRANSFORMER=1 runs
    FuseTransformerBlockPass at build time (fused QKV / matmul
    epilogues / add+LN backed by kernels/matmul_fused.py) — the JSON
    then reports ``fused_stages`` + per-category counts; BENCH_PROFILE
    adds xplane-sourced ``per_category_ms``.  FLAGS_autotune_cache_dir
    (or BENCH_AUTOTUNE_CACHE) points the kernels at the persistent
    tile cache the tune tools write."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.core.flags import FLAGS
    from paddle_tpu.models import transformer

    if os.environ.get("BENCH_FUSED_TRANSFORMER") is not None:
        FLAGS.transformer_fuse = \
            os.environ["BENCH_FUSED_TRANSFORMER"] == "1"
    if os.environ.get("BENCH_AUTOTUNE_CACHE"):
        FLAGS.autotune_cache_dir = os.environ["BENCH_AUTOTUNE_CACHE"]

    if as_dict:
        bs, seq, iters = 16, 2048, 10
        d_model, n_layers, n_head = 1024, 6, 8
    elif on_accel:
        bs = int(os.environ.get("BENCH_BATCH", "16"))
        seq = int(os.environ.get("BENCH_SEQ", "2048"))
        iters = int(os.environ.get("BENCH_ITERS", "30"))
        d_model = int(os.environ.get("BENCH_DMODEL", "1024"))
        n_layers = int(os.environ.get("BENCH_LAYERS", "6"))
        n_head = int(os.environ.get("BENCH_HEADS", "8"))
    else:
        # CPU tier: tiny defaults, but explicit BENCH_* dims are
        # honored so the fused-vs-unfused comparison can run at a
        # noise-resistant shape (PROFILE_r07.md uses bs4 seq256 d256)
        bs = int(os.environ.get("BENCH_BATCH", "2"))
        seq = int(os.environ.get("BENCH_SEQ", "128"))
        iters = int(os.environ.get("BENCH_ITERS", "3"))
        d_model = int(os.environ.get("BENCH_DMODEL", "64"))
        n_layers = int(os.environ.get("BENCH_LAYERS", "2"))
        n_head = int(os.environ.get("BENCH_HEADS", "4"))
    vocab = 8192
    amp = os.environ.get("BENCH_AMP", "1" if on_accel else "0") == "1"

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        avg_cost, (src, label), _ = transformer.get_model(
            vocab_size=vocab, seq_len=seq, d_model=d_model,
            n_head=n_head, n_layers=n_layers, d_ff=4 * d_model)
    if amp:
        fluid.transpiler.Float16Transpiler().transpile(main_prog)
    place = fluid.TPUPlace() if on_accel else fluid.CPUPlace()
    exe = fluid.Executor(place)
    exe.run(startup)

    rng = np.random.RandomState(0)
    feed = {src.name: rng.randint(0, vocab, (bs, seq)).astype(np.int64),
            label.name: rng.randint(0, vocab,
                                    (bs, seq, 1)).astype(np.int64)}
    try:
        import jax
        dev = place.jax_device()
        feed = {k: jax.device_put(v, dev) for k, v in feed.items()}
    except Exception:
        pass
    for _ in range(2):
        exe.run(main_prog, feed=feed, fetch_list=[avg_cost])
    import contextlib
    prof_ctx = contextlib.nullcontext()
    profile_dir = None
    if os.environ.get("BENCH_PROFILE"):
        import jax
        # own subdir: the headline loop's capture globs the same root
        profile_dir = os.path.join(os.environ["BENCH_PROFILE"],
                                   "transformer")
        prof_ctx = jax.profiler.trace(profile_dir)
    from paddle_tpu.observability import metrics as obs_metrics
    h_step = obs_metrics.histogram(
        "bench_transformer_step_ms",
        "per-step wall of the transformer bench loop")
    with prof_ctx:
        t0 = time.time()
        for _ in range(iters):
            ts_step = time.time()
            loss, = exe.run(main_prog, feed=feed,
                            fetch_list=[avg_cost], return_numpy=False)
            h_step.observe((time.time() - ts_step) * 1e3)
        loss = np.asarray(loss)
        elapsed = time.time() - t0
    tokens_per_sec = bs * seq * iters / elapsed
    # fused-stage evidence (ISSUE 7): the JSON row names the program it
    # measured, like the headline's data_format/fused_stages fields
    fwd_fused = [op.type for op in main_prog.desc.blocks[0].ops
                 if op.type.startswith("fused_") and
                 not op.type.endswith("_grad")]
    fused_counts = {}
    for t in fwd_fused:
        fused_counts[t] = fused_counts.get(t, 0) + 1
    out = {
        "metric": "transformer_lm_d%d_L%d_train_bs%d_seq%d%s" % (
            d_model, n_layers, bs, seq, "_bf16" if amp else ""),
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec",
        "vs_baseline": 0.0,  # no reference transformer baseline exists
        "amp": amp,
        "step_ms_p50": round(h_step.percentile(50), 3),
        "step_ms_p90": round(h_step.percentile(90), 3),
        "step_ms_p99": round(h_step.percentile(99), 3),
        "fused_stages": len(fwd_fused),
    }
    if fused_counts:
        out["fused_stage_counts"] = fused_counts
    if FLAGS.autotune_cache_dir:
        from paddle_tpu import tuning
        out["autotune_cache_dir"] = FLAGS.autotune_cache_dir
        out["autotune_cache_entries"] = len(tuning.entries())
    if profile_dir:
        cats = _xplane_categories(profile_dir)
        if cats:
            out["per_category_ms"] = cats
    if on_accel:
        # standard analytic count: 6*N_params FLOPs/token (fwd+bwd) +
        # causal attention 6*L*d_model*T (the scaling-book estimate)
        n_params = sum(
            int(np.prod(p.shape))
            for p in main_prog.global_block().all_parameters())
        flops_tok = 6.0 * n_params + 6.0 * n_layers * d_model * seq
        tflops = tokens_per_sec * flops_tok / 1e12
        out["params_m"] = round(n_params / 1e6, 1)
        out["tflops"] = round(tflops, 1)
        if amp:
            peak = float(os.environ.get("BENCH_PEAK_TFLOPS",
                                        DEFAULT_PEAK_TFLOPS))
            out["mfu"] = round(tflops / peak, 3)
    if as_dict:
        return out
    print(json.dumps(out))


def lstm_bench(on_accel):
    """BENCH_MODEL=lstm: the stacked dynamic-LSTM text classifier
    (fluid-benchmark stacked_dynamic_lstm).  Reports ms/batch alongside
    examples/sec — the reference's legacy LSTM numbers are ms/batch
    (benchmark/README.md:113-135: 184 ms at bs64/hidden512 on a K40m)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.models import stacked_dynamic_lstm

    if on_accel:
        bs = int(os.environ.get("BENCH_BATCH", "64"))
        hidden = int(os.environ.get("BENCH_HIDDEN", "512"))
        seq = int(os.environ.get("BENCH_SEQ", "80"))
        iters = int(os.environ.get("BENCH_ITERS", "30"))
    else:
        bs, hidden, seq, iters = 4, 32, 16, 3
    amp = os.environ.get("BENCH_AMP", "1" if on_accel else "0") == "1"

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        avg_cost, (words, label), _ = stacked_dynamic_lstm.get_model(
            dict_dim=5000, hidden_dim=hidden)
    if amp:
        fluid.transpiler.Float16Transpiler().transpile(main_prog)
    place = fluid.TPUPlace() if on_accel else fluid.CPUPlace()
    exe = fluid.Executor(place)
    exe.run(startup)

    rng = np.random.RandomState(0)
    feeder = fluid.DataFeeder([words, label], program=main_prog)
    batch = [(rng.randint(0, 5000, seq).tolist(), [int(rng.randint(2))])
             for _ in range(bs)]
    feed = feeder.feed(batch)
    for _ in range(2):
        exe.run(main_prog, feed=feed, fetch_list=[avg_cost])
    t0 = time.time()
    for _ in range(iters):
        loss, = exe.run(main_prog, feed=feed, fetch_list=[avg_cost],
                        return_numpy=False)
    np.asarray(loss)
    elapsed = time.time() - t0
    ms_per_batch = elapsed / iters * 1000
    # K40m, bs64 hidden512 (benchmark/README.md:113-119 via
    # BASELINE.md:20).  Indicative: that net is a 2-layer LSTM stack,
    # this model is the fluid-benchmark 3-stack — and the ratio is only
    # emitted when the run matches the baseline's bs/hidden config.
    baseline_ms = 184.0
    vs = (round(baseline_ms / ms_per_batch, 3)
          if (bs, hidden) == (64, 512) else 0.0)
    print(json.dumps({
        "metric": "stacked_lstm_train_bs%d_h%d_seq%d%s" % (
            bs, hidden, seq, "_bf16" if amp else ""),
        "value": round(ms_per_batch, 2),
        "unit": "ms/batch",
        "vs_baseline": vs,
        "examples_per_sec": round(bs * iters / elapsed, 1),
        "amp": amp,
    }))


def main():
    model_name = os.environ.get("BENCH_MODEL", "resnet50")
    if model_name not in ("resnet50", "resnet32", "vgg", "transformer",
                          "lstm", "alexnet", "googlenet"):
        raise SystemExit(
            "BENCH_MODEL must be resnet50|resnet32|vgg|transformer|"
            "lstm|alexnet|googlenet, got %r" % model_name)
    # Scheduler-flag knobs (ISSUE 5 lever c) must hit XLA_FLAGS BEFORE
    # the first backend touch (the liveness probe below initializes
    # jax); FLAGS_xla_latency_hiding_scheduler=1 / FLAGS_xla_extra_flags
    # env vars flow through the flag registry into apply_xla_flags, and
    # the same values ride the executor compile-cache key.
    from paddle_tpu.core.flags import FLAGS, apply_xla_flags
    xla_tokens = apply_xla_flags()
    # a driver SIGTERM (wall-clock kill) leaves a flight-recorder JSON
    # naming the open span every thread was blocked in, instead of
    # nothing (ISSUE 6 tentpole d).  SIGALRM stays with _wall_budget,
    # whose handler dumps before raising.
    try:
        from paddle_tpu.observability import flight
        flight.install_signal_handlers(("SIGTERM",))
    except Exception:
        pass
    # Watchtower (ISSUE 13): with FLAGS_tsdb_dir set, a bench run
    # retains its whole metric history (bench_step_ms, compile-cache
    # counters, numerics gauges) as durable time series the perf
    # sentinel and watchtower report read afterwards
    try:
        from paddle_tpu.observability import tsdb as _tsdb
        _tsdb.ensure_sampler()
    except Exception:
        pass
    on_accel = False
    try:
        import jax
        on_accel = any(d.platform != "cpu" for d in jax.devices())
    except Exception:
        pass
    # liveness first: a dead tunnel yields a fast, explicit JSON error
    # artifact instead of an rc:124 with nothing on stdout
    try:
        _probe_backend(float(os.environ.get("BENCH_LIVENESS_TIMEOUT",
                                            "90")))
    except Exception as e:
        _exit_with_error_artifact("%s_train" % model_name, e, on_accel)
    if model_name == "transformer":
        return transformer_bench(on_accel)
    if model_name == "lstm":
        return lstm_bench(on_accel)
    # Keep CPU smoke-runs fast; real run uses ImageNet shapes.
    if on_accel:
        batch_size = int(os.environ.get("BENCH_BATCH", "256"))
        data_set = os.environ.get("BENCH_DATASET", "flowers")
        iters = int(os.environ.get("BENCH_ITERS", "60"))
    else:
        batch_size = int(os.environ.get("BENCH_BATCH", "16"))
        data_set = os.environ.get("BENCH_DATASET", "cifar10")
        iters = int(os.environ.get("BENCH_ITERS", "5"))
    amp = os.environ.get("BENCH_AMP", "1" if on_accel else "0") == "1"
    # Real data is the accelerator default for the ResNet headline (the
    # only mode with the uint8 device-normalize input); BENCH_FAKE
    # overrides either way.
    use_fake = os.environ.get(
        "BENCH_FAKE",
        "0" if (on_accel and model_name == "resnet50") else "1") == "1"
    uint8_input = not use_fake and model_name == "resnet50"

    import paddle_tpu.fluid as fluid
    from paddle_tpu.models import alexnet, googlenet, resnet, vgg

    # measured knobs (see PROFILE_r04.md for the numbers behind the
    # defaults): bf16 pass-through batch_norm and NHWC conv lowering
    if os.environ.get("BENCH_BN_BF16", "1" if amp else "0") == "1":
        FLAGS.bn_bf16 = True
    if os.environ.get("BENCH_NHWC", "0") == "1":
        FLAGS.conv_nhwc = True
    # ISSUE 5 levers a/b: the layout-pinned NHWC pipeline + Pallas
    # fused conv stages (models/resnet.py runs the LayoutTranspiler
    # pre-minimize when the flag says NHWC).  BENCH_LAYOUT=NHWC /
    # BENCH_FUSED_STAGES=0 control them; FLAGS_conv_layout env works
    # too.  NCHW default — the bisection baseline.
    data_format = os.environ.get("BENCH_LAYOUT", FLAGS.conv_layout or
                                 "NCHW").upper()
    FLAGS.conv_layout = data_format
    if os.environ.get("BENCH_FUSED_STAGES") is not None:
        FLAGS.conv_fused_stages = \
            os.environ["BENCH_FUSED_STAGES"] == "1"
    bench_depth = int(os.environ.get("BENCH_DEPTH", "0"))
    # numerics observatory (ISSUE 8): BENCH_CHECK_NUMERICS=metrics runs
    # the headline WITH the fused health fetch (grad-norm / absmax /
    # nonfinite stats in the always-on registry) — the measured
    # overhead per mode is recorded in PROFILE_r08.md, and the JSON row
    # carries the mode so A/B rows stay self-describing
    if os.environ.get("BENCH_CHECK_NUMERICS"):
        FLAGS.check_numerics = os.environ["BENCH_CHECK_NUMERICS"]

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        if model_name == "vgg":
            # vgg16_bn_drop — the fluid-benchmark VGG config; the only
            # published reference number is legacy VGG-19 on CPU
            avg_cost, (data, label), (acc,) = vgg.get_model(
                data_set=data_set)
        elif model_name in ("alexnet", "googlenet"):
            # legacy-benchmark families: 224x224 only (googlenet's final
            # 7x7 avg pool requires it), so BENCH_DATASET is ignored and
            # the CPU smoke path shrinks batch/iters instead of shapes
            data_set = "flowers"
            if not on_accel:
                batch_size, iters = min(batch_size, 4), min(iters, 2)
            mod = alexnet if model_name == "alexnet" else googlenet
            avg_cost, (data, label), (acc,) = mod.get_model()
        else:
            avg_cost, (data, label), (acc,) = resnet.get_model(
                data_set=data_set,
                depth=bench_depth or (50 if model_name == "resnet50"
                                      else 32),
                input_dtype="uint8" if uint8_input else "float32")
    if amp:
        fluid.transpiler.Float16Transpiler().transpile(main_prog)

    place = fluid.TPUPlace() if on_accel else fluid.CPUPlace()
    exe = fluid.Executor(place)
    exe.run(startup)

    dshape = [batch_size] + list(data.shape[1:])
    rng = np.random.RandomState(0)
    if uint8_input:  # warmup must compile the same (uint8) feed spec
        images = rng.randint(0, 256, dshape).astype(np.uint8)
    else:
        images = rng.rand(*dshape).astype(np.float32)
    class_dim = 102 if data_set == "flowers" else 10
    labels = rng.randint(0, class_dim, (batch_size, 1)).astype(np.int64)
    feed = {data.name: images, label.name: labels}

    # Real-data mode: a flowers-shaped recordio file feeds training.
    # Images travel uint8 and are cast+scaled on device (get_model
    # input_dtype='uint8') — the TPU-native version of the reference's
    # host-side normalize, at a quarter of the f32 link bytes.
    #
    # Datasets that fit in HBM go through DeviceDatasetCache (recordio
    # scanner -> stage once -> per-epoch jitted shuffle + gather, zero
    # per-step host traffic — the tf.data cache()-on-accelerator idiom).
    # MEASURED (the post-loop stream probe emits these fields every
    # run; r4 numbers): h2d_mb_per_sec_idle = 6.3 MB/s sustained over
    # this rig's tunnel, streaming_imgs_per_sec = 362 through the
    # double-buffered DeviceLoader vs 2698 cached — feeding bs256
    # uint8 images at the cached step rate needs ~405 MB/s, ~64x what
    # the tunnel delivers, so streaming overlap physically cannot keep
    # a ~95 ms step fed here.  Larger datasets stream through the
    # decorated chain — recordio -> shuffle -> batch -> double-buffered
    # DeviceLoader (reference reader decorators +
    # create_recordio_file_reader / create_double_buffer_reader_op).
    loader_iter = None
    device_cached = False
    if not use_fake:
        import paddle_tpu as pt
        from paddle_tpu.reader import creator

        rio_path = _ensure_bench_recordio(dshape[1:], data_set)
        img_elems = int(np.prod(dshape[1:]))

        def _deser(rec):
            lab = np.frombuffer(rec, np.int64, count=1)
            img = np.frombuffer(rec, np.uint8, offset=8,
                                count=img_elems).reshape(dshape[1:])
            if not uint8_input:  # program without the uint8 front-end
                img = img.astype(np.float32) / 255.0
            return img, lab

        base = creator.recordio(rio_path, _deser)
        try:
            loader = pt.reader.DeviceDatasetCache(
                base, [data.name, label.name], place, batch_size,
                max_bytes=int(os.environ.get("BENCH_CACHE_BUDGET",
                                             str(4 << 30))))
            device_cached = True
        except pt.reader.DatasetExceedsBudget:
            loader = pt.reader.DeviceLoader(
                pt.batch(pt.reader.shuffle(base, buf_size=batch_size * 4),
                         batch_size=batch_size),
                [data.name, label.name], place, capacity=3)

        def forever():
            while True:
                n = 0
                for d in loader:  # each epoch reshuffles (+restages)
                    n += 1
                    yield d
                if n == 0:
                    raise RuntimeError("reader yielded no batches")

        loader_iter = forever()
        # warm up (compile) with a real loader batch: its feed spec is
        # what the timed loop sees (device-resident, int32 labels after
        # the x64-off conversion) — warming with the synthetic host
        # batch would compile a second program inside the timed loop
        feed = next(loader_iter)

    # Pre-stage the batch on device (the reference reads from a
    # double-buffered reader; a constant device-resident batch is the
    # use_fake_data analog) and warm up compile + autotuning.
    try:
        import jax
        dev = place.jax_device()
        feed = {k: jax.device_put(v, dev) for k, v in feed.items()}
    except Exception:
        pass
    for _ in range(2):
        exe.run(main_prog, feed=feed, fetch_list=[avg_cost])

    # Timed loop: steps are dispatched asynchronously (XLA execution is
    # async like the reference's CUDA streams); one sync at the end.
    # BENCH_PROFILE=<dir> wraps the loop in jax.profiler.trace and
    # prints the per-hlo-category breakdown (utils/xplane.py) to stderr.
    import contextlib
    profile_dir = os.environ.get("BENCH_PROFILE")
    prof_ctx = contextlib.nullcontext()
    if profile_dir:
        import jax
        prof_ctx = jax.profiler.trace(profile_dir)
    # Prepared hot path (Executor.prepare / run_prepared): per-step cost
    # is feed staging + one dispatch — parameters/optimizer state stay
    # device-resident instead of round-tripping the Scope every step.
    # BENCH_PREPARED=0 times the classic run() path instead.
    prepared = None
    if os.environ.get("BENCH_PREPARED", "1") == "1":
        try:
            prepared = exe.prepare(main_prog, feed_specs=feed,
                                   fetch_list=[avg_cost])
        except ValueError:
            prepared = None  # host ops in the block: run() path
    # per-step wall times land in an always-on metrics histogram; the
    # JSON's step_ms_p50/p90/p99 come from ITS snapshot (ISSUE 6).
    # Steps are dispatched async, so per-step wall is host-side issue
    # time except the final step, which absorbs the drain — the
    # percentiles catch host-side stalls (recompiles, loader hiccups)
    # the mean hides.
    from paddle_tpu.observability import metrics as obs_metrics
    h_step = obs_metrics.histogram(
        "bench_step_ms", "per-step wall of the timed bench loop")
    with prof_ctx:  # exception-safe: a mid-run OOM still finalizes
        t0 = time.time()
        t_host = 0.0  # host-side dispatch time (wall minus run-ahead)
        prepared_steps = 0
        loss = None
        from paddle_tpu.core.executor_impl import PreparedShapeMismatch
        for _ in range(iters):
            ts_step = time.time()
            step_feed = next(loader_iter) if loader_iter is not None \
                else feed
            td = time.time()
            if prepared is not None:
                try:
                    loss, = prepared.run_prepared(step_feed)
                    prepared_steps += 1
                except PreparedShapeMismatch:
                    # AOT fixed-shape entry + a drifted (partial) batch:
                    # flush the device state BEFORE dropping the last
                    # reference, then finish the loop via run().  The
                    # sync is transition cost, not dispatch cost — keep
                    # it out of t_host so step_host_ms stays steady-state
                    prepared.sync_scope()
                    prepared = None
                    td = time.time()
            if prepared is None:
                loss, = exe.run(main_prog, feed=step_feed,
                                fetch_list=[avg_cost],
                                return_numpy=False)
            t_host += time.time() - td
            h_step.observe((time.time() - ts_step) * 1e3)
        loss = np.asarray(loss)  # blocks until the chain has drained
        elapsed = time.time() - t0
    if prepared is not None:
        prepared.sync_scope()
    # xplane-sourced per-category device ms for the headline JSON
    # (ISSUE 5): where the step's bytes actually go — the "data
    # formatting" row is lever (a)'s target
    per_category_ms = _xplane_categories(profile_dir) if profile_dir \
        else None

    images_per_sec = batch_size * iters / elapsed

    # Streaming-input evidence (round-3 VERDICT weak #2): measure the
    # tunnel and the streaming DeviceLoader path so the cache-vs-stream
    # decision above cites numbers, not an assertion.  Runs AFTER the
    # timed loop so the headline is undisturbed.  BENCH_STREAM_PROBE=0
    # skips.
    stream_stats = {}

    def _stream_probe():
        import jax

        import paddle_tpu as pt
        from paddle_tpu.reader import creator

        dev = place.jax_device()
        # (a) idle-device h2d bandwidth: one big uint8 buffer, drained
        # by a 1-element d2h fetch (block_until_ready alone returns
        # before the remote transfer lands on this rig)
        nbytes = 64 << 20
        buf = np.ones(nbytes, np.uint8)
        t0 = time.time()
        x = jax.device_put(buf, dev)
        _ = np.asarray(x[:1])
        stream_stats["h2d_mb_per_sec_idle"] = round(
            nbytes / (time.time() - t0) / 1e6, 1)
        del x
        # (b) the streaming DeviceLoader path end-to-end (recordio ->
        # shuffle -> batch -> double-buffered h2d overlapped with the
        # training step): images/sec over a short run
        base = creator.recordio(rio_path, _deser)
        sloader = pt.reader.DeviceLoader(
            pt.batch(pt.reader.shuffle(base, buf_size=batch_size * 4),
                     batch_size=batch_size),
            [data.name, label.name], place, capacity=3)
        sit = iter(sloader)
        sfeed = next(sit)
        exe.run(main_prog, feed=sfeed, fetch_list=[avg_cost])  # warm
        s_iters = int(os.environ.get("BENCH_STREAM_ITERS", "8"))
        t0 = time.time()
        sloss = None
        n_done = 0
        for sfeed in sit:
            sloss, = exe.run(main_prog, feed=sfeed,
                             fetch_list=[avg_cost], return_numpy=False)
            n_done += 1
            if n_done >= s_iters:
                break
        np.asarray(sloss)
        t_stream = time.time() - t0
        stream_stats["streaming_imgs_per_sec"] = round(
            batch_size * n_done / t_stream, 1)
        # (c) overlap evidence (round-4 VERDICT weak #3): does the
        # double buffer hide transfer behind compute?  Per-step wall
        # of the streamed run vs the sum of its parts (compute-only
        # step at the headline rate + this batch's bytes at the idle
        # h2d rate).  ratio -> ~(a+b)/max(a,b) means full overlap,
        # ~1.0 means serialized — which is what this rig's tunnel
        # does to transfers interleaved with executes (see
        # PROFILE_r05.md notes); tests/test_data_pipeline.py proves
        # the loader overlaps where the transport allows it.
        batch_mb = sum(v.nbytes for v in sfeed.values()) / 1e6 \
            if hasattr(next(iter(sfeed.values())), "nbytes") else 0.0
        t_compute = batch_size / max(images_per_sec, 1e-9)
        t_h2d = batch_mb / max(
            stream_stats.get("h2d_mb_per_sec_idle", 1e9), 1e-9)
        t_step = t_stream / max(n_done, 1)
        stream_stats["stream_overlap_ratio"] = round(
            (t_compute + t_h2d) / max(t_step, 1e-9), 3)

    if model_name == "vgg":
        # closest published number: legacy VGG-19 train, MKL-DNN CPU,
        # bs256 (IntelOptimizedPaddle.md:36) — vgg16 here, so the ratio
        # is indicative, not exact
        baseline = 30.44
    elif model_name == "alexnet":
        baseline = 626.53  # MKL-DNN CPU bs256 (IntelOptimizedPaddle.md:63)
    elif model_name == "googlenet":
        baseline = 269.50  # MKL-DNN CPU bs256 (IntelOptimizedPaddle.md:54)
    else:
        baseline = 81.69  # MKL-DNN CPU ResNet-50 bs64 (IntelOptimizedPaddle.md:41)
    out = {
        "metric": "%s_%s_train_bs%d%s" % (
            model_name, data_set, batch_size, "_bf16" if amp else ""),
        "value": round(images_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(images_per_sec / baseline, 3),
        "amp": amp,
        "fake_data": use_fake,
        # dispatch-cost tracking (ISSUE 2): per-step wall, the host
        # time spent issuing the step (wall minus the device run-ahead
        # the async dispatch buys), and its share of the step — future
        # BENCH_*.json watch this for host-side regressions.
        # prepared_steps < iters means a mid-loop fallback to run()
        # (AOT shape drift) mixed the timings.
        "prepared": prepared_steps == iters,
        "prepared_steps": prepared_steps,
        "step_wall_ms": round(elapsed / iters * 1e3, 3),
        "step_host_ms": round(t_host / iters * 1e3, 3),
        "host_overhead_frac": round(t_host / max(elapsed, 1e-9), 4),
        # per-step distribution, sourced from the telemetry histogram
        # (ISSUE 6): tail stalls (recompiles, loader hiccups) show in
        # p99 where the mean hides them.  The last step absorbs the
        # async drain, so p99 ~ the device step time.
        "step_ms_p50": round(h_step.percentile(50), 3),
        "step_ms_p90": round(h_step.percentile(90), 3),
        "step_ms_p99": round(h_step.percentile(99), 3),
        # numerics observatory mode this row ran under (ISSUE 8); with
        # 'metrics' on, grad_global_norm percentiles ride along below
        # so the bench doubles as a training-health probe
        "check_numerics": str(FLAGS.check_numerics or "off"),
        # ISSUE 5 lever evidence: layout, fused stage count and the
        # scheduler flags the run compiled under — BENCH_*.json rows
        # are self-describing experiments, not env archaeology.
        # data_format reflects the PROGRAM (only models that honor
        # FLAGS_conv_layout transpile; vgg/alexnet/googlenet stay NCHW)
        "data_format": ("NHWC" if any(
            op.attr("data_format", op.attr("data_layout", "NCHW"))
            == "NHWC" for op in main_prog.desc.blocks[0].ops)
            else "NCHW"),
        "fused_stages": sum(
            1 for op in main_prog.desc.blocks[0].ops
            if op.type == "fused_conv2d_bn_act"),
        "xla_flags": xla_tokens,
    }
    if per_category_ms:
        out["per_category_ms"] = per_category_ms
    if out["check_numerics"] not in ("", "off"):
        # training-health evidence from the always-on registry
        # (observability/numerics.py): the run's grad-norm distribution
        # + any nonfinite sightings
        from paddle_tpu.observability import metrics as _metrics
        snap = _metrics.snapshot()
        gh = snap.get("grad_global_norm", {})
        out["grad_global_norm_p50"] = gh.get("p50", 0.0)
        out["grad_global_norm_p99"] = gh.get("p99", 0.0)
        out["nonfinite_total"] = snap.get(
            "numerics_nonfinite_total", {}).get("value", 0)
    if bench_depth:
        out["depth"] = bench_depth  # non-default model size: mark it
    if not use_fake:
        out["device_cached"] = device_cached
    # 224x224 only: that's what the analytic FLOP counts are for
    per_img = {"resnet50": TRAIN_FLOPS_PER_IMG_224,
               "vgg": TRAIN_FLOPS_PER_IMG_VGG16_224}.get(model_name)
    if data_set in ("flowers", "imagenet") and per_img:
        tflops = images_per_sec * per_img / 1e12
        out["tflops"] = round(tflops, 1)
        if amp:  # MFU only vs the bf16 peak the run actually targets
            peak = float(os.environ.get("BENCH_PEAK_TFLOPS",
                                        DEFAULT_PEAK_TFLOPS))
            out["mfu"] = round(tflops / peak, 3)
            # Roofline context, measured via utils/xplane.py category
            # profiles committed in PROFILE_r04.md (v5e defaults: peak
            # 197 TF/s, bs256): ResNet-50 bf16 is HBM-bound — 94% of
            # device step time runs inside XLA fusions at 82-85% of the
            # 819 GB/s HBM peak (conv fusions: 85% HBM, 38% MXU),
            # because the model's arithmetic intensity sits far below
            # the chip's ridge point (197e12/819e9 ≈ 240 FLOP/byte).
            # At 100% HBM for the bytes XLA actually schedules the
            # analytic-FLOP MFU caps at ~0.20 (0.167/0.85); bf16-BN,
            # NHWC and bs512 are all measured ≤±1% (PROFILE_r04.md
            # knob table).  A compute-bound workload on the same stack
            # reaches 0.52 (see secondary).  Only emitted for the
            # measured config so another chip/batch never inherits it.
            if (model_name == "resnet50" and batch_size == 256
                    and peak == DEFAULT_PEAK_TFLOPS):
                out["hbm_bound"] = True
                out["mfu_roofline_cap"] = 0.20
                out["profile_evidence"] = "PROFILE_r04.md"
    # the headline is UN-LOSABLE: emit it the moment it exists, BEFORE
    # the stream probe / secondary bench — if either wedges past its
    # budget or the process dies, the driver still has this line.  The
    # enriched line at exit repeats it with the evidence fields.
    print(json.dumps(dict(out, partial=True)), flush=True)

    if (not use_fake and on_accel
            and os.environ.get("BENCH_STREAM_PROBE", "1") == "1"):
        try:
            with _wall_budget(
                    float(os.environ.get("BENCH_STREAM_BUDGET", "180")),
                    "stream probe"):
                _stream_probe()
        except Exception as e:
            # evidence fields must never sink the headline the driver
            # records
            stream_stats["stream_probe_error"] = str(e)[:200]
    if not use_fake:
        out.update(stream_stats)
    if on_accel and model_name == "resnet50" and \
            os.environ.get("BENCH_SECONDARY", "1") == "1":
        try:
            with _wall_budget(
                    float(os.environ.get("BENCH_SECONDARY_BUDGET",
                                         "420")),
                    "secondary transformer bench"):
                out["secondary"] = transformer_bench(True, as_dict=True)
        except Exception as e:  # secondary must never sink the headline
            out["secondary_error"] = str(e)[:200]
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
