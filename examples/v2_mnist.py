"""The classic v2 MNIST script, unchanged except the import line
(reference: python/paddle/v2 usage in the book's recognize_digits
chapter — layer.data/fc chains, parameters.create, trainer.SGD with
Momentum, event handler, paddle.infer).

Run:  python examples/v2_mnist.py        (a couple of minutes on CPU;
      set PASSES/BATCHES_PER_PASS down for a smoke run)
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import paddle_tpu.v2 as paddle  # was: import paddle.v2 as paddle

PASSES = int(os.environ.get("PASSES", "2"))
BATCHES_PER_PASS = int(os.environ.get("BATCHES_PER_PASS", "50"))


def main():
    paddle.init(use_gpu=False, trainer_count=1)

    images = paddle.layer.data(
        name="pixel", type=paddle.data_type.dense_vector(784))
    label = paddle.layer.data(
        name="label", type=paddle.data_type.integer_value(10))
    hidden1 = paddle.layer.fc(input=images, size=128,
                              act=paddle.activation.Relu(), name="h1")
    hidden2 = paddle.layer.fc(input=hidden1, size=64,
                              act=paddle.activation.Relu(), name="h2")
    predict = paddle.layer.fc(input=hidden2, size=10,
                              act=paddle.activation.Softmax(),
                              name="pred")
    cost = paddle.layer.classification_cost(input=predict, label=label)

    parameters = paddle.parameters.create(cost)
    optimizer = paddle.optimizer.Momentum(
        learning_rate=0.1 / 128.0, momentum=0.9,
        regularization=paddle.optimizer.L2Regularization(
            rate=0.0005 * 128))
    trainer = paddle.trainer.SGD(cost=cost, parameters=parameters,
                                 update_equation=optimizer)

    def bounded(reader, n):
        def r():
            for i, item in enumerate(reader()):
                if i >= n:
                    return
                yield item
        return r

    train_reader = bounded(
        paddle.batch(paddle.reader.shuffle(paddle.dataset.mnist.train(),
                                           buf_size=8192),
                     batch_size=128),
        BATCHES_PER_PASS)

    def event_handler(event):
        if isinstance(event, paddle.event.EndIteration):
            if event.batch_id % 20 == 0:
                print("pass %d batch %d cost %.4f err %.3f" % (
                    event.pass_id, event.batch_id, event.cost,
                    event.metrics["classification_error_evaluator"]))
        elif isinstance(event, paddle.event.EndPass):
            result = trainer.test(reader=bounded(
                paddle.batch(paddle.dataset.mnist.test(),
                             batch_size=128), 10))
            print("pass %d test cost %.4f err %.3f" % (
                event.pass_id, result.cost,
                result.metrics["classification_error_evaluator"]))

    trainer.train(reader=train_reader, num_passes=PASSES,
                  event_handler=event_handler)

    # serve a few digits through paddle.infer (same [-1,1] images the
    # trainer consumed)
    test_rows = []
    for i, (img, lab) in enumerate(paddle.dataset.mnist.test()()):
        test_rows.append((img, lab))
        if i >= 7:
            break
    probs = paddle.infer(output_layer=predict, parameters=parameters,
                         input=[(r[0],) for r in test_rows])
    got = np.argmax(np.asarray(probs), axis=1)
    print("infer:", list(got), "labels:", [r[1] for r in test_rows])


if __name__ == "__main__":
    main()
