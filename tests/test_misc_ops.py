"""Specialty ops closing the reference op census (reference
operators/{conv_shift,fake_dequantize,polygon_box_transform,
pool_with_index,unpool,roi_pool,positive_negative_pair}_op.cc),
pinned against hand-computed values."""
import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.core.registry import get_op_info
from paddle_tpu.core.lowering import Ins, LoweringContext
from paddle_tpu.core.desc import ProgramDesc

import jax
import jax.numpy as jnp


def _run(op_type, ins, attrs=None):
    ctx = LoweringContext(ProgramDesc(), 0, {}, jax.random.PRNGKey(0),
                          "train")
    wrapped = {k: [jnp.asarray(v)] for k, v in ins.items()}
    return get_op_info(op_type).lower(ctx, Ins(wrapped), attrs or {},
                                      None)


def test_conv_shift_matches_naive():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 7).astype(np.float32)
    y = rng.randn(2, 3).astype(np.float32)
    got = np.asarray(_run("conv_shift", {"X": x, "Y": y})["Out"])
    want = np.zeros_like(x)
    m, n = 7, 3
    for b in range(2):
        for i in range(m):
            for j in range(n):
                want[b, i] += x[b, (i + j - n // 2) % m] * y[b, j]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_fake_dequantize():
    x = np.asarray([[127.0, -63.5]], np.float32)
    got = _run("fake_dequantize_max_abs",
               {"X": x, "Scale": np.asarray([0.5], np.float32)},
               {"max_range": 127.0})["Out"]
    np.testing.assert_allclose(np.asarray(got), [[0.5, -0.25]],
                               rtol=1e-6)


def test_polygon_box_transform():
    x = np.ones((1, 4, 2, 2), np.float32)
    got = np.asarray(_run("polygon_box_transform",
                          {"Input": x})["Output"])
    # even channels: x-coord = col*4 - 1; odd: row*4 - 1
    np.testing.assert_allclose(got[0, 0], [[-1, 3], [-1, 3]])
    np.testing.assert_allclose(got[0, 1], [[-1, -1], [3, 3]])


def test_pool_with_index_and_unpool_roundtrip():
    rng = np.random.RandomState(1)
    # positive data: the unpool re-pool check compares against zeros
    x = rng.rand(2, 3, 4, 4).astype(np.float32) + 0.1
    outs = _run("max_pool2d_with_index", {"X": x},
                {"ksize": [2, 2], "strides": [2, 2],
                 "paddings": [0, 0]})
    out, mask = np.asarray(outs["Out"]), np.asarray(outs["Mask"])
    np.testing.assert_allclose(
        out, x.reshape(2, 3, 2, 2, 2, 2).max(axis=(3, 5)), rtol=1e-6)
    # mask points at the argmax in the ORIGINAL map
    flat = x.reshape(2, 3, 16)
    np.testing.assert_allclose(
        np.take_along_axis(flat, mask.reshape(2, 3, 4), axis=2),
        out.reshape(2, 3, 4), rtol=1e-6)
    # unpool scatters back: re-pooling recovers the same maxima
    up = _run("unpool", {"X": jnp.asarray(out),
                         "Indices": jnp.asarray(mask)},
              {"ksize": [2, 2], "strides": [2, 2],
               "paddings": [0, 0]})["Out"]
    up = np.asarray(up)
    assert up.shape == x.shape
    np.testing.assert_allclose(
        up.reshape(2, 3, 2, 2, 2, 2).max(axis=(3, 5)), out, rtol=1e-6)
    assert np.count_nonzero(up) <= 2 * 3 * 4


def test_roi_pool_hand_case():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    rois = np.asarray([[0, 0, 0, 1, 1],     # top-left 2x2
                       [0, 2, 2, 3, 3]], np.float32)
    got = np.asarray(_run("roi_pool", {"X": x, "ROIs": rois},
                          {"spatial_scale": 1.0, "pooled_height": 1,
                           "pooled_width": 1})["Out"])
    np.testing.assert_allclose(got[:, 0, 0, 0], [5.0, 15.0])


def test_positive_negative_pair():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                s = fluid.layers.data(name="s", shape=[1],
                                      dtype="float32")
                l = fluid.layers.data(name="l", shape=[1],
                                      dtype="float32")
                q = fluid.layers.data(name="q", shape=[1],
                                      dtype="int64")
                helper = fluid.layer_helper.LayerHelper("pnp")
                pos = helper.create_tmp_variable("float32")
                neg = helper.create_tmp_variable("float32")
                neu = helper.create_tmp_variable("float32")
                helper.append_op(
                    type="positive_negative_pair",
                    inputs={"Score": [s], "Label": [l],
                            "QueryID": [q]},
                    outputs={"PositivePair": [pos],
                             "NegativePair": [neg],
                             "NeutralPair": [neu]})
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        # query 0: scores (3,1) labels (1,0) -> positive pair
        #          scores (3,2) labels (1,1) -> same label, skipped
        # query 1: scores (1,2) labels (1,0) -> negative pair
        got = exe.run(main, feed={
            "s": np.asarray([[3], [1], [3], [1], [2]], np.float32),
            "l": np.asarray([[1], [0], [1], [1], [0]], np.float32),
            "q": np.asarray([[0], [0], [0], [1], [1]], np.int64)},
            fetch_list=[pos, neg, neu])
    p, n, u = [float(np.ravel(g)[0]) for g in got]
    assert (p, n, u) == (2.0, 1.0, 0.0)


def test_unpool_overlapping_windows_assigns_once():
    x = np.zeros((1, 1, 3, 3), np.float32)
    x[0, 0, 1, 1] = 5.0
    outs = _run("max_pool2d_with_index", {"X": x},
                {"ksize": [2, 2], "strides": [1, 1],
                 "paddings": [0, 0]})
    up = _run("unpool", {"X": outs["Out"], "Indices": outs["Mask"]},
              {"ksize": [2, 2], "strides": [1, 1],
               "paddings": [0, 0]})["Out"]
    # every window recorded index (1,1); unpool must ASSIGN 5, not 20
    np.testing.assert_allclose(np.asarray(up)[0, 0, 1, 1], 5.0)


def test_roi_pool_argmax():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    rois = np.asarray([[0, 0, 0, 1, 1]], np.float32)
    outs = _run("roi_pool", {"X": x, "ROIs": rois},
                {"spatial_scale": 1.0, "pooled_height": 1,
                 "pooled_width": 1})
    # max of the top-left 2x2 is 5 at flat index 5
    np.testing.assert_allclose(np.asarray(outs["Out"])[0, 0, 0, 0], 5.0)
    assert int(np.asarray(outs["Argmax"])[0, 0, 0, 0]) == 5


def test_int64_feed_overflow_fails_loudly(prog_scope, exe):
    """MIGRATION.md 'int64 ids and offsets': an id beyond 2^31 must
    raise at the feed boundary, never silently wrap (reference keeps
    true int64 ids, framework/lod_tensor.h:58)."""
    import pytest
    layers = fluid.layers
    main, startup, scope = prog_scope
    ids = layers.data(name="big_ids", shape=[1], dtype="int64")
    emb = layers.embedding(ids, size=[8, 4])
    exe.run(startup)
    ok = np.asarray([[1], [7]], np.int64)
    exe.run(main, feed={"big_ids": ok}, fetch_list=[emb])
    bad = np.asarray([[1], [2 ** 31 + 5]], np.int64)
    with pytest.raises(ValueError, match="int32 range"):
        exe.run(main, feed={"big_ids": bad}, fetch_list=[emb])


def test_scale_sub_region_vs_numpy(prog_scope, exe):
    """Per-sample sub-box scaling with 1-based inclusive bounds
    (reference ScaleSubRegionLayer)."""
    layers = fluid.layers
    main, startup, scope = prog_scope
    x = layers.data(name="ssr_x", shape=[3, 4, 4], dtype="float32")
    ind = layers.data(name="ssr_i", shape=[6], dtype="int64")
    out = layers.scale_sub_region(x, layers.cast(ind, "int32"), 2.0)
    exe.run(startup)
    rng = np.random.RandomState(0)
    xv = rng.randn(2, 3, 4, 4).astype(np.float32)
    iv = np.asarray([[1, 2, 1, 3, 2, 4], [2, 3, 2, 2, 1, 1]], np.int64)
    got, = exe.run(main, feed={"ssr_x": xv, "ssr_i": iv},
                   fetch_list=[out])
    want = xv.copy()
    for s in range(2):
        c0, c1, h0, h1, w0, w1 = iv[s] - 1
        want[s, c0:c1 + 1, h0:h1 + 1, w0:w1 + 1] *= 2.0
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-6)
