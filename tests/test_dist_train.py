"""End-to-end pserver training on localhost (reference test_dist_train.py):
2 trainers x 2 pservers over gRPC, compared against the single-process
run — constant inits + identical batches make sync-SGD losses match
exactly (up to float accumulation order).

The emb_sparse variant drives the full distributed SelectedRows path:
lookup_table_grad -> send row-range split -> gRPC sparse wire format
(kind=1) -> pserver sparse mean aggregation -> sparse sgd apply.
"""
import multiprocessing as mp
import socket

import numpy as np
import pytest

import dist_train_helpers as H


def _baseline_to_queue(steps, kind, queue):
    queue.put(H.run_local_baseline(steps, kind))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_dist(kind, steps=8, sync_mode=True):
    import os

    # spawn children as PURE-CPU jax processes: the axon TPU plugin
    # registers at interpreter start (sitecustomize) gated on this env
    # var, and its client init can block every jax call when the TPU
    # tunnel is unavailable — pserver/trainer hosts never need it
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"

    ctx = mp.get_context("spawn")
    eps = ["127.0.0.1:%d" % _free_port() for _ in range(2)]
    pservers = ",".join(eps)
    n_trainers = 2

    ps_procs = [ctx.Process(target=H.run_pserver,
                            args=(ep, pservers, n_trainers, kind,
                                  sync_mode))
                for ep in eps]
    for p in ps_procs:
        p.start()

    q = ctx.Queue()
    tr_procs = [ctx.Process(target=H.run_trainer,
                            args=(tid, pservers, n_trainers, steps, q,
                                  kind, sync_mode))
                for tid in range(n_trainers)]
    for p in tr_procs:
        p.start()

    results = {}
    for _ in range(n_trainers):
        tid, losses = q.get(timeout=240)
        results[tid] = losses
    for p in tr_procs:
        p.join(timeout=60)
    for p in ps_procs:
        p.join(timeout=60)
        if p.is_alive():
            p.terminate()
            pytest.fail("pserver did not shut down after SendComplete")

    # baseline in a spawned child too: the pytest parent may have the
    # axon TPU plugin registered (interpreter start), and its client
    # init can block every jax call when the tunnel is down
    bq = ctx.Queue()
    bp = ctx.Process(target=_baseline_to_queue, args=(steps, kind, bq))
    bp.start()
    local = bq.get(timeout=240)
    bp.join(timeout=60)
    if sync_mode:
        for tid in range(n_trainers):
            np.testing.assert_allclose(results[tid], local, rtol=1e-4,
                                       atol=1e-5)
        return local
    return results, local


def test_dist_train_matches_local():
    local = _run_dist("softmax")
    assert local[-1] < local[0] * 0.8  # actually learning


def test_dist_train_distributed_lookup_table():
    """embedding(is_distributed=True): the table lives ONLY on the
    pservers (sharded by rows); trainers prefetch rows over RPC in the
    forward and ship sparse grads back.  Must match the local run."""
    local = _run_dist("emb_dist")
    assert local[-1] < local[0]


def test_dist_train_sparse_embedding():
    """Distributed SelectedRows: sparse grads travel the wire split by
    row range and the pserver applies them; must match the local run."""
    local = _run_dist("emb_sparse")
    assert local[-1] < local[0]  # embedding actually moved


def test_large_shard_over_the_wire():
    """A parameter shard well past gRPC's 4MB default message cap must
    roundtrip (regression: GRPC_OPTIONS unlimited sizes — a 100MB fc
    shard used to fail with 'Received message larger than max')."""
    import numpy as np

    from paddle_tpu.core.scope import Scope
    from paddle_tpu.distributed.rpc import RPCClient, VariableServer

    big = np.random.RandomState(0).rand(1200, 2048).astype(np.float32)
    scope = Scope()
    scope.set("w", big)                       # ~9.8 MB
    applied = []
    srv = VariableServer(scope, {"w@GRAD": 0}, applied.append, fanin=1)
    port = srv.start("127.0.0.1:0")
    ep = "127.0.0.1:%d" % port
    # the singleton's step counter may have advanced in earlier tests;
    # a fresh server starts at round 0 and sync get_vars would wait
    # forever on a higher round
    RPCClient.reset()
    cli = RPCClient.instance()
    try:
        cli.send_var(ep, "w@GRAD", big * 0.5)  # >4MB up
        cli.send_barrier([ep])
        got, = cli.get_vars([(ep, "w")])       # >4MB down
        np.testing.assert_array_equal(np.asarray(got), big)
        assert applied == [0]
    finally:
        cli.send_complete([ep])
        srv.wait()


def test_dist_train_async_mode():
    """Async pserver (reference listen_and_serv RunAsyncLoop): no
    barriers, grads applied on arrival.  Losses cannot match the sync
    baseline exactly; both trainers must still converge."""
    results, local = _run_dist("softmax", steps=12, sync_mode=False)
    for tid, losses in results.items():
        assert len(losses) == 12
        assert np.isfinite(losses).all()
        # async interleaving is nondeterministic: a trainer can regress
        # transiently on the LAST few steps, so gate on the best post-
        # warmup loss rather than the tail mean
        assert np.min(losses[4:]) < losses[0] * 0.85, (tid, losses)
