"""SelectedRows sparse gradients (reference framework/selected_rows.h:30,
lookup_table_op.cc sparse grad kernel, sgd/adam SelectedRows kernels,
sum_op SelectedRows kernel, split_selected_rows_op.cc)."""
import numpy as np

import paddle_tpu.fluid as fluid


def test_merge_rows_unit():
    import jax.numpy as jnp
    from paddle_tpu.core.selected_rows import SelectedRows, merge_rows

    sr = SelectedRows(jnp.asarray([3, 1, 3, 0], jnp.int32),
                      jnp.asarray([[1.], [2.], [10.], [4.]]), height=5)
    m = merge_rows(sr)
    dense = np.zeros((5, 1), np.float32)
    for r, v in zip([3, 1, 3, 0], [1., 2., 10., 4.]):
        dense[r, 0] += v
    np.testing.assert_allclose(np.asarray(m.to_dense()), dense)
    # inactive slots point out of bounds so scatters drop them
    rows = np.asarray(m.rows)
    assert (rows == 5).sum() == 1  # 4 entries, 3 unique


def test_merge_rows_empty():
    import jax.numpy as jnp
    from paddle_tpu.core.selected_rows import SelectedRows, merge_rows

    sr = SelectedRows(jnp.zeros((0,), jnp.int32),
                      jnp.zeros((0, 3), jnp.float32), height=7)
    m = merge_rows(sr)
    assert m.rows.shape == (0,)
    np.testing.assert_allclose(np.asarray(m.to_dense()),
                               np.zeros((7, 3), np.float32))


def test_sparse_grad_with_momentum_densifies():
    """Optimizers without a row-subset kernel fall back to the dense
    update; sparse training must match dense training exactly."""
    opt = lambda: fluid.optimizer.Momentum(learning_rate=0.05,
                                           momentum=0.9)
    dense_l, dense_w = _train_embedding(False, opt, steps=8)
    sparse_l, sparse_w = _train_embedding(True, opt, steps=8)
    np.testing.assert_allclose(sparse_l, dense_l, rtol=1e-5)
    np.testing.assert_allclose(sparse_w, dense_w, rtol=1e-5)


def test_split_selected_rows_static_shape():
    """send's row-range split keeps K static and drops out-of-range rows
    via height-pointing slots (no per-step recompiles on the pserver)."""
    from paddle_tpu.core.selected_rows import SelectedRows

    class FakeClient:
        sent = []

        @classmethod
        def instance(cls):
            return cls()

        def send_vars(self, triples):
            FakeClient.sent = triples

    import paddle_tpu.distributed.rpc as rpc
    from paddle_tpu.core.registry import get_op_info
    from paddle_tpu.core.scope import Scope

    orig = rpc.RPCClient
    rpc.RPCClient = FakeClient
    try:
        scope = Scope()
        sr = SelectedRows(np.asarray([1, 5, 9, 1], np.int32),
                          np.arange(8, dtype=np.float32).reshape(4, 2),
                          height=12)
        scope.set("g", sr)

        class FakeOp:
            def input(self, _):
                return ["g"]

            def attr(self, name, default=None):
                return {"epmap": ["a:1", "b:1"],
                        "block_names": ["g.b0", "g.b1"],
                        "sections": [6, 6]}.get(name, default)

        get_op_info("send").lower(None, FakeOp(), scope, {}, env=None)
        (ep0, _, p0), (ep1, _, p1) = FakeClient.sent
        assert p0.rows.shape == (4,) and p1.rows.shape == (4,)
        # block 0 holds rows [0,6): ids 1,5,1 kept; 9 -> height(6)=dropped
        np.testing.assert_array_equal(p0.rows, [1, 5, 6, 1])
        np.testing.assert_allclose(
            np.asarray(p0.to_dense())[1], [0 + 6, 1 + 7])
        # block 1 holds rows [6,12): id 9 -> 3; others dropped
        np.testing.assert_array_equal(p1.rows, [6, 6, 3, 6])
        np.testing.assert_allclose(np.asarray(p1.to_dense())[3], [4, 5])
    finally:
        rpc.RPCClient = orig


def _train_embedding(is_sparse, optimizer, steps=12, seed=0):
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                ids = fluid.layers.data(name="ids", shape=[6],
                                        dtype="int64")
                y = fluid.layers.data(name="y", shape=[1],
                                      dtype="float32")
                emb = fluid.layers.embedding(
                    ids, size=[40, 8], is_sparse=is_sparse,
                    param_attr=fluid.ParamAttr(
                        name="emb_w",
                        initializer=fluid.initializer.
                        ConstantInitializer(0.05)))
                pooled = fluid.layers.reduce_mean(emb, dim=1)
                pred = fluid.layers.fc(
                    input=pooled, size=1,
                    param_attr=fluid.ParamAttr(
                        name="w2", initializer=fluid.initializer.
                        ConstantInitializer(0.1)))
                loss = fluid.layers.mean(
                    fluid.layers.square_error_cost(pred, y))
                optimizer().minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        rng = np.random.RandomState(seed)
        losses = []
        for _ in range(steps):
            idv = rng.randint(0, 40, (16, 6)).astype(np.int64)
            yv = (np.cos(idv).sum(1, keepdims=True) * 0.2).astype(
                np.float32)
            l, = exe.run(main, feed={"ids": idv, "y": yv},
                         fetch_list=[loss])
            losses.append(float(np.ravel(l)[0]))
        w = np.asarray(scope.find_var("emb_w"))
    return losses, w


def test_sparse_matches_dense_sgd():
    """Scatter-add sparse SGD == dense SGD exactly."""
    dense_l, dense_w = _train_embedding(
        False, lambda: fluid.optimizer.SGD(learning_rate=0.1), steps=40)
    sparse_l, sparse_w = _train_embedding(
        True, lambda: fluid.optimizer.SGD(learning_rate=0.1), steps=40)
    np.testing.assert_allclose(sparse_l, dense_l, rtol=1e-5)
    np.testing.assert_allclose(sparse_w, dense_w, rtol=1e-5)
    # fresh random batches each step => noisy loss; compare windowed means
    assert np.mean(dense_l[-4:]) < np.mean(dense_l[:4])


def test_sparse_adam_trains():
    """Lazy adam (row-subset moments) converges; not bitwise-equal to
    dense adam by design (untouched rows don't decay)."""
    losses, _ = _train_embedding(
        True, lambda: fluid.optimizer.Adam(learning_rate=0.01), steps=25)
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def _train_shared_embedding(is_sparse, steps=15):
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                a = fluid.layers.data(name="a", shape=[3], dtype="int64")
                b = fluid.layers.data(name="b", shape=[3], dtype="int64")
                y = fluid.layers.data(name="y", shape=[1],
                                      dtype="float32")
                attr = fluid.ParamAttr(
                    name="shared_w",
                    initializer=fluid.initializer.ConstantInitializer(
                        0.02))
                ea = fluid.layers.embedding(a, size=[30, 4],
                                            is_sparse=is_sparse,
                                            param_attr=attr)
                eb = fluid.layers.embedding(b, size=[30, 4],
                                            is_sparse=is_sparse,
                                            param_attr=attr)
                merged = fluid.layers.elementwise_add(
                    x=fluid.layers.reduce_mean(ea, dim=1),
                    y=fluid.layers.reduce_mean(eb, dim=1))
                pred = fluid.layers.fc(
                    input=merged, size=1,
                    param_attr=fluid.ParamAttr(
                        name="w_out", initializer=fluid.initializer.
                        ConstantInitializer(0.1)))
                loss = fluid.layers.mean(
                    fluid.layers.square_error_cost(pred, y))
                fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        rng = np.random.RandomState(3)
        ls = []
        for _ in range(steps):
            av = rng.randint(0, 30, (8, 3)).astype(np.int64)
            bv = rng.randint(0, 30, (8, 3)).astype(np.int64)
            yv = rng.randn(8, 1).astype(np.float32) * 0.1
            l, = exe.run(main, feed={"a": av, "b": bv, "y": yv},
                         fetch_list=[loss])
            ls.append(float(np.ravel(l)[0]))
        w = np.asarray(scope.find_var("shared_w"))
    return ls, w


def test_sum_of_selected_rows():
    """Two sparse grads into one table (shared embedding) sum correctly:
    the sparse path must match the dense path exactly."""
    dense_l, dense_w = _train_shared_embedding(False)
    sparse_l, sparse_w = _train_shared_embedding(True)
    np.testing.assert_allclose(sparse_l, dense_l, rtol=1e-5)
    np.testing.assert_allclose(sparse_w, dense_w, rtol=1e-5)
    # weights actually moved (grads flowed through both branches)
    assert not np.allclose(sparse_w, 0.02)


def test_shared_table_grads_sum_one_step():
    """Analytical pin: both embedding branches see ids {0,1,2}, so after
    one SGD step each touched row must move by lr * 2*pred*(2/3)*w_out —
    the factor 2 only appears if the two branches' grads are summed."""
    import paddle_tpu.fluid as fluid

    for is_sparse in (False, True):
        main, startup = fluid.Program(), fluid.Program()
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            with fluid.program_guard(main, startup):
                with fluid.unique_name.guard():
                    a = fluid.layers.data(name="a", shape=[3],
                                          dtype="int64")
                    b = fluid.layers.data(name="b", shape=[3],
                                          dtype="int64")
                    y = fluid.layers.data(name="y", shape=[1],
                                          dtype="float32")
                    attr = fluid.ParamAttr(
                        name="shared_w",
                        initializer=fluid.initializer.
                        ConstantInitializer(0.02))
                    ea = fluid.layers.embedding(
                        a, size=[30, 4], is_sparse=is_sparse,
                        param_attr=attr)
                    eb = fluid.layers.embedding(
                        b, size=[30, 4], is_sparse=is_sparse,
                        param_attr=attr)
                    merged = fluid.layers.elementwise_add(
                        x=fluid.layers.reduce_mean(ea, dim=1),
                        y=fluid.layers.reduce_mean(eb, dim=1))
                    pred = fluid.layers.fc(
                        input=merged, size=1, bias_attr=False,
                        param_attr=fluid.ParamAttr(
                            name="w_out",
                            initializer=fluid.initializer.
                            ConstantInitializer(0.1)))
                    loss = fluid.layers.mean(
                        fluid.layers.square_error_cost(pred, y))
                    fluid.optimizer.SGD(learning_rate=0.1).minimize(
                        loss)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            ids = np.asarray([[0, 1, 2]], np.int64)
            exe.run(main, feed={"a": ids, "b": ids,
                                "y": np.zeros((1, 1), np.float32)},
                    fetch_list=[loss])
            w = np.asarray(scope.find_var("shared_w"))
        # pred = sum over 4 dims of (0.02+0.02)*0.1 = 0.016
        # dloss/d row[r,j] = 2*pred * (1/3)*w_out[j] per branch, x2 summed
        pred_v = 0.016
        grad = 2 * pred_v * (2.0 / 3.0) * 0.1
        expect_touched = 0.02 - 0.1 * grad
        np.testing.assert_allclose(w[:3], expect_touched, rtol=1e-5,
                                   err_msg=f"is_sparse={is_sparse}")
        np.testing.assert_allclose(w[3:], 0.02, rtol=1e-6)


def test_rpc_wire_format_roundtrip():
    """The raw dtype|shape|bytes RPC frame (distributed/rpc.py
    _enc_tensor/_dec_tensor) roundtrips dense arrays of every common
    dtype/rank, 0-d scalars, empty arrays, and SelectedRows."""
    import numpy as np

    from paddle_tpu.core.selected_rows import SelectedRows
    from paddle_tpu.distributed.rpc import _dec_tensor, _enc_tensor

    cases = [
        np.arange(12, dtype=np.float32).reshape(3, 4),
        np.arange(8, dtype=np.int64),
        np.float32(3.5),                       # 0-d
        np.zeros((0, 5), np.float32),          # empty
        np.random.RandomState(0).randn(2, 3, 4).astype(np.float64),
        np.array([True, False]),
    ]
    for i, arr in enumerate(cases):
        name, got, extra = _dec_tensor(
            _enc_tensor("var_%d" % i, arr, extra=i - 2))
        assert name == "var_%d" % i and extra == i - 2
        assert got.dtype == np.asarray(arr).dtype
        assert got.shape == np.asarray(arr).shape
        np.testing.assert_array_equal(got, arr)

    sr = SelectedRows(np.array([1, 5, 7]),
                      np.random.RandomState(1).randn(3, 4)
                      .astype(np.float32), 10)
    name, got, _ = _dec_tensor(_enc_tensor("emb@GRAD", sr, 3))
    assert isinstance(got, SelectedRows) and got.height == 10
    np.testing.assert_array_equal(got.rows, sr.rows)
    np.testing.assert_array_equal(np.asarray(got.values),
                                  np.asarray(sr.values))
