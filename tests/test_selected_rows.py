"""SelectedRows sparse gradients (reference framework/selected_rows.h:30,
lookup_table_op.cc sparse grad kernel, sgd/adam SelectedRows kernels,
sum_op SelectedRows kernel, split_selected_rows_op.cc)."""
import numpy as np

import paddle_tpu.fluid as fluid


def test_merge_rows_unit():
    import jax.numpy as jnp
    from paddle_tpu.core.selected_rows import SelectedRows, merge_rows

    sr = SelectedRows(jnp.asarray([3, 1, 3, 0], jnp.int32),
                      jnp.asarray([[1.], [2.], [10.], [4.]]), height=5)
    m = merge_rows(sr)
    dense = np.zeros((5, 1), np.float32)
    for r, v in zip([3, 1, 3, 0], [1., 2., 10., 4.]):
        dense[r, 0] += v
    np.testing.assert_allclose(np.asarray(m.to_dense()), dense)
    # inactive slots point out of bounds so scatters drop them
    rows = np.asarray(m.rows)
    assert (rows == 5).sum() == 1  # 4 entries, 3 unique


def _train_embedding(is_sparse, optimizer, steps=12, seed=0):
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                ids = fluid.layers.data(name="ids", shape=[6],
                                        dtype="int64")
                y = fluid.layers.data(name="y", shape=[1],
                                      dtype="float32")
                emb = fluid.layers.embedding(
                    ids, size=[40, 8], is_sparse=is_sparse,
                    param_attr=fluid.ParamAttr(
                        name="emb_w",
                        initializer=fluid.initializer.
                        ConstantInitializer(0.05)))
                pooled = fluid.layers.reduce_mean(emb, dim=1)
                pred = fluid.layers.fc(
                    input=pooled, size=1,
                    param_attr=fluid.ParamAttr(
                        name="w2", initializer=fluid.initializer.
                        ConstantInitializer(0.1)))
                loss = fluid.layers.mean(
                    fluid.layers.square_error_cost(pred, y))
                optimizer().minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        rng = np.random.RandomState(seed)
        losses = []
        for _ in range(steps):
            idv = rng.randint(0, 40, (16, 6)).astype(np.int64)
            yv = (np.cos(idv).sum(1, keepdims=True) * 0.2).astype(
                np.float32)
            l, = exe.run(main, feed={"ids": idv, "y": yv},
                         fetch_list=[loss])
            losses.append(float(np.ravel(l)[0]))
        w = np.asarray(scope.find_var("emb_w"))
    return losses, w


def test_sparse_matches_dense_sgd():
    """Scatter-add sparse SGD == dense SGD exactly."""
    dense_l, dense_w = _train_embedding(
        False, lambda: fluid.optimizer.SGD(learning_rate=0.1))
    sparse_l, sparse_w = _train_embedding(
        True, lambda: fluid.optimizer.SGD(learning_rate=0.1))
    np.testing.assert_allclose(sparse_l, dense_l, rtol=1e-5)
    np.testing.assert_allclose(sparse_w, dense_w, rtol=1e-5)
    assert dense_l[-1] < dense_l[0] * 0.7


def test_sparse_adam_trains():
    """Lazy adam (row-subset moments) converges; not bitwise-equal to
    dense adam by design (untouched rows don't decay)."""
    losses, _ = _train_embedding(
        True, lambda: fluid.optimizer.Adam(learning_rate=0.01), steps=25)
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_sum_of_selected_rows():
    """Two sparse grads into one table (shared embedding) sum correctly."""
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                a = fluid.layers.data(name="a", shape=[3], dtype="int64")
                b = fluid.layers.data(name="b", shape=[3], dtype="int64")
                y = fluid.layers.data(name="y", shape=[1],
                                      dtype="float32")
                attr = fluid.ParamAttr(
                    name="shared_w",
                    initializer=fluid.initializer.ConstantInitializer(
                        0.02))
                ea = fluid.layers.embedding(a, size=[30, 4],
                                            is_sparse=True,
                                            param_attr=attr)
                eb = fluid.layers.embedding(b, size=[30, 4],
                                            is_sparse=True,
                                            param_attr=attr)
                merged = fluid.layers.elementwise_add(
                    x=fluid.layers.reduce_mean(ea, dim=1),
                    y=fluid.layers.reduce_mean(eb, dim=1))
                pred = fluid.layers.fc(input=merged, size=1)
                loss = fluid.layers.mean(
                    fluid.layers.square_error_cost(pred, y))
                fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        rng = np.random.RandomState(3)
        ls = []
        for _ in range(15):
            av = rng.randint(0, 30, (8, 3)).astype(np.int64)
            bv = rng.randint(0, 30, (8, 3)).astype(np.int64)
            yv = rng.randn(8, 1).astype(np.float32) * 0.1
            l, = exe.run(main, feed={"a": av, "b": bv, "y": yv},
                         fetch_list=[loss])
            ls.append(float(np.ravel(l)[0]))
        assert ls[-1] < ls[0], ls
