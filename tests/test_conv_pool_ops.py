"""conv2d / pool2d tests (cf. reference test_conv2d_op.py, test_pool2d_op.py)."""
import numpy as np

from op_test import OpTest

rng = np.random.RandomState(5)


def _conv2d_ref(x, w, stride, pad):
    n, c, h, wd = x.shape
    oc, ic, kh, kw = w.shape
    xp = np.pad(x, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    oh = (h + 2 * pad - kh) // stride + 1
    ow = (wd + 2 * pad - kw) // stride + 1
    out = np.zeros((n, oc, oh, ow), dtype=np.float64)
    for i in range(oh):
        for j in range(ow):
            patch = xp[:, :, i * stride:i * stride + kh,
                       j * stride:j * stride + kw]
            out[:, :, i, j] = np.tensordot(patch, w, axes=([1, 2, 3],
                                                           [1, 2, 3]))
    return out


def test_conv2d_basic():
    x = rng.randn(2, 3, 7, 7).astype(np.float32)
    w = rng.randn(4, 3, 3, 3).astype(np.float32) * 0.5

    class T(OpTest):
        op_type = "conv2d"
        inputs = {"Input": x, "Filter": w}
        attrs = {"strides": [1, 1], "paddings": [1, 1],
                 "dilations": [1, 1], "groups": 1}
        outputs = {"Output": _conv2d_ref(x, w, 1, 1).astype(np.float32)}

    T().check_output(atol=1e-4)


def test_conv2d_stride_grad():
    x = rng.randn(1, 2, 5, 5).astype(np.float32)
    w = rng.randn(3, 2, 3, 3).astype(np.float32) * 0.5

    class T(OpTest):
        op_type = "conv2d"
        inputs = {"Input": x, "Filter": w}
        attrs = {"strides": [2, 2], "paddings": [0, 0],
                 "dilations": [1, 1], "groups": 1}
        outputs = {"Output": _conv2d_ref(x, w, 2, 0).astype(np.float32)}

    T().check_output(atol=1e-4)
    T().check_grad(["Input", "Filter"], max_relative_error=0.02)


def test_pool2d_max():
    x = rng.randn(2, 3, 6, 6).astype(np.float32)
    ref = x.reshape(2, 3, 3, 2, 3, 2).max(axis=(3, 5))

    class T(OpTest):
        op_type = "pool2d"
        inputs = {"X": x}
        attrs = {"pooling_type": "max", "ksize": [2, 2],
                 "strides": [2, 2], "paddings": [0, 0]}
        outputs = {"Out": ref}

    T().check_output()
    T().check_grad(["X"], max_relative_error=0.02)


def test_pool2d_avg():
    x = rng.randn(2, 3, 6, 6).astype(np.float32)
    ref = x.reshape(2, 3, 3, 2, 3, 2).mean(axis=(3, 5))

    class T(OpTest):
        op_type = "pool2d"
        inputs = {"X": x}
        attrs = {"pooling_type": "avg", "ksize": [2, 2],
                 "strides": [2, 2], "paddings": [0, 0]}
        outputs = {"Out": ref}

    T().check_output()
    T().check_grad(["X"])


def test_pool2d_global():
    x = rng.randn(2, 3, 4, 4).astype(np.float32)

    class T(OpTest):
        op_type = "pool2d"
        inputs = {"X": x}
        attrs = {"pooling_type": "avg", "ksize": [1, 1],
                 "strides": [1, 1], "paddings": [0, 0],
                 "global_pooling": True}
        outputs = {"Out": x.mean(axis=(2, 3), keepdims=True)}

    T().check_output()


def test_conv2d_transpose_shape():
    import paddle_tpu.fluid as fluid
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        x = fluid.layers.data(name="x", shape=[3, 8, 8], dtype="float32")
        y = fluid.layers.conv2d_transpose(x, num_filters=6, filter_size=4,
                                          stride=2, padding=1)
        assert tuple(y.shape[1:]) == (6, 16, 16), y.shape
