"""Watchtower SLO engine (ISSUE 13): spec parsing, burn-rate math,
alert lifecycle, per-tenant serving tagging, the fault drill the
fault_matrix 'slo' preset runs, the <2% sampler/evaluator overhead
gate, and the e2e acceptance run (serving + 2x2 pserver workload with
the tsdb sampler + SLO evaluator armed in every process)."""
import glob
import json
import multiprocessing as mp
import os
import socket
import sys
import time

import numpy as np
import pytest

from paddle_tpu.core.flags import FLAGS
from paddle_tpu.observability import flight
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.observability import slo, tsdb

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")


def _tool(name):
    sys.path.insert(0, TOOLS)
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture(autouse=True)
def _clean():
    slo.reset()
    yield
    slo.reset()
    tsdb.stop_sampler()


# ------------------------------------------------------------- parsing

def test_parse_objective_and_inline_specs():
    assert slo.parse_objective("serve_request_ms.p99 <= 10") == (
        "serve_request_ms.p99", "<=", 10.0)
    specs = slo.load_specs(
        "serve_request_ms.p99<=10,"
        "pserver_rounds_applied_total.rate>=1.5,"
        "numerics_nonfinite_total==0")
    assert [s.metric for s in specs] == [
        "serve_request_ms.p99", "pserver_rounds_applied_total.rate",
        "numerics_nonfinite_total"]
    assert specs[0].op == "<=" and specs[0].threshold == 10.0
    assert specs[1].op == ">=" and specs[1].threshold == 1.5
    # defaults ride along
    assert specs[0].budget == slo.DEFAULT_BUDGET
    assert specs[0].fast_s == slo.DEFAULT_FAST_S
    with pytest.raises(ValueError):
        slo.parse_objective("metric ~ 5")
    with pytest.raises(ValueError):
        slo.load_specs("a<=1,a<=2")       # duplicate names
    assert slo.load_specs("") == []


def test_load_specs_json_and_toml(tmp_path):
    spec = {"slo": [
        {"name": "p99", "objective": "serve_request_ms.p99 <= 10",
         "budget": 0.02, "fast_s": 60, "slow_s": 600,
         "burn_fast": 10.0, "burn_slow": 1.5},
        "numerics_nonfinite_total == 0",
    ]}
    jpath = str(tmp_path / "slo.json")
    with open(jpath, "w") as f:
        json.dump(spec, f)
    specs = slo.load_specs(jpath)
    assert specs[0].name == "p99" and specs[0].budget == 0.02
    assert specs[0].fast_s == 60 and specs[0].burn_fast == 10.0
    assert specs[1].metric == "numerics_nonfinite_total"

    tpath = str(tmp_path / "slo.toml")
    with open(tpath, "w") as f:
        f.write('[[slo]]\nname = "p99"\n'
                'objective = "serve_request_ms.p99 <= 10"\n'
                'budget = 0.02\n'
                '[[slo]]\n'
                'objective = "numerics_nonfinite_total == 0"\n')
    specs2 = slo.load_specs(tpath)
    assert specs2[0].name == "p99" and specs2[0].budget == 0.02
    assert specs2[1].op == "=="
    with pytest.raises(ValueError):
        slo.SLO("m", "<=", 1, budget=0.0)   # bad budget
    with pytest.raises(ValueError):
        slo.SLO("m", "~", 1)                # bad op
    # a typo'd spec-file path must raise, never silently re-parse as
    # inline objectives (that would disable monitoring undiagnosed)
    with pytest.raises(FileNotFoundError):
        slo.load_specs(str(tmp_path / "nope.json"))
    with pytest.raises(FileNotFoundError):
        slo.load_specs(str(tmp_path / "nope.toml"))


# ------------------------------------------------------ burn-rate math

def _mk_store(tmp_path, values, name="m", now=None, step=1.0):
    store = tsdb.TSDB(str(tmp_path / "ts"))
    now = now or time.time()
    for i, v in enumerate(values):
        store.append(name, v, t=now - (len(values) - i) * step)
    return store, now


def test_burn_rate_math(tmp_path):
    """burn = bad_frac / budget, per window, firing at its
    threshold."""
    # 20 samples, 10 violate m<=5 -> bad_frac 0.5; budget 0.05 ->
    # burn 10
    store, now = _mk_store(tmp_path, [1.0] * 10 + [9.0] * 10)
    spec = slo.SLO("m", "<=", 5, budget=0.05, fast_s=60, slow_s=600,
                   burn_fast=8.0, burn_slow=2.0)
    ev = slo.Evaluator(store, [spec], dump_alerts=False)
    row = ev.evaluate(now=now)[0]
    fast = row["windows"]["fast"]
    assert fast["samples"] == 20 and fast["bad"] == 10
    assert fast["bad_frac"] == pytest.approx(0.5)
    assert fast["burn"] == pytest.approx(10.0)
    assert fast["firing"]                   # 10 >= burn_fast 8
    slow = row["windows"]["slow"]
    assert slow["burn"] == pytest.approx(10.0) and slow["firing"]
    assert row["budget_remaining"] == 0.0   # 0.5/0.05 clamps at 0
    # healthy series: zero burn, full budget
    store2, now2 = _mk_store(tmp_path / "h", [1.0] * 20)
    row2 = slo.Evaluator(store2, [spec],
                         dump_alerts=False).evaluate(now=now2)[0]
    assert row2["windows"]["fast"]["burn"] == 0.0
    assert not row2["windows"]["fast"]["firing"]
    assert row2["budget_remaining"] == 1.0


def test_burn_needs_min_samples_and_empty_window(tmp_path):
    store, now = _mk_store(tmp_path, [9.0, 9.0])   # violating, but 2
    spec = slo.SLO("m", "<=", 5, budget=0.01, min_samples=3)
    ev = slo.Evaluator(store, [spec], dump_alerts=False)
    fast = ev.evaluate(now=now)[0]["windows"]["fast"]
    assert fast["burn"] > 0 and not fast["firing"]
    # a window with NO samples is unknown, not firing
    empty = ev.evaluate(now=now + 10000)[0]["windows"]["fast"]
    assert empty["samples"] == 0 and not empty["firing"]


def test_rate_objective_windows(tmp_path):
    """A .rate objective evaluates consecutive-sample rates: a
    throughput floor fires when the counter stalls."""
    store = tsdb.TSDB(str(tmp_path / "ts"))
    now = time.time()
    # counter advances 5/s for 20 s, then STALLS for 20 s
    for i in range(20):
        store.append("rounds_total", 5.0 * i, t=now - 40 + i)
    for i in range(20):
        store.append("rounds_total", 95.0, t=now - 20 + i)
    spec = slo.SLO("rounds_total.rate", ">=", 1.0, budget=0.3,
                   fast_s=15, slow_s=45, burn_fast=2.0,
                   burn_slow=2.0)
    ev = slo.Evaluator(store, [spec], dump_alerts=False)
    row = ev.evaluate(now=now)[0]
    # fast window only sees the stall -> 100% bad -> burn 1/0.3
    assert row["windows"]["fast"]["burn"] == pytest.approx(1 / 0.3,
                                                           rel=1e-3)
    assert row["windows"]["fast"]["firing"]
    # slow window is ~half healthy (20 of 39 rate points bad ->
    # burn ~1.71), under its 2.0 threshold
    assert row["windows"]["slow"]["burn"] == pytest.approx(
        20 / 39 / 0.3, rel=1e-3)
    assert not row["windows"]["slow"]["firing"]


# ------------------------------------------------------ alert lifecycle

def test_alert_fires_once_per_slo_window_with_series(tmp_path):
    """A firing (slo, window) bumps slo_alerts_total, mirrors gauges,
    and writes EXACTLY ONE flight dump embedding the offending
    series — repeated evaluations do not re-dump."""
    obs_metrics.zero_all()
    store, now = _mk_store(tmp_path, [9.0] * 10)
    spec = slo.SLO("m", "<=", 5, name="drill", budget=0.05,
                   fast_s=60, slow_s=600)
    prev = FLAGS.telemetry_dump_dir
    FLAGS.telemetry_dump_dir = str(tmp_path / "dumps")
    try:
        ev = slo.Evaluator(store, [spec])
        for _ in range(4):                  # repeated passes
            ev.evaluate(now=now)
        assert obs_metrics.counter("slo_alerts_total").value == 2
        assert obs_metrics.gauge("slo_alerts_active").value == 2
        assert obs_metrics.gauge("slo_burn_fast_drill").value \
            == pytest.approx(20.0)
        assert obs_metrics.gauge(
            "slo_budget_remaining_drill").value == 0.0
        dumps = sorted(glob.glob(
            str(tmp_path / "dumps" / "flight_*.json")))
        reasons = {}
        for p in dumps:
            with open(p) as f:
                rec = json.load(f)
            reasons.setdefault(rec["reason"], []).append(rec)
        # exactly one dump per (slo, window)
        assert sorted(reasons) == ["slo:drill:fast", "slo:drill:slow"]
        assert all(len(v) == 1 for v in reasons.values())
        alert = reasons["slo:drill:fast"][0]["slo"]["alert"]
        assert alert["slo"] == "drill" and alert["window"] == "fast"
        assert alert["objective"] == "m <= 5"
        assert len(alert["series"]) == 10   # the offending series
        assert all(v == 9.0 for _, v in alert["series"])
        # a FIRST-evaluation alert's dump still carries the current
        # pass's status table (alerts fire after status commit)
        status = reasons["slo:drill:fast"][0]["slo"]["status"]
        assert status and status[0]["name"] == "drill"
        assert status[0]["windows"]["fast"]["burn"] \
            == pytest.approx(20.0)
        # alert state is visible via the module introspection surface
        ev2 = slo._EVAL   # not installed; use the evaluator directly
        assert {(a["slo"], a["window"])
                for a in ev.active_alerts()} \
            == {("drill", "fast"), ("drill", "slow")}
    finally:
        FLAGS.telemetry_dump_dir = prev


def test_alert_clears_when_burn_recovers(tmp_path):
    store, now = _mk_store(tmp_path, [9.0] * 10)
    spec = slo.SLO("m", "<=", 5, name="rec", budget=0.05, fast_s=30,
                   slow_s=30000)
    ev = slo.Evaluator(store, [spec], dump_alerts=False)
    ev.evaluate(now=now)
    assert ("rec", "fast") in {(a["slo"], a["window"])
                               for a in ev.active_alerts()}
    # healthy samples push the bad window out of the fast horizon
    for i in range(60):
        store.append("m", 1.0, t=now + i)
    ev.evaluate(now=now + 60)
    assert ("rec", "fast") not in {(a["slo"], a["window"])
                                   for a in ev.active_alerts()}


def test_barrier_status_carries_slo_alerts(tmp_path):
    """BarrierStatus-style introspection: the pserver's status reply
    names currently-firing alerts."""
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.distributed.rpc import VariableServer

    srv = VariableServer(Scope(), {}, lambda b: None, fanin=1)
    st = json.loads(srv._barrier_status(b"").decode())
    assert st["slo_alerts"] == []
    store, now = _mk_store(tmp_path, [9.0] * 10)
    ev = slo.install(store=store,
                     specs=[slo.SLO("m", "<=", 5, name="ps",
                                    budget=0.05)],
                     dump_alerts=False)
    ev.evaluate(now=now)
    st = json.loads(srv._barrier_status(b"").decode())
    assert "ps:fast" in st["slo_alerts"]


# ---------------------------------------------- per-tenant serving tags

def _save_tiny_model(d):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.core.scope import Scope

    main_p, startup = fluid.Program(), fluid.Program()
    scope = Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main_p, startup):
            with fluid.unique_name.guard():
                x = fluid.layers.data(name="x", shape=[16],
                                      dtype="float32")
                h = fluid.layers.fc(x, size=32, act="tanh")
                out = fluid.layers.fc(h, size=4, act="softmax")
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        fluid.io.save_inference_model(
            d, ["x"], [out], exe, main_program=main_p,
            aot_feed_specs={"x": ((1, 16), "float32")})
    return np.ones((1, 16), np.float32)


def test_per_tenant_request_metrics(tmp_path):
    """server.submit tags every request into the tenant's own
    latency histogram; failures/drops land in its error counter —
    the series a per-tenant SLO evaluates."""
    from paddle_tpu import serving

    obs_metrics.zero_all()
    d = str(tmp_path / "model")
    x = _save_tiny_model(d)
    with serving.InferenceServer(max_batch=2, max_wait_us=0) as srv:
        srv.load("tenant_a", d, warm=[1])
        for _ in range(5):
            srv.predict("tenant_a", {"x": x})
        h = obs_metrics.histogram("serve_request_ms_tenant_a")
        assert h.count == 5
        assert obs_metrics.counter(
            "serve_request_errors_total_tenant_a").value == 0
        # a failing request (wrong feed width caught in-batch) counts
        # as that tenant's error, not a latency sample
        with pytest.raises(Exception):
            srv.predict("tenant_a",
                        {"x": np.ones((1, 7), np.float32)})
        assert obs_metrics.counter(
            "serve_request_errors_total_tenant_a").value >= 1
        assert h.count == 5


# ----------------------------------------------------- the fault drill

def test_slo_fault_drill(tmp_path):
    """The fault_matrix 'slo' preset body: a short serve+train loop
    with the tsdb sampler feeding a store and the SLO evaluator armed,
    while an injected serve_dispatch DELAY fault burns the
    request-latency budget.  Asserts the burn-rate alert fires within
    the fast window, exactly one flight dump lands per (slo, window)
    naming the violated SLO with the offending series embedded, and
    the healthy train-side SLO never fires."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu import serving
    from paddle_tpu.distributed import resilience

    obs_metrics.zero_all()
    dump_dir = FLAGS.telemetry_dump_dir or str(tmp_path / "dumps")
    prev_dump = FLAGS.telemetry_dump_dir
    FLAGS.telemetry_dump_dir = dump_dir
    store = tsdb.TSDB(str(tmp_path / "ts"))
    prev_inj = resilience.get_injector()
    if not any(r.point == "serve_dispatch" for r in prev_inj.rules):
        # standalone run: the preset exports FLAGS_fault_spec itself
        resilience.install_faults("serve_dispatch:delay:0.02")
    try:
        # -- train half: a few prepared steps feed the executor
        # step-wall histogram the healthy SLO watches
        main_p, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main_p, startup):
            with fluid.unique_name.guard():
                x = fluid.layers.data(name="x", shape=[8],
                                      dtype="float32")
                loss = fluid.layers.mean(fluid.layers.fc(x, size=4))
                fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            feed = {"x": np.ones((4, 8), np.float32)}
            prep = exe.prepare(main_p, feed_specs=feed,
                               fetch_list=[loss])
            for _ in range(5):
                prep.run_prepared(feed)
            prep.sync_scope()

        # -- serve half under the injected latency fault
        d = str(tmp_path / "model")
        xfeed = _save_tiny_model(d)
        specs = [
            slo.SLO("serve_request_ms_m.p99", "<=", 2.0,
                    name="serve_p99", budget=0.05, fast_s=30,
                    slow_s=300, min_samples=3),
            slo.SLO("executor_step_wall_ms.p99", "<=", 1e9,
                    name="train_step", budget=0.05, fast_s=30,
                    slow_s=300, min_samples=3),
        ]
        ev = slo.install(store=store, specs=specs)
        t_fault = time.time()
        alert_at = None
        with serving.InferenceServer(max_batch=2,
                                     max_wait_us=0) as srv:
            srv.load("m", d, warm=[1])
            for i in range(30):
                srv.predict("m", {"x": xfeed})
                tsdb.sample_registry(store)
                ev.evaluate()
                if alert_at is None and ev.active_alerts():
                    alert_at = time.time()
        assert alert_at is not None, "burn-rate alert never fired"
        # (1) within the fast window of the fault's onset
        assert alert_at - t_fault < specs[0].fast_s
        firing = {(a["slo"], a["window"])
                  for a in ev.active_alerts()}
        assert ("serve_p99", "fast") in firing
        # extra evaluation passes must not re-dump
        for _ in range(3):
            ev.evaluate()
        # (2) exactly one flight dump per (slo, window), naming the
        # violated SLO and embedding the offending series
        by_reason = {}
        for p in glob.glob(os.path.join(dump_dir, "flight_*.json")):
            with open(p) as f:
                rec = json.load(f)
            if str(rec.get("reason", "")).startswith("slo:"):
                by_reason.setdefault(rec["reason"], []).append(rec)
        assert set(by_reason) == {"slo:serve_p99:fast",
                                  "slo:serve_p99:slow"}
        assert all(len(v) == 1 for v in by_reason.values())
        alert = by_reason["slo:serve_p99:fast"][0]["slo"]["alert"]
        assert alert["slo"] == "serve_p99"
        assert alert["objective"].startswith(
            "serve_request_ms_m.p99")
        assert alert["series"], "offending series not embedded"
        assert all(v > 2.0 for _, v in alert["series"][-3:])
        # the healthy train-side SLO never fired
        assert ("train_step", "fast") not in firing
        assert obs_metrics.gauge(
            "slo_burn_fast_train_step").value == 0.0
    finally:
        resilience._injector = prev_inj
        FLAGS.telemetry_dump_dir = prev_dump
        store.close()


# ------------------------------------------------------- overhead gate

def test_sampler_and_evaluator_overhead_gate():
    """Acceptance (3): one full registry sample and one full SLO
    evaluation pass each cost < 2% of their sampling interval, and
    the measured fractions land in the registry as
    telemetry_gate_* gauges (satellite: gate history reaches the
    tsdb instead of living in tool stdout)."""
    T = _tool("telemetry_overhead")
    tsdb_us, tsdb_ms = T._measure_tsdb_us(repeats=2, iters=100)
    tsdb_frac = tsdb_us / (tsdb_ms * 1e3)
    slo_us, slo_ms = T._measure_slo_us(repeats=2, iters=60)
    slo_frac = slo_us / (slo_ms * 1e3)
    assert tsdb_frac < 0.02, tsdb_frac
    assert slo_frac < 0.02, slo_frac
    names = T.record_gate_gauges(
        {"tsdb_overhead_frac": tsdb_frac,
         "slo_overhead_frac": slo_frac})
    assert set(names) == {"telemetry_gate_tsdb_overhead_frac",
                          "telemetry_gate_slo_overhead_frac"}
    snap = obs_metrics.snapshot()
    assert snap["telemetry_gate_tsdb_overhead_frac"]["value"] \
        == pytest.approx(tsdb_frac)


# ------------------------------------------------- e2e acceptance run

def test_e2e_pserver_workload_retains_history(tmp_path):
    """Acceptance core: a real 2x2 pserver workload with
    FLAGS_tsdb_dir set in every process — each trainer/pserver
    retains its own metric history, the SLO file evaluates in-process
    (burn gauges ride the telemetry dumps), and the parent evaluates
    the same SLO file read-only against the pserver's store: sane
    floors hold, an impossible floor fires."""
    import dist_train_helpers as H

    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    tsdb_root = str(tmp_path / "tsdb")
    dump_dir = str(tmp_path / "dumps")
    slo_path = str(tmp_path / "slo.json")
    with open(slo_path, "w") as f:
        json.dump({"slo": [
            {"name": "nonfinite",
             "objective": "numerics_nonfinite_total == 0",
             "fast_s": 5, "slow_s": 60},
            {"name": "barrier_p99",
             "objective": "pserver_barrier_ms.p99 <= 60000",
             "fast_s": 5, "slow_s": 60},
            {"name": "stale",
             "objective": "pserver_staleness_gap <= 4",
             "fast_s": 5, "slow_s": 60},
        ]}, f)
    env = {"FLAGS_telemetry": "1",
           "FLAGS_telemetry_dump_dir": dump_dir,
           "FLAGS_tsdb_dir": tsdb_root,
           "FLAGS_tsdb_sample_ms": "25",
           "FLAGS_slo_spec": slo_path,
           "FLAGS_slo_eval_ms": "50"}
    ctx = mp.get_context("spawn")
    eps = ["127.0.0.1:%d" % _free_port() for _ in range(2)]
    pservers = ",".join(eps)
    steps = 3
    ps_procs = [ctx.Process(target=H.run_pserver,
                            args=(ep, pservers, 2, "softmax", True,
                                  env))
                for ep in eps]
    for p in ps_procs:
        p.start()
    q = ctx.Queue()
    tr_procs = [ctx.Process(target=H.run_trainer,
                            args=(tid, pservers, 2, steps, q,
                                  "softmax", True, env))
                for tid in range(2)]
    for p in tr_procs:
        p.start()
    for _ in range(2):
        q.get(timeout=240)
    for p in tr_procs + ps_procs:
        p.join(timeout=60)
        if p.is_alive():
            p.terminate()
            pytest.fail("worker did not exit")

    # every process left its own store, and they are disjoint dirs
    stores = tsdb.open_stores(tsdb_root)
    assert len(stores) == 4, sorted(stores)
    ps_stores = {k: s for k, s in stores.items()
                 if (s.latest("pserver_rounds_applied_total")
                     or (0, 0))[1] >= steps}
    tr_stores = {k: s for k, s in stores.items()
                 if (s.latest("rpc_bytes_sent_total")
                     or (0, 0))[1] > 0 and k not in ps_stores}
    assert len(ps_stores) == 2, sorted(stores)
    assert len(tr_stores) == 2, sorted(stores)
    for s in ps_stores.values():
        # durable history, not just a final value: multiple samples
        # and the barrier-latency histogram decomposition
        t, v = s.scan("pserver_rounds_applied_total")
        assert len(t) >= 3 and v[-1] >= steps
        assert s.latest("pserver_barrier_ms.count")[1] > 0
        assert s.latest("pserver_barrier_ms.p99") is not None
    # the in-child evaluator ran: burn gauges rode the trace dumps
    trace_dumps = glob.glob(os.path.join(dump_dir, "trace_*.json"))
    assert len(trace_dumps) == 4
    saw_gauges = 0
    for p in trace_dumps:
        with open(p) as f:
            m = json.load(f).get("metrics", {})
        if "slo_burn_fast_nonfinite" in m:
            saw_gauges += 1
            assert m["slo_burn_fast_nonfinite"]["value"] == 0.0
    assert saw_gauges >= 1, "no child evaluator ever evaluated"
    # no alert fired on the healthy run
    assert not [p for p in glob.glob(
        os.path.join(dump_dir, "flight_*.json"))
        if json.load(open(p)).get("reason", "").startswith("slo:")]

    # parent-side: evaluate the SAME file read-only against a pserver
    # store — sane objectives hold; an impossible floor fires
    store = list(ps_stores.values())[0]
    specs = slo.load_specs(slo_path)
    ev = slo.Evaluator(store, specs, dump_alerts=False)
    t_last, _ = store.latest("pserver_rounds_applied_total")
    rows = {r["name"]: r for r in ev.evaluate(now=t_last)}
    assert not rows["nonfinite"]["windows"]["fast"]["firing"]
    assert not rows["barrier_p99"]["windows"]["fast"]["firing"]
    # every sample violates (the counter is never negative), so the
    # burn is 1/budget regardless of when each sample landed
    impossible = slo.SLO("pserver_rounds_applied_total", "<=", -1,
                         name="impossible", budget=0.01, fast_s=120,
                         slow_s=600)
    ev2 = slo.Evaluator(store, [impossible], dump_alerts=False)
    row = ev2.evaluate(now=t_last)[0]
    assert row["windows"]["fast"]["firing"]
    assert row["windows"]["fast"]["burn"] == pytest.approx(100.0)

    # and the full-pile sentinel still passes on the genuine artifacts
    # while flagging a degraded one (acceptance 4 — details in
    # test_watchtower.py)
    ps_tool = _tool("perf_sentinel")
    traj = ps_tool.build_trajectory(REPO, tsdb_root=tsdb_root)
    assert traj["metrics"]["serve_floor_qps"]["floor"] > 0
    assert traj["tsdb"], "tsdb evidence missing from trajectory"
