"""Tensor-manipulation op tests (cf. reference test_concat_op.py,
test_reshape_op.py, test_transpose_op.py, test_lookup_table_op.py, ...)."""
import numpy as np

from op_test import OpTest

rng = np.random.RandomState(9)


def test_concat():
    a = rng.randn(2, 3).astype(np.float32)
    b = rng.randn(2, 4).astype(np.float32)

    class T(OpTest):
        op_type = "concat"
        inputs = {"X": [("a", a), ("b", b)]}
        attrs = {"axis": 1}
        outputs = {"Out": np.concatenate([a, b], axis=1)}

    T().check_output()
    T().check_grad(["a", "b"])


def test_split():
    x = rng.randn(4, 6).astype(np.float32)

    class T(OpTest):
        op_type = "split"
        inputs = {"X": x}
        attrs = {"axis": 1, "num": 0, "sections": [2, 4]}
        outputs = {"Out": [("o0", x[:, :2]), ("o1", x[:, 2:])]}

    T().check_output()


def test_reshape():
    x = rng.randn(2, 6).astype(np.float32)

    class T(OpTest):
        op_type = "reshape"
        inputs = {"X": x}
        attrs = {"shape": [4, 3]}
        outputs = {"Out": x.reshape(4, 3)}

    T().check_output()
    T().check_grad(["X"])


def test_reshape_infer():
    x = rng.randn(2, 6).astype(np.float32)

    class T(OpTest):
        op_type = "reshape"
        inputs = {"X": x}
        attrs = {"shape": [-1, 4]}
        outputs = {"Out": x.reshape(3, 4)}

    T().check_output()


def test_transpose():
    x = rng.randn(2, 3, 4).astype(np.float32)

    class T(OpTest):
        op_type = "transpose"
        inputs = {"X": x}
        attrs = {"axis": [1, 0, 2]}
        outputs = {"Out": x.transpose(1, 0, 2)}

    T().check_output()
    T().check_grad(["X"])


def test_lookup_table():
    w = rng.randn(10, 4).astype(np.float32)
    ids = np.array([[1], [3], [1], [7]], dtype=np.int64)

    class T(OpTest):
        op_type = "lookup_table"
        inputs = {"W": w, "Ids": ids}
        outputs = {"Out": w[ids[:, 0]]}

    T().check_output()
    # grad of W is a scatter-add: ids 1 appears twice
    T().check_grad(["W"])


def test_lookup_table_padding_idx():
    w = rng.randn(6, 3).astype(np.float32)
    ids = np.array([[0], [2], [5]], dtype=np.int64)
    expected = w[ids[:, 0]].copy()
    expected[ids[:, 0] == 2] = 0

    class T(OpTest):
        op_type = "lookup_table"
        inputs = {"W": w, "Ids": ids}
        attrs = {"padding_idx": 2}
        outputs = {"Out": expected}

    T().check_output()


def test_gather():
    x = rng.randn(5, 3).astype(np.float32)
    idx = np.array([0, 2, 4], dtype=np.int32)

    class T(OpTest):
        op_type = "gather"
        inputs = {"X": x, "Index": idx}
        outputs = {"Out": x[idx]}

    T().check_output()
    T().check_grad(["X"])


def test_top_k():
    x = rng.randn(3, 6).astype(np.float32)
    k = 2
    idx = np.argsort(-x, axis=1)[:, :k]
    vals = np.take_along_axis(x, idx, axis=1)

    class T(OpTest):
        op_type = "top_k"
        inputs = {"X": x}
        attrs = {"k": k}
        outputs = {"Out": vals, "Indices": idx.astype(np.int64)}

    T().check_output()


def test_one_hot():
    x = np.array([[1], [0], [3]], dtype=np.int64)
    expected = np.zeros((3, 4), np.float32)
    expected[np.arange(3), x[:, 0]] = 1

    class T(OpTest):
        op_type = "one_hot"
        inputs = {"X": x}
        attrs = {"depth": 4}
        outputs = {"Out": expected}

    T().check_output()


def test_cast():
    from paddle_tpu.core.types import DataType
    x = rng.randn(3, 4).astype(np.float32)

    class T(OpTest):
        op_type = "cast"
        inputs = {"X": x}
        attrs = {"in_dtype": DataType.FP32, "out_dtype": DataType.FP64}
        outputs = {"Out": x.astype(np.float64)}

    T().check_output()


def test_expand():
    x = rng.randn(2, 3).astype(np.float32)

    class T(OpTest):
        op_type = "expand"
        inputs = {"X": x}
        attrs = {"expand_times": [2, 2]}
        outputs = {"Out": np.tile(x, (2, 2))}

    T().check_output()
    T().check_grad(["X"])


def test_pad():
    x = rng.randn(2, 3).astype(np.float32)

    class T(OpTest):
        op_type = "pad"
        inputs = {"X": x}
        attrs = {"paddings": [0, 1, 2, 0], "pad_value": 0.5}
        outputs = {"Out": np.pad(x, ((0, 1), (2, 0)),
                                 constant_values=0.5)}

    T().check_output()


def test_slice():
    x = rng.randn(4, 5).astype(np.float32)

    class T(OpTest):
        op_type = "slice"
        inputs = {"Input": x}
        attrs = {"axes": [0, 1], "starts": [1, 0], "ends": [3, 4]}
        outputs = {"Out": x[1:3, 0:4]}

    T().check_output()


def test_sum_multi():
    a = rng.randn(3, 3).astype(np.float32)
    b = rng.randn(3, 3).astype(np.float32)
    c = rng.randn(3, 3).astype(np.float32)

    class T(OpTest):
        op_type = "sum"
        inputs = {"X": [("sa", a), ("sb", b), ("sc", c)]}
        outputs = {"Out": a + b + c}

    T().check_output()
    T().check_grad(["sa", "sb", "sc"])


def test_reduce_ops():
    x = rng.randn(3, 4, 5).astype(np.float32)
    for op, fn in [("reduce_sum", np.sum), ("reduce_mean", np.mean),
                   ("reduce_max", np.max)]:
        class T(OpTest):
            op_type = op
            inputs = {"X": x}
            attrs = {"dim": [1], "keep_dim": False, "reduce_all": False}
            outputs = {"Out": fn(x, axis=1)}

        T().check_output(atol=1e-5)


def test_scale_op():
    x = rng.randn(3, 4).astype(np.float32)

    class T(OpTest):
        op_type = "scale"
        inputs = {"X": x}
        attrs = {"scale": 2.5}
        outputs = {"Out": 2.5 * x}

    T().check_output()
    T().check_grad(["X"])
