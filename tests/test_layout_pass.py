"""NHWC layout transpiler (ISSUE 5 tentpole lever a): the transformed
program — NHWC propagation, HWIO-pinned weights, boundary transposes,
fused conv stages — must match the NCHW baseline numerically (fp32
exactly-ish, AMP at bf16 tolerance), stay flag-gated, and pin the
parameters in storage, not just at op boundaries."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.core.flags import FLAGS
from paddle_tpu.core.scope import Scope
from paddle_tpu.models import resnet

OIHW_TO_HWIO = (2, 3, 1, 0)


def _run_resnet(data_format, fuse, params=None, steps=3, amp=False,
                depth=8):
    main, startup = fluid.Program(), fluid.Program()
    scope = Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                loss, (data, label), (acc,) = resnet.get_model(
                    data_set="cifar10", depth=depth,
                    data_format=data_format, fused_stages=fuse)
        if amp:
            fluid.transpiler.Float16Transpiler().transpile(main)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        if params is not None:
            for name, v in params.items():
                cur = np.asarray(scope.find_var(name))
                if v.shape != cur.shape and v.ndim == 4:
                    v = np.ascontiguousarray(
                        np.transpose(v, OIHW_TO_HWIO))
                assert v.shape == cur.shape, (name, v.shape, cur.shape)
                scope.set(name, v.astype(cur.dtype))
        snap = {n: np.asarray(scope.find_var(n))
                for n in scope.local_var_names()}
        rng = np.random.RandomState(0)
        feed = {"data": rng.rand(4, 3, 32, 32).astype(np.float32),
                "label": rng.randint(0, 10, (4, 1)).astype(np.int64)}
        losses = []
        for _ in range(steps):
            l, = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(l).ravel()[0]))
        post = {n: np.asarray(scope.find_var(n))
                for n in scope.local_var_names()}
    counts = {}
    for op in main.desc.blocks[0].ops:
        counts[op.type] = counts.get(op.type, 0) + 1
    return losses, snap, post, counts, main, startup


def test_nhwc_training_parity_fp32():
    """Same params => same per-step losses and same post-step params
    (grads + optimizer verified end to end), fused and unfused."""
    base, params, base_post, c0, _, _ = _run_resnet("NCHW", False)
    for fuse in (False, True):
        got, _, post, c1, _, _ = _run_resnet("NHWC", fuse,
                                             params=dict(params))
        np.testing.assert_allclose(base, got, rtol=2e-4, atol=2e-4)
        drift = []
        for n, v in base_post.items():
            w = post.get(n)
            if w is None or v.dtype.kind != "f":
                continue
            if v.shape != w.shape and v.ndim == 4:
                v = np.transpose(v, OIHW_TO_HWIO)
            if v.shape == w.shape:
                drift.append(float(np.abs(v - w).max()))
        assert drift and max(drift) < 5e-4, max(drift)
        if fuse:
            assert c1.get("conv2d", 0) == 0
            assert c1.get("batch_norm", 0) == 0
            assert c1["fused_conv2d_bn_act"] == c0["conv2d"]
        else:
            assert c1["conv2d"] == c0["conv2d"]


def test_nhwc_training_parity_amp():
    """AMP-tolerance parity (acceptance criterion): the bf16 NHWC+fused
    step tracks the bf16 NCHW step within bf16 noise."""
    base, params, _, _, _, _ = _run_resnet("NCHW", False, amp=True)
    got, _, _, _, _, _ = _run_resnet("NHWC", True, params=dict(params),
                                     amp=True)
    np.testing.assert_allclose(base, got, rtol=2e-2, atol=2e-2)


def test_boundary_transposes_are_minimal():
    """Exactly one transpose bridges the NCHW feed in and one bridges
    the image domain out to the fc flatten — NOT two per conv (the old
    FLAGS.conv_nhwc scheme XLA had to cancel)."""
    _, _, _, counts, _, _ = _run_resnet("NHWC", True, steps=1)
    assert counts.get("transpose", 0) == 2, counts.get("transpose")


def test_filters_pinned_hwio_in_storage():
    """The pin is at CREATION: main + startup VarDescs, the startup
    initializer's shape attr, and (when transpiling a live program) the
    scope value itself."""
    _, _, _, _, main, startup = _run_resnet("NHWC", True, steps=1)
    pinned = 0
    for op in main.desc.blocks[0].ops:
        if op.type != "fused_conv2d_bn_act":
            continue
        fname = op.input("Filter")[0]
        mvd = main.desc.blocks[0].vars[fname]
        co = main.desc.blocks[0].vars[op.output("Y")[0]].shape[3]
        assert mvd.shape[3] == co, (fname, mvd.shape)   # HWIO: O last
        svd = startup.desc.blocks[0].vars.get(fname)
        assert svd is None or tuple(svd.shape) == tuple(mvd.shape)
        for sop in startup.desc.blocks[0].ops:
            if fname in sop.output_arg_names() and sop.has_attr("shape"):
                assert tuple(sop.attr("shape")) == tuple(mvd.shape)
                pinned += 1
    assert pinned > 0
    # live-scope pinning: transpile AFTER startup ran
    main, startup = fluid.Program(), fluid.Program()
    scope = Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                x = fluid.layers.data(name="x", shape=[3, 8, 8],
                                      dtype="float32")
                y = fluid.layers.conv2d(input=x, num_filters=4,
                                        filter_size=3, padding=1,
                                        act=None, bias_attr=False)
                fname = [op.input("Filter")[0]
                         for op in main.desc.blocks[0].ops
                         if op.type == "conv2d"][0]
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        before = np.asarray(scope.find_var(fname))
        fluid.transpiler.LayoutTranspiler().transpile(
            main, startup_program=startup, scope=scope,
            data_format="NHWC", fuse_stages=False)
        after = np.asarray(scope.find_var(fname))
        assert after.shape == tuple(np.transpose(
            before, OIHW_TO_HWIO).shape)
        np.testing.assert_array_equal(
            after, np.transpose(before, OIHW_TO_HWIO))


def test_flag_gating_and_bisection_path():
    """FLAGS.conv_layout drives get_model's default; NCHW (default)
    leaves the program untouched, so the old path stays selectable."""
    assert FLAGS.conv_layout == "NCHW"      # repo default
    _, _, _, counts, _, _ = _run_resnet(None, None, steps=1)
    assert counts.get("fused_conv2d_bn_act", 0) == 0
    assert counts.get("transpose", 0) == 0
    FLAGS.conv_layout = "NHWC"
    try:
        _, _, _, counts, _, _ = _run_resnet(None, None, steps=1)
        assert counts.get("fused_conv2d_bn_act", 0) > 0
    finally:
        FLAGS.conv_layout = "NCHW"


def test_pin_bn_dtype_option():
    """BN affine params stored in the fused compute dtype (tentpole
    'BN params fused-dtype' knob): VarDesc dtype flips and training
    stays finite.  Experimental, off by default."""
    from paddle_tpu.core.types import DataType

    main, startup = fluid.Program(), fluid.Program()
    scope = Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                # is_test: no minimize inside get_model — the pass runs
                # pre-backward by contract
                loss, _, _ = resnet.get_model(
                    data_set="cifar10", depth=8, is_test=True,
                    data_format="NCHW", fused_stages=False)
    # NCHW leaves it alone; now transpile explicitly with the pin
    with fluid.scope_guard(scope):
        fluid.transpiler.LayoutTranspiler().transpile(
            main, startup_program=startup, scope=scope,
            data_format="NHWC", fuse_stages=True,
            pin_bn_dtype="bfloat16")
    pinned = [vd for vd in main.desc.blocks[0].vars.values()
              if vd.dtype == DataType.BF16]
    assert pinned, "no BN param pinned to bf16"
