"""Token-level generative serving (ISSUE 11): paged KV cache
accounting, decode-mode paged attention (XLA + interpret-mode Pallas
kernel parity), int8 weight-quantized matmul parity, batcher
token-granularity — a prefill admitted mid-decode produces
bit-identical tokens to the same request run solo — eviction/requeue
under block-pool exhaustion, and the serve_bench generate smoke."""
import json
import os
import subprocess
import sys
import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

from paddle_tpu.observability import metrics
from paddle_tpu.serving import (BlockPool, GenerativeEngine,
                                InferenceServer, tiny_lm)
from paddle_tpu.serving.batcher import TokenScheduler
from paddle_tpu.serving.engine import StepCache, pow2_bucket
from paddle_tpu.serving.generative import GenRequest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# one small config shared across the e2e tests (module-scoped engines
# would share KV pools across tests — fresh engines per test instead,
# sized so each compiles only the buckets it touches)
CFG_KW = dict(vocab=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
              block_size=8, max_blocks=8, max_batch=4)


def _prompts(seed, n, lo=3, hi=15):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, 64, size=rng.randint(lo, hi)).tolist()
            for _ in range(n)]


# ---------------------------------------------------------------- unit

def test_block_pool_accounting():
    used0 = metrics.gauge("serve_kv_blocks_used").value
    total0 = metrics.gauge("serve_kv_blocks_total").value
    fails0 = metrics.counter("serve_kv_alloc_failures_total").value
    pool = BlockPool(8, 16)
    assert pool.capacity == 7          # block 0 reserved
    assert metrics.gauge("serve_kv_blocks_total").value == total0 + 7
    a = pool.alloc(3)
    assert len(a) == 3 and 0 not in a
    assert pool.used_blocks == 3
    assert metrics.gauge("serve_kv_blocks_used").value == used0 + 3
    assert pool.alloc(5) is None       # only 4 left
    assert metrics.counter(
        "serve_kv_alloc_failures_total").value == fails0 + 1
    b = pool.alloc(4)
    assert pool.free_blocks == 0
    pool.free(a)
    pool.free(b)
    assert pool.used_blocks == 0
    assert metrics.gauge("serve_kv_blocks_used").value == used0
    with pytest.raises(ValueError):
        pool.free([0])                 # the reserved scratch block
    assert pool.blocks_for(1) == 1
    assert pool.blocks_for(16) == 1
    assert pool.blocks_for(17) == 2
    pool.close()
    assert metrics.gauge("serve_kv_blocks_total").value == total0


def test_lm_config_rejects_degenerate_block_size():
    from paddle_tpu.core.flags import FLAGS
    from paddle_tpu.serving import LMConfig

    for bad in (-8, 12):
        with pytest.raises(ValueError, match="power of"):
            LMConfig(64, 32, 2, 2, 64, block_size=bad)
    # block_size=0/None falls back to the flag; a degenerate FLAG
    # value must fail HERE with the named error, not as a
    # ZeroDivisionError deep inside the first generate
    prev = FLAGS.serve_kv_block_size
    FLAGS.serve_kv_block_size = 0
    try:
        with pytest.raises(ValueError, match="power of"):
            LMConfig(64, 32, 2, 2, 64)
    finally:
        FLAGS.serve_kv_block_size = prev


def test_pow2_bucket():
    assert pow2_bucket(1, 16) == 1
    assert pow2_bucket(3, 16) == 4
    assert pow2_bucket(16, 16) == 16
    assert pow2_bucket(17, 12) == 12   # cap joins the ladder


def test_step_cache_covering_and_sync_compile():
    compiled = []

    def build(key):
        compiled.append(key)
        return ("exe",) + key

    cache = StepCache(build, name="t")
    cache.warm([(2, 8), (4, 8)])
    assert cache.warm_keys == [(2, 8), (4, 8)]
    # exact hit
    key, exe = cache.pick((2, 8))
    assert key == (2, 8) and exe == ("exe", 2, 8)
    # covered miss: smallest covering answers, ideal compiles in bg
    key, exe = cache.pick((2, 4))
    assert key == (2, 8)
    deadline = time.time() + 30
    while (2, 4) not in cache.warm_keys and time.time() < deadline:
        time.sleep(0.01)
    assert (2, 4) in cache.warm_keys
    # nothing covers: synchronous compile
    key, exe = cache.pick((8, 8))
    assert key == (8, 8) and (8, 8) in cache.warm_keys
    cache.drain()


# ------------------------------------------------ paged attention

def _paged_ref(q, kp, vp, tables, lens):
    """Dense per-sequence reference: gather contiguous K/V, plain
    softmax attention over the first ``lens[b]`` positions."""
    B, H, D = q.shape
    outs = []
    for b in range(B):
        L = int(lens[b])
        kc = kp[tables[b]].reshape(-1, H, D)[:L]
        vc = vp[tables[b]].reshape(-1, H, D)[:L]
        s = np.einsum("hd,shd->hs", q[b], kc) / np.sqrt(D)
        p = np.exp(s - s.max(axis=1, keepdims=True))
        p /= p.sum(axis=1, keepdims=True)
        outs.append(np.einsum("hs,shd->hd", p, vc))
    return np.stack(outs)


def _paged_case(seed=0):
    rng = np.random.RandomState(seed)
    B, H, D, bs, NB, N = 3, 2, 16, 8, 4, 32
    q = rng.randn(B, H, D).astype(np.float32)
    kp = rng.randn(N, bs, H, D).astype(np.float32)
    vp = rng.randn(N, bs, H, D).astype(np.float32)
    tables = np.array([[1, 2, 3, 4], [5, 6, 7, 8], [9, 10, 11, 12]],
                      np.int32)
    lens = np.array([5, 17, 32], np.int32)
    return q, kp, vp, tables, lens


def test_paged_attention_xla_parity():
    from paddle_tpu.kernels.flash_attention import paged_attention

    q, kp, vp, tables, lens = _paged_case()
    out = np.asarray(paged_attention(q, kp, vp, tables, lens,
                                     force_xla=True))
    ref = _paged_ref(q, kp, vp, tables, lens)
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_paged_attention_kernel_interpret_parity():
    """The Pallas scalar-prefetch kernel (the TPU path) must answer the
    XLA gather path's floats — interpret mode runs the same kernel
    body the TPU compiles."""
    from paddle_tpu.kernels.flash_attention import paged_attention

    q, kp, vp, tables, lens = _paged_case(seed=4)
    ref = np.asarray(paged_attention(q, kp, vp, tables, lens,
                                     force_xla=True))
    out = np.asarray(paged_attention(q, kp, vp, tables, lens,
                                     interpret=True))
    np.testing.assert_allclose(out, ref, atol=1e-5)


# ------------------------------------------------ int8 matmul

def test_quantize_weight_roundtrip_bound():
    from paddle_tpu.kernels.matmul_fused import (dequantize_weight,
                                                 quantize_weight)

    rng = np.random.RandomState(2)
    w = (rng.randn(128, 64) * 0.1).astype(np.float32)
    q, s, chunk = quantize_weight(w, chunk=32)
    assert q.dtype == np.int8 and s.shape == (128 // 32, 64)
    wd = np.asarray(dequantize_weight(q, s, chunk))
    # per-chunk symmetric: error bounded by half a quantization step
    for c in range(128 // 32):
        seg = slice(c * 32, (c + 1) * 32)
        bound = s[c] * 0.5 + 1e-7
        assert (np.abs(wd[seg] - w[seg]) <= bound[None, :]).all()


def test_matmul_int8_kernel_matches_xla():
    from paddle_tpu.kernels.matmul_fused import (dequantize_weight,
                                                 matmul_epilogue_reference,
                                                 matmul_int8_dequant,
                                                 quantize_weight)

    rng = np.random.RandomState(3)
    x = rng.randn(8, 256).astype(np.float32)
    w = (rng.randn(256, 128) * 0.1).astype(np.float32)
    bias = rng.randn(128).astype(np.float32)
    q, s, chunk = quantize_weight(w, chunk=128)
    xla = np.asarray(matmul_int8_dequant(x, q, s, chunk, bias=bias,
                                         act="gelu", force_xla=True))
    kern = np.asarray(matmul_int8_dequant(x, q, s, chunk, bias=bias,
                                          act="gelu", interpret=True))
    np.testing.assert_allclose(kern, xla, atol=1e-5)
    # and both equal the reference over the dequantized weights
    ref, _ = matmul_epilogue_reference(
        x, np.asarray(dequantize_weight(q, s, chunk)), bias, None,
        "gelu")
    np.testing.assert_allclose(xla, np.asarray(ref), atol=1e-5)


# ------------------------------------------------ generate e2e

def test_generate_e2e_and_kv_drain():
    cfg, params = tiny_lm(7, **CFG_KW)
    with InferenceServer() as srv:
        eng = srv.load_generative("g", cfg, params, kv_blocks=32,
                                  warm=False)
        futs = [srv.generate("g", p, max_new_tokens=6)
                for p in _prompts(1, 5)]
        for f in futs:
            res = f.result(180)
            assert len(res["tokens"]) == 6
            assert res["ttft_ms"] is not None
            assert len(res["itl_ms"]) == 5
            assert all(0 <= t < cfg.vocab for t in res["tokens"])
        # every finished sequence returned its blocks
        assert eng.pool.used_blocks == 0


def test_generate_eos_stops_early():
    cfg, params = tiny_lm(7, **CFG_KW)
    with InferenceServer() as srv:
        srv.load_generative("g", cfg, params, kv_blocks=32, warm=False)
        ref = srv.generate("g", [1, 2, 3],
                           max_new_tokens=12).result(180)["tokens"]
        assert len(ref) == 12
        eos = ref[4]
        res = srv.generate("g", [1, 2, 3], max_new_tokens=12,
                           eos_id=eos).result(180)["tokens"]
        assert res == ref[:ref.index(eos) + 1], (res, ref)


def test_generate_validation():
    cfg, params = tiny_lm(7, **CFG_KW)
    with InferenceServer() as srv:
        srv.load_generative("g", cfg, params, kv_blocks=32, warm=False)
        with pytest.raises(ValueError):
            srv.generate("g", [], max_new_tokens=4)
        with pytest.raises(ValueError):
            srv.generate("g", [999], max_new_tokens=4)   # out of vocab
        with pytest.raises(ValueError):
            srv.generate("g", [1], max_new_tokens=0)
        # in-vocab tokens, so the LENGTH check itself must fire (an
        # out-of-vocab token here would mask a missing length guard)
        with pytest.raises(ValueError, match="max_seq"):
            srv.generate("g", [1] * 130, max_new_tokens=4)
        with pytest.raises(TypeError):
            srv.predict("g", {"x": np.zeros((1, 4), np.float32)})
        with pytest.raises(TypeError):
            srv.swap("g", "/nonexistent")   # predict-tier op
        with pytest.raises(KeyError):
            srv.generate("ghost", [1], max_new_tokens=1)


def test_predict_tenant_rejects_generate(tmp_path):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.core.scope import Scope

    d = str(tmp_path / "m")
    main, startup = fluid.Program(), fluid.Program()
    scope = Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                x = fluid.layers.data(name="x", shape=[4],
                                      dtype="float32")
                out = fluid.layers.fc(x, size=3)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        fluid.io.save_inference_model(d, ["x"], [out], exe,
                                      main_program=main)
    with InferenceServer(max_batch=2) as srv:
        srv.load("m", d)
        with pytest.raises(TypeError, match="generate"):
            srv.generate("m", [1, 2], max_new_tokens=2)


# ------------------------------------- token-granularity determinism

def test_prefill_admitted_mid_decode_bit_identical():
    """THE batcher token-granularity contract (ISSUE 11 satellite): a
    request admitted into a RUNNING decode batch must produce tokens
    bit-identical to the same request run solo — greedy decode is
    deterministic regardless of which (batch, block-count) buckets its
    iterations landed on or which neighbours shared them."""
    cfg, params = tiny_lm(11, **CFG_KW)
    prompts = _prompts(3, 4)
    with InferenceServer() as srv:
        srv.load_generative("g", cfg, params, kv_blocks=64, warm=False)
        solo = [srv.generate("g", p, max_new_tokens=16).result(180)
                ["tokens"] for p in prompts]
    metrics.zero_all()
    with InferenceServer() as srv:
        srv.load_generative("g", cfg, params, kv_blocks=64, warm=False)
        futs = []
        for p in prompts:
            futs.append(srv.generate("g", p, max_new_tokens=16))
            time.sleep(0.02)       # stagger: admission lands mid-decode
        batched = [f.result(180)["tokens"] for f in futs]
    # the runs genuinely overlapped: some decode iterations carried
    # more than one sequence
    rows = metrics.counter("serve_decode_rows_total").value
    steps = metrics.counter("serve_decode_steps_total").value
    assert rows > steps, "sequences never overlapped — test is vacuous"
    for i, (s, b) in enumerate(zip(solo, batched)):
        assert s == b, "request %d diverged: solo %r vs batched %r" % (
            i, s, b)


def test_pool_exhaustion_preempts_and_requeues():
    """Eviction/requeue (ISSUE 11 satellite): with a pool too small for
    all sequences, the scheduler preempts the youngest (counted),
    requeues it at the front, and the evicted request still completes
    with its solo tokens (greedy recompute determinism)."""
    cfg, params = tiny_lm(11, **CFG_KW)
    prompts = _prompts(9, 3, lo=6, hi=12)
    with InferenceServer() as srv:
        srv.load_generative("g", cfg, params, kv_blocks=64, warm=False)
        solo = [srv.generate("g", p, max_new_tokens=20).result(180)
                ["tokens"] for p in prompts]
    metrics.zero_all()
    with InferenceServer() as srv:
        # 7 usable blocks: 3 growing sequences (prompt 6-12 + 20 new
        # tokens -> up to 4 blocks each) cannot all fit
        srv.load_generative("g", cfg, params, kv_blocks=8, warm=False)
        futs = [srv.generate("g", p, max_new_tokens=20)
                for p in prompts]
        res = [f.result(300) for f in futs]
    preempts = metrics.counter("serve_kv_preemptions_total").value
    fails = metrics.counter("serve_kv_alloc_failures_total").value
    assert preempts > 0, "pool was never exhausted — test is vacuous"
    assert fails > 0
    assert any(r["preempted"] for r in res)
    for i, (s, r) in enumerate(zip(solo, res)):
        assert s == r["tokens"], "request %d diverged after preemption" % i


def test_lone_sequence_too_big_for_pool_fails_cleanly():
    cfg, params = tiny_lm(11, **CFG_KW)
    with InferenceServer() as srv:
        # 2 usable blocks = 16 positions; prompt 10 + 16 new > 16
        srv.load_generative("g", cfg, params, kv_blocks=3, warm=False)
        fut = srv.generate("g", list(range(10)), max_new_tokens=16)
        with pytest.raises(RuntimeError, match="pool too small"):
            fut.result(180)


def test_engine_ctor_failure_retires_pool_gauges():
    """A GenerativeEngine that fails mid-construction (bad params, a
    warm-compile error) must retire its just-registered BlockPool from
    the process gauges — review finding: every failed load left
    phantom serve_kv_blocks capacity behind."""
    total0 = metrics.gauge("serve_kv_blocks_total").value
    cfg, params = tiny_lm(7, **CFG_KW)
    bad = dict(params)
    del bad["lm_head"]
    with pytest.raises(KeyError):
        GenerativeEngine(cfg, bad, kv_blocks=16, warm=True)
    assert metrics.gauge("serve_kv_blocks_total").value == total0


def test_prompt_wider_than_whole_pool_rejected_at_generate():
    """A prompt that can NEVER be admitted (needs more blocks than the
    pool holds) must be rejected synchronously at generate() — left in
    the queue it would spin the decode loop forever AND, since
    admission is FIFO, block every request behind it."""
    cfg, params = tiny_lm(11, **CFG_KW)
    with InferenceServer() as srv:
        srv.load_generative("g", cfg, params, kv_blocks=3, warm=False)
        with pytest.raises(ValueError, match="KV blocks"):
            srv.generate("g", [1] * 20, max_new_tokens=2)  # needs 3 > 2


def test_prefill_failure_fails_only_that_request():
    """A prefill that raises during admission must fail THAT request's
    future, return its just-allocated blocks to the pool, and leave
    the loop serving later traffic (review finding: the blocks leaked
    and the future hung)."""
    cfg, params = tiny_lm(11, **CFG_KW)
    with InferenceServer() as srv:
        eng = srv.load_generative("g", cfg, params, kv_blocks=32,
                                  warm=False)
        orig = eng.prefill

        def bomb(seq):
            raise RuntimeError("synthetic prefill fault")

        eng.prefill = bomb
        fut = srv.generate("g", [1, 2, 3], max_new_tokens=4)
        with pytest.raises(RuntimeError, match="synthetic"):
            fut.result(60)
        eng.prefill = orig
        assert eng.pool.used_blocks == 0, "admission blocks leaked"
        res = srv.generate("g", [1, 2, 3], max_new_tokens=4).result(180)
        assert len(res["tokens"]) == 4


# ------------------------------------------------ speculative decoding

def _spec_pair(seed=13):
    """Target + 1-layer draft sharing vocab/paging geometry (the
    draft-contract _init_draft enforces)."""
    cfg, params = tiny_lm(seed, **CFG_KW)
    dcfg, dparams = tiny_lm(seed + 1, **dict(CFG_KW, n_layers=1))
    return cfg, params, dcfg, dparams


def test_spec_accept_rate_accounting():
    """The serve_spec_* counters must add up against the emission
    contract: per (round, sequence) the engine proposes k, accepts
    m <= k, emits m+1 — so proposed == k * verify-rows, accepted stays
    within proposed, and delivered tokens land between the exact
    emission sum and that sum minus the worst-case final-round
    overshoot trim (k per request)."""
    k = 3
    cfg, params, dcfg, dparams = _spec_pair()
    prompts = _prompts(21, 3, lo=4, hi=10)
    metrics.zero_all()
    with InferenceServer() as srv:
        srv.load_generative("g", cfg, params, kv_blocks=64, warm=False,
                            spec_k=k, draft=(dcfg, dparams))
        res = [srv.generate("g", p, max_new_tokens=12).result(300)
               for p in prompts]
    rounds = metrics.counter("serve_spec_rounds_total").value
    proposed = metrics.counter("serve_spec_proposed_total").value
    accepted = metrics.counter("serve_spec_accepted_total").value
    rows = metrics.counter("serve_decode_rows_total").value
    prefills = metrics.counter("serve_prefills_total").value
    assert rounds > 0, "spec engine never ran a speculative round"
    assert proposed == k * rows
    assert 0 <= accepted <= proposed
    delivered = sum(len(r["tokens"]) for r in res)
    emitted = prefills + accepted + rows      # 1 + sum(m_i + 1)
    assert delivered <= emitted <= delivered + k * len(prompts)
    # draft/verify wall-time observability rides the same gate
    assert metrics.counter("serve_spec_verify_us_total").value > 0


def test_spec_k0_degenerate_equals_plain():
    """spec_k=0 IS plain decode: identical tokens, no draft engine,
    and the serve_spec_* counters never move."""
    cfg, params, _, _ = _spec_pair()
    prompts = _prompts(23, 2, lo=4, hi=9)
    with InferenceServer() as srv:
        srv.load_generative("g", cfg, params, kv_blocks=64, warm=False)
        base = [srv.generate("g", p, max_new_tokens=10).result(300)
                ["tokens"] for p in prompts]
    metrics.zero_all()
    with InferenceServer() as srv:
        eng = srv.load_generative("g", cfg, params, kv_blocks=64,
                                  warm=False, spec_k=0)
        assert eng.draft is None
        k0 = [srv.generate("g", p, max_new_tokens=10).result(300)
              ["tokens"] for p in prompts]
    assert k0 == base
    assert metrics.counter("serve_spec_rounds_total").value == 0
    assert metrics.counter("serve_spec_proposed_total").value == 0


def test_spec_certified_greedy_parity():
    """THE spec-decode correctness contract on the bench LM: the
    speculative token stream is bit-identical to plain greedy decode
    and the per-round acceptance accounting closes exactly —
    serve_bench documents the same certificate in SERVE_BENCH.json."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import serve_bench
    finally:
        sys.path.pop(0)
    rec = serve_bench._gen_spec_parity(steps=24, k=3, fat=512)
    assert rec["identical"], rec
    assert rec["accounting_ok"], rec
    assert rec["certified"], rec
    assert rec["rounds"] > 0
    assert 0.0 <= rec["accept_rate"] <= 1.0


def test_spec_draft_target_bucket_ladder_coexistence():
    """Draft and target run separate StepCache ladders (propose/verify
    vs decode) inside one engine: staggered admissions through the
    spec engine must stay bit-identical to plain solo decode, with
    both ladders demonstrably compiled-through."""
    cfg, params, dcfg, dparams = _spec_pair()
    prompts = _prompts(29, 3, lo=4, hi=10)
    with InferenceServer() as srv:
        srv.load_generative("g", cfg, params, kv_blocks=64, warm=False)
        solo = [srv.generate("g", p, max_new_tokens=14).result(300)
                ["tokens"] for p in prompts]
    metrics.zero_all()
    with InferenceServer() as srv:
        eng = srv.load_generative("g", cfg, params, kv_blocks=64,
                                  warm=False, spec_k=3,
                                  draft=(dcfg, dparams))
        futs = []
        for p in prompts:
            futs.append(srv.generate("g", p, max_new_tokens=14))
            time.sleep(0.02)   # stagger: admissions land mid-round
        batched = [f.result(300)["tokens"] for f in futs]
        assert eng._verify.warm_keys, "target verify ladder never used"
        assert eng.draft._propose.warm_keys, \
            "draft propose ladder never used"
    for i, (s, b) in enumerate(zip(solo, batched)):
        assert s == b, "request %d diverged under spec decode: " \
            "solo %r vs spec %r" % (i, s, b)


# ------------------------------------------------ int8 serving parity

def test_int8_decode_greedy_parity():
    """int8 weight-quantized decode must be token-exact with fp32 on
    the bench model over 64 greedy steps, with the margin certificate:
    every step's fp32 top-2 logit margin exceeds the worst observed
    logit delta (serve_bench documents the same numbers in
    SERVE_BENCH.json)."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import serve_bench
    finally:
        sys.path.pop(0)
    rec = serve_bench._gen_int8_parity(max_batch=4, kv_blocks=32,
                                       steps=64)
    assert rec["parity_ok"], rec
    assert rec["certified"], rec
    assert rec["min_top2_margin"] > rec["max_logit_delta"]


# ------------------------------------------------------------ bench

def test_serve_bench_quick_generate_smoke():
    """tools/serve_bench.py --quick --mode generate completes on the
    CPU backend and reports the generate artifact schema — tier-1
    catches a wedged decode loop, not just schema drift (ISSUE 11
    satellite; the predict smoke lives in test_serving.py)."""
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", SVB_MAX_BATCH="4",
               SVB_GEN_KV_BLOCKS="64", SVB_GEN_MAX_NEW="8",
               SVB_GEN_PARITY_STEPS="16")   # the full 64-step parity
    # guarantee lives in test_int8_decode_greedy_parity (in-process)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve_bench.py"),
         "--quick", "--mode", "generate", "--seconds", "0.8"],
        capture_output=True, text=True, timeout=560, env=env, cwd=REPO)
    assert proc.returncode in (0, 1), proc.stderr[-2000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "serve_bench"
    assert rec["mode"] == "generate"
    gen = rec["generate"]
    for key in ("floor", "poisson", "occupancy", "kv", "int8",
                "load_warm_s", "speedup_tokens_vs_floor"):
        assert key in gen, key
    assert gen["poisson"]["completed"] == gen["poisson"]["n_requests"]
    assert gen["poisson"]["tokens"] > 0
    assert gen["drop"]["zero_dropped"] is True
    # the hard guarantee holds even in the smoke: int8 decode is
    # token-exact with fp32 over the smoke's parity horizon
    assert gen["int8"]["parity_ok"] is True
    assert gen["kv"]["blocks_used_after_drain"] == 0


@pytest.mark.parametrize("feature_env,check", [
    ({"SVB_GEN_PREFIX_CACHE": "1"}, "prefix"),
    ({"SVB_GEN_SPEC_K": "2"}, "spec"),
])
def test_serve_bench_quick_generate_feature_smoke(feature_env, check):
    """The generate smoke parametrized over the ISSUE 19 features: the
    SAME Poisson trace with the prefix cache on / a draft speculating
    must complete with zero drops, a drained pool, AND the feature
    demonstrably engaged (hits > 0 / rounds > 0 in the artifact's
    features block) — not just schema presence."""
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", SVB_MAX_BATCH="4",
               SVB_GEN_KV_BLOCKS="64", SVB_GEN_MAX_NEW="8",
               SVB_GEN_PARITY_STEPS="16")
    env.update(feature_env)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve_bench.py"),
         "--quick", "--mode", "generate", "--seconds", "0.8"],
        capture_output=True, text=True, timeout=560, env=env, cwd=REPO)
    assert proc.returncode in (0, 1), proc.stderr[-2000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    gen = rec["generate"]
    feats = gen["features"]
    if check == "prefix":
        assert feats["prefix_cache"] is True
        assert feats["prefix_hits"] > 0, feats
        assert feats["prefix_tokens_cached"] > 0, feats
    else:
        assert feats["spec_k"] == 2
        assert feats["spec_rounds"] > 0, feats
        assert 0.0 <= feats["spec_accept_rate"] <= 1.0
    assert gen["poisson"]["completed"] == gen["poisson"]["n_requests"]
    assert gen["drop"]["zero_dropped"] is True
    assert gen["kv"]["blocks_used_after_drain"] == 0
