"""Data pipeline: reader decorators (reference reader/decorator.py +
tests/decorator_test.py), recordio writer/scanner (reference
paddle/fluid/recordio/*_test.cc), dataset adapters, and the
double-buffered DeviceLoader (reference operators/reader/)."""
import os
import struct
import zlib

import numpy as np
import pytest

import paddle_tpu.reader as reader
from paddle_tpu import dataset, recordio


# --------------------------- decorators ---------------------------------

def _counter(n):
    def r():
        for i in range(n):
            yield i

    return r


def test_map_readers():
    got = list(reader.map_readers(lambda a, b: a + b,
                                  _counter(4), _counter(4))())
    assert got == [0, 2, 4, 6]


def test_shuffle_is_permutation():
    got = list(reader.shuffle(_counter(20), 7)())
    assert sorted(got) == list(range(20))


def test_chain_and_firstn():
    got = list(reader.firstn(reader.chain(_counter(3), _counter(3)), 5)())
    assert got == [0, 1, 2, 0, 1]


def test_compose_flattens_and_checks_alignment():
    def pairs():
        for i in range(3):
            yield (i, i * 10)

    got = list(reader.compose(_counter(3), lambda: pairs())())
    assert got == [(0, 0, 0), (1, 1, 10), (2, 2, 20)]
    with pytest.raises(reader.ComposeNotAligned):
        list(reader.compose(_counter(3), _counter(5))())
    # alignment off: stops at the shortest
    got = list(reader.compose(_counter(3), _counter(5),
                              check_alignment=False)())
    assert len(got) == 3


def test_buffered_and_cache():
    assert list(reader.buffered(_counter(10), 3)()) == list(range(10))
    calls = []

    def tracked():
        calls.append(1)
        for i in range(4):
            yield i

    c = reader.cache(tracked)
    assert list(c()) == list(range(4))
    assert list(c()) == list(range(4))
    assert len(calls) == 1  # second epoch replayed from memory


@pytest.mark.parametrize("order", [False, True])
def test_xmap_readers(order):
    got = list(reader.xmap_readers(lambda x: x * x, _counter(20), 4, 8,
                                   order=order)())
    if order:
        assert got == [i * i for i in range(20)]
    else:
        assert sorted(got) == sorted(i * i for i in range(20))


def test_batch():
    got = list(reader.batch(_counter(7), 3)())
    assert got == [[0, 1, 2], [3, 4, 5]]
    got = list(reader.batch(_counter(7), 3, drop_last=False)())
    assert got[-1] == [6]


# ---------------------------- recordio ----------------------------------

RECS = [b"a", b"", b"z" * 4096, bytes(range(256))]


@pytest.mark.parametrize("wn,rn", [(True, True), (True, False),
                                   (False, True), (False, False)])
def test_recordio_roundtrip_cross_impl(tmp_path, wn, rn):
    """C++ and Python codecs produce/consume the same on-disk format."""
    if (wn or rn) and not recordio.native_available():
        pytest.skip("no native toolchain")
    p = str(tmp_path / "r.rio")
    recordio.write_records(p, RECS, use_native=wn)
    assert list(recordio.read_records(p, use_native=rn)) == RECS


def test_recordio_skips_corrupt_chunk(tmp_path):
    p = str(tmp_path / "c.rio")
    recordio.write_records(p, RECS, use_native=False)
    raw = struct.pack("<I", 2) + b"ok"
    stored = zlib.compress(raw)
    hdr = struct.Struct("<6I")
    with open(p, "ab") as f:
        f.write(hdr.pack(recordio.MAGIC, recordio.ZLIB, 1, len(raw),
                         len(stored), 0xBAD))   # wrong crc -> skipped
        f.write(stored)
        f.write(hdr.pack(recordio.MAGIC, recordio.ZLIB, 1, len(raw),
                         len(stored), zlib.crc32(stored)))
        f.write(stored)
    for native in ([True, False] if recordio.native_available()
                   else [False]):
        assert list(recordio.read_records(p, use_native=native)) == \
            RECS + [b"ok"]


def test_recordio_reader_creator(tmp_path):
    p = str(tmp_path / "n.rio")
    arrs = [np.arange(4, dtype=np.float32) * i for i in range(5)]
    recordio.write_records(p, [a.tobytes() for a in arrs])
    got = list(reader.creator.recordio(
        p, deserializer=lambda b: np.frombuffer(b, np.float32))())
    for g, a in zip(got, arrs):
        np.testing.assert_array_equal(g, a)


# ---------------------------- datasets ----------------------------------

def test_mnist_shapes():
    it = dataset.mnist.train()()
    img, lab = next(it)
    assert img.shape == (784,) and img.dtype == np.float32
    assert -1.0 <= img.min() and img.max() <= 1.0
    assert isinstance(lab, int) and 0 <= lab < 10


def test_cifar_shapes():
    img, lab = next(dataset.cifar.train10()())
    assert img.shape == (3072,) and img.dtype == np.float32
    assert 0 <= lab < 10
    img, lab = next(dataset.cifar.train100()())
    assert 0 <= lab < 100


def test_uci_housing_learnable():
    xs, ys = zip(*list(dataset.uci_housing.train()()))
    x, y = np.stack(xs), np.stack(ys)
    assert x.shape[1] == 13
    # linear regression closed form fits it well (synthetic is linear;
    # the real dataset also has strong linear signal)
    w, *_ = np.linalg.lstsq(
        np.concatenate([x, np.ones((len(x), 1), np.float32)], 1), y,
        rcond=None)
    pred = np.concatenate([x, np.ones((len(x), 1), np.float32)], 1) @ w
    rel = np.mean((pred - y) ** 2) / max(np.var(y), 1e-6)
    assert rel < 0.5


def test_dataset_split_and_cluster_reader(tmp_path):
    pat = str(tmp_path / "part-%05d.pickle")
    n = dataset.common.split(_counter(10), 3, suffix=pat)
    assert n == 4
    shard0 = list(dataset.common.cluster_files_reader(
        str(tmp_path / "part-*.pickle"), 2, 0)())
    shard1 = list(dataset.common.cluster_files_reader(
        str(tmp_path / "part-*.pickle"), 2, 1)())
    assert sorted(shard0 + shard1) == list(range(10))
    assert shard0 and shard1


def test_device_loader_early_break_stops_producer():
    """Abandoning the iterator mid-epoch must release the producer
    thread (no leaked thread pinning device-staged batches)."""
    import threading
    import time

    import paddle_tpu.fluid as fluid

    def slow_reader():
        for i in range(100):
            yield [(np.zeros(4, np.float32),) for _ in range(2)]

    before = threading.active_count()
    loader = reader.DeviceLoader(slow_reader, ["x"], fluid.CPUPlace(),
                                 capacity=2)
    it = iter(loader)
    next(it)
    it.close()  # generator finally -> stop event
    deadline = time.time() + 5
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= before


# -------------------------- device loader -------------------------------

def test_device_loader_feeds_training():
    import paddle_tpu.fluid as fluid

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                img = fluid.layers.data(name="img", shape=[784],
                                        dtype="float32")
                lab = fluid.layers.data(name="label", shape=[1],
                                        dtype="int64")
                pred = fluid.layers.fc(img, size=10, act="softmax")
                loss = fluid.layers.mean(
                    fluid.layers.cross_entropy(pred, lab))
                fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)

        r = reader.batch(
            reader.shuffle(
                reader.map_readers(
                    lambda s: (s[0], np.asarray([s[1]], np.int64)),
                    dataset.mnist.train()),
                buf_size=256),
            batch_size=64)
        loader = reader.DeviceLoader(r, ["img", "label"],
                                     fluid.CPUPlace(), capacity=2)
        losses = []
        for feed in loader:
            l, = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(np.ravel(l)[0]))
        assert len(losses) == 2048 // 64
        # learnable synthetic blobs: one epoch must cut loss in half
        assert np.mean(losses[-4:]) < losses[0] * 0.5


def test_pipe_reader_plain_and_gzip(tmp_path):
    import gzip
    import os

    from paddle_tpu.reader import PipeReader

    pr = PipeReader("echo alpha beta")
    assert list(pr.get_line()) == ["alpha beta"]

    path = os.path.join(str(tmp_path), "x.gz")
    with gzip.open(path, "wb") as f:
        f.write(b"l1\nl2\nl3\n")
    pr = PipeReader("cat %s" % path, file_type="gzip")
    assert list(pr.get_line()) == ["l1", "l2", "l3"]


# ----------------------- DeviceDatasetCache -----------------------------

def _labeled_reader(n, dim=4):
    def r():
        for i in range(n):
            yield (np.full((dim,), i, np.float32),
                   np.asarray([i], np.int64))

    return r


def test_device_dataset_cache_epoch_coverage_and_reshuffle():
    import paddle_tpu.fluid as fluid

    n, bs = 20, 5
    cache = reader.DeviceDatasetCache(
        _labeled_reader(n), ["x", "y"], fluid.CPUPlace(), bs, seed=7)

    def epoch_ids():
        ids = []
        batches = 0
        for d in cache:
            assert d["x"].shape == (bs, 4)
            assert d["y"].shape == (bs, 1)
            # field alignment: the label matches the image fill value
            assert np.array_equal(np.asarray(d["x"])[:, 0],
                                  np.asarray(d["y"])[:, 0])
            ids.extend(np.asarray(d["y"])[:, 0].tolist())
            batches += 1
        assert batches == n // bs
        return ids

    e0, e1 = epoch_ids(), epoch_ids()
    # every sample exactly once per epoch, different order across epochs
    assert sorted(e0) == list(range(n))
    assert sorted(e1) == list(range(n))
    assert e0 != e1


def test_device_dataset_cache_budget_and_small_dataset():
    import paddle_tpu.fluid as fluid

    with pytest.raises(ValueError, match="max_bytes"):
        reader.DeviceDatasetCache(_labeled_reader(8), ["x", "y"],
                                  fluid.CPUPlace(), 2, max_bytes=16)
    with pytest.raises(ValueError, match="smaller than one batch"):
        reader.DeviceDatasetCache(_labeled_reader(3), ["x", "y"],
                                  fluid.CPUPlace(), 4)


def test_resnet_uint8_input_matches_float(tmp_path):
    """get_model(input_dtype='uint8') — device-side cast+scale gives the
    same forward loss as feeding img/255 as float32."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.models import resnet

    rng = np.random.RandomState(0)
    u8 = rng.randint(0, 256, (2, 3, 32, 32)).astype(np.uint8)
    lab = rng.randint(0, 10, (2, 1)).astype(np.int64)
    losses = {}
    for dt in ("uint8", "float32"):
        main, startup = fluid.Program(), fluid.Program()
        scope = Scope()
        with fluid.scope_guard(scope):
            with fluid.program_guard(main, startup):
                with fluid.unique_name.guard():
                    avg_cost, (data, label), _ = resnet.get_model(
                        data_set="cifar10", input_dtype=dt, is_test=True)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            feed = {data.name: u8 if dt == "uint8"
                    else (u8.astype(np.float32) / 255.0),
                    label.name: lab}
            loss, = exe.run(main, feed=feed, fetch_list=[avg_cost])
        losses[dt] = float(np.asarray(loss).ravel()[0])
    assert np.isfinite(losses["uint8"])
    assert abs(losses["uint8"] - losses["float32"]) < 1e-4


def test_device_loader_hides_producer_latency():
    """The double-buffer contract (reference
    create_double_buffer_reader_op.cc): reader latency (disk/network
    waits) hides behind compute — the streamed loop costs
    ~max(compute, produce), not the sum.  Pure H2D overlap is a
    hardware property the CPU backend cannot exhibit (its "transfer"
    is a memcpy on the same cores as compute; work is conserved) —
    bench.py's stream_overlap_ratio field reports that number on the
    real chip.  Reader latency here is a wall-clock sleep, so the
    assertion is load-independent."""
    import time

    import jax
    import jax.numpy as jnp

    import paddle_tpu as pt
    import paddle_tpu.fluid as fluid

    place = fluid.CPUPlace()
    dev = place.jax_device()
    n_batches = 6
    field = np.random.RandomState(0).rand(1 << 20).astype(np.float32)
    prebuilt = [field + np.float32(i) for i in range(n_batches)]

    w = jax.device_put(np.random.RandomState(1).rand(1024, 1024)
                       .astype(np.float32), dev)

    @jax.jit
    def compute(x, w):
        acc = w
        for _ in range(8):
            acc = jnp.tanh(acc @ w)
        return acc.sum() + x.reshape(-1)[0]

    compute(jax.device_put(field[None], dev), w).block_until_ready()

    # per-batch compute time on THIS rig: the reader delay is sized to
    # match it, so the overlappable quantity (min(compute, delay) per
    # steady-state batch) is a fixed fraction of the loop whatever the
    # machine's speed — a hard-coded delay made the bound unsatisfiable
    # on rigs whose compute runs faster than the delay (the streamed
    # loop is then reader-bound at ~n*delay, which can exceed
    # t_naive - hidden for ANY overlap quality)
    t0 = time.time()
    for i in range(n_batches):
        compute(jax.device_put(prebuilt[i][None], dev),
                w).block_until_ready()
    t_comp = (time.time() - t0) / n_batches
    delay = max(0.03, t_comp)

    def reader():
        for b in prebuilt:
            time.sleep(delay)
            yield [(b,)]

    # naive serial loop: read -> stage -> compute, one at a time
    t0 = time.time()
    for samples in reader():
        x = jax.device_put(np.stack([samples[0][0]])[None], dev)
        r = compute(x, w)
        r.block_until_ready()
    t_naive = time.time() - t0

    # double-buffered: reader sleeps overlap the running compute
    loader = pt.reader.DeviceLoader(reader, ["x"], place, capacity=3)
    t0 = time.time()
    for feed in loader:
        r = compute(feed["x"], w)
        r.block_until_ready()
    t_stream = time.time() - t0

    # the loader must hide most of the hideable time.  Hideable =
    # min(compute, delay) per steady-state batch; allow keeping one
    # pipeline-fill delay plus 1.5 more for scheduler noise.
    hideable = min(t_comp, delay)
    budget = t_naive - (n_batches - 2.5) * hideable
    assert t_stream < budget, (
        "reader latency not hidden: naive %.3fs, streamed %.3fs, "
        "budget %.3fs (compute %.3fs, delay %.3fs x %d batches)"
        % (t_naive, t_stream, budget, t_comp, delay, n_batches))
