"""Scale observatory (ISSUE 12): resource-ledger accounting, the
bounded collector, collapse forensics, knee detection, the incremental
barrier quorum, the bounded reply/replay caches, and the scale_bench
--quick smoke."""
import glob
import json
import os
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np
import pytest

from paddle_tpu.core.flags import FLAGS
from paddle_tpu.core.scope import Scope
from paddle_tpu.distributed.rpc import (RPCClient, VariableServer,
                                        _enc_msg, _enc_tensor,
                                        _pack_round_sender)
from paddle_tpu.observability import flight, ledger
from paddle_tpu.observability import metrics as obs_metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

A, B = 0x111111, 0x222222


@pytest.fixture(autouse=True)
def _clean():
    prev = (FLAGS.pserver_reply_cache_mb, FLAGS.rpc_replay_cache_mb,
            FLAGS.barrier_rescan, FLAGS.ledger_watch,
            FLAGS.telemetry_dump_dir, FLAGS.dist_staleness,
            FLAGS.ledger_ring)
    ledger.reset()
    yield
    (FLAGS.pserver_reply_cache_mb, FLAGS.rpc_replay_cache_mb,
     FLAGS.barrier_rescan, FLAGS.ledger_watch,
     FLAGS.telemetry_dump_dir, FLAGS.dist_staleness,
     FLAGS.ledger_ring) = prev
    ledger.reset()
    RPCClient.reset()


def _grad(sender, round_, seq, n=16, fill=1.0):
    return _enc_tensor("g1", np.full(n, fill, np.float32),
                       _pack_round_sender(round_, sender, seq))


def _barrier(sender, round_):
    return _enc_msg("t%x" % sender, _pack_round_sender(round_, sender))


def _server(fanin=2, staleness=0, grads=("g1",)):
    scope = Scope()
    return VariableServer(scope, {g: i for i, g in enumerate(grads)},
                          lambda b: None, fanin=fanin,
                          staleness=staleness)


# ---------------------------------------------------------------------------
# ledger accounting on the pserver
# ---------------------------------------------------------------------------

def test_pending_ledger_exact_under_injected_growth():
    """k=2 lets one sender run ahead without the peer: every pending
    byte/entry and the backlog/age resources must be EXACT."""
    srv = _server(fanin=2, staleness=2)
    nb = np.zeros(16, np.float32).nbytes
    for r in range(3):
        srv._send_variable(_grad(A, r, seq=r + 1))
    probe = srv._ledger_probe()
    assert probe["pserver_pending_grad_bytes"] == 3 * nb
    assert probe["pserver_pending_grad_entries"] == 3
    # a same-(round, sender) replay overwrites — no double count
    srv._send_variable(_grad(A, 1, seq=9))
    probe = srv._ledger_probe()
    assert probe["pserver_pending_grad_bytes"] == 3 * nb
    assert probe["pserver_pending_grad_entries"] == 3
    # the peer contributes its own entries
    srv._send_variable(_grad(B, 0, seq=1))
    probe = srv._ledger_probe()
    assert probe["pserver_pending_grad_bytes"] == 4 * nb
    assert probe["pserver_pending_grad_entries"] == 4
    assert probe["pserver_oldest_pending_age_s"] >= 0.0
    # barriers for rounds 0..1 ack instantly at k=2 (durable > r-2)
    # and no apply worker is running: backlog grows, quorum counts A
    srv._send_barrier(_barrier(A, 0))
    srv._send_barrier(_barrier(A, 1))
    probe = srv._ledger_probe()
    assert probe["pserver_apply_backlog_rounds"] == 2
    assert probe["pserver_barrier_set"] == 1
    assert probe["pserver_known_senders"] == 2


def test_pending_ledger_drains_to_zero_after_apply():
    srv = _server(fanin=2)
    srv._send_variable(_grad(A, 0, seq=1))
    srv._send_variable(_grad(B, 0, seq=1))
    t = threading.Thread(target=srv._send_barrier,
                         args=(_barrier(A, 0),))
    t.start()
    srv._send_barrier(_barrier(B, 0))
    t.join(timeout=10)
    assert not t.is_alive()
    probe = srv._ledger_probe()
    assert probe["pserver_pending_grad_bytes"] == 0
    assert probe["pserver_pending_grad_entries"] == 0
    assert probe["pserver_apply_backlog_rounds"] == 0
    assert srv._round_seen == {} and srv._round_entries == {}


def test_reply_cache_bytes_and_lru_eviction():
    obs_metrics.zero_all()
    srv = _server(fanin=1, grads=("g1", "g2", "g3"))
    for name in ("p1", "p2", "p3"):
        srv.scope.set(name, np.zeros(256, np.float32))
    with srv._cv:
        for name in ("p1", "p2"):
            srv._materialize_locked(name)
        exact = srv._reply_bytes
        assert exact == sum(e[2] for e in srv._reply_cache.values())
        assert set(srv._reply_cache) == {"p1", "p2"}
        # serve p1 again: LRU order now p2, p1 — then cap to ~1 entry
        srv._materialize_locked("p1")
        FLAGS.pserver_reply_cache_mb = (exact / 2) / 1e6
        srv._materialize_locked("p3")
    ev = obs_metrics.snapshot()[
        "pserver_reply_cache_evictions_total"]["value"]
    assert ev >= 2
    # the entry just served always survives; the LRU ones went first
    assert "p3" in srv._reply_cache
    assert srv._reply_bytes == sum(e[2]
                                   for e in srv._reply_cache.values())


def test_replay_cache_cap_evicts_oldest_rounds_not_current():
    obs_metrics.zero_all()
    RPCClient.reset()
    cli = RPCClient.instance()
    FLAGS.dist_staleness = 8          # retain many rounds
    arr = np.zeros(1024, np.float32)  # 4 KB
    for r in range(4):
        cli.step = r
        cli._record_send("ep0", "g1", arr)
    assert cli._replay_bytes == 4 * arr.nbytes
    # cap to ~2 rounds: the two OLDEST evict, the current survives
    FLAGS.rpc_replay_cache_mb = (2 * arr.nbytes) / 1e6
    cli.step = 4
    cli._record_send("ep0", "g1", arr)
    rounds = sorted(cli._round_cache["ep0"])
    assert 4 in rounds and 0 not in rounds and 1 not in rounds
    ev = obs_metrics.snapshot()[
        "rpc_replay_cache_evictions_total"]["value"]
    assert ev >= 2
    assert cli._replay_bytes == sum(
        c["bytes"] for eph in cli._round_cache.values()
        for c in eph.values())
    probe = cli._ledger_probe()
    assert probe["rpc_replay_cache_bytes"] == cli._replay_bytes
    assert probe["rpc_replay_cache_rounds"] == len(rounds)


# ---------------------------------------------------------------------------
# incremental barrier quorum
# ---------------------------------------------------------------------------

def test_quorum_incremental_matches_full_scan():
    srv = _server(fanin=3, staleness=2)

    def parity():
        with srv._cv:
            scan = srv._barrier_scan_locked()
        assert srv._quorum + srv._legacy_barriers == scan

    parity()
    srv._send_barrier(_barrier(A, 0))
    parity()
    srv._send_barrier(_barrier(A, 1))   # same sender, higher round
    parity()
    assert srv._quorum == 1
    srv._send_barrier(_barrier(B, 0))
    parity()
    assert srv._quorum == 2
    # completion excludes the sender from the quorum
    srv._send_complete(_enc_msg("tA", _pack_round_sender(2, A)))
    parity()
    assert srv._quorum == 1
    # the legacy rescan flag answers the same number
    FLAGS.barrier_rescan = True
    with srv._cv:
        legacy = srv._barrier_count()
    FLAGS.barrier_rescan = False
    with srv._cv:
        assert srv._barrier_count() == legacy


def test_quorum_scan_counter_separates_legacy_from_incremental():
    """The before/after evidence channel: per-ack work is O(1) on the
    incremental path and O(senders) under FLAGS_barrier_rescan."""
    obs_metrics.zero_all()
    srv = _server(fanin=64, staleness=4)
    for i in range(32):
        srv._send_barrier(_barrier(0x300000 + i, 0))
    inc_ops = obs_metrics.snapshot()[
        "pserver_quorum_scan_ops_total"]["value"]
    # one +1 per ack (no apply happened): far below senders^2
    assert inc_ops <= 64
    obs_metrics.zero_all()
    FLAGS.barrier_rescan = True
    for i in range(32):
        with srv._cv:
            srv._barrier_count()
    rescan_ops = obs_metrics.snapshot()[
        "pserver_quorum_scan_ops_total"]["value"]
    assert rescan_ops == 32 * 32


# ---------------------------------------------------------------------------
# collector / ring / flight integration
# ---------------------------------------------------------------------------

def test_collector_ring_is_bounded():
    FLAGS.ledger_ring = 8
    ledger.reset()
    ledger.register("t", lambda: {"r": 1})
    for _ in range(40):
        ledger.sample_now()
    assert len(ledger.series()) == 8
    assert ledger.peaks() == {"r": 1}


def test_probe_sum_weakref_and_gauge_export():
    class Box:
        def probe(self):
            return {"x_bytes": 7}

    b1, b2 = Box(), Box()
    ledger.register("s1", Box.probe, owner=b1)
    ledger.register("s2", Box.probe, owner=b2)
    assert ledger.sample_now()["x_bytes"] == 14
    assert obs_metrics.snapshot()["ledger_x_bytes"]["value"] == 14
    del b2
    import gc
    gc.collect()
    assert ledger.sample_now()["x_bytes"] == 7
    # a resource whose LAST probe died must read 0, not freeze at its
    # final value (a later flight dump would blame a dead subsystem)
    del b1
    gc.collect()
    assert "x_bytes" not in ledger.sample_now()
    assert obs_metrics.snapshot()["ledger_x_bytes"]["value"] == 0


def test_transient_probe_failure_serves_last_row_not_zero():
    """Regression (review): a probe losing a race (RuntimeError from a
    lock-free dict walk) must serve its LAST row — zeroing it would
    make the busiest sample of a collapse look idle.  Only a dead
    owner drops the resource."""
    state = {"boom": False}

    class Box:
        def probe(self):
            if state["boom"]:
                raise RuntimeError("dict changed size during iteration")
            return {"p_bytes": 42}

    b = Box()
    ledger.register("t", Box.probe, owner=b)
    assert ledger.sample_now()["p_bytes"] == 42
    state["boom"] = True
    assert ledger.sample_now()["p_bytes"] == 42   # last row, not 0
    assert obs_metrics.snapshot()["ledger_p_bytes"]["value"] == 42
    del b
    import gc
    gc.collect()
    assert "p_bytes" not in ledger.sample_now()   # dead owner: gone
    assert obs_metrics.snapshot()["ledger_p_bytes"]["value"] == 0


def test_fastwire_gauges_absolute_across_zero_all():
    """Regression (review): conn/inflight gauges are recomputed from
    absolute live counts — a mid-run metrics.zero_all() (the bench
    rebasing pattern) must not leave them stuck negative."""
    from paddle_tpu.distributed import fastwire

    base = fastwire._live["conns"]
    fastwire._live_adj("conns", 1, fastwire._M_CONNS)
    obs_metrics.zero_all()
    fastwire._live_adj("conns", -1, fastwire._M_CONNS)
    assert fastwire._live["conns"] == base
    assert obs_metrics.snapshot()[
        "fastwire_server_conns"]["value"] == base


def test_flight_dump_contains_ledger_snapshot():
    d = tempfile.mkdtemp(prefix="ledger_flight_")
    ledger.register("t", lambda: {"pending": 1234})
    ledger.sample_now()
    path = flight.dump("test", directory=d)
    with open(path) as f:
        rec = json.load(f)
    assert rec["ledger"]["resources"]["pending"] == 1234
    assert any(s["values"].get("pending") == 1234
               for s in rec["ledger"]["series"])


def test_ledger_watch_trips_one_flight_dump():
    d = tempfile.mkdtemp(prefix="ledger_watch_")
    FLAGS.telemetry_dump_dir = d
    FLAGS.ledger_watch = "grow_bytes>100"
    state = {"v": 10}
    ledger.register("t", lambda: {"grow_bytes": state["v"]})
    ledger.sample_now()
    assert glob.glob(os.path.join(d, "flight_*.json")) == []
    state["v"] = 500
    ledger.sample_now()
    ledger.sample_now()   # second crossing must NOT dump again
    arts = glob.glob(os.path.join(d, "flight_*.json"))
    assert len(arts) == 1
    with open(arts[0]) as f:
        rec = json.load(f)
    assert rec["reason"] == "ledger:grow_bytes"
    assert rec["blocked"]["threshold"] == 100.0


def test_hier_fanin_buffer_ledger():
    from paddle_tpu.distributed import fastwire, hierarchy

    if not fastwire.native_available():
        pytest.skip("fastwire native library unavailable")
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    agg = hierarchy.HostAggregator(2, port)
    try:
        arr = np.ones(64, np.float32)
        agg.stash(0, "ep0", "g1", arr, sender=A)
        assert agg._ledger_probe() == {
            "hier_fanin_bytes": arr.nbytes, "hier_fanin_entries": 1,
            "hier_inflight_uploads": 0}
        agg.stash(0, "ep0", "g1", arr, sender=A)   # overwrite
        assert agg._ledger_probe()["hier_fanin_entries"] == 1
        agg.stash(0, "ep0", "g1", arr * 3, sender=B)
        assert agg._ledger_probe()["hier_fanin_bytes"] == 2 * arr.nbytes
        agg._h_barrier(_barrier(B, 0))
        out = agg.flush(0, deadline=10)
        assert len(out) == 1
        np.testing.assert_allclose(out[0][2], arr * 2)
        probe = agg._ledger_probe()
        assert probe["hier_fanin_bytes"] == 0
        assert probe["hier_fanin_entries"] == 0
    finally:
        agg.stop()


# ---------------------------------------------------------------------------
# knee detection + rollup
# ---------------------------------------------------------------------------

def test_knee_detector_on_synthetic_curves():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        from scale_bench import detect_knee
    finally:
        sys.path.pop(0)
    # perfectly linear scaling: no knee
    assert detect_knee([(8, 800), (16, 1600), (32, 3200)]) is None
    # saturation: marginal throughput/trainer collapses at 32
    knee = detect_knee([(8, 800), (16, 1600), (32, 2000), (64, 2100)])
    assert knee["trainers"] == 32
    assert knee["marginal_per_trainer"] == 25.0
    assert knee["base_per_trainer"] == 100.0
    # regression past the knee still names the FIRST bend
    knee = detect_knee([(8, 800), (16, 1500), (32, 1400)])
    assert knee["trainers"] == 32
    # degenerate inputs
    assert detect_knee([(8, 800)]) is None
    assert detect_knee([]) is None


def test_scale_rows_rollup_reads_ledger_gauges():
    from paddle_tpu.observability import export

    dump = {"label": "pserver@x", "metrics": {
        "ledger_pserver_pending_grad_bytes": {"value": 4096},
        "ledger_pserver_barrier_set": {"value": 17},
        "pserver_quorum_scan_ops_total": {"value": 99},
        "rpc_replay_cache_evictions_total": {"value": 3},
    }}
    rows = export.scale_rows([dump])
    assert rows[0]["pending_bytes"] == 4096
    assert rows[0]["barrier_set"] == 17
    assert rows[0]["quorum_scan_ops"] == 99
    assert rows[0]["replay_evictions"] == 3
    assert "pserver@x" in export.format_scale_table(rows)


# ---------------------------------------------------------------------------
# the harness itself (tier-1 smoke, like pserver_bench --quick)
# ---------------------------------------------------------------------------

def test_scale_bench_quick_smoke():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "tools/scale_bench.py", "--quick",
         "--no-variants", "--trainers", "4,8", "--rounds", "2"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["metric"] == "scale_bench" and out["quick"]
    assert len(out["sweep"]) == 2
    for row in out["sweep"]:
        assert row["rows_per_sec"] > 0
        assert row["barrier_ms_p99"] >= row["barrier_ms_p50"] > 0
        peaks = row["ledger_peaks"]
        assert peaks["pserver_pending_grad_bytes"] > 0
        assert peaks["pserver_barrier_set"] == row["trainers"]
    assert "knee" in out
