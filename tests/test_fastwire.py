"""fastwire data plane (reference pserver/LightNetwork.cpp role).

The dist-train suite exercises it end-to-end through real transpiled
programs; these tests pin the transport contract in isolation:
frame round-trip, handshake rejection of foreign listeners (the gRPC
fallback trigger), and connection-pool reuse.
"""
import socket
import threading

import numpy as np
import pytest

from paddle_tpu.distributed import fastwire
from paddle_tpu.distributed.rpc import _dec_tensor, _enc_tensor


@pytest.mark.skipif(not fastwire.native_available(),
                    reason="no native toolchain")
def test_fastwire_echo_roundtrip_and_pool_reuse():
    arr = np.random.RandomState(0).randn(64, 33).astype(np.float32)

    def echo(req):
        name, a, extra = _dec_tensor(req)
        return _enc_tensor(name, np.asarray(a) * 2.0, extra)

    srv = fastwire.FastServer(39251, {"SendVariable": echo,
                                      "GetVariable": echo})
    try:
        pool = fastwire.FastConnPool(0)
        conn = pool.checkout("127.0.0.1:39251")
        assert conn is not None
        for _ in range(3):
            reply = conn.call("SendVariable", _enc_tensor("w", arr, 7))
            name, back, extra = _dec_tensor(reply)
            assert name == "w" and extra == 7
            np.testing.assert_allclose(np.asarray(back), arr * 2.0)
        pool.checkin("127.0.0.1:39251", conn)
        # reuse: the same connection comes back
        again = pool.checkout("127.0.0.1:39251")
        assert again is conn
        pool.discard(again)
    finally:
        srv.stop()


@pytest.mark.skipif(not fastwire.native_available(),
                    reason="no native toolchain")
def test_fastwire_foreign_listener_marks_endpoint_dead():
    """A non-fastwire listener (e.g. another pserver's gRPC port) must
    fail the magic handshake -> checkout returns None and the endpoint
    is never retried (the caller stays on gRPC)."""
    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 39261))
    lsock.listen(1)
    got = []

    def accept_once():
        c, _ = lsock.accept()
        got.append(c.recv(16))   # swallow the magic, answer garbage
        c.sendall(b"HTTP/1.1 400\r\n\r\n")
        c.close()

    t = threading.Thread(target=accept_once, daemon=True)
    t.start()
    try:
        pool = fastwire.FastConnPool(0)
        assert pool.checkout("127.0.0.1:39261") is None
        # dead-marked: no second connection attempt
        assert pool.checkout("127.0.0.1:39261") is None
        assert "127.0.0.1:39261" in pool._dead
    finally:
        lsock.close()
