"""save/load persistables, inference model export, checkpoints
(cf. reference io.py tests + book test save/load paths)."""
import os

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.core.scope import Scope


def _build(main, startup):
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        y = fluid.layers.fc(x, size=3, act="softmax",
                            param_attr=fluid.ParamAttr(name="fc_w"),
                            bias_attr=fluid.ParamAttr(name="fc_b"))
        loss = fluid.layers.mean(y)
        fluid.optimizer.SGD(0.1).minimize(loss)
    return x, y, loss


def test_save_load_persistables(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    _build(main, startup)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        w_before = np.asarray(scope.find_var("fc_w")).copy()
        fluid.io.save_persistables(exe, str(tmp_path / "model"), main)
        # clobber and reload
        scope.set("fc_w", np.zeros_like(w_before))
        fluid.io.load_persistables(exe, str(tmp_path / "model"), main)
        np.testing.assert_allclose(np.asarray(scope.find_var("fc_w")),
                                   w_before)


def test_save_load_combined(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    _build(main, startup)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        w_before = np.asarray(scope.find_var("fc_w")).copy()
        fluid.io.save_persistables(exe, str(tmp_path / "model"), main,
                                   filename="all_params")
        assert os.path.exists(tmp_path / "model" / "all_params")
        scope.set("fc_w", np.zeros_like(w_before))
        fluid.io.load_persistables(exe, str(tmp_path / "model"), main,
                                   filename="all_params")
        np.testing.assert_allclose(np.asarray(scope.find_var("fc_w")),
                                   w_before)


def test_inference_model_roundtrip(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    x, y, loss = _build(main, startup)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        xs = np.random.RandomState(0).randn(2, 4).astype(np.float32)
        want, = exe.run(main.clone(for_test=True), feed={"x": xs},
                        fetch_list=[y])
        fluid.io.save_inference_model(str(tmp_path / "infer"), ["x"], [y],
                                      exe, main)
    # fresh scope = fresh process simulation
    scope2 = Scope()
    with fluid.scope_guard(scope2):
        prog, feed_names, fetch_vars = fluid.io.load_inference_model(
            str(tmp_path / "infer"), exe)
        assert feed_names == ["x"]
        got, = exe.run(prog, feed={"x": xs}, fetch_list=fetch_vars)
        np.testing.assert_allclose(got, want, rtol=1e-5)


def test_checkpoint_serial_dirs(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    _build(main, startup)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = Scope()
    ckpt = str(tmp_path / "ckpt")
    with fluid.scope_guard(scope):
        exe.run(startup)
        for i in range(5):
            serial = fluid.io.save_checkpoint(
                exe, ckpt, trainer_args={"step": i}, main_program=main)
        assert serial == 4
        # keep-last-3 scroll delete (reference io.py:682)
        dirs = sorted(os.listdir(ckpt))
        assert dirs == ["checkpoint_2", "checkpoint_3", "checkpoint_4"]
        assert fluid.io.get_latest_checkpoint_serial(ckpt) == 4
        w = np.asarray(scope.find_var("fc_w")).copy()
        scope.set("fc_w", np.zeros_like(w))
        fluid.io.load_checkpoint(exe, ckpt, main_program=main)
        np.testing.assert_allclose(np.asarray(scope.find_var("fc_w")), w)
        args = fluid.io.load_trainer_args(ckpt, 4, 0)
        assert args["step"] == 4


def test_checkpoint_missing_success_marker_skipped(tmp_path):
    """A serial dir without _SUCCESS is an interrupted save: it must be
    invisible to get_latest_checkpoint_serial (a crash mid-save_checkpoint
    leaves exactly this shape behind)."""
    ckpt = str(tmp_path / "ckpt")
    for serial, complete in [(0, True), (1, True), (2, False)]:
        model = os.path.join(ckpt, "checkpoint_%d" % serial, "__model__")
        os.makedirs(model)
        if complete:
            with open(os.path.join(model, "_SUCCESS"), "w") as f:
                f.write("0")
    assert fluid.io.get_latest_checkpoint_serial(ckpt) == 1
    # no completed checkpoint at all -> -1 (fresh start)
    empty = str(tmp_path / "empty")
    os.makedirs(os.path.join(empty, "checkpoint_7", "__model__"))
    assert fluid.io.get_latest_checkpoint_serial(empty) == -1
    assert fluid.io.get_latest_checkpoint_serial(str(tmp_path / "no")) \
        == -1


def test_scroll_delete_keep_last_3_non_contiguous(tmp_path):
    """Keep-last-3 ranks by SERIAL NUMBER even when serials are sparse
    (crashes / manual cleanup leave holes), and ignores stray non-dir
    entries that happen to match the prefix."""
    from paddle_tpu.fluid.io import _scroll_delete

    ckpt = str(tmp_path / "ckpt")
    for serial in (1, 4, 9, 12):
        model = os.path.join(ckpt, "checkpoint_%d" % serial, "__model__")
        os.makedirs(model)
        with open(os.path.join(model, "_SUCCESS"), "w") as f:
            f.write("0")
    stray = os.path.join(ckpt, "checkpoint_7")   # a FILE, not a dir
    with open(stray, "w") as f:
        f.write("torn tmp junk")
    _scroll_delete(ckpt, max_num_checkpoints=3)
    kept = sorted(d for d in os.listdir(ckpt)
                  if os.path.isdir(os.path.join(ckpt, d)))
    assert kept == ["checkpoint_12", "checkpoint_4", "checkpoint_9"]
    assert os.path.exists(stray)   # never rm -rf something we don't own
    assert fluid.io.get_latest_checkpoint_serial(ckpt) == 12
