"""Spawned-process worker for the AOT inference test: loads a saved
model in a FRESH process and serves it, recording every XLA compilation
the process performs (own module — multiprocessing 'spawn' re-imports
the worker's module in the child)."""
import logging

import numpy as np


def aot_serve_worker(model_dir, x_list, q):
    try:
        records = []

        class Capture(logging.Handler):
            def emit(self, r):
                records.append(r.getMessage())

        import jax

        jax.config.update("jax_log_compiles", True)
        logger = logging.getLogger("jax._src.dispatch")
        logger.addHandler(Capture())
        logger.setLevel(logging.WARNING)

        from paddle_tpu import inference as inf

        pred = inf.create_paddle_predictor(
            inf.NativeConfig(model_dir=model_dir))
        x = np.asarray(x_list, np.float32)
        out = pred.run({"x": x})
        compiles = [m for m in records if "compilation" in m.lower()]
        q.put((out[0].data.tolist(), compiles, pred.aot is not None))
    except Exception as e:
        q.put(("ERROR: %r" % e, [], False))
