"""AOT inference export/serve: save_inference_model(aot_feed_specs=...)
serializes the compiled XLA executable (inference/aot.py — the
pre-compiled-engine analog of reference inference/tensorrt/engine.cc and
the native predictor, contrib/inference/paddle_inference_api.h:61); a
fresh process loads it and serves with ZERO XLA compilations and
identical outputs."""
import multiprocessing as mp
import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.core.scope import Scope


def _build_and_save(tmpdir):
    main, startup = fluid.Program(), fluid.Program()
    scope = Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                x = fluid.layers.data(name="x", shape=[6], dtype="float32")
                h = fluid.layers.fc(x, size=5, act="tanh")
                out = fluid.layers.fc(h, size=3, act="softmax")
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        fluid.io.save_inference_model(
            tmpdir, ["x"], [out], exe, main_program=main,
            aot_feed_specs={"x": ((4, 6), "float32")})
        # reference outputs computed through the normal executor path
        xs = np.linspace(-1, 1, 24).astype(np.float32).reshape(4, 6)
        infer = main.clone(for_test=True)
        ref, = exe.run(infer, feed={"x": xs}, fetch_list=[out])
    return xs, np.asarray(ref)


def test_aot_artifacts_written(tmp_path):
    d = str(tmp_path / "m")
    _build_and_save(d)
    assert os.path.exists(os.path.join(d, "__aot__.pkl"))
    assert os.path.exists(os.path.join(d, "__aot__.json"))


def test_aot_serves_fresh_process_no_compile(tmp_path):
    from tests import inference_helpers as H

    d = str(tmp_path / "m")
    xs, ref = _build_and_save(d)

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    p = ctx.Process(target=H.aot_serve_worker,
                    args=(d, xs.tolist(), q))
    p.start()
    got, compiles, used_aot = q.get(timeout=180)
    p.join(timeout=30)
    assert not (isinstance(got, str) and got.startswith("ERROR")), got
    assert used_aot, "predictor did not load the AOT executable"
    assert compiles == [], "fresh process compiled: %r" % compiles
    np.testing.assert_allclose(np.asarray(got), ref, atol=1e-6)


def test_aot_spec_mismatch_falls_back(tmp_path):
    """A feed whose shape differs from the exported spec must still be
    served (re-jit path), with correct results."""
    from paddle_tpu import inference as inf

    d = str(tmp_path / "m")
    xs, ref = _build_and_save(d)
    pred = inf.create_paddle_predictor(inf.NativeConfig(model_dir=d))
    assert pred.aot is not None
    other = np.vstack([xs, xs])  # batch 8 != exported batch 4
    out = pred.run({"x": other})
    np.testing.assert_allclose(out[0].data[:4], ref, atol=1e-6)
    np.testing.assert_allclose(out[0].data[4:], ref, atol=1e-6)
    # the exported batch still goes through the AOT executable
    out2 = pred.run({"x": xs})
    np.testing.assert_allclose(out2[0].data, ref, atol=1e-6)


def _build_and_save_bn(tmpdir):
    """conv+BN model: exercises donated persistables (BN running stats)
    and the analysis-pass interaction."""
    main, startup = fluid.Program(), fluid.Program()
    scope = Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                x = fluid.layers.data(name="x", shape=[2, 8, 8],
                                      dtype="float32")
                c = fluid.layers.conv2d(x, num_filters=3, filter_size=3,
                                        padding=1)
                b = fluid.layers.batch_norm(c)
                out = fluid.layers.reduce_mean(b, dim=[2, 3])
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        fluid.io.save_inference_model(
            tmpdir, ["x"], [out], exe, main_program=main,
            aot_feed_specs={"x": ((2, 2, 8, 8), "float32")})
        xs = np.linspace(-1, 1, 256).astype(np.float32).reshape(2, 2, 8, 8)
        infer = main.clone(for_test=True)
        ref, = exe.run(infer, feed={"x": xs}, fetch_list=[out])
    return xs, np.asarray(ref)


def test_aot_bn_model_repeat_runs(tmp_path):
    """Donated BN running-stat buffers must be written back between
    calls — the second run() used to hand the executable deleted
    arrays."""
    from paddle_tpu import inference as inf

    d = str(tmp_path / "m")
    xs, ref = _build_and_save_bn(d)
    pred = inf.create_paddle_predictor(inf.NativeConfig(model_dir=d))
    assert pred.aot is not None
    for _ in range(3):  # repeated serving through the same executable
        out = pred.run({"x": xs})
        np.testing.assert_allclose(out[0].data, ref, atol=1e-5)


def test_aot_concurrent_cloned_predictors(tmp_path):
    """clone() shares the AotExecutable; run() donates the staged BN
    running-stat buffers, so two in-flight calls without the per-
    executable lock would hand the same donated buffer to two
    executions (crash / corrupt outputs)."""
    import threading

    from paddle_tpu import inference as inf

    d = str(tmp_path / "m")
    xs, ref = _build_and_save_bn(d)
    pred = inf.create_paddle_predictor(inf.NativeConfig(model_dir=d))
    assert pred.aot is not None
    preds = [pred] + [pred.clone() for _ in range(3)]
    errors = []

    def serve(p):
        try:
            for _ in range(8):
                out = p.run({"x": xs})
                np.testing.assert_allclose(out[0].data, ref, atol=1e-5)
        except Exception as e:
            errors.append(e)

    ts = [threading.Thread(target=serve, args=(p,)) for p in preds]
    for t in ts:
        t.start()
    for t in ts:
        t.join(120)
    assert not any(t.is_alive() for t in ts), "serve thread hung"
    assert not errors, errors[0]


def test_aot_lock_skipped_without_persists(tmp_path):
    """ISSUE 9 satellite: a pure test-mode executable (no written
    persistables, nothing donated) must NOT serialize dispatches on
    _run_lock — cloned predictors overlap.  Proof: run() completes
    while another thread HOLDS the lock."""
    import threading

    from paddle_tpu import inference as inf

    d = str(tmp_path / "m")
    xs, ref = _build_and_save(d)           # fc model: no BN stats
    pred = inf.create_paddle_predictor(inf.NativeConfig(model_dir=d))
    assert pred.aot is not None
    assert pred.aot._persist_slots == []
    done = threading.Event()
    out = {}

    def serve():
        out["v"] = pred.run({"x": xs})
        done.set()

    with pred.aot._run_lock:               # a "stuck" concurrent run
        t = threading.Thread(target=serve, daemon=True)
        t.start()
        assert done.wait(60), \
            "persist-free run() blocked on _run_lock"
    t.join(30)
    np.testing.assert_allclose(out["v"][0].data, ref, atol=1e-6)


def test_aot_lock_still_serializes_persist_writeback(tmp_path):
    """Counterpart: an executable WITH donated persistables (BN running
    stats) must keep taking the lock — two overlapped calls would hand
    the same donated buffer to two executions."""
    import threading

    from paddle_tpu import inference as inf

    d = str(tmp_path / "m")
    xs, _ = _build_and_save_bn(d)
    pred = inf.create_paddle_predictor(inf.NativeConfig(model_dir=d))
    assert pred.aot is not None
    assert pred.aot._persist_slots, "BN model lost its persist slots"
    done = threading.Event()

    def serve():
        pred.run({"x": xs})
        done.set()

    with pred.aot._run_lock:
        t = threading.Thread(target=serve, daemon=True)
        t.start()
        assert not done.wait(0.5), \
            "run() with donated persistables skipped _run_lock"
    assert done.wait(60)
    t.join(30)


def test_aot_load_fallback_metered(tmp_path):
    """ISSUE 9 satellite: load_aot falling back to re-jit must feed
    aot_load_fallback_total and record the reason — a fleet quietly on
    the slow path is visible in SERVE_BENCH.json, not only in a
    warning."""
    import json as _json
    import warnings

    from paddle_tpu.core.scope import Scope as _Scope
    from paddle_tpu.inference import aot as aot_mod
    from paddle_tpu.observability import metrics as _metrics

    ctr = _metrics.counter("aot_load_fallback_total")
    d = str(tmp_path / "m")
    _build_and_save(d)
    # corrupt artifact -> load_error fallback
    with open(os.path.join(d, "__aot__.pkl"), "wb") as f:
        f.write(b"\x80\x04 garbage")
    before = ctr.value
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        got = aot_mod.load_aot(d, _Scope(), __import__(
            "paddle_tpu.fluid", fromlist=["CPUPlace"]).CPUPlace())
    assert got is None
    assert ctr.value == before + 1
    assert aot_mod.FALLBACKS[-1]["reason"] == "load_error"
    assert aot_mod.FALLBACKS[-1]["dir"] == d
    # platform mismatch -> its own reason, counted too
    meta_path = os.path.join(d, "__aot__.json")
    with open(meta_path) as f:
        meta = _json.load(f)
    meta["platform"] = "not-a-platform"
    with open(meta_path, "w") as f:
        _json.dump(meta, f)
    got = aot_mod.load_aot(d, _Scope(), __import__(
        "paddle_tpu.fluid", fromlist=["CPUPlace"]).CPUPlace())
    assert got is None
    assert ctr.value == before + 2
    assert aot_mod.FALLBACKS[-1]["reason"] == "platform_mismatch"


def test_aot_skipped_under_analysis_passes(tmp_path):
    """AnalysisConfig's BN-fold mutates the parameter scope; the AOT
    artifact (compiled from the unfolded program) must not be served
    against it."""
    from paddle_tpu import inference as inf

    d = str(tmp_path / "m")
    xs, ref = _build_and_save_bn(d)
    pred = inf.create_paddle_predictor(inf.AnalysisConfig(model_dir=d))
    assert pred.aot is None
    out = pred.run({"x": xs})
    np.testing.assert_allclose(out[0].data, ref, atol=1e-4)
