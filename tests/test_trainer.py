"""High-level Trainer/Inferencer (reference python/paddle/fluid/
trainer.py:35-460, inferencer.py:29; usage shape from
tests/book/high-level-api).  Covers the event loop, test(), params
save + Inferencer load, and checkpoint kill-and-resume restoring
epoch/step with matching loss trajectory."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid

LR = 0.05
N_FEAT = 8


def _train_func():
    x = fluid.layers.data(name="x", shape=[N_FEAT], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    pred = fluid.layers.fc(
        x, size=1,
        param_attr=fluid.ParamAttr(
            name="w", initializer=fluid.initializer.
            ConstantInitializer(0.0)),
        bias_attr=fluid.ParamAttr(
            name="b", initializer=fluid.initializer.
            ConstantInitializer(0.0)))
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    return [loss]


def _infer_func():
    x = fluid.layers.data(name="x", shape=[N_FEAT], dtype="float32")
    return fluid.layers.fc(
        x, size=1, param_attr=fluid.ParamAttr(name="w"),
        bias_attr=fluid.ParamAttr(name="b"))


def _opt_func():
    return fluid.optimizer.SGD(learning_rate=LR)


_W = np.random.RandomState(3).randn(N_FEAT, 1).astype(np.float32)


def _reader(n=48, seed=0):
    import paddle_tpu as pt

    def r():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            x = rng.randn(N_FEAT).astype(np.float32)
            yield (x, (x @ _W).astype(np.float32))

    return pt.batch(r, 8)


def test_trainer_events_and_test_and_infer(tmp_path):
    events = []

    def handler(ev):
        events.append(type(ev).__name__)
        if isinstance(ev, fluid.EndStepEvent) and ev.metrics:
            losses.append(float(np.ravel(ev.metrics[0])[0]))

    losses = []
    t = fluid.Trainer(train_func=_train_func, optimizer_func=_opt_func,
                      place=fluid.CPUPlace())
    t.train(num_epochs=2, event_handler=handler, reader=_reader(),
            feed_order=["x", "y"])
    # event protocol: Begin/EndEpoch wrap Begin/EndStep pairs
    assert events[0] == "BeginEpochEvent"
    assert events[-1] == "EndEpochEvent"
    assert events.count("BeginEpochEvent") == 2
    assert events.count("BeginStepEvent") == \
        events.count("EndStepEvent") == 12
    assert losses[-1] < losses[0] * 0.5

    test_metrics = t.test(reader=_reader(seed=1), feed_order=["x", "y"])
    assert len(test_metrics) == 1 and test_metrics[0] < losses[0]

    # save -> Inferencer round trip
    d = str(tmp_path / "params")
    t.save_params(d)
    inf = fluid.Inferencer(infer_func=_infer_func, param_path=d,
                           place=fluid.CPUPlace())
    xv = np.ones((4, N_FEAT), np.float32)
    out, = inf.infer({"x": xv})
    np.testing.assert_allclose(np.asarray(out),
                               xv @ np.asarray(
                                   inf.scope.find_var("w")) +
                               np.asarray(inf.scope.find_var("b")),
                               rtol=1e-5)


def test_trainer_checkpoint_kill_and_resume(tmp_path):
    ckpt = str(tmp_path / "ckpt")

    class Killed(Exception):
        pass

    def run(kill_at=None, num_epochs=3):
        seen = []

        def handler(ev):
            if isinstance(ev, fluid.EndStepEvent):
                seen.append((ev.epoch, ev.step,
                             float(np.ravel(ev.metrics[0])[0])))
                if kill_at is not None and \
                        (ev.epoch, ev.step) == kill_at:
                    raise Killed()  # hard crash: checkpoints survive
                    # (trainer.stop() is the GRACEFUL path and cleans
                    # them, reference trainer.py:375-378)

        cfg = fluid.CheckpointConfig(checkpoint_dir=ckpt,
                                     epoch_interval=1, step_interval=2)
        t = fluid.Trainer(train_func=_train_func,
                          optimizer_func=_opt_func,
                          place=fluid.CPUPlace(), checkpoint_config=cfg)
        try:
            t.train(num_epochs=num_epochs, event_handler=handler,
                    reader=_reader(), feed_order=["x", "y"])
        except Killed:
            pass
        return seen, t

    # uninterrupted baseline
    base, tb = run()
    import shutil
    shutil.rmtree(ckpt, ignore_errors=True)

    # killed mid-epoch-1 (checkpoint saved at (1, 2) covers steps <= 2)
    first, _ = run(kill_at=(1, 2))
    assert first[-1][:2] == (1, 2)

    # resume: cursor is (1, 3) — the step (1,2) whose update is already
    # in the checkpointed params is NOT re-run, and the trajectory from
    # (1,3) on matches the uninterrupted baseline exactly
    second, _ = run()
    resumed = {(e, s): l for e, s, l in second}
    assert (0, 0) not in resumed          # epoch 0 not repeated
    assert (1, 2) not in resumed          # checkpointed step not re-run
    baseline = {(e, s): l for e, s, l in base}
    for key in [(1, 3), (1, 4), (2, 0), (2, 5)]:
        assert key in resumed, (key, sorted(resumed))
        np.testing.assert_allclose(resumed[key], baseline[key],
                                   rtol=1e-4, err_msg=str(key))


def test_trainer_fit_a_line_uci_housing(tmp_path):
    """The high-level-api fit_a_line chapter end-to-end: Trainer over
    the uci_housing adapter, EndEpoch test() gate, then Inferencer
    (reference book/high-level-api/fit_a_line/test_fit_a_line.py)."""
    import paddle_tpu as pt
    from paddle_tpu import dataset

    def train_func():
        x = fluid.layers.data(name="x", shape=[13], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(x, size=1, act=None,
                               param_attr=fluid.ParamAttr(name="fw"),
                               bias_attr=fluid.ParamAttr(name="fb"))
        return [fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))]

    def infer_func():
        x = fluid.layers.data(name="x", shape=[13], dtype="float32")
        return fluid.layers.fc(x, size=1, act=None,
                               param_attr=fluid.ParamAttr(name="fw"),
                               bias_attr=fluid.ParamAttr(name="fb"))

    trainer = fluid.Trainer(
        train_func=train_func,
        optimizer_func=lambda: fluid.optimizer.SGD(learning_rate=0.01),
        place=fluid.CPUPlace())

    test_losses = []

    def handler(ev):
        if isinstance(ev, fluid.EndEpochEvent):
            test_losses.append(trainer.test(
                reader=pt.batch(dataset.uci_housing.test(), 32),
                feed_order=["x", "y"])[0])

    trainer.train(num_epochs=12,
                  event_handler=handler,
                  reader=pt.batch(dataset.uci_housing.train(), 32),
                  feed_order=["x", "y"])
    # held-out MSE must fall substantially from the untrained start
    assert test_losses[-1] < test_losses[0] * 0.5, test_losses[:3]

    param_path = str(tmp_path / "fit_a_line")
    trainer.save_params(param_path)
    inferencer = fluid.Inferencer(infer_func=infer_func,
                                  param_path=param_path,
                                  place=fluid.CPUPlace())
    batch = np.stack([s[0] for s in list(
        dataset.uci_housing.test()())[:8]]).astype(np.float32)
    preds = np.asarray(inferencer.infer({"x": batch})[0])
    assert preds.shape == (8, 1) and np.isfinite(preds).all()
