"""v2 API shim surface (reference python/paddle/v2 data utilities +
graph API entry points; full graph-API behavior is tested in
test_v2_api.py)."""
import pytest

import paddle_tpu.v2 as paddle_v2


def test_v2_data_utilities_alias():
    paddle_v2.init(trainer_count=1)
    r = paddle_v2.batch(lambda: iter(range(10)), 4)
    assert list(r()) == [[0, 1, 2, 3], [4, 5, 6, 7]]
    assert paddle_v2.dataset.mnist is not None
    assert paddle_v2.reader.shuffle is not None


def test_v2_graph_api_importable():
    """Round 3 raised on these names; the round-4 adapter provides
    them (VERDICT r3 missing #1)."""
    assert callable(paddle_v2.layer.fc)
    assert callable(paddle_v2.layer.data)
    assert callable(paddle_v2.infer)
    assert paddle_v2.trainer.SGD is not None
    assert paddle_v2.optimizer.Momentum is not None
    assert paddle_v2.parameters.create is not None
    assert paddle_v2.activation.Softmax is not None
    with pytest.raises(ValueError):
        paddle_v2.init(trainer_count=0)
