"""v2 API shim (reference python/paddle/v2 data utilities)."""
import pytest

import paddle_tpu.v2 as paddle_v2


def test_v2_data_utilities_alias():
    paddle_v2.init(trainer_count=1)
    r = paddle_v2.batch(lambda: iter(range(10)), 4)
    assert list(r()) == [[0, 1, 2, 3], [4, 5, 6, 7]]
    assert paddle_v2.dataset.mnist is not None
    assert paddle_v2.reader.shuffle is not None


def test_v2_graph_api_points_to_fluid():
    with pytest.raises(AttributeError, match="superseded"):
        paddle_v2.layer
    with pytest.raises(NotImplementedError):
        paddle_v2.infer()
    with pytest.raises(ValueError):
        paddle_v2.init(trainer_count=0)
