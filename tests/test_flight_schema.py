"""Flight-recorder envelope golden (ISSUE 13 satellite).

The flight_*.json artifact is parsed by tools/fault_matrix.py (every
preset), tools/trace_report.py (--scale/--slo rollups),
tools/watchtower.py (alert section) and the scale/slo preset asserts.
PR 12 embedded the ledger with no versioning, so a shape change broke
downstream parsers silently — this golden pins the envelope (reason,
spans, metrics, ledger, slo, ...) and its schema_version: changing
either without touching this file is a test failure, which is the
point.
"""
import json
import os

from paddle_tpu.core.flags import FLAGS
from paddle_tpu.observability import flight, ledger
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.observability import slo, tsdb

# THE golden: the exact top-level key set of a flight dump.  Adding,
# removing or renaming a key is a schema change — bump
# flight.SCHEMA_VERSION and update this set in the same commit.
ENVELOPE_KEYS = {
    "kind", "schema_version", "reason", "wall_time", "pid", "label",
    "telemetry_on", "blocked", "open_spans", "recent_spans",
    "metrics", "ledger", "slo",
}
SCHEMA_VERSION = 1


def _dump(tmp_path, **kw):
    path = flight.dump("schema:test", directory=str(tmp_path), **kw)
    assert path is not None
    with open(path) as f:
        return json.load(f)


def test_envelope_keys_and_version(tmp_path):
    rec = _dump(tmp_path)
    assert set(rec.keys()) == ENVELOPE_KEYS, (
        "flight envelope changed — bump flight.SCHEMA_VERSION and "
        "update ENVELOPE_KEYS together: %r"
        % sorted(set(rec.keys()) ^ ENVELOPE_KEYS))
    assert rec["schema_version"] == SCHEMA_VERSION
    assert flight.SCHEMA_VERSION == SCHEMA_VERSION
    assert rec["kind"] == "flight_recorder"
    assert rec["reason"] == "schema:test"
    assert rec["pid"] == os.getpid()
    # always-present sections keep their shape even when empty
    assert isinstance(rec["metrics"], dict)
    assert isinstance(rec["open_spans"], list)
    assert isinstance(rec["recent_spans"], list)


def test_ledger_section_shape(tmp_path):
    """The embedded ledger keeps its {resources, series} shape (the
    PR 12 contract fault_matrix's scale preset parses)."""
    ledger.reset()
    ledger.register("t", lambda: {"schema_probe_bytes": 42})
    try:
        ledger.sample_now()
        rec = _dump(tmp_path)
        led = rec["ledger"]
        assert set(led.keys()) == {"resources", "series"}
        assert led["resources"]["schema_probe_bytes"] == 42
        assert isinstance(led["series"], list)
        assert led["series"][-1]["values"]["schema_probe_bytes"] == 42
    finally:
        ledger.reset()


def test_slo_section_shape(tmp_path):
    """Without an evaluator the slo key is present-but-None; with one
    it carries {status, alerts}; an alert-written dump's sections
    override embeds the offending series under slo.alert."""
    slo.reset()
    rec = _dump(tmp_path)
    assert rec["slo"] is None

    store = tsdb.TSDB(str(tmp_path / "ts"))
    import time
    now = time.time()
    for i in range(10):
        store.append_row({"m": 1.0}, t=now - 10 + i)
    ev = slo.install(store=store,
                     specs=slo.load_specs("m<=5"))
    ev.evaluate(now=now)
    try:
        rec = _dump(tmp_path)
        assert set(rec["slo"].keys()) == {"status", "alerts"}
        assert rec["slo"]["status"][0]["name"] == "m"
        # sections= enriches the envelope without changing its keys
        rec2 = _dump(tmp_path, sections={"slo": {"alert": {
            "slo": "m", "series": [[now, 1.0]]}}})
        assert set(rec2.keys()) == ENVELOPE_KEYS
        assert rec2["slo"]["alert"]["slo"] == "m"
    finally:
        slo.reset()
        store.close()


def test_dump_is_json_roundtrippable(tmp_path):
    """Every envelope value is plain JSON (no numpy scalars leak):
    a full dumps/loads round trip is identity."""
    obs_metrics.counter("flight_schema_counter").inc(3)
    rec = _dump(tmp_path)
    assert json.loads(json.dumps(rec)) == rec
