"""Long-context ring attention (ISSUE 15): flash-chunk ring parity,
causal block skipping, fully-masked-block numerics, MoE routing stats,
and the longctx_bench tier-1 smoke.

Parity discipline: the single-device flash path
(kernels/flash_attention.py; the identical-math XLA fallback on this
CPU suite) is the reference for both directions — the acceptance pin
is fwd+bwd <= 1e-5 fp32."""
import json
import os
import subprocess
import sys

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.kernels.flash_attention import (
    NEG_INF, chunk_finalize, flash_attention, flash_attention_chunk,
    flash_attention_chunk_bwd, flash_attention_fwd_lse,
    resolve_chunk_blocks)
from paddle_tpu.parallel import make_mesh
from paddle_tpu.parallel.ring import (causal_step_counts,
                                      ring_attention,
                                      ring_attention_bwd,
                                      ring_attention_fwd_lse)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cpu(n):
    devs = jax.devices("cpu")
    if len(devs) < n:
        pytest.skip("needs %d cpu devices" % n)
    return devs[:n]


def _qkv(shape, dtype=np.float32, seed=0, scale=0.5):
    rng = np.random.RandomState(seed)
    return tuple(jnp.asarray((rng.randn(*shape) * scale).astype(
        np.float32)).astype(dtype) for _ in range(3))


# ------------------------------------------------------- ring parity

@pytest.mark.parametrize("p", [2, 4, 8])
@pytest.mark.parametrize("causal", [True, False])
def test_ring_fwd_parity_fp32(p, causal):
    mesh = make_mesh({"sp": p}, devices=_cpu(p))
    q, k, v = _qkv((2, 3, 32, 8))
    out = ring_attention(q, k, v, mesh, causal=causal)
    ref = flash_attention(q, k, v, causal=causal)
    assert float(jnp.abs(out - ref).max()) <= 1e-5, (p, causal)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_fwd_parity_bf16(causal):
    mesh = make_mesh({"sp": 4}, devices=_cpu(4))
    q, k, v = _qkv((1, 2, 16, 8), dtype=jnp.bfloat16)
    out = ring_attention(q, k, v, mesh, causal=causal)
    ref = flash_attention(q, k, v, causal=causal)
    diff = jnp.abs(out.astype(jnp.float32) - ref.astype(jnp.float32))
    assert float(diff.max()) <= 3e-2, causal
    assert out.dtype == jnp.bfloat16


@pytest.mark.parametrize("p", [2, 4, 8])
@pytest.mark.parametrize("causal", [True, False])
def test_ring_bwd_parity_fp32(p, causal):
    """Grads through the ring's custom_vjp (the saved-lse reverse ring)
    vs the single-device flash vjp — the acceptance pin."""
    mesh = make_mesh({"sp": p}, devices=_cpu(p))
    q, k, v = _qkv((1, 2, 16, 8), seed=1)

    def loss(fn):
        return lambda q, k, v: (fn(q, k, v).astype(jnp.float32)
                                ** 2).sum()

    g_ring = jax.grad(loss(lambda q, k, v: ring_attention(
        q, k, v, mesh, causal=causal)), argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss(lambda q, k, v: flash_attention(
        q, k, v, causal=causal)), argnums=(0, 1, 2))(q, k, v)
    for gr, gf, name in zip(g_ring, g_ref, "qkv"):
        rel = float(jnp.abs(gr - gf).max()) / max(
            float(jnp.abs(gf).max()), 1e-9)
        assert rel <= 1e-5, (p, causal, name, rel)


def test_ring_lse_parity():
    """The op-level saved-LSE residual is the REAL per-position
    log-sum-exp, not the pre-ISSUE-15 zeros placeholder."""
    mesh = make_mesh({"sp": 4}, devices=_cpu(4))
    q, k, v = _qkv((1, 2, 16, 8), seed=2)
    out, lse = ring_attention_fwd_lse(q, k, v, mesh, causal=True)
    ref_out, ref_lse = flash_attention_fwd_lse(q, k, v, causal=True,
                                               force_xla=True)
    assert float(jnp.abs(out - ref_out).max()) <= 1e-5
    assert float(jnp.abs(lse - ref_lse).max()) <= 1e-4
    assert float(jnp.abs(lse).max()) > 0.0


def test_ring_bwd_from_residuals():
    """ring_attention_bwd (the grad op's entry: residuals in, no
    forward recompute) matches the autodiff path exactly."""
    mesh = make_mesh({"sp": 4}, devices=_cpu(4))
    q, k, v = _qkv((1, 2, 16, 8), seed=3)
    out, lse = ring_attention_fwd_lse(q, k, v, mesh, causal=True)
    do = out * 0.7 + 0.1
    dq, dk, dv = ring_attention_bwd(q, k, v, out, lse, do, mesh,
                                    causal=True)
    g = jax.vjp(lambda q, k, v: ring_attention(q, k, v, mesh,
                                               causal=True),
                q, k, v)[1](do)
    for a, b, name in zip((dq, dk, dv), g, "qkv"):
        assert float(jnp.abs(a - b).max()) <= 1e-5, name


def test_ring_shard_boundary_rows():
    """Skip-step correctness at every shard offset: the first and last
    Q row of EVERY shard matches the dense reference (a wrong liveness
    predicate shows up exactly at these rows)."""
    p, sq = 8, 4
    mesh = make_mesh({"sp": p}, devices=_cpu(p))
    q, k, v = _qkv((1, 1, p * sq, 8), seed=4)
    out = np.asarray(ring_attention(q, k, v, mesh, causal=True))
    ref = np.asarray(flash_attention(q, k, v, causal=True))
    for s in range(p):
        for row in (s * sq, s * sq + sq - 1):
            diff = np.abs(out[:, :, row] - ref[:, :, row]).max()
            assert diff <= 1e-5, (s, row, diff)


# -------------------------------------------------- causal skipping

def test_causal_step_counts():
    """The FLOP-skip evidence: under causal, ring position i executes
    i+1 forward chunks (sum p(p+1)/2 vs p^2 dense) and the backward
    mirror; non-causal runs everything."""
    mesh = make_mesh({"sp": 8}, devices=_cpu(8))
    fwd = [int(c) for c in np.asarray(causal_step_counts(mesh))]
    bwd = [int(c) for c in np.asarray(
        causal_step_counts(mesh, direction="bwd"))]
    assert fwd == list(range(1, 9))
    assert bwd == list(range(8, 0, -1))
    assert sum(fwd) == 36          # 36/64 = ~2x fewer steps at p=8
    dense = [int(c) for c in np.asarray(
        causal_step_counts(mesh, causal=False))]
    assert dense == [8] * 8


def test_ring_hlo_double_buffer_structure():
    """Optimized-HLO inventory (the MESH_PROFILE_r06.md method): the
    forward schedules exactly 2*(p-1) collective-permutes — the
    double-buffered rotation with the last step elided; the naive scan
    form rotated 2*p — and p-1 causal-skip conditionals."""
    p = 4
    mesh = make_mesh({"sp": p}, devices=_cpu(p))
    q, k, v = _qkv((1, 1, 16, 8))

    def fwd(q, k, v):
        return ring_attention_fwd_lse(q, k, v, mesh, causal=True)[0]

    txt = jax.jit(fwd).lower(q, k, v).compile().as_text()
    import re
    perms = len(re.findall(r"\bcollective-permute(?:-start)?\(", txt))
    conds = len(re.findall(r"\bconditional\(", txt))
    assert perms == 2 * (p - 1), perms
    assert conds == p - 1, conds


# ------------------------------------------- chunk kernel + numerics

def test_chunk_carry_matches_full_flash():
    """Threading the (m, l, acc) carry across split K/V blocks equals
    one full flash attention — the exact invariant the ring relies
    on."""
    q, k, v = _qkv((2, 2, 32, 8), seed=5)
    m = jnp.full(q.shape[:3], NEG_INF, jnp.float32)
    l = jnp.zeros(q.shape[:3], jnp.float32)
    acc = jnp.zeros(q.shape, jnp.float32)
    for lo, hi in ((0, 8), (8, 24), (24, 32)):
        m, l, acc = flash_attention_chunk(
            q, k[:, :, lo:hi], v[:, :, lo:hi], m, l, acc,
            force_xla=True)
    out, lse = chunk_finalize(m, l, acc, q.dtype)
    ref, ref_lse = flash_attention_fwd_lse(q, k, v, force_xla=True)
    assert float(jnp.abs(out - ref).max()) <= 1e-5
    assert float(jnp.abs(lse - ref_lse).max()) <= 1e-4


@pytest.mark.parametrize("causal", [True, False])
def test_chunk_kernel_interpret_parity(causal):
    """The Pallas chunk kernel (interpret mode) is bit-compatible with
    the blockwise XLA fallback — the CPU-parity-transfers contract of
    every kernel PR."""
    q, k, v = _qkv((1, 2, 32, 8), seed=6)
    m = jnp.full(q.shape[:3], NEG_INF, jnp.float32)
    l = jnp.zeros(q.shape[:3], jnp.float32)
    acc = jnp.zeros(q.shape, jnp.float32)
    a = flash_attention_chunk(q, k, v, m, l, acc, causal=causal,
                              block_q=8, block_k=8, interpret=True)
    b = flash_attention_chunk(q, k, v, m, l, acc, causal=causal,
                              block_q=8, block_k=8, force_xla=True)
    for x, y, name in zip(a, b, ("m", "l", "acc")):
        assert float(jnp.abs(x - y).max()) <= 1e-6, (causal, name)


def test_chunk_bwd_interpret_parity():
    q, k, v = _qkv((1, 2, 32, 8), seed=7)
    out, lse = flash_attention_fwd_lse(q, k, v, causal=True,
                                       force_xla=True)
    do = out * 0.3
    delta = (do.astype(jnp.float32) * out.astype(jnp.float32)).sum(-1)
    a = flash_attention_chunk_bwd(q, k, v, do, lse, delta, causal=True,
                                  block_q=8, block_k=8, interpret=True)
    b = flash_attention_chunk_bwd(q, k, v, do, lse, delta, causal=True,
                                  block_q=8, block_k=8, force_xla=True)
    for x, y, name in zip(a, b, ("dq", "dk", "dv")):
        assert float(jnp.abs(x - y).max()) <= 2e-5, name


def test_fully_masked_block_guard():
    """The ISSUE 15 numerics hazard, pinned at the shard boundary: a
    causal block ENTIRELY in the future (k_offset >= Sq) must leave
    the carry unchanged and finite — without the guard, the online-
    softmax max collapses and exp() manufactures mass (or NaN)."""
    q, k, v = _qkv((1, 1, 8, 4), seed=8)
    m0 = jnp.full(q.shape[:3], NEG_INF, jnp.float32)
    l0 = jnp.zeros(q.shape[:3], jnp.float32)
    a0 = jnp.zeros(q.shape, jnp.float32)
    for interp in (False, True):
        kw = {"interpret": True} if interp else {"force_xla": True}
        m, l, acc = flash_attention_chunk(
            q, k, v, m0, l0, a0, causal=True, k_offset=8,
            block_q=4, block_k=4, **kw)
        assert bool(jnp.isfinite(l).all()) and bool(
            jnp.isfinite(acc).all()), interp
        assert float(jnp.abs(l - l0).max()) == 0.0, interp
        assert float(jnp.abs(acc - a0).max()) == 0.0, interp
    # rows with no live key EVER finalize to zero output + NEG_INF lse
    out, lse = chunk_finalize(m, l, acc, q.dtype)
    assert bool(jnp.isfinite(out).all())
    assert float(jnp.abs(out).max()) == 0.0
    assert float(lse.max()) <= 0.5 * NEG_INF


def test_partially_masked_boundary_block():
    """A half-future block (k_offset mid-shard) keeps the live half and
    zeroes the rest — the off-by-one surface of the guard."""
    q, k, v = _qkv((1, 1, 8, 4), seed=9)
    m = jnp.full(q.shape[:3], NEG_INF, jnp.float32)
    l = jnp.zeros(q.shape[:3], jnp.float32)
    acc = jnp.zeros(q.shape, jnp.float32)
    m, l, acc = flash_attention_chunk(q, k[:, :, :4], v[:, :, :4], m,
                                      l, acc, causal=True, k_offset=4,
                                      force_xla=True)
    out, lse = chunk_finalize(m, l, acc, q.dtype)
    assert bool(jnp.isfinite(out).all())
    # rows 0..3 see nothing (keys start at position 4); rows 4..7 do
    assert float(jnp.abs(out[:, :, :4]).max()) == 0.0
    assert float(jnp.abs(out[:, :, 4:]).max()) > 0.0
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k[:, :, :4].astype(jnp.float32)) * (4 ** -0.5)
    mask = (jnp.arange(8)[:, None] >= 4 + jnp.arange(4)[None, :])
    s = jnp.where(mask[None, None], s, -jnp.inf)
    ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1),
                     v[:, :, :4].astype(jnp.float32))
    assert float(jnp.abs(out[:, :, 4:] - ref[:, :, 4:]).max()) <= 1e-5


def test_chunk_bwd_k_offset_matches_forward_mask():
    """The chunk backward honors the SAME static k_offset as the
    forward: keys masked in the forward contribute zero gradient, and
    the live half matches autodiff through the offset-masked
    reference."""
    q, k4, v4 = _qkv((1, 1, 8, 4), seed=11)
    k4, v4 = k4[:, :, :4], v4[:, :, :4]

    def ref(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) * (4 ** -0.5)
        mask = (jnp.arange(8)[:, None] >= 4 + jnp.arange(4)[None, :])
        s = jnp.where(mask[None, None], s, NEG_INF)
        p = jnp.exp(s - jax.nn.logsumexp(s, axis=-1, keepdims=True))
        # rows with no live key: force their (uniform-softmax) mass out
        p = jnp.where(mask[None, None], p, 0.0)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))

    m = jnp.full(q.shape[:3], NEG_INF, jnp.float32)
    l = jnp.zeros(q.shape[:3], jnp.float32)
    acc = jnp.zeros(q.shape, jnp.float32)
    m, l, acc = flash_attention_chunk(q, k4, v4, m, l, acc,
                                      causal=True, k_offset=4,
                                      force_xla=True)
    out, lse = chunk_finalize(m, l, acc, q.dtype)
    do = jnp.ones_like(out) * 0.5
    delta = (do.astype(jnp.float32) * out.astype(jnp.float32)).sum(-1)
    dq, dk, dv = flash_attention_chunk_bwd(q, k4, v4, do, lse, delta,
                                           causal=True, k_offset=4,
                                           force_xla=True)
    g = jax.vjp(ref, q, k4, v4)[1](do.astype(jnp.float32))
    for a, b, name in zip((dq, dk, dv), g, ("dq", "dk", "dv")):
        assert float(jnp.abs(a - b).max()) <= 1e-5, name
    # dead rows (q_pos < 4) attend to nothing: their dq is exactly 0
    assert float(jnp.abs(dq[:, :, :4]).max()) == 0.0


# ------------------------------------------------- autotune plumbing

def test_ring_chunk_blocks_from_autotune_cache(tmp_path):
    """Ring chunk tiles resolve through the 'ring_attention' cache
    entry (tools/flash_tune.py --ring writes it); explicit args always
    win; a miss falls back to the flash defaults fitted to the
    shard."""
    from paddle_tpu import tuning
    from paddle_tpu.core.flags import FLAGS

    old = FLAGS.autotune_cache_dir
    FLAGS.autotune_cache_dir = str(tmp_path)
    tuning.invalidate()
    try:
        shape = (1, 2, 64, 8)
        assert resolve_chunk_blocks(shape, 64, jnp.float32) == (64, 64)
        assert tuning.record("ring_attention", shape + (64,),
                             "float32", {"block_q": 16, "block_k": 32})
        assert resolve_chunk_blocks(shape, 64, jnp.float32) == (16, 32)
        # explicit argument beats the cache
        assert resolve_chunk_blocks(shape, 64, jnp.float32,
                                    block_q=8) == (8, 32)
        # and the tuned tiles actually reach the chunk math unchanged
        q, k, v = _qkv(shape, seed=10)
        m = jnp.full(shape[:3], NEG_INF, jnp.float32)
        l = jnp.zeros(shape[:3], jnp.float32)
        acc = jnp.zeros(shape, jnp.float32)
        got = flash_attention_chunk(q, k, v, m, l, acc, force_xla=True)
        ref = flash_attention_chunk(q, k, v, m, l, acc, force_xla=True,
                                    block_q=64, block_k=64)
        # different tile sizes reorder the reduction; same math
        for x, y in zip(got, ref):
            assert float(jnp.abs(x - y).max()) <= 1e-4
    finally:
        FLAGS.autotune_cache_dir = old
        tuning.invalidate()


# --------------------------------------------------- MoE stats rider

def test_moe_router_stats_registry():
    """parallel/moe.py feeds the always-on registry: per-expert load
    histogram, dropped-token fraction, router entropy (ISSUE 15 MoE
    rider) — and FLAGS_moe_metrics=0 removes the callback."""
    from paddle_tpu.core.flags import FLAGS
    from paddle_tpu.observability import metrics
    from paddle_tpu.parallel import moe_ffn

    devs = _cpu(4)
    mesh = make_mesh({"ep": 4}, devices=devs)
    D, E, F, T = 8, 4, 16, 32
    rng = np.random.RandomState(0)
    ops = (jnp.asarray(rng.randn(T, D).astype(np.float32)),
           jnp.asarray(rng.randn(D, E).astype(np.float32)),
           jnp.asarray(rng.randn(E, D, F).astype(np.float32) * 0.2),
           jnp.asarray(rng.randn(E, F, D).astype(np.float32) * 0.2))
    metrics.zero_all()
    # capacity_factor 0.25 -> cap 2/expert/device: drops guaranteed
    y = moe_ffn(*ops, mesh, capacity_factor=0.25)
    jax.block_until_ready(y)
    snap = metrics.snapshot()
    assert snap["moe_router_steps_total"]["value"] == 1
    assert snap["moe_tokens_total"]["value"] == T
    assert snap["moe_expert_load_tokens"]["count"] == E
    assert snap["moe_dropped_token_frac"]["value"] > 0.0
    assert snap["moe_dropped_tokens_total"]["value"] > 0
    assert 0.0 < snap["moe_router_entropy"]["value"] <= np.log(E) + 1e-3
    # the rollup row renders from any dump carrying the snapshot
    from paddle_tpu.observability import export
    rows = export.moe_rows([{"label": "trainer", "metrics": snap}])
    assert len(rows) == 1 and rows[0]["tokens"] == T
    assert "trainer" in export.format_moe_table(rows)
    # flag off: no callback in the traced program at all
    FLAGS.moe_metrics = False
    try:
        metrics.zero_all()
        jax.block_until_ready(moe_ffn(*ops, mesh, capacity_factor=0.25))
        assert metrics.snapshot().get("moe_router_steps_total",
                                      {}).get("value", 0) == 0
    finally:
        FLAGS.moe_metrics = True


def test_trace_report_moe_rollup(tmp_path, capsys):
    """tools/trace_report.py --moe prints the registry-driven rollup
    from a process dump (ISSUE 15 rider; ROLLUPS registry row)."""
    from paddle_tpu.observability import metrics
    from paddle_tpu.parallel import moe_ffn

    mesh = make_mesh({"ep": 4}, devices=_cpu(4))
    rng = np.random.RandomState(0)
    metrics.zero_all()
    y = moe_ffn(jnp.asarray(rng.randn(16, 8).astype(np.float32)),
                jnp.asarray(rng.randn(8, 4).astype(np.float32)),
                jnp.asarray(rng.randn(4, 8, 16).astype(np.float32)),
                jnp.asarray(rng.randn(4, 16, 8).astype(np.float32)),
                mesh)
    jax.block_until_ready(y)
    dump = {"label": "moe_proc", "pid": 1, "spans": [],
            "metrics": metrics.snapshot()}
    path = tmp_path / "trace_moe_1.json"
    path.write_text(json.dumps(dump))
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import trace_report
    rc = trace_report.main([str(path), "--moe"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "moe rollup" in out and "moe_proc" in out


# ------------------------------------------------------ bench smoke

def test_longctx_bench_quick_smoke():
    """tools/longctx_bench.py --quick completes on the CPU backend and
    reports the full artifact schema: ring/baseline points, the parity
    pin, the skip counts, the HLO double-buffer inventory (ISSUE 15
    satellite; wired like serve_bench/pserver_bench smokes)."""
    env = dict(os.environ)
    env["LONGCTX_CHILD_TIMEOUT"] = "300"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "longctx_bench.py"),
         "--quick", "--seqs", "1024", "--steps", "1"],
        capture_output=True, text=True, timeout=560, env=env, cwd=REPO)
    assert proc.returncode == 0, (proc.stdout[-1500:],
                                  proc.stderr[-1500:])
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "longctx_bench" and rec["quick"] is True
    assert rec["ok"] is True
    pt = rec["points"][0]
    assert pt["ring"]["tokens_s"] > 0
    assert pt["ring"]["peak_rss_mb"] > 0
    assert pt["baseline"]["tokens_s"] > 0
    assert rec["parity"]["ok"] is True
    assert rec["parity"]["fwd_maxdiff"] <= 1e-5
    assert rec["skip"]["counts"] == list(range(1, rec["p"] + 1))
    assert rec["hlo"]["double_buffer_structure"] is True
    assert rec["hlo"]["causal_skip_structure"] is True
