"""Host-side memory discipline across long trainings (SURVEY §2.1 row
10 / memory_optimize subsumption): XLA buffer assignment owns device
memory, but the HOST scope must not grow either — the compiled path
keeps temporaries in the traced env and writes back only persistables,
so step count must not change the scope's var census."""
import numpy as np

import paddle_tpu.fluid as fluid


def test_scope_var_count_stable_over_steps(prog_scope, exe):
    main, startup, scope = prog_scope
    x = fluid.layers.data(name="x", shape=[16], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    h = fluid.layers.fc(x, size=32, act="relu")
    h2 = fluid.layers.fc(h, size=32, act="relu")
    pred = fluid.layers.fc(h2, size=1)
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    exe.run(startup)

    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(8, 16).astype(np.float32),
            "y": rng.randn(8, 1).astype(np.float32)}
    exe.run(main, feed=feed, fetch_list=[loss])
    baseline = len(scope.local_var_names())
    for _ in range(50):
        exe.run(main, feed=feed, fetch_list=[loss])
    after = len(scope.local_var_names())
    # temporaries live inside the jitted step, not the scope: fifty
    # steps add zero host vars (the memory_optimize guarantee the
    # transpiler shim documents as subsumed)
    assert after == baseline, (baseline, after)
    # and only persistables landed there at all
    names = set(scope.local_var_names())
    block = main.global_block()
    non_persist = [n for n in names
                   if n in block.vars and not block.vars[n].persistable]
    assert non_persist == [], non_persist


def test_num_iteration_per_drop_scope_bounds_growth():
    """ExecutionStrategy.num_iteration_per_drop_scope is REAL: a
    program whose interpreted/host tail writes non-persistable values
    into the scope stays bounded over 1k iterations because the PE
    erases them every N runs (the reference
    ScopeBufferedSSAGraphExecutor role,
    details/scope_buffered_ssa_graph_executor.cc)."""
    from paddle_tpu.core.scope import Scope

    main, startup = fluid.Program(), fluid.Program()
    scope = Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                x = fluid.layers.data(name="x", shape=[4],
                                      dtype="float32")
                y = fluid.layers.data(name="y", shape=[1],
                                      dtype="float32")
                pred = fluid.layers.fc(x, size=1)
                loss = fluid.layers.mean(
                    fluid.layers.square_error_cost(pred, y))
                fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
        fluid.Executor(fluid.CPUPlace()).run(startup)
        strat = fluid.ExecutionStrategy()
        strat.num_iteration_per_drop_scope = 10
        pe = fluid.ParallelExecutor(
            use_tpu=False, loss_name=loss.name, main_program=main,
            scope=scope, num_devices=1, exec_strategy=strat)
        rng = np.random.RandomState(0)
        feed = {"x": rng.randn(4, 4).astype(np.float32),
                "y": rng.randn(4, 1).astype(np.float32)}
        block = main.global_block()
        temp = next(n for n in block.vars
                    if not block.vars[n].persistable
                    and n not in feed and "tmp" in n)
        sizes = []
        for i in range(1000):
            pe.run(feed=feed, fetch_list=[loss.name])
            # simulate a host op leaving a non-persistable temp in the
            # scope each step (distinct payloads, same program var)
            scope.set(temp, np.full((64,), i, np.float32))
            scope.new_scope()  # and a kid step-scope
            sizes.append(len(scope.local_var_names()))
        # the census never exceeds baseline + the one leaked temp, and
        # the drop pass reclaims the temp and the kid scopes
        assert max(sizes) <= sizes[0] + 1, (sizes[0], max(sizes))
        assert len(scope._kids) <= 10
        leaked = [n for n in scope.local_var_names()
                  if n in block.vars and not block.vars[n].persistable
                  and n not in feed]
        # at most the current cycle's leak survives between drops
        assert len(leaked) <= 1, leaked
