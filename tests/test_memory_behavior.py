"""Host-side memory discipline across long trainings (SURVEY §2.1 row
10 / memory_optimize subsumption): XLA buffer assignment owns device
memory, but the HOST scope must not grow either — the compiled path
keeps temporaries in the traced env and writes back only persistables,
so step count must not change the scope's var census."""
import numpy as np

import paddle_tpu.fluid as fluid


def test_scope_var_count_stable_over_steps(prog_scope, exe):
    main, startup, scope = prog_scope
    x = fluid.layers.data(name="x", shape=[16], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    h = fluid.layers.fc(x, size=32, act="relu")
    h2 = fluid.layers.fc(h, size=32, act="relu")
    pred = fluid.layers.fc(h2, size=1)
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    exe.run(startup)

    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(8, 16).astype(np.float32),
            "y": rng.randn(8, 1).astype(np.float32)}
    exe.run(main, feed=feed, fetch_list=[loss])
    baseline = len(scope.local_var_names())
    for _ in range(50):
        exe.run(main, feed=feed, fetch_list=[loss])
    after = len(scope.local_var_names())
    # temporaries live inside the jitted step, not the scope: fifty
    # steps add zero host vars (the memory_optimize guarantee the
    # transpiler shim documents as subsumed)
    assert after == baseline, (baseline, after)
    # and only persistables landed there at all
    names = set(scope.local_var_names())
    block = main.global_block()
    non_persist = [n for n in names
                   if n in block.vars and not block.vars[n].persistable]
    assert non_persist == [], non_persist
