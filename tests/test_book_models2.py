"""The remaining book chapters end-to-end (reference book tests:
notest_understand_sentiment, test_recommender_system,
test_label_semantic_roles) on their dataset adapters' synthetic
fallbacks — each must genuinely train, not just run."""
import itertools

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu import dataset


def _batches(reader, batch_size):
    it = reader()
    while True:
        b = list(itertools.islice(it, batch_size))
        if len(b) < batch_size:
            return
        yield b


# --- builders (reused by tests/test_program_lint.py as the verifier's
# known-good corpus: build into the current default programs, no I/O) ---

def build_understand_sentiment_conv(dict_dim=200):
    from paddle_tpu.models.understand_sentiment import get_model
    return get_model(dict_dim=dict_dim, net="conv", learning_rate=0.05)


def build_understand_sentiment_dyn_rnn(dict_dim=200):
    from paddle_tpu.models.understand_sentiment import get_model
    return get_model(dict_dim=dict_dim, net="dyn_rnn", emb_dim=16,
                     hid_dim=32, learning_rate=0.05)


def build_resnet_cifar(depth=20):
    from paddle_tpu.models.resnet import resnet_cifar10
    images = fluid.layers.data(name="pixel", shape=[3, 32, 32],
                               dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    logits = resnet_cifar10(images, 10, depth=depth)
    loss = fluid.layers.mean(
        fluid.layers.cross_entropy(input=logits, label=label))
    acc = fluid.layers.accuracy(input=logits, label=label)
    fluid.optimizer.Adam(learning_rate=2e-3).minimize(loss)
    return loss, acc


def test_understand_sentiment_conv(prog_scope, exe):
    main, startup, scope = prog_scope
    word_dict = dataset.imdb.word_dict()
    loss, feeds, (acc,) = build_understand_sentiment_conv(
        dict_dim=len(word_dict))
    exe.run(startup)
    feeder = fluid.DataFeeder(feeds, program=main)
    train = dataset.imdb.train(word_dict)

    ls = []
    for _ in range(3):  # epochs over the synthetic corpus
        for batch in _batches(train, 32):
            batch = [(doc, [label]) for doc, label in batch]
            l, = exe.run(main, feed=feeder.feed(batch), fetch_list=[loss])
            ls.append(float(np.asarray(l).ravel()[0]))
    # class-conditional word distributions are separable: conv tower
    # must cut the initial ~0.693 binary cross-entropy roughly in half
    assert ls[-1] < 0.4, (ls[0], ls[-1])


def test_understand_sentiment_dyn_rnn(prog_scope, exe):
    main, startup, scope = prog_scope
    loss, feeds, _ = build_understand_sentiment_dyn_rnn()
    exe.run(startup)
    feeder = fluid.DataFeeder(feeds, program=main)
    rng = np.random.RandomState(5)
    ls = []
    for _ in range(40):
        batch = []
        for _ in range(16):
            y = int(rng.randint(0, 2))
            L = int(rng.randint(3, 10))
            toks = rng.randint(0, 100, L) + (100 if y else 0)
            batch.append((toks.tolist(), [y]))
        l, = exe.run(main, feed=feeder.feed(batch), fetch_list=[loss])
        ls.append(float(np.asarray(l).ravel()[0]))
    assert ls[-1] < 0.45, (ls[0], ls[-1])


def test_recommender_system(prog_scope, exe):
    from paddle_tpu.models.recommender import get_model
    main, startup, scope = prog_scope
    loss, feeds, _ = get_model(learning_rate=0.3)
    exe.run(startup)
    feeder = fluid.DataFeeder(feeds, program=main)

    epoch_means = []
    for _ in range(6):
        ls = []
        for batch in _batches(dataset.movielens.train(), 64):
            l, = exe.run(main, feed=feeder.feed(batch), fetch_list=[loss])
            ls.append(float(np.asarray(l).ravel()[0]))
        epoch_means.append(float(np.mean(ls)))
    # synthetic ratings follow the model's own cos-similarity form;
    # must beat predict-the-mean (~6.5 MSE on the +-5 scale) and keep
    # improving epoch over epoch
    assert epoch_means[-1] < epoch_means[0] * 0.85, epoch_means
    assert epoch_means[-1] < 6.2, epoch_means


def test_machine_translation_wmt14(prog_scope, exe):
    """Seq2seq-attention on the wmt14 adapter's permutation-cipher
    synthetic corpus (reference book test_machine_translation trains on
    the real wmt14)."""
    from paddle_tpu.models.machine_translation import get_model
    main, startup, scope = prog_scope
    dict_size = 80
    loss, feeds, _ = get_model(src_dict_dim=dict_size,
                               trg_dict_dim=dict_size, emb_dim=32,
                               hidden_dim=32, learning_rate=1e-2)
    exe.run(startup)
    feeder = fluid.DataFeeder(feeds, program=main)
    src_dict, trg_dict = dataset.wmt14.get_dict(dict_size)
    assert len(src_dict) == dict_size and src_dict[0] == "<s>"

    ls = []
    for _ in range(8):
        for batch in _batches(dataset.wmt14.train(dict_size), 16):
            l, = exe.run(main, feed=feeder.feed(batch), fetch_list=[loss])
            ls.append(float(np.asarray(l).ravel()[0]))
    # token-level cipher: cross-entropy must fall far below its
    # ln(dict_size)~4.4 start once attention locks on (~epoch 6)
    assert ls[-1] < ls[0] * 0.5, (ls[0], ls[-1])


def test_image_classification_resnet_cifar(prog_scope, exe):
    """The image_classification book chapter: resnet_cifar10 trained on
    the cifar adapter (reference book test_image_classification)."""
    main, startup, scope = prog_scope
    loss, acc = build_resnet_cifar(depth=20)
    exe.run(startup)

    samples = list(itertools.islice(dataset.cifar.train10()(), 64))
    xs = np.stack([np.asarray(s[0], np.float32).reshape(3, 32, 32)
                   for s in samples])
    ys = np.asarray([[s[1]] for s in samples], np.int64)
    ls = []
    for _ in range(15):
        l, a = exe.run(main, feed={"pixel": xs, "label": ys},
                       fetch_list=[loss, acc])
        ls.append(float(np.asarray(l).ravel()[0]))
    # 20-layer resnet must overfit 64 cifar images to ~zero loss
    assert ls[-1] < 0.1, (ls[0], ls[-1])
    assert float(np.asarray(a).ravel()[0]) > 0.95


def test_word2vec_imikolov(prog_scope, exe):
    """The reference 5-gram word2vec net on the imikolov adapter's
    Markov-chain synthetic corpus (reference book test_word2vec)."""
    from paddle_tpu.models.word2vec import get_model, N
    main, startup, scope = prog_scope
    word_dict = dataset.imikolov.build_dict()
    loss, feeds, _ = get_model(dict_size=len(word_dict),
                               hidden_size=64, learning_rate=0.3)
    exe.run(startup)
    feeder = fluid.DataFeeder(feeds, program=main)

    epoch_means = []
    for _ in range(2):
        ls = []
        for batch in _batches(dataset.imikolov.train(word_dict, N), 64):
            batch = [tuple([w] for w in gram) for gram in batch]
            l, = exe.run(main, feed=feeder.feed(batch), fetch_list=[loss])
            ls.append(float(np.asarray(l).ravel()[0]))
        epoch_means.append(float(np.mean(ls)))
    # the reference book test's own bar is just avg_cost < 5.8 (SGD is
    # glacial on this net — test_word2vec.py bails once under 5.8);
    # require dipping below the ln(203)=5.31 uniform start instead
    assert epoch_means[-1] < 5.2, epoch_means
    assert epoch_means[-1] < epoch_means[0], epoch_means


def test_label_semantic_roles(prog_scope, exe):
    from paddle_tpu.models.label_semantic_roles import get_model
    main, startup, scope = prog_scope
    word_dict, verb_dict, label_dict = dataset.conll05.get_dict()
    loss, feeds, (crf_decode,) = get_model(
        word_dict_len=len(word_dict), label_dict_len=len(label_dict),
        pred_dict_len=len(verb_dict), hidden_dim=64, depth=2,
        train_word_emb=True, learning_rate=0.1)
    exe.run(startup)
    feeder = fluid.DataFeeder(feeds, program=main)

    epoch_first, epoch_last = [], []
    for _ in range(3):
        ls = []
        for batch in _batches(dataset.conll05.test(), 16):
            l, = exe.run(main, feed=feeder.feed(batch), fetch_list=[loss])
            ls.append(float(np.asarray(l).ravel()[0]))
        assert np.isfinite(ls).all()
        epoch_first.append(ls[0])
        epoch_last.append(ls[-1])
    # per-sequence CRF NLL starts at ~len*ln(K)~31; it must fall hard
    # within the first epoch and keep improving across epochs (full
    # convergence takes hours even in the reference — not a unit test)
    assert epoch_last[0] < epoch_first[0] * 0.85, (epoch_first, epoch_last)
    assert epoch_last[-1] < epoch_last[0], (epoch_first, epoch_last)

    # decode path: predicted tags are valid label ids with plausible
    # agreement given the label/word correlation in the synthetic corpus
    batch = next(_batches(dataset.conll05.test(), 8))
    decoded, = exe.run(main, feed=feeder.feed(batch),
                       fetch_list=[crf_decode])
    decoded = np.asarray(decoded)
    assert decoded.min() >= 0 and decoded.max() < len(label_dict)


def test_alexnet_googlenet_build_and_step(prog_scope, exe):
    """The legacy-benchmark conv families build and take a finite train
    step (full 224x224 training runs on the accelerator via bench.py;
    one CPU step pins the graphs)."""
    from paddle_tpu.models import alexnet, googlenet
    rng = np.random.RandomState(0)
    feed = {"data": rng.rand(2, 3, 224, 224).astype(np.float32),
            "label": rng.randint(0, 102, (2, 1)).astype(np.int64)}
    for mod in (alexnet, googlenet):
        main, startup = fluid.Program(), fluid.Program()
        from paddle_tpu.core.scope import Scope
        scope = Scope()
        with fluid.scope_guard(scope):
            with fluid.program_guard(main, startup):
                with fluid.unique_name.guard():
                    loss, feeds, (acc,) = mod.get_model()
            exe.run(startup)
            pname = main.global_block().all_parameters()[0].name
            before = np.array(scope.find_var(pname), copy=True)
            l1, = exe.run(main, feed=feed, fetch_list=[loss])
            l2, = exe.run(main, feed=feed, fetch_list=[loss])
            a, b = (float(np.asarray(v).ravel()[0]) for v in (l1, l2))
            assert np.isfinite([a, b]).all()
            # loss-vs-loss is dropout-mask noise at bs2; the robust
            # signal that the momentum step ran is the weights moving
            after = np.asarray(scope.find_var(pname))
            assert not np.allclose(before, after)
