"""Inference transpiler BN-fold (reference transpiler/
inference_transpiler.py fuse_batch_norm) + memory_optimize API."""
import numpy as np

import paddle_tpu.fluid as fluid

layers = fluid.layers


def _build_convnet(with_bias):
    img = fluid.layers.data(name="img", shape=[3, 8, 8],
                            dtype="float32")
    conv = layers.conv2d(img, num_filters=4, filter_size=3, padding=1,
                         bias_attr=True if with_bias else False)
    bn = layers.batch_norm(conv, is_test=True)
    out = layers.relu(bn)
    return out


def _count_ops(program, type_):
    return sum(1 for op in program.desc.blocks[0].ops
               if op.type == type_)


def _run_fold(with_bias):
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                out = _build_convnet(with_bias)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        # make bn stats non-trivial so the fold actually moves numbers
        for op in main.desc.blocks[0].ops:
            if op.type == "batch_norm":
                rng = np.random.RandomState(1)
                scope.set(op.inputs["Mean"][0],
                          rng.randn(4).astype(np.float32) * 0.1)
                scope.set(op.inputs["Variance"][0],
                          (rng.rand(4) + 0.5).astype(np.float32))
                scope.set(op.inputs["Scale"][0],
                          (rng.rand(4) + 0.5).astype(np.float32))
                scope.set(op.inputs["Bias"][0],
                          rng.randn(4).astype(np.float32) * 0.1)
        xv = np.random.RandomState(0).rand(2, 3, 8, 8).astype(
            np.float32)
        before, = exe.run(main, feed={"img": xv}, fetch_list=[out])
        assert _count_ops(main, "batch_norm") == 1
        fluid.transpiler.InferenceTranspiler().transpile(main,
                                                         scope=scope)
        assert _count_ops(main, "batch_norm") == 0
        after, = exe.run(main, feed={"img": xv}, fetch_list=[out])
    np.testing.assert_allclose(np.asarray(after), np.asarray(before),
                               rtol=1e-4, atol=1e-5)


def test_bn_fold_with_conv_bias():
    _run_fold(with_bias=True)


def test_bn_fold_without_conv_bias():
    _run_fold(with_bias=False)


def test_bn_fold_skips_residual_add():
    """conv -> elementwise_add(conv_out, skip) -> bn is NOT a bias
    pattern; the transpiler must leave it (and the weights) untouched."""
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                img = fluid.layers.data(name="img", shape=[3, 8, 8],
                                        dtype="float32")
                conv = layers.conv2d(img, num_filters=3, filter_size=3,
                                     padding=1, bias_attr=False)
                merged = layers.elementwise_add(x=conv, y=img)
                out = layers.batch_norm(merged, is_test=True)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        w_name = [op.inputs["Filter"][0]
                  for op in main.desc.blocks[0].ops
                  if op.type == "conv2d"][0]
        w_before = np.asarray(scope.find_var(w_name)).copy()
        fluid.transpiler.InferenceTranspiler().transpile(main,
                                                         scope=scope)
        assert _count_ops(main, "batch_norm") == 1  # untouched
        np.testing.assert_array_equal(
            np.asarray(scope.find_var(w_name)), w_before)


def test_memory_optimize_liveness():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data(name="x", shape=[4], dtype="float32")
            h = layers.relu(layers.scale(x, scale=2.0))
            layers.mean(h)
    live = fluid.transpiler.memory_optimize(main)
    # every non-persistable temp has a [first, last] interval
    assert all(f <= l for f, l in live.values()) and live

def _build_attention(b, h, t, d, with_scale, name_prefix):
    """Plain-layer attention: matmul(QK^T)->[scale]->softmax->matmul.V
    on [B,H,T,D] data vars (what a saved transformer from the plain
    front-end looks like)."""
    q = layers.data(name=name_prefix + "q", shape=[h, t, d],
                    dtype="float32")
    k = layers.data(name=name_prefix + "k", shape=[h, t, d],
                    dtype="float32")
    v = layers.data(name=name_prefix + "v", shape=[h, t, d],
                    dtype="float32")
    scores = layers.matmul(q, k, transpose_y=True)
    if with_scale:
        scores = layers.scale(scores, scale=d ** -0.5)
    attn = layers.softmax(scores)
    out = layers.matmul(attn, v)
    # a consumer after the chain so the fused output is load-bearing
    return layers.scale(out, scale=2.0)


def _run_attention_fuse(with_scale, prefix):
    """Save a plain-layer attention program, LOAD it, transpile, assert
    the op rewrite AND output equality (round-3 VERDICT missing #3 —
    the reference's subgraph->engine analysis role,
    inference/analysis/subgraph_splitter.cc)."""
    import tempfile

    b, h, t, d = 2, 2, 8, 4
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                out = _build_attention(b, h, t, d, with_scale, prefix)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        model_dir = tempfile.mkdtemp()
        fluid.io.save_inference_model(
            model_dir, [prefix + "q", prefix + "k", prefix + "v"],
            [out], exe, main_program=main)

    # fresh load: the pass must work on a program parsed from disk
    load_scope = fluid.Scope()
    with fluid.scope_guard(load_scope):
        exe = fluid.Executor(fluid.CPUPlace())
        prog, feeds, fetches = fluid.io.load_inference_model(model_dir,
                                                             exe)
        rng = np.random.RandomState(0)
        feed = {prefix + n: rng.randn(b, h, t, d).astype(np.float32)
                for n in ("q", "k", "v")}
        before, = exe.run(prog, feed=feed, fetch_list=fetches)

        assert _count_ops(prog, "matmul") == 2
        n = fluid.transpiler.InferenceTranspiler().fuse_attention(prog)
        assert n == 1
        assert _count_ops(prog, "matmul") == 0
        assert _count_ops(prog, "softmax") == 0
        assert _count_ops(prog, "ring_attention") == 1
        after, = exe.run(prog, feed=feed, fetch_list=fetches)
    np.testing.assert_allclose(np.asarray(after), np.asarray(before),
                               rtol=2e-3, atol=2e-4)


def test_attention_fuse_with_scale():
    _run_attention_fuse(True, "as_")


def test_attention_fuse_bare_chain():
    """No scale op: the fused kernel must use scale=1.0, NOT the
    1/sqrt(D) flash default — output equality catches it."""
    _run_attention_fuse(False, "ab_")


def test_attention_fuse_skips_observed_scores():
    """If the softmax scores are fetched/consumed elsewhere, the chain
    must NOT fuse (the scores would disappear)."""
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                q = layers.data(name="oq", shape=[2, 8, 4],
                                dtype="float32")
                k = layers.data(name="ok", shape=[2, 8, 4],
                                dtype="float32")
                v = layers.data(name="ov", shape=[2, 8, 4],
                                dtype="float32")
                scores = layers.matmul(q, k, transpose_y=True)
                attn = layers.softmax(scores)
                out = layers.matmul(attn, v)
                # second consumer of the raw scores
                probe = layers.scale(scores, scale=3.0)
        n = fluid.transpiler.InferenceTranspiler().fuse_attention(main)
        assert n == 0
        assert _count_ops(main, "matmul") == 2


def test_attention_fuse_rejects_self_attention_v():
    """matmul(attn, attn) must NOT fuse: V would name a chain
    intermediate whose producer the fusion deletes."""
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                q = fluid.layers.data(name="sq", shape=[2, 8, 8],
                                      dtype="float32")
                k = fluid.layers.data(name="sk", shape=[2, 8, 8],
                                      dtype="float32")
                scores = layers.matmul(q, k, transpose_y=True)
                attn = layers.softmax(scores)
                out = layers.matmul(attn, attn)
        n = fluid.transpiler.InferenceTranspiler().fuse_attention(main)
        assert n == 0
        assert _count_ops(main, "softmax") == 1


def test_layer_norm_fuse_pass_output_equality(prog_scope, exe):
    """Third pass on the shared framework: the composed LN chain
    collapses to one layer_norm op with identical outputs."""
    main, startup, scope = prog_scope
    x = layers.data(name="ln_x", shape=[6], dtype="float32")
    m = layers.reduce_mean(x, dim=[1], keep_dim=True)
    d = layers.elementwise_sub(x, m)
    sq = layers.square(d)
    v = layers.reduce_mean(sq, dim=[1], keep_dim=True)
    ve = layers.scale(v, scale=1.0, bias=1e-5)
    std = layers.sqrt(ve)
    y = layers.elementwise_div(d, std)
    exe.run(startup)
    rng = np.random.RandomState(0)
    xv = rng.randn(4, 6).astype(np.float32)
    ref, = exe.run(main, feed={"ln_x": xv}, fetch_list=[y])

    infer = main.clone(for_test=True)
    t = fluid.transpiler.InferenceTranspiler()
    n = t.fuse_layer_norm(infer, scope=scope)
    assert n == 1
    types = [op.type for op in infer.desc.blocks[0].ops]
    assert "layer_norm" in types
    assert "elementwise_div" not in types
    # declared aux var descs agree with the lowering's runtime shapes
    # (ADVICE low: _layer_norm emits Mean/Variance as x.shape[:begin],
    # no trailing 1)
    blk = infer.desc.blocks[0]
    for nm in (y.name + "@ln_mean", y.name + "@ln_var"):
        assert tuple(blk.vars[nm].shape) == (-1,)
    got, = exe.run(infer, feed={"ln_x": xv}, fetch_list=[y.name])
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_attention_fuse_skips_persistable_intermediate(prog_scope, exe):
    """ADVICE r4: a persistable chain intermediate must block the
    fusion (a serving caller may fetch it by name)."""
    import paddle_tpu.fluid as fl
    main, startup, scope = prog_scope
    q = layers.data(name="pq", shape=[2, 4, 3], dtype="float32")
    k = layers.data(name="pk", shape=[2, 4, 3], dtype="float32")
    v = layers.data(name="pv", shape=[2, 4, 3], dtype="float32")
    s = layers.matmul(q, k, transpose_y=True, alpha=0.5)
    p = layers.softmax(s)
    out = layers.matmul(p, v)
    # mark the attention probabilities as persistable (observable)
    main.global_block().var(p.name).persistable = True
    infer = main.clone(for_test=True)
    t = fl.transpiler.InferenceTranspiler()
    assert t.fuse_attention(infer) == 0
    # non-persistable chain fuses, and the dead score var desc is gone
    infer2 = main.clone(for_test=True)
    infer2.global_block().var(p.name).persistable = False
    assert t.fuse_attention(infer2) == 1
    assert not infer2.desc.blocks[0].has_var(s.name)


def test_layer_norm_fuse_mul_spelling(prog_scope, exe):
    """The elementwise_mul(d, d) square spelling must fuse too (an op
    reading one var through two slots is ONE consumer in DefUse)."""
    main, startup, scope = prog_scope
    x = layers.data(name="lnm_x", shape=[5], dtype="float32")
    m = layers.reduce_mean(x, dim=[1], keep_dim=True)
    d = layers.elementwise_sub(x, m)
    sq = layers.elementwise_mul(d, d)
    v = layers.reduce_mean(sq, dim=[1], keep_dim=True)
    std = layers.sqrt(layers.scale(v, scale=1.0, bias=1e-5))
    y = layers.elementwise_div(d, std)
    exe.run(startup)
    xv = np.random.RandomState(1).randn(3, 5).astype(np.float32)
    ref, = exe.run(main, feed={"lnm_x": xv}, fetch_list=[y])
    infer = main.clone(for_test=True)
    assert fluid.transpiler.InferenceTranspiler().fuse_layer_norm(
        infer, scope=scope) == 1
    got, = exe.run(infer, feed={"lnm_x": xv}, fetch_list=[y.name])
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)
