"""Inference transpiler BN-fold (reference transpiler/
inference_transpiler.py fuse_batch_norm) + memory_optimize API."""
import numpy as np

import paddle_tpu.fluid as fluid

layers = fluid.layers


def _build_convnet(with_bias):
    img = fluid.layers.data(name="img", shape=[3, 8, 8],
                            dtype="float32")
    conv = layers.conv2d(img, num_filters=4, filter_size=3, padding=1,
                         bias_attr=True if with_bias else False)
    bn = layers.batch_norm(conv, is_test=True)
    out = layers.relu(bn)
    return out


def _count_ops(program, type_):
    return sum(1 for op in program.desc.blocks[0].ops
               if op.type == type_)


def _run_fold(with_bias):
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                out = _build_convnet(with_bias)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        # make bn stats non-trivial so the fold actually moves numbers
        for op in main.desc.blocks[0].ops:
            if op.type == "batch_norm":
                rng = np.random.RandomState(1)
                scope.set(op.inputs["Mean"][0],
                          rng.randn(4).astype(np.float32) * 0.1)
                scope.set(op.inputs["Variance"][0],
                          (rng.rand(4) + 0.5).astype(np.float32))
                scope.set(op.inputs["Scale"][0],
                          (rng.rand(4) + 0.5).astype(np.float32))
                scope.set(op.inputs["Bias"][0],
                          rng.randn(4).astype(np.float32) * 0.1)
        xv = np.random.RandomState(0).rand(2, 3, 8, 8).astype(
            np.float32)
        before, = exe.run(main, feed={"img": xv}, fetch_list=[out])
        assert _count_ops(main, "batch_norm") == 1
        fluid.transpiler.InferenceTranspiler().transpile(main,
                                                         scope=scope)
        assert _count_ops(main, "batch_norm") == 0
        after, = exe.run(main, feed={"img": xv}, fetch_list=[out])
    np.testing.assert_allclose(np.asarray(after), np.asarray(before),
                               rtol=1e-4, atol=1e-5)


def test_bn_fold_with_conv_bias():
    _run_fold(with_bias=True)


def test_bn_fold_without_conv_bias():
    _run_fold(with_bias=False)


def test_bn_fold_skips_residual_add():
    """conv -> elementwise_add(conv_out, skip) -> bn is NOT a bias
    pattern; the transpiler must leave it (and the weights) untouched."""
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                img = fluid.layers.data(name="img", shape=[3, 8, 8],
                                        dtype="float32")
                conv = layers.conv2d(img, num_filters=3, filter_size=3,
                                     padding=1, bias_attr=False)
                merged = layers.elementwise_add(x=conv, y=img)
                out = layers.batch_norm(merged, is_test=True)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        w_name = [op.inputs["Filter"][0]
                  for op in main.desc.blocks[0].ops
                  if op.type == "conv2d"][0]
        w_before = np.asarray(scope.find_var(w_name)).copy()
        fluid.transpiler.InferenceTranspiler().transpile(main,
                                                         scope=scope)
        assert _count_ops(main, "batch_norm") == 1  # untouched
        np.testing.assert_array_equal(
            np.asarray(scope.find_var(w_name)), w_before)


def test_memory_optimize_liveness():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data(name="x", shape=[4], dtype="float32")
            h = layers.relu(layers.scale(x, scale=2.0))
            layers.mean(h)
    live = fluid.transpiler.memory_optimize(main)
    # every non-persistable temp has a [first, last] interval
    assert all(f <= l for f, l in live.values()) and live