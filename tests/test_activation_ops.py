"""Activation op tests (cf. reference test_activation_op.py)."""
import numpy as np
import pytest

from op_test import OpTest

rng = np.random.RandomState(0)


def _sigmoid(x):
    return 1 / (1 + np.exp(-x))


CASES = {
    "relu": lambda x: np.maximum(x, 0),
    "sigmoid": _sigmoid,
    "tanh": np.tanh,
    "exp": np.exp,
    "log": lambda x: np.log(x),
    "sqrt": lambda x: np.sqrt(x),
    "square": np.square,
    "abs": np.abs,
    "reciprocal": lambda x: 1.0 / x,
    "softplus": lambda x: np.log1p(np.exp(x)),
    "softsign": lambda x: x / (1 + np.abs(x)),
    "logsigmoid": lambda x: np.log(_sigmoid(x)),
    "tanh_shrink": lambda x: x - np.tanh(x),
    "sin": np.sin,
    "cos": np.cos,
}

POSITIVE_ONLY = {"log", "sqrt", "reciprocal"}


@pytest.mark.parametrize("op_type", sorted(CASES))
def test_activation(op_type):
    if op_type in POSITIVE_ONLY:
        x = rng.uniform(0.5, 2.0, (3, 5)).astype(np.float32)
    else:
        x = rng.uniform(-1.5, 1.5, (3, 5)).astype(np.float32)
        x[np.abs(x) < 0.05] = 0.5  # keep away from kinks for numeric grad

    class T(OpTest):
        pass

    T.op_type = op_type
    T.inputs = {"X": x}
    T.outputs = {"Out": CASES[op_type](x.astype(np.float64)).astype(
        np.float32)}
    t = T()
    t.check_output(atol=1e-5)
    t.check_grad(["X"], max_relative_error=0.01)


def test_leaky_relu():
    x = rng.uniform(-2, 2, (3, 4)).astype(np.float32)
    x[np.abs(x) < 0.1] = 0.5

    class T(OpTest):
        op_type = "leaky_relu"
        inputs = {"X": x}
        attrs = {"alpha": 0.1}
        outputs = {"Out": np.where(x > 0, x, 0.1 * x)}

    T().check_output()
    T().check_grad(["X"])


def test_elu():
    x = rng.uniform(-2, 2, (3, 4)).astype(np.float32)
    x[np.abs(x) < 0.1] = 0.5

    class T(OpTest):
        op_type = "elu"
        inputs = {"X": x}
        attrs = {"alpha": 1.0}
        outputs = {"Out": np.where(x > 0, x, np.exp(np.minimum(x, 0)) - 1)
                   .astype(np.float32)}

    T().check_output()
    T().check_grad(["X"], max_relative_error=0.01)
