"""Numerics observatory (ISSUE 8): on-device tensor-health guards,
gradient telemetry, and first-bad-op forensics.

Pins the tentpole contracts:

- planted-overflow e2e: an fp32 model whose activation overflows at a
  KNOWN op (exp of a large pre-activation) — ``bisect`` must name
  exactly that op on BOTH the compiled run() path and the prepared
  one-dispatch path, leave a ``numerics_*.json`` flight artifact, and
  (prepared) restore the pre-step parameters for post-mortem;
- bit-exactness of ``metrics`` mode vs ``off``: the fused health
  reduction is an extra OUTPUT, never a change to the math — losses
  and params identical over 3 steps on run() AND prepared paths;
- guard-trip flight-dump schema golden;
- gradient telemetry feeding the always-on registry;
- the legacy FLAGS_check_nan_inf no longer refuses prepare() — it maps
  onto the guard+bisect machinery with the same first-bad-op answer;
- wire-corruption attribution: a NaN-poisoned gradient injected at a
  chosen sync round (FaultInjector ``corrupt``) leaves a pserver-side
  numerics artifact naming that round's cid and the sender
  (tools/fault_matrix.py --preset numerics drives this same test).
"""
import glob
import json
import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.core.flags import FLAGS
from paddle_tpu.core.scope import Scope
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.observability import numerics
from paddle_tpu.observability.numerics import NumericsError

CORRUPT_ROUND = 2  # keep in sync with tools/fault_matrix.py NUMERICS_ROUND


@pytest.fixture(autouse=True)
def _numerics_flags(tmp_path):
    """Every test runs with a private dump dir and restored flags."""
    saved = (FLAGS.check_numerics, FLAGS.check_numerics_every,
             FLAGS.check_nan_inf, FLAGS.telemetry_dump_dir)
    # normalize: each test states its own mode (the fault_matrix
    # preset exports FLAGS_check_numerics=guard process-wide)
    FLAGS.check_numerics = "off"
    FLAGS.check_numerics_every = 16
    FLAGS.check_nan_inf = False
    FLAGS.telemetry_dump_dir = str(tmp_path / "dumps")
    numerics.reset()
    yield
    (FLAGS.check_numerics, FLAGS.check_numerics_every,
     FLAGS.check_nan_inf, FLAGS.telemetry_dump_dir) = saved
    numerics.reset()


def _artifacts():
    return sorted(glob.glob(
        os.path.join(FLAGS.telemetry_dump_dir, "numerics_*.json")))


def _overflow_model(train=False):
    """exp() of a 300x-scaled pre-activation: with constant 0.1
    weights and an all-ones feed the fc output is 0.4, 300*0.4 = 120,
    and exp(120) overflows float32 -> inf AT THE EXP OP."""
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    h = fluid.layers.fc(x, size=4, param_attr=fluid.ParamAttr(
        name="w", initializer=fluid.initializer.ConstantInitializer(0.1)))
    bad = fluid.layers.exp(fluid.layers.scale(h, scale=300.0))
    loss = fluid.layers.mean(bad)
    if train:
        fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
    return loss


def _build(model_fn, **kw):
    main, startup = fluid.Program(), fluid.Program()
    scope = Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                loss = model_fn(**kw)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
    return main, scope, exe, loss


FEED = {"x": np.ones((2, 4), np.float32)}


# ---------------------------------------------------------------- bisect

def test_bisect_names_planted_overflow_op_on_run_path():
    main, scope, exe, loss = _build(_overflow_model)
    with fluid.scope_guard(scope):
        FLAGS.check_numerics = "bisect"
        with pytest.raises(NumericsError) as ei:
            exe.run(main, feed=FEED, fetch_list=[loss])
    e = ei.value
    assert e.op_type == "exp"
    assert "'exp'" in str(e)
    assert e.location["block"] == 0 and e.location["op_idx"] is not None
    # forensics artifact names the same op
    arts = _artifacts()
    assert arts, "bisect trip left no numerics_*.json"
    rec = json.loads(open(arts[0]).read())
    assert rec["kind"] == "numerics"
    assert rec["first_bad_op"]["type"] == "exp"
    assert rec["first_bad_op"]["inputs"]  # input stats recorded


def test_bisect_on_prepared_path_names_op_and_restores_state():
    main, scope, exe, loss = _build(_overflow_model, train=True)
    with fluid.scope_guard(scope):
        FLAGS.check_numerics = "bisect"
        prep = exe.prepare(main, feed_specs=FEED, fetch_list=[loss])
        w0 = np.array(np.asarray(scope.find_var("w")), copy=True)
        with pytest.raises(NumericsError) as ei:
            prep.run_prepared(FEED)
        assert ei.value.op_type == "exp"
        # the pre-step snapshot was restored: params are NOT poisoned
        # and NOT donated husks — post-mortem inspection works
        assert np.array_equal(w0, np.asarray(scope.find_var("w")))
    assert any("first_bad_op" in json.loads(open(p).read())
               for p in _artifacts())


def test_legacy_check_nan_inf_is_allowed_on_prepared_path():
    """PR 2 refused prepare() under FLAGS.check_nan_inf; the flag now
    maps onto the guard+bisect machinery and gives the reference
    answer (first bad op, by name) without giving up the one-dispatch
    step (MIGRATION.md)."""
    main, scope, exe, loss = _build(_overflow_model)
    with fluid.scope_guard(scope):
        FLAGS.check_nan_inf = True
        prep = exe.prepare(main, feed_specs=FEED,
                           fetch_list=[loss])  # must NOT raise
        with pytest.raises(FloatingPointError) as ei:
            prep.run_prepared(FEED)
    assert getattr(ei.value, "op_type", None) == "exp"


def test_bisect_run_path_trip_at_later_step_of_training_program():
    """Regression (review): from step 2 on, the scope's persistables
    ARE the arrays donated to the dispatch — a trip then must still
    produce the first-bad-op answer (pre-step snapshot, like the
    prepared path) and leave the scope holding LIVE pre-step values,
    not consumed husks."""
    main, scope, exe, loss = _build(_overflow_model, train=True)
    with fluid.scope_guard(scope):
        FLAGS.check_numerics = "bisect"
        # step 1: tiny feed, exp(300*0.004*4) stays finite; params
        # update in place (donation)
        exe.run(main, feed={"x": np.full((2, 4), 0.001, np.float32)},
                fetch_list=[loss])
        w1 = np.array(np.asarray(scope.find_var("w")), copy=True)
        # step 2: the planted overflow (large feed overwhelms the
        # bias shift step 1's update introduced)
        with pytest.raises(NumericsError) as ei:
            exe.run(main, feed={"x": np.full((2, 4), 10.0, np.float32)},
                    fetch_list=[loss])
        assert ei.value.op_type == "exp"
        # scope restored to pre-step-2 values, readable (live buffers)
        assert np.array_equal(w1, np.asarray(scope.find_var("w")))


def test_guard_run_path_trip_leaves_live_scope():
    """Guard mode (no snapshot): a trip at step 2 publishes the
    post-step values first — poisoned, but live and readable for
    post-mortem (never donated husks)."""
    main, scope, exe, loss = _build(_overflow_model, train=True)
    with fluid.scope_guard(scope):
        FLAGS.check_numerics = "guard"
        FLAGS.check_numerics_every = 1
        exe.run(main, feed={"x": np.full((2, 4), 0.001, np.float32)},
                fetch_list=[loss])
        with pytest.raises(NumericsError):
            exe.run(main, feed={"x": np.full((2, 4), 10.0, np.float32)},
                    fetch_list=[loss])
        np.asarray(scope.find_var("w"))  # must not raise 'deleted'


# ---------------------------------------------------------------- guard

def test_guard_trip_flight_dump_schema():
    main, scope, exe, loss = _build(_overflow_model)
    with fluid.scope_guard(scope):
        FLAGS.check_numerics = "guard"
        numerics.note_loss(1.25)  # recent-loss context rides the dump
        with pytest.raises(NumericsError) as ei:
            exe.run(main, feed=FEED, fetch_list=[loss])
    assert ei.value.flight_path and os.path.exists(ei.value.flight_path)
    rec = json.loads(open(ei.value.flight_path).read())
    # schema golden: the keys the tooling (trace_report --numerics,
    # fault_matrix) and humans rely on
    for key in ("kind", "reason", "wall_time", "pid", "mode", "losses",
                "site", "step", "trip_vars", "stats"):
        assert key in rec, key
    assert rec["kind"] == "numerics"
    assert rec["mode"] == "guard"
    assert rec["reason"].startswith("guard:")
    assert rec["losses"][-1] == 1.25
    assert rec["trip_vars"]
    tripped = rec["stats"][rec["trip_vars"][0]]
    assert tripped["finite"] == 0.0
    assert set(tripped) == set(numerics.STAT_FIELDS)


def test_off_mode_lets_nonfinite_flow():
    main, scope, exe, loss = _build(_overflow_model)
    with fluid.scope_guard(scope):
        out, = exe.run(main, feed=FEED, fetch_list=[loss])
    assert np.isinf(np.asarray(out)).all()
    assert _artifacts() == []


# --------------------------------------------------------------- metrics

def _healthy_model():
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    h = fluid.layers.fc(x, size=8, act="relu")
    loss = fluid.layers.mean(fluid.layers.fc(h, size=2))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return loss


def _train_steps(mode, prepared, steps=3):
    # build + startup under 'off' so the mode applies to exactly the
    # training steps (a startup run would otherwise contribute a
    # health check of its own)
    FLAGS.check_numerics = "off"
    main, scope, exe, loss = _build(_healthy_model)
    FLAGS.check_numerics = mode
    losses = []
    with fluid.scope_guard(scope):
        prep = exe.prepare(main, feed_specs=FEED, fetch_list=[loss]) \
            if prepared else None
        for i in range(steps):
            feed = {"x": np.full((2, 4), 1.0 + i, np.float32)}
            if prep is not None:
                out, = prep.run_prepared(feed)
            else:
                out, = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(np.array(np.asarray(out), copy=True))
        if prep is not None:
            prep.sync_scope()
        params = {n: np.array(np.asarray(scope.find_var(n)), copy=True)
                  for n in ("fc_0.w_0", "fc_0.b_0", "fc_1.w_0",
                            "fc_1.b_0")}
    return losses, params


@pytest.mark.parametrize("prepared", [False, True],
                         ids=["run", "prepared"])
def test_metrics_mode_is_bit_exact_with_off(prepared):
    """The health reduction is an extra OUTPUT of the step, never a
    change to its math: losses and params bitwise identical."""
    FLAGS.check_numerics_every = 1
    base_l, base_p = _train_steps("off", prepared)
    met_l, met_p = _train_steps("metrics", prepared)
    for a, b in zip(base_l, met_l):
        assert np.array_equal(a, b)
    for n in base_p:
        assert np.array_equal(base_p[n], met_p[n]), n


def test_metrics_mode_feeds_registry():
    obs_metrics.zero_all()
    FLAGS.check_numerics_every = 1
    _train_steps("metrics", True, steps=4)
    snap = obs_metrics.snapshot()
    assert snap["numerics_checks_total"]["value"] >= 4
    assert snap["grad_global_norm"]["count"] >= 4
    assert snap["grad_global_norm"]["p50"] > 0.0
    assert snap["param_absmax"]["value"] > 0.0
    assert snap["numerics_nonfinite_total"]["value"] == 0
    assert snap["numerics_trips_total"]["value"] == 0


def test_cadence_amortizes_health_dispatch():
    """With every=4, only steps 1, 4, 8, ... dispatch the health twin
    (the rest run the plain executable): checks_total counts exactly
    the cadence steps."""
    obs_metrics.zero_all()
    FLAGS.check_numerics_every = 4
    _train_steps("metrics", True, steps=8)
    snap = obs_metrics.snapshot()
    assert snap["numerics_checks_total"]["value"] == 3  # steps 1, 4, 8


# ------------------------------------------------- wire corruption e2e

def test_corrupt_round_is_attributed_to_sender_cid():
    """FaultInjector 'corrupt' poisons ONE wire gradient with NaN at
    round CORRUPT_ROUND; the pserver scatter health check writes a
    numerics artifact naming that round's cid and the sender — the
    contract tools/fault_matrix.py --preset numerics enforces."""
    from paddle_tpu.distributed.resilience import install_faults
    from paddle_tpu.distributed.rpc import RPCClient, VariableServer

    FLAGS.check_numerics = "guard"
    # tools/fault_matrix.py --preset numerics exports a dump dir and
    # asserts the corrupt-round artifact lands THERE; standalone runs
    # keep the fixture's private tmp dir
    env_dir = os.environ.get("FLAGS_telemetry_dump_dir")
    if env_dir:
        FLAGS.telemetry_dump_dir = env_dir
    install_faults("send_grad:corrupt:%d:1" % CORRUPT_ROUND)
    scope = Scope()
    scope.set("p1", np.zeros((8, 4), np.float32))

    def apply_block(bid):
        p = np.array(np.asarray(scope.find_var("p1")), copy=True)
        p -= np.asarray(scope.find_var("g1"))
        scope.set("p1", p)

    srv = VariableServer(scope, {"g1": 0}, apply_block, fanin=1,
                         grad_params={"g1": ("p1",)})
    port = srv.start("127.0.0.1:0")
    ep = "127.0.0.1:%d" % port
    RPCClient.reset()
    cli = RPCClient.instance()
    try:
        for _ in range(CORRUPT_ROUND + 2):
            cli.send_vars([(ep, "g1",
                            np.full((8, 4), 1.0, np.float32))])
            cli.send_barrier([ep])
            cli.get_vars([(ep, "p1")])
    finally:
        try:
            cli.send_complete([ep])
            srv.wait()
        finally:
            install_faults("")
            RPCClient.reset()
    arts = _artifacts()
    assert arts, "poisoned round left no numerics artifact"
    recs = [json.loads(open(p).read()) for p in arts]
    hit = [r for r in recs if r.get("cid") == "round:%d" % CORRUPT_ROUND]
    assert hit, [r.get("cid") for r in recs]
    assert hit[0]["site"] == "pserver.scatter"
    assert hit[0]["sender"]
    assert hit[0]["stats"]["nan"] == 1  # exactly one poisoned element
    assert obs_metrics.snapshot()[
        "pserver_nonfinite_grads_total"]["value"] >= 1


def test_corrupt_rule_poisons_copy_not_caller_buffer():
    from paddle_tpu.distributed.resilience import FaultInjector

    inj = FaultInjector("send_grad:corrupt:3:1")
    arr = np.ones((4,), np.float32)
    out = inj.maybe_corrupt("send_grad", 3, arr)
    assert np.isnan(out[0]) and not np.isnan(arr).any()
    # limit exhausted: second call passes through
    again = inj.maybe_corrupt("send_grad", 3, arr)
    assert not np.isnan(again).any()
    # wrong round / wrong point: untouched
    inj2 = FaultInjector("send_grad:corrupt:3:1")
    assert not np.isnan(
        inj2.maybe_corrupt("send_grad", 2, arr)).any()
    assert not np.isnan(
        inj2.maybe_corrupt("get_param", 3, arr)).any()


# ------------------------------------------------------------- tooling

def test_trace_report_numerics_rollup(tmp_path, capsys):
    """trace_report --numerics prints the grad-norm rollup from a
    trace dump and summarizes numerics trip artifacts."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import trace_report

    obs_metrics.zero_all()
    FLAGS.check_numerics_every = 1
    _train_steps("metrics", True, steps=3)
    from paddle_tpu.observability.trace import Tracer
    dump = str(tmp_path / "trace_t0.json")
    t = Tracer(enabled=True)
    t.set_label("trainer0")
    t.end(t.begin("step.prepared"))  # one span so the report has rows
    t.dump(dump)
    trip = str(tmp_path / "numerics_1_1.json")
    with open(trip, "w") as f:
        json.dump({"kind": "numerics", "reason": "guard:test",
                   "cid": "round:7", "trip_vars": ["w"],
                   "losses": [1.0, 2.0]}, f)
    rc = trace_report.main([dump, trip, "--numerics"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "numerics rollup" in out
    assert "trainer0" in out
    assert "numerics trip artifacts" in out and "round:7" in out
