"""Unified telemetry layer (ISSUE 6): span nesting + ring eviction,
disabled-path no-op (zero allocations), Prometheus/JSON metric exports,
executor step spans, cross-process (round, sender, seq) correlation on
a 2-trainer x 2-pserver localhost run, flight-recorder dumps on
injected WatchdogTimeout, the profiler rebase, and the < 2% hot-path
overhead gate."""
import glob
import json
import multiprocessing as mp
import os
import socket
import sys
import time

import numpy as np
import pytest

from paddle_tpu.core.flags import FLAGS
from paddle_tpu.observability import export, metrics, trace
from paddle_tpu.observability.trace import TRACER

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------- spans

def test_span_nesting_and_ring_eviction():
    tr = trace.Tracer(ring_size=4, enabled=True)
    with tr.span("outer"):
        with tr.span("inner"):
            pass
    done = tr.completed()
    assert [s["name"] for s in done] == ["inner", "outer"]
    by = {s["name"]: s for s in done}
    assert by["outer"]["depth"] == 0
    assert by["inner"]["depth"] == 1
    assert by["inner"]["ts_us"] >= by["outer"]["ts_us"]
    # ring eviction: only the newest ring_size spans survive
    for i in range(10):
        tr.end(tr.begin("s%d" % i))
    names = [s["name"] for s in tr.completed()]
    assert names == ["s6", "s7", "s8", "s9"]
    # limit= slices BEFORE dict conversion (the flight recorder's
    # signal-handler bound) and keeps the newest
    assert [s["name"] for s in tr.completed(limit=2)] == ["s8", "s9"]
    assert [s["name"] for s in tr.completed(limit=99)] == names


def test_open_spans_visible_and_unbalanced_end():
    tr = trace.Tracer(ring_size=16, enabled=True)
    outer = tr.begin("blocked.here", cid="round:7")
    tr.begin("child")   # never ended — an exception unwound past it
    open_ = tr.open_spans()
    assert {s["name"] for s in open_} == {"blocked.here", "child"}
    assert any(s.get("cid") == "round:7" for s in open_)
    tr.end(outer)       # pops back through the orphaned child
    assert tr.open_spans() == []
    assert tr.completed()[-1]["name"] == "blocked.here"


def test_disabled_path_is_noop_and_allocation_free():
    assert not TRACER.on
    # warm: the probe's counter object and code paths exist already
    trace.disabled_step_probe(2000)
    before = sys.getallocatedblocks()
    trace.disabled_step_probe(20000)
    after = sys.getallocatedblocks()
    # counted-steps microbench: the disabled path must not allocate
    # (small tolerance for interpreter-internal churn)
    assert abs(after - before) < 32, (before, after)
    assert TRACER.completed() is not None  # and recorded no spans for it


def test_runtime_flag_flip_reaches_tracer():
    """`FLAGS.telemetry = True` set programmatically (not just env at
    import) must actually enable tracing — and the ring resizes when
    FLAGS_telemetry_ring_size is assigned."""
    assert not TRACER.on
    old_ring = int(FLAGS.telemetry_ring_size)
    try:
        FLAGS.telemetry = True
        assert TRACER.on
        TRACER.end(TRACER.begin("flag.flip"))
        assert any(s["name"] == "flag.flip" for s in TRACER.completed())
        FLAGS.telemetry_ring_size = 8
        assert TRACER._ring.maxlen == 8
    finally:
        FLAGS.telemetry = False
        FLAGS.telemetry_ring_size = old_ring
    assert not TRACER.on
    assert TRACER._ring.maxlen == old_ring


def test_flight_dump_from_signal_mid_observe(tmp_path):
    """A signal landing on the thread that is INSIDE Histogram.observe
    (lock held) must still produce a dump, not deadlock — the metric
    locks are reentrant for exactly this."""
    import signal

    h = metrics.histogram("t_unit_sig_ms")
    from paddle_tpu.observability import flight

    got = {}

    def handler(signum, frame):
        got["path"] = flight.dump("signal:test",
                                  directory=str(tmp_path))

    prev = signal.signal(signal.SIGALRM, handler)
    try:
        with h._lock:           # simulate: interrupted mid-observe
            signal.raise_signal(signal.SIGALRM)
        assert got["path"] and os.path.exists(got["path"])
    finally:
        signal.signal(signal.SIGALRM, prev)


def test_span_decorator_and_correlation_id():
    calls = []

    @trace.traced("deco.site", lambda x: {"x": x})
    def fn(x):
        calls.append(x)
        return x * 2

    assert fn(3) == 6          # disabled: pure passthrough
    TRACER.clear()
    TRACER.enable()
    try:
        assert fn(4) == 8
    finally:
        TRACER.disable()
    spans = TRACER.completed()
    assert any(s["name"] == "deco.site" and s["args"] == {"x": 4}
               for s in spans)
    assert trace.round_cid(12) == "round:12"


# -------------------------------------------------------------- metrics

def test_metrics_prometheus_and_json_export():
    c = metrics.counter("t_unit_requests_total", "unit-test counter")
    c.zero()
    c.inc()
    c.inc(2)
    g = metrics.gauge("t_unit_depth", "unit-test gauge")
    g.set(1.5)
    h = metrics.histogram("t_unit_lat_ms", "unit-test histogram",
                          bounds=(1.0, 10.0, 100.0))
    h.zero()
    for v in (0.5, 2.0, 2.0, 50.0, 200.0):
        h.observe(v)

    text = metrics.prometheus_text()
    assert "# TYPE t_unit_requests_total counter" in text
    assert "t_unit_requests_total 3" in text
    assert "# TYPE t_unit_depth gauge" in text
    assert "t_unit_depth 1.5" in text
    assert "# TYPE t_unit_lat_ms histogram" in text
    # cumulative buckets: le=1 -> 1, le=10 -> 3, le=100 -> 4, +Inf -> 5
    assert 't_unit_lat_ms_bucket{le="1"} 1' in text
    assert 't_unit_lat_ms_bucket{le="10"} 3' in text
    assert 't_unit_lat_ms_bucket{le="100"} 4' in text
    assert 't_unit_lat_ms_bucket{le="+Inf"} 5' in text
    assert "t_unit_lat_ms_count 5" in text

    # full precision for large counters: '%g'-style 6-significant-digit
    # rounding would freeze a byte counter between scrapes
    big = metrics.counter("t_unit_bytes_total")
    big.zero()
    big.inc(123456789)
    assert "t_unit_bytes_total 123456789" in metrics.prometheus_text()

    snap = metrics.snapshot()
    assert snap["t_unit_requests_total"]["value"] == 3
    assert snap["t_unit_lat_ms"]["count"] == 5
    assert snap["t_unit_lat_ms"]["p50"] == 2.0
    assert snap["t_unit_lat_ms"]["p99"] == 200.0
    assert h.percentile(50) == 2.0
    # same name re-registration returns the same object; kind clash dies
    assert metrics.counter("t_unit_requests_total") is c
    with pytest.raises(TypeError):
        metrics.gauge("t_unit_requests_total")


# ---------------------------------------------------- executor coverage

def test_executor_step_spans_and_counters():
    import paddle_tpu.fluid as fluid

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[8], dtype="float32")
            loss = fluid.layers.mean(fluid.layers.fc(x, size=4))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        feed = {"x": np.ones((2, 8), np.float32)}
        steps0 = metrics.counter("executor_steps_total").value
        h = metrics.histogram("step_wall_ms")
        hn0 = h.count
        TRACER.clear()
        TRACER.enable()
        try:
            exe.run(main, feed=feed, fetch_list=[loss])
            prep = exe.prepare(main, feed_specs=feed,
                               fetch_list=[loss])
            for _ in range(2):
                prep.run_prepared(feed)
            prep.sync_scope()
        finally:
            TRACER.disable()
    names = {s["name"] for s in TRACER.completed()}
    assert {"executor.run", "executor.dispatch", "step.prepared",
            "step.feed", "step.dispatch",
            "step.sync_scope"} <= names
    assert metrics.counter("executor_steps_total").value >= steps0 + 3
    assert h.count >= hn0 + 3  # run + 2 prepared steps observed


def test_sub_block_runs_are_not_steps(monkeypatch):
    # a pserver's listen_and_serv applies each shard's optimize block
    # via ExecutorCore.run(block_id=N) — those must not land in the
    # step counter / step_wall_ms histogram (they'd report shard-apply
    # time as the process's step stats)
    from paddle_tpu.core.executor_impl import ExecutorCore
    import paddle_tpu.fluid as fluid

    monkeypatch.setattr(ExecutorCore, "_run_impl",
                        lambda self, *a, **kw: [])
    core = fluid.Executor(fluid.CPUPlace())._core
    desc = fluid.Program().desc
    steps = metrics.counter("executor_steps_total")
    h = metrics.histogram("step_wall_ms")
    for enabled in (False, True):
        (TRACER.enable if enabled else TRACER.disable)()
        try:
            s0, h0 = steps.value, h.count
            core.run(desc, None, block_id=3)
            assert (steps.value, h.count) == (s0, h0)
            core.run(desc, None, block_id=0)
            assert steps.value == s0 + 1
            assert h.count == (h0 + 1 if enabled else h0)
        finally:
            TRACER.disable()


# ------------------------------------------------------- export + tools

def _make_dump(tmp_path, label, spans, pid):
    path = tmp_path / ("trace_%s_%d.json" % (label, pid))
    path.write_text(json.dumps({
        "label": label, "pid": pid, "spans": spans, "open_spans": [],
        "metrics": {}}))
    return str(path)


def test_export_merge_and_phase_report(tmp_path, capsys):
    t0 = 1000.0
    d1 = _make_dump(tmp_path, "trainer0", [
        {"name": "rpc.send_vars", "ts_us": t0, "dur_us": 500.0,
         "tid": 1, "cid": "round:0"},
        {"name": "step.dispatch", "ts_us": t0 + 600, "dur_us": 100.0,
         "tid": 1},
    ], pid=11)
    d2 = _make_dump(tmp_path, "pserver", [
        {"name": "pserver.apply_round", "ts_us": t0 + 200,
         "dur_us": 300.0, "tid": 2, "cid": "round:0"},
    ], pid=22)
    out = str(tmp_path / "merged.json")
    trace_dict, dumps = export.merge_files([d1, d2], out_path=out)
    assert os.path.exists(out)
    evs = [e for e in trace_dict["traceEvents"] if e.get("ph") == "X"]
    with_cid = [e for e in evs
                if (e.get("args") or {}).get("cid") == "round:0"]
    assert {e["pid"] for e in with_cid} == {11, 22}
    # process names carried through
    names = {e["args"]["name"] for e in trace_dict["traceEvents"]
             if e.get("ph") == "M"}
    assert {"trainer0", "pserver"} <= names
    rows = export.phase_rows(dumps)
    assert rows[0]["name"] == "rpc.send_vars"  # largest total first
    assert rows[0]["total_ms"] == 0.5

    # the CLI prints the per-phase table and writes a merge
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import trace_report
    finally:
        sys.path.pop(0)
    rc = trace_report.main([d1, d2, "--merge",
                            str(tmp_path / "m2.json")])
    assert rc == 0
    printed = capsys.readouterr().out
    assert "rpc.send_vars" in printed and "pserver.apply_round" in printed
    assert "total_ms" in printed
    assert os.path.exists(tmp_path / "m2.json")


def test_kernel_rollup_groups_launch_sites_and_device_ops(tmp_path,
                                                          capsys):
    """ISSUE 7 satellite: the per-kernel rollup groups pallas.*
    launch-site spans by kernel name and xplane device events by their
    normalized op family, and the trace_report CLI prints it."""
    d1 = _make_dump(tmp_path, "trainer0", [
        {"name": "pallas.matmul_fused", "ts_us": 0.0, "dur_us": 1000.0,
         "tid": 1},
        {"name": "pallas.matmul_fused", "ts_us": 5.0, "dur_us": 3000.0,
         "tid": 1},
        {"name": "pallas.flash_attention", "ts_us": 9.0,
         "dur_us": 500.0, "tid": 1},
        {"name": "step.dispatch", "ts_us": 20.0, "dur_us": 400.0,
         "tid": 1},
    ], pid=31)
    dumps = [export.load_dump(d1)]
    trace = {"traceEvents": [
        {"name": "%fusion.123", "cat": "device", "ph": "X", "ts": 0,
         "dur": 2000},
        {"name": "%fusion.7", "cat": "device", "ph": "X", "ts": 1,
         "dur": 1000},
        {"name": "jit__matmul_kernel.3", "cat": "device", "ph": "X",
         "ts": 2, "dur": 500},
    ]}
    rows = export.kernel_rows(dumps, trace)
    by = {(r["kernel"], r["side"]): r for r in rows}
    assert by[("matmul_fused", "host")]["count"] == 2
    assert by[("matmul_fused", "host")]["total_ms"] == 4.0
    assert by[("flash_attention", "host")]["count"] == 1
    assert by[("fusion", "device")]["count"] == 2
    assert by[("fusion", "device")]["total_ms"] == 3.0
    assert by[("jit__matmul_kernel", "device")]["count"] == 1
    # non-pallas host spans stay out of the kernel rollup
    assert ("step.dispatch", "host") not in by
    # CLI prints the rollup table whenever kernel rows exist
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import trace_report
    finally:
        sys.path.pop(0)
    rc = trace_report.main([d1])
    assert rc == 0
    printed = capsys.readouterr().out
    assert "per-kernel rollup" in printed
    assert "matmul_fused" in printed


# ------------------------------------------- cross-process correlation

def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_cross_process_round_correlation(tmp_path):
    """2 trainers x 2 pservers on localhost with FLAGS_telemetry on:
    every process dumps its trace, and the merged timeline correlates
    trainer send/barrier/get spans with the pserver scatter/apply spans
    of the same round via the shared cid (acceptance criterion)."""
    import dist_train_helpers as H

    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    env = {"FLAGS_telemetry": "1",
           "FLAGS_telemetry_dump_dir": str(tmp_path)}
    ctx = mp.get_context("spawn")
    eps = ["127.0.0.1:%d" % _free_port() for _ in range(2)]
    pservers = ",".join(eps)
    steps = 3

    ps_procs = [ctx.Process(target=H.run_pserver,
                            args=(ep, pservers, 2, "softmax", True, env))
                for ep in eps]
    for p in ps_procs:
        p.start()
    q = ctx.Queue()
    tr_procs = [ctx.Process(target=H.run_trainer,
                            args=(tid, pservers, 2, steps, q, "softmax",
                                  True, env))
                for tid in range(2)]
    for p in tr_procs:
        p.start()
    for _ in range(2):
        q.get(timeout=240)
    for p in tr_procs + ps_procs:
        p.join(timeout=60)
        if p.is_alive():
            p.terminate()
            pytest.fail("worker did not exit")

    dump_paths = sorted(glob.glob(str(tmp_path / "trace_*.json")))
    assert len(dump_paths) == 4, dump_paths
    dumps = [export.load_dump(p) for p in dump_paths]
    trainer_dumps = [d for d in dumps if d["label"].startswith("trainer")]
    pserver_dumps = [d for d in dumps if d["label"].startswith("pserver")]
    assert len(trainer_dumps) == 2 and len(pserver_dumps) == 2

    def cids(dump, prefix):
        return {s["cid"] for s in dump["spans"]
                if s.get("cid") and s["name"].startswith(prefix)}

    # acceptance: trainer send/get spans and pserver apply spans of the
    # same round share a correlation id, across every process pair
    for td in trainer_dumps:
        send_cids = cids(td, "rpc.send_vars")
        get_cids = cids(td, "rpc.get_vars")
        assert trace.round_cid(0) in send_cids
        assert send_cids & get_cids, (send_cids, get_cids)
        for pd in pserver_dumps:
            apply_cids = cids(pd, "pserver.apply_round")
            scatter_cids = cids(pd, "pserver.scatter")
            assert send_cids & apply_cids, (td["label"], pd["label"])
            assert send_cids & scatter_cids
    # pserver rounds metric rode the dump
    for pd in pserver_dumps:
        applied = pd["metrics"]["pserver_rounds_applied_total"]["value"]
        assert applied >= steps
    # and the merge produces ONE chrome trace whose correlated events
    # span trainer and pserver pids
    merged, _ = export.merge_files(dump_paths,
                                   out_path=str(tmp_path / "merged.json"))
    cid0 = trace.round_cid(0)
    pids = {e["pid"] for e in merged["traceEvents"]
            if (e.get("args") or {}).get("cid") == cid0}
    assert len(pids) >= 3  # 2 trainers + at least one pserver


# ------------------------------------------------------ flight recorder

def test_flight_recorder_on_injected_watchdog(tmp_path):
    from paddle_tpu.distributed.resilience import (WatchdogTimeout,
                                                   watchdog_error)

    old = FLAGS.telemetry_dump_dir
    FLAGS.telemetry_dump_dir = str(tmp_path)
    try:
        TRACER.enable()
        blocked_span = TRACER.begin("op.recv", cid="round:5")
        err = watchdog_error(
            "recv", ["127.0.0.1:6174"],
            lambda ep: {"applied_round": 4, "barriers": 1, "alive": 2,
                        "known": ["trainer0", "trainer1"],
                        "waiting_for": ["trainer1"]})
        TRACER.end(blocked_span)
    finally:
        TRACER.disable()
        FLAGS.telemetry_dump_dir = old
    assert isinstance(err, WatchdogTimeout)
    # the dump path is attached to the raised error message
    assert "flight recorder:" in str(err)
    assert err.flight_path and os.path.exists(err.flight_path)
    rec = json.loads(open(err.flight_path).read())
    assert rec["reason"] == "watchdog:recv"
    # names the blocked op and the missing peer
    assert rec["blocked"]["op"] == "recv"
    assert "trainer1" in json.dumps(rec["blocked"]["details"])
    # and the open span the process was blocked in
    assert any(s["name"] == "op.recv" and s.get("cid") == "round:5"
               for s in rec["open_spans"])
    assert "executor_steps_total" in rec["metrics"]


def test_flight_recorder_on_injected_fault(tmp_path):
    from paddle_tpu.distributed import resilience

    old = FLAGS.telemetry_dump_dir
    FLAGS.telemetry_dump_dir = str(tmp_path)
    try:
        inj = resilience.install_faults("t_point:drop:1.0:1")
        with pytest.raises(resilience.InjectedFault):
            resilience.fault_point("t_point")
        assert inj.stats["t_point"] == 1
    finally:
        FLAGS.telemetry_dump_dir = old
        resilience.install_faults("")
    dumps = glob.glob(str(tmp_path / "flight_*.json"))
    assert dumps, "injected fault left no flight artifact"
    rec = json.loads(open(dumps[0]).read())
    assert rec["reason"] == "fault:t_point"


# ------------------------------------------------------ profiler rebase

def test_profiler_api_backed_by_telemetry(tmp_path, capsys):
    from paddle_tpu.fluid import profiler

    path = str(tmp_path / "prof")
    was_on = TRACER.on
    with profiler.profiler(state="CPU", sorted_key="total",
                           profile_path=path):
        with profiler.RecordEvent("my_event"):
            time.sleep(0.002)
        with profiler.RecordEvent("my_event"):
            pass
    assert TRACER.on == was_on  # session restored the tracer state
    out = capsys.readouterr().out
    assert "my_event" in out and "Calls" in out
    data = json.loads(open(path).read())
    evs = [e for e in data["traceEvents"] if e["name"] == "my_event"]
    assert len(evs) == 2
    assert evs[0]["dur"] > 0


def test_profiler_events_are_bounded():
    """The old module-level events list grew without bound; events now
    live in the tracer ring (FLAGS_telemetry_ring_size)."""
    from paddle_tpu.fluid import profiler

    ring = int(FLAGS.telemetry_ring_size)
    profiler.start_profiler("CPU")
    try:
        for i in range(ring + 100):
            with profiler.RecordEvent("bounded"):
                pass
        assert len(TRACER.completed()) <= ring
    finally:
        profiler.stop_profiler(profile_path=None)


# --------------------------------------------------------- overhead gate

def test_instrumented_disabled_hot_path_under_two_percent():
    """CI satellite: tools/telemetry_overhead.py gate, in-process."""
    os.environ.setdefault("TELEMETRY_OVERHEAD_STEPS", "150")
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import telemetry_overhead
    finally:
        sys.path.pop(0)
    assert not TRACER.on
    assert telemetry_overhead.main([]) == 0
