"""Native C serving path (reference paddle/capi): a pure-C program
links libpaddle_tpu_capi.so, loads a saved (AOT-exported) model and
serves it — outputs must match the in-process Python predictor."""
import os
import subprocess

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.core.scope import Scope

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _save_model(dirname, n, d):
    main, startup = fluid.Program(), fluid.Program()
    scope = Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                x = fluid.layers.data(name="x", shape=[d],
                                      dtype="float32")
                h = fluid.layers.fc(x, size=6, act="tanh")
                out = fluid.layers.fc(h, size=3, act="softmax")
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        fluid.io.save_inference_model(
            dirname, ["x"], [out], exe, main_program=main,
            aot_feed_specs={"x": ((n, d), "float32")})
        xs = (0.01 * np.arange(n * d, dtype=np.float32)).reshape(n, d)
        infer = main.clone(for_test=True)
        ref, = exe.run(infer, feed={"x": xs}, fetch_list=[out])
    return np.asarray(ref)


@pytest.fixture(scope="module")
def capi_binary(tmp_path_factory):
    from paddle_tpu import capi

    lib = capi.build()
    exe_path = str(tmp_path_factory.mktemp("capi") / "capi_main")
    src = os.path.join(REPO, "tests", "capi_main.c")
    cmd = ["g++", "-O2", "-o", exe_path, src,
           "-I" + os.path.dirname(capi.header_path()),
           lib, "-Wl,-rpath," + os.path.dirname(lib)]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    return exe_path


@pytest.mark.parametrize("mode", ["predictor", "server"])
def test_c_program_serves_model(tmp_path, capi_binary, mode):
    """mode 'predictor': the classic pd_create_predictor path.  mode
    'server' (ISSUE 9 rider): the same C contract routed through
    pd_create_server — the continuous-batching serving tier's
    in-process API — closing the reference paddle_inference_api.h
    role gap."""
    n, d = 4, 5
    model_dir = str(tmp_path / "model")
    ref = _save_model(model_dir, n, d)

    env = dict(os.environ)
    env.pop("PYTHONPATH", None)  # repo path goes through pd_init
    # the embedded interpreter has no accelerator plugin on its path;
    # serve on host CPU (use_accelerator=0 in the C program too)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [capi_binary, REPO, model_dir, "x", str(n), str(d), mode],
        capture_output=True, text=True, timeout=240, env=env, cwd=REPO)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    got = np.asarray([float(v) for v in
                      proc.stdout.strip().split(",")], np.float32)
    np.testing.assert_allclose(got.reshape(ref.shape), ref, atol=1e-5)
