"""Multi-host data parallelism: ParallelExecutor(num_trainers=2) over
jax.distributed — the reference's "nccl2 mode"
(parallel_executor.cc:84-95, platform/nccl_helper.h:81,
operators/gen_nccl_id_op.cc).

Two spawned localhost processes x 4 forced host devices each join one
collective world through the PADDLE_TRAINER_ENDPOINTS env contract
(distributed/collective.py — the gen_nccl_id analog); each feeds its
local half of a fixed global batch.  Losses must match a single-process
8-device SPMD run of the same program bit-for-bit-ish (gloo float
reductions: 1e-5)."""
import multiprocessing as mp
import os
import socket

import numpy as np
import pytest

# ISSUE 7 triage: this rig's jax builds its CPU PjRt client without
# multiprocess collective support — every cross-process computation
# dies with XlaRuntimeError("Multiprocess computations aren't
# implemented on the CPU backend"), an environment property, not a
# repo regression.  Non-strict so a rig whose jax ships the gloo CPU
# collectives (or a real chip) reports XPASS and the marks can come
# off.
pytestmark = pytest.mark.xfail(
    reason="jax CPU backend on this rig lacks multiprocess "
           "collectives (XlaRuntimeError: Multiprocess computations "
           "aren't implemented on the CPU backend)",
    strict=False)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _child_env:
    """Temporarily mutate os.environ so spawned children are BORN with
    the right platform config (sitecustomize touches jax at interpreter
    start, before worker code can set env)."""

    def __init__(self, **kv):
        self.kv = kv
        self.saved = {}

    def __enter__(self):
        for k, v in self.kv.items():
            self.saved[k] = os.environ.get(k)
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    def __exit__(self, *exc):
        for k, old in self.saved.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old


@pytest.mark.timeout(300)
def test_two_process_pe_matches_single_process():
    from tests import multihost_helpers as H

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = []

    with _child_env(JAX_PLATFORMS="cpu",
                    XLA_FLAGS="--xla_force_host_platform_device_count=8",
                    PALLAS_AXON_POOL_IPS=None,
                    PADDLE_TRAINER_ENDPOINTS=None,
                    PADDLE_TRAINER_ID=None):
        procs.append(ctx.Process(target=H.baseline_worker, args=(q,)))
        procs[-1].start()

    port = _free_port()
    eps = "127.0.0.1:%d,127.0.0.1:%d" % (port, port + 1)
    for i in range(2):
        with _child_env(
                JAX_PLATFORMS="cpu",
                XLA_FLAGS="--xla_force_host_platform_device_count=4",
                PALLAS_AXON_POOL_IPS=None,
                PADDLE_TRAINER_ENDPOINTS=eps,
                PADDLE_TRAINER_ID=str(i)):
            procs.append(ctx.Process(target=H.trainer_worker, args=(i, q)))
            procs[-1].start()

    try:
        results = {}
        for _ in range(3):
            tag, losses, ndev = q.get(timeout=240)
            results[tag] = (losses, ndev)
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()

    for tag, (losses, _) in results.items():
        assert not isinstance(losses, str), (tag, losses)

    base, nb = results["baseline"]
    assert nb == 8
    # both trainers saw the union of devices (the bootstrap smoke:
    # init_collective_env really joined one world)
    assert results["trainer0"][1] == 8
    assert results["trainer1"][1] == 8
    # identical loss trajectory: same global batch, same deterministic
    # init, psum-of-local == global mean
    t0, t1 = results["trainer0"][0], results["trainer1"][0]
    assert np.allclose(t0, t1, atol=1e-6), (t0, t1)
    assert np.allclose(base, t0, atol=1e-5), (base, t0)
    # and training actually trains
    assert base[-1] < base[0]


@pytest.mark.timeout(300)
def test_two_process_pe_with_tensor_parallel_params():
    """dp=2 x tp=4 mesh spanning two processes: TENSOR-PARALLEL weight
    shards cross the host boundary — each process materializes its
    addressable shards from the full deterministic init
    (executor_impl._put global-value semantics).  Losses must match a
    single-process run of the same mesh."""
    from tests import multihost_helpers as H

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = []

    with _child_env(JAX_PLATFORMS="cpu",
                    XLA_FLAGS="--xla_force_host_platform_device_count=8",
                    PALLAS_AXON_POOL_IPS=None,
                    PADDLE_TRAINER_ENDPOINTS=None,
                    PADDLE_TRAINER_ID=None):
        procs.append(ctx.Process(target=H.baseline_worker_tp, args=(q,)))
        procs[-1].start()

    port = _free_port()
    eps = "127.0.0.1:%d,127.0.0.1:%d" % (port, port + 1)
    for i in range(2):
        with _child_env(
                JAX_PLATFORMS="cpu",
                XLA_FLAGS="--xla_force_host_platform_device_count=4",
                PALLAS_AXON_POOL_IPS=None,
                PADDLE_TRAINER_ENDPOINTS=eps,
                PADDLE_TRAINER_ID=str(i)):
            procs.append(ctx.Process(target=H.trainer_worker_tp,
                                     args=(i, q)))
            procs[-1].start()

    try:
        results = {}
        for _ in range(3):
            tag, losses, ndev = q.get(timeout=240)
            results[tag] = (losses, ndev)
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()

    for tag, (losses, _) in results.items():
        assert not isinstance(losses, str), (tag, losses)
    base = results["tpbase"][0]
    t0, t1 = results["tp0"][0], results["tp1"][0]
    assert np.allclose(t0, t1, atol=1e-6), (t0, t1)
    assert np.allclose(base, t0, atol=1e-5), (base, t0)


@pytest.mark.timeout(300)
def test_two_process_pe_with_reader_chain(tmp_path):
    """Each trainer reads its own recordio shard through program-level
    reader ops; the global loss is the mean over BOTH shards — wrong
    (halved/duplicated) assembly of the scope-resident batches would
    change the value."""
    import paddle_tpu.fluid as fluid

    data_dir = str(tmp_path)
    vals = {}
    for i in range(2):
        rows = np.full((8, 4), float(i + 1), np.float32)  # shard i: i+1
        def reader(rows=rows):
            for r in rows:
                yield (r,)
        fluid.recordio_writer.convert_reader_to_recordio_file(
            "%s/shard%d.recordio" % (data_dir, i), reader)
        vals[i] = rows.mean()
    expect = (vals[0] + vals[1]) / 2.0  # 1.5

    from tests import multihost_helpers as H

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = []
    port = _free_port()
    eps = "127.0.0.1:%d,127.0.0.1:%d" % (port, port + 1)
    for i in range(2):
        with _child_env(
                JAX_PLATFORMS="cpu",
                XLA_FLAGS="--xla_force_host_platform_device_count=4",
                PALLAS_AXON_POOL_IPS=None,
                PADDLE_TRAINER_ENDPOINTS=eps,
                PADDLE_TRAINER_ID=str(i)):
            procs.append(ctx.Process(target=H.trainer_worker_reader,
                                     args=(i, q, data_dir)))
            procs[-1].start()
    try:
        results = {}
        for _ in range(2):
            tag, val, ndev = q.get(timeout=240)
            results[tag] = (val, ndev)
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()
    for tag, (val, _) in results.items():
        assert not isinstance(val, str), (tag, val)
    assert abs(results["reader0"][0] - expect) < 1e-6, results
    assert abs(results["reader1"][0] - expect) < 1e-6, results
