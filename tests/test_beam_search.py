"""Beam search (reference operators/beam_search_op.cc,
beam_search_decode_op.cc, book/test_machine_translation.py decode
program): per-step selection semantics, and a full While-loop decode
program where beam=2 provably beats greedy on a garden-path LM."""
import numpy as np

import paddle_tpu.fluid as fluid

layers = fluid.layers

END = 0


def test_beam_search_step_semantics(prog_scope, exe):
    """One step, N=1 sentences x B=2 beams, K=3 candidates."""
    main, startup, scope = prog_scope
    pre_ids = layers.data(name="pre_ids", shape=[1], dtype="int64",
                          append_batch_size=False)
    pre_scores = layers.data(name="pre_scores", shape=[1],
                             dtype="float32", append_batch_size=False)
    ids = layers.data(name="ids", shape=[3], dtype="int64",
                      append_batch_size=False)
    scores = layers.data(name="scores", shape=[3], dtype="float32",
                         append_batch_size=False)
    sel_ids, sel_scores, parent = layers.beam_search(
        pre_ids, pre_scores, ids, scores, beam_size=2, end_id=END)
    exe.run(startup)
    # beam 0 alive (pre_id=5), beam 1 finished (pre_id=END, score -0.1)
    out = exe.run(main, feed={
        "pre_ids": np.asarray([[5], [END]], np.int64),
        "pre_scores": np.asarray([[-0.5], [-0.1]], np.float32),
        "ids": np.asarray([[7, 8, END], [1, 2, 3]], np.int64),
        "scores": np.asarray([[-0.6, -0.9, -2.0],
                              [-9.0, -9.0, -9.0]], np.float32),
    }, fetch_list=[sel_ids, sel_scores, parent])
    got_ids, got_scores, got_parent = [np.asarray(o) for o in out]
    # candidates: beam0 -> (7,-0.6) (8,-0.9) (END,-2.0); beam1 frozen
    # -> (END,-0.1).  top-2 overall: (END,-0.1) from beam1, (7,-0.6).
    assert got_ids.reshape(-1).tolist() == [END, 7]
    np.testing.assert_allclose(got_scores.reshape(-1), [-0.1, -0.6],
                               rtol=1e-6)
    assert got_parent.tolist() == [1, 0]


def _build_decode(beam_size, max_len=4, vocab=5):
    """While-loop decode over a fixed transition table (the reference
    machine_translation decode program shape, states = log-prob rows)."""
    counter = layers.fill_constant(shape=[1], dtype="int64", value=0)
    limit = layers.fill_constant(shape=[1], dtype="int64", value=max_len)
    nb = beam_size  # N=1 sentence

    init_ids = layers.fill_constant(shape=[nb, 1], dtype="int64", value=1)
    # only beam 0 is live at t=0 so beams diverge from one start token
    init_scores = layers.assign(
        np.asarray([[0.0]] + [[-1e9]] * (nb - 1), np.float32))

    ids_arr = layers.array_write(init_ids, i=counter, capacity=max_len + 1)
    sc_arr = layers.array_write(init_scores, i=counter,
                                capacity=max_len + 1)
    par_arr = layers.array_write(
        layers.assign(np.zeros((nb,), np.int32)), i=counter,
        capacity=max_len + 1)

    cond = layers.less_than(x=counter, y=limit)
    w = layers.While(cond=cond)
    with w.block():
        pre_ids = layers.array_read(ids_arr, i=counter)
        pre_scores = layers.array_read(sc_arr, i=counter)
        # "model": log-prob of next token = table row of pre_id
        logp = layers.embedding(
            pre_ids, size=[vocab, vocab],
            param_attr=fluid.ParamAttr(name="table"))
        logp = layers.reshape(logp, [nb, vocab])
        accu = layers.elementwise_add(x=logp, y=pre_scores)
        cand_scores, cand_ids = layers.topk(accu, k=vocab - 1)
        sel_ids, sel_scores, parent = layers.beam_search(
            pre_ids, pre_scores, cand_ids, cand_scores,
            beam_size=beam_size, end_id=END)
        layers.increment(x=counter, value=1, in_place=True)
        layers.array_write(sel_ids, i=counter, array=ids_arr)
        layers.array_write(sel_scores, i=counter, array=sc_arr)
        layers.array_write(parent, i=counter, array=par_arr)
        layers.less_than(x=counter, y=limit, cond=cond)

    return layers.beam_search_decode(ids_arr, sc_arr, par_arr,
                                     beam_size, END)


def _table():
    """Garden-path transitions: greedy 1->2 then 2's best continuation
    is weak; 1->3->END has higher total probability."""
    t = np.full((5, 5), -1e9, np.float32)
    t[1, 2] = np.log(0.6)
    t[1, 3] = np.log(0.4)
    t[2, 4] = np.log(0.55)
    t[2, END] = np.log(0.45)
    t[4, END] = 0.0              # log 1.0
    t[3, END] = 0.0
    t[END, END] = 0.0            # harmless: finished beams are frozen
    return t


def _run_decode(beam_size):
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                sent_ids, sent_scores = _build_decode(beam_size)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        scope.set("table", _table())
        ids, scores = exe.run(main,
                              fetch_list=[sent_ids, sent_scores])
    return np.asarray(ids), np.asarray(scores)


def test_beam_beats_greedy_on_garden_path():
    # sequences include the start token (step 0's array entry)
    # greedy (beam 1): 1 -> 2 -> 4 -> END, logp = log(0.6*0.55)
    g_ids, g_scores = _run_decode(1)
    assert g_ids[0, 0].tolist()[:4] == [1, 2, 4, END]
    np.testing.assert_allclose(g_scores[0, 0], np.log(0.6 * 0.55),
                               rtol=1e-5)
    # beam 2 recovers the delayed-reward path: 1 -> 3 -> END, logp=log 0.4
    b_ids, b_scores = _run_decode(2)
    assert b_ids[0, 0].tolist()[:3] == [1, 3, END]
    np.testing.assert_allclose(b_scores[0, 0], np.log(0.4), rtol=1e-5)
    assert b_scores[0, 0] > g_scores[0, 0]
    # runner-up beam is exactly the greedy path
    assert b_ids[0, 1].tolist()[:4] == [1, 2, 4, END]
