"""mul / matmul tests (cf. reference test_mul_op.py, test_matmul_op.py)."""
import numpy as np

from op_test import OpTest

rng = np.random.RandomState(3)


def test_mul_2d():
    x = rng.randn(4, 5).astype(np.float32)
    y = rng.randn(5, 3).astype(np.float32)

    class T(OpTest):
        op_type = "mul"
        inputs = {"X": x, "Y": y}
        attrs = {"x_num_col_dims": 1, "y_num_col_dims": 1}
        outputs = {"Out": x @ y}

    T().check_output()
    T().check_grad(["X", "Y"])


def test_mul_flatten():
    x = rng.randn(2, 3, 4).astype(np.float32)
    y = rng.randn(12, 5).astype(np.float32)

    class T(OpTest):
        op_type = "mul"
        inputs = {"X": x, "Y": y}
        attrs = {"x_num_col_dims": 1, "y_num_col_dims": 1}
        outputs = {"Out": x.reshape(2, 12) @ y}

    T().check_output()
    T().check_grad(["X", "Y"])


def test_matmul_transpose():
    x = rng.randn(3, 4).astype(np.float32)
    y = rng.randn(5, 4).astype(np.float32)

    class T(OpTest):
        op_type = "matmul"
        inputs = {"X": x, "Y": y}
        attrs = {"transpose_X": False, "transpose_Y": True}
        outputs = {"Out": x @ y.T}

    T().check_output()
    T().check_grad(["X", "Y"])


def test_matmul_batched():
    x = rng.randn(2, 3, 4).astype(np.float32)
    y = rng.randn(2, 4, 5).astype(np.float32)

    class T(OpTest):
        op_type = "matmul"
        inputs = {"X": x, "Y": y}
        outputs = {"Out": np.matmul(x, y)}

    T().check_output()
    T().check_grad(["X", "Y"])
