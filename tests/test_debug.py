"""Debug subsystem: FLAGS registry (gflags analog), per-op nan/inf
detection naming the culprit op (reference FLAGS_check_nan_inf,
framework/operator.cc:590), and graphviz/pseudo-code program dumps
(reference python/paddle/fluid/debuger.py)."""
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu.fluid as fluid


def _nan_model():
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    h = fluid.layers.fc(x, size=4, act="relu",
                        param_attr=fluid.ParamAttr(
                            name="w", initializer=fluid.initializer.
                            ConstantInitializer(0.1)))
    # log(relu(h) - big) -> log of a negative number -> nan, at THIS op
    shifted = fluid.layers.scale(h, scale=1.0, bias=-100.0)
    bad = fluid.layers.log(shifted)
    loss = fluid.layers.mean(bad)
    return loss


def test_check_nan_inf_names_the_op():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                loss = _nan_model()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        xv = np.ones((2, 4), np.float32)
        # without the flag: nan flows to the fetch silently
        out, = exe.run(main, feed={"x": xv}, fetch_list=[loss])
        assert np.isnan(np.asarray(out)).all()
        fluid.FLAGS.check_nan_inf = True
        try:
            with pytest.raises(FloatingPointError) as ei:
                exe.run(main, feed={"x": xv}, fetch_list=[loss])
        finally:
            fluid.FLAGS.check_nan_inf = False
        # the first nan-producing op is 'log', not the downstream mean
        assert "'log'" in str(ei.value)


def test_host_ops_run_once_in_interpreted_mode(capsys, tmp_path):
    """Interpreted path (forced by check_nan_inf) must not double-run
    head/tail host ops — e.g. a double-send would desync a pserver."""
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                x = fluid.layers.data(name="x", shape=[2],
                                      dtype="float32")
                y = fluid.layers.scale(x, scale=2.0)
                fluid.layers.Print(y, message="tailprint")
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        for flag in (False, True):
            fluid.FLAGS.check_nan_inf = flag
            try:
                capsys.readouterr()
                exe.run(main, feed={"x": np.ones((1, 2), np.float32)},
                        fetch_list=[y])
            finally:
                fluid.FLAGS.check_nan_inf = False
            printed = capsys.readouterr().out
            assert printed.count("tailprint") == 1, (flag, printed)


def test_flags_benchmark_prints(capsys):
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                x = fluid.layers.data(name="x", shape=[2],
                                      dtype="float32")
                y = fluid.layers.scale(x, scale=2.0)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        fluid.FLAGS.benchmark = True
        try:
            exe.run(main, feed={"x": np.ones((1, 2), np.float32)},
                    fetch_list=[y])
        finally:
            fluid.FLAGS.benchmark = False
        assert "[benchmark] block 0 ran in" in capsys.readouterr().err


def test_flags_env_forwarding():
    code = ("import paddle_tpu.fluid as fluid; "
            "print(fluid.FLAGS.check_nan_inf, fluid.FLAGS.benchmark)")
    env = dict(os.environ, FLAGS_check_nan_inf="true",
               JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True)
    assert out.stdout.strip() == "True False", out.stderr


def test_flags_unknown_raises():
    with pytest.raises(AttributeError):
        fluid.FLAGS.not_a_flag
    with pytest.raises(AttributeError):
        fluid.FLAGS.also_not_a_flag = 1
    fluid.define_flag("custom_test_flag", 7)
    assert fluid.FLAGS.custom_test_flag == 7


def test_program_dumps():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            _nan_model()
    text = fluid.debugger.pprint_program(main)
    assert "mul(" in text and "block_0" in text
    dot = fluid.debugger.draw_block_graphviz(main.global_block())
    assert dot.startswith("digraph G {") and dot.rstrip().endswith("}")
    assert '[label="mul"' in dot
    assert "fillcolor=\"lightgrey\"" in dot  # parameter shading
    # every edge endpoint is a declared node
    import re
    nodes = set(re.findall(r"^\s{2}(\w+) \[", dot, re.M))
    for a, b in re.findall(r"^\s{2}(\w+) -> (\w+);", dot, re.M):
        assert a in nodes and b in nodes
