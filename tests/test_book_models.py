"""Book-style end-to-end model tests (cf. reference tests/book/):
fit_a_line, recognize_digits (mlp + conv), word2vec-style embeddings —
each trained a few iterations with loss-decrease assertions.

The ``build_*`` functions append the model to the CURRENT default
main/startup programs and return the fetch targets; they are reused by
tests/test_program_lint.py as the verifier's known-good corpus, so keep
them pure builders (no running, no feeding)."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid


def build_fit_a_line():
    x = fluid.layers.data(name="x", shape=[13], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    y_predict = fluid.layers.fc(input=x, size=1, act=None)
    cost = fluid.layers.square_error_cost(input=y_predict, label=y)
    avg_cost = fluid.layers.mean(cost)
    fluid.optimizer.SGD(learning_rate=0.01).minimize(avg_cost)
    return avg_cost


def build_recognize_digits_mlp():
    img = fluid.layers.data(name="img", shape=[784], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    hidden = fluid.layers.fc(img, size=64, act="relu")
    prediction = fluid.layers.fc(hidden, size=10, act="softmax")
    cost = fluid.layers.cross_entropy(input=prediction, label=label)
    avg_cost = fluid.layers.mean(cost)
    acc = fluid.layers.accuracy(input=prediction, label=label)
    fluid.optimizer.Adam(learning_rate=0.01).minimize(avg_cost)
    return avg_cost, acc


def build_recognize_digits_conv():
    img = fluid.layers.data(name="img", shape=[1, 28, 28], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    conv = fluid.nets.simple_img_conv_pool(img, 8, 5, 2, 2, act="relu")
    prediction = fluid.layers.fc(conv, size=10, act="softmax")
    avg_cost = fluid.layers.mean(
        fluid.layers.cross_entropy(input=prediction, label=label))
    fluid.optimizer.Adam(learning_rate=0.01).minimize(avg_cost)
    return avg_cost


def build_word2vec_embeddings(dict_size=50, emb_size=16):
    """N-gram LM with shared embedding tables (reference book/word2vec)."""
    embs = []
    for i in range(3):
        w = fluid.layers.data(name="w%d" % i, shape=[1], dtype="int64")
        embs.append(fluid.layers.embedding(
            w, size=[dict_size, emb_size],
            param_attr=fluid.ParamAttr(name="shared_emb")))
    concat = fluid.layers.concat(embs, axis=1)
    hidden = fluid.layers.fc(concat, size=32, act="relu")
    predict = fluid.layers.fc(hidden, size=dict_size, act="softmax")
    next_w = fluid.layers.data(name="next_w", shape=[1], dtype="int64")
    avg_cost = fluid.layers.mean(
        fluid.layers.cross_entropy(input=predict, label=next_w))
    fluid.optimizer.Adam(learning_rate=0.01).minimize(avg_cost)
    return avg_cost


def test_fit_a_line(prog_scope, exe):
    main, startup, scope = prog_scope
    np.random.seed(0)
    avg_cost = build_fit_a_line()
    exe.run(startup)
    true_w = np.random.randn(13, 1).astype(np.float32)
    losses = []
    for _ in range(80):
        xs = np.random.randn(32, 13).astype(np.float32)
        ys = xs @ true_w
        loss, = exe.run(main, feed={"x": xs, "y": ys},
                        fetch_list=[avg_cost])
        losses.append(float(loss[0]))
    assert losses[-1] < losses[0] * 0.2, (losses[0], losses[-1])


def test_recognize_digits_mlp(prog_scope, exe):
    main, startup, scope = prog_scope
    np.random.seed(1)
    avg_cost, acc = build_recognize_digits_mlp()
    exe.run(startup)
    losses = []
    for i in range(80):
        ys = np.random.randint(0, 10, (32, 1)).astype(np.int64)
        xs = np.zeros((32, 784), np.float32)
        xs[np.arange(32), ys[:, 0] * 78] = 1.0  # separable signal
        loss, a = exe.run(main, feed={"img": xs, "label": ys},
                          fetch_list=[avg_cost, acc])
        losses.append(float(loss[0]))
    assert losses[-1] < losses[0] * 0.5
    assert float(a[0]) > 0.9


def test_recognize_digits_conv(prog_scope, exe):
    main, startup, scope = prog_scope
    np.random.seed(2)
    avg_cost = build_recognize_digits_conv()
    exe.run(startup)
    losses = []
    for i in range(25):
        ys = np.random.randint(0, 10, (16, 1)).astype(np.int64)
        xs = np.zeros((16, 1, 28, 28), np.float32)
        for j, c in enumerate(ys[:, 0]):
            xs[j, 0, c * 2: c * 2 + 2, :] = 1.0
        loss, = exe.run(main, feed={"img": xs, "label": ys},
                        fetch_list=[avg_cost])
        losses.append(float(loss[0]))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_word2vec_embeddings(prog_scope, exe):
    main, startup, scope = prog_scope
    np.random.seed(3)
    dict_size = 50
    avg_cost = build_word2vec_embeddings(dict_size=dict_size)
    exe.run(startup)
    losses = []
    for _ in range(30):
        seq = np.random.randint(0, dict_size - 4, (24, 1)).astype(np.int64)
        feed = {"w0": seq, "w1": seq + 1, "w2": seq + 2,
                "next_w": seq + 3}  # deterministic successor pattern
        loss, = exe.run(main, feed=feed, fetch_list=[avg_cost])
        losses.append(float(loss[0]))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])
    # the shared table must have received summed grads from 3 lookups
    assert any("shared_emb" == n for n in scope.local_var_names())
