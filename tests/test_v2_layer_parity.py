"""v2 layer-surface parity against the reference name list.

Reference python/paddle/trainer_config_helpers/layers.py:1 ``__all__``
(118 names, vendored below verbatim) exposed under the v2 naming rule
of reference python/paddle/v2/layer.py:56 ``__convert_name__``.  Every
converted name must exist on paddle_tpu.v2.layer and either build a
working topology (exercised by the behavior tests below) or raise the
documented NotImplementedError pointer (the MIGRATION.md refusal
contract) — never a bare AttributeError.
"""
import numpy as np
import pytest

import paddle_tpu.v2 as paddle
from paddle_tpu.v2 import layer as L

# --- reference trainer_config_helpers/layers.py __all__ (verbatim) ---
REFERENCE_ALL = [
    "full_matrix_projection", "AggregateLevel", "ExpandLevel",
    "identity_projection", "dotmul_projection", "dotmul_operator",
    "repeat_layer", "seq_reshape_layer", "table_projection", "mixed_layer",
    "data_layer", "embedding_layer", "fc_layer", "grumemory",
    "pooling_layer", "lstmemory", "last_seq", "first_seq", "cos_sim",
    "l2_distance_layer", "hsigmoid", "conv_projection", "square_error_cost",
    "regression_cost", "classification_cost", "LayerOutput",
    "img_conv_layer", "img_pool_layer", "batch_norm_layer",
    "img_cmrnorm_layer", "addto_layer", "concat_layer", "seq_concat_layer",
    "lstm_step_layer", "recurrent_group", "memory", "StaticInput",
    "expand_layer", "scaling_layer", "scaling_projection", "power_layer",
    "interpolation_layer", "bilinear_interp_layer", "trans_layer",
    "rotate_layer", "sum_to_one_norm_layer", "row_l2_norm_layer",
    "get_output_layer", "LayerType", "context_projection", "beam_search",
    "maxid_layer", "GeneratedInput", "SubsequenceInput", "gru_step_layer",
    "gru_step_naive_layer", "recurrent_layer", "BaseGeneratedInput",
    "conv_operator", "conv_shift_layer", "tensor_layer",
    "selective_fc_layer", "sampling_id_layer", "slope_intercept_layer",
    "trans_full_matrix_projection", "linear_comb_layer",
    "convex_comb_layer", "ctc_layer", "warp_ctc_layer", "crf_layer",
    "crf_decoding_layer", "nce_layer", "cross_entropy_with_selfnorm",
    "cross_entropy", "BeamInput", "cross_entropy_over_beam",
    "multi_binary_label_cross_entropy", "sum_cost", "rank_cost",
    "lambda_cost", "huber_regression_cost", "huber_classification_cost",
    "block_expand_layer", "maxout_layer", "dot_prod_layer",
    "out_prod_layer", "printer_layer", "print_layer", "priorbox_layer",
    "cross_channel_norm_layer", "multibox_loss_layer",
    "detection_output_layer", "roi_pool_layer", "spp_layer", "pad_layer",
    "eos_layer", "smooth_l1_cost", "layer_support", "multiplex_layer",
    "row_conv_layer", "dropout_layer", "prelu_layer", "switch_order_layer",
    "gated_unit_layer", "crop_layer", "sub_nested_seq_layer", "clip_layer",
    "slice_projection", "seq_slice_layer", "kmax_seq_score_layer",
    "img_pool3d_layer", "scale_shift_layer", "img_conv3d_layer",
    "resize_layer", "sub_seq_layer", "scale_sub_region_layer",
    "upsample_layer", "factorization_machine",
]


def convert_name(inname):
    """Reference python/paddle/v2/layer.py:56 __convert_name__."""
    keep = {"StaticInput", "SubsequenceInput", "GeneratedInput",
            "LayerType", "layer_support", "BaseGeneratedInput"}
    if inname in keep:
        return inname
    if inname == "maxid_layer":
        return "max_id"
    if (inname.endswith("memory") or inname.endswith("_seq")
            or inname.endswith("_sim") or inname == "hsigmoid"):
        return inname
    if inname in ("cross_entropy", "multi_binary_label_cross_entropy",
                  "cross_entropy_with_selfnorm"):
        return inname + "_cost"
    if inname.endswith("_cost"):
        return inname
    if inname.endswith("_layer"):
        return inname[:-len("_layer")]
    return inname


# Names whose reference semantics are documented refusals: calling them
# raises NotImplementedError pointing at the fluid carrier (the
# MIGRATION.md "v2 layer coverage" contract).
REFUSALS = {
    "get_output", "cross_entropy_over_beam",
    "SubsequenceInput",
}


def test_every_reference_name_exists():
    assert len(REFERENCE_ALL) == 118
    missing = []
    for raw in REFERENCE_ALL:
        name = convert_name(raw)
        if not hasattr(L, name):
            missing.append("%s (-> %s)" % (raw, name))
    assert not missing, "unconverted reference names: %s" % missing


def test_refusals_raise_documented_pointer():
    for name in sorted(REFUSALS):
        fn = getattr(L, name)
        with pytest.raises(NotImplementedError) as exc:
            fn("x")
        msg = str(exc.value)
        assert "fluid" in msg or "layer." in msg or "sequence" in msg, (
            name, msg)


# ---------------------------------------------------------------------------
# Behavior: math layers vs numpy oracles through paddle.infer
# ---------------------------------------------------------------------------

def _infer(outputs, feeding, rows):
    """feeding: column order of the row tuples (data-layer names)."""
    params = paddle.parameters.create(
        outputs[0] if len(outputs) == 1 else outputs[0],
        extra_layers=outputs[1:])
    inf = paddle.inference.Inference(output_layer=list(outputs),
                                     parameters=params)
    return inf.run(rows, feeding=feeding), params


def test_math_layers_match_numpy():
    rng = np.random.RandomState(0)
    d = 6
    a = L.data(name="pa", type=paddle.data_type.dense_vector(d))
    b = L.data(name="pb", type=paddle.data_type.dense_vector(d))
    w = L.data(name="pw", type=paddle.data_type.dense_vector(1))
    outs = [
        L.scaling(a, w), L.power(L.clip(a, 0.1, 2.0), w),
        L.interpolation([a, b], w), L.slope_intercept(a, slope=2.0,
                                                      intercept=0.5),
        L.sum_to_one_norm(L.clip(a, 0.05, 3.0)), L.row_l2_norm(a),
        L.l2_distance(a, b), L.dot_prod(a, b), L.out_prod(a, b),
        L.repeat(a, 2), L.repeat(a, 2, as_row_vector=False),
        L.resize(a, d // 2), L.clip(a, -0.3, 0.3),
    ]
    av = rng.uniform(0.2, 1.5, (4, d)).astype(np.float32)
    bv = rng.uniform(0.2, 1.5, (4, d)).astype(np.float32)
    wv = rng.uniform(0.3, 0.8, (4, 1)).astype(np.float32)
    rows = [(av[i], bv[i], wv[i]) for i in range(4)]
    got, _ = _infer(outs, ["pa", "pb", "pw"], rows)
    a64, b64, w64 = av.astype(np.float64), bv.astype(np.float64), \
        wv.astype(np.float64)
    ac = np.clip(a64, 0.1, 2.0)
    an = np.clip(a64, 0.05, 3.0)
    want = [
        a64 * w64, ac ** w64,
        w64 * a64 + (1 - w64) * b64, 2.0 * a64 + 0.5,
        an / an.sum(1, keepdims=True),
        a64 / np.sqrt((a64 ** 2).sum(1, keepdims=True)),
        np.sqrt(((a64 - b64) ** 2).sum(1, keepdims=True)),
        (a64 * b64).sum(1, keepdims=True),
        np.einsum("ni,nj->nij", a64, b64).reshape(4, -1),
        np.tile(a64, (1, 2)), np.repeat(a64, 2, axis=1),
        a64.reshape(8, d // 2), np.clip(a64, -0.3, 0.3),
    ]
    for i, (g, x) in enumerate(zip(got, want)):
        np.testing.assert_allclose(np.asarray(g), x, atol=1e-4,
                                   rtol=1e-4, err_msg="output %d" % i)


def test_linear_comb_and_trans():
    rng = np.random.RandomState(1)
    s, d = 3, 4
    wl = L.data(name="lc_w", type=paddle.data_type.dense_vector(s))
    vl = L.data(name="lc_v", type=paddle.data_type.dense_vector(s * d))
    al = L.data(name="lc_a", type=paddle.data_type.dense_vector(d))
    outs = [L.linear_comb(wl, vl, size=d), L.convex_comb(wl, vl, size=d),
            L.trans(al)]
    wv = rng.randn(2, s).astype(np.float32)
    vv = rng.randn(2, s * d).astype(np.float32)
    av = rng.randn(2, d).astype(np.float32)
    got, _ = _infer(outs, ["lc_w", "lc_v", "lc_a"],
                    [(wv[i], vv[i], av[i]) for i in range(2)])
    want = np.einsum("ns,nsd->nd", wv, vv.reshape(2, s, d))
    np.testing.assert_allclose(np.asarray(got[0]), want, atol=1e-4,
                               rtol=1e-4)
    np.testing.assert_allclose(np.asarray(got[1]), want, atol=1e-4,
                               rtol=1e-4)
    np.testing.assert_allclose(np.asarray(got[2]), av.T, atol=1e-6)


def test_image_layers_build_and_shapes():
    """maxout/spp/block_expand/cmrnorm/pad/crop/bilinear_interp/rotate
    on a 1-channel 4x4 image batch."""
    img = L.data(name="img16", type=paddle.data_type.dense_vector(16),
                 height=4, width=4)
    rot = L.rotate(img, height=4, width=4)
    # 2-channel image for maxout grouping
    img2 = L.data(name="img32", type=paddle.data_type.dense_vector(32))
    img2.num_channels = 2
    outs = [
        rot,
        L.maxout(img2, groups=2, num_channels=2),
        L.spp(img, pyramid_height=2, num_channels=1),
        L.block_expand(img, block_x=2, block_y=2, stride_x=2, stride_y=2,
                       num_channels=1),
        L.img_cmrnorm(img2, size=3, num_channels=2),
        L.pad(img, pad_h=[1, 1], pad_w=[0, 0]),
        L.crop(img, offset=[1, 1], shape=[2, 2]),
        L.bilinear_interp(img, out_size_x=8, out_size_y=8),
    ]
    rng = np.random.RandomState(2)
    x16 = rng.randn(3, 16).astype(np.float32)
    x32 = rng.randn(3, 32).astype(np.float32)
    got, _ = _infer(outs, ["img16", "img32"],
                    [(x16[i], x32[i]) for i in range(3)])
    rot_v = np.asarray(got[0]).reshape(3, 4, 4)
    base = x16.reshape(3, 4, 4)
    # rotate 90deg CCW: out[w, h] = in[h, W-1-w] == np.rot90(in, 1)
    for k in range(3):
        np.testing.assert_allclose(rot_v[k], np.rot90(base[k], 1),
                                   atol=1e-6)
    assert np.asarray(got[1]).shape == (3, 1, 4, 4)      # maxout
    assert np.asarray(got[2]).shape == (3, 1 * (1 + 4))  # spp levels 1+4
    assert np.asarray(got[3]).shape[1] == 4              # 2x2 patches
    assert np.asarray(got[4]).shape == (3, 2, 4, 4)      # cmrnorm
    assert np.asarray(got[5]).shape == (3, 1, 6, 4)      # pad h
    assert np.asarray(got[6]).shape == (3, 1, 2, 2)      # crop
    np.testing.assert_allclose(
        np.asarray(got[6]), x16.reshape(3, 1, 4, 4)[:, :, 1:3, 1:3],
        atol=1e-6)
    assert np.asarray(got[7]).shape == (3, 1, 8, 8)      # bilinear


def test_param_layers_build_and_train():
    """gated_unit / factorization_machine / scale_shift / tensor /
    selective_fc / row_conv-free composite trains end-to-end."""
    rng = np.random.RandomState(3)
    d = 8
    x = L.data(name="pl_x", type=paddle.data_type.dense_vector(d))
    y = L.data(name="pl_y", type=paddle.data_type.dense_vector(1))
    g = L.gated_unit(x, size=6)
    fm = L.factorization_machine(x, factor_size=3)
    ss = L.scale_shift(L.selective_fc(x, size=4,
                                      act=paddle.activation.Tanh()))
    t = L.tensor(g, ss, size=2, act=paddle.activation.Tanh())
    pred = L.fc([t, fm], size=1)
    cost = L.mse_cost(pred, y)
    params = paddle.parameters.create(cost)
    opt = paddle.optimizer.Momentum(learning_rate=0.05, momentum=0.9)
    trainer = paddle.trainer.SGD(cost=cost, parameters=params,
                                 update_equation=opt)
    xv = rng.randn(64, d).astype(np.float32)
    yv = (xv[:, :1] * 0.7).astype(np.float32)

    def reader():
        for _ in range(12):
            yield [(xv[i], yv[i]) for i in range(64)]

    costs = []
    trainer.train(reader, num_passes=1, event_handler=lambda e: costs.append(
        e.cost) if isinstance(e, paddle.event.EndIteration) else None)
    assert costs[-1] < costs[0], costs


def test_mixed_projection_tail_shapes():
    d = 6
    x = L.data(name="mp_x", type=paddle.data_type.dense_vector(d))
    y = L.data(name="mp_y", type=paddle.data_type.dense_vector(d))
    m1 = L.mixed(size=d, input=[L.dotmul_projection(x)])
    m2 = L.mixed(size=d, input=[L.scaling_projection(x)])
    m3 = L.mixed(size=4, input=[L.trans_full_matrix_projection(x, size=4)])
    m4 = L.mixed(size=4, input=[L.slice_projection(x, [(0, 2), (3, 5)])])
    m5 = L.mixed(size=3, input=[L.identity_projection(x, offset=2,
                                                      size=3)])
    m6 = L.mixed(size=d, input=[L.dotmul_operator(a=x, b=y, scale=2.0)])
    rng = np.random.RandomState(4)
    xv = rng.randn(2, d).astype(np.float32)
    yv = rng.randn(2, d).astype(np.float32)
    got, _ = _infer([m1, m2, m3, m4, m5, m6], ["mp_x", "mp_y"],
                    [(xv[i], yv[i]) for i in range(2)])
    assert np.asarray(got[0]).shape == (2, d)
    assert np.asarray(got[1]).shape == (2, d)
    assert np.asarray(got[2]).shape == (2, 4)
    np.testing.assert_allclose(np.asarray(got[3]),
                               np.concatenate([xv[:, 0:2], xv[:, 3:5]], 1),
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(got[4]), xv[:, 2:5], atol=1e-6)
    np.testing.assert_allclose(np.asarray(got[5]), 2.0 * xv * yv,
                               atol=1e-5)


def test_context_projection_windows():
    d = 2
    x = L.data(name="cp_x", type=paddle.data_type.dense_vector_sequence(d))
    m = L.mixed(size=3 * d, input=[L.context_projection(x, context_len=3)])
    rows = [([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]],),
            ([[7.0, 8.0]],)]
    got, _ = _infer([m], ["cp_x"], rows)
    v = np.asarray(got[0])
    # first sequence, middle token: window = [x0, x1, x2]
    np.testing.assert_allclose(v[1], [1, 2, 3, 4, 5, 6], atol=1e-5)
    # boundary zero-padding on the first token
    np.testing.assert_allclose(v[0], [0, 0, 1, 2, 3, 4], atol=1e-5)


def test_recurrent_and_step_layers():
    rng = np.random.RandomState(5)
    d = 4
    x = L.data(name="rc_x", type=paddle.data_type.dense_vector_sequence(d))
    rec = L.recurrent(x)
    agg = L.pooling(rec, pooling_type=paddle.pooling.Sum())
    # gru_step inside a recurrent_group
    xp = L.data(name="gs_x",
                type=paddle.data_type.dense_vector_sequence(3 * d))

    def gstep(x_t):
        h = L.memory(name="g_h", size=d)
        out = L.gru_step(x_t, h, size=d, name="g_h")
        return out

    gr = L.recurrent_group(gstep, [xp])
    gagg = L.last_seq(gr)
    rows = []
    for _ in range(3):
        t = rng.randint(2, 5)
        rows.append((rng.randn(t, d).astype(np.float32),
                     rng.randn(t, 3 * d).astype(np.float32)))
    got, _ = _infer([agg, gagg], ["rc_x", "gs_x"], rows)
    assert np.asarray(got[0]).shape == (3, d)
    assert np.asarray(got[1]).shape == (3, d)
    assert np.isfinite(np.asarray(got[0])).all()
    assert np.isfinite(np.asarray(got[1])).all()


def test_cost_layers_forward_finite():
    rng = np.random.RandomState(6)
    d, classes = 6, 5
    x = L.data(name="c_x", type=paddle.data_type.dense_vector(d))
    lab1 = L.data(name="c_l1", type=paddle.data_type.integer_value(classes))
    reg = L.data(name="c_r", type=paddle.data_type.dense_vector(1))
    multi = L.data(name="c_m", type=paddle.data_type.dense_vector(4))
    left = L.fc(x, size=1)
    right = L.fc(x, size=1)
    probs = L.fc(x, size=4, act=paddle.activation.Softmax())
    sig = L.fc(x, size=4, act=paddle.activation.Sigmoid())
    costs = [
        L.nce(L.fc(x, size=d), lab1, num_classes=classes,
              num_neg_samples=3),
        L.hsigmoid(L.fc(x, size=d), lab1, num_classes=classes),
        L.rank_cost(left, right, reg),
        L.sum_cost(L.fc(x, size=2)),
        L.huber_regression_cost(left, reg),
        L.huber_classification_cost(left, reg),
        L.smooth_l1_cost(L.fc(x, size=4), probs),
        L.multi_binary_label_cross_entropy_cost(sig, multi),
        L.cross_entropy_with_selfnorm_cost(probs, lab1_small := L.data(
            name="c_l4", type=paddle.data_type.integer_value(4))),
    ]
    rows = []
    for _ in range(4):
        rows.append((rng.randn(d).astype(np.float32),
                     int(rng.randint(classes)),
                     np.asarray([float(rng.randint(2))], np.float32),
                     rng.randint(0, 2, 4).astype(np.float32),
                     int(rng.randint(4))))
    got, _ = _infer(costs, ["c_x", "c_l1", "c_r", "c_m", "c_l4"], rows)
    for i, gv in enumerate(got):
        assert np.isfinite(np.asarray(gv)).all(), (i, gv)


def test_seq_and_misc_layers():
    rng = np.random.RandomState(7)
    d = 4
    x = L.data(name="s_x", type=paddle.data_type.dense_vector_sequence(d))
    rs = L.seq_reshape(x, reshape_size=2)
    idx = L.data(name="s_i", type=paddle.data_type.integer_value(2))
    c1 = L.data(name="s_c1", type=paddle.data_type.dense_vector(3))
    c2 = L.data(name="s_c2", type=paddle.data_type.dense_vector(3))
    mx = L.multiplex([idx, c1, c2])
    sid = L.sampling_id(L.mixed(size=3, input=[L.full_matrix_projection(
        c1, size=3)], act=paddle.activation.Softmax()))
    a8 = L.data(name="s_a8", type=paddle.data_type.dense_vector(8))
    b3 = L.data(name="s_b3", type=paddle.data_type.dense_vector(3))
    cs = L.conv_shift(a8, b3)
    rc = L.row_conv(x, context_len=2)
    pr = L.prelu(c1)
    rows = []
    for _ in range(2):
        t = rng.randint(2, 4)
        rows.append((rng.randn(t, d).astype(np.float32),
                     int(rng.randint(2)),
                     rng.randn(3).astype(np.float32),
                     rng.randn(3).astype(np.float32),
                     rng.randn(8).astype(np.float32),
                     rng.randn(3).astype(np.float32)))
    eo = L.eos(idx, eos_id=1)
    got, _ = _infer([rs, mx, sid, cs, rc, pr, eo],
                    ["s_x", "s_i", "s_c1", "s_c2", "s_a8", "s_b3"],
                    rows)
    for i, gv in enumerate(got):
        assert np.isfinite(np.asarray(gv, np.float64)).all(), i
    assert np.asarray(got[3]).shape == (2, 8)
    # eos: indicator of idx == 1 per sample
    idx_col = np.asarray([r[1] for r in rows], np.float64)[:, None]
    np.testing.assert_allclose(np.asarray(got[6], np.float64),
                               (idx_col == 1).astype(np.float64))


def test_detection_layers_smoke():
    rng = np.random.RandomState(8)
    feat = L.data(name="d_f", type=paddle.data_type.dense_vector(2 * 4),
                  height=2, width=2)
    feat.num_channels = 2
    img = L.data(name="d_img", type=paddle.data_type.dense_vector(3 * 64),
                 height=8, width=8)
    img.num_channels = 3
    pb = L.priorbox(feat, img, aspect_ratio=[2.0],
                    variance=[0.1, 0.1, 0.2, 0.2], min_size=[4.0],
                    max_size=[8.0])
    rows = [(rng.randn(8).astype(np.float32),
             rng.randn(192).astype(np.float32))]
    got, _ = _infer([pb], ["d_f", "d_img"], rows)
    v = np.asarray(got)          # single output -> bare array
    assert v.ndim == 2 and v.shape[1] == 8 and v.shape[0] > 0
    # cross_channel_norm trains a per-channel scale
    ccn = L.cross_channel_norm(feat)
    got2, _ = _infer([ccn], ["d_f", "d_img"], rows)
    assert np.asarray(got2).shape == (1, 2, 2, 2)
