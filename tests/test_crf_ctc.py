"""CRF / CTC / chunk_eval / new sequence ops, numerically pinned against
brute-force enumeration (reference linear_chain_crf_op.h forward
algorithm, crf_decoding_op.h Viterbi, warpctc_op.cc, ctc_align_op.h,
chunk_eval_op.h, sequence_{concat,reshape,slice}_op.cc, lstmp_op.cc)."""
import itertools

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.core.lod import LoDTensor

layers = fluid.layers


# --------------------------- linear_chain_crf ----------------------------

def _crf_brute(em, trans, lens):
    """Enumerate all paths: logZ and per-path scores."""
    start, stop, pair = trans[0], trans[1], trans[2:]
    n, t, k = em.shape

    def score(row, path):
        s = start[path[0]] + em[row, 0, path[0]] + stop[path[-1]]
        for i in range(1, len(path)):
            s += em[row, i, path[i]] + pair[path[i - 1], path[i]]
        return s

    logz = np.zeros(n)
    for row in range(n):
        ln = lens[row]
        scores = [score(row, p)
                  for p in itertools.product(range(k), repeat=ln)]
        logz[row] = np.log(np.sum(np.exp(scores)))
    return logz, score


def test_linear_chain_crf_matches_brute_force():
    rng = np.random.RandomState(0)
    n, t, k = 2, 3, 3
    em = rng.randn(n, t, k).astype(np.float32)
    trans = (rng.randn(k + 2, k) * 0.5).astype(np.float32)
    lens = [3, 2]
    label = rng.randint(0, k, (n, t)).astype(np.int64)

    e_lod = LoDTensor.from_sequences(
        [em[i, :lens[i]] for i in range(n)])
    lab_lod = LoDTensor.from_sequences(
        [label[i, :lens[i], None] for i in range(n)])
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                e = layers.data(name="e", shape=[k], lod_level=1,
                                dtype="float32")
                lab = layers.data(name="lab", shape=[1], lod_level=1,
                                  dtype="int64")
                ll = layers.linear_chain_crf(
                    e, lab, param_attr=fluid.ParamAttr(name="crf_w"))
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        scope.set("crf_w", trans)
        got, = exe.run(main, feed={"e": e_lod, "lab": lab_lod},
                       fetch_list=[ll])
    got = np.ravel(np.asarray(got))

    logz, score = _crf_brute(em, trans, lens)
    for row in range(n):
        gold = score(row, list(label[row, :lens[row]]))
        np.testing.assert_allclose(got[row], logz[row] - gold,
                                   rtol=2e-4, atol=2e-4)


def test_crf_decoding_matches_brute_force():
    rng = np.random.RandomState(1)
    n, t, k = 2, 4, 3
    em = rng.randn(n, t, k).astype(np.float32)
    trans = (rng.randn(k + 2, k) * 0.5).astype(np.float32)
    lens = [4, 2]

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                e = layers.data(name="e", shape=[k], lod_level=1,
                                dtype="float32")
                lab = layers.data(name="lab", shape=[1], lod_level=1,
                                  dtype="int64")
                # build the crf to create the parameter, then decode
                layers.linear_chain_crf(
                    e, lab, param_attr=fluid.ParamAttr(name="crf_w"))
                path = layers.crf_decoding(
                    e, param_attr=fluid.ParamAttr(name="crf_w"))
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        scope.set("crf_w", trans)
        e_lod = LoDTensor.from_sequences(
            [em[i, :lens[i]] for i in range(n)])
        lab_lod = LoDTensor.from_sequences(
            [np.zeros((lens[i], 1), np.int64) for i in range(n)])
        got, = exe.run(main, feed={"e": e_lod, "lab": lab_lod},
                       fetch_list=[path])
    got = np.asarray(got)[..., 0]

    _, score = _crf_brute(em, trans, lens)
    for row in range(n):
        best = max(itertools.product(range(k), repeat=lens[row]),
                   key=lambda p: score(row, list(p)))
        assert got[row, :lens[row]].tolist() == list(best), row


# ------------------------------- warpctc ---------------------------------

def _ctc_brute(logits, label, blank):
    """-log sum of probabilities of all alignments collapsing to label."""
    t, v = logits.shape
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)

    def collapse(path):
        out = []
        prev = None
        for s in path:
            if s != prev and s != blank:
                out.append(s)
            prev = s
        return out

    total = 0.0
    for path in itertools.product(range(v), repeat=t):
        if collapse(path) == list(label):
            total += np.prod([p[i, s] for i, s in enumerate(path)])
    return -np.log(total)


def test_warpctc_matches_brute_force():
    rng = np.random.RandomState(2)
    n, t, v = 2, 4, 3
    logits = rng.randn(n, t, v).astype(np.float32)
    labels = [[1, 2], [2]]
    t_lens = [4, 3]

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                lg = layers.data(name="lg", shape=[v], lod_level=1,
                                 dtype="float32")
                lab = layers.data(name="lab", shape=[1], lod_level=1,
                                  dtype="int64")
                loss = layers.warpctc(lg, lab, blank=0)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        lg_lod = LoDTensor.from_sequences(
            [logits[i, :t_lens[i]] for i in range(n)])
        lab_lod = LoDTensor.from_sequences(
            [np.asarray(labels[i], np.int64)[:, None]
             for i in range(n)])
        got, = exe.run(main, feed={"lg": lg_lod, "lab": lab_lod},
                       fetch_list=[loss])
    got = np.ravel(np.asarray(got))
    for i in range(n):
        expect = _ctc_brute(logits[i, :t_lens[i]], labels[i], 0)
        np.testing.assert_allclose(got[i], expect, rtol=1e-4)


def test_warpctc_trains():
    """CTC on a one-sample copy task: loss decreases under SGD (grads
    flow through the scan via jax.vjp)."""
    rng = np.random.RandomState(3)
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                x = layers.data(name="x", shape=[8], lod_level=1,
                                dtype="float32")
                lab = layers.data(name="lab", shape=[1], lod_level=1,
                                  dtype="int64")
                h = layers.fc(x, size=5)
                loss = layers.mean(layers.warpctc(h, lab, blank=0))
                fluid.optimizer.SGD(learning_rate=0.5).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        xv = LoDTensor.from_sequences(
            [rng.randn(6, 8).astype(np.float32),
             rng.randn(4, 8).astype(np.float32)])
        labv = LoDTensor.from_sequences(
            [np.asarray([[1], [3]], np.int64),
             np.asarray([[2]], np.int64)])
        ls = []
        for _ in range(25):
            l, = exe.run(main, feed={"x": xv, "lab": labv},
                         fetch_list=[loss])
            ls.append(float(np.ravel(l)[0]))
    assert ls[-1] < ls[0] * 0.5, (ls[0], ls[-1])


def test_ctc_align(prog_scope, exe):
    main, startup, scope = prog_scope
    x = layers.data(name="x", shape=[8], dtype="int64",
                    append_batch_size=False)
    out = layers.ctc_greedy_decoder  # noqa: F841 (api presence)
    helper = fluid.layer_helper.LayerHelper("ctc_align")
    o = helper.create_tmp_variable(dtype="int64")
    helper.append_op(type="ctc_align", inputs={"Input": [x]},
                     outputs={"Output": [o]},
                     attrs={"blank": 0, "padding_value": 0})
    exe.run(startup)
    xv = np.asarray([[0, 1, 1, 0, 2, 2, 0, 3],
                     [1, 1, 2, 0, 0, 2, 2, 1]], np.int64)
    got, = exe.run(main, feed={"x": xv}, fetch_list=[o])
    got = np.asarray(got)
    np.testing.assert_array_equal(got[0], [1, 2, 3, 0, 0, 0, 0, 0])
    np.testing.assert_array_equal(got[1], [1, 2, 2, 1, 0, 0, 0, 0])


# ------------------------------ chunk_eval -------------------------------

def test_chunk_eval_iob():
    # 2 types, IOB: tag = type*2 + {B:0, I:1}, O = 4
    # label row: [B0 I0 O B1] -> chunks {(0,2,0), (3,4,1)}
    # infer row: [B0 I0 O B0] -> chunks {(0,2,0), (3,4,0)}
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                inf = layers.data(name="inf", shape=[1], lod_level=1,
                                  dtype="int64")
                lab = layers.data(name="lab", shape=[1], lod_level=1,
                                  dtype="int64")
                outs = layers.chunk_eval(inf, lab, "IOB",
                                         num_chunk_types=2)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        inf_lod = LoDTensor.from_sequences(
            [np.asarray([[0], [1], [4], [0]], np.int64)])
        lab_lod = LoDTensor.from_sequences(
            [np.asarray([[0], [1], [4], [2]], np.int64)])
        p, r, f1, ni, nl, nc = exe.run(
            main, feed={"inf": inf_lod, "lab": lab_lod},
            fetch_list=list(outs))
    assert int(ni[0]) == 2 and int(nl[0]) == 2 and int(nc[0]) == 1
    np.testing.assert_allclose(float(p[0]), 0.5)
    np.testing.assert_allclose(float(r[0]), 0.5)
    np.testing.assert_allclose(float(f1[0]), 0.5)


def test_chunk_eval_computed_input_respects_lengths():
    """chunk_eval on a COMPUTED (non-fed) inference var must still see
    the real sequence lengths, not the padded T."""
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                inf = layers.data(name="inf", shape=[1], lod_level=1,
                                  dtype="int64")
                lab = layers.data(name="lab", shape=[1], lod_level=1,
                                  dtype="int64")
                # computed temp (scale by 1 keeps values, changes var)
                inf2 = layers.cast(layers.scale(
                    layers.cast(inf, "float32"), scale=1.0), "int64")
                outs = layers.chunk_eval(inf2, lab, "IOB",
                                         num_chunk_types=2)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        # rows of different lengths; padding would parse as B-type0
        inf_lod = LoDTensor.from_sequences(
            [np.asarray([[0], [1], [4], [0]], np.int64),
             np.asarray([[2]], np.int64)])
        lab_lod = LoDTensor.from_sequences(
            [np.asarray([[0], [1], [4], [2]], np.int64),
             np.asarray([[2]], np.int64)])
        p, r, f1, ni, nl, nc = exe.run(
            main, feed={"inf": inf_lod, "lab": lab_lod},
            fetch_list=list(outs))
    assert int(ni[0]) == 3 and int(nl[0]) == 3 and int(nc[0]) == 2


def test_postlude_host_op_chain():
    """A host op reading another postlude host op's output (chunk_eval
    -> Print) must not be treated as a compiled-program fetch."""
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                inf = layers.data(name="inf", shape=[1], lod_level=1,
                                  dtype="int64")
                lab = layers.data(name="lab", shape=[1], lod_level=1,
                                  dtype="int64")
                inf2 = layers.cast(layers.scale(
                    layers.cast(inf, "float32"), scale=1.0), "int64")
                outs = layers.chunk_eval(inf2, lab, "IOB",
                                         num_chunk_types=2)
                layers.Print(outs[0], message="prec")
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        seq = [np.asarray([[0], [1]], np.int64)]
        got = exe.run(main,
                      feed={"inf": LoDTensor.from_sequences(seq),
                            "lab": LoDTensor.from_sequences(seq)},
                      fetch_list=[outs[0]])
    np.testing.assert_allclose(float(np.ravel(got[0])[0]), 1.0)


# --------------------------- new sequence ops ----------------------------

def test_sequence_concat():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                a = layers.data(name="a", shape=[2], lod_level=1,
                                dtype="float32")
                b = layers.data(name="b", shape=[2], lod_level=1,
                                dtype="float32")
                out = layers.sequence_concat([a, b])
                pooled = layers.sequence_pool(out, "sum")
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        a_seqs = [np.ones((2, 2), np.float32),
                  np.ones((1, 2), np.float32) * 2]
        b_seqs = [np.ones((3, 2), np.float32) * 10,
                  np.ones((1, 2), np.float32) * 20]
        got, = exe.run(main, feed={
            "a": LoDTensor.from_sequences(a_seqs),
            "b": LoDTensor.from_sequences(b_seqs)},
            fetch_list=[pooled])
    # row sums: row0 = 2*1 + 3*10 = 32; row1 = 2 + 20 = 22 (per feature)
    np.testing.assert_allclose(np.asarray(got),
                               [[32, 32], [22, 22]])


def test_sequence_reshape_and_slice(prog_scope, exe):
    main, startup, scope = prog_scope
    x = layers.data(name="x", shape=[4, 2], dtype="float32")
    r = layers.sequence_reshape(x, new_dim=4)
    off = layers.data(name="off", shape=[1], dtype="int64")
    ln = layers.data(name="ln", shape=[1], dtype="int64")
    s = layers.sequence_slice(x, off, ln)
    exe.run(startup)
    xv = np.arange(16, dtype=np.float32).reshape(2, 4, 2)
    got_r, got_s = exe.run(
        main, feed={"x": xv,
                    "off": np.asarray([[1], [0]], np.int64),
                    "ln": np.asarray([[2], [1]], np.int64)},
        fetch_list=[r, s])
    np.testing.assert_allclose(np.asarray(got_r),
                               xv.reshape(2, 2, 4))
    got_s = np.asarray(got_s)
    np.testing.assert_allclose(got_s[0, :2], xv[0, 1:3])
    np.testing.assert_allclose(got_s[0, 2:], 0)
    np.testing.assert_allclose(got_s[1, :1], xv[1, :1])


def test_dynamic_lstmp_shapes_and_training(prog_scope, exe):
    main, startup, scope = prog_scope
    x = layers.data(name="x", shape=[5, 16], dtype="float32")
    y = layers.data(name="y", shape=[3], dtype="float32")
    # a user-supplied ParamAttr must not collide Weight/ProjWeight
    proj, cell = layers.dynamic_lstmp(
        x, size=16, proj_size=6,
        param_attr=fluid.ParamAttr(
            initializer=fluid.initializer.NormalInitializer(0.0, 0.1)))
    assert tuple(proj.shape[1:]) == (5, 6)
    assert tuple(cell.shape[1:]) == (5, 4)
    pred = layers.fc(layers.reduce_mean(proj, dim=1), size=3)
    loss = layers.mean(layers.square_error_cost(pred, y))
    fluid.optimizer.Adam(learning_rate=0.02).minimize(loss)
    exe.run(startup)
    rng = np.random.RandomState(5)
    xv = rng.randn(8, 5, 16).astype(np.float32)
    yv = np.stack([xv.sum((1, 2)), xv.mean((1, 2)),
                   xv.std((1, 2))], 1).astype(np.float32)
    ls = []
    for _ in range(40):
        l, = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss])
        ls.append(float(np.ravel(l)[0]))
    assert ls[-1] < ls[0] * 0.5, (ls[0], ls[-1])
