"""Pallas fused matmul / add+LN kernels (kernels/matmul_fused.py) and
the fused transformer ops: interpret-mode kernel parity vs the XLA
path (fwd + grad, bf16 and f32, odd-tail shapes exercising the VMEM
fallback), mirroring tests/test_conv_fused.py."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu.fluid as fluid
from paddle_tpu.core.scope import Scope
from paddle_tpu.kernels import matmul_fused

TILES = {"block_m": 8, "block_n": 128, "block_k": 128}


def _tol(dtype):
    return (2e-2, 2e-2) if dtype == jnp.bfloat16 else (1e-4, 1e-4)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16],
                         ids=["f32", "bf16"])
@pytest.mark.parametrize("act", ["", "relu", "gelu"])
@pytest.mark.parametrize("with_bias,with_residual", [
    (True, False), (True, True), (False, False)])
def test_kernel_matches_xla(dtype, act, with_bias, with_residual):
    m, k, n = 16, 128, 256
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(m, k), dtype)
    w = jnp.asarray(rng.randn(k, n) * 0.1, dtype)
    bias = jnp.asarray(rng.randn(n), jnp.float32) if with_bias else None
    res = jnp.asarray(rng.randn(m, n), dtype) if with_residual else None
    got = matmul_fused.matmul_epilogue(x, w, bias, res, act,
                                       config=TILES, interpret=True)
    want, _ = matmul_fused.matmul_epilogue_reference(x, w, bias, res,
                                                    act)
    rtol, atol = _tol(dtype)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=rtol, atol=atol)


def test_kernel_save_preact():
    m, k, n = 16, 128, 128
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(m, k), jnp.float32)
    w = jnp.asarray(rng.randn(k, n) * 0.1, jnp.float32)
    bias = jnp.asarray(rng.randn(n), jnp.float32)
    y, pre = matmul_fused.matmul_epilogue(
        x, w, bias, None, "gelu", save_preact=True, config=TILES,
        interpret=True)
    want_y, want_pre = matmul_fused.matmul_epilogue_reference(
        x, w, bias, None, "gelu")
    np.testing.assert_allclose(np.asarray(pre), np.asarray(want_pre),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(y), np.asarray(want_y),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("shape", [
    (7, 100, 60),     # nothing tiles
    (16, 130, 256),   # K has no 128-multiple divisor
    (16, 128, 60),    # N below the 128-lane floor
])
def test_odd_tails_take_the_fallback(shape):
    """Non-tiling shapes must demote to the identical-math XLA path —
    the plan says 'not usable' and the result still matches the
    reference bit-for-bit (it IS the reference)."""
    m, k, n = shape
    _, _, _, usable = matmul_fused.plan_matmul(m, k, n, jnp.float32)
    assert not usable
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(m, k), jnp.float32)
    w = jnp.asarray(rng.randn(k, n) * 0.1, jnp.float32)
    got = matmul_fused.matmul_epilogue(x, w, None, None, "relu",
                                       interpret=True)
    want, _ = matmul_fused.matmul_epilogue_reference(x, w, None, None,
                                                     "relu")
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_plan_respects_vmem_budget():
    """A tile request the VMEM budget can't hold is not usable."""
    cfg = {"block_m": 4096, "block_n": 4096, "block_k": 4096}
    _, _, _, usable = matmul_fused.plan_matmul(4096, 4096, 4096,
                                               jnp.float32, cfg)
    assert not usable


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16],
                         ids=["f32", "bf16"])
@pytest.mark.parametrize("with_affine", [True, False])
def test_add_ln_kernel_matches_reference(dtype, with_affine):
    m, d = 16, 128
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(m, d), dtype)
    y = jnp.asarray(rng.randn(m, d), dtype)
    scale = jnp.asarray(rng.rand(d) + 0.5, jnp.float32) \
        if with_affine else None
    bias = jnp.asarray(rng.randn(d), jnp.float32) if with_affine \
        else None
    got = matmul_fused.add_ln(x, y, scale, bias,
                              config={"block_m": 8}, interpret=True)
    want = matmul_fused.add_ln_reference(x, y, scale, bias)
    rtol, atol = _tol(dtype)
    for g, w_, name in zip(got, want, ("out", "sum", "mean", "var")):
        np.testing.assert_allclose(
            np.asarray(g, np.float32), np.asarray(w_, np.float32),
            rtol=rtol, atol=atol, err_msg=name)


def test_add_ln_odd_rows_fall_back():
    m, d = 7, 100
    _, usable = matmul_fused.plan_add_ln(m, d, jnp.float32)
    assert not usable
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(m, d), jnp.float32)
    y = jnp.asarray(rng.randn(m, d), jnp.float32)
    got = matmul_fused.add_ln(x, y, interpret=True)
    want = matmul_fused.add_ln_reference(x, y)
    for g, w_ in zip(got, want):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w_))


# ---------------------------------------------------------------------------
# Op-level fwd+grad parity with the interpret-mode kernels in the loop
# ---------------------------------------------------------------------------

def _build_chain(b, t, d, act, with_residual, with_dropout=False):
    """mul -> bias add (-> act) (-> dropout) (-> residual add) on a
    [B, T, D] stream, plus the QKV triple: the transformer block in
    miniature, built from fluid layers so the fuse pass sees the real
    op idioms."""
    x = fluid.layers.data(name="x", shape=[t, d], dtype="float32")
    x.stop_gradient = False
    h = fluid.layers.fc(x, size=d, num_flatten_dims=2, act=act or None,
                        name="up")
    if with_dropout:
        h = fluid.layers.dropout(h, dropout_prob=0.3, seed=11)
    if with_residual:
        out = fluid.layers.elementwise_add(x, h)
    else:
        out = h
    loss = fluid.layers.reduce_sum(out)
    return loss


@pytest.mark.parametrize("act,with_residual,with_dropout", [
    ("", False, False), ("relu", False, False), ("gelu", False, False),
    ("relu", True, False), ("", True, True), ("gelu", True, True)])
def test_fused_op_training_parity_interpret(act, with_residual,
                                            with_dropout):
    """The transpiled fused_matmul_bias_act program — with the Pallas
    kernel forced through the interpreter — must match the unfused
    mul+add(+act)(+dropout)(+residual) chain: loss AND post-step
    parameters over several SGD steps."""
    b, t, d = 2, 8, 128

    def run(transpile, params=None, steps=3):
        main, startup = fluid.Program(), fluid.Program()
        scope = Scope()
        with fluid.scope_guard(scope):
            with fluid.program_guard(main, startup):
                with fluid.unique_name.guard():
                    loss = _build_chain(b, t, d, act, with_residual,
                                        with_dropout)
                    if transpile:
                        from paddle_tpu.fluid.transpiler import \
                            TransformerFuseTranspiler
                        counts = TransformerFuseTranspiler().transpile(
                            main)
                        assert counts.get("matmul_bias_act"), counts
                        for op in main.desc.blocks[0].ops:
                            if op.type.startswith("fused_"):
                                op.set_attr("interpret", True)
                    fluid.optimizer.SGD(learning_rate=0.05).minimize(
                        loss)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            if params is not None:
                for n, v in params.items():
                    scope.set(n, v)
            snap = {n: np.asarray(scope.find_var(n)).copy()
                    for n in scope.local_var_names()}
            rng = np.random.RandomState(3)
            feed = {"x": rng.randn(b, t, d).astype(np.float32)}
            losses = []
            for _ in range(steps):
                l, = exe.run(main, feed=feed, fetch_list=[loss])
                losses.append(float(np.asarray(l).ravel()[0]))
            post = {n: np.asarray(scope.find_var(n)).copy()
                    for n in scope.local_var_names()}
        ops = [o.type for o in main.desc.blocks[0].ops]
        return losses, snap, post, ops

    base_losses, params, base_post, base_ops = run(False)
    losses, _, post, ops = run(True, params=dict(params))
    assert "fused_matmul_bias_act" in ops
    assert "mul" not in ops
    assert "fused_matmul_bias_act_grad" in ops
    if with_dropout:
        assert "dropout" not in ops
    np.testing.assert_allclose(base_losses, losses, rtol=2e-4,
                               atol=2e-4)
    for n, v in base_post.items():
        w = post.get(n)
        if w is None or v.dtype.kind != "f" or v.shape != w.shape:
            continue
        np.testing.assert_allclose(v, w, rtol=1e-4, atol=4e-7,
                                   err_msg=n)


def test_fused_qkv_training_parity_interpret():
    """Three muls sharing an input collapse to fused_qkv_matmul; loss
    and parameter updates must match the unfused triple."""
    b, t, d = 2, 8, 128

    def run(transpile, params=None, steps=3):
        main, startup = fluid.Program(), fluid.Program()
        scope = Scope()
        with fluid.scope_guard(scope):
            with fluid.program_guard(main, startup):
                with fluid.unique_name.guard():
                    x = fluid.layers.data(name="x", shape=[t, d],
                                          dtype="float32")
                    x.stop_gradient = False
                    hs = [fluid.layers.fc(
                        x, size=d, num_flatten_dims=2, bias_attr=False,
                        name="p_%s" % nm) for nm in ("q", "k", "v")]
                    out = hs[0]
                    for h in hs[1:]:
                        out = fluid.layers.elementwise_add(out, h)
                    loss = fluid.layers.reduce_sum(out)
                    if transpile:
                        from paddle_tpu.fluid.transpiler import \
                            TransformerFuseTranspiler
                        counts = TransformerFuseTranspiler().transpile(
                            main)
                        assert counts.get("qkv") == 1, counts
                        for op in main.desc.blocks[0].ops:
                            if op.type.startswith("fused_"):
                                op.set_attr("interpret", True)
                    fluid.optimizer.SGD(learning_rate=0.05).minimize(
                        loss)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            if params is not None:
                for n, v in params.items():
                    scope.set(n, v)
            snap = {n: np.asarray(scope.find_var(n)).copy()
                    for n in scope.local_var_names()}
            rng = np.random.RandomState(5)
            feed = {"x": rng.randn(b, t, d).astype(np.float32)}
            losses = []
            for _ in range(steps):
                l, = exe.run(main, feed=feed, fetch_list=[loss])
                losses.append(float(np.asarray(l).ravel()[0]))
            post = {n: np.asarray(scope.find_var(n)).copy()
                    for n in scope.local_var_names()}
        ops = [o.type for o in main.desc.blocks[0].ops]
        return losses, snap, post, ops

    base_losses, params, base_post, base_ops = run(False)
    losses, _, post, ops = run(True, params=dict(params))
    assert "fused_qkv_matmul" in ops and "mul" not in ops
    assert "fused_qkv_matmul_grad" in ops
    np.testing.assert_allclose(base_losses, losses, rtol=2e-4,
                               atol=2e-4)
    for n, v in base_post.items():
        w = post.get(n)
        if w is None or v.dtype.kind != "f" or v.shape != w.shape:
            continue
        np.testing.assert_allclose(v, w, rtol=1e-4, atol=4e-7,
                                   err_msg=n)
