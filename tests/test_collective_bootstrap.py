"""Multi-host bootstrap env contract (reference gen_nccl_id_op.cc /
trainer.py:_transpile_nccl2_dist env parsing)."""
from paddle_tpu.distributed.collective import (collective_env,
                                               init_collective_env)


def test_endpoint_form():
    env = {"PADDLE_TRAINER_ENDPOINTS": "10.0.0.1:7164,10.0.0.2:7164",
           "PADDLE_CURRENT_ENDPOINT": "10.0.0.2:7164"}
    assert collective_env(env) == ("10.0.0.1:7164", 2, 1)


def test_trainer_id_overrides_endpoint_lookup():
    env = {"PADDLE_TRAINER_ENDPOINTS": "a:1,b:1,c:1",
           "PADDLE_TRAINER_ID": "2"}
    assert collective_env(env) == ("a:1", 3, 2)


def test_legacy_ips_form():
    env = {"PADDLE_TRAINER_IPS": "10.1.1.1,10.1.1.2",
           "PADDLE_PSERVER_PORT": "6174", "POD_IP": "10.1.1.1"}
    assert collective_env(env) == ("10.1.1.1:6174", 2, 0)


def test_unconfigured_is_noop():
    assert collective_env({}) is None
    assert init_collective_env({}) == (1, 0)


def test_misconfigured_current_endpoint_fails_fast():
    import pytest

    env = {"PADDLE_TRAINER_ENDPOINTS": "10.0.0.1:7164,10.0.0.2:7164",
           "PADDLE_CURRENT_ENDPOINT": "10.0.0.99:7164"}  # typo
    with pytest.raises(ValueError, match="not among them"):
        collective_env(env)


def test_single_process_is_noop():
    env = {"PADDLE_TRAINER_ENDPOINTS": "10.0.0.1:7164",
           "PADDLE_TRAINER_ID": "0"}
    assert init_collective_env(env) == (1, 0)
