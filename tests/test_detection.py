"""Detection (SSD) ops + layers (reference operators/detection/*,
layers/detection.py; test shapes from tests/unittests/test_prior_box_op,
test_bipartite_match_op, test_multiclass_nms_op, book test_image_
detection usage)."""
import numpy as np

import paddle_tpu.fluid as fluid

layers = fluid.layers


def _exe_prog():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    return main, startup, scope


def test_prior_box_geometry(prog_scope, exe):
    main, startup, scope = prog_scope
    feat = layers.data(name="feat", shape=[8, 2, 2], dtype="float32")
    img = layers.data(name="img", shape=[3, 32, 32], dtype="float32")
    boxes, variances = layers.detection.prior_box(
        feat, img, min_sizes=[4.0], max_sizes=[8.0],
        aspect_ratios=[2.0], flip=True, clip=True)
    exe.run(startup)
    b, v = exe.run(main, feed={
        "feat": np.zeros((1, 8, 2, 2), np.float32),
        "img": np.zeros((1, 3, 32, 32), np.float32)},
        fetch_list=[boxes, variances])
    b, v = np.asarray(b), np.asarray(v)
    # priors per cell: square(min) + ar2 + ar0.5 + sqrt(min*max) = 4
    assert b.shape == (2, 2, 4, 4) and v.shape == b.shape
    # cell (0,0) center = (0.5*16, 0.5*16) = (8, 8); min square 4x4
    np.testing.assert_allclose(
        b[0, 0, 0], [6 / 32, 6 / 32, 10 / 32, 10 / 32], rtol=1e-6)
    # max-size square sqrt(4*8)
    s = np.sqrt(32.0)
    np.testing.assert_allclose(
        b[0, 0, 3], [(8 - s / 2) / 32, (8 - s / 2) / 32,
                     (8 + s / 2) / 32, (8 + s / 2) / 32], rtol=1e-6)
    assert (b >= 0).all() and (b <= 1).all()  # clip
    np.testing.assert_allclose(v[1, 1, 2], [0.1, 0.1, 0.2, 0.2])


def test_iou_similarity_values(prog_scope, exe):
    main, startup, scope = prog_scope
    x = layers.data(name="x", shape=[4], dtype="float32")
    y = layers.data(name="y", shape=[2, 4], dtype="float32",
                    append_batch_size=False)
    iou = layers.detection.iou_similarity(x, y)
    exe.run(startup)
    xv = np.asarray([[0, 0, 2, 2], [1, 1, 3, 3]], np.float32)
    yv = np.asarray([[0, 0, 2, 2], [2, 2, 4, 4]], np.float32)
    got, = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[iou])
    got = np.asarray(got)
    np.testing.assert_allclose(got[0], [1.0, 0.0], atol=1e-6)
    np.testing.assert_allclose(got[1], [1 / 7, 1 / 7], rtol=1e-5)


def test_box_coder_roundtrip(prog_scope, exe):
    main, startup, scope = prog_scope
    prior = layers.data(name="prior", shape=[3, 4], dtype="float32",
                        append_batch_size=False)
    pvar = layers.data(name="pvar", shape=[3, 4], dtype="float32",
                       append_batch_size=False)
    gt = layers.data(name="gt", shape=[2, 4], dtype="float32",
                     append_batch_size=False)
    enc = layers.detection.box_coder(prior, pvar, gt,
                                     "encode_center_size")
    dec_in = layers.data(name="den", shape=[2, 3, 4], dtype="float32",
                         append_batch_size=False)
    dec = layers.detection.box_coder(prior, pvar, dec_in,
                                     "decode_center_size")
    exe.run(startup)
    rng = np.random.RandomState(0)
    # sort the two corner points per coordinate: [x0,y0,x1,y1] valid
    priors = np.sort(rng.rand(3, 2, 2), axis=1).reshape(
        3, 4).astype(np.float32)
    gts = np.sort(rng.rand(2, 2, 2), axis=1).reshape(
        2, 4).astype(np.float32)
    pv = np.full((3, 4), 0.5, np.float32)
    e, = exe.run(main, feed={"prior": priors, "pvar": pv, "gt": gts,
                             "den": np.zeros((2, 3, 4), np.float32)},
                 fetch_list=[enc])
    d, = exe.run(main, feed={"prior": priors, "pvar": pv, "gt": gts,
                             "den": np.asarray(e)}, fetch_list=[dec])
    # decode(encode(gt)) == gt for every (gt, prior) pair
    d = np.asarray(d)
    for g in range(2):
        for m in range(3):
            np.testing.assert_allclose(d[g, m], gts[g], rtol=1e-4,
                                       atol=1e-5)


def test_bipartite_match_greedy(prog_scope, exe):
    main, startup, scope = prog_scope
    dist = layers.data(name="dist", shape=[2, 3], dtype="float32")
    mi, md = layers.detection.bipartite_match(dist)
    mi2, md2 = layers.detection.bipartite_match(
        dist, match_type="per_prediction", dist_threshold=0.55)
    exe.run(startup)
    dv = np.asarray([[[0.9, 0.8, 0.1],
                      [0.85, 0.2, 0.6]]], np.float32)
    a, b, c, d = exe.run(main, feed={"dist": dv},
                         fetch_list=[mi, md, mi2, md2])
    # greedy: global max 0.9 -> gt0<-prior0; next best for gt1 is 0.6
    np.testing.assert_array_equal(np.asarray(a)[0], [0, -1, 1])
    np.testing.assert_allclose(np.asarray(b)[0], [0.9, 0.0, 0.6])
    # per_prediction: leftover prior1's best gt is gt0 at 0.8 > 0.55
    np.testing.assert_array_equal(np.asarray(c)[0], [0, 0, 1])
    np.testing.assert_allclose(np.asarray(d)[0], [0.9, 0.8, 0.6])


def test_mine_hard_examples(prog_scope, exe):
    main, startup, scope = prog_scope
    cls = layers.data(name="cls", shape=[6], dtype="float32")
    mi = layers.data(name="mi", shape=[6], dtype="int32")
    helper = fluid.layer_helper.LayerHelper("mine")
    neg = helper.create_tmp_variable(dtype="int32")
    upd = helper.create_tmp_variable(dtype="int32")
    helper.append_op(type="mine_hard_examples",
                     inputs={"ClsLoss": [cls], "MatchIndices": [mi]},
                     outputs={"NegIndices": [neg],
                              "UpdatedMatchIndices": [upd]},
                     attrs={"neg_pos_ratio": 2.0})
    exe.run(startup)
    clsv = np.asarray([[5.0, 1.0, 3.0, 4.0, 2.0, 0.5]], np.float32)
    miv = np.asarray([[0, -1, -1, -1, -1, -1]], np.int32)
    got, = exe.run(main, feed={"cls": clsv, "mi": miv},
                   fetch_list=[neg])
    # 1 positive -> keep top-2 negatives by loss: priors 3 (4.0), 2 (3.0)
    np.testing.assert_array_equal(np.asarray(got)[0],
                                  [0, 0, 1, 1, 0, 0])


def test_multiclass_nms_suppression(prog_scope, exe):
    main, startup, scope = prog_scope
    bb = layers.data(name="bb", shape=[3, 4], dtype="float32")
    sc = layers.data(name="sc", shape=[2, 3], dtype="float32")
    out = layers.detection.multiclass_nms(
        bb, sc, background_label=0, score_threshold=0.1,
        nms_threshold=0.4, keep_top_k=10)
    exe.run(startup)
    boxes = np.asarray([[[0, 0, 1, 1], [0, 0, 1.05, 1.05],
                         [2, 2, 3, 3]]], np.float32)
    scores = np.asarray([[[0.9, 0.8, 0.7],        # class 0 = background
                          [0.6, 0.95, 0.5]]], np.float32)
    got, = exe.run(main, feed={"bb": boxes, "sc": scores},
                   fetch_list=[out])
    got = np.asarray(got)
    # class 1 only: box1 (0.95) kept, box0 suppressed (IoU ~0.9),
    # box2 kept (disjoint); sorted by score
    assert got.shape == (2, 6)
    np.testing.assert_allclose(got[0, :2], [1.0, 0.95])
    np.testing.assert_allclose(got[0, 2:], [0, 0, 1.05, 1.05])
    np.testing.assert_allclose(got[1, :2], [1.0, 0.5])


def test_ssd_head_and_loss_trains(prog_scope, exe):
    """multi_box_head + ssd_loss smoke: loss is finite and decreases."""
    main, startup, scope = prog_scope
    img = layers.data(name="img", shape=[3, 16, 16], dtype="float32")
    gt_box = layers.data(name="gt_box", shape=[2, 4], dtype="float32")
    gt_lab = layers.data(name="gt_lab", shape=[2, 1], dtype="int64")
    c1 = layers.conv2d(img, num_filters=8, filter_size=3, padding=1,
                       stride=2, act="relu")          # [N,8,8,8]
    c2 = layers.conv2d(c1, num_filters=8, filter_size=3, padding=1,
                       stride=2, act="relu")          # [N,8,4,4]
    locs, confs, boxes, vars_ = layers.detection.multi_box_head(
        inputs=[c1, c2], image=img, base_size=16, num_classes=3,
        aspect_ratios=[[2.0], [2.0]], min_sizes=[4.0, 8.0],
        max_sizes=[8.0, 12.0], flip=True)
    loss = layers.mean(layers.detection.ssd_loss(
        locs, confs, gt_box, gt_lab, boxes, vars_))
    fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    exe.run(startup)
    rng = np.random.RandomState(0)
    imgv = rng.rand(2, 3, 16, 16).astype(np.float32)
    gbv = np.asarray([[[0.1, 0.1, 0.4, 0.4], [0.5, 0.5, 0.9, 0.9]],
                      [[0.2, 0.3, 0.6, 0.7], [0.0, 0.0, 0.3, 0.2]]],
                     np.float32)
    glv = np.asarray([[[1], [2]], [[2], [1]]], np.int64)
    ls = []
    for _ in range(15):
        l, = exe.run(main, feed={"img": imgv, "gt_box": gbv,
                                 "gt_lab": glv}, fetch_list=[loss])
        ls.append(float(np.ravel(l)[0]))
    assert np.isfinite(ls).all()
    assert ls[-1] < ls[0], (ls[0], ls[-1])


def test_ssd_loss_default_prior_var_and_threshold_zero(prog_scope, exe):
    """prior_box_var=None must run (op defaults variances to 1), and an
    explicit dist_threshold=0.0 must not be silently replaced."""
    main, startup, scope = prog_scope
    loc = layers.data(name="loc", shape=[4, 4], dtype="float32")
    conf = layers.data(name="conf", shape=[4, 3], dtype="float32")
    gt_box = layers.data(name="gt_box", shape=[1, 4], dtype="float32")
    gt_lab = layers.data(name="gt_lab", shape=[1, 1], dtype="int64")
    prior = layers.data(name="prior", shape=[4, 4], dtype="float32",
                        append_batch_size=False)
    loss = layers.detection.ssd_loss(loc, conf, gt_box, gt_lab, prior)
    dist = layers.data(name="dist", shape=[1, 4], dtype="float32")
    mi0, _ = layers.detection.bipartite_match(
        dist, match_type="per_prediction", dist_threshold=0.0)
    exe.run(startup)
    rng = np.random.RandomState(0)
    priors = np.asarray([[0.0, 0.0, 0.5, 0.5], [0.5, 0.5, 1, 1],
                         [0, 0.5, 0.5, 1], [0.5, 0, 1, 0.5]],
                        np.float32)
    got_loss, got_mi = exe.run(main, feed={
        "loc": rng.randn(1, 4, 4).astype(np.float32) * 0.1,
        "conf": rng.randn(1, 4, 3).astype(np.float32),
        "gt_box": np.asarray([[[0.1, 0.1, 0.4, 0.4]]], np.float32),
        "gt_lab": np.asarray([[[1]]], np.int64),
        "prior": priors,
        "dist": np.asarray([[[0.3, 0.2, 0.1, 0.05]]], np.float32)},
        fetch_list=[loss, mi0])
    assert np.isfinite(np.asarray(got_loss)).all()
    # threshold 0.0: EVERY prior with positive best-IoU gets matched
    np.testing.assert_array_equal(np.asarray(got_mi)[0], [0, 0, 0, 0])


def test_detection_output_end_to_end(prog_scope, exe):
    main, startup, scope = prog_scope
    loc = layers.data(name="loc", shape=[4, 4], dtype="float32")
    sc = layers.data(name="sc", shape=[4, 3], dtype="float32")
    prior = layers.data(name="prior", shape=[4, 4], dtype="float32",
                        append_batch_size=False)
    pvar = layers.data(name="pvar", shape=[4, 4], dtype="float32",
                       append_batch_size=False)
    out = layers.detection.detection_output(loc, sc, prior, pvar)
    exe.run(startup)
    priors = np.asarray([[0.1, 0.1, 0.3, 0.3], [0.4, 0.4, 0.6, 0.6],
                         [0.6, 0.6, 0.8, 0.8], [0.2, 0.2, 0.5, 0.5]],
                        np.float32)
    got, = exe.run(main, feed={
        "loc": np.zeros((1, 4, 4), np.float32),   # offsets 0 = priors
        "sc": np.asarray([[[0.1, 0.8, 0.1], [0.2, 0.2, 0.6],
                           [0.8, 0.1, 0.1], [0.7, 0.2, 0.1]]],
                         np.float32),
        "prior": priors, "pvar": np.full((4, 4), 0.1, np.float32)},
        fetch_list=[out])
    got = np.asarray(got)
    assert got.ndim == 2 and got.shape[1] == 6
    # highest-confidence non-background: class1@prior0 (0.8)
    np.testing.assert_allclose(got[0, :2], [1.0, 0.8])
    np.testing.assert_allclose(got[0, 2:], priors[0], atol=1e-6)


def test_detection_map_hand_computed():
    """2 images, 2 classes; hand-computed integral AP."""
    from paddle_tpu.core.lod import LoDTensor
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                det = layers.data(name="det", shape=[6], dtype="float32",
                                  append_batch_size=False)
                lab = layers.data(name="lab", shape=[5], lod_level=1,
                                  dtype="float32")
                m = layers.detection.detection_map(det, lab,
                                                   class_num=2)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        # image 0: one gt class0 at [0,0,1,1]; detections: a hit (0.9)
        # and a miss (0.8).  image 1: one gt class1, detection hits.
        detv = np.asarray([
            [0, 0.9, 0.0, 0.0, 1.0, 1.0],    # tp class0
            [0, 0.8, 5.0, 5.0, 6.0, 6.0],    # fp class0
            [1, 0.7, 0.0, 0.0, 1.0, 1.0],    # tp class1
        ], np.float32)
        scope.set("det@ROWS", np.asarray([2, 1], np.int64))
        labv = LoDTensor.from_sequences([
            np.asarray([[0, 0, 0, 1, 1]], np.float32),
            np.asarray([[1, 0, 0, 1, 1]], np.float32)])
        got, = exe.run(main, feed={"det": detv, "lab": labv},
                       fetch_list=[m])
    # class0: precision-at-recall steps: tp@0.9 -> r=1, p=1; fp after.
    # integral AP = 1.0.  class1: AP = 1.0.  mAP = 1.0
    np.testing.assert_allclose(float(np.ravel(got)[0]), 1.0)


def test_detection_map_half():
    from paddle_tpu.core.lod import LoDTensor
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                det = layers.data(name="det", shape=[6], dtype="float32",
                                  append_batch_size=False)
                lab = layers.data(name="lab", shape=[5], lod_level=1,
                                  dtype="float32")
                m = layers.detection.detection_map(det, lab,
                                                   class_num=2)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        # 2 gts (class 1; class 0 is background and excluded), detection
        # hits one with the HIGHER-scored being a miss:
        # hits order: fp(0.9), tp(0.8) -> recall .5 at precision .5
        detv = np.asarray([
            [1, 0.9, 5, 5, 6, 6],
            [1, 0.8, 0, 0, 1, 1],
        ], np.float32)
        scope.set("det@ROWS", np.asarray([2], np.int64))
        labv = LoDTensor.from_sequences([
            np.asarray([[1, 0, 0, 1, 1], [1, 2, 2, 3, 3]], np.float32)])
        got, = exe.run(main, feed={"det": detv, "lab": labv},
                       fetch_list=[m])
    np.testing.assert_allclose(float(np.ravel(got)[0]), 0.25, atol=1e-6)
