"""OpTest harness.

Parity: reference python/paddle/fluid/tests/unittests/op_test.py:113 — a test
declares op_type, numpy inputs/attrs and expected outputs; the harness builds
a one-op program, checks outputs, and checks the emitted grad ops against
numeric finite differences of the forward program (get_numeric_gradient:40).

Place sweep parity (reference op_test.py:261 check_output_with_place, :320
check_output iterating CPUPlace + CUDAPlace): ``check_output`` always checks
on CPUPlace; when the env var ``TPU_OPTEST=1`` is set it additionally runs
the same program on ``fluid.TPUPlace()`` (the real chip on this rig) and
holds it to the same tolerances.  ``tools/tpu_optest.py`` drives the full
registry sweep on top of the same harness (CPU result as the oracle).
"""
from __future__ import annotations

import os

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.core.lod import LoDTensor
from paddle_tpu.core.scope import Scope


def places_to_check():
    """CPUPlace always; TPUPlace too when the sweep is enabled via env."""
    places = [fluid.CPUPlace()]
    if os.environ.get("TPU_OPTEST") == "1":
        places.append(fluid.TPUPlace())
    return places


class OpTest:
    """Subclass sets: op_type, inputs {slot: array or [(name, array), ...]},
    attrs, outputs {slot: expected or [(name, expected), ...]}.
    Inputs may be LoDTensor (fed with lod preserved, var gets lod_level)."""

    op_type = None
    inputs = {}
    attrs = {}
    outputs = {}

    # --- program construction ---
    def _build(self, extra_fetch=()):
        main = fluid.Program()
        startup = fluid.Program()
        feed = {}
        fetches = []
        with fluid.program_guard(main, startup), fluid.unique_name.guard():
            block = main.global_block()
            in_map = {}
            for slot, val in self.inputs.items():
                entries = val if isinstance(val, list) else [(slot, val)]
                names = []
                for name, arr in entries:
                    if isinstance(arr, LoDTensor):
                        block.create_var(name=name, shape=arr.shape,
                                         dtype=arr.dtype,
                                         lod_level=arr.lod_level(),
                                         stop_gradient=False)
                        feed[name] = arr
                    else:
                        arr = np.asarray(arr)
                        block.create_var(name=name, shape=arr.shape,
                                         dtype=arr.dtype, stop_gradient=False)
                        feed[name] = arr
                    names.append(name)
                in_map[slot] = names
            out_map = {}
            self._expected = {}
            for slot, val in self.outputs.items():
                entries = val if isinstance(val, list) else [(slot, val)]
                names = []
                for name, arr in entries:
                    arr = np.asarray(arr)
                    block.create_var(name=name, shape=arr.shape,
                                     dtype=arr.dtype)
                    names.append(name)
                    self._expected[name] = arr
                out_map[slot] = names
            block.append_op(type=self.op_type, inputs=in_map,
                            outputs=out_map, attrs=dict(self.attrs),
                            infer_shape=False)
        return main, startup, feed

    def run_outputs(self, place, fetch_names=None):
        """Run the one-op program on `place`; returns {name: np.ndarray}."""
        main, startup, feed = self._build()
        # kept for the abstract-shape parity property (check_output)
        self._main_for_parity = main
        self._feed_for_parity = feed
        exe = fluid.Executor(place)
        scope = Scope()
        with fluid.scope_guard(scope):
            fetch_names = list(fetch_names or self._expected.keys())
            outs = exe.run(main, feed=feed, fetch_list=fetch_names)
        return {n: np.asarray(v) for n, v in zip(fetch_names, outs)}

    def check_output_with_place(self, place, atol=1e-5, rtol=1e-5):
        """Reference op_test.py:261 — check outputs on one specific place."""
        got_map = self.run_outputs(place)
        for name, got in got_map.items():
            want = self._expected[name]
            np.testing.assert_allclose(
                np.asarray(got, dtype=np.float64),
                np.asarray(want, dtype=np.float64),
                atol=atol, rtol=rtol,
                err_msg="op %s output %s mismatch on %r" % (
                    self.op_type, name, place))
        return got_map

    # opt-out for specs whose outputs are legitimately data-dependent
    check_abstract_parity = True

    def check_abstract_parity_against(self, got_map):
        """Property: the program verifier's abstract shape inference
        (registered infer_shape or the jax.eval_shape fallback — the
        same path paddle_tpu/analysis' shape checker walks) must agree
        with the concrete output shapes/dtypes this spec just produced,
        so checker and runtime cannot drift.  Specs abstract evaluation
        cannot model are skipped (the checker downgrades those to notes,
        never errors); LoD specs are skipped because the runtime pads
        ragged feeds to bucketed shapes the declared desc does not
        carry."""
        if not self.check_abstract_parity:
            return
        for val in self._feed_for_parity.values():
            if isinstance(val, LoDTensor) and val.lod:
                return
        from paddle_tpu.analysis.shapes import canon_dtype as canon
        from paddle_tpu.core import lowering

        main = self._main_for_parity
        block = main.desc.blocks[0]
        op = block.ops[0]
        try:
            inferred = lowering.infer_op_outputs(main.desc, block, op)
        except Exception:
            return  # unmodelable: the checker reports a note, not an error
        for name, (shape, dtype) in inferred.items():
            got = got_map.get(name)
            if got is None:
                continue
            concrete = np.asarray(got)
            assert len(shape) == concrete.ndim and all(
                d == -1 or int(d) == int(c)
                for d, c in zip(shape, concrete.shape)), (
                "op %s output %s: abstract shape %s != concrete %s — "
                "the verifier's shape checker has drifted from the "
                "runtime" % (self.op_type, name, tuple(shape),
                             concrete.shape))
            assert canon(dtype) == canon(concrete.dtype), (
                "op %s output %s: abstract dtype %s != concrete %s"
                % (self.op_type, name, np.dtype(dtype), concrete.dtype))

    def check_output(self, atol=1e-5, rtol=1e-5):
        """Reference op_test.py:320 — sweep all available places; on the
        CPU place additionally hold abstract shape inference to the
        concrete outputs (see check_abstract_parity_against)."""
        for place in places_to_check():
            got_map = self.check_output_with_place(place, atol=atol,
                                                   rtol=rtol)
            if isinstance(place, fluid.CPUPlace):
                self.check_abstract_parity_against(got_map)

    # --- gradient check ---
    def check_grad(self, inputs_to_check, output_names=None,
                   max_relative_error=0.005, delta=1e-3):
        """Analytic grads (append_backward over the one-op program) vs
        numeric finite differences of a scalar head: sum(out * W) with fixed
        random W per output.  With TPU_OPTEST=1, additionally holds the
        TPU-place analytic grads to the CPU-place analytic grads (the CPU
        grads being the finite-difference-validated oracle)."""
        output_names = output_names or [
            n for n in self._first_float_outputs()]
        main, startup, feed = self._build()
        rng = np.random.RandomState(7)
        weights = {}
        with fluid.program_guard(main, startup):
            block = main.global_block()
            parts = []
            for oname in output_names:
                ovar = block.var(oname)
                w = rng.uniform(0.5, 1.5,
                                [int(d) for d in ovar.shape]).astype(
                                    np.float32)
                weights[oname] = w
                wvar = fluid.layers.assign(w)
                wvar.stop_gradient = True
                prod = fluid.layers.elementwise_mul(ovar, wvar)
                parts.append(fluid.layers.reduce_sum(prod))
            head = parts[0] if len(parts) == 1 else fluid.layers.sums(parts)
            loss = fluid.layers.reduce_sum(head)
            grads = fluid.backward.calc_gradient(
                loss, [block.var(n) for n in inputs_to_check])
        executors = {}   # one Executor per place: its jit cache is
                         # per-instance, and the FD loop re-runs the
                         # same program hundreds of times

        def run_fetch(names, feed_over=None, place=None):
            f = dict(feed)
            if feed_over:
                f.update(feed_over)
            place = place or fluid.CPUPlace()
            exe = executors.setdefault(place, fluid.Executor(place))
            scope = Scope()
            with fluid.scope_guard(scope):
                return exe.run(main, feed=f, fetch_list=names)

        grad_names = [g.name for g in grads]
        analytic = run_fetch(grad_names)

        for iname, a_grad in zip(inputs_to_check, analytic):
            x = np.asarray(feed[iname], dtype=np.float64)
            num = np.zeros_like(x)
            flat = x.reshape(-1)
            for i in range(flat.size):
                for sgn, store in ((1, "p"), (-1, "m")):
                    pert = flat.copy()
                    pert[i] += sgn * delta
                    out = run_fetch([loss.name],
                                    {iname: pert.reshape(x.shape).astype(
                                        feed[iname].dtype)})
                    if sgn == 1:
                        fp = float(np.asarray(out[0]).reshape(-1)[0])
                    else:
                        fm = float(np.asarray(out[0]).reshape(-1)[0])
                num.reshape(-1)[i] = (fp - fm) / (2 * delta)
            a = np.asarray(a_grad, dtype=np.float64)
            # normalize by the LARGEST gradient magnitude: fp32 forward +
            # finite differences put an absolute noise floor on every
            # element, so per-element relative error is meaningless for
            # near-zero entries (reference op_test.py __assert_is_close
            # uses the same idea)
            scale_ = max(np.abs(a).max(), np.abs(num).max(), 1e-3)
            rel = np.abs(a - num) / scale_
            assert rel.max() <= max_relative_error, (
                "op %s grad wrt %s: max rel err %.5f (analytic %s vs "
                "numeric %s)" % (self.op_type, iname, rel.max(),
                                 a.reshape(-1)[:5], num.reshape(-1)[:5]))

        # Cross-place grad check: device analytic grads vs the CPU analytic
        # grads just validated above (reference check_grad_with_place role).
        for place in places_to_check()[1:]:
            dev = run_fetch(grad_names, place=place)
            for iname, a_grad, d_grad in zip(inputs_to_check, analytic, dev):
                a = np.asarray(a_grad, dtype=np.float64)
                d = np.asarray(d_grad, dtype=np.float64)
                scale_ = max(np.abs(a).max(), 1e-3)
                rel = np.abs(a - d) / scale_
                assert rel.max() <= max_relative_error, (
                    "op %s grad wrt %s: CPU vs %r max rel err %.5f" %
                    (self.op_type, iname, place, rel.max()))

    def _first_float_outputs(self):
        names = []
        for slot, val in self.outputs.items():
            entries = val if isinstance(val, list) else [(slot, val)]
            for name, arr in entries:
                if np.issubdtype(np.asarray(arr).dtype, np.floating):
                    names.append(name)
        return names
