"""Prepared-program hot path (reference Executor::Prepare +
RunPreparedContext, framework/executor.cc:127): run_prepared must be
bit-identical to run() — same RNG counter stream, same persistable
values — while keeping the train state device-resident between steps
(zero per-step scope round-trips), flushing back via sync_scope on
checkpoint/save paths and on run() interleaving, and measurably
cutting per-step host dispatch overhead."""
import os
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.core.lod import LoDTensor
from paddle_tpu.core.scope import Scope

N_FEAT = 8


def _build_mlp(dropout=False):
    """fc -> (dropout) -> fc -> mse, Adam.  Returns the loss var."""
    x = fluid.layers.data(name="x", shape=[N_FEAT], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    h = fluid.layers.fc(x, size=16, act="tanh")
    if dropout:
        h = fluid.layers.dropout(h, dropout_prob=0.3)
    pred = fluid.layers.fc(h, size=1)
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    return loss


def _programs(builder=_build_mlp, **kw):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            out = builder(**kw)
    return main, startup, out


def _feeds(n, batch=4, seed=0):
    rng = np.random.RandomState(seed)
    return [{"x": rng.randn(batch, N_FEAT).astype(np.float32),
             "y": rng.randn(batch, 1).astype(np.float32)}
            for _ in range(n)]


def _persistables(main, scope):
    return {v.name: np.asarray(scope.find_var(v.name)).copy()
            for v in main.list_vars() if v.persistable}


def test_run_prepared_matches_run_exact():
    """>=20-step parity, stochastic model: identical losses AND
    identical persistables (params, Adam moments, beta pows) proves the
    prepared path replays the same RNG counter stream and the same
    compiled computation as run()."""
    main, startup, loss = _programs(dropout=True)
    feeds = _feeds(24)
    exe = fluid.Executor(fluid.CPUPlace())

    sa = Scope()
    with fluid.scope_guard(sa):
        exe.run(startup)
        la = [np.asarray(exe.run(main, feed=f, fetch_list=[loss])[0])
              for f in feeds]

    sb = Scope()
    with fluid.scope_guard(sb):
        exe.run(startup)
        with exe.prepare(main, feed_specs=feeds[0],
                         fetch_list=[loss]) as prep:
            lb = [np.asarray(prep.run_prepared(f)[0]) for f in feeds]
        # context exit flushed the device-resident state
        pa, pb = _persistables(main, sa), _persistables(main, sb)
    assert len(pa) >= 8  # params + Adam moments + beta pows + lr
    for a, b in zip(la, lb):
        np.testing.assert_array_equal(a, b)
    for name in pa:
        np.testing.assert_array_equal(pa[name], pb[name], err_msg=name)
    # training actually progressed
    assert float(np.ravel(lb[-1])[0]) < float(np.ravel(lb[0])[0])


def test_prepared_checkpoint_sync_and_resume(tmp_path):
    """Mid-loop checkpoint save (forces sync_scope via the io path) +
    load-and-continue: both the continued loop and a fresh-process-style
    resume land exactly on the 20-step run() reference."""
    main, startup, loss = _programs(dropout=False)
    feeds = _feeds(20, seed=7)
    exe = fluid.Executor(fluid.CPUPlace())
    ckpt = str(tmp_path / "ckpt")

    sa = Scope()
    with fluid.scope_guard(sa):
        exe.run(startup)
        for f in feeds[:10]:
            exe.run(main, feed=f, fetch_list=[loss])
        ref10 = _persistables(main, sa)
        for f in feeds[10:]:
            exe.run(main, feed=f, fetch_list=[loss])
        ref20 = _persistables(main, sa)

    sb = Scope()
    with fluid.scope_guard(sb):
        exe.run(startup)
        prep = exe.prepare(main, feed_specs=feeds[0], fetch_list=[loss])
        for f in feeds[:10]:
            prep.run_prepared(f)
        # the save path must flush the device-resident step-10 state
        serial = fluid.io.save_checkpoint(exe, ckpt, main_program=main)
        mid = _persistables(main, sb)
        for name in ref10:
            np.testing.assert_array_equal(ref10[name], mid[name],
                                          err_msg=name)
        for f in feeds[10:]:
            prep.run_prepared(f)
        prep.sync_scope()
        got20 = _persistables(main, sb)
    for name in ref20:
        np.testing.assert_array_equal(ref20[name], got20[name],
                                      err_msg=name)

    # resume: fresh scope, load the mid-loop checkpoint, prepare, finish
    sc = Scope()
    with fluid.scope_guard(sc):
        exe.run(startup)
        fluid.io.load_checkpoint(exe, ckpt, serial, main)
        prep = exe.prepare(main, feed_specs=feeds[10], fetch_list=[loss])
        for f in feeds[10:]:
            prep.run_prepared(f)
        prep.sync_scope()
        res20 = _persistables(main, sc)
    for name in ref20:
        np.testing.assert_array_equal(ref20[name], res20[name],
                                      err_msg=name)


def test_run_and_run_prepared_interleave():
    """run() between prepared steps: the unprepared path flushes the
    device state first (reads current values, donation-safe) and the
    prepared path re-stages from the scope after run() wrote it."""
    main, startup, loss = _programs(dropout=False)
    feeds = _feeds(10, seed=3)
    exe = fluid.Executor(fluid.CPUPlace())

    sa = Scope()
    with fluid.scope_guard(sa):
        exe.run(startup)
        for f in feeds:
            exe.run(main, feed=f, fetch_list=[loss])
        ref = _persistables(main, sa)

    sb = Scope()
    with fluid.scope_guard(sb):
        exe.run(startup)
        prep = exe.prepare(main, feed_specs=feeds[0], fetch_list=[loss])
        for i, f in enumerate(feeds):
            if i == 5:  # unprepared step mid-loop
                exe.run(main, feed=f, fetch_list=[loss])
            else:
                prep.run_prepared(f)
        prep.sync_scope()
        got = _persistables(main, sb)
    for name in ref:
        np.testing.assert_array_equal(ref[name], got[name], err_msg=name)


def test_direct_scope_read_sees_prepared_state():
    """Scope.find_var flushes attached device state: a direct read
    (fetch_var, a pserver handler, a debug probe) between prepared
    steps observes CURRENT values — never a stale copy or a donated
    buffer husk."""
    main, startup, loss = _programs(dropout=False)
    feeds = _feeds(5, seed=11)
    exe = fluid.Executor(fluid.CPUPlace())

    sa = Scope()
    with fluid.scope_guard(sa):
        exe.run(startup)
        for f in feeds:
            exe.run(main, feed=f, fetch_list=[loss])
        ref = _persistables(main, sa)

    sb = Scope()
    with fluid.scope_guard(sb):
        exe.run(startup)
        prep = exe.prepare(main, feed_specs=feeds[0], fetch_list=[loss])
        for f in feeds:
            prep.run_prepared(f)
        # NO explicit sync_scope: the read itself must flush
        for name in ref:
            got = fluid.fetch_var(name, scope=sb)
            np.testing.assert_array_equal(ref[name], got, err_msg=name)


def test_external_scope_write_wins_over_device_state():
    """A raw scope.set of a written persistable between dirty prepared
    steps (a debug weight patch, v2 Parameters.set) must win: the next
    step trains from the externally written value, exactly like run()
    would — the device copy is dropped, not synced over it."""
    main, startup, loss = _programs(dropout=False)
    feeds = _feeds(4, seed=5)
    exe = fluid.Executor(fluid.CPUPlace())
    wname = next(v.name for v in main.list_vars()
                 if v.persistable and v.name.endswith(".w_0"))

    # shape from the desc, NOT scope.find_var: a read would flush the
    # prepared state first — the point is to write while it is dirty
    wshape = tuple(main.global_block().vars[wname].shape)
    new_w = np.full(wshape, 0.25, np.float32)

    def patched_run(scope, runner):
        with fluid.scope_guard(scope):
            exe.run(startup)
            runner(feeds[0])
            scope.set(wname, new_w.copy())  # external write while dirty
            for f in feeds[1:]:
                runner(f)
            return _persistables(main, scope)

    sa = Scope()
    ref = patched_run(
        sa, lambda f: exe.run(main, feed=f, fetch_list=[loss]))
    sb = Scope()
    prep_box = []

    def prepared_runner(f):
        if not prep_box:
            prep_box.append(exe.prepare(main, feed_specs=f,
                                        fetch_list=[loss]))
        prep_box[0].run_prepared(f)

    got = patched_run(sb, prepared_runner)
    for name in ref:
        np.testing.assert_array_equal(ref[name], got[name], err_msg=name)


def test_parent_scope_reader_sees_child_prepared_state():
    """Persistables living in a PARENT scope, training driven from a
    child (local-scope idiom): the prepared program registers on the
    scopes that OWN its state, so a reader rooted at the parent — which
    never walks down into the child — still flushes before reading."""
    main, startup, loss = _programs(dropout=False)
    feeds = _feeds(5, seed=9)
    exe = fluid.Executor(fluid.CPUPlace())

    sa = Scope()
    with fluid.scope_guard(sa):
        exe.run(startup)
        for f in feeds:
            exe.run(main, feed=f, fetch_list=[loss])
        ref = _persistables(main, sa)

    parent = Scope()
    with fluid.scope_guard(parent):
        exe.run(startup)  # persistables land in the parent
    child = parent.new_scope()
    with fluid.scope_guard(child):
        prep = exe.prepare(main, feed_specs=feeds[0], fetch_list=[loss])
        for f in feeds:
            prep.run_prepared(f)
    # NO sync, and the read starts at the PARENT
    for name in ref:
        np.testing.assert_array_equal(
            ref[name], fluid.fetch_var(name, scope=parent),
            err_msg=name)


def test_stale_program_raises_and_pe_repreparess():
    """After a program mutation (version bump by a pass) run_prepared
    refuses the stale entry loudly; ParallelExecutor flushes and
    re-prepares transparently, like its old per-version run() cache."""
    main, startup, loss = _programs(dropout=False)
    feeds = _feeds(3, seed=13)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        prep = exe.prepare(main, feed_specs=feeds[0], fetch_list=[loss])
        prep.run_prepared(feeds[0])
        main.desc.bump_version()
        assert prep.is_stale
        with pytest.raises(RuntimeError, match="mutated"):
            prep.run_prepared(feeds[1])
        prep.sync_scope()

    scope2 = Scope()
    with fluid.scope_guard(scope2):
        exe.run(startup)
        pe = fluid.ParallelExecutor(use_tpu=False, loss_name=loss.name,
                                    main_program=main, scope=scope2,
                                    num_devices=1)
        l0 = pe.run(feed=feeds[0], fetch_list=[loss])[0]
        main.desc.bump_version()
        l1 = pe.run(feed=feeds[1], fetch_list=[loss])[0]  # re-prepared
        assert np.isfinite(np.ravel(l0)).all()
        assert np.isfinite(np.ravel(l1)).all()


def test_prepare_without_feed_specs():
    """Zero-feed programs (scope-resident data) prepare with
    feed_specs omitted."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            w = fluid.layers.create_global_var(
                [4], 0.0, "float32", persistable=True, name="nf_w")
            fluid.layers.increment(w, value=1.0, in_place=True)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        prep = exe.prepare(main, fetch_list=["nf_w"])
        for _ in range(3):
            out = prep.run_prepared()
        np.testing.assert_array_equal(np.asarray(out[0]),
                                      np.full((4,), 3.0, np.float32))


def test_external_write_to_read_only_state_not_masked_by_flush():
    """An external write to READ-ONLY resident state (the classic: a
    user decaying the learning-rate var) while the program is dirty
    must survive the next flush — the flush's epoch fast-forward must
    not mask it, and the following step must train with the new
    value."""

    def sgd_model():
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(x, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        return loss

    main, startup, loss = _programs(sgd_model)
    lr_name = next(v.name for v in main.list_vars()
                   if v.persistable and "learning_rate" in v.name)
    wname = next(v.name for v in main.list_vars()
                 if v.persistable and v.name.endswith(".w_0"))
    feed = {"x": np.ones((2, 4), np.float32),
            "y": np.ones((2, 1), np.float32)}
    exe = fluid.Executor(fluid.CPUPlace())
    scope = Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        prep = exe.prepare(main, feed_specs=feed, fetch_list=[loss])
        prep.run_prepared(feed)  # dirty
        scope.set(lr_name, np.zeros((1,), np.float32))  # lr -> 0
        # flushing read: installs our params AND must notice the lr
        w_after = fluid.fetch_var(wname, scope=scope).copy()
        # with lr=0 the next steps change nothing
        prep.run_prepared(feed)
        prep.run_prepared(feed)
        prep.sync_scope()
        np.testing.assert_array_equal(
            fluid.fetch_var(wname, scope=scope), w_after)


def test_fed_written_persistable_feed_wins():
    """A name that is both FED and WRITTEN by the block: the feed must
    take precedence as the step's input (run() semantics) — the device
    copy kept for sync_scope must never shadow it."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            w = fluid.layers.create_global_var(
                [4], 0.0, "float32", persistable=True, name="fed_w")
            fluid.layers.increment(w, value=1.0, in_place=True)
    exe = fluid.Executor(fluid.CPUPlace())
    feeds = [{"fed_w": np.full((4,), 10.0 * k, np.float32)}
             for k in range(4)]

    def drive(scope, runner):
        with fluid.scope_guard(scope):
            exe.run(startup)
            outs = [np.asarray(runner(f)) for f in feeds]
            return outs, np.asarray(scope.find_var("fed_w")).copy()

    sa = Scope()
    ref_outs, ref_w = drive(
        sa, lambda f: exe.run(main, feed=f, fetch_list=["fed_w"])[0])
    sb = Scope()
    box = []

    def prepared(f):
        if not box:
            box.append(exe.prepare(main, feed_specs=f,
                                   fetch_list=["fed_w"]))
        return box[0].run_prepared(f)[0]

    got_outs, got_w = drive(sb, prepared)
    for a, b in zip(ref_outs, got_outs):
        np.testing.assert_array_equal(a, b)  # each step = its feed + 1
    np.testing.assert_array_equal(ref_w, got_w)


def test_external_write_to_write_only_persistable_wins():
    """A persistable the block writes but never reads: an external
    scope.set between a dirty step and the flush must survive the flush
    (the stale device copy is dropped, not installed over it)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data(name="x", shape=[4], dtype="float32")
            probe = fluid.layers.create_global_var(
                [1], 0.0, "float32", persistable=True, name="probe")
            fluid.layers.assign(fluid.layers.mean(x), output=probe)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = Scope()
    marker = np.full((1,), 123.0, np.float32)
    with fluid.scope_guard(scope):
        exe.run(startup)
        prep = exe.prepare(main, feed_specs=["x"], fetch_list=[])
        prep.run_prepared({"x": np.ones((2, 4), np.float32)})  # dirty
        scope.set("probe", marker.copy())  # external write while dirty
        # the read flushes; the external value must win
        np.testing.assert_array_equal(
            fluid.fetch_var("probe", scope=scope), marker)
        # and the next step recomputes it, exactly like run() would
        prep.run_prepared({"x": np.full((2, 4), 8.0, np.float32)})
        prep.sync_scope()
        np.testing.assert_array_equal(
            fluid.fetch_var("probe", scope=scope),
            np.full((1,), 8.0, np.float32))


def test_prepared_lod_feed_parity():
    """Ragged (LoDTensor) feeds travel the same pad+'@LEN' bridge on the
    prepared path; the prepared signature includes the length vectors."""

    def seq_model():
        ids = fluid.layers.data(name="ids", shape=[1], dtype="int64",
                                lod_level=1)
        emb = fluid.layers.embedding(ids, size=[30, 6])
        pooled = fluid.layers.sequence_pool(emb, pool_type="sum")
        pred = fluid.layers.fc(pooled, size=1)
        loss = fluid.layers.mean(pred)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        return loss

    main, startup, loss = _programs(seq_model)
    rng = np.random.RandomState(0)

    def lod_feed(i):
        lens = [int(rng.randint(1, 6)) for _ in range(3)]
        offs = np.cumsum([0] + lens).tolist()
        flat = rng.randint(0, 30, size=(offs[-1], 1)).astype(np.int64)
        return {"ids": LoDTensor(flat, [offs])}

    feeds = [lod_feed(i) for i in range(6)]
    exe = fluid.Executor(fluid.CPUPlace())

    sa = Scope()
    with fluid.scope_guard(sa):
        exe.run(startup)
        la = [np.asarray(exe.run(main, feed=f, fetch_list=[loss])[0])
              for f in feeds]
    sb = Scope()
    with fluid.scope_guard(sb):
        exe.run(startup)
        prep = exe.prepare(main, feed_specs=feeds[0], fetch_list=[loss])
        lb = [np.asarray(prep.run_prepared(f)[0]) for f in feeds]
        prep.sync_scope()
        pa, pb = _persistables(main, sa), _persistables(main, sb)
    for a, b in zip(la, lb):
        np.testing.assert_array_equal(a, b)
    for name in pa:
        np.testing.assert_array_equal(pa[name], pb[name], err_msg=name)


def test_prepare_rejects_host_ops():
    """Programs the compiled path cannot own whole fall back loudly."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data(name="x", shape=[2], dtype="float32")
            y = fluid.layers.scale(x, scale=2.0)
            fluid.layers.Print(y)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        with pytest.raises(ValueError, match="host op"):
            exe.prepare(main, feed_specs=["x"], fetch_list=[y])


def test_prepared_feed_name_errors():
    main, startup, loss = _programs(dropout=False)
    feeds = _feeds(2)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        prep = exe.prepare(main, feed_specs=feeds[0], fetch_list=[loss])
        with pytest.raises(KeyError, match="expects feed"):
            prep.run_prepared({"x": feeds[0]["x"]})  # 'y' missing


def _build_many_persistables(n=120):
    """n persistable vars, each updated in place every step — the
    scope-round-trip worst case the prepared path exists to kill."""
    ws = []
    for i in range(n):
        w = fluid.layers.create_global_var(
            [4], 0.0, "float32", persistable=True, name="hot_w%d" % i)
        fluid.layers.increment(w, value=1.0, in_place=True)
        ws.append(w)
    return ws[0]


def test_prepared_host_overhead_microbench():
    """Acceptance: on a cached program with >=100 written persistables
    the prepared path's per-step host overhead is >=30% below run()'s
    (it skips the feed-spec key build and 2x100 scope round-trips)."""
    steps = 60
    main, startup, w0 = _programs(_build_many_persistables)
    exe = fluid.Executor(fluid.CPUPlace())

    def timed(fn, sync):
        best = None
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(steps):
                fn()
            np.asarray(sync())  # drain the async chain
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        return best

    sa = Scope()
    with fluid.scope_guard(sa):
        exe.run(startup)
        exe.run(main, feed={}, fetch_list=[w0])  # warm the compile cache
        t_run = timed(
            lambda: exe.run(main, feed={}, fetch_list=[w0],
                            return_numpy=False),
            lambda: sa.find_var(w0.name))

    sb = Scope()
    with fluid.scope_guard(sb):
        exe.run(startup)
        prep = exe.prepare(main, feed_specs={}, fetch_list=[w0])
        prep.run_prepared({})  # warm
        last = []
        t_prep = timed(lambda: last.__setitem__(
            slice(None), prep.run_prepared({})),
            lambda: last[0])
        prep.sync_scope()
        # both paths really ran all steps (warm + 3 timed rounds)
        np.testing.assert_array_equal(
            np.asarray(sb.find_var(w0.name)),
            np.asarray(sa.find_var(w0.name)))
    overhead_ratio = t_prep / t_run
    assert overhead_ratio <= 0.7, (
        "prepared per-step host overhead %.3fms not >=30%% below run() "
        "%.3fms (ratio %.2f)" %
        (t_prep / steps * 1e3, t_run / steps * 1e3, overhead_ratio))


def test_overlapped_post_send_fastwire_error_surfaces():
    """ADVICE high (rpc.py): a fastwire failure AFTER the payload went
    out must not silently fall back to a gRPC resend (double-apply); the
    per-thread exception is captured, the item excluded from the
    fallback, and the error re-raised after the join."""
    from paddle_tpu.distributed.rpc import RPCClient

    c = object.__new__(RPCClient)
    resent = []

    def fast_call(ep, method, payload):
        if ep == "bad:1":
            e = ConnectionError("fastwire send failed mid-payload")
            e.sent_payload = True
            raise e
        return b"ok"

    c._fast_pool = lambda: object()  # non-None: fast path active
    c._fast_call = fast_call
    c._retry_op = lambda *a, **k: resent.append(a) or b"grpc"

    class _FakeFut:
        def result(self):
            return b"grpc"

    class _FakeStub:
        def future(self, payload, **kw):
            return _FakeFut()

    c._stub = lambda ep, method: _FakeStub()

    class _Retry:
        call_timeout = 1.0

    c.retry = _Retry()

    with pytest.raises(ConnectionError, match="mid-payload"):
        c._overlapped("SendVariable", "send_grad",
                      ["good:1", "bad:1"], [b"p0", b"p1"], replay=True,
                      idempotent=False)
    assert resent == []  # the failed item was NOT resent over gRPC
    # the same post-send failure on an IDEMPOTENT read keeps its gRPC
    # fallback: re-fetching cannot double-apply anything
    out = c._overlapped("GetVariable", "get_param",
                        ["good:1", "bad:1"], [b"p0", b"p1"], replay=True)
    assert out == [b"ok", b"grpc"]

    # mixed failures: OTHER endpoints' pre-send (safe) items complete
    # their gRPC fallback BEFORE the post-send error surfaces
    grpc_eps = []

    def fast_call2(ep, method, payload):
        e = ConnectionError("both fail")
        e.sent_payload = ep == "bad:1"
        raise e

    class _FakeStub2:
        def __init__(self, ep):
            self.ep = ep

        def future(self, payload, **kw):
            grpc_eps.append(self.ep)
            return _FakeFut()

    c._fast_call = fast_call2
    c._stub = lambda ep, method: _FakeStub2(ep)
    with pytest.raises(ConnectionError, match="both fail"):
        c._overlapped("SendVariable", "send_grad",
                      ["pre:1", "bad:1"], [b"a", b"b"], replay=True,
                      idempotent=False)
    assert grpc_eps == ["pre:1"]  # safe resend happened, bad excluded


def test_overlapped_pre_send_error_still_falls_back():
    """A failure BEFORE the payload went out is a stale pooled socket:
    the gRPC fallback is safe and must still happen."""
    from paddle_tpu.distributed.rpc import RPCClient

    c = object.__new__(RPCClient)

    def fast_call(ep, method, payload):
        e = ConnectionError("stale pooled connection")
        e.sent_payload = False
        raise e

    c._fast_pool = lambda: object()
    c._fast_call = fast_call

    class _FakeFut:
        def result(self):
            return b"grpc-replied"

    class _FakeStub:
        def future(self, payload, **kw):
            return _FakeFut()

    c._stub = lambda ep, method: _FakeStub()

    class _Retry:
        call_timeout = 1.0

    c.retry = _Retry()
    out = c._overlapped("GetVariable", "get_param", ["a:1"], [b"p"],
                        replay=True)
    assert out == [b"grpc-replied"]
