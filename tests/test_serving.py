"""Serving tier e2e (ISSUE 9): continuous-batching multi-tenant server
on the AOT path — per-request bit-exactness vs a direct
AotExecutable.run, deadline-launch (partial batch) behavior,
bucket-miss fallback to the nearest warm bucket, hot swap under load
with zero dropped requests, the fastwire-framed socket endpoint, and
the serve_bench --quick tier-1 smoke."""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.core.scope import Scope
from paddle_tpu.observability import metrics
from paddle_tpu.serving import (InferenceServer, PredictClient,
                                RemoteError, bucket_ladder)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

D_IN, HIDDEN, D_OUT = 6, 5, 3


def _save_model(dirname, seed, aot_batch=1):
    """Deterministic little fc model; ``seed`` differentiates the
    parameter draw between versions.  Returns a reference fn computing
    outputs through the plain executor path."""
    main, startup = fluid.Program(), fluid.Program()
    scope = Scope()
    init = fluid.initializer.UniformInitializer
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                x = fluid.layers.data(name="x", shape=[D_IN],
                                      dtype="float32")
                h = fluid.layers.fc(
                    x, size=HIDDEN, act="tanh",
                    param_attr=fluid.ParamAttr(
                        initializer=init(-0.5, 0.5, seed=seed)))
                out = fluid.layers.fc(
                    h, size=D_OUT, act="softmax",
                    param_attr=fluid.ParamAttr(
                        initializer=init(-0.5, 0.5, seed=seed + 1)))
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        fluid.io.save_inference_model(
            dirname, ["x"], [out], exe, main_program=main,
            aot_feed_specs={"x": ((aot_batch, D_IN), "float32")})
        infer = main.clone(for_test=True)

        def ref(xs):
            with fluid.scope_guard(scope):
                r, = exe.run(infer, feed={"x": np.asarray(xs)},
                             fetch_list=[out])
            return np.asarray(r)

    return ref


def _xs(rng, n=1):
    return rng.uniform(-1, 1, size=(n, D_IN)).astype(np.float32)


# ---------------------------------------------------------------- unit

def test_bucket_ladder():
    assert bucket_ladder(16) == [1, 2, 4, 8, 16]
    assert bucket_ladder(1) == [1]
    assert bucket_ladder(12) == [1, 2, 4, 8, 12]


def test_request_validation(tmp_path):
    d = str(tmp_path / "m")
    _save_model(d, seed=3)
    with InferenceServer(max_batch=4) as srv:
        srv.load("m", d)
        rng = np.random.RandomState(0)
        with pytest.raises(KeyError):
            srv.submit("nope", {"x": _xs(rng)})
        with pytest.raises(ValueError):
            srv.submit("m", {})                       # missing feed
        with pytest.raises(ValueError):
            srv.submit("m", {"x": _xs(rng)[:, :3]})   # wrong sample dim
        with pytest.raises(ValueError):
            srv.submit("m", {"x": _xs(rng).astype(np.float64)})
        with pytest.raises(ValueError):
            srv.submit("m", {"x": _xs(rng, 5)})       # > max_batch
        with pytest.raises(ValueError):
            srv.load("m", d)                          # dup tenant


# ------------------------------------------------- correctness / e2e

def test_serial_bit_exact_vs_direct_aot(tmp_path):
    """max_wait=0 serial traffic forms batches of 1 on bucket 1 — the
    server's answers must be BIT-exact with a direct AotExecutable.run
    of that bucket's executable."""
    d = str(tmp_path / "m")
    _save_model(d, seed=5)
    rng = np.random.RandomState(1)
    with InferenceServer(max_batch=4, max_wait_us=0) as srv:
        srv.load("m", d)
        direct = srv.engine("m").executable(1)
        assert direct is not None
        for _ in range(5):
            xs = _xs(rng)
            got = srv.predict("m", {"x": xs})
            want = direct.run({"x": xs})[0]
            np.testing.assert_array_equal(
                next(iter(got.values())), np.asarray(want))


def test_concurrent_clients_e2e(tmp_path):
    """Concurrent client threads over BOTH request planes (in-process
    futures + the fastwire-framed socket); every response must match
    the plain-executor reference for its own input."""
    d = str(tmp_path / "m")
    ref = _save_model(d, seed=7)
    n_threads, n_reqs = 6, 12
    errors = []
    with InferenceServer(max_batch=8, max_wait_us=2000) as srv:
        srv.load("m", d)
        port = srv.start_endpoint()

        def client(tid):
            rng = np.random.RandomState(100 + tid)
            try:
                cli = PredictClient("127.0.0.1", port) \
                    if tid % 3 == 0 else None
                for _ in range(n_reqs):
                    xs = _xs(rng)
                    if cli is not None:
                        got = next(iter(
                            cli.predict("m", {"x": xs}).values()))
                    else:
                        got = next(iter(
                            srv.predict("m", {"x": xs}).values()))
                    np.testing.assert_allclose(got, ref(xs),
                                               atol=1e-5)
                if cli is not None:
                    cli.close()
            except Exception as e:
                errors.append(e)

        ts = [threading.Thread(target=client, args=(i,))
              for i in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(120)
        assert not any(t.is_alive() for t in ts), "client thread hung"
    assert not errors, errors[0]


def test_wire_error_paths(tmp_path):
    d = str(tmp_path / "m")
    _save_model(d, seed=9)
    rng = np.random.RandomState(2)
    with InferenceServer(max_batch=4) as srv:
        srv.load("m", d)
        port = srv.start_endpoint()
        with PredictClient("127.0.0.1", port) as cli:
            with pytest.raises(RemoteError, match="unknown model"):
                cli.predict("ghost", {"x": _xs(rng)})
            with pytest.raises(RemoteError, match="serve_max_batch"):
                cli.predict("m", {"x": _xs(rng, 9)})
            # the connection survives error replies
            out = cli.predict("m", {"x": _xs(rng)})
            assert next(iter(out.values())).shape == (1, D_OUT)


# ------------------------------------------------- batching behavior

def test_deadline_launches_partial_batch(tmp_path):
    """A lone request must launch when the max_wait deadline expires —
    never wait for a full batch; a burst that FILLS the batch must
    launch immediately, well before the deadline."""
    d = str(tmp_path / "m")
    _save_model(d, seed=11)
    rng = np.random.RandomState(3)
    wait_s = 0.3
    with InferenceServer(max_batch=4,
                         max_wait_us=int(wait_s * 1e6)) as srv:
        srv.load("m", d)
        srv.predict("m", {"x": _xs(rng)})   # warm
        batches0 = metrics.counter("serve_batches_total").value
        # lone request: held until the deadline, then launched partial
        t0 = time.perf_counter()
        srv.predict("m", {"x": _xs(rng)})
        lone = time.perf_counter() - t0
        assert lone >= wait_s * 0.5, \
            "partial batch launched before the deadline (%.3fs)" % lone
        assert lone < wait_s + 10.0
        # full burst: launches the moment it is full, no deadline wait
        t0 = time.perf_counter()
        futs = [srv.submit("m", {"x": _xs(rng)}) for _ in range(4)]
        for f in futs:
            f.result(30)
        burst = time.perf_counter() - t0
        assert burst < wait_s * 0.5, \
            "full batch waited for the deadline (%.3fs)" % burst
        batches = metrics.counter("serve_batches_total").value - batches0
        assert batches == 2, \
            "expected lone + one coalesced burst batch, got %d" % batches


def test_bucket_miss_falls_to_warm_and_backfills(tmp_path):
    """With only bucket 1 warm, a coalesced batch dispatches row-by-row
    on the warm bucket (correct answers, miss counted) while the ideal
    bucket compiles in the background; once it lands, traffic uses it."""
    d = str(tmp_path / "m")
    ref = _save_model(d, seed=13)
    rng = np.random.RandomState(4)
    miss0 = metrics.counter("serve_bucket_miss_total").value
    with InferenceServer(max_batch=8, max_wait_us=50000) as srv:
        srv.load("m", d, warm=[1])
        assert srv.engine("m").warm_buckets == [1]
        inputs = [_xs(rng) for _ in range(6)]
        futs = [srv.submit("m", {"x": xs}) for xs in inputs]
        for xs, f in zip(inputs, futs):
            got = next(iter(f.result(60).values()))
            np.testing.assert_allclose(got, ref(xs), atol=1e-5)
        assert metrics.counter("serve_bucket_miss_total").value > miss0
        # the background compile fills the missed bucket in
        deadline = time.time() + 60
        while time.time() < deadline:
            if 8 in srv.engine("m").warm_buckets:
                break
            time.sleep(0.05)
        assert 8 in srv.engine("m").warm_buckets, \
            "background bucket compile never landed"
        futs = [srv.submit("m", {"x": xs}) for xs in inputs]
        for xs, f in zip(inputs, futs):
            np.testing.assert_allclose(
                next(iter(f.result(60).values())), ref(xs), atol=1e-5)


# ------------------------------------------------------------- swap

def test_hot_swap_under_load_zero_dropped(tmp_path):
    """swap() under continuous traffic: every request completes, and
    every response classifies cleanly as EXACTLY one model version —
    zero dropped, zero torn."""
    d1, d2 = str(tmp_path / "v1"), str(tmp_path / "v2")
    _save_model(d1, seed=21)
    _save_model(d2, seed=87)
    xs = _xs(np.random.RandomState(5))
    results, errors = [], []
    lock = threading.Lock()
    stop = threading.Event()
    with InferenceServer(max_batch=8, max_wait_us=1000) as srv:
        srv.load("m", d1)
        ref_v1 = next(iter(srv.predict("m", {"x": xs}).values()))

        def load_gen():
            futs = []
            while not stop.is_set():
                futs.append(srv.submit("m", {"x": xs}))
                if len(futs) >= 16:
                    _drain(futs)
                time.sleep(0.001)
            _drain(futs)

        def _drain(futs):
            for f in futs:
                try:
                    with lock:
                        results.append(np.asarray(
                            next(iter(f.result(60).values()))))
                except Exception as e:
                    with lock:
                        errors.append(e)
            del futs[:]

        gen = threading.Thread(target=load_gen)
        gen.start()
        time.sleep(0.15)              # traffic flowing on v1
        srv.swap("m", d2)             # shadow build + atomic flip
        time.sleep(0.15)              # traffic flowing on v2
        stop.set()
        gen.join(120)
        assert not gen.is_alive()
        ref_v2 = next(iter(srv.predict("m", {"x": xs}).values()))
    assert not errors, "dropped/failed requests: %r" % errors[:3]
    assert not np.allclose(ref_v1, ref_v2, atol=1e-5), \
        "versions indistinguishable — the test can't see the swap"
    v1 = sum(1 for o in results if np.allclose(o, ref_v1, atol=1e-5))
    v2 = sum(1 for o in results if np.allclose(o, ref_v2, atol=1e-5))
    assert v1 + v2 == len(results), \
        "torn responses: %d of %d" % (len(results) - v1 - v2,
                                      len(results))
    assert v1 > 0 and v2 > 0, (v1, v2)


def test_multi_tenant_isolation(tmp_path):
    """Two tenants multiplexed in one process answer with their OWN
    parameters."""
    d1, d2 = str(tmp_path / "a"), str(tmp_path / "b")
    ref_a = _save_model(d1, seed=31)
    ref_b = _save_model(d2, seed=77)
    rng = np.random.RandomState(6)
    xs = _xs(rng)
    with InferenceServer(max_batch=4, max_wait_us=0) as srv:
        srv.load("a", d1)
        srv.load("b", d2)
        assert srv.models() == ["a", "b"]
        got_a = next(iter(srv.predict("a", {"x": xs}).values()))
        got_b = next(iter(srv.predict("b", {"x": xs}).values()))
    np.testing.assert_allclose(got_a, ref_a(xs), atol=1e-5)
    np.testing.assert_allclose(got_b, ref_b(xs), atol=1e-5)
    assert not np.allclose(got_a, got_b, atol=1e-5)


def test_cross_row_fetch_rejected_at_load(tmp_path):
    """A fetch without a leading batch dim (cross-row output) cannot be
    sliced back per request — the engine must refuse the model at load,
    not silently mis-slice coalesced batches (MIGRATION.md contract)."""
    d = str(tmp_path / "m")
    main, startup = fluid.Program(), fluid.Program()
    scope = Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                x = fluid.layers.data(name="x", shape=[D_IN],
                                      dtype="float32")
                h = fluid.layers.fc(x, size=D_OUT)
                scalar = fluid.layers.mean(h)      # batch-axis reduce
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        fluid.io.save_inference_model(d, ["x"], [scalar], exe,
                                      main_program=main)
    with InferenceServer(max_batch=4) as srv:
        with pytest.raises(ValueError, match="batch dim leading"):
            srv.load("m", d)


def test_dispatcher_survives_launch_failure(tmp_path):
    """An exception escaping the launch path must fail THAT batch's
    futures and leave the dispatcher alive for later traffic — a dead
    dispatcher wedges the tenant with unresolved futures forever."""
    d = str(tmp_path / "m")
    ref = _save_model(d, seed=41)
    rng = np.random.RandomState(8)
    with InferenceServer(max_batch=4, max_wait_us=0) as srv:
        engine = srv.load("m", d)
        orig = engine.pick_bucket
        trips = {"n": 0}

        def bomb(rows):
            trips["n"] += 1
            raise RuntimeError("synthetic scheduler fault")

        engine.pick_bucket = bomb
        fut = srv.submit("m", {"x": _xs(rng)})
        with pytest.raises(RuntimeError, match="synthetic"):
            fut.result(30)
        engine.pick_bucket = orig
        assert trips["n"] == 1
        xs = _xs(rng)
        got = next(iter(srv.predict("m", {"x": xs}, timeout=30).values()))
        np.testing.assert_allclose(got, ref(xs), atol=1e-5)


# ------------------------------------------------------------ bench

def test_serve_bench_quick_smoke():
    """tools/serve_bench.py --quick completes in seconds on the CPU
    backend and reports the full artifact schema (wired like
    pserver_bench --quick).  Perf gates (speedup/p99) are asserted by
    the full bench run, not here — CI boxes vary.  --mode predict:
    the generate-mode smoke lives in test_generative_serving.py."""
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", SVB_D_IN="32", SVB_HIDDEN="64",
               SVB_MAX_BATCH="8")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve_bench.py"),
         "--quick", "--seconds", "0.4", "--mode", "predict"],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO)
    assert proc.returncode in (0, 1), proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    rec = json.loads(line)
    assert rec["metric"] == "serve_bench"
    assert rec["quick"] is True
    for key in ("floor", "saturated", "poisson", "poisson_under_swap",
                "speedup_vs_floor", "batch_occupancy", "phases",
                "swap", "wire", "aot_load_fallback_total"):
        assert key in rec, key
    assert rec["floor"]["qps"] > 0
    assert rec["poisson"]["completed"] == rec["poisson"]["n_requests"]
    # the hard guarantees hold even in the smoke: nothing dropped or
    # torn across the under-load swap, and the wire answered
    assert rec["swap"]["zero_dropped"] is True
    assert rec["swap"]["torn"] == 0
    assert rec["wire"]["ok"] is True
