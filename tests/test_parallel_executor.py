"""ParallelExecutor SPMD tests: loss parity with single-device Executor
(cf. reference test_parallel_executor_mnist.py comparing PE vs Executor)."""
import numpy as np

import jax
import paddle_tpu.fluid as fluid


def _build_mnist_mlp():
    img = fluid.layers.data(name="img", shape=[64], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    hidden = fluid.layers.fc(img, size=32, act="relu")
    prediction = fluid.layers.fc(hidden, size=10, act="softmax")
    avg_cost = fluid.layers.mean(
        fluid.layers.cross_entropy(input=prediction, label=label))
    return avg_cost


def test_pe_matches_single_device(prog_scope):
    """Same init + same data => PE loss must equal Executor loss, because
    SPMD data parallelism computes the identical global batch math."""
    main, startup, scope = prog_scope
    main.random_seed = 7
    startup.random_seed = 7
    avg_cost = _build_mnist_mlp()
    fluid.optimizer.SGD(learning_rate=0.1).minimize(avg_cost)

    np.random.seed(5)
    data = []
    for _ in range(6):
        xs = np.random.randn(32, 64).astype(np.float32)
        ys = np.random.randint(0, 10, (32, 1)).astype(np.int64)
        data.append((xs, ys))

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    single_losses = []
    for xs, ys in data:
        loss, = exe.run(main, feed={"img": xs, "label": ys},
                        fetch_list=[avg_cost])
        single_losses.append(float(np.asarray(loss).reshape(-1)[0]))

    # fresh scope, same seeds -> same init
    from paddle_tpu.core.scope import Scope
    scope2 = Scope()
    with fluid.scope_guard(scope2):
        exe2 = fluid.Executor(fluid.CPUPlace())
        exe2.run(startup)
        pe = fluid.ParallelExecutor(use_cuda=False,
                                    loss_name=avg_cost.name,
                                    main_program=main, scope=scope2)
        assert pe.device_count == 8, "conftest must force 8 host devices"
        pe_losses = []
        for xs, ys in data:
            loss, = pe.run(fetch_list=[avg_cost],
                           feed={"img": xs, "label": ys})
            pe_losses.append(float(np.asarray(loss).reshape(-1)[0]))

    np.testing.assert_allclose(single_losses, pe_losses, rtol=2e-4,
                               atol=1e-5)
    assert single_losses[-1] < single_losses[0]


def test_pe_batch_divisibility_error(prog_scope):
    main, startup, scope = prog_scope
    avg_cost = _build_mnist_mlp()
    fluid.optimizer.SGD(learning_rate=0.1).minimize(avg_cost)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    pe = fluid.ParallelExecutor(use_cuda=False, loss_name=avg_cost.name,
                                main_program=main)
    xs = np.random.randn(30, 64).astype(np.float32)  # 30 % 8 != 0
    ys = np.random.randint(0, 10, (30, 1)).astype(np.int64)
    try:
        pe.run(fetch_list=[avg_cost], feed={"img": xs, "label": ys})
        raise AssertionError("expected divisibility error")
    except ValueError as e:
        assert "divisible" in str(e)


def test_pe_per_device_feed_list(prog_scope):
    """reference PE accepts a list of per-device feed dicts."""
    main, startup, scope = prog_scope
    avg_cost = _build_mnist_mlp()
    fluid.optimizer.SGD(learning_rate=0.1).minimize(avg_cost)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    pe = fluid.ParallelExecutor(use_cuda=False, loss_name=avg_cost.name,
                                main_program=main)
    feeds = []
    for _ in range(pe.device_count):
        feeds.append({"img": np.random.randn(4, 64).astype(np.float32),
                      "label": np.random.randint(0, 10, (4, 1))
                      .astype(np.int64)})
    loss, = pe.run(fetch_list=[avg_cost], feed=feeds)
    assert np.isfinite(np.asarray(loss)).all()
