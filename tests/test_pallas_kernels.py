"""Pallas TPU kernels, validated in interpret mode on CPU against the
same-math XLA paths (flash attention: Dao et al. online softmax;
fused softmax+CE: one-pass logsumexp+pick)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.kernels import (flash_attention,
                                fused_softmax_cross_entropy)
from paddle_tpu.kernels.flash_attention import _attention_xla
from paddle_tpu.kernels.fused import _xla_path


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_xla(causal):
    rng = np.random.RandomState(0)
    b, h, t, d = 2, 3, 256, 32
    q = jnp.asarray(rng.randn(b, h, t, d), jnp.float32) * 0.2
    k = jnp.asarray(rng.randn(b, h, t, d), jnp.float32) * 0.2
    v = jnp.asarray(rng.randn(b, h, t, d), jnp.float32) * 0.2
    got = flash_attention(q, k, v, causal=causal, block_q=64,
                          block_k=64, interpret=True)
    want = _attention_xla(q, k, v, 1.0 / np.sqrt(d), causal)
    # this host's CPU matmuls run reduced precision (both paths), so
    # different blockings diverge at ~1e-3 absolute
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-2, atol=2e-3)


def test_flash_attention_grads_match_xla():
    rng = np.random.RandomState(1)
    b, h, t, d = 1, 2, 128, 16
    q = jnp.asarray(rng.randn(b, h, t, d), jnp.float32) * 0.2
    k = jnp.asarray(rng.randn(b, h, t, d), jnp.float32) * 0.2
    v = jnp.asarray(rng.randn(b, h, t, d), jnp.float32) * 0.2

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, causal=True, block_q=64,
                               block_k=64, interpret=True).sum()

    def loss_xla(q, k, v):
        return _attention_xla(q, k, v, 1.0 / np.sqrt(d), True).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    # the backward is now the tiled Pallas kernel pair (dQ; dK/dV), not
    # XLA's vjp: different reduction order + this host's reduced-
    # precision CPU matmuls need the usual ~1e-3 comparison window
    for a, b_ in zip(gf, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-3, atol=2e-3)


def test_flash_attention_fallback_on_odd_shapes():
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(1, 1, 100, 16), jnp.float32)  # 100 % 64 != 0
    out = flash_attention(q, q, q, causal=False, interpret=True)
    want = _attention_xla(q, q, q, 0.25, False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-2, atol=5e-3)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("bq,bk", [(64, 64), (32, 64), (64, 32)])
def test_dkv_kernel_grad_parity_vs_generic_vjp(causal, bq, bk):
    """VERDICT r5 weak #2: the TRANSPOSE-FREE _dkv_kernel
    (flash_attention.py) rebuilds pT as [bk, bq] from k @ q.T — pin its
    dK/dV directly against the generic-vjp (XLA autodiff) path, per
    tile shape incl. asymmetric tiles, in interpret mode."""
    from paddle_tpu.kernels.flash_attention import (_flash_bwd_dkv,
                                                    _flash_pallas)

    rng = np.random.RandomState(7)
    b, h, t, d = 1, 2, 128, 16
    scale = 1.0 / np.sqrt(d)
    q = jnp.asarray(rng.randn(b, h, t, d), jnp.float32) * 0.3
    k = jnp.asarray(rng.randn(b, h, t, d), jnp.float32) * 0.3
    v = jnp.asarray(rng.randn(b, h, t, d), jnp.float32) * 0.3
    do = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)

    # generic-vjp reference: differentiate the plain XLA attention
    def f(k_, v_):
        return (_attention_xla(q, k_, v_, scale, causal) * do).sum()

    dk_ref, dv_ref = jax.grad(f, argnums=(0, 1))(k, v)

    # kernel path: forward (for out/lse) then the dkv kernel alone
    out, lse = _flash_pallas(q, k, v, scale, causal, 64, 64,
                             interpret=True)
    delta = (do * out).sum(-1)
    dk, dv = _flash_bwd_dkv(q, k, v, do, lse, delta, scale, causal,
                            bq, bk, interpret=True)
    np.testing.assert_allclose(np.asarray(dk), np.asarray(dk_ref),
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(dv), np.asarray(dv_ref),
                               rtol=2e-3, atol=2e-3)


def test_dkv_tile_overrides_end_to_end():
    """block_q_dkv/block_k_dkv (the flash_tune sweep knobs) change only
    the dK/dV kernel's tiling, never its values."""
    rng = np.random.RandomState(8)
    b, h, t, d = 1, 2, 128, 16
    q = jnp.asarray(rng.randn(b, h, t, d), jnp.float32) * 0.3

    def grads(**kw):
        def loss(q_, k_, v_):
            return flash_attention(q_, k_, v_, causal=True, block_q=64,
                                   block_k=64, interpret=True,
                                   **kw).sum()
        return jax.grad(loss, argnums=(0, 1, 2))(q, q, q)

    base = grads()
    tuned = grads(block_q_dkv=32, block_k_dkv=64)
    for a, b_ in zip(base, tuned):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-4)


def test_fused_ce_matches_xla():
    rng = np.random.RandomState(3)
    n, c = 64, 4096
    logits = jnp.asarray(rng.randn(n, c), jnp.float32)
    labels = jnp.asarray(rng.randint(0, c, n), jnp.int32)
    got = fused_softmax_cross_entropy(logits, labels, block_n=16,
                                      block_c=512, interpret=True)
    want = _xla_path(logits, labels)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_ring_attention_op_dense_path_uses_flash_fallback():
    """The ring_attention op's dense path routes through
    flash_attention (XLA fallback off-TPU) and stays trainable."""
    import paddle_tpu.fluid as fluid

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                x = fluid.layers.data(name="x", shape=[2, 64, 16],
                                      dtype="float32")
                helper = fluid.layer_helper.LayerHelper("attn")
                out_v = helper.create_tmp_variable("float32")
                helper.append_op(type="ring_attention",
                                 inputs={"Q": [x], "K": [x], "V": [x]},
                                 outputs={"Out": [out_v]},
                                 attrs={"causal": True})
                loss = fluid.layers.mean(out_v)
                fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        xv = np.random.RandomState(0).randn(2, 2, 64, 16).astype(
            np.float32)
        l, = exe.run(main, feed={"x": xv}, fetch_list=[loss])
        assert np.isfinite(np.asarray(l)).all()


def test_ring_attention_lse_residual_grads_match_generic():
    """The op-level residual path (LSE wired as an output ->
    ring_attention_grad runs flash_attention_bwd) must produce the same
    gradients as the generic-vjp path (no LSE output, forward re-run
    inside the grad op)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.core.scope import Scope

    B, H, T, D = 2, 2, 16, 8
    rng = np.random.RandomState(3)
    feed = {n: rng.randn(B, H, T, D).astype(np.float32) for n in "qkv"}

    def run(with_lse):
        main, startup = fluid.Program(), fluid.Program()
        scope = Scope()
        with fluid.scope_guard(scope), \
                fluid.program_guard(main, startup), \
                fluid.unique_name.guard():
            qv = fluid.layers.data(name="q", shape=[H, T, D],
                                   dtype="float32")
            kv = fluid.layers.data(name="k", shape=[H, T, D],
                                   dtype="float32")
            vv = fluid.layers.data(name="v", shape=[H, T, D],
                                   dtype="float32")
            for var in (qv, kv, vv):
                var.stop_gradient = False
            helper = fluid.layer_helper.LayerHelper("ring")
            att = helper.create_tmp_variable("float32")
            outputs = {"Out": [att]}
            if with_lse:
                lse = helper.create_tmp_variable("float32")
                lse.stop_gradient = True
                outputs["LSE"] = [lse]
            helper.append_op(type="ring_attention",
                             inputs={"Q": [qv], "K": [kv], "V": [vv]},
                             outputs=outputs, attrs={"causal": True})
            loss = fluid.layers.reduce_sum(att)
            grads = fluid.backward.calc_gradient(loss, [qv, kv, vv])
            exe = fluid.Executor(fluid.CPUPlace())
            return exe.run(main, feed=dict(feed),
                           fetch_list=[g.name for g in grads])

    a = run(with_lse=True)
    b = run(with_lse=False)
    for ga, gb, nm in zip(a, b, "qkv"):
        np.testing.assert_allclose(np.asarray(ga), np.asarray(gb),
                                   atol=1e-4, rtol=1e-4,
                                   err_msg="d%s" % nm)
