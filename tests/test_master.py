"""Fault-tolerant task-queue master (reference go/master/service.go:
280 GetTask, 313 TaskFinished, 341 TaskFailed, 368 lease timeout,
411 snapshot, 455 pass/epoch accounting)."""
import os
import socket
import threading
import time

import numpy as np

from paddle_tpu.distributed.master import (Master, MasterClient,
                                           MasterServer, master_reader)


def test_master_queue_basics():
    m = Master(num_epochs=1)
    m.set_dataset(["a", "b", "c"])
    t1, t2 = m.get_task(), m.get_task()
    assert {t1.payload, t2.payload} == {"a", "b"}
    assert m.counts()["pending"] == 2
    assert m.task_finished(t1.task_id)
    assert not m.task_finished(t1.task_id)  # double-finish rejected
    t3 = m.get_task()
    assert t3.payload == "c"
    m.task_finished(t2.task_id)
    m.task_finished(t3.task_id)
    assert m.get_task() is None  # single epoch complete
    assert m.counts()["done"] == 3


def test_master_lease_timeout_requeues():
    m = Master(lease_timeout=0.15, num_epochs=1)
    m.set_dataset(["x"])
    t = m.get_task()
    assert t.payload == "x"
    got = m.get_task()
    assert isinstance(got, tuple) and got[0] == "wait"  # still leased
    time.sleep(0.2)
    t2 = m.get_task()  # lease expired: same task re-dispatched
    assert t2.payload == "x" and t2.retries == 1
    # the dead worker's stale finish is rejected after re-dispatch wins
    assert m.task_finished(t2.task_id)
    assert m.counts()["done"] == 1


def test_master_retry_cap_fails_task():
    m = Master(max_retry=2, num_epochs=1)
    m.set_dataset(["poison", "fine"])
    for _ in range(3):  # 3 failures > max_retry=2
        t = m.get_task()
        while t.payload != "poison":
            m.task_finished(t.task_id)
            t = m.get_task()
        m.task_failed(t.task_id)
    c = m.counts()
    assert c["failed"] == 1  # poisoned task gave up
    while True:
        t = m.get_task()
        if t is None or isinstance(t, tuple):
            break
        m.task_finished(t.task_id)
    assert m.get_task() is None


def test_master_epochs_roll():
    m = Master(num_epochs=2)
    m.set_dataset(["a", "b"])
    seen = []
    while True:
        t = m.get_task()
        if t is None:
            break
        seen.append((m.counts()["epoch"], t.payload))
        m.task_finished(t.task_id)
    assert sorted(seen) == [(0, "a"), (0, "b"), (1, "a"), (1, "b")]


def test_master_snapshot_recover(tmp_path):
    snap = str(tmp_path / "master.json")
    m = Master(snapshot_path=snap, num_epochs=1)
    m.set_dataset(["a", "b", "c"])
    t = m.get_task()
    m.task_finished(t.task_id)
    m.get_task()  # leave one pending (lease dies with the master)
    # "crash" the master; recover from snapshot
    m2 = Master(snapshot_path=snap, num_epochs=1)
    c = m2.counts()
    assert c["done"] == 1
    assert c["todo"] == 2  # the pending lease was voided back to todo
    remaining = set()
    while True:
        t = m2.get_task()
        if t is None:
            break
        remaining.add(t.payload)
        m2.task_finished(t.task_id)
    assert len(remaining) == 2


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def test_master_over_grpc_with_dead_worker(tmp_path):
    """2 workers, one dies mid-task: every record is delivered exactly
    once across the healthy worker's stream + the dead worker's partial
    consumption is re-dispatched whole (at-least-once dispatch,
    exactly-once completion)."""
    from paddle_tpu import recordio

    # 4 task files x 8 records
    paths = []
    for i in range(4):
        p = str(tmp_path / ("part-%d.rio" % i))
        recordio.write_records(
            p, [("%d:%d" % (i, j)).encode() for j in range(8)])
        paths.append(p)

    m = Master(lease_timeout=0.5, num_epochs=1)
    server = MasterServer(m)
    port = server.start("127.0.0.1:%d" % _free_port())
    ep = "127.0.0.1:%d" % port
    try:
        client = MasterClient(ep)
        client.set_dataset(paths)

        # dead worker: leases a task and vanishes without finishing
        dead = client.get_task()
        assert dead is not None

        got = []
        r = master_reader(ep, deserializer=lambda b: b.decode())

        def consume():
            for rec in r():
                got.append(rec)

        w = threading.Thread(target=consume)
        w.start()
        w.join(timeout=30)
        assert not w.is_alive()

        expected = sorted("%d:%d" % (i, j)
                          for i in range(4) for j in range(8))
        assert sorted(got) == expected  # exactly once each
        assert m.counts()["done"] == 4
    finally:
        server.stop()


def test_master_ha_takeover_completes_dataset_once(tmp_path):
    """Kill the active master mid-epoch; a standby takes over the
    leader lock, recovers from the shared snapshot, re-registers, and
    the HA client finishes the dataset — every task completed exactly
    once (reference go/master/etcd_client.go:27-31 leader election +
    snapshot recovery)."""
    from paddle_tpu.distributed.discovery import (HAMasterClient,
                                                  MasterHA)

    root = str(tmp_path / "svc")
    os.makedirs(root)
    n_tasks = 8
    ttl = 1.0

    ep_a = "127.0.0.1:%d" % _free_port()
    a = MasterHA(root, ep_a, ttl=ttl, lease_timeout=5.0)
    a.campaign(timeout=10)

    client = HAMasterClient(root, timeout=30.0, ttl=ttl)
    client.set_dataset(list(range(n_tasks)))

    finished = []
    for _ in range(3):  # first tranche under master A
        t = client.get_task()
        client.task_finished(t.task_id)
        finished.append(t.task_id)

    # A dies (no clean release: simulate a crash by only stopping the
    # server; the lock goes stale and is STOLEN after ttl)
    a.registry.unregister(MasterHA.KIND, ep_a)
    a.server.stop()
    if a.lock._stop is not None:
        a.lock._stop.set()  # heartbeat stops; holder looks dead

    ep_b = "127.0.0.1:%d" % _free_port()
    b = MasterHA(root, ep_b, ttl=ttl, lease_timeout=5.0)
    b.campaign(timeout=30)  # blocks until A's lock is stale, recovers

    try:
        while True:
            t = client.get_task()
            if t is None:
                break
            client.task_finished(t.task_id)
            finished.append(t.task_id)
        # exactly once: completed set == dataset, no duplicates (the
        # finished tasks survived in the snapshot; only unleased todo
        # work was re-dispatched)
        assert sorted(finished) == list(range(n_tasks)), finished
        counts = client.counts()
        assert counts["done"] == n_tasks and counts["failed"] == 0
    finally:
        b.stop()


def test_endpoint_registry_and_lock(tmp_path):
    from paddle_tpu.distributed.discovery import (EndpointRegistry,
                                                  FileLock)

    root = str(tmp_path / "reg")
    reg = EndpointRegistry(root, ttl=0.5)
    reg.register("pserver", "h1:1", heartbeat=False)
    reg.register("pserver", "h2:2", heartbeat=False)
    assert reg.wait_for("pserver", 2, timeout=2) == ["h1:1", "h2:2"]
    time.sleep(0.7)  # no heartbeat -> both expire
    assert reg.list("pserver") == []

    l1 = FileLock(os.path.join(root, "l"), ttl=0.5)
    l2 = FileLock(os.path.join(root, "l"), ttl=0.5)
    assert l1.try_acquire()
    assert not l2.try_acquire()     # held + heartbeating
    l1._stop.set()                  # holder "crashes"
    time.sleep(0.8)
    assert l2.try_acquire()         # stale lock stolen
    l2.release()


def test_lock_steal_is_single_winner(tmp_path):
    """The stale-lock steal goes through an O_EXCL intent file: while
    one candidate's steal is in flight, every other candidate backs
    off (split-brain guard)."""
    from paddle_tpu.distributed.discovery import FileLock

    path = os.path.join(str(tmp_path), "l")
    holder = FileLock(path, ttl=0.4)
    assert holder.try_acquire()
    holder._stop.set()          # holder crashes (heartbeat stops)
    time.sleep(0.6)

    a = FileLock(path, ttl=0.4)
    b = FileLock(path, ttl=0.4)
    # b observes a steal in progress -> must NOT acquire
    open(path + ".steal", "w").write("other")
    assert not b.try_acquire()
    os.remove(path + ".steal")
    # now a steals cleanly; b then sees a FRESH lock and backs off
    assert a.try_acquire()
    assert not b.try_acquire()
    a.release()


def test_lock_tokens_distinct_within_one_thread(tmp_path):
    """Two FileLock instances created in the SAME thread must carry
    distinct tokens, or the non-holder's release()/heartbeat could act
    on the holder's lock (in-process active+standby fencing)."""
    from paddle_tpu.distributed.discovery import FileLock

    path = os.path.join(str(tmp_path), "l")
    a = FileLock(path, ttl=5.0)
    b = FileLock(path, ttl=5.0)
    assert a.token != b.token
    assert a.try_acquire()
    # b never acquired: its release must NOT remove a's lock file
    b.release()
    assert os.path.exists(path)
    assert not b.try_acquire()
    a.release()
    assert not os.path.exists(path)


def test_trainer_discovers_pservers_via_registry(tmp_path, monkeypatch):
    """Trainer._dist_transpile_if_necessary resolves pserver endpoints
    from the discovery registry when PADDLE_DISCOVERY_ROOT +
    PADDLE_PSERVERS_EXPECTED are set (reference
    go/pserver/etcd_client.go registration/watch), instead of the
    static IP list."""
    from paddle_tpu.distributed.discovery import EndpointRegistry

    root = str(tmp_path / "disc")
    reg = EndpointRegistry(root)
    reg.register("pserver", "10.0.0.1:6174", heartbeat=False)
    reg.register("pserver", "10.0.0.2:6174", heartbeat=False)

    captured = {}

    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import trainer as trainer_mod

    class FakeTranspiler:
        def transpile(self, tid, program=None, startup_program=None,
                      pservers=None, trainers=None):
            captured["pservers"] = pservers

        def get_trainer_program(self):
            return fluid.Program()

    monkeypatch.setattr(trainer_mod, "DistributeTranspiler",
                        FakeTranspiler)
    monkeypatch.setenv("PADDLE_TRAINING_ROLE", "TRAINER")
    monkeypatch.setenv("PADDLE_DISCOVERY_ROOT", root)
    monkeypatch.setenv("PADDLE_PSERVERS_EXPECTED", "2")
    monkeypatch.setenv("PADDLE_TRAINER_ID", "0")
    monkeypatch.setenv("PADDLE_TRAINERS", "1")
    monkeypatch.delenv("PADDLE_PSERVER_IPS", raising=False)

    t = trainer_mod.Trainer.__new__(trainer_mod.Trainer)
    t.train_program = fluid.Program()
    t.startup_program = fluid.Program()
    t.scope = fluid.Scope()
    t.checkpoint_cfg = None
    t._dist_transpile_if_necessary()
    assert captured["pservers"] == "10.0.0.1:6174,10.0.0.2:6174"


def test_pserver_shard_checkpoint_roundtrip(tmp_path):
    """VariableServer persists its parameter shard and a restarted
    server resumes from it (reference go/pserver/service.go:346)."""
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.distributed.rpc import VariableServer

    d = os.path.join(str(tmp_path), "shard")
    scope = Scope()
    scope.set("w", np.arange(6, dtype=np.float32).reshape(2, 3))
    scope.set("emb/part0", np.ones((4,), np.float32))
    srv = VariableServer(scope, {}, lambda b: None, fanin=1,
                         checkpoint_dir=d, checkpoint_every_n=1)
    srv.save_shard(d)
    # mutate (a later round), snapshot again: atomic replace
    scope.set("w", np.full((2, 3), 7.0, np.float32))
    srv.save_shard(d)

    scope2 = Scope()
    VariableServer(scope2, {}, lambda b: None, fanin=1,
                   checkpoint_dir=d)  # auto-restores on construction
    np.testing.assert_allclose(np.asarray(scope2.find_var("w")),
                               np.full((2, 3), 7.0))
    np.testing.assert_allclose(np.asarray(scope2.find_var("emb/part0")),
                               np.ones((4,)))


def test_pserver_checkpoint_survives_crash_between_renames(tmp_path):
    """Crash window: dirname renamed to .old but tmp not yet in place —
    restore must find the .old fallback, and _applied_round must come
    back from _SUCCESS."""
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.distributed.rpc import VariableServer

    d = os.path.join(str(tmp_path), "shard")
    s1 = Scope()
    s1.set("under__scored", np.full((3,), 5.0, np.float32))
    srv = VariableServer(s1, {}, lambda b: None, fanin=1)
    srv._applied_round = 17
    srv.save_shard(d)
    os.rename(d, d + ".old")  # simulate the torn swap

    s2 = Scope()
    srv2 = VariableServer(s2, {}, lambda b: None, fanin=1,
                          checkpoint_dir=d)
    assert srv2._applied_round == 17
    # injective name mapping: double underscores survive round-trip
    np.testing.assert_allclose(
        np.asarray(s2.find_var("under__scored")), 5.0)


def test_pserver_remote_profile_toggle(tmp_path):
    """Trainer-driven pserver profiling (reference send_recv.proto:76
    VariableMessage.profile): ToggleProfile(on) starts the server-side
    profiler, ToggleProfile(off) writes the table to the given path."""
    import numpy as np

    from paddle_tpu.core.scope import Scope
    from paddle_tpu.distributed.rpc import RPCClient, VariableServer

    scope = Scope()
    scope.set("w", np.zeros(4, np.float32))
    applied = []
    srv = VariableServer(scope, {"w@GRAD": 0}, applied.append, fanin=1)
    port = srv.start("127.0.0.1:0")
    ep = "127.0.0.1:%d" % port
    RPCClient.reset()  # fresh round counter for the fresh server
    cli = RPCClient.instance()
    prof_path = str(tmp_path / "ps_profile")
    try:
        cli.toggle_profile([ep], True)
        # profiled work: one sync round through the server
        cli.send_var(ep, "w@GRAD", np.ones(4, np.float32))
        cli.send_barrier([ep])
        cli.toggle_profile([ep], False, profile_path=prof_path)
        assert applied == [0]
        text = open(prof_path).read()
        assert "Event" in text or len(text) > 0
    finally:
        cli.send_complete([ep])
        srv.wait()
