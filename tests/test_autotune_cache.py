"""Persistent shape-keyed autotune cache (paddle_tpu/tuning): sweep
writes an entry, a fresh process's lowering picks it up, a cached tile
config provably changes the lowered kernel's grid/block spec, the
executor compile-cache key tracks the cache state, and corrupt/missing
cache files degrade to defaults without error (ISSUE 7 acceptance)."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu.fluid as fluid  # noqa: F401 — registers ops
from paddle_tpu import tuning
from paddle_tpu.core.flags import FLAGS
from paddle_tpu.kernels import matmul_fused

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def cache_dir(tmp_path):
    old = FLAGS.autotune_cache_dir
    FLAGS.autotune_cache_dir = str(tmp_path)
    tuning.invalidate()
    yield str(tmp_path)
    FLAGS.autotune_cache_dir = old
    tuning.invalidate()


def test_record_lookup_roundtrip(cache_dir):
    assert tuning.lookup("matmul_fused", (16, 128, 256),
                         "float32") is None
    fp0 = tuning.fingerprint()
    assert tuning.record("matmul_fused", (16, 128, 256), "float32",
                         {"block_m": 16, "block_n": 128,
                          "block_k": 128}, ms=1.25, source="test")
    cfg = tuning.lookup("matmul_fused", (16, 128, 256), "float32")
    assert cfg == {"block_m": 16, "block_n": 128, "block_k": 128}
    # different shape/dtype/kernel miss
    assert tuning.lookup("matmul_fused", (16, 128, 512),
                         "float32") is None
    assert tuning.lookup("matmul_fused", (16, 128, 256),
                         "bfloat16") is None
    assert tuning.lookup("flash_attention", (16, 128, 256),
                         "float32") is None
    # the fingerprint changed -> executor compile cache cannot serve a
    # stale executable
    assert tuning.fingerprint() != fp0
    # file on disk is the human-readable JSON
    with open(tuning.cache_path()) as f:
        data = json.load(f)
    assert any("matmul_fused|16x128x256" in k for k in data["entries"])


def test_disabled_cache_is_inert():
    old = FLAGS.autotune_cache_dir
    FLAGS.autotune_cache_dir = ""
    tuning.invalidate()
    try:
        assert tuning.cache_path() is None
        assert tuning.lookup("matmul_fused", (1, 2, 3),
                             "float32") is None
        assert tuning.record("matmul_fused", (1, 2, 3), "float32",
                             {"block_m": 8}) is False
        assert tuning.fingerprint() == ("", 0, 0)
    finally:
        FLAGS.autotune_cache_dir = old
        tuning.invalidate()


def test_corrupt_cache_degrades_to_defaults(cache_dir):
    with open(os.path.join(cache_dir, tuning.CACHE_FILE), "w") as f:
        f.write("{not json!!")
    assert tuning.lookup("matmul_fused", (16, 128, 256),
                         "float32") is None
    # a kernel call with the corrupt cache present still runs (defaults)
    x = jnp.ones((8, 128), jnp.float32)
    w = jnp.ones((128, 128), jnp.float32)
    y = matmul_fused.matmul_epilogue(x, w, interpret=True)
    assert np.asarray(y).shape == (8, 128)
    # and record() recovers the file
    assert tuning.record("matmul_fused", (8, 128, 128), "float32",
                         {"block_m": 8})
    assert tuning.lookup("matmul_fused", (8, 128, 128),
                         "float32") == {"block_m": 8}


def _capture_grids(monkeypatch):
    grids = []
    orig = matmul_fused._pallas_call

    def spy(kernel, **kwargs):
        grids.append(kwargs.get("grid"))
        return orig(kernel, **kwargs)

    monkeypatch.setattr(matmul_fused, "_pallas_call", spy)
    return grids


def test_cached_tile_config_changes_grid(cache_dir, monkeypatch):
    """ACCEPTANCE: a cached tile config changes the lowered kernel's
    grid/block spec.  Same call, same shape — the only difference is
    the cache entry, and the pallas grid provably follows it."""
    grids = _capture_grids(monkeypatch)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(32, 256), jnp.float32)
    w = jnp.asarray(rng.randn(256, 256) * 0.1, jnp.float32)

    y0 = matmul_fused.matmul_epilogue(x, w, interpret=True)
    # defaults: blocks clamp to (32, 256, 256) -> grid (1, 1, 1)
    assert grids[-1] == (1, 1, 1)

    tuning.record("matmul_fused", (32, 256, 256), "float32",
                  {"block_m": 8, "block_n": 128, "block_k": 128},
                  source="test")
    y1 = matmul_fused.matmul_epilogue(x, w, interpret=True)
    assert grids[-1] == (4, 2, 2)   # 32/8, 256/128, 256/128
    np.testing.assert_allclose(np.asarray(y0), np.asarray(y1),
                               rtol=1e-5, atol=1e-5)


def test_flash_attention_blocks_from_cache(cache_dir, monkeypatch):
    """The flash kernels resolve None block args through the cache: the
    tuned block_q/block_k reshape the pallas grid."""
    import importlib

    # the kernels package re-exports the flash_attention FUNCTION under
    # the same name; import_module gets the module itself
    fa = importlib.import_module("paddle_tpu.kernels.flash_attention")
    grids = []
    orig = fa.pl.pallas_call

    def spy(kernel, **kwargs):
        grids.append(kwargs.get("grid"))
        return orig(kernel, **kwargs)

    monkeypatch.setattr(fa.pl, "pallas_call", spy)
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(1, 2, 256, 64), jnp.float32)
    k = jnp.asarray(rng.randn(1, 2, 256, 64), jnp.float32)
    v = jnp.asarray(rng.randn(1, 2, 256, 64), jnp.float32)
    out0, _ = fa.flash_attention_fwd_lse(q, k, v, causal=True,
                                         interpret=True)
    # defaults clamp to T=256 -> one q tile, one k tile
    assert grids[-1] == (2, 1, 1)
    tuning.record("flash_attention", (1, 2, 256, 64, 256), "float32",
                  {"block_q": 64, "block_k": 128}, source="test")
    out1, _ = fa.flash_attention_fwd_lse(q, k, v, causal=True,
                                         interpret=True)
    assert grids[-1] == (2, 4, 2)   # t/64, tk/128
    np.testing.assert_allclose(np.asarray(out0), np.asarray(out1),
                               rtol=1e-5, atol=1e-5)


def test_conv_impl_from_cache(cache_dir, monkeypatch):
    """conv_tune.py's recorded winner ('xla' vs 'pallas') steers the
    fused conv lowering's force_xla choice."""
    from paddle_tpu.kernels import conv_fused
    from paddle_tpu.ops import nn as ops_nn
    from paddle_tpu.core.lowering import Ins

    calls = []
    orig = conv_fused.conv2d_nhwc

    def spy(*args, **kwargs):
        calls.append(kwargs.get("force_xla", False))
        return orig(*args, **kwargs)

    monkeypatch.setattr(conv_fused, "conv2d_nhwc", spy)

    rng = np.random.RandomState(0)
    ins = Ins({
        "Input": [jnp.asarray(rng.randn(2, 8, 8, 4), jnp.float32)],
        "Filter": [jnp.asarray(rng.randn(3, 3, 4, 8) * 0.1,
                               jnp.float32)],
        "Scale": [jnp.asarray(rng.rand(8) + 0.5, jnp.float32)],
        "Bias": [jnp.asarray(rng.randn(8), jnp.float32)],
        "Mean": [jnp.asarray(rng.randn(8) * 0.1, jnp.float32)],
        "Variance": [jnp.asarray(rng.rand(8) + 0.5, jnp.float32)],
    })

    class _Ctx:
        mode = "train"
        amp = False

    class _Op:
        outputs = {}

    attrs = {"strides": [1, 1], "paddings": [1, 1], "epsilon": 1e-5,
             "momentum": 0.9, "act": "relu"}
    ops_nn._fused_conv_bn_lower(_Ctx(), ins, attrs, _Op())
    assert calls[-1] is False
    shape = (2, 8, 8, 4, 3, 3, 4, 8, 1, 1, 1, 1)
    tuning.record("fused_conv2d_bn_act", shape, "float32",
                  {"impl": "xla"}, source="test")
    ops_nn._fused_conv_bn_lower(_Ctx(), ins, attrs, _Op())
    assert calls[-1] is True


def test_executor_cache_key_tracks_cache_state(cache_dir):
    """The compile-cache key includes the tuning fingerprint: an
    in-process record() (or a new cache file) changes the key, so a
    re-tuned cache never serves a stale executable."""
    from paddle_tpu.core import executor_impl

    prog = fluid.Program().desc
    key0 = executor_impl._cache_key(prog, 0, ("spec",), ["f"], "train")
    tuning.record("matmul_fused", (1, 2, 3), "float32",
                  {"block_m": 8}, source="test")
    key1 = executor_impl._cache_key(prog, 0, ("spec",), ["f"], "train")
    assert key0 != key1


def test_fresh_process_lowering_picks_up_cache(cache_dir):
    """ACCEPTANCE: an entry written by one process (the sweep) is
    consulted by a FRESH process's lowering via the
    FLAGS_autotune_cache_dir env contract."""
    tuning.record("matmul_fused", (32, 256, 256), "float32",
                  {"block_m": 8, "block_n": 128, "block_k": 128},
                  source="parent")
    code = """
import numpy as np, jax.numpy as jnp
from paddle_tpu.kernels import matmul_fused
grids = []
orig = matmul_fused._pallas_call
def spy(kernel, **kw):
    grids.append(kw.get("grid"))
    return orig(kernel, **kw)
matmul_fused._pallas_call = spy
x = jnp.ones((32, 256), jnp.float32)
w = jnp.ones((256, 256), jnp.float32)
matmul_fused.matmul_epilogue(x, w, interpret=True)
print("GRID", grids[-1])
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               FLAGS_autotune_cache_dir=cache_dir)
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=240,
                         cwd=REPO)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "GRID (4, 2, 2)" in out.stdout, out.stdout


def test_tune_tools_record_into_cache(cache_dir, monkeypatch):
    """All three tune tools persist winners (acceptance): their record
    paths write entries keyed exactly as the lowerings look them up."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    argv = sys.argv
    sys.argv = [argv[0]]     # the tools read argv[1] as a step count
    try:
        import conv_tune
        import flash_tune
        import matmul_tune
    finally:
        sys.argv = argv
        sys.path.pop(0)
    # conv_tune: stage winner -> impl choice under the lowering's key
    stage = ("r1_3x3", 56, 64, 64, 3, 1, 1)
    conv_tune._record_stage(stage, {"fused": 2.0, "nhwc": 1.0,
                                    "nchw": 1.5})
    key_shape = (conv_tune.BATCH, 56, 56, 64, 3, 3, 64, 64, 1, 1, 1, 1)
    assert tuning.lookup("fused_conv2d_bn_act", key_shape,
                         "bfloat16") == {"impl": "xla"}
    # flash_tune: best config under the flash key
    flash_tune._record_best((1024, 1024, 512, 1024, 1024, 512), 0.012)
    cfg = tuning.lookup(
        "flash_attention",
        (flash_tune.B, flash_tune.H, flash_tune.T, flash_tune.D,
         flash_tune.T), "bfloat16")
    assert cfg["block_q"] == 1024 and cfg["block_q_dkv"] == 1024
    # matmul_tune: one real (tiny) sweep stage end to end
    monkeypatch.setattr(matmul_tune, "TILE_GRID", [(8, 128, 128)])
    monkeypatch.setattr(matmul_tune, "STEPS", 1)
    best_cfg, _ = matmul_tune.tune_stage("tiny", 16, 128, 128, "",
                                         False, dtype=jnp.float32)
    assert best_cfg == {"block_m": 8, "block_n": 128, "block_k": 128}
    assert tuning.lookup("matmul_fused", (16, 128, 128),
                         "float32") == best_cfg
