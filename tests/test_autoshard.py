"""ISSUE 20 unit gates for the elastic SPMD runtime
(paddle_tpu/parallel/spmd.py): annotation propagation through the
ShardingPass, measured-cost ingestion (autotune cache / TSDB history /
calibration), search determinism, and a live small-mesh reshard with
loss parity.
"""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.core.scope import Scope
from paddle_tpu.parallel import spmd


def _mlp(main, startup):
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data(name="x", shape=[16], dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="float32")
            h = fluid.layers.fc(input=x, size=32, act="relu")
            out = fluid.layers.fc(input=h, size=1)
            loss = fluid.layers.reduce_mean(
                fluid.layers.square(out - y))
            fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
    return loss


def _transformer(main, startup, **kw):
    from paddle_tpu.models.transformer import get_model
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            args = dict(vocab_size=32, seq_len=8, d_model=16, n_head=2,
                        n_layers=1, d_ff=32)
            args.update(kw)
            loss, feeds, _ = get_model(**args)
    return loss, feeds


# ---------------------------------------------------------------------------
# propagation
# ---------------------------------------------------------------------------

class TestPropagation:
    def test_seed_propagates_to_activations_grads_and_moments(self):
        """A column-sharded fc weight must imply: sharded matmul output,
        mirrored weight @GRAD, mirrored optimizer slots — without any of
        them being seeded explicitly."""
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                x = fluid.layers.data(name="x", shape=[16],
                                      dtype="float32")
                y = fluid.layers.data(name="y", shape=[1],
                                      dtype="float32")
                h = fluid.layers.fc(input=x, size=32)
                out = fluid.layers.fc(input=h, size=1)
                loss = fluid.layers.reduce_mean(
                    fluid.layers.square(out - y))
                fluid.optimizer.Adam(
                    learning_rate=0.01).minimize(loss)
        block = main.desc.blocks[0]
        w0 = next(op.input("Y")[0]
                  for op in block.ops if op.type == "mul")
        pl = spmd.Placement({"tp": 2}, {w0: (None, "tp")}, 0.0, [],
                            "tp2")
        spmd.apply_placement(main, pl)
        sh = main.desc.var_shardings
        # the seed survived
        assert sh[w0] == (None, "tp")
        # grad mirror
        assert sh.get(w0 + "@GRAD") == (None, "tp")
        # Adam moments mirror the param's layout
        moments = [n for n in sh
                   if n.startswith(w0) and "moment" in n.lower()]
        assert moments, "no optimizer-state mirrors for %s" % w0
        for m in moments:
            assert sh[m] == (None, "tp"), m
        # the matmul output inherited the column shard on its last dim
        out_name = next(op.output("Out")[0] for op in block.ops
                        if op.type == "mul" and w0 in op.input("Y"))
        assert sh.get(out_name, (None, None))[-1] == "tp"

    def test_propagation_respects_rank(self):
        """Annotations never exceed the var's rank and never duplicate
        a mesh axis within one var."""
        main, startup = fluid.Program(), fluid.Program()
        _mlp(main, startup)
        pl = spmd.auto_shard(main, 8, cost_model=spmd.CostModel(),
                             batch_size=8)
        spmd.apply_placement(main, pl)
        block = main.desc.blocks[0]
        for name, spec in main.desc.var_shardings.items():
            var = block.find_var_recursive(name)
            if var is None or not var.shape:
                continue
            assert len(spec) == len(var.shape), (name, spec, var.shape)
            axes = [a for a in spec if a]
            assert len(axes) == len(set(axes)), (name, spec)

    def test_pass_is_idempotent_at_fixpoint(self):
        """A second ShardingPass run over an already-annotated program
        adds nothing (the pass reports 0 rewrites, so the PassManager
        fixpoint terminates)."""
        main, startup = fluid.Program(), fluid.Program()
        _mlp(main, startup)
        pl = spmd.auto_shard(main, 4, cost_model=spmd.CostModel(),
                             batch_size=8)
        spmd.apply_placement(main, pl)
        first = dict(main.desc.var_shardings)
        spmd.apply_placement(main, pl)
        assert dict(main.desc.var_shardings) == first


# ---------------------------------------------------------------------------
# cost ingestion
# ---------------------------------------------------------------------------

class TestCostIngestion:
    def test_autotune_entry_overrides_roofline(self):
        key_ms = 7.25
        from paddle_tpu import tuning
        key = tuning.make_key("mul", (8, 16, 32), "float32", "cpu")
        cm = spmd.CostModel(
            kernel_table={key: {"ms": key_ms,
                                "source": "autotune:%s" % key}})
        got = cm.kernel_ms("mul", (8, 16, 32))
        assert got == key_ms
        assert cm.trace[-1]["source"].startswith("autotune:")
        # uncached shape falls back to the roofline, and says so
        cm.kernel_ms("mul", (8, 16, 64))
        assert cm.trace[-1]["source"] == "model:roofline"

    def test_tsdb_history_drives_prediction(self):
        """A strategy with measured step history is predicted from that
        history, with tsdb provenance in the trace."""
        main, startup = fluid.Program(), fluid.Program()
        _mlp(main, startup)
        cm = spmd.CostModel(step_history={
            "dp4": {"ms": 42.0, "n": 3,
                    "source": "tsdb:autoshard.step_ms.dp4"}})
        pl = spmd.auto_shard(main, 4, cost_model=cm, batch_size=8)
        considered = {t["term"]: t for t in pl.trace}
        hist_terms = [t for t in pl.trace
                      if t["term"] == "history:dp4"] or \
                     [t for t in pl.trace
                      if str(t.get("source", "")).startswith("tsdb:")]
        assert hist_terms or pl.strategy == "dp4", considered

    def test_pessimistic_calibration_protects_measurements(self):
        """When history says the measured strategy is SLOWER than the
        roofline claims, unmeasured strategies get charged the same
        measured/model ratio — an optimistic analytic estimate cannot
        outrank a real measurement."""
        main, startup = fluid.Program(), fluid.Program()
        _mlp(main, startup)
        # predict dp4's model-only cost first
        cm0 = spmd.CostModel()
        _, model_ms, _, _, _ = spmd._strategy_cost(
            main.desc, {"dp": 4}, cm0, 8)
        # history: dp4 measured 10x worse than the model thinks
        cm = spmd.CostModel(step_history={
            "dp4": {"ms": model_ms * 10.0, "n": 2,
                    "source": "tsdb:autoshard.step_ms.dp4"}})
        pl = spmd.auto_shard(main, 4, cost_model=cm, batch_size=8)
        # every model-only candidate carries the calibration term
        cal = [t for t in pl.trace
               if t.get("source") == "tsdb:calibration"]
        considered = [t for t in pl.trace
                      if t["term"].startswith("considered:")]
        if pl.strategy != "dp4":
            assert cal, "chosen model-only strategy lacks calibration"
            assert cal[-1]["scale"] >= 9.9
        else:
            assert considered  # search still ranked alternatives

    def test_from_repo_degrades_without_stores(self, monkeypatch):
        monkeypatch.delenv("FLAGS_tsdb_dir", raising=False)
        cm = spmd.CostModel.from_repo(tsdb_dir=None)
        assert isinstance(cm, spmd.CostModel)
        # roofline still prices a kernel
        assert cm.kernel_ms("mul", (4, 8, 8)) > 0


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------

class TestSearch:
    def test_deterministic(self):
        main, startup = fluid.Program(), fluid.Program()
        _transformer(main, startup)
        runs = []
        for _ in range(3):
            pl = spmd.auto_shard(main, 8,
                                 cost_model=spmd.CostModel(),
                                 batch_size=8)
            runs.append((pl.strategy, dict(pl.mesh_axes),
                         round(pl.predicted_ms, 6),
                         sorted(pl.var_shardings.items())))
        assert runs[0] == runs[1] == runs[2]

    def test_every_cost_term_has_provenance(self):
        main, startup = fluid.Program(), fluid.Program()
        _transformer(main, startup)
        pl = spmd.auto_shard(main, 8, cost_model=spmd.CostModel(),
                             batch_size=8)
        assert pl.trace
        for term in pl.trace:
            assert term.get("source"), term

    def test_search_covers_legal_factorizations(self):
        main, startup = fluid.Program(), fluid.Program()
        _transformer(main, startup)
        names = [spmd.strategy_name(a)
                 for a in spmd.enumerate_strategies(main.desc, 8, 8)]
        assert "dp8" in names
        assert any("tp" in n for n in names)
        # the transformer attention lowers through ring_attention ops,
        # so sp legs are legal for it...
        assert any("sp" in n for n in names)
        # ...but a ring-free program must not get sp legs
        mlp_main, mlp_startup = fluid.Program(), fluid.Program()
        _mlp(mlp_main, mlp_startup)
        mlp_names = [spmd.strategy_name(a)
                     for a in spmd.enumerate_strategies(mlp_main.desc, 8, 8)]
        assert not any("sp" in n for n in mlp_names)

    def test_illegal_device_count_raises(self):
        main, startup = fluid.Program(), fluid.Program()
        _mlp(main, startup)
        with pytest.raises(ValueError):
            spmd.auto_shard(main, 0, cost_model=spmd.CostModel())


# ---------------------------------------------------------------------------
# reshard (small mesh, live)
# ---------------------------------------------------------------------------

class TestReshard:
    def test_shrink_4_to_2_with_loss_parity(self):
        """Train annotated at p=4, quiesce, reshard to p=2 via the real
        reshard() entry point, and check the next-step loss matches the
        unchanged-mesh continuation (same global batch, same math)."""
        main, startup = fluid.Program(), fluid.Program()
        scope = Scope()
        with fluid.scope_guard(scope):
            loss, feeds = _transformer(main, startup)
            cm = spmd.CostModel()
            spmd.apply_placement(
                main, spmd.auto_shard(main, 4, cost_model=cm,
                                      batch_size=4))
            fluid.Executor(fluid.CPUPlace()).run(startup, scope=scope)
            pe = fluid.ParallelExecutor(
                use_tpu=False, loss_name=loss.name, main_program=main,
                scope=scope, num_devices=4)
            rng = np.random.RandomState(0)
            xs = rng.randint(0, 32, (4, 8)).astype(np.int64)
            ys = np.roll(xs, -1, 1)[:, :, None].astype(np.int64)
            feed = {feeds[0].name: xs, feeds[1].name: ys}
            for _ in range(2):
                pe.run(feed=feed, fetch_list=[loss])
            # quiesce + snapshot, reference continuation on p=4
            scope.flush_prepared()
            block = main.global_block()
            persist = [n for n, v in block.vars.items()
                       if v.persistable and scope.has_var(n)]
            snap = {n: np.array(np.asarray(scope.find_var(n)),
                                copy=True) for n in persist}
            ref, = pe.run(feed=feed, fetch_list=[loss])
            ref = float(np.asarray(ref).reshape(-1)[0])
            # restore + reshard to 2
            scope.flush_prepared()
            for n in persist:
                scope.set(n, snap[n])
            pe2, report = spmd.reshard(main, scope, 2, cost_model=cm,
                                       batch_size=4, verify=True)
            assert report["verify_errors"] == 0
            got, = pe2.run(feed=feed, fetch_list=[loss])
            got = float(np.asarray(got).reshape(-1)[0])
            assert abs(got - ref) <= 5e-3 * max(1.0, abs(ref)), \
                (got, ref, report)
