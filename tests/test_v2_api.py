"""v2 graph-building API (reference python/paddle/v2: layer.py,
trainer.py:137 SGD.train, parameters.py, inference.py,
tests/test_layer.py).  A reference v2 script runs with only the import
line changed: layers declared anywhere, parameters.create(cost),
trainer.SGD(...).train(reader, event_handler), paddle.infer(...)."""
import io

import numpy as np
import pytest

import paddle_tpu.v2 as paddle


def _digit_reader(rng, n_batches=20, batch_size=16, dim=64, classes=10):
    stride = dim // classes
    def reader():
        for _ in range(n_batches):
            batch = []
            for _ in range(batch_size):
                y = int(rng.randint(classes))
                x = np.zeros(dim, np.float32)
                x[y * stride] = 1.0
                batch.append((x, y))
            yield batch
    return reader


def _mlp(dim=64, classes=10, named=False):
    images = paddle.layer.data(name="pixel",
                               type=paddle.data_type.dense_vector(dim))
    label = paddle.layer.data(name="label",
                              type=paddle.data_type.integer_value(classes))
    # explicit names keep parameter names stable across re-declarations
    # (anonymous __fc_layer_N__ counters are process-global, as in the
    # reference's v1 config naming)
    h1 = paddle.layer.fc(input=images, size=32,
                         act=paddle.activation.Relu(),
                         name="h1" if named else None)
    predict = paddle.layer.fc(input=h1, size=classes,
                              act=paddle.activation.Softmax(),
                              name="pred" if named else None)
    cost = paddle.layer.classification_cost(input=predict, label=label)
    return predict, cost


def test_v2_mnist_style_mlp_trains_and_infers():
    """The reference MNIST v2 script shape: declare layers, create
    parameters, train with Momentum+L2, events fire with cost and the
    classification_error metric, then paddle.infer serves."""
    paddle.init(use_gpu=False, trainer_count=1)
    predict, cost = _mlp()
    parameters = paddle.parameters.create(cost)
    assert len(parameters.names()) == 4  # 2x fc (w + bias)
    optimizer = paddle.optimizer.Momentum(
        learning_rate=0.05, momentum=0.9,
        regularization=paddle.optimizer.L2Regularization(rate=1e-4))
    trainer = paddle.trainer.SGD(cost=cost, parameters=parameters,
                                 update_equation=optimizer)
    events = {"begin_pass": 0, "end_pass": 0, "iters": []}

    def handler(event):
        if isinstance(event, paddle.event.BeginPass):
            events["begin_pass"] += 1
        elif isinstance(event, paddle.event.EndPass):
            events["end_pass"] += 1
            assert "classification_error_evaluator" in event.metrics
        elif isinstance(event, paddle.event.EndIteration):
            events["iters"].append(
                (event.pass_id, event.batch_id, event.cost,
                 event.metrics["classification_error_evaluator"]))

    rng = np.random.RandomState(0)
    trainer.train(reader=_digit_reader(rng), num_passes=3,
                  event_handler=handler)
    assert events["begin_pass"] == 3 and events["end_pass"] == 3
    costs = [c for _, _, c, _ in events["iters"]]
    assert costs[-1] < costs[0] * 0.5
    # the separable toy task should be fully learned
    assert events["iters"][-1][3] < 0.1

    probs = paddle.infer(
        output_layer=predict, parameters=parameters,
        input=[(np.eye(64, dtype=np.float32)[y * 6],) for y in range(10)])
    assert list(np.argmax(np.asarray(probs), axis=1)) == list(range(10))

    result = trainer.test(reader=_digit_reader(np.random.RandomState(7)))
    assert result.cost < costs[0]
    assert result.metrics["classification_error_evaluator"] < 0.1


def test_v2_conv_network_via_networks():
    """simple_img_conv_pool on dense_vector image input (the v2 conv
    MNIST config): v1 infers the 2-D image shape from the flat size."""
    paddle.init(use_gpu=False, trainer_count=1)
    images = paddle.layer.data(
        name="cimg", type=paddle.data_type.dense_vector(144))
    label = paddle.layer.data(
        name="clabel", type=paddle.data_type.integer_value(4))
    conv = paddle.networks.simple_img_conv_pool(
        input=images, filter_size=3, num_filters=4, num_channel=1,
        pool_size=2, pool_stride=2, act=paddle.activation.Relu())
    predict = paddle.layer.fc(input=conv, size=4,
                              act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=predict, label=label)
    parameters = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=0.01))
    rng = np.random.RandomState(3)

    def reader():
        for _ in range(15):
            batch = []
            for _ in range(8):
                y = int(rng.randint(4))
                img = np.zeros((12, 12), np.float32)
                img[y * 3: y * 3 + 3, :] = 1.0
                batch.append((img.ravel(), y))
            yield batch

    costs = []
    trainer.train(reader=reader, num_passes=2, event_handler=lambda e:
                  costs.append(e.cost)
                  if isinstance(e, paddle.event.EndIteration) else None)
    assert costs[-1] < costs[0] * 0.6


def test_v2_sequence_embedding_pooling():
    """integer_value_sequence -> embedding -> seq pooling -> fc: the
    text-classification v2 config over the LoD bridge."""
    paddle.init(use_gpu=False, trainer_count=1)
    words = paddle.layer.data(
        name="words", type=paddle.data_type.integer_value_sequence(20))
    label = paddle.layer.data(
        name="slabel", type=paddle.data_type.integer_value(2))
    emb = paddle.layer.embedding(input=words, size=8)
    pooled = paddle.layer.pooling(input=emb,
                                  pooling_type=paddle.pooling.Avg())
    predict = paddle.layer.fc(input=pooled, size=2,
                              act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=predict, label=label)
    parameters = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=0.05))
    rng = np.random.RandomState(5)

    def reader():
        for _ in range(20):
            batch = []
            for _ in range(8):
                y = int(rng.randint(2))
                # class decides which half of the vocab words come from
                length = int(rng.randint(2, 6))
                seq = rng.randint(y * 10, y * 10 + 10,
                                  size=length).tolist()
                batch.append((seq, y))
            yield batch

    costs = []
    trainer.train(reader=reader, num_passes=3, event_handler=lambda e:
                  costs.append(e.cost)
                  if isinstance(e, paddle.event.EndIteration) else None)
    assert costs[-1] < costs[0] * 0.6


def test_v2_word2vec_shared_embedding():
    """The reference test_paramconf_order.py topology: N context words
    through table projections sharing one named parameter, concat, fc
    — shared param_attr names must alias ONE parameter."""
    paddle.init(use_gpu=False, trainer_count=1)
    shared = paddle.attr.Param(name="wordvecs")
    ws = [paddle.layer.data(
        name="w%d" % i, type=paddle.data_type.integer_value(30))
        for i in range(4)]
    nextw = paddle.layer.data(
        name="wnext", type=paddle.data_type.integer_value(30))
    embs = [paddle.layer.table_projection(input=w, size=6,
                                          param_attr=shared) for w in ws]
    ctx = paddle.layer.concat(input=embs)
    hidden = paddle.layer.fc(input=ctx, size=16,
                             act=paddle.activation.Sigmoid())
    predict = paddle.layer.fc(input=hidden, size=30,
                              act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=predict, label=nextw)
    parameters = paddle.parameters.create(cost)
    assert parameters.names().count("wordvecs") == 1
    assert parameters.get_shape("wordvecs") == (30, 6)


def test_v2_parameters_tar_roundtrip_and_warm_start():
    """to_tar/from_tar roundtrip; a NEW trainer warm-started from the
    tar continues from the saved weights (reference
    Parameters.from_tar + init_from_tar)."""
    paddle.init(use_gpu=False, trainer_count=1)
    predict, cost = _mlp(named=True)
    parameters = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=0.05))
    rng = np.random.RandomState(1)
    trainer.train(reader=_digit_reader(rng, n_batches=15), num_passes=2)
    buf = io.BytesIO()
    trainer.save_parameter_to_tar(buf)
    buf.seek(0)

    loaded = paddle.parameters.Parameters.from_tar(buf)
    assert sorted(loaded.names()) == sorted(parameters.names())
    np.testing.assert_allclose(loaded.get(parameters.names()[0]),
                               parameters.get(parameters.names()[0]))

    # fresh DAG + trainer warm-started from the tar: first-batch cost
    # must match the trained model's, not a random init's
    predict2, cost2 = _mlp(named=True)
    trainer2 = paddle.trainer.SGD(
        cost=cost2, parameters=loaded,
        update_equation=paddle.optimizer.Adam(learning_rate=0.05))
    first = []

    def grab_first(event):
        if isinstance(event, paddle.event.EndIteration) and not first:
            first.append(event.cost)

    trainer2.train(reader=_digit_reader(np.random.RandomState(2),
                                        n_batches=2),
                   num_passes=1, event_handler=grab_first)
    assert first[0] < 0.7  # random init would sit near ln(10) ~ 2.3


def test_v2_regression_cost_and_sgd():
    paddle.init(use_gpu=False, trainer_count=1)
    x = paddle.layer.data(name="rx",
                          type=paddle.data_type.dense_vector(13))
    y = paddle.layer.data(name="ry",
                          type=paddle.data_type.dense_vector(1))
    pred = paddle.layer.fc(input=x, size=1,
                           act=paddle.activation.Linear())
    cost = paddle.layer.square_error_cost(input=pred, label=y)
    parameters = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Momentum(learning_rate=0.01,
                                                  momentum=0.0))
    rng = np.random.RandomState(0)
    true_w = rng.randn(13, 1).astype(np.float32)

    def reader():
        for _ in range(40):
            xs = rng.randn(16, 13).astype(np.float32)
            ys = xs @ true_w
            yield [(xs[i], ys[i]) for i in range(16)]

    costs = []
    trainer.train(reader=reader, num_passes=2, event_handler=lambda e:
                  costs.append(e.cost)
                  if isinstance(e, paddle.event.EndIteration) else None)
    assert costs[-1] < costs[0] * 0.2


def test_v2_feeding_map_reorders_columns():
    """feeding={name: index} must pick reader columns by index, not
    declaration order (reference trainer.py feeding contract)."""
    paddle.init(use_gpu=False, trainer_count=1)
    predict, cost = _mlp(dim=16, classes=4)
    parameters = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=0.05))
    rng = np.random.RandomState(4)

    def reader():  # label FIRST, pixels second
        for _ in range(10):
            batch = []
            for _ in range(8):
                yv = int(rng.randint(4))
                x = np.zeros(16, np.float32)
                x[yv * 4] = 1.0
                batch.append((yv, x))
            yield batch

    costs = []
    trainer.train(reader=reader, num_passes=2,
                  feeding={"pixel": 1, "label": 0},
                  event_handler=lambda e: costs.append(e.cost)
                  if isinstance(e, paddle.event.EndIteration) else None)
    assert costs[-1] < costs[0] * 0.7


def test_v2_parse_network_and_data_utilities():
    paddle.init(trainer_count=1)
    r = paddle.batch(lambda: iter(range(10)), 4)
    assert list(r()) == [[0, 1, 2, 3], [4, 5, 6, 7]]
    assert paddle.dataset.mnist is not None
    assert paddle.reader.shuffle is not None
    with pytest.raises(ValueError):
        paddle.init(trainer_count=0)

    x = paddle.layer.data(name="pn_x",
                          type=paddle.data_type.dense_vector(8))
    h = paddle.layer.fc(input=x, size=4, act=paddle.activation.Tanh())
    desc = paddle.layer.parse_network(h)
    assert any(op.type == "mul" for op in desc.blocks[0].ops)


def test_v2_anonymous_param_attr_not_aliased():
    """One anonymous ParamAttr object reused across two layers must
    produce two distinct parameters, not silently share weights."""
    from paddle_tpu.fluid.param_attr import ParamAttr
    shared_anon = ParamAttr()
    x = paddle.layer.data(name="ap_x",
                          type=paddle.data_type.dense_vector(8))
    y = paddle.layer.data(name="ap_y",
                          type=paddle.data_type.integer_value(2))
    h = paddle.layer.fc(input=x, size=6, param_attr=shared_anon,
                        name="ap_h")
    p = paddle.layer.fc(input=h, size=2, param_attr=shared_anon,
                        act=paddle.activation.Softmax(), name="ap_p")
    cost = paddle.layer.classification_cost(input=p, label=y)
    params = paddle.parameters.create(cost)
    assert params.get_shape("_ap_h.w0") == (8, 6)
    assert params.get_shape("_ap_p.w0") == (6, 2)
    assert shared_anon.name is None  # user's object untouched


def test_v2_init_from_tar_skips_unknown_names():
    paddle.init(trainer_count=1)
    predict, cost = _mlp(dim=16, classes=4, named=True)
    params = paddle.parameters.create(cost)
    extra = paddle.parameters.Parameters()
    extra.set("_h1.w0", params.get("_h1.w0") * 0 + 1.0)
    extra.set("not_in_topology", np.zeros(3, np.float32))
    buf = io.BytesIO()
    extra.to_tar(buf)
    buf.seek(0)
    params.init_from_tar(buf)  # must not raise on the unknown name
    assert float(params.get("_h1.w0").ravel()[0]) == 1.0
    assert not params.has_key("not_in_topology")


def test_v2_sequence_conv_pool_has_context_window():
    """sequence_conv_pool must apply a real context_len window (a
    sequence_conv op), not a per-timestep fc."""
    words = paddle.layer.data(
        name="scp_w", type=paddle.data_type.dense_vector_sequence(5))
    out = paddle.networks.sequence_conv_pool(
        input=words, context_len=3, hidden_size=7)
    desc = paddle.layer.parse_network(out)
    assert any(op.type == "sequence_conv"
               for op in desc.blocks[0].ops)


def test_v2_img_conv_trans_builds_transpose():
    img = paddle.layer.data(
        name="tc_img", type=paddle.data_type.dense_vector(64))
    up = paddle.layer.img_conv(input=img, filter_size=3, num_filters=2,
                               num_channels=1, stride=2, trans=True)
    desc = paddle.layer.parse_network(up)
    assert any("transpose" in op.type for op in desc.blocks[0].ops)


def test_v2_second_trainer_on_same_parameters():
    """A second SGD over the same cost/parameters (re-train with a
    different optimizer) must work and continue from the current
    weights, not crash on a second backward pass."""
    paddle.init(trainer_count=1)
    predict, cost = _mlp(dim=16, classes=4, named=True)
    params = paddle.parameters.create(cost)
    t1 = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=0.05))
    rng = np.random.RandomState(9)
    t1.train(reader=_digit_reader(rng, n_batches=10, dim=16, classes=4),
             num_passes=2)
    w_after_t1 = params.get("_h1.w0").copy()
    t2 = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Momentum(learning_rate=0.01,
                                                  momentum=0.9))
    # t2 starts from t1's weights
    np.testing.assert_allclose(params.get("_h1.w0"), w_after_t1)
    costs = []
    t2.train(reader=_digit_reader(rng, n_batches=5, dim=16, classes=4),
             num_passes=1, event_handler=lambda e:
             costs.append(e.cost)
             if isinstance(e, paddle.event.EndIteration) else None)
    assert costs[0] < 1.0  # warm start, not a random re-init (~ln 4)


def test_v2_explicit_linear_activation_preserved():
    """activation.Linear() passed explicitly must not be coerced to the
    tanh/sigmoid defaults (lstm gates, sequence_conv_pool)."""
    words = paddle.layer.data(
        name="lin_w", type=paddle.data_type.dense_vector_sequence(4))
    out = paddle.networks.sequence_conv_pool(
        input=words, context_len=2, hidden_size=3,
        fc_act=paddle.activation.Linear())
    desc = paddle.layer.parse_network(out)
    conv_ops = [op for op in desc.blocks[0].ops
                if op.type == "sequence_conv"]
    assert conv_ops and not any(op.type == "tanh"
                                for op in desc.blocks[0].ops)


def test_v2_img_conv_default_padding_is_zero():
    """Reference img_conv_layer pads 0 by default: a 12x12 input with
    filter 3 must give 10x10 maps, keeping migrated shapes identical."""
    img = paddle.layer.data(
        name="pz_img", type=paddle.data_type.dense_vector(144))
    conv = paddle.layer.img_conv(input=img, filter_size=3,
                                 num_filters=2, num_channels=1,
                                 name="pz_conv")
    pool = paddle.layer.img_pool(input=conv, pool_size=2, stride=2,
                                 num_channels=2)
    fc = paddle.layer.fc(input=pool, size=3, name="pz_fc")
    from paddle_tpu.v2.topology import Topology
    topo = Topology(fc)
    # conv2d(12,k3,p0)->10; pool(2,s2,ceil)->5; fc in = 2*5*5 = 50
    assert topo.var_of(fc).shape[-1] == 3
    w = topo.main_program.global_block().var("_pz_fc.w0")
    assert w.shape[0] == 2 * 5 * 5


def test_v2_optimizer_strictness_and_clip():
    with pytest.raises(NotImplementedError, match="learning_rate_sch"):
        paddle.optimizer.Adam(learning_rate=0.01,
                              learning_rate_schedule="poly")
    with pytest.raises(NotImplementedError, match="momentum"):
        paddle.attr.Param(momentum=0.9)
    # gradient_clipping_threshold reaches the fluid clip attr
    a = paddle.attr.Param(name="clip_p", gradient_clipping_threshold=5.0)
    fa = a.to_fluid()
    assert fa.gradient_clip is not None


def test_v2_unported_layer_names_fail_loudly():
    # conv_projection is ported as of round 5 — unknown names still
    # fail loudly with the fluid hint
    assert callable(paddle.layer.conv_projection)
    with pytest.raises(AttributeError, match="ported v2 subset"):
        paddle.layer.definitely_not_a_layer  # noqa: B018
    # a name with no curated pointer gets the generic fluid hint
    with pytest.raises(AttributeError, match="fluid.layers equivalent"):
        paddle.layer.hsigmoid_layer_from_v1


def test_v2_sentiment_lstm_via_networks():
    """The v2 sentiment config shape: integer_value_sequence ->
    embedding -> networks.simple_lstm -> last_seq -> softmax fc; must
    train on a separable toy task (exercises the lstmemory builder
    over the LoD bridge)."""
    paddle.init(trainer_count=1)
    words = paddle.layer.data(
        name="sl_w", type=paddle.data_type.integer_value_sequence(20))
    label = paddle.layer.data(
        name="sl_y", type=paddle.data_type.integer_value(2))
    emb = paddle.layer.embedding(input=words, size=8)
    lstm = paddle.networks.simple_lstm(input=emb, size=8)
    last = paddle.layer.last_seq(input=lstm)
    predict = paddle.layer.fc(input=last, size=2,
                              act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=predict, label=label)
    parameters = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=0.05))
    rng = np.random.RandomState(11)

    def reader():
        for _ in range(15):
            batch = []
            for _ in range(8):
                y = int(rng.randint(2))
                length = int(rng.randint(3, 7))
                seq = rng.randint(y * 10, y * 10 + 10,
                                  size=length).tolist()
                batch.append((seq, y))
            yield batch

    costs = []
    trainer.train(reader=reader, num_passes=4, event_handler=lambda e:
                  costs.append(e.cost)
                  if isinstance(e, paddle.event.EndIteration) else None)
    assert costs[-1] < costs[0] * 0.6, (costs[0], costs[-1])


def test_v2_evaluator_attaches_metric():
    """paddle.v2.evaluator.* layers attach named metrics that surface
    in events and test() results via extra_layers."""
    paddle.init(trainer_count=1)
    predict, cost = _mlp(dim=16, classes=4)
    ev = paddle.evaluator.classification_error(
        input=predict,
        label=cost.inputs[1],  # the label data layer of the cost
        name="my_err")
    parameters = paddle.parameters.create(cost, extra_layers=[ev])
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=0.05))
    seen = []
    trainer.train(
        reader=_digit_reader(np.random.RandomState(6), n_batches=4,
                             dim=16, classes=4),
        num_passes=1,
        event_handler=lambda e: seen.append(e.metrics)
        if isinstance(e, paddle.event.EndIteration) else None)
    assert seen and all("my_err" in m for m in seen)
    res = trainer.test(reader=_digit_reader(np.random.RandomState(8),
                                            n_batches=2, dim=16,
                                            classes=4))
    assert "my_err" in res.metrics


def test_v2_auc_evaluator_state_resets():
    """Streaming auc accumulators reset at each pass / test() start:
    two identical test() calls must return the SAME auc, and train
    statistics must not leak into test results."""
    paddle.init(trainer_count=1)
    x = paddle.layer.data(name="auc_x",
                          type=paddle.data_type.dense_vector(8))
    y = paddle.layer.data(name="auc_y",
                          type=paddle.data_type.integer_value(2))
    predict = paddle.layer.fc(input=x, size=2,
                              act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=predict, label=y)
    ev = paddle.evaluator.auc(input=predict, label=y, name="the_auc")
    parameters = paddle.parameters.create(cost, extra_layers=[ev])
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=0.05))
    rng = np.random.RandomState(12)

    def reader():
        for _ in range(6):
            batch = []
            for _ in range(16):
                yv = int(rng.randint(2))
                xv = rng.randn(8).astype(np.float32)
                xv[0] += 2.0 * yv
                batch.append((xv, yv))
            yield batch

    trainer.train(reader=reader, num_passes=2)
    fixed = np.random.RandomState(13)

    def fixed_reader():
        for _ in range(4):
            batch = []
            for _ in range(16):
                yv = int(fixed.randint(2))
                xv = fixed.randn(8).astype(np.float32)
                xv[0] += 2.0 * yv
                batch.append((xv, yv))
            yield batch

    rows = list(fixed_reader())
    r1 = trainer.test(reader=lambda: iter(rows))
    r2 = trainer.test(reader=lambda: iter(rows))
    assert abs(r1.metrics["the_auc"] - r2.metrics["the_auc"]) < 1e-6
    assert r1.metrics["the_auc"] > 0.5  # learned the separable signal


def test_v2_evaluator_rejects_unknown_kwargs():
    x = paddle.layer.data(name="ek_x",
                          type=paddle.data_type.dense_vector(4))
    y = paddle.layer.data(name="ek_y",
                          type=paddle.data_type.integer_value(2))
    p = paddle.layer.fc(input=x, size=2,
                        act=paddle.activation.Softmax())
    with pytest.raises(NotImplementedError, match="chunk_scheme"):
        paddle.evaluator.auc(input=p, label=y, chunk_scheme="plain")
    with pytest.raises(NotImplementedError, match="binary"):
        p4 = paddle.layer.fc(input=x, size=4,
                             act=paddle.activation.Softmax())
        paddle.evaluator.precision_recall(input=p4, label=y)


def test_v2_recurrent_group_trains():
    """recurrent_group + layer.memory: a hand-written simple RNN
    (h_t = tanh(W[x_t, h_{t-1}])) lowered to ONE DynamicRNN/lax.scan —
    the reference's most-used v2 recurrence primitive
    (trainer_config_helpers recurrent_group)."""
    paddle.init(trainer_count=1)
    words = paddle.layer.data(
        name="rg_w", type=paddle.data_type.integer_value_sequence(20))
    label = paddle.layer.data(
        name="rg_y", type=paddle.data_type.integer_value(2))
    emb = paddle.layer.embedding(input=words, size=8)

    def step(x):
        h_prev = paddle.layer.memory(name="rg_h", size=8)
        return paddle.layer.fc(input=[x, h_prev], size=8,
                               act=paddle.activation.Tanh(),
                               name="rg_h")

    rnn = paddle.layer.recurrent_group(step=step, input=emb)
    last = paddle.layer.last_seq(input=rnn)
    pred = paddle.layer.fc(input=last, size=2,
                           act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=pred, label=label)
    params = paddle.parameters.create(cost)
    tr = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=0.05))
    rng = np.random.RandomState(0)

    def reader():
        for _ in range(15):
            b = []
            for _ in range(8):
                y = int(rng.randint(2))
                length = int(rng.randint(3, 7))
                b.append((rng.randint(y * 10, y * 10 + 10,
                                      size=length).tolist(), y))
            yield b

    costs = []
    tr.train(reader=reader, num_passes=4, event_handler=lambda e:
             costs.append(e.cost)
             if isinstance(e, paddle.event.EndIteration) else None)
    assert costs[-1] < costs[0] * 0.6, (costs[0], costs[-1])


def test_v2_recurrent_group_static_input():
    """StaticInput arrives whole every step (not time-sliced): the
    step can condition on a per-example context vector."""
    paddle.init(trainer_count=1)
    seqs = paddle.layer.data(
        name="si_x", type=paddle.data_type.dense_vector_sequence(4))
    ctx_v = paddle.layer.data(
        name="si_c", type=paddle.data_type.dense_vector(4))
    y = paddle.layer.data(name="si_y",
                          type=paddle.data_type.dense_vector(1))

    def step(x, c):
        h_prev = paddle.layer.memory(name="si_h", size=4)
        return paddle.layer.fc(input=[x, c, h_prev], size=4,
                               act=paddle.activation.Tanh(),
                               name="si_h")

    rnn = paddle.layer.recurrent_group(
        step=step, input=[seqs, paddle.layer.StaticInput(ctx_v)])
    last = paddle.layer.last_seq(input=rnn)
    pred = paddle.layer.fc(input=last, size=1)
    cost = paddle.layer.mse_cost(input=pred, label=y)
    params = paddle.parameters.create(cost)
    tr = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=0.02))
    rng = np.random.RandomState(2)

    def reader():
        for _ in range(10):
            b = []
            for _ in range(8):
                length = int(rng.randint(2, 5))
                xs = rng.randn(length, 4).astype(np.float32)
                c = rng.randn(4).astype(np.float32)
                b.append(([r for r in xs], c,
                          np.asarray([c.sum()], np.float32)))
            yield b

    costs = []
    tr.train(reader=reader, num_passes=3, event_handler=lambda e:
             costs.append(e.cost)
             if isinstance(e, paddle.event.EndIteration) else None)
    assert costs[-1] < costs[0] * 0.7, (costs[0], costs[-1])


def test_v2_memory_errors():
    with pytest.raises(ValueError, match="name"):
        paddle.layer.memory(size=4)
    with pytest.raises(NotImplementedError, match="is_seq"):
        paddle.layer.memory(name="m", size=4, is_seq=True)
    with pytest.raises(NotImplementedError, match="is_seq"):
        x0 = paddle.layer.data(name="me_s",
                               type=paddle.data_type.dense_vector(4))
        paddle.layer.StaticInput(x0, is_seq=True)
    with pytest.raises(NotImplementedError, match="unsupported"):
        paddle.layer.recurrent_group(step=lambda x: x, input=[],
                                     targetInlink=None)
    # memory outside a recurrent_group step fails at build time
    x = paddle.layer.data(name="me_x",
                          type=paddle.data_type.dense_vector(4))
    m = paddle.layer.memory(name="nope", size=4)
    out = paddle.layer.fc(input=[x, m], size=1)
    from paddle_tpu.v2.topology import Topology
    with pytest.raises(RuntimeError, match="recurrent_group"):
        Topology(out)


def test_v2_recurrent_group_boot_layer():
    """memory(boot_layer=...) seeds step 0 from a layer built OUTSIDE
    the scan; its data layer must join the feeding order."""
    paddle.init(trainer_count=1)
    seqs = paddle.layer.data(
        name="bl_x", type=paddle.data_type.dense_vector_sequence(4))
    boot_src = paddle.layer.data(
        name="bl_b", type=paddle.data_type.dense_vector(3))
    y = paddle.layer.data(name="bl_y",
                          type=paddle.data_type.dense_vector(1))
    boot = paddle.layer.fc(input=boot_src, size=4,
                           act=paddle.activation.Tanh(), name="bl_boot")

    def step(x):
        h_prev = paddle.layer.memory(name="bl_h", size=4,
                                     boot_layer=boot)
        return paddle.layer.fc(input=[x, h_prev], size=4,
                               act=paddle.activation.Tanh(),
                               name="bl_h")

    rnn = paddle.layer.recurrent_group(step=step, input=seqs)
    last = paddle.layer.last_seq(input=rnn)
    pred = paddle.layer.fc(input=last, size=1)
    cost = paddle.layer.mse_cost(input=pred, label=y)
    from paddle_tpu.v2.topology import Topology
    topo = Topology(cost)
    feed_names = [n for n, _ in topo.data_type()]
    assert "bl_b" in feed_names, feed_names

    params = paddle.parameters.create(cost)
    tr = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=0.02))
    rng = np.random.RandomState(4)

    def reader():
        for _ in range(8):
            b = []
            for _ in range(8):
                length = int(rng.randint(2, 5))
                xs = [r for r in
                      rng.randn(length, 4).astype(np.float32)]
                bv = rng.randn(3).astype(np.float32)
                b.append((xs, bv,
                          np.asarray([bv.sum()], np.float32)))
            yield b

    costs = []
    tr.train(reader=reader, num_passes=3, event_handler=lambda e:
             costs.append(e.cost)
             if isinstance(e, paddle.event.EndIteration) else None)
    assert costs[-1] < costs[0], (costs[0], costs[-1])


def test_v2_seq_concat_and_expand_build():
    """seq_concat / expand materialize to the fluid sequence ops."""
    a = paddle.layer.data(
        name="sc_a", type=paddle.data_type.dense_vector_sequence(3))
    b = paddle.layer.data(
        name="sc_b", type=paddle.data_type.dense_vector_sequence(3))
    cat = paddle.layer.seq_concat(a=a, b=b)
    per_seq = paddle.layer.pooling(input=cat,
                                   pooling_type=paddle.pooling.Avg())
    ex = paddle.layer.expand(input=per_seq, expand_as=cat)
    desc = paddle.layer.parse_network(ex)
    types = [op.type for op in desc.blocks[0].ops]
    assert "sequence_concat" in types and "sequence_expand" in types
    # guarded surface: width mismatch and nested expand fail loudly
    w5 = paddle.layer.data(
        name="sc_w5", type=paddle.data_type.dense_vector_sequence(5))
    with pytest.raises(ValueError, match="feature width"):
        paddle.layer.seq_concat(a=a, b=w5)
    with pytest.raises(NotImplementedError, match="FROM_NO_SEQUENCE"):
        paddle.layer.expand(
            input=per_seq, expand_as=cat,
            expand_level=paddle.layer.ExpandLevel.FROM_SEQUENCE)


def test_v2_mixed_projections_train():
    """mixed + full_matrix/identity projections (the v1 projection-sum
    container): contributions add into [N, size], bias + act apply, and
    the whole thing trains."""
    paddle.init(trainer_count=1)
    x = paddle.layer.data(name="mx",
                          type=paddle.data_type.dense_vector(6))
    z = paddle.layer.data(name="mz",
                          type=paddle.data_type.dense_vector(8))
    y = paddle.layer.data(name="my",
                          type=paddle.data_type.dense_vector(1))
    h = paddle.layer.mixed(
        size=8,
        input=[paddle.layer.full_matrix_projection(input=x),
               paddle.layer.identity_projection(input=z)],
        act=paddle.activation.Tanh(), bias_attr=True, name="mh")
    pred = paddle.layer.fc(input=h, size=1)
    cost = paddle.layer.mse_cost(input=pred, label=y)
    params = paddle.parameters.create(cost)
    tr = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=0.05))
    rng = np.random.RandomState(0)

    def reader():
        for _ in range(20):
            b = []
            for _ in range(16):
                xv = rng.rand(6).astype(np.float32)
                zv = rng.rand(8).astype(np.float32)
                b.append((xv, zv,
                          np.asarray([xv.sum() - zv.sum()],
                                     np.float32)))
            yield b

    costs = []
    tr.train(reader=reader, num_passes=5, event_handler=lambda e:
             costs.append(e.cost)
             if isinstance(e, paddle.event.EndIteration) else None)
    assert costs[-1] < costs[0] * 0.2, (costs[0], costs[-1])
    # declaration-time guards
    with pytest.raises(ValueError, match="size"):
        paddle.layer.mixed(input=[
            paddle.layer.full_matrix_projection(input=x)])
    with pytest.raises(ValueError, match="width"):
        paddle.layer.mixed(size=5, input=[
            paddle.layer.identity_projection(input=z)])
    with pytest.raises(ValueError, match="width"):
        paddle.layer.mixed(size=8, input=[
            paddle.layer.full_matrix_projection(input=x, size=4)])
    # identity_projection(offset=...) is now a real feature-window
    # slice (round-5); pin the sliced width instead of the old refusal
    off = paddle.layer.mixed(size=3, input=[
        paddle.layer.identity_projection(input=x, offset=1, size=3)])
    assert off.size == 3


def test_v2_beam_search_beats_greedy():
    """v2 beam_search (reference trainer_config_helpers beam_search):
    generation over a garden-path transition table — greedy takes the
    trap, beam 2 recovers the delayed-reward path."""
    END, BOS, V = 0, 1, 5
    gen = paddle.layer.GeneratedInput(size=V, embedding_name="gp_T",
                                      embedding_size=V)

    def step(prev):
        return paddle.layer.mixed(
            size=V,
            input=[paddle.layer.identity_projection(input=prev)],
            act=paddle.activation.Softmax())

    def run(beam):
        out = paddle.layer.beam_search(
            step=step, input=[gen], bos_id=BOS, eos_id=END,
            beam_size=beam, max_length=4)
        params = paddle.parameters.create(out)
        t = np.full((V, V), -1e9, np.float32)
        t[1, 2] = np.log(.6)
        t[1, 3] = np.log(.4)
        t[2, 4] = np.log(.55)
        t[2, END] = np.log(.45)
        t[4, END] = t[3, END] = t[END, END] = 0.0
        params.set("gp_T", t)
        return np.asarray(paddle.infer(output_layer=out,
                                       parameters=params, input=[()]))

    g = run(1)
    assert g[0, 0].tolist()[:4] == [1, 2, 4, END]  # greedy trap
    b = run(2)
    assert b[0, 0].tolist()[:3] == [1, 3, END]     # beam recovers
    assert b[0, 1].tolist()[:4] == [1, 2, 4, END]  # runner-up = greedy


def test_v2_beam_search_with_decoder_state():
    """beam_search + layer.memory: decoder state accumulates embedded
    tokens and is parent-gathered between steps; weights are designed
    so the forced sequence depends on the WHOLE history (wrong state
    carrying would derail it)."""
    END, BOS, V = 0, 1, 4
    gen = paddle.layer.GeneratedInput(size=V, embedding_name="bs_E",
                                      embedding_size=V)

    def step(prev):
        h_prev = paddle.layer.memory(name="bs_h", size=V)
        h = paddle.layer.fc(input=[prev, h_prev], size=V,
                            act=paddle.activation.Linear(),
                            name="bs_h", bias_attr=False)
        return paddle.layer.mixed(
            size=V,
            input=[paddle.layer.full_matrix_projection(input=h)],
            act=paddle.activation.Softmax(), name="bs_p")

    def run(beam):
        out = paddle.layer.beam_search(
            step=step, input=[gen], bos_id=BOS, eos_id=END,
            beam_size=beam, max_length=5)
        params = paddle.parameters.create(out)
        eye = np.eye(V, dtype=np.float32)
        params.set("bs_E", eye)
        params.set("_bs_h.w0", eye)
        params.set("_bs_h.w1", eye)
        # h = sum of one-hots seen; rows pick: {1}->2, {1,2}->3,
        # {1,2,3}->END
        M = np.array([[0, -99, 0, 0], [1, -99, 5, 3],
                      [1, -99, -9, 2], [1, -99, 0, -9]],
                     np.float32) * 4.0
        params.set("_bs_p.w0", M)
        return np.asarray(paddle.infer(output_layer=out,
                                       parameters=params, input=[()]))

    assert run(1)[0, 0].tolist()[:4] == [1, 2, 3, END]
    assert run(2)[0, 0].tolist()[:4] == [1, 2, 3, END]


def test_v2_beam_search_two_memories_not_crossed():
    """Two sibling memories (h accumulates token one-hots, c counts
    steps) must each carry THEIR OWN state — cross-wiring them swaps
    the roles and derails the forced sequence [1, 2, 2, END]."""
    END, BOS, V = 0, 1, 4
    gen = paddle.layer.GeneratedInput(size=V, embedding_name="tm_E",
                                      embedding_size=V)

    def step(prev):
        c_prev = paddle.layer.memory(name="tm_c", size=V)
        h_prev = paddle.layer.memory(name="tm_h", size=V)
        h = paddle.layer.fc(input=[prev, h_prev], size=V,
                            act=paddle.activation.Linear(),
                            name="tm_h", bias_attr=False)
        c = paddle.layer.fc(input=[prev, c_prev], size=V,
                            act=paddle.activation.Linear(),
                            name="tm_c")
        return paddle.layer.mixed(
            size=V,
            input=[paddle.layer.full_matrix_projection(input=h),
                   paddle.layer.full_matrix_projection(input=c)],
            act=paddle.activation.Softmax(), bias_attr=True,
            name="tm_p")

    out = paddle.layer.beam_search(step=step, input=[gen], bos_id=BOS,
                                   eos_id=END, beam_size=1,
                                   max_length=6)
    params = paddle.parameters.create(out)
    eye = np.eye(V, dtype=np.float32)
    zero = np.zeros((V, V), np.float32)
    params.set("tm_E", eye)
    params.set("_tm_h.w0", eye)      # h += one-hot(prev)
    params.set("_tm_h.w1", eye)
    params.set("_tm_c.w0", zero)     # c += 1 (bias), prev ignored
    params.set("_tm_c.w1", eye)
    params.set("_tm_c.wbias", np.ones(V, np.float32))
    Mh = np.zeros((V, V), np.float32)
    Mh[:, 1] = -99.0
    Mh[1, 2] = 3.0                   # h[1] (BOS seen) favors token 2
    Mc = np.zeros((V, V), np.float32)
    Mc[:, 1] = -99.0
    Mc[0, 0] = 10.0                  # s_END = 10 * step_count - 25
    params.set("_tm_p.w0", Mh)
    params.set("_tm_p.w1", Mc)
    params.set("_tm_p.wbias",
               np.asarray([-25, 0, 0, 0], np.float32))
    ids = np.asarray(paddle.infer(output_layer=out, parameters=params,
                                  input=[()]))
    # t=1,2: s_END = -15,-5 < s_2 = 3; t=3: s_END = +5 -> END.
    # crossed memories would make s_2 grow with t and s_END stay
    # negative: the sequence would never terminate at step 3
    assert ids[0, 0].tolist()[:4] == [1, 2, 2, END], ids[0, 0]


def test_v2_train_then_generate_shared_parameters():
    """The canonical v2 generation workflow: TRAIN a next-token RNN LM
    with recurrent_group, then build a separate GENERATION topology
    (beam_search) over the same layer/param names and decode with the
    TRAINED Parameters — weights transfer by name through infer()."""
    paddle.init(trainer_count=1)
    V, BOS, END = 6, 1, 0
    EMB, H = 8, 8

    def rnn_cell(x):
        h_prev = paddle.layer.memory(name="g_h", size=H)
        h = paddle.layer.fc(input=[x, h_prev], size=H,
                            act=paddle.activation.Tanh(), name="g_h")
        return paddle.layer.fc(input=h, size=V,
                               act=paddle.activation.Softmax(),
                               name="g_p")

    # ---- training topology: teacher-forced next-token prediction
    words = paddle.layer.data(
        name="g_w", type=paddle.data_type.integer_value_sequence(V))
    nxt = paddle.layer.data(
        name="g_n", type=paddle.data_type.integer_value_sequence(V))
    emb = paddle.layer.embedding(input=words, size=EMB,
                                 param_attr=paddle.attr.Param(
                                     name="g_emb"))
    probs = paddle.layer.recurrent_group(step=rnn_cell, input=emb)
    cost = paddle.layer.classification_cost(input=probs, label=nxt)
    params = paddle.parameters.create(cost)
    tr = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=0.05))

    seq = [BOS, 2, 3, 4]
    labels = [2, 3, 4, END]

    def reader():
        for _ in range(20):
            yield [(seq, labels)] * 8

    costs = []
    tr.train(reader=reader, num_passes=4, event_handler=lambda e:
             costs.append(e.cost)
             if isinstance(e, paddle.event.EndIteration) else None)
    assert costs[-1] < 0.2, (costs[0], costs[-1])

    # ---- generation topology: SAME layer names, trained weights flow
    # in by name via paddle.infer(parameters=params)
    gen_in = paddle.layer.GeneratedInput(size=V, embedding_name="g_emb",
                                         embedding_size=EMB)
    gen = paddle.layer.beam_search(step=rnn_cell, input=[gen_in],
                                   bos_id=BOS, eos_id=END, beam_size=2,
                                   max_length=6)
    ids = np.asarray(paddle.infer(output_layer=gen, parameters=params,
                                  input=[()]))
    assert ids[0, 0].tolist()[:5] == [BOS, 2, 3, 4, END], ids[0, 0]


def test_v2_beam_search_multi_sample_static_input():
    """N=2 samples decode in ONE beam_search program: each sample's
    StaticInput steers ITS OWN beams (flat [N*B] layout, per-sample
    gather) — sample 0 suppresses token 3 and must take the garden
    path, sample 1 boosts it and must finish [1, 3, END]."""
    END, BOS, V = 0, 1, 5
    gen = paddle.layer.GeneratedInput(size=V, embedding_name="ms_T",
                                      embedding_size=V)
    bias = paddle.layer.data(name="ms_bias",
                             type=paddle.data_type.dense_vector(V))

    def step(prev, b):
        return paddle.layer.mixed(
            size=V,
            input=[paddle.layer.identity_projection(input=prev),
                   paddle.layer.identity_projection(input=b)],
            act=paddle.activation.Softmax())

    out = paddle.layer.beam_search(
        step=step, input=[gen, paddle.layer.StaticInput(bias)],
        bos_id=BOS, eos_id=END, beam_size=2, max_length=4)
    params = paddle.parameters.create(out)
    t = np.full((V, V), -30.0, np.float32)
    t[1, 2] = np.log(.6)
    t[1, 3] = np.log(.4)
    t[2, 4] = np.log(.55)
    t[2, END] = np.log(.45)
    t[4, END] = t[3, END] = t[END, END] = 0.0
    params.set("ms_T", t)
    b0 = np.zeros(V, np.float32)
    b0[3] = -5.0                     # sample 0: token 3 suppressed
    b1 = np.zeros(V, np.float32)
    b1[3] = +5.0                     # sample 1: token 3 boosted
    ids = np.asarray(paddle.infer(output_layer=out, parameters=params,
                                  input=[(b0,), (b1,)]))
    assert ids.shape[0] == 2
    assert ids[0, 0].tolist()[:4] == [1, 2, 4, END], ids[0, 0]
    assert ids[1, 0].tolist()[:3] == [1, 3, END], ids[1, 0]


def test_v2_sparse_inputs_stay_sparse():
    """Round 5: sparse columns feed as ragged index lists (the dense
    [dim] vector never materializes — tests/test_v2_sparse_input.py
    trains a 1M-dim input through the lookup path)."""
    paddle.init(trainer_count=1)
    t = paddle.data_type.sparse_binary_vector(10)
    assert t.convert_column([1, 4, 7]) == [[1], [4], [7]]
    assert t.lod_level == 1 and t.dtype == "int64"
    tv = paddle.data_type.sparse_float_vector(6)
    assert tv.convert_column([(0, 0.5), (5, 2.0)]) == \
        [[0.0, 0.5], [5.0, 2.0]]
    assert tv.shape == [2]
