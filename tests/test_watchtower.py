"""Watchtower (ISSUE 13): tsdb store semantics, the registry sampler,
the perf-regression sentinel's tier-1 quick modes (synthetic planted
regression -> rc 3, clean -> rc 0), the watchtower report, and the
trace_report --all registry dispatch."""
import json
import os
import sys
import time

import numpy as np
import pytest

from paddle_tpu.core.flags import FLAGS
from paddle_tpu.observability import flight
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.observability import slo, tsdb

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")


def _tool(name):
    sys.path.insert(0, TOOLS)
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


@pytest.fixture(autouse=True)
def _clean_slo():
    slo.reset()
    yield
    slo.reset()
    tsdb.stop_sampler()


# ----------------------------------------------------------- tsdb store

def test_tsdb_append_scan_roundtrip(tmp_path):
    s = tsdb.TSDB(str(tmp_path / "ts"))
    t0 = time.time()
    for i in range(20):
        s.append_row({"g": float(i), "c_total": 2 * i}, t=t0 + i)
    t, v = s.scan("g")
    assert len(t) == 20 and v[0] == 0.0 and v[-1] == 19.0
    # range scan
    t, v = s.scan("g", t0 + 5, t0 + 9)
    assert list(v) == [5.0, 6.0, 7.0, 8.0, 9.0]
    # unknown series -> empty, not an error
    t, v = s.scan("nope")
    assert len(t) == 0
    assert s.latest("g") == (pytest.approx(t0 + 19), 19.0)
    assert s.rate("c_total") == pytest.approx(2.0)
    s.close()


def test_tsdb_rotation_retention_and_reopen(tmp_path):
    # 2 records/row * 20 bytes: a 200-byte segment seals every 5 rows
    s = tsdb.TSDB(str(tmp_path / "ts"), segment_bytes=200,
                  retention_bytes=1000)
    t0 = time.time()
    for i in range(100):
        s.append_row({"a": i, "b": -i}, t=t0 + i)
    segs = [f for f in os.listdir(str(tmp_path / "ts"))
            if f.startswith("seg_")]
    assert len(segs) > 1, "no rotation happened"
    assert s.total_bytes() <= 1000 + 200   # retention (+active slack)
    # oldest samples dropped, newest survive
    t, v = s.scan("a")
    assert v[-1] == 99.0 and v[0] > 0.0
    s.close()
    # a fresh read-only open (another process's view) sees the same
    r = tsdb.TSDB(str(tmp_path / "ts"), create=False)
    t2, v2 = r.scan("a")
    assert list(v2) == list(v)
    assert r.names() == ["a", "b"]
    # read-only stores refuse writes
    with pytest.raises(IOError):
        r.append("a", 1.0)


def test_tsdb_sealed_segment_cache(tmp_path):
    """Sealed segments parse once and serve repeated window queries
    from the cache (the SLO evaluator re-scans every tick); retention
    eviction drops the cached array with the file."""
    # 5 sealed segments — under the cache bound (queries that span
    # more sealed segments than the cache re-parse the overflow)
    s = tsdb.TSDB(str(tmp_path / "ts"), segment_bytes=400,
                  retention_bytes=100000)
    t0 = time.time()
    for i in range(55):
        s.append_row({"a": i, "b": -i}, t=t0 + i)
    assert not s._seg_cache            # nothing read yet
    t1_, v1 = s.scan("a")
    assert s._seg_cache                # sealed segments now cached
    cached = {f: id(arr) for f, (_sz, arr) in s._seg_cache.items()}
    t2_, v2 = s.scan("a")
    assert list(v2) == list(v1)
    for f, (_sz, arr) in s._seg_cache.items():
        assert id(arr) == cached[f], "sealed segment re-parsed"
    # retention keeps the cache in step with the files on disk
    s.retention_bytes = 2000
    for i in range(200):
        s.append_row({"a": 55 + i, "b": 0}, t=t0 + 55 + i)
    assert all(os.path.exists(os.path.join(s.dir, f))
               for f in s._seg_cache)
    s.close()


def test_sentinel_skips_non_numeric_bench_lines():
    """A malformed tail line ({'value': 'n/a'}) is dropped, not
    propagated as an empty metric that crashes the trajectory."""
    ps = _tool("perf_sentinel")
    found = ps._extract_bench_lines(
        '{"metric": "good", "value": 5.0, "unit": "images/sec"}\n'
        '{"metric": "bad", "value": "n/a"}\n'
        '{"metric": "worse", "value": [1, 2]}\n')
    assert set(found) == {"good"}
    traj = ps.build_trajectory(runs=[("x.json", found, False)])
    assert traj["metrics"]["good"]["floor"] == 5.0


def test_tsdb_torn_tail_truncates(tmp_path):
    """A crash mid-frame loses ONE sample, never a parse."""
    s = tsdb.TSDB(str(tmp_path / "ts"))
    t0 = time.time()
    for i in range(5):
        s.append("a", float(i), t=t0 + i)
    s.close()
    seg = os.path.join(str(tmp_path / "ts"), "seg_000001.bin")
    with open(seg, "ab") as f:
        f.write(b"\x01\x02\x03")   # torn partial record
    r = tsdb.TSDB(str(tmp_path / "ts"), create=False)
    t, v = r.scan("a")
    assert list(v) == [0.0, 1.0, 2.0, 3.0, 4.0]


def test_tsdb_rate_handles_counter_reset(tmp_path):
    s = tsdb.TSDB(str(tmp_path / "ts"))
    t0 = time.time()
    for i, val in enumerate([0, 10, 20, 0, 10]):   # reset at i=3
        s.append("c_total", val, t=t0 + i)
    # positive deltas only: 10+10+10 over 4s
    assert s.rate("c_total") == pytest.approx(30 / 4.0)
    # .rate series view clamps the reset interval to 0
    t, v = tsdb.series_values(s, "c_total.rate")
    assert list(v) == [10.0, 10.0, 0.0, 10.0]
    s.close()


def test_tsdb_downsample(tmp_path):
    s = tsdb.TSDB(str(tmp_path / "ts"))
    t0 = time.time()
    for i in range(40):
        s.append("a", float(i), t=t0 + i)
    ds = s.downsample("a", buckets=4)
    assert len(ds) == 4
    assert sum(d["count"] for d in ds) == 40
    assert ds[0]["min"] == 0.0 and ds[-1]["max"] == 39.0
    assert ds[0]["mean"] < ds[-1]["mean"]
    s.close()


def test_registry_sampler_decomposes_histograms(tmp_path):
    obs_metrics.counter("wt_count_total").inc(7)
    obs_metrics.gauge("wt_gauge").set(3.5)
    h = obs_metrics.histogram("wt_hist_ms")
    for x in (1.0, 2.0, 3.0, 100.0):
        h.observe(x)
    s = tsdb.TSDB(str(tmp_path / "ts"))
    n = tsdb.sample_registry(s)
    assert n > 0
    assert s.latest("wt_count_total")[1] == 7
    assert s.latest("wt_gauge")[1] == 3.5
    assert s.latest("wt_hist_ms.count")[1] == 4
    assert s.latest("wt_hist_ms.p99")[1] == 100.0
    assert s.latest("wt_hist_ms.sum")[1] == pytest.approx(106.0)
    s.close()


def test_default_store_and_background_sampler(tmp_path):
    """FLAGS_tsdb_dir + ensure_sampler: a per-(label, pid) store
    appears and fills without any explicit sampling calls."""
    prev_dir, prev_ms = FLAGS.tsdb_dir, FLAGS.tsdb_sample_ms
    FLAGS.tsdb_dir = str(tmp_path / "root")
    FLAGS.tsdb_sample_ms = 20
    try:
        assert tsdb.ensure_sampler() is not None
        obs_metrics.counter("wt_bg_total").inc(5)
        deadline = time.time() + 5.0
        got = None
        while time.time() < deadline:
            stores = tsdb.open_stores(str(tmp_path / "root"))
            for label, st in stores.items():
                if st.latest("wt_bg_total"):
                    got = (label, st.latest("wt_bg_total")[1])
                    break
            if got:
                break
            time.sleep(0.05)
        assert got is not None, "sampler never wrote the store"
        assert got[1] >= 5
        assert str(os.getpid()) in got[0]
    finally:
        tsdb.stop_sampler()
        FLAGS.tsdb_dir, FLAGS.tsdb_sample_ms = prev_dir, prev_ms


# -------------------------------------------------------- perf sentinel

def _fake_runs():
    """A synthetic trajectory: two historical runs of one qps metric
    (higher better) and one latency metric (lower better)."""
    return [
        ("RUN_r01.json",
         {"qps": {"value": 900.0, "higher_is_better": True,
                  "unit": "qps"},
          "p99_ms": {"value": 12.0, "higher_is_better": False,
                     "unit": "ms"}}, False),
        ("RUN_r02.json",
         {"qps": {"value": 1000.0, "higher_is_better": True,
                  "unit": "qps"},
          "p99_ms": {"value": 10.0, "higher_is_better": False,
                     "unit": "ms"}}, False),
    ]


def test_sentinel_synthetic_regression_rc3_and_clean_rc0():
    ps = _tool("perf_sentinel")
    traj = ps.build_trajectory(runs=_fake_runs())
    assert traj["metrics"]["qps"]["floor"] == 1000.0
    assert traj["metrics"]["p99_ms"]["floor"] == 10.0

    # clean run: within 15% of both floors
    clean = {"qps": {"value": 980.0, "higher_is_better": True},
             "p99_ms": {"value": 10.5, "higher_is_better": False}}
    regs, checked, skipped = ps.check_metrics(traj, clean)
    assert not regs and len(checked) == 2 and not skipped

    # planted regression: qps halves, p99 triples
    bad = {"qps": {"value": 500.0, "higher_is_better": True},
           "p99_ms": {"value": 30.0, "higher_is_better": False}}
    regs, _, _ = ps.check_metrics(traj, bad)
    assert {r["metric"] for r in regs} == {"qps", "p99_ms"}
    assert regs[0]["regress_frac"] > 0.15


def test_sentinel_cli_quick_modes(tmp_path):
    """The tier-1 smoke the ISSUE names: a degraded copy of the real
    SERVE_BENCH.json exits rc 3 through the CLI; the genuine artifact
    exits rc 0."""
    ps = _tool("perf_sentinel")
    src = os.path.join(REPO, "SERVE_BENCH.json")
    if not os.path.exists(src):
        pytest.skip("no SERVE_BENCH.json in this checkout")
    with open(src) as f:
        obj = json.load(f)
    degraded = dict(obj)
    degraded["floor"] = dict(obj["floor"],
                             qps=obj["floor"]["qps"] * 0.5)
    bad_path = str(tmp_path / "degraded.json")
    with open(bad_path, "w") as f:
        json.dump(degraded, f)
    assert ps.main(["--no-write", "--check", bad_path]) == 3
    assert ps.main(["--no-write", "--check", src]) == 0


def test_sentinel_quick_runs_gate_against_quick_floors_only():
    """A seconds-scale CI smoke must not be judged against a full
    run's floor (and vice versa)."""
    ps = _tool("perf_sentinel")
    runs = _fake_runs() + [
        ("RUN_quick.json",
         {"qps": {"value": 100.0, "higher_is_better": True}}, True)]
    traj = ps.build_trajectory(runs=runs)
    assert traj["metrics"]["qps"]["floor"] == 1000.0      # full only
    assert traj["metrics"]["qps"]["quick_floor"] == 100.0
    # a quick run at 95 qps: fine vs the quick floor, catastrophic vs
    # the full floor — it must compare against quick only
    regs, checked, _ = ps.check_metrics(
        traj, {"qps": {"value": 95.0, "higher_is_better": True}},
        quick=True)
    assert not regs and checked[0]["quick"]
    # and a quick run WITH a real quick regression still fails
    regs, _, _ = ps.check_metrics(
        traj, {"qps": {"value": 40.0, "higher_is_better": True}},
        quick=True)
    assert regs


def test_sentinel_builds_from_repo_artifacts(tmp_path):
    """The real in-repo *_BENCH.json + BENCH_r*.json pile becomes one
    trajectory with the expected headline metrics."""
    ps = _tool("perf_sentinel")
    traj = ps.build_trajectory(REPO)
    names = set(traj["metrics"])
    assert "serve_floor_qps" in names
    assert "pserver_dense_rounds_per_sec" in names
    assert "scale_peak_rows_per_sec" in names
    # training rounds parsed out of the driver-wrapped tails
    assert any(n.startswith("resnet50") for n in names)
    for ent in traj["metrics"].values():
        assert ent["runs"] and ent["latest"] is not None
    # the CLI writes the canonical record atomically
    out = str(tmp_path / "PERF_TRAJECTORY.json")
    assert ps.main(["--repo", REPO, "--out", out]) == 0
    with open(out) as f:
        written = json.load(f)
    assert written["kind"] == "perf_trajectory"


def test_sentinel_ingests_tsdb(tmp_path):
    ps = _tool("perf_sentinel")
    store = tsdb.TSDB(str(tmp_path / "ts" / "proc_1"))
    t0 = time.time()
    for i in range(5):
        store.append("m_total", i * 2.0, t=t0 + i)
    store.close()
    traj = ps.build_trajectory(
        REPO, tsdb_root=str(tmp_path / "ts"),
        runs=_fake_runs())
    assert traj["tsdb"]["proc_1"]["m_total"]["last"] == 8.0
    assert traj["tsdb"]["proc_1"]["m_total"]["n"] == 5


# ------------------------------------------------------- watchtower CLI

def _canned_state(tmp_path):
    """A canned operational state: one store with a violating series,
    an slo:* flight dump, and a tiny trajectory file."""
    store = tsdb.TSDB(str(tmp_path / "ts" / "serve_1"))
    now = time.time()
    for i in range(30):
        store.append_row({"serve_request_ms_m.p99": 50.0 + i,
                          "serve_requests_total": 10 * i}, t=now - 30 + i)
    store.close()
    ev = slo.Evaluator(
        tsdb.TSDB(str(tmp_path / "ts" / "serve_1"), create=False),
        slo.load_specs("serve_request_ms_m.p99<=10"))
    FLAGS.telemetry_dump_dir, prev = str(tmp_path / "dumps"), \
        FLAGS.telemetry_dump_dir
    try:
        ev.evaluate(now=now)
    finally:
        FLAGS.telemetry_dump_dir = prev
    traj = {"kind": "perf_trajectory", "version": 1, "metrics": {
        "qps": {"higher_is_better": True, "unit": "qps",
                "runs": [{"source": "a", "value": 1000.0,
                          "quick": False},
                         {"source": "b", "value": 500.0,
                          "quick": False}],
                "floor": 1000.0, "latest": 500.0}}}
    tpath = str(tmp_path / "PERF_TRAJECTORY.json")
    with open(tpath, "w") as f:
        json.dump(traj, f)
    return tpath


def test_watchtower_report_from_canned_dump_dir(tmp_path, capsys):
    wt = _tool("watchtower")
    tpath = _canned_state(tmp_path)
    rc = wt.main(["--tsdb", str(tmp_path / "ts"),
                  "--dump-dir", str(tmp_path / "dumps"),
                  "--slo", "serve_request_ms_m.p99<=10",
                  "--trajectory", tpath])
    assert rc == 0
    out = capsys.readouterr().out
    assert "SLO status" in out
    assert "serve_request_ms_m_p99" in out
    assert "fast" in out                      # firing marker
    assert "alerts (" in out and "slo:" in out
    assert "hot series" in out
    # sparkline block characters actually rendered
    assert any(c in out for c in wt.SPARK)
    assert "bench trajectory" in out and "REGRESSED" in out


def test_watchtower_json_report(tmp_path, capsys):
    wt = _tool("watchtower")
    tpath = _canned_state(tmp_path)
    rc = wt.main(["--tsdb", str(tmp_path / "ts"),
                  "--dump-dir", str(tmp_path / "dumps"),
                  "--slo", "serve_request_ms_m.p99<=10",
                  "--trajectory", tpath, "--json"])
    assert rc == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["kind"] == "watchtower_report"
    row = rep["slo"][0]
    assert row["firing"]                      # violating series fires
    assert row["budget_remaining"] == 0.0
    assert rep["alerts"] and rep["alerts"][0]["slo"] \
        == "serve_request_ms_m_p99"
    assert rep["alerts"][0]["series_samples"] > 0
    assert rep["bench"][0]["regressed"]


def test_watchtower_slo_anchors_at_store_time(tmp_path, capsys):
    """Post-hoc reads anchor windows at the store's newest sample:
    a collapse from hours ago still shows its burn instead of an
    empty (and therefore 'healthy') wall-clock window."""
    wt = _tool("watchtower")
    store = tsdb.TSDB(str(tmp_path / "ts" / "old_1"))
    old = time.time() - 7200          # two hours ago
    for i in range(20):
        store.append("m", 9.0, t=old + i)
    store.close()
    rc = wt.main(["--tsdb", str(tmp_path / "ts"), "--slo", "m<=5",
                  "--json"])
    assert rc == 0
    rep = json.loads(capsys.readouterr().out)
    row = rep["slo"][0]
    assert row["as_of"] == pytest.approx(old + 19)
    assert "fast" in row["firing"]
    assert row["budget_remaining"] == 0.0


def test_sparkline_shapes():
    wt = _tool("watchtower")
    assert wt.sparkline([]) == ""
    assert wt.sparkline([1.0, 1.0, 1.0]) == wt.SPARK[0] * 3
    s = wt.sparkline(list(range(64)), width=8)
    assert len(s) == 8
    assert s[0] == wt.SPARK[0] and s[-1] == wt.SPARK[-1]


# ----------------------------------------------- trace_report registry

def test_trace_report_all_implies_every_rollup(tmp_path, capsys):
    """--all = --kernels + every registered rollup, through the ONE
    table-registry loop (the per-flag copy-paste dispatch is gone)."""
    tr = _tool("trace_report")
    # registry covers exactly the known rollups
    assert [r[0] for r in tr.ROLLUPS] == [
        "numerics", "wire", "serve", "scale", "slo", "moe", "weaver"]
    from paddle_tpu.observability.trace import Tracer
    obs_metrics.counter("slo_alerts_total").inc()
    t = Tracer(enabled=True)
    t.set_label("proc0")
    t.end(t.begin("step.prepared"))
    dump = str(tmp_path / "trace_p.json")
    t.dump(dump)
    rc = tr.main([dump, "--all"])
    assert rc == 0
    out = capsys.readouterr().out
    for title_frag in ("numerics rollup", "wire rollup",
                       "serve rollup", "scale rollup", "slo rollup",
                       "moe rollup"):
        assert title_frag in out, title_frag
    # JSON mode wraps every requested rollup key
    rc = tr.main([dump, "--all", "--json"])
    assert rc == 0
    obj = json.loads(capsys.readouterr().out)
    assert set(obj) == {"phases", "kernels", "numerics", "wire",
                        "serve", "scale", "slo", "moe", "weaver"}


def test_trace_report_slo_rollup_reads_gauges(tmp_path, capsys):
    """The --slo rollup reads the evaluator's mirrored gauges out of
    any dump's metrics snapshot."""
    tr = _tool("trace_report")
    obs_metrics.gauge("slo_burn_fast_myslo").set(21.5)
    obs_metrics.gauge("slo_burn_slow_myslo").set(3.25)
    obs_metrics.gauge("slo_budget_remaining_myslo").set(0.4)
    obs_metrics.counter("slo_alerts_total").inc(2)
    from paddle_tpu.observability.trace import Tracer
    t = Tracer(enabled=True)
    t.set_label("trainer0")
    t.end(t.begin("step.prepared"))
    dump = str(tmp_path / "trace_t.json")
    t.dump(dump)
    rc = tr.main([dump, "--slo"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "slo rollup" in out
    assert "myslo" in out and "21.50" in out
