"""Parallel strategies: ring attention / pipeline / MoE vs dense
references, and fluid-level tp/sp/ep training on multi-axis meshes.

Mirrors the reference's multi-device testing approach (SURVEY §4.3:
op-handle tests over fake multi-place lists) on the virtual 8-device CPU
mesh.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.parallel import (make_mesh, auto_mesh_axes, ring_attention,
                                 pipeline_apply, moe_ffn)


def _cpu(n):
    devs = jax.devices("cpu")
    if len(devs) < n:
        pytest.skip("needs %d cpu devices" % n)
    return devs[:n]


def test_ring_attention_matches_dense():
    devs = _cpu(4)
    mesh = make_mesh({"sp": 4}, devices=devs)
    B, H, S, D = 2, 3, 16, 8
    rng = np.random.RandomState(0)
    qn, kn, vn = [rng.randn(B, H, S, D).astype(np.float32)
                  for _ in range(3)]
    q, k, v = map(jnp.asarray, (qn, kn, vn))
    for causal in (True, False):
        out = np.asarray(ring_attention(q, k, v, mesh, causal=causal))
        s = np.einsum("bhqd,bhkd->bhqk", qn.astype(np.float64),
                      kn.astype(np.float64)) * (D ** -0.5)
        if causal:
            mask = np.tril(np.ones((S, S), bool))
            s = np.where(mask[None, None], s, -np.inf)
        e = np.exp(s - s.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        ref = np.einsum("bhqk,bhkd->bhqd", p, vn.astype(np.float64))
        assert np.abs(out - ref).max() < 1e-4, causal


def test_ring_attention_grad():
    devs = _cpu(4)
    mesh = make_mesh({"sp": 4}, devices=devs)
    rng = np.random.RandomState(1)
    q, k, v = [jnp.asarray(rng.randn(1, 2, 8, 4).astype(np.float32))
               for _ in range(3)]
    g = jax.grad(lambda q: ring_attention(q, k, v, mesh).sum())(q)
    assert bool(jnp.isfinite(g).all())


def test_pipeline_matches_sequential():
    devs = _cpu(4)
    P_, M, mb, D = 4, 8, 2, 16
    mesh = make_mesh({"pp": P_}, devices=devs)
    rng = np.random.RandomState(0)
    Wn = rng.randn(P_, D, D).astype(np.float32) * 0.3
    xn = rng.randn(M, mb, D).astype(np.float32)

    def stage(w, x):
        return jnp.tanh(x @ w)

    out = np.asarray(pipeline_apply(jnp.asarray(Wn), jnp.asarray(xn),
                                    mesh, stage))
    ref = xn.astype(np.float64)
    for s in range(P_):
        ref = np.tanh(ref @ Wn[s])
    assert np.abs(out - ref).max() < 1e-4


def test_pipeline_train_step():
    devs = _cpu(4)
    mesh = make_mesh({"pp": 4}, devices=devs)
    rng = np.random.RandomState(0)
    ws = jnp.asarray(rng.randn(4, 8, 8).astype(np.float32) * 0.3)
    xs = jnp.asarray(rng.randn(4, 2, 8).astype(np.float32))

    def step(ws):
        out = pipeline_apply(ws, xs, mesh,
                             lambda w, x: jnp.tanh(x @ w))
        return jnp.mean(out ** 2)

    loss, g = jax.value_and_grad(step)(ws)
    assert np.isfinite(float(loss)) and bool(jnp.isfinite(g).all())


def test_moe_matches_dense_dispatch():
    devs = _cpu(4)
    mesh = make_mesh({"ep": 4}, devices=devs)
    D, E, F, T = 16, 4, 32, 64
    rng = np.random.RandomState(0)
    wgn = rng.randn(D, E).astype(np.float32) * 0.5
    w1n = rng.randn(E, D, F).astype(np.float32) * 0.2
    w2n = rng.randn(E, F, D).astype(np.float32) * 0.2
    xn = rng.randn(T, D).astype(np.float32)
    y = np.asarray(moe_ffn(jnp.asarray(xn), jnp.asarray(wgn),
                           jnp.asarray(w1n), jnp.asarray(w2n), mesh,
                           capacity_factor=4.0))
    logits = xn @ wgn
    g = np.exp(logits - logits.max(-1, keepdims=True))
    g /= g.sum(-1, keepdims=True)
    expi = g.argmax(-1)
    gate = g[np.arange(T), expi]
    ref = np.zeros_like(xn)
    for t in range(T):
        h = np.maximum(xn[t] @ w1n[expi[t]], 0)
        ref[t] = (h @ w2n[expi[t]]) * gate[t]
    assert np.abs(y - ref).max() < 1e-4


def test_auto_mesh_axes():
    assert auto_mesh_axes(1) == {"dp": 1, "tp": 1, "sp": 1, "pp": 1}
    for n in (2, 4, 6, 8, 12):
        axes = auto_mesh_axes(n)
        assert int(np.prod(list(axes.values()))) == n


def test_fluid_tp_training(prog_scope):
    main, startup, scope = prog_scope
    x = fluid.layers.data(name="x", shape=[32], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    h = fluid.layers.fc(x, size=64, act="relu",
                        param_attr=fluid.param_attr.ParamAttr(
                            sharding=(None, "tp")))
    out = fluid.layers.fc(h, size=1,
                          param_attr=fluid.param_attr.ParamAttr(
                              sharding=("tp", None)))
    loss = fluid.layers.mean(fluid.layers.square_error_cost(out, y))
    fluid.optimizer.SGD(0.05).minimize(loss)
    fluid.Executor(fluid.CPUPlace()).run(startup)
    pe = fluid.ParallelExecutor(use_tpu=False, loss_name=loss.name,
                                main_program=main, scope=scope,
                                mesh_axes={"dp": 2, "tp": 4})
    rng = np.random.RandomState(0)
    true_w = rng.randn(32, 1).astype(np.float32)
    losses = []
    for _ in range(40):
        xs = rng.randn(16, 32).astype(np.float32)
        losses.append(float(np.asarray(pe.run(
            feed={"x": xs, "y": xs @ true_w}, fetch_list=[loss])[0])
            .ravel()[0]))
    assert losses[-1] < losses[0] * 0.2
    # the weight must physically live sharded over tp
    wname = [n for n in scope.local_var_names()
             if n.endswith("fc_0.w_0")][0]
    w = scope.find_var(wname)
    assert "tp" in str(w.sharding.spec)


def test_transformer_sp_tp_mesh(prog_scope):
    from paddle_tpu.models.transformer import get_model
    main, startup, scope = prog_scope
    loss, (src, label), _ = get_model(
        vocab_size=64, seq_len=16, d_model=32, n_head=4, n_layers=2,
        d_ff=64, learning_rate=3e-3, tp=True, sp=True)
    fluid.Executor(fluid.CPUPlace()).run(startup)
    pe = fluid.ParallelExecutor(use_tpu=False, loss_name=loss.name,
                                main_program=main, scope=scope,
                                mesh_axes={"dp": 2, "tp": 2, "sp": 2})
    rng = np.random.RandomState(0)
    xs = rng.randint(0, 64, (4, 16)).astype(np.int64)
    ys = np.roll(xs, -1, axis=1)[:, :, None].astype(np.int64)
    ls = []
    for _ in range(25):
        l, = pe.run(feed={"src": xs, "label": ys}, fetch_list=[loss])
        ls.append(float(np.asarray(l).ravel()[0]))
    assert ls[-1] < ls[0], (ls[0], ls[-1])


def test_transformer_moe_ep_mesh(prog_scope):
    from paddle_tpu.models.transformer import get_model
    main, startup, scope = prog_scope
    loss, (src, label), _ = get_model(
        vocab_size=64, seq_len=16, d_model=32, n_head=4, n_layers=2,
        d_ff=64, learning_rate=3e-3, moe_experts=4, ep=True)
    fluid.Executor(fluid.CPUPlace()).run(startup)
    pe = fluid.ParallelExecutor(use_tpu=False, loss_name=loss.name,
                                main_program=main, scope=scope,
                                mesh_axes={"dp": 2, "ep": 4})
    rng = np.random.RandomState(0)
    xs = rng.randint(0, 64, (4, 16)).astype(np.int64)
    ys = np.roll(xs, -1, axis=1)[:, :, None].astype(np.int64)
    ls = []
    for _ in range(25):
        l, = pe.run(feed={"src": xs, "label": ys}, fetch_list=[loss])
        ls.append(float(np.asarray(l).ravel()[0]))
    assert ls[-1] < ls[0], (ls[0], ls[-1])
