"""Program verifier unit tests: for every checker one positive case (a
deliberately seeded defect it must flag with the right diagnostic) and
one negative case (a valid program passes clean), plus the executor /
FLAGS_check_program wiring and the OpDesc mutation-bumps-version
regression the verifier's cache-miss cadence depends on."""
import warnings

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import analysis
from paddle_tpu.analysis import (ProgramLintWarning,
                                 ProgramVerificationError, Severity)
from paddle_tpu.core import desc as core_desc
from paddle_tpu.core.flags import FLAGS
from paddle_tpu.core.scope import Scope

from test_book_models import build_fit_a_line


def _diags(prog, checker=None):
    out = analysis.verify_program(prog)
    if checker is not None:
        out = [d for d in out if d.checker == checker]
    return out


def _errors(diags):
    return [d for d in diags if d.severity == Severity.ERROR]


def _prog_with(ops, vars_=()):
    prog = core_desc.ProgramDesc()
    b = prog.blocks[0]
    for vd in vars_:
        b.add_var(vd)
    for op in ops:
        b.append_op(op)
    return prog


V = core_desc.VarDesc
O = core_desc.OpDesc


# ---------------------------------------------------------------------------
# def-use
# ---------------------------------------------------------------------------

def test_def_use_flags_undeclared_var():
    prog = _prog_with(
        [O("relu", {"X": ["ghost"]}, {"Out": ["a"]})],
        [V("a", shape=(2, 3))])
    errs = _errors(_diags(prog, "def-use"))
    assert len(errs) == 1
    d = errs[0]
    assert d.var == "ghost" and d.op_type == "relu" and d.block_idx == 0
    assert "no reachable VarDesc" in d.message


def test_def_use_flags_use_before_def():
    prog = _prog_with(
        [O("relu", {"X": ["t"]}, {"Out": ["o"]}),      # reads t first...
         O("relu", {"X": ["x"]}, {"Out": ["t"]})],     # ...written later
        [V("x", shape=(2,)), V("t", shape=(2,)), V("o", shape=(2,))])
    diags = _diags(prog, "def-use")
    assert any(d.var == "t" and d.severity == Severity.WARNING
               and "read before its first write" in d.message
               for d in diags)


def test_def_use_clean_program(prog_scope):
    main, startup, scope = prog_scope
    build_fit_a_line()
    assert _diags(main.desc, "def-use") == []
    assert _diags(startup.desc, "def-use") == []


# ---------------------------------------------------------------------------
# block-refs
# ---------------------------------------------------------------------------

def test_block_refs_flags_dangling_sub_block():
    prog = _prog_with([O("while", {}, {}, {"sub_block": 7})])
    errs = _errors(_diags(prog, "block-refs"))
    assert len(errs) == 1
    assert "sub-block 7" in errs[0].message and errs[0].op_type == "while"


def test_block_refs_accepts_valid_sub_block():
    prog = core_desc.ProgramDesc()
    sub = prog.append_block(parent_idx=0)
    prog.blocks[0].append_op(O("go", {}, {}, {"sub_block": sub.idx}))
    assert _diags(prog, "block-refs") == []


# ---------------------------------------------------------------------------
# shapes
# ---------------------------------------------------------------------------

def test_shapes_flags_contracting_dim_mismatch():
    prog = _prog_with(
        [O("mul", {"X": ["x"], "Y": ["w"]}, {"Out": ["o"]})],
        [V("x", shape=(4, 3)), V("w", shape=(5, 6)), V("o", shape=(4, 6))])
    errs = _errors(_diags(prog, "shapes"))
    assert len(errs) == 1
    assert errs[0].op_type == "mul"
    assert "abstract evaluation failed" in errs[0].message


def test_shapes_flags_declared_dtype_drift():
    from paddle_tpu.core.types import DataType
    prog = _prog_with(
        [O("relu", {"X": ["x"]}, {"Out": ["o"]})],
        [V("x", shape=(2, 3)),
         V("o", shape=(2, 3), dtype=DataType.INT32)])
    errs = _errors(_diags(prog, "shapes"))
    assert any(d.var == "o" and "declared dtype" in d.message
               for d in errs)


def test_shapes_clean_program(prog_scope):
    main, startup, scope = prog_scope
    build_fit_a_line()
    assert _errors(_diags(main.desc, "shapes")) == []


# ---------------------------------------------------------------------------
# grad-completeness
# ---------------------------------------------------------------------------

def test_grad_completeness_flags_orphan_grad_op():
    prog = _prog_with(
        [O("totally_bogus_grad", {"X": ["x"]}, {"Out": ["o"]})],
        [V("x", shape=(2,)), V("o", shape=(2,))])
    errs = _errors(_diags(prog, "grad-completeness"))
    assert len(errs) == 1
    assert "no registered lowering" in errs[0].message
    assert errs[0].op_type == "totally_bogus_grad"


def test_grad_completeness_accepts_synthesized_vjp():
    # relu_grad is not explicitly registered; the forward IS, so the
    # generic vjp lowering applies and the checker must stay silent
    prog = _prog_with(
        [O("relu_grad", {"X": ["x"], "Out": ["o"],
                         "Out@GRAD": ["og"]}, {"X@GRAD": ["xg"]})],
        [V(n, shape=(2,)) for n in ("x", "o", "og", "xg")])
    assert _diags(prog, "grad-completeness") == []


# ---------------------------------------------------------------------------
# dist-pairing
# ---------------------------------------------------------------------------

def _send(eps, sections, names, var="g"):
    return O("send", {"X": [var]}, {},
             {"epmap": eps, "sections": sections, "block_names": names})


def test_dist_pairing_flags_misrouted_slices():
    prog = _prog_with(
        [_send(["h:1", "h:2"], [4], ["g.block0", "g.block1"])],
        [V("g", shape=(8, 2), persistable=True)])
    errs = _errors(_diags(prog, "dist-pairing"))
    assert any("lengths disagree" in d.message for d in errs)


def test_dist_pairing_flags_recv_before_barrier():
    prog = _prog_with(
        [_send(["h:1"], [8], ["g.block0"]),
         O("recv", {}, {"Out": ["p"]},
           {"epmap": ["h:1"], "sections": [8],
            "block_names": ["p.block0"]}),
         O("send_barrier", {}, {}, {"endpoints": ["h:1"]})],
        [V("g", shape=(8, 2), persistable=True),
         V("p", shape=(8, 2), persistable=True)])
    errs = _errors(_diags(prog, "dist-pairing"))
    assert any("recv appears before the send_barrier" in d.message
               for d in errs)


def test_dist_pairing_clean_transpiled_program(prog_scope):
    main, startup, scope = prog_scope
    build_fit_a_line()
    t = fluid.DistributeTranspiler()
    t.transpile(trainer_id=0, program=main, startup_program=startup,
                pservers="127.0.0.1:6184,127.0.0.1:6185", trainers=2)
    assert _errors(_diags(main.desc)) == []
    assert _errors(_diags(startup.desc)) == []


def test_dist_pairing_cross_program(prog_scope):
    main, startup, scope = prog_scope
    build_fit_a_line()
    t = fluid.DistributeTranspiler()
    t.transpile(trainer_id=0, program=main, startup_program=startup,
                pservers="127.0.0.1:6186", trainers=1)
    ps = t.get_pserver_program("127.0.0.1:6186")
    clean = analysis.verify_transpiled_pair(
        main.desc, {"127.0.0.1:6186": ps.desc})
    assert clean == []
    # drop one served grad: the pairing check must name the orphan send
    for op in ps.desc.blocks[0].ops:
        if op.type == "listen_and_serv":
            entries = op.attr("grad_to_block_id")
            op.set_attr("grad_to_block_id", entries[1:])
    broken = analysis.verify_transpiled_pair(
        main.desc, {"127.0.0.1:6186": ps.desc})
    assert any(d.op_type == "send" and "dropped" in d.message
               for d in broken)


# ---------------------------------------------------------------------------
# concurrency
# ---------------------------------------------------------------------------

def test_concurrency_flags_two_concurrent_writers():
    prog = core_desc.ProgramDesc()
    b0 = prog.blocks[0]
    b0.add_var(V("x", shape=(2,)))
    b0.add_var(V("shared", shape=(2,)))
    for _ in range(2):
        sub = prog.append_block(parent_idx=0)
        sub.append_op(O("scale", {"X": ["x"]}, {"Out": ["shared"]},
                        {"scale": 2.0}))
        b0.append_op(O("go", {"X": ["x"]}, {}, {"sub_block": sub.idx}))
    errs = _errors(_diags(prog, "concurrency"))
    assert any(d.var == "shared"
               and "written by concurrent blocks" in d.message
               for d in errs)


def test_concurrency_flags_unsynced_parent_write():
    prog = core_desc.ProgramDesc()
    b0 = prog.blocks[0]
    b0.add_var(V("x", shape=(2,)))
    b0.add_var(V("shared", shape=(2,)))
    sub = prog.append_block(parent_idx=0)
    sub.append_op(O("scale", {"X": ["x"]}, {"Out": ["shared"]},
                    {"scale": 2.0}))
    b0.append_op(O("go", {"X": ["x"]}, {}, {"sub_block": sub.idx}))
    b0.append_op(O("scale", {"X": ["x"]}, {"Out": ["shared"]},
                   {"scale": 3.0}))
    errs = _errors(_diags(prog, "concurrency"))
    assert any(d.var == "shared" and d.op_type == "scale" for d in errs)


def test_concurrency_channel_recv_synchronizes(prog_scope):
    """The canonical CSP producer/consumer (go -> channel -> recv) must
    pass clean: the recv between launch and the consuming ops IS the
    synchronization."""
    main, startup, scope = prog_scope
    from paddle_tpu.fluid import concurrency as C
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    ch = C.program_make_channel(dtype="float32", capacity=2)
    with C.ProgramGo():
        doubled = fluid.layers.scale(x, scale=2.0)
        C.program_channel_send(ch, doubled)
    got = fluid.layers.data(name="got_buf", shape=[4], dtype="float32")
    C.program_channel_recv(ch, got)
    fluid.layers.scale(got, scale=10.0)
    assert _errors(_diags(main.desc, "concurrency")) == []


def test_lifetime_flags_donation_hazard():
    """The PR 3 concurrency checker's prepared-donation hazard moved to
    the dedicated 'lifetime' checker (ISSUE 14) — same shape, richer
    state model; the concurrency checker no longer reports it."""
    prog = _prog_with(
        [O("save", {"X": ["w"]}, {}, {"file_path": "/tmp/x"}),
         O("scale", {"X": ["w"]}, {"Out": ["w"]}, {"scale": 0.9})],
        [V("w", shape=(4,), persistable=True)])
    diags = _diags(prog, "lifetime")
    assert any(d.var == "w" and d.severity == Severity.WARNING
               and "donates" in d.message for d in diags)
    assert not any(d.var == "w" for d in _diags(prog, "concurrency"))


# ---------------------------------------------------------------------------
# executor wiring: FLAGS_check_program gate, verify-on-cache-miss cadence
# ---------------------------------------------------------------------------

def _bad_shape_program():
    main = fluid.Program()
    b = main.desc.blocks[0]
    b.add_var(V("x", shape=(4, 3)))
    b.add_var(V("w", shape=(5, 6)))
    b.add_var(V("o", shape=(4, 6)))
    b.append_op(O("mul", {"X": ["x"], "Y": ["w"]}, {"Out": ["o"]}))
    return main


def test_executor_error_mode_raises_before_tracing():
    main = _bad_shape_program()
    exe = fluid.Executor(fluid.CPUPlace())
    old = FLAGS.check_program
    FLAGS.check_program = "error"
    try:
        with pytest.raises(ProgramVerificationError) as ei:
            with fluid.scope_guard(Scope()):
                exe.run(main, feed={"x": np.ones((4, 3), np.float32),
                                    "w": np.ones((5, 6), np.float32)},
                        fetch_list=["o"])
        assert "shapes" in str(ei.value)
    finally:
        FLAGS.check_program = old


def test_executor_warn_mode_warns_once_per_version():
    main = _bad_shape_program()
    exe = fluid.Executor(fluid.CPUPlace())
    assert FLAGS.check_program == "warn"  # the documented default
    feed = {"x": np.ones((4, 3), np.float32),
            "w": np.ones((5, 6), np.float32)}
    with pytest.warns(ProgramLintWarning):
        with pytest.raises(Exception):
            with fluid.scope_guard(Scope()):
                exe.run(main, feed=feed, fetch_list=["o"])
    # same version: verified marker short-circuits, no second warning
    with warnings.catch_warnings():
        warnings.simplefilter("error", ProgramLintWarning)
        with pytest.raises(Exception):
            with fluid.scope_guard(Scope()):
                exe.run(main, feed=feed, fetch_list=["o"])


# ---------------------------------------------------------------------------
# OpDesc mutation bumps the program version (stale-cache regression)
# ---------------------------------------------------------------------------

def test_op_desc_mutators_bump_version(prog_scope):
    main, startup, scope = prog_scope
    build_fit_a_line()
    desc = main.desc
    op = desc.blocks[0].ops[0]
    v0 = desc.version
    op.set_attr("some_attr", 1)
    assert desc.version > v0, "set_attr must invalidate compiled caches"
    v1 = desc.version
    old = op.input_arg_names()[0]
    op.rename_input(old, old + "@renamed")
    assert desc.version > v1
    v2 = desc.version
    op.rename_input("no_such_name", "whatever")  # no-op: no bump
    assert desc.version == v2
    out = op.output_arg_names()[0]
    op.rename_output(out, out + "@renamed")
    assert desc.version > v2


def test_pruned_program_mutators_still_bump_version(prog_scope, exe):
    """prune() rebuilds its op list outside BlockDesc.append_op; the
    rebuilt ops must still carry the block backref or post-prune
    mutations silently skip the version bump."""
    main, startup, scope = prog_scope
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    p = fluid.layers.fc(input=x, size=2, act=None)
    pruned = main.prune([p])
    v0 = pruned.desc.version
    pruned.desc.blocks[0].ops[0].set_attr("post_prune_attr", 1)
    assert pruned.desc.version > v0


def test_prepared_program_sees_post_rename_mutation(prog_scope, exe):
    """PR 2 regression: prepared entries are keyed on program version;
    an OpDesc rename after prepare() must mark the entry stale instead
    of silently serving the pre-rename executable."""
    main, startup, scope = prog_scope
    avg_cost = build_fit_a_line()
    exe.run(startup)
    feed = {"x": np.ones((8, 13), np.float32),
            "y": np.ones((8, 1), np.float32)}
    prep = exe.prepare(main, feed_specs=feed, fetch_list=[avg_cost])
    assert not prep.is_stale
    op = main.desc.blocks[0].ops[0]
    op.set_attr("mutated_after_prepare", True)
    assert prep.is_stale, ("a transpiler-style mutation must invalidate "
                           "the prepared entry")


# ---------------------------------------------------------------------------
# slot errors (OpDesc.input/output)
# ---------------------------------------------------------------------------

def test_op_slot_error_names_op_and_slots():
    op = O("mul", {"X": ["x"], "Y": ["w"]}, {"Out": ["o"]})
    with pytest.raises(KeyError) as ei:
        op.input("Z")
    msg = str(ei.value)
    assert "mul" in msg and "'Z'" in msg and "X" in msg and "Y" in msg
    with pytest.raises(KeyError) as ei:
        op.output("Result")
    msg = str(ei.value)
    assert "mul" in msg and "Out" in msg
    # probing with an explicit default stays non-raising
    assert op.input("Z", []) == []
    assert op.output("Result", []) == []


# ---------------------------------------------------------------------------
# numerics (ISSUE 8): risk ops x half-precision inputs
# ---------------------------------------------------------------------------

def test_numerics_flags_declared_half_precision_risk_input():
    from paddle_tpu.core.types import DataType

    prog = _prog_with(
        [O("exp", {"X": ["h"]}, {"Out": ["e"]})],
        [V("h", shape=(2, 3), dtype=DataType.FP16),
         V("e", shape=(2, 3), dtype=DataType.FP16)])
    diags = _diags(prog, "numerics")
    assert len(diags) == 1
    d = diags[0]
    assert d.severity == Severity.WARNING and d.op_type == "exp" \
        and d.var == "h"
    assert "half-precision" in d.message


def test_numerics_flags_amp_white_producer_into_unprotected_risk_op():
    prog = _prog_with(
        [O("mul", {"X": ["x"], "Y": ["w"]}, {"Out": ["h"]}),
         O("elementwise_div", {"X": ["h"], "Y": ["d"]},
           {"Out": ["q"]})],
        [V("x", shape=(2, 3)), V("w", shape=(3, 3)),
         V("h", shape=(2, 3)), V("d", shape=(2, 3)),
         V("q", shape=(2, 3))])
    # without AMP: nothing is bf16 at trace time -> clean
    assert _diags(prog, "numerics") == []
    prog.amp_bf16 = True
    diags = _diags(prog, "numerics")
    assert any(d.op_type == "elementwise_div" and d.var == "h"
               and "bf16 output of autocast op 'mul'" in d.message
               for d in diags)


def test_numerics_amp_black_risk_op_is_protected():
    """log/exp are AMP_BLACK: the lowering casts their inputs back to
    f32 under AMP, so no diagnostic is due for the same pattern."""
    prog = _prog_with(
        [O("mul", {"X": ["x"], "Y": ["w"]}, {"Out": ["h"]}),
         O("log", {"X": ["h"]}, {"Out": ["l"]})],
        [V("x", shape=(2, 3)), V("w", shape=(3, 3)),
         V("h", shape=(2, 3)), V("l", shape=(2, 3))])
    prog.amp_bf16 = True
    assert _diags(prog, "numerics") == []


def test_numerics_clean_f32_program(prog_scope):
    main, startup, scope = prog_scope
    build_fit_a_line()
    assert _diags(main.desc, "numerics") == []
