"""Spawned-process workers for the multi-host ParallelExecutor test.

Lives in its own module (not the test file): multiprocessing 'spawn'
re-imports the worker's module in the child, and the child must not
re-run pytest collection or the conftest of the parent.  The parent
sets the platform env (JAX_PLATFORMS/XLA_FLAGS/PADDLE_* contract)
BEFORE Process.start(): sitecustomize touches jax at interpreter
startup, so env set inside the worker would be too late.
"""
import numpy as np


def _build_and_train(num_trainers, trainer_id, steps=3, mesh_axes=None,
                     tp=False):
    """Tiny deterministic regression program trained with the SPMD
    ParallelExecutor; returns (losses, n_global_devices).

    Feed contract: the GLOBAL batch is 8 fixed rows; a multi-host
    trainer feeds only its own 8/num_trainers rows (reference nccl2
    semantics, parallel_executor.cc:84-95)."""
    import jax

    import paddle_tpu.fluid as fluid
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.distributed import collective

    if num_trainers > 1:
        # must happen before ANY jax backend touch (jax.distributed
        # contract) — a real trainer joins the world first thing, the
        # same place the reference ran gen_nccl_id
        collective.init_collective_env()

    rng = np.random.RandomState(0)
    xs = rng.randn(8, 16).astype(np.float32)
    ws = rng.randn(16, 1).astype(np.float32)
    ys = (xs @ ws).astype(np.float32)
    lo = trainer_id * (8 // num_trainers)
    hi = lo + 8 // num_trainers
    x_local, y_local = xs[lo:hi], ys[lo:hi]

    main, startup = fluid.Program(), fluid.Program()
    scope = Scope()
    col = fluid.param_attr.ParamAttr(sharding=(None, "tp")) if tp else None
    row = fluid.param_attr.ParamAttr(sharding=("tp", None)) if tp else None
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                x = fluid.layers.data(name="x", shape=[16],
                                      dtype="float32")
                y = fluid.layers.data(name="y", shape=[1],
                                      dtype="float32")
                h = fluid.layers.fc(x, size=8, act="tanh",
                                    param_attr=col)
                pred = fluid.layers.fc(h, size=1, param_attr=row)
                loss = fluid.layers.mean(
                    fluid.layers.square_error_cost(pred, y))
                fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        fluid.Executor(fluid.CPUPlace()).run(startup)
        pe = fluid.ParallelExecutor(
            use_tpu=False, loss_name=loss.name, main_program=main,
            scope=scope, num_trainers=num_trainers, trainer_id=trainer_id,
            mesh_axes=mesh_axes)
        losses = []
        for _ in range(steps):
            out, = pe.run(feed={x.name: x_local, y.name: y_local},
                          fetch_list=[loss])
            losses.append(float(np.asarray(out).ravel()[0]))
    return losses, len(jax.devices())


def baseline_worker(q):
    """Single-process 8-device SPMD run over the full batch."""
    try:
        q.put(("baseline",) + _build_and_train(1, 0))
    except Exception as e:  # surface the child's failure to the parent
        q.put(("baseline", "ERROR: %r" % e, 0))


def trainer_worker(i, q):
    """One of two jax.distributed processes; the PE joins the world
    itself through the PADDLE_TRAINER_ENDPOINTS env contract."""
    try:
        q.put(("trainer%d" % i,) + _build_and_train(2, i))
    except Exception as e:
        q.put(("trainer%d" % i, "ERROR: %r" % e, 0))


def trainer_worker_tp(i, q):
    """dp=2 x tp=4 over two processes: tensor-parallel parameter shards
    span hosts; each process contributes its addressable shards of the
    full (deterministically initialized) value."""
    try:
        q.put(("tp%d" % i,) + _build_and_train(
            2, i, mesh_axes={"dp": 2, "tp": 4}, tp=True))
    except Exception as e:
        q.put(("tp%d" % i, "ERROR: %r" % e, 0))


def baseline_worker_tp(q):
    try:
        q.put(("tpbase",) + _build_and_train(
            1, 0, mesh_axes={"dp": 2, "tp": 4}, tp=True))
    except Exception as e:
        q.put(("tpbase", "ERROR: %r" % e, 0))


def trainer_worker_reader(i, q, data_dir):
    """Program-level reader chain under num_trainers=2: each process
    reads ITS OWN recordio shard; the read batches must assemble as
    local rows (executor_impl._put reader tag), giving the same global
    loss both processes (and matching the arithmetic oracle)."""
    try:
        import jax

        import paddle_tpu.fluid as fluid
        from paddle_tpu.core.scope import Scope
        from paddle_tpu.distributed import collective

        collective.init_collective_env()

        main, startup = fluid.Program(), fluid.Program()
        scope = Scope()
        with fluid.scope_guard(scope):
            with fluid.program_guard(main, startup):
                with fluid.unique_name.guard():
                    reader = fluid.layers.io.open_recordio_file(
                        "%s/shard%d.recordio" % (data_dir, i),
                        shapes=[[-1, 4]], lod_levels=[0],
                        dtypes=["float32"])
                    reader = fluid.layers.io.batch(reader, batch_size=4)
                    x = fluid.layers.io.read_file(reader)
                    loss = fluid.layers.mean(x)
            fluid.Executor(fluid.CPUPlace()).run(startup)
            pe = fluid.ParallelExecutor(
                use_tpu=False, loss_name=loss.name, main_program=main,
                scope=scope, num_trainers=2, trainer_id=i)
            out, = pe.run(feed={}, fetch_list=[loss])
        q.put(("reader%d" % i,
               float(np.asarray(out).ravel()[0]), len(jax.devices())))
    except Exception as e:
        q.put(("reader%d" % i, "ERROR: %r" % e, 0))
