"""Optimizer op tests vs numpy reference updates (cf. reference
test_sgd_op.py, test_adam_op.py, test_momentum_op.py, ...)."""
import numpy as np

from op_test import OpTest

rng = np.random.RandomState(21)


def test_sgd():
    p = rng.randn(4, 3).astype(np.float32)
    g = rng.randn(4, 3).astype(np.float32)
    lr = np.array([0.1], np.float32)

    class T(OpTest):
        op_type = "sgd"
        inputs = {"Param": p, "Grad": g, "LearningRate": lr}
        outputs = {"ParamOut": p - 0.1 * g}

    T().check_output()


def test_momentum():
    p = rng.randn(4).astype(np.float32)
    g = rng.randn(4).astype(np.float32)
    v = rng.randn(4).astype(np.float32)
    lr = np.array([0.01], np.float32)
    mu = 0.9
    v_out = mu * v + g
    p_out = p - 0.01 * v_out

    class T(OpTest):
        op_type = "momentum"
        inputs = {"Param": p, "Grad": g, "Velocity": v, "LearningRate": lr}
        attrs = {"mu": mu, "use_nesterov": False}
        outputs = {"ParamOut": p_out, "VelocityOut": v_out}

    T().check_output()


def test_adam():
    p = rng.randn(6).astype(np.float32)
    g = rng.randn(6).astype(np.float32)
    m1 = rng.rand(6).astype(np.float32)
    m2 = rng.rand(6).astype(np.float32)
    b1, b2, eps = 0.9, 0.999, 1e-8
    b1p = np.array([b1 ** 3], np.float32)
    b2p = np.array([b2 ** 3], np.float32)
    lr = np.array([0.001], np.float32)
    m1o = b1 * m1 + (1 - b1) * g
    m2o = b2 * m2 + (1 - b2) * g * g
    lr_t = 0.001 * np.sqrt(1 - b2p[0]) / (1 - b1p[0])
    po = p - lr_t * m1o / (np.sqrt(m2o) + eps)

    class T(OpTest):
        op_type = "adam"
        inputs = {"Param": p, "Grad": g, "Moment1": m1, "Moment2": m2,
                  "Beta1Pow": b1p, "Beta2Pow": b2p, "LearningRate": lr}
        attrs = {"beta1": b1, "beta2": b2, "epsilon": eps}
        outputs = {"ParamOut": po, "Moment1Out": m1o, "Moment2Out": m2o,
                   "Beta1PowOut": b1p * b1, "Beta2PowOut": b2p * b2}

    T().check_output(atol=1e-5)


def test_adagrad():
    p = rng.randn(5).astype(np.float32)
    g = rng.randn(5).astype(np.float32)
    m = np.abs(rng.randn(5)).astype(np.float32)
    lr = np.array([0.01], np.float32)
    eps = 1e-6
    mo = m + g * g
    po = p - 0.01 * g / (np.sqrt(mo) + eps)

    class T(OpTest):
        op_type = "adagrad"
        inputs = {"Param": p, "Grad": g, "Moment": m, "LearningRate": lr}
        attrs = {"epsilon": eps}
        outputs = {"ParamOut": po, "MomentOut": mo}

    T().check_output()


def test_rmsprop():
    p = rng.randn(5).astype(np.float32)
    g = rng.randn(5).astype(np.float32)
    ms = np.abs(rng.randn(5)).astype(np.float32)
    mom = rng.randn(5).astype(np.float32)
    lr = np.array([0.01], np.float32)
    rho, eps, momentum = 0.9, 1e-10, 0.5
    ms_o = rho * ms + (1 - rho) * g * g
    mom_o = momentum * mom + 0.01 * g / np.sqrt(ms_o + eps)
    p_o = p - mom_o

    class T(OpTest):
        op_type = "rmsprop"
        inputs = {"Param": p, "Grad": g, "MeanSquare": ms, "Moment": mom,
                  "LearningRate": lr}
        attrs = {"decay": rho, "epsilon": eps, "momentum": momentum}
        outputs = {"ParamOut": p_o, "MeanSquareOut": ms_o,
                   "MomentOut": mom_o}

    T().check_output(atol=1e-5)


def test_optimizer_accumulators_e2e(prog_scope, exe):
    """Adam end-to-end: accumulators must update across runs (the executor's
    persistable write-back, reference test_optimizer.py)."""
    import paddle_tpu.fluid as fluid
    main, startup, scope = prog_scope
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    y = fluid.layers.fc(x, size=1)
    loss = fluid.layers.mean(y)
    opt = fluid.optimizer.Adam(learning_rate=0.01)
    opt.minimize(loss)
    exe.run(startup)
    feed = {"x": np.ones((2, 4), np.float32)}
    exe.run(main, feed=feed, fetch_list=[loss])
    accs = [v for v in scope.local_var_names() if "beta1_pow" in v]
    assert accs, "beta1 pow accumulator missing"
    val1 = float(np.asarray(scope.find_var(accs[0]))[0])
    exe.run(main, feed=feed, fetch_list=[loss])
    val2 = float(np.asarray(scope.find_var(accs[0]))[0])
    # init fill = beta1 (0.9); each step multiplies by beta1
    assert abs(val1 - 0.81) < 1e-6
    assert abs(val2 - 0.729) < 1e-6
