"""Sequence (LoD) ops on the padded representation vs numpy references.

Mirrors reference tests/unittests/test_lstm_op.py, test_gru_op.py,
test_seq_pool.py, test_sequence_softmax_op.py, test_sequence_erase_op.py,
test_edit_distance_op.py — adapted to padded batches + length vectors.
"""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.core.lod import LoDTensor


def _run_seq_op(prog_scope, exe, build, feeds, fetch):
    main, startup, scope = prog_scope
    outs = build()
    exe.run(startup)
    vals = exe.run(main, feed=feeds, fetch_list=fetch(outs))
    return vals


def _lod(data, lens, dtype=np.float32):
    """Build a LoDTensor from a padded [N,T,...] array + lengths."""
    parts = [data[i, :l] for i, l in enumerate(lens)]
    flat = np.concatenate(parts, 0).astype(dtype)
    offs = np.concatenate([[0], np.cumsum(lens)]).tolist()
    return LoDTensor(flat, [offs])


def test_sequence_pool_types(prog_scope, exe):
    main, startup, scope = prog_scope
    x = fluid.layers.data(name="x", shape=[4], lod_level=1,
                          dtype="float32")
    outs = {t: fluid.layers.sequence_pool(x, t)
            for t in ["sum", "average", "sqrt", "max", "last", "first"]}
    exe.run(startup)
    rng = np.random.RandomState(0)
    lens = [3, 5, 1]
    data = rng.randn(3, 8, 4).astype(np.float32)
    feed = {"x": _lod(data, lens)}
    names = list(outs)
    vals = exe.run(main, feed=feed, fetch_list=[outs[n] for n in names])
    for name, got in zip(names, vals):
        for i, l in enumerate(lens):
            seq = data[i, :l].astype(np.float64)
            want = {
                "sum": seq.sum(0), "average": seq.mean(0),
                "sqrt": seq.sum(0) / np.sqrt(l), "max": seq.max(0),
                "last": seq[-1], "first": seq[0],
            }[name]
            np.testing.assert_allclose(got[i], want, rtol=2e-5,
                                       atol=1e-5, err_msg=name)


def test_dynamic_lstm_vs_numpy(prog_scope, exe):
    main, startup, scope = prog_scope
    h = 8
    x = fluid.layers.data(name="x", shape=[4 * h], lod_level=1,
                          dtype="float32")
    hid, cell = fluid.layers.dynamic_lstm(x, size=4 * h,
                                          use_peepholes=False)
    exe.run(startup)
    rng = np.random.RandomState(1)
    lens = [5, 2, 7]
    data = rng.randn(3, 8, 4 * h).astype(np.float32) * 0.5
    feed = {"x": _lod(data, lens)}
    got_h, = exe.run(main, feed=feed, fetch_list=[hid])

    w = np.asarray(scope.find_var("lstm_0.w_0"))
    b = np.asarray(scope.find_var("lstm_0.b_0"))

    def sigmoid(v):
        return 1 / (1 + np.exp(-v))

    for i, l in enumerate(lens):
        hp = np.zeros(h)
        cp = np.zeros(h)
        for t in range(l):
            g = data[i, t] + b[0] + hp @ w
            cand, gi, gf, go = np.split(g, 4)
            ii, ff, oo = sigmoid(gi), sigmoid(gf), sigmoid(go)
            cp = ff * cp + ii * np.tanh(cand)
            hp = oo * np.tanh(cp)
            np.testing.assert_allclose(got_h[i, t], hp, rtol=2e-4,
                                       atol=2e-5)
        # padded positions are zero
        assert np.abs(got_h[i, l:]).max() == 0.0


def test_dynamic_gru_vs_numpy(prog_scope, exe):
    main, startup, scope = prog_scope
    d = 6
    x = fluid.layers.data(name="x", shape=[3 * d], lod_level=1,
                          dtype="float32")
    hid = fluid.layers.dynamic_gru(x, size=d)
    exe.run(startup)
    rng = np.random.RandomState(2)
    lens = [4, 6]
    data = rng.randn(2, 8, 3 * d).astype(np.float32) * 0.5
    got_h, = exe.run(main, feed={"x": _lod(data, lens)}, fetch_list=[hid])

    w = np.asarray(scope.find_var("gru_0.w_0"))
    b = np.asarray(scope.find_var("gru_0.b_0"))

    def sigmoid(v):
        return 1 / (1 + np.exp(-v))

    for i, l in enumerate(lens):
        hp = np.zeros(d)
        for t in range(l):
            xt = data[i, t] + b[0]
            xu, xr, xc = np.split(xt, 3)
            u = sigmoid(xu + hp @ w[:, :d])
            r = sigmoid(xr + hp @ w[:, d: 2 * d])
            cand = np.tanh(xc + (r * hp) @ w[:, 2 * d:])
            hp = (1 - u) * hp + u * cand
            np.testing.assert_allclose(got_h[i, t], hp, rtol=2e-4,
                                       atol=2e-5)


def test_sequence_softmax_masks_padding(prog_scope, exe):
    main, startup, scope = prog_scope
    x = fluid.layers.data(name="x", shape=[1], lod_level=1,
                          dtype="float32")
    out = fluid.layers.sequence_softmax(x)
    exe.run(startup)
    lens = [3, 6]
    data = np.random.RandomState(3).randn(2, 8, 1).astype(np.float32)
    got, = exe.run(main, feed={"x": _lod(data, lens)}, fetch_list=[out])
    for i, l in enumerate(lens):
        e = np.exp(data[i, :l, 0] - data[i, :l, 0].max())
        np.testing.assert_allclose(got[i, :l, 0], e / e.sum(), rtol=1e-5,
                                   atol=1e-6)
        assert np.abs(got[i, l:]).max() == 0.0


def test_sequence_expand(prog_scope, exe):
    main, startup, scope = prog_scope
    x = fluid.layers.data(name="x", shape=[3], dtype="float32")
    y = fluid.layers.data(name="y", shape=[2], lod_level=1,
                          dtype="float32")
    out = fluid.layers.sequence_expand(x, y)
    exe.run(startup)
    lens = [2, 4]
    ydata = np.zeros((2, 8, 2), np.float32)
    xdata = np.random.RandomState(4).randn(2, 3).astype(np.float32)
    got, = exe.run(main, feed={"x": xdata, "y": _lod(ydata, lens)},
                   fetch_list=[out])
    for i, l in enumerate(lens):
        for t in range(l):
            np.testing.assert_allclose(got[i, t], xdata[i], rtol=1e-6)
        assert np.abs(got[i, l:]).max() == 0.0


def test_sequence_erase(prog_scope, exe):
    main, startup, scope = prog_scope
    x = fluid.layers.data(name="x", shape=[1], lod_level=1, dtype="int64")
    out = fluid.layers.sequence_erase(x, tokens=[2, 5])
    exe.run(startup)
    lens = [6, 4]
    data = np.array([[1, 2, 3, 2, 5, 4, 0, 0],
                     [2, 2, 7, 5, 0, 0, 0, 0]])[..., None]
    got, = exe.run(main, feed={"x": _lod(data, lens, np.int64)},
                   fetch_list=[out])
    np.testing.assert_array_equal(got[0, :3, 0], [1, 3, 4])
    np.testing.assert_array_equal(got[1, :1, 0], [7])
    assert np.abs(got[0, 3:]).max() == 0 and np.abs(got[1, 1:]).max() == 0


def test_edit_distance(prog_scope, exe):
    main, startup, scope = prog_scope
    hyp = fluid.layers.data(name="hyp", shape=[1], lod_level=1,
                            dtype="int64")
    ref = fluid.layers.data(name="ref", shape=[1], lod_level=1,
                            dtype="int64")
    dist, seq_num = fluid.layers.edit_distance(hyp, ref,
                                               normalized=False)
    exe.run(startup)

    def lev(a, b):
        dp = np.arange(len(b) + 1, dtype=float)
        for i, ca in enumerate(a):
            prev = dp.copy()
            dp[0] = i + 1
            for j, cb in enumerate(b):
                dp[j + 1] = min(prev[j + 1] + 1, dp[j] + 1,
                                prev[j] + (ca != cb))
        return dp[-1]

    hyps = [[1, 2, 3], [4, 5, 6, 7, 8]]
    refs = [[1, 3, 3, 4], [4, 5, 8]]
    hl = [len(s) for s in hyps]
    rl = [len(s) for s in refs]
    hp = np.zeros((2, 8, 1), np.int64)
    rp = np.zeros((2, 8, 1), np.int64)
    for i, s in enumerate(hyps):
        hp[i, :len(s), 0] = s
    for i, s in enumerate(refs):
        rp[i, :len(s), 0] = s
    got, = exe.run(main, feed={"hyp": _lod(hp, hl, np.int64),
                               "ref": _lod(rp, rl, np.int64)},
                   fetch_list=[dist])
    for i in range(2):
        assert got[i, 0] == lev(hyps[i], refs[i]), (i, got[i, 0])


def test_lstm_sentiment_e2e(prog_scope, exe):
    """Variable-length classification converges (grad flows through the
    masked scan) — the stacked_dynamic_lstm pattern."""
    main, startup, scope = prog_scope
    words = fluid.layers.data(name="words", shape=[1], lod_level=1,
                              dtype="int64")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    emb = fluid.layers.embedding(words, size=[100, 16])
    proj = fluid.layers.fc(emb, size=64, act=None)
    hidden, _ = fluid.layers.dynamic_lstm(proj, size=64,
                                          use_peepholes=False)
    last = fluid.layers.sequence_pool(hidden, "max")
    logit = fluid.layers.fc(last, size=2, act="softmax")
    loss = fluid.layers.mean(fluid.layers.cross_entropy(logit, label))
    fluid.optimizer.Adam(5e-3).minimize(loss)
    exe.run(startup)
    feeder = fluid.DataFeeder([words, label], program=main)
    rng = np.random.RandomState(0)
    ls = []
    for _ in range(40):
        batch = []
        for _ in range(16):
            y = rng.randint(0, 2)
            L = rng.randint(3, 12)
            toks = rng.randint(0, 50, L) + (50 if y else 0)
            batch.append(([int(t) for t in toks], [y]))
        l, = exe.run(main, feed=feeder.feed(batch), fetch_list=[loss])
        ls.append(float(l[0]))
    assert ls[-1] < 0.3, (ls[0], ls[-1])


def test_level2_lod_feed_pads_correctly():
    """data(lod_level=2) round trip: nested padding + both length
    sidecars reach the device function (reference lod_tensor.h:58
    hierarchical LoD; previously level-2 feeds mispadded)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.core.lod import LoDTensor

    # 2 sentences: [[a(2 tok), b(3 tok)], [c(1 tok)]], token dim 2
    seqs = [np.arange(4, dtype=np.float32).reshape(2, 2),
            np.arange(6, dtype=np.float32).reshape(3, 2) + 10,
            np.arange(2, dtype=np.float32).reshape(1, 2) + 100]
    flat = np.concatenate(seqs, axis=0)
    lt = LoDTensor(flat, [[0, 2, 3], [0, 2, 5, 6]])

    padded, outer, inner = lt.to_padded_2level()
    assert padded.shape == (2, 2, 3, 2)
    np.testing.assert_array_equal(outer, [2, 1])
    np.testing.assert_array_equal(inner, [[2, 3], [1, 0]])
    np.testing.assert_allclose(padded[0, 0, :2], seqs[0])
    np.testing.assert_allclose(padded[0, 1, :3], seqs[1])
    np.testing.assert_allclose(padded[1, 0, :1], seqs[2])
    np.testing.assert_allclose(padded[1, 1], 0.0)
    back = LoDTensor.from_padded_2level(padded, outer, inner)
    np.testing.assert_allclose(np.asarray(back.data), flat)
    assert back.lod == lt.lod

    # end to end: feed through a program; the reduction sees only the
    # real tokens when masked by the sidecars
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                x = fluid.layers.data(name="x", shape=[2, 3, 2],
                                      dtype="float32", lod_level=2,
                                      append_batch_size=True)
                total = fluid.layers.reduce_sum(x)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        got, = exe.run(main, feed={"x": lt}, fetch_list=[total])
    np.testing.assert_allclose(float(np.ravel(got)[0]), flat.sum(),
                               rtol=1e-6)


def _lod2(seqs_nested, width):
    """LoDTensor from nested [doc][sent] lists of [W_i, width] arrays."""
    outer = [0]
    inner = [0]
    flat = []
    for doc in seqs_nested:
        outer.append(outer[-1] + len(doc))
        for sent in doc:
            inner.append(inner[-1] + len(sent))
            flat.append(np.asarray(sent, np.float32).reshape(-1, width))
    return LoDTensor(np.concatenate(flat, 0), [outer, inner])


def test_level2_sequence_pool_finest_level(prog_scope, exe):
    """sequence_pool over level-2 LoD pools each INNER sub-sequence
    (reference finest-level semantics, lod_tensor.h:58-110 +
    sequence_pool_op.cc).  AVERAGE makes the answer CHANGE if inner
    padding leaks into the divisor; pinned against a host-side LoD
    oracle."""
    rng = np.random.RandomState(0)
    # ragged docs: [2 sents (3, 5 toks)], [1 sent (2 toks)] — widths
    # force real inner padding inside the [N, S, W, D] bridge
    docs = [[rng.randn(3, 4), rng.randn(5, 4)], [rng.randn(2, 4)]]
    lt = _lod2(docs, 4)

    main, startup, scope = prog_scope
    x = fluid.layers.data(name="x", shape=[4], dtype="float32",
                          lod_level=2)
    pooled = fluid.layers.sequence_pool(x, pool_type="average")
    # second hop: outer-level pool of the per-sentence vectors -> [N, D]
    doc_vec = fluid.layers.sequence_pool(pooled, pool_type="sum")
    exe.run(startup)
    got_pool, got_doc = exe.run(main, feed={"x": lt},
                                fetch_list=[pooled, doc_vec])

    # host oracle straight off the raw LoD
    sent_means = [[np.mean(s, axis=0) for s in doc] for doc in docs]
    got_pool = np.asarray(got_pool)
    for i, doc in enumerate(sent_means):
        for j, v in enumerate(doc):
            np.testing.assert_allclose(got_pool[i, j], v, rtol=1e-5,
                                       atol=1e-6)
    doc_sums = np.stack([np.sum(np.stack(d, 0), 0) if d else 0
                         for d in sent_means])
    np.testing.assert_allclose(np.asarray(got_doc), doc_sums, rtol=1e-5,
                               atol=1e-6)


def test_level2_sequence_softmax_finest_level(prog_scope, exe):
    """sequence_softmax normalizes within each inner sub-sequence —
    pinned vs a host-side oracle on ragged level-2 data."""
    rng = np.random.RandomState(1)
    docs = [[rng.randn(3, 1), rng.randn(6, 1)], [rng.randn(2, 1)]]
    lt = _lod2(docs, 1)

    main, startup, scope = prog_scope
    x = fluid.layers.data(name="x", shape=[1], dtype="float32",
                          lod_level=2)
    sm = fluid.layers.sequence_softmax(x)
    exe.run(startup)
    got, = exe.run(main, feed={"x": lt}, fetch_list=[sm])
    got = np.asarray(got)
    for i, doc in enumerate(docs):
        for j, sent in enumerate(doc):
            v = sent[:, 0]
            e = np.exp(v - v.max())
            np.testing.assert_allclose(got[i, j, :len(v), 0],
                                       e / e.sum(), rtol=1e-5,
                                       atol=1e-6)
            # padding rows carry zero probability mass
            np.testing.assert_allclose(got[i, j, len(v):, 0], 0,
                                       atol=1e-7)
    # all-padding sentences (outer padding) contribute nothing
    np.testing.assert_allclose(got[1, 1:], 0, atol=1e-7)


def test_level2_sequence_conv_window_stays_inside_subseq(prog_scope, exe):
    """sequence_conv over level-2 LoD: the context window never crosses
    an inner sub-sequence boundary (finest-level semantics,
    sequence_conv_op.cc) — pinned against a host-side per-sentence
    conv oracle whose answer CHANGES if windows leak across sentences
    or into padding."""
    rng = np.random.RandomState(2)
    d, f = 3, 2
    docs = [[rng.randn(4, d), rng.randn(6, d)], [rng.randn(3, d)]]
    lt = _lod2(docs, d)
    filt = rng.randn(3 * d, f).astype(np.float32)

    main, startup, scope = prog_scope
    x = fluid.layers.data(name="x", shape=[d], dtype="float32",
                          lod_level=2)
    conv = fluid.layers.sequence_conv(
        x, num_filters=f, filter_size=3,
        param_attr=fluid.ParamAttr(name="seqconv_w"), bias_attr=False)
    exe.run(startup)
    scope.set("seqconv_w", filt)
    got, = exe.run(main, feed={"x": lt}, fetch_list=[conv])
    got = np.asarray(got)

    def oracle(sent):
        L = len(sent)
        out = np.zeros((L, f), np.float32)
        for t in range(L):
            col = []
            for k in (-1, 0, 1):  # contextStart=-1, len 3
                col.append(sent[t + k] if 0 <= t + k < L
                           else np.zeros(d, np.float32))
            out[t] = np.concatenate(col) @ filt
        return out

    for i, doc in enumerate(docs):
        for j, sent in enumerate(doc):
            np.testing.assert_allclose(
                got[i, j, :len(sent)], oracle(sent.astype(np.float32)),
                rtol=1e-4, atol=1e-5)


def _lod3(docs, width):
    """LoDTensor from [doc][para][sent] nesting of [W_i, width] arrays
    (level-3 LoD: three offset tables)."""
    l0, l1, l2 = [0], [0], [0]
    flat = []
    for doc in docs:
        l0.append(l0[-1] + len(doc))
        for para in doc:
            l1.append(l1[-1] + len(para))
            for sent in para:
                l2.append(l2[-1] + len(sent))
                flat.append(np.asarray(sent, np.float32).reshape(-1,
                                                                 width))
    return LoDTensor(np.concatenate(flat, 0), [l0, l1, l2])


def test_level3_sequence_pool_chain_vs_host_oracle(prog_scope, exe):
    """Arbitrary-depth LoD (round-3 VERDICT missing #2): a level-3 feed
    pools at the FINEST level, then each subsequent pool consumes one
    level — [N,S1,S2,W,D] -> [N,S1,S2,D] -> [N,S1,D] -> [N,D], pinned
    against a host oracle computed straight off the ragged lists.
    AVERAGE at the finest hop makes the answer change if padding leaks
    into any divisor (reference lod_tensor.h:58 depth-unbounded LoD)."""
    rng = np.random.RandomState(3)
    d = 4
    docs = [
        [  # doc 0: 2 paragraphs
            [rng.randn(3, d), rng.randn(5, d)],          # para: 2 sents
            [rng.randn(2, d)],                           # para: 1 sent
        ],
        [  # doc 1: 1 paragraph of 3 sentences
            [rng.randn(4, d), rng.randn(1, d), rng.randn(6, d)],
        ],
    ]
    lt = _lod3(docs, d)

    main, startup, scope = prog_scope
    x = fluid.layers.data(name="x", shape=[d], dtype="float32",
                          lod_level=3)
    sent_vec = fluid.layers.sequence_pool(x, pool_type="average")
    para_vec = fluid.layers.sequence_pool(sent_vec, pool_type="sum")
    doc_vec = fluid.layers.sequence_pool(para_vec, pool_type="max")
    exe.run(startup)
    got_s, got_p, got_d = exe.run(
        main, feed={"x": lt}, fetch_list=[sent_vec, para_vec, doc_vec])
    got_s, got_p, got_d = map(np.asarray, (got_s, got_p, got_d))

    sent_means = [[[np.mean(s, 0) for s in para] for para in doc]
                  for doc in docs]
    for i, doc in enumerate(sent_means):
        for j, para in enumerate(doc):
            for k, v in enumerate(para):
                np.testing.assert_allclose(got_s[i, j, k], v,
                                           rtol=1e-5, atol=1e-6)
    para_sums = [[np.sum(np.stack(p, 0), 0) for p in doc]
                 for doc in sent_means]
    for i, doc in enumerate(para_sums):
        for j, v in enumerate(doc):
            np.testing.assert_allclose(got_p[i, j], v,
                                       rtol=1e-5, atol=1e-6)
    doc_maxes = np.stack([np.max(np.stack(doc, 0), 0)
                          for doc in para_sums])
    np.testing.assert_allclose(got_d, doc_maxes, rtol=1e-5, atol=1e-6)


def test_klevel_pad_roundtrip():
    """to_padded_klevel/from_padded_klevel invert each other on a
    ragged level-3 tensor."""
    rng = np.random.RandomState(4)
    docs = [
        [[rng.randn(2, 3)], [rng.randn(4, 3), rng.randn(1, 3)]],
        [[rng.randn(3, 3)]],
    ]
    lt = _lod3(docs, 3)
    padded, lens = lt.to_padded_klevel()
    assert padded.ndim == 5  # [N, S1, S2, W, D]
    assert [tuple(np.shape(l)) for l in lens] == [
        (2,), (2, 2), (2, 2, 2)]
    back = LoDTensor.from_padded_klevel(padded, lens)
    assert back.lod == lt.lod
    np.testing.assert_allclose(np.asarray(back.data),
                               np.asarray(lt.data), rtol=1e-6)
    # all-empty batch: reconstructed data keeps the FEATURE rank only
    empty = LoDTensor.from_padded_klevel(
        np.zeros_like(padded), [np.zeros_like(l) for l in lens])
    assert empty.data.shape == (0, 3)
    assert empty.lod[0] == [0, 0, 0]  # N=2 empty docs


def test_kmax_seq_score_positions(prog_scope, exe):
    """Top-k positions per ragged sequence, -1 padded (reference
    kmax_seq_score_layer)."""
    main, startup, scope = prog_scope
    x = fluid.layers.data(name="km_x", shape=[1], lod_level=1,
                          dtype="float32")
    out = fluid.layers.kmax_seq_score(x, beam_size=3)
    exe.run(startup)
    rows = np.zeros((2, 5, 1), np.float32)
    rows[0, :5, 0] = [0.1, 0.9, 0.3, 0.8, 0.2]
    rows[1, :2, 0] = [0.5, 0.7]
    got, = exe.run(main, feed={"km_x": _lod(rows, [5, 2])},
                   fetch_list=[out])
    got = np.asarray(got)
    assert got[0].tolist() == [1, 3, 2]
    assert got[1].tolist() == [1, 0, -1]


def test_sub_nested_seq_selects_inner_rows(prog_scope, exe):
    """Level-2 selection by per-sample index lists (reference
    SubNestedSequenceLayer): output keeps the chosen inner
    sub-sequences, pooling over it sees only those rows."""
    main, startup, scope = prog_scope
    x = fluid.layers.data(name="sn_x", shape=[2], lod_level=2,
                          dtype="float32")
    sel = fluid.layers.data(name="sn_i", shape=[1], lod_level=1,
                            dtype="int64")
    sub = fluid.layers.sub_nested_seq(
        x, fluid.layers.cast(sel, "int32"))
    pooled = fluid.layers.sequence_pool(sub, pool_type="SUM")
    exe.run(startup)
    from paddle_tpu.core.lod import LoDTensor
    # sample 0: inner seqs A=[[1,1],[2,2]], B=[[10,10]];
    # sample 1: C=[[3,3],[4,4]], D=[[5,5]], E=[[6,6]]
    data = np.asarray([[1, 1], [2, 2], [10, 10], [3, 3], [4, 4],
                       [5, 5], [6, 6]], np.float32)
    xfeed = LoDTensor(data, [[0, 2, 5], [0, 2, 3, 5, 6, 7]])
    # sample 0 selects inner seq 1 then 0; sample 1 selects inner 2
    sfeed = LoDTensor(np.asarray([[1], [0], [2]], np.int64),
                      [[0, 2, 3]])
    got, = exe.run(main, feed={"sn_x": xfeed, "sn_i": sfeed},
                   fetch_list=[pooled])
    got = np.asarray(got)
    # sample 0 selected: inner1 = [10,10] (len 1), inner0 = rows
    # [1,1]+[2,2] summed = [3,3]; sample 1 selected inner2 = [6,6]
    np.testing.assert_allclose(got[0, 0], [10, 10], atol=1e-5)
    np.testing.assert_allclose(got[0, 1], [3, 3], atol=1e-5)
    np.testing.assert_allclose(got[1, 0], [6, 6], atol=1e-5)
