"""XPlane trace reader (paddle_tpu/utils/xplane.py): minimal protobuf
wire parsing validated against a hand-encoded XSpace."""
import os

import pytest

from paddle_tpu.utils import xplane


def _varint(x):
    out = b""
    while True:
        b = x & 0x7F
        x >>= 7
        if x:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _ld(fno, payload):  # length-delimited field
    return _varint((fno << 3) | 2) + _varint(len(payload)) + payload


def _vi(fno, v):  # varint field
    return _varint(fno << 3) + _varint(v)


def _event(meta_id, dur_ps):
    return _vi(1, meta_id) + _vi(3, dur_ps)


def _line(name, events):
    return _ld(2, name.encode()) + b"".join(_ld(4, e) for e in events)


def _md_entry(mid, name):
    inner = _vi(1, mid) + _ld(2, name.encode())
    return _vi(1, mid) + _ld(2, inner)


def _plane(name, lines, metadata):
    return (_ld(2, name.encode())
            + b"".join(_ld(3, ln) for ln in lines)
            + b"".join(_ld(4, _md_entry(k, v))
                       for k, v in metadata.items()))


def _xspace(planes):
    return b"".join(_ld(1, p) for p in planes)


@pytest.fixture
def trace_file(tmp_path):
    md = {1: "%fusion.1 = f32[8]{0} fusion(...)",
          2: "%fusion.2 = f32[8]{0} fusion(...)",
          3: "%convolution"}
    ops = _line("XLA Ops", [_event(1, 1000), _event(2, 500),
                            _event(1, 250), _event(3, 2000)])
    steps = _line("Steps", [_event(1, 4000)])
    dev = _plane("/device:TPU:0", [steps, ops], md)
    host = _plane("/host:CPU", [_line("python", [_event(9, 7)])], {9: "py"})
    run_dir = tmp_path / "plugins" / "profile" / "run1"
    os.makedirs(run_dir)
    path = run_dir / "host.xplane.pb"
    path.write_bytes(_xspace([dev, host]))
    return str(tmp_path)


def test_read_xspace_structure(trace_file):
    planes = xplane.read_xspace(trace_file)
    names = [p["name"] for p in planes]
    assert names == ["/device:TPU:0", "/host:CPU"]
    dev = planes[0]
    assert dev["event_metadata"][3] == "%convolution"
    lines = dict(dev["lines"])
    assert lines["XLA Ops"] == [(1, 1000), (2, 500), (1, 250), (3, 2000)]


def test_op_totals_folds_suffixes(trace_file):
    agg = xplane.op_totals(trace_file)
    # %fusion.1 + %fusion.2 fold into one family; names cut at " = "
    assert agg == {"%fusion": 1750, "%convolution": 2000}
    raw = xplane.op_totals(trace_file, strip_suffix=False)
    assert raw == {"%fusion.1": 1250, "%fusion.2": 500,
                   "%convolution": 2000}


def test_op_totals_missing_plane(trace_file):
    assert xplane.op_totals(trace_file, plane_re="no-such-plane") == {}


def test_op_totals_sums_all_device_planes(tmp_path):
    """Multi-chip traces must aggregate EVERY matching plane, and a dir
    read must include every host's file in the newest run dir."""
    md = {1: "%fusion"}
    planes0 = [_plane("/device:TPU:0",
                      [_line("XLA Ops", [_event(1, 100)])], md)]
    planes1 = [_plane("/device:TPU:1",
                      [_line("XLA Ops", [_event(1, 40)])], md)]
    run_dir = tmp_path / "plugins" / "profile" / "run1"
    os.makedirs(run_dir)
    (run_dir / "hostA.xplane.pb").write_bytes(_xspace(planes0))
    (run_dir / "hostB.xplane.pb").write_bytes(_xspace(planes1))
    assert xplane.op_totals(str(tmp_path)) == {"%fusion": 140}


def test_read_xspace_missing_dir(tmp_path):
    with pytest.raises(FileNotFoundError):
        xplane.read_xspace(str(tmp_path))


def test_truncated_file_raises(tmp_path):
    md = {1: "%fusion"}
    good = _xspace([_plane("/device:TPU:0",
                           [_line("XLA Ops", [_event(1, 100)])], md)])
    run_dir = tmp_path / "plugins" / "profile" / "r"
    os.makedirs(run_dir)
    (run_dir / "t.xplane.pb").write_bytes(good[:-3])  # cut mid-field
    with pytest.raises(ValueError, match="truncated"):
        xplane.read_xspace(str(tmp_path))
