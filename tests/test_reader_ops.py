"""Program-level reader-op chain (reference operators/reader/* +
layers/io.py open_recordio_file/shuffle/batch/double_buffer/read_file):
records -> decorated chain -> read op feeding a compiled train block,
EOF + reset semantics."""
import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid


def _write_samples(path, n=50, seed=0):
    rng = np.random.RandomState(seed)

    def reader():
        for _ in range(n):
            img = rng.rand(784).astype(np.float32)
            label = np.asarray(
                [rng.randint(0, 10)], np.int64)
            yield (img, label)

    return fluid.recordio_writer.convert_reader_to_recordio_file(
        path, reader)


def test_recordio_read_train_eof_reset(prog_scope, exe, tmp_path):
    path = os.path.join(str(tmp_path), "mnist.recordio")
    assert _write_samples(path, n=50) == 50

    main, startup, scope = prog_scope
    reader = fluid.layers.io.open_recordio_file(
        path, shapes=[[-1, 784], [-1, 1]], lod_levels=[0, 0],
        dtypes=["float32", "int64"])
    reader = fluid.layers.io.shuffle(reader, buffer_size=25)
    reader = fluid.layers.io.batch(reader, batch_size=10)
    reader = fluid.layers.io.double_buffer(reader)
    img, label = fluid.layers.io.read_file(reader)
    fc = fluid.layers.fc(img, size=10, act="softmax")
    loss = fluid.layers.mean(
        fluid.layers.cross_entropy(input=fc, label=label))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe.run(startup)

    # 50 samples / batch 10 -> exactly 5 reads, then EOF
    losses = []
    for _ in range(5):
        l, = exe.run(main, fetch_list=[loss])
        losses.append(float(np.asarray(l).ravel()[0]))
    assert np.isfinite(losses).all()
    with pytest.raises(fluid.core.EOFException):
        exe.run(main, fetch_list=[loss])

    # reset rewinds the whole chain for another epoch
    reader.reset()
    more = []
    for _ in range(5):
        l, = exe.run(main, fetch_list=[loss])
        more.append(float(np.asarray(l).ravel()[0]))
    # second epoch sees the same (shuffled) data and keeps training
    assert np.mean(more) < np.mean(losses) + 0.5


def test_pass_num_multiplies_epochs(prog_scope, exe, tmp_path):
    path = os.path.join(str(tmp_path), "p2.recordio")
    _write_samples(path, n=20, seed=5)
    main, startup, scope = prog_scope
    reader = fluid.layers.io.open_recordio_file(
        path, shapes=[[-1, 784], [-1, 1]], lod_levels=[0, 0],
        dtypes=["float32", "int64"], pass_num=2)
    reader = fluid.layers.io.batch(reader, batch_size=10)
    img, label = fluid.layers.io.read_file(reader)
    out = fluid.layers.reduce_sum(img)
    exe.run(startup)
    for _ in range(4):  # 20 samples x 2 passes / batch 10
        exe.run(main, fetch_list=[out])
    with pytest.raises(fluid.core.EOFException):
        exe.run(main, fetch_list=[out])


def test_double_buffer_mid_epoch_reset(prog_scope, exe, tmp_path):
    """reset() before EOF must kill the prefetch thread and restart the
    chain cleanly — full epochs must still deliver every batch."""
    path = os.path.join(str(tmp_path), "mid.recordio")
    _write_samples(path, n=40, seed=7)
    main, startup, scope = prog_scope
    reader = fluid.layers.io.open_recordio_file(
        path, shapes=[[-1, 784], [-1, 1]], lod_levels=[0, 0],
        dtypes=["float32", "int64"])
    reader = fluid.layers.io.batch(reader, batch_size=10)
    reader = fluid.layers.io.double_buffer(reader)
    img, label = fluid.layers.io.read_file(reader)
    out = fluid.layers.reduce_sum(img)
    exe.run(startup)
    exe.run(main, fetch_list=[out])  # one batch, then bail mid-epoch
    reader.reset()
    for _ in range(4):  # a clean full epoch follows
        exe.run(main, fetch_list=[out])
    with pytest.raises(fluid.core.EOFException):
        exe.run(main, fetch_list=[out])


def test_open_files_concatenates(prog_scope, exe, tmp_path):
    p1 = os.path.join(str(tmp_path), "a.recordio")
    p2 = os.path.join(str(tmp_path), "b.recordio")
    _write_samples(p1, n=15, seed=1)
    _write_samples(p2, n=15, seed=2)
    main, startup, scope = prog_scope
    reader = fluid.layers.io.open_files(
        [p1, p2], shapes=[[-1, 784], [-1, 1]], lod_levels=[0, 0],
        dtypes=["float32", "int64"])
    reader = fluid.layers.io.batch(reader, batch_size=10)
    img, label = fluid.layers.io.read_file(reader)
    out = fluid.layers.reduce_sum(img)
    exe.run(startup)
    for _ in range(3):  # 30 samples across both files / batch 10
        exe.run(main, fetch_list=[out])
    with pytest.raises(fluid.core.EOFException):
        exe.run(main, fetch_list=[out])
    reader.reset()
    exe.run(main, fetch_list=[out])  # rewound across the file list


def test_random_data_generator(prog_scope, exe):
    main, startup, scope = prog_scope
    reader = fluid.layers.io.random_data_generator(
        low=-1.0, high=1.0, shapes=[[-1, 8], [-1, 3]], lod_levels=[0, 0])
    reader = fluid.layers.io.batch(reader, batch_size=4)
    a, b = fluid.layers.io.read_file(reader)
    out = fluid.layers.reduce_max(a)
    exe.run(startup)
    v, = exe.run(main, fetch_list=[out])
    assert -1.0 <= float(np.asarray(v).ravel()[0]) <= 1.0


def test_batch_reader_drops_partial(prog_scope, exe, tmp_path):
    path = os.path.join(str(tmp_path), "odd.recordio")
    _write_samples(path, n=25, seed=3)
    main, startup, scope = prog_scope
    reader = fluid.layers.io.open_recordio_file(
        path, shapes=[[-1, 784], [-1, 1]], lod_levels=[0, 0],
        dtypes=["float32", "int64"])
    reader = fluid.layers.io.batch(reader, batch_size=10)
    img, label = fluid.layers.io.read_file(reader)
    out = fluid.layers.reduce_sum(img)
    exe.run(startup)
    for _ in range(2):  # 25 -> two full batches, partial third dropped
        exe.run(main, fetch_list=[out])
    with pytest.raises(fluid.core.EOFException):
        exe.run(main, fetch_list=[out])


def test_multi_pass_reader(prog_scope, exe, tmp_path):
    """create_multi_pass_reader: N epochs appear as one stream, then
    EOF; reset restarts the pass count (reference
    create_multi_pass_reader_op.cc)."""
    path = os.path.join(str(tmp_path), "mp.recordio")
    _write_samples(path, n=20, seed=3)
    main, startup, scope = prog_scope
    reader = fluid.layers.io.open_recordio_file(
        path, shapes=[[-1, 784], [-1, 1]], lod_levels=[0, 0],
        dtypes=["float32", "int64"])
    reader = fluid.layers.io.batch(reader, batch_size=10)
    reader = fluid.layers.io.multi_pass(reader, pass_num=3)
    img, label = fluid.layers.io.read_file(reader)
    out = fluid.layers.reduce_sum(img)
    exe.run(startup)
    for _ in range(6):  # 20/10 = 2 batches x 3 passes
        exe.run(main, fetch_list=[out])
    with pytest.raises(fluid.core.EOFException):
        exe.run(main, fetch_list=[out])
    reader.reset()
    for _ in range(6):
        exe.run(main, fetch_list=[out])


def test_threaded_reader(prog_scope, exe, tmp_path):
    """create_threaded_reader: prefetching front yields every batch
    exactly once, EOF propagates, reset rewinds (reference
    create_threaded_reader_op.cc)."""
    path = os.path.join(str(tmp_path), "th.recordio")
    _write_samples(path, n=30, seed=4)
    main, startup, scope = prog_scope
    reader = fluid.layers.io.open_recordio_file(
        path, shapes=[[-1, 784], [-1, 1]], lod_levels=[0, 0],
        dtypes=["float32", "int64"])
    reader = fluid.layers.io.batch(reader, batch_size=10)
    reader = fluid.layers.io.threaded(reader, capacity=2)
    img, label = fluid.layers.io.read_file(reader)
    out = fluid.layers.reduce_sum(label)
    exe.run(startup)
    seen = []
    for _ in range(3):
        s, = exe.run(main, fetch_list=[out])
        seen.append(float(np.ravel(s)[0]))
    with pytest.raises(fluid.core.EOFException):
        exe.run(main, fetch_list=[out])
    reader.reset()
    again = []
    for _ in range(3):
        s, = exe.run(main, fetch_list=[out])
        again.append(float(np.ravel(s)[0]))
    assert sorted(seen) == sorted(again)  # same data both epochs


def test_open_files_thread_pool(prog_scope, exe, tmp_path):
    """open_files(thread_num>1): worker-pool scan covers every sample
    of every file exactly once per epoch (order across files free)."""
    paths = []
    for i in range(3):
        p = os.path.join(str(tmp_path), "f%d.recordio" % i)
        _write_samples(p, n=10, seed=10 + i)
        paths.append(p)
    main, startup, scope = prog_scope
    reader = fluid.layers.io.open_files(
        paths, shapes=[[-1, 784], [-1, 1]], lod_levels=[0, 0],
        dtypes=["float32", "int64"], thread_num=3)
    reader = fluid.layers.io.batch(reader, batch_size=10)
    img, label = fluid.layers.io.read_file(reader)
    out = fluid.layers.reduce_sum(img)
    exe.run(startup)
    total = 0.0
    for _ in range(3):  # 30 samples / batch 10
        s, = exe.run(main, fetch_list=[out])
        total += float(np.ravel(s)[0])
    with pytest.raises(fluid.core.EOFException):
        exe.run(main, fetch_list=[out])
    # epoch sum is order-independent: compare against a sequential scan
    reader2_total = 0.0
    from paddle_tpu import recordio
    import pickle
    for p in paths:
        for rec in recordio.read_records(p):
            sample = pickle.loads(rec)
            vals = (list(sample.values()) if isinstance(sample, dict)
                    else sample)
            reader2_total += float(np.sum(np.asarray(vals[0])))
    np.testing.assert_allclose(total, reader2_total, rtol=1e-4)
    reader.reset()
    exe.run(main, fetch_list=[out])  # pool restarts after reset


def test_custom_reader_preprocessor(prog_scope, exe, tmp_path):
    """Preprocessor sub-block transforms every batch in-stream
    (reference Preprocessor:587 + create_custom_reader_op.cc): images
    are scaled and recentered by fluid ops BEFORE read_file pops them."""
    path = os.path.join(str(tmp_path), "pp.recordio")
    _write_samples(path, n=20, seed=5)
    main, startup, scope = prog_scope
    reader = fluid.layers.io.open_recordio_file(
        path, shapes=[[-1, 784], [-1, 1]], lod_levels=[0, 0],
        dtypes=["float32", "int64"])
    reader = fluid.layers.io.batch(reader, batch_size=10)
    p = fluid.layers.io.Preprocessor(reader)
    with p.block():
        img, lbl = p.inputs()
        scaled = fluid.layers.scale(img, scale=2.0, bias=-1.0)
        p.outputs(scaled, lbl)
    reader = p()
    img_v, lbl_v = fluid.layers.io.read_file(reader)
    out = fluid.layers.reduce_mean(img_v)
    exe.run(startup)
    got, = exe.run(main, fetch_list=[out])

    # oracle: mean of 2*x-1 over the first epoch's first batch — the
    # underlying reader is deterministic (no shuffle), so recompute
    import pickle
    from paddle_tpu import recordio
    samples = []
    for rec in recordio.read_records(path):
        s = pickle.loads(rec)
        vals = list(s.values()) if isinstance(s, dict) else s
        samples.append(np.asarray(vals[0], np.float32))
        if len(samples) == 10:
            break
    want = np.mean(np.stack(samples) * 2.0 - 1.0)
    np.testing.assert_allclose(float(np.ravel(got)[0]), want, rtol=1e-5)


def test_custom_reader_with_parameterized_layer(prog_scope, exe,
                                                tmp_path):
    """A Preprocessor sub-block may use parameterized layers (fc): the
    custom reader executes in a kid scope of the run scope, so it sees
    the weights the startup program initialized."""
    path = os.path.join(str(tmp_path), "ppw.recordio")
    _write_samples(path, n=10, seed=6)
    main, startup, scope = prog_scope
    reader = fluid.layers.io.open_recordio_file(
        path, shapes=[[-1, 784], [-1, 1]], lod_levels=[0, 0],
        dtypes=["float32", "int64"])
    reader = fluid.layers.io.batch(reader, batch_size=5)
    p = fluid.layers.io.Preprocessor(reader)
    with p.block():
        img, lbl = p.inputs()
        proj = fluid.layers.fc(img, size=16, act="tanh",
                               param_attr=fluid.ParamAttr(name="pp_w"),
                               bias_attr=False)
        p.outputs(proj, lbl)
    reader = p()
    img_v, lbl_v = fluid.layers.io.read_file(reader)
    out = fluid.layers.reduce_mean(img_v)
    exe.run(startup)
    got, = exe.run(main, fetch_list=[out])
    assert np.isfinite(np.ravel(got)).all()
    assert np.asarray(scope.find_var("pp_w")).shape == (784, 16)
