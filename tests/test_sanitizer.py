"""Sanitizer suite tests (ISSUE 14): static donation-lifetime checker
positives/negatives (including the historical PR 2/8/10/11 shapes as
minimized regression programs), runtime buffer-sanitizer husk behavior
on the run()/prepared/rpc/KV paths, epoch re-bind bit-exactness with
the sanitizer on vs off, and the lock sanitizer's order-inversion /
signal-handler-reentrancy machinery.

The ``fault_plant`` tests double as the tools/fault_matrix.py
'sanitizer' preset: run with FLAGS_sanitizer=all and a telemetry dump
dir, they must leave NAMED artifacts (a sanitizer:buffer:* flight dump
carrying the planted var, a lockgraph_<pid>.json cycling both planted
locks) — the preset FAILs otherwise.
"""
import json
import os
import threading
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import analysis
from paddle_tpu.analysis import Severity
from paddle_tpu.analysis import lifetime as lt
from paddle_tpu.core import desc as core_desc
from paddle_tpu.core import sanitizer as san
from paddle_tpu.core.flags import FLAGS
from paddle_tpu.core.scope import Scope

V = core_desc.VarDesc
O = core_desc.OpDesc

PLANT_VAR = "sanitizer_plant_w"          # fault_matrix greps for these
PLANT_LOCKS = ("plant.A", "plant.B")


@pytest.fixture
def san_mode():
    """Restore FLAGS_sanitizer (and the lock graph) after the test."""
    prev = FLAGS.sanitizer
    yield
    FLAGS.sanitizer = prev
    san.reset_lock_graph()


def _prog_with(ops, vars_=()):
    prog = core_desc.ProgramDesc()
    b = prog.blocks[0]
    for vd in vars_:
        b.add_var(vd)
    for op in ops:
        b.append_op(op)
    return prog


def _lifetime(prog):
    return analysis.verify_program(prog, ["lifetime"])


def _errors(diags):
    return [d for d in diags if d.severity == Severity.ERROR]


# ---------------------------------------------------------------------------
# static checker: the four historical shapes, minimized
# ---------------------------------------------------------------------------

def test_pr2_shape_host_read_before_donate_warns():
    """PR 2 (donated-husk flush protocol): a synchronous host op reads
    a persistable the step later donates — flush-dependent WARNING."""
    prog = _prog_with(
        [O("save", {"X": ["w"]}, {}, {"file_path": "/tmp/x"}),
         O("scale", {"X": ["w"]}, {"Out": ["w"]}, {"scale": 0.9})],
        [V("w", shape=(4,), persistable=True)])
    diags = _lifetime(prog)
    assert _errors(diags) == []
    w = [d for d in diags if d.severity == Severity.WARNING]
    assert len(w) == 1 and w[0].var == "w" and w[0].op_type == "save"
    assert "flush" in w[0].message
    assert w[0].suggestion          # every lifetime finding has a fix


def test_by_reference_send_of_donated_errors():
    """A sender-thread (by-reference) host op racing the donation is an
    ERROR, not a flush-dependent warning — no flush covers it."""
    prog = _prog_with(
        [O("send", {"X": ["w"]}, {},
           {"epmap": ["ep"], "sections": [4], "block_names": ["w"]}),
         O("scale", {"X": ["w"]}, {"Out": ["w"]}, {"scale": 0.9})],
        [V("w", shape=(4,), persistable=True)])
    errs = _errors(_lifetime(prog))
    assert len(errs) == 1 and errs[0].var == "w"
    assert errs[0].op_type == "send"
    assert "by-reference" in errs[0].message


def test_pr8_pr11_shape_fetch_of_donated_errors():
    """PR 8 (guard read of consumed buffers) / PR 11 (KV-pool aliasing
    fetch): a fetch op naming donated state is an ERROR."""
    prog = _prog_with(
        [O("scale", {"X": ["w"]}, {"Out": ["w"]}, {"scale": 0.9}),
         O("fetch", {"X": ["w"]}, {"Out": ["w_f"]})],
        [V("w", shape=(4,), persistable=True), V("w_f", shape=(4,))])
    errs = _errors(_lifetime(prog))
    assert len(errs) == 1 and errs[0].var == "w"
    assert errs[0].op_type == "fetch"
    assert "donated" in errs[0].message


def test_pr10_shape_concurrent_read_of_donated_errors():
    """PR 10 (k-stale reads racing the optimize block's donated
    params): a concurrent sub-block reading a parent persistable the
    parent's step donates is an ERROR."""
    prog = core_desc.ProgramDesc()
    b0 = prog.blocks[0]
    b0.add_var(V("w", shape=(4,), persistable=True))
    sub = prog.append_block(parent_idx=0)
    sub.add_var(V("local", shape=(4,)))
    sub.append_op(O("scale", {"X": ["w"]}, {"Out": ["local"]},
                    {"scale": 2.0}))
    b0.append_op(O("go", {}, {}, {"sub_block": sub.idx}))
    b0.append_op(O("scale", {"X": ["w"]}, {"Out": ["w"]},
                   {"scale": 0.9}))
    errs = _errors(_lifetime(prog))
    assert len(errs) == 1 and errs[0].var == "w"
    assert "k-stale" in errs[0].message or "donates" in errs[0].message


def test_double_donation_errors():
    """Parent step donates w AND a launched sub-block's dispatch
    overwrites it in the same step: two dispatches, one buffer."""
    prog = core_desc.ProgramDesc()
    b0 = prog.blocks[0]
    b0.add_var(V("w", shape=(4,), persistable=True))
    sub = prog.append_block(parent_idx=0)
    sub.append_op(O("scale", {"X": ["w"]}, {"Out": ["w"]},
                    {"scale": 2.0}))
    b0.append_op(O("go", {}, {}, {"sub_block": sub.idx}))
    b0.append_op(O("scale", {"X": ["w"]}, {"Out": ["w"]},
                   {"scale": 0.9}))
    errs = _errors(_lifetime(prog))
    assert any("double-donation" in d.message and d.var == "w"
               for d in errs)


def test_lifetime_negatives():
    """No donation -> no findings; host read AFTER the device write is
    restaged; a non-persistable temp never reports."""
    # read after the write-back: restaged, clean
    prog = _prog_with(
        [O("scale", {"X": ["w"]}, {"Out": ["w"]}, {"scale": 0.9}),
         O("save", {"X": ["w"]}, {}, {"file_path": "/tmp/x"})],
        [V("w", shape=(4,), persistable=True)])
    assert _lifetime(prog) == []
    # non-persistable: never donated
    prog = _prog_with(
        [O("save", {"X": ["t"]}, {}, {"file_path": "/tmp/x"}),
         O("scale", {"X": ["t"]}, {"Out": ["t"]}, {"scale": 0.9})],
        [V("t", shape=(4,))])
    assert _lifetime(prog) == []
    # write-only persistable (not read by the block): rebuilt, not
    # donated — a fetch of it is fine
    prog = _prog_with(
        [O("fill_constant", {}, {"Out": ["acc"]},
           {"shape": [4], "value": 0.0}),
         O("fetch", {"X": ["acc"]}, {"Out": ["acc_f"]})],
        [V("acc", shape=(4,), persistable=True), V("acc_f", shape=(4,))])
    assert _lifetime(prog) == []


def test_check_suppress_flag_skips_checker(san_mode):
    prog = _prog_with(
        [O("scale", {"X": ["w"]}, {"Out": ["w"]}, {"scale": 0.9}),
         O("fetch", {"X": ["w"]}, {"Out": ["w_f"]})],
        [V("w", shape=(4,), persistable=True), V("w_f", shape=(4,))])
    assert any(d.checker == "lifetime"
               for d in analysis.verify_program(prog))
    prev = FLAGS.check_suppress
    FLAGS.check_suppress = "lifetime"
    try:
        assert not any(d.checker == "lifetime"
                       for d in analysis.verify_program(prog))
        # explicit names win over the suppression
        assert _lifetime(prog)
    finally:
        FLAGS.check_suppress = prev


def test_serving_fetch_helper():
    diags = lt.check_serving_fetches(["tokens", "kv_pages"],
                                     ["kv_pages"], site="tenant g")
    assert len(diags) == 1 and diags[0].var == "kv_pages"
    assert diags[0].severity == Severity.ERROR
    assert lt.check_serving_fetches(["tokens"], ["kv_pages"]) == []


# ---------------------------------------------------------------------------
# runtime buffer sanitizer: prepared path
# ---------------------------------------------------------------------------

def _build_sgd(param_name):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data(name="x", shape=[8], dtype="float32")
            h = fluid.layers.fc(
                x, size=8, act="relu",
                param_attr=fluid.ParamAttr(
                    name=param_name,
                    initializer=fluid.initializer.ConstantInitializer(
                        0.05)))
            loss = fluid.layers.mean(fluid.layers.fc(h, size=4))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def test_husk_raises_named_error_on_prepared_path(san_mode):
    FLAGS.sanitizer = "buffers"
    main, startup, loss = _build_sgd("w_husk")
    scope = Scope()
    feed = {"x": np.ones((4, 8), np.float32)}
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        prep = exe.prepare(main, feed_specs=feed, fetch_list=[loss])
        prep.run_prepared(feed)
        prep.run_prepared(feed)
        # a raw read that BYPASSES the flush protocol sees the husk
        owner = scope.find_scope_of("w_husk")
        raw = owner._vars["w_husk"]
        assert san.is_husk(raw)
        with pytest.raises(san.BufferLifetimeError) as ei:
            np.asarray(raw)
        err = ei.value
        assert err.var == "w_husk" and err.op == "run_prepared"
        assert isinstance(err.step, int)
        assert "prepared block 0" in str(err.site)
        assert san.buffer_epoch(scope, "w_husk") >= 1
        # the sanctioned read path (find_var flushes -> re-bind) works
        val = np.asarray(scope.find_var("w_husk"))
        assert np.isfinite(val).all()
        # and training continues after the re-stage
        prep.run_prepared(feed)
        prep.sync_scope()


def test_trips_counted_and_dumped(san_mode, tmp_path):
    from paddle_tpu.observability import metrics

    FLAGS.sanitizer = "buffers"
    trips = metrics.counter("sanitizer_trips_total")
    before = trips.value
    prev_dir = FLAGS.telemetry_dump_dir
    FLAGS.telemetry_dump_dir = str(tmp_path)
    try:
        scope = Scope()
        scope.set("v", np.ones(3, np.float32))
        arr = scope._vars["v"]
        assert san.poison_donated(scope, {"v": arr}, op="test.dispatch",
                                  step=7, site="unit") == 1
        with pytest.raises(san.BufferLifetimeError):
            np.asarray(scope._vars["v"])
    finally:
        FLAGS.telemetry_dump_dir = prev_dir
    assert trips.value == before + 1
    arts = [p for p in os.listdir(str(tmp_path))
            if p.startswith("flight_")]
    assert arts, "a trip with a dump dir configured must leave a dump"
    with open(str(tmp_path / arts[0])) as f:
        rec = json.load(f)
    assert rec["reason"] == "sanitizer:buffer:v"
    assert rec["blocked"]["var"] == "v"
    assert rec["blocked"]["op"] == "test.dispatch"
    # re-bind: a scope write replaces the husk
    scope.set("v", np.zeros(3, np.float32))
    assert np.asarray(scope.find_var("v")).sum() == 0.0


def test_poison_skips_fresh_values(san_mode):
    """A slot rewritten since the dispatch (external write wins) is
    never poisoned; only_dead never husks a live identity match."""
    FLAGS.sanitizer = "buffers"
    scope = Scope()
    old = np.ones(3, np.float32)
    scope.set("v", old)
    fresh = np.zeros(3, np.float32)
    scope.set("v", fresh)
    assert san.poison_donated(scope, {"v": old}, op="d") == 0
    assert scope._vars["v"] is fresh
    # identity match but only_dead: a live numpy value stays live
    assert san.poison_donated(scope, {"v": fresh}, op="d",
                              only_dead=True) == 0
    assert scope._vars["v"] is fresh


def test_bitexact_with_sanitizer_on_vs_off(san_mode):
    """The epoch/husk machinery must not change a single bit of the
    training trajectory (prepared path, 4 SGD steps)."""
    from paddle_tpu.observability import metrics

    def run(mode):
        FLAGS.sanitizer = mode
        main, startup, loss = _build_sgd("w_exact")
        scope = Scope()
        feed = {"x": np.linspace(0, 1, 32, dtype=np.float32)
                .reshape(4, 8)}
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            prep = exe.prepare(main, feed_specs=feed,
                               fetch_list=[loss])
            losses = [np.asarray(prep.run_prepared(feed)[0])
                      for _ in range(4)]
            prep.sync_scope()
            w = np.asarray(scope.find_var("w_exact"))
        return losses, w

    trips = metrics.counter("sanitizer_trips_total")
    before = trips.value
    losses_off, w_off = run("off")
    losses_on, w_on = run("buffers")
    for a, b in zip(losses_off, losses_on):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(w_off, w_on)
    assert trips.value == before     # a clean run never trips


# ---------------------------------------------------------------------------
# runtime buffer sanitizer: rpc (pserver) path
# ---------------------------------------------------------------------------

def test_rpc_read_of_husk_without_fence_raises(san_mode):
    from paddle_tpu.distributed.rpc import VariableServer

    FLAGS.sanitizer = "buffers"
    scope = Scope()
    scope.set("w", np.ones(4, np.float32))
    srv = VariableServer(scope, {"w@GRAD": 0}, lambda b: None, fanin=1)
    # the apply committed... except it didn't: husk with no apply in
    # flight means the re-bind never happened — named error, not hang
    scope._vars["w"] = san.PoisonedHusk("w", op="apply", step=3,
                                        site="shard")
    with srv._cv:
        with pytest.raises(san.BufferLifetimeError) as ei:
            srv._read_var_locked("w")
    assert ei.value.var == "w" and ei.value.op == "apply"


def test_rpc_read_waits_for_apply_commit(san_mode):
    """The sanctioned k-stale read (PR 10): husk + apply in flight ->
    wait for the commit's re-bind, return the fresh value."""
    from paddle_tpu.distributed.rpc import VariableServer

    FLAGS.sanitizer = "buffers"
    scope = Scope()
    scope.set("w", np.ones(4, np.float32))
    srv = VariableServer(scope, {"w@GRAD": 0}, lambda b: None, fanin=1)
    scope._vars["w"] = san.PoisonedHusk("w", op="apply", step=1,
                                        site="shard")
    srv._applying = True
    fresh = np.full(4, 7.0, np.float32)

    def commit():
        time.sleep(0.15)
        with srv._cv:
            scope.set("w", fresh)
            srv._applying = False
            srv._cv.notify_all()

    t = threading.Thread(target=commit)
    t.start()
    with srv._cv:
        got = srv._read_var_locked("w")
    t.join()
    np.testing.assert_array_equal(got, fresh)


# ---------------------------------------------------------------------------
# runtime buffer sanitizer: serving KV path
# ---------------------------------------------------------------------------

def test_kv_epoch_guard_and_pool_double_free(san_mode):
    from paddle_tpu.serving import GenerativeEngine, tiny_lm

    FLAGS.sanitizer = "buffers"
    cfg, params = tiny_lm(5, vocab=32, d_model=32, n_heads=2,
                          n_layers=1, d_ff=64, block_size=8,
                          max_blocks=4, max_batch=2)
    eng = GenerativeEngine(cfg, params, kv_blocks=8, warm=False)
    try:
        kp, vp, e0 = eng.kv_pages()
        eng.check_kv_epoch(e0)          # current: fine
        # a dispatch donates the pages: mid-flight access trips...
        eng._kv_guard.begin("decode", 1)
        with pytest.raises(san.BufferLifetimeError) as ei:
            eng.kv_pages()
        assert "dispatch in flight" in str(ei.value.site)
        eng._kv_guard.rebind()
        # ...and the retained pre-rebind epoch is now stale
        with pytest.raises(san.BufferLifetimeError) as ei:
            eng.check_kv_epoch(e0)
        assert ei.value.var == "kv_pool"
        assert "stale epoch" in str(ei.value.site)
        # double-free of KV blocks = the block-id form of the bug
        blocks = eng.pool.alloc(2)
        eng.pool.free(blocks)
        with pytest.raises(san.BufferLifetimeError):
            eng.pool.free(blocks)
    finally:
        eng.close()


def test_kv_epoch_bumps_on_real_decode(san_mode):
    """A real prefill/decode round-trip bumps the epoch per dispatch
    and produces the same tokens with the sanitizer on."""
    from paddle_tpu import serving

    cfg, params = tiny_lm_small()
    prompt = [1, 2, 3]

    def generate(mode):
        FLAGS.sanitizer = mode
        with serving.InferenceServer() as srv:
            srv.load_generative("g", cfg, params, kv_blocks=16,
                                warm=False)
            res = srv.generate("g", prompt,
                               max_new_tokens=6).result(300)
        return res["tokens"]

    t_off = generate("off")
    t_on = generate("buffers")
    assert t_off == t_on


def tiny_lm_small():
    from paddle_tpu.serving import tiny_lm
    return tiny_lm(9, vocab=32, d_model=32, n_heads=2, n_layers=1,
                   d_ff=64, block_size=8, max_blocks=4, max_batch=2)


# ---------------------------------------------------------------------------
# lock sanitizer
# ---------------------------------------------------------------------------

def test_make_lock_mode_selection(san_mode):
    FLAGS.sanitizer = "off"
    assert not isinstance(san.make_lock("x"), san.InstrumentedLock)
    FLAGS.sanitizer = "locks"
    lk = san.make_lock("x", reentrant=True)
    assert isinstance(lk, san.InstrumentedLock) and lk.reentrant


def test_lock_order_inversion_detected_and_reported(san_mode,
                                                    tmp_path):
    FLAGS.sanitizer = "locks"
    san.reset_lock_graph()
    a = san.InstrumentedLock("inv.A")
    b = san.InstrumentedLock("inv.B")

    def t1():
        with a:
            with b:
                pass

    th = threading.Thread(target=t1)
    th.start()
    th.join()
    with b:
        with a:      # the inversion: B -> A after A -> B
            pass
    assert ("inv.A", "inv.B") in san.GRAPH.inversions
    path = san.write_lockgraph(str(tmp_path))
    with open(path) as f:
        rec = json.load(f)
    assert rec["kind"] == "lockgraph"
    cyc = rec["cycles"]
    assert any(set(c["locks"]) == {"inv.A", "inv.B"} for c in cyc)
    assert rec["inversions"][0]["locks"] == ["inv.A", "inv.B"]


def test_non_reentrant_reacquire_raises_not_hangs(san_mode):
    FLAGS.sanitizer = "locks"
    san.reset_lock_graph()
    lk = san.InstrumentedLock("plain")
    with lk:
        with pytest.raises(san.LockDisciplineError) as ei:
            lk.acquire()
    assert "plain" in str(ei.value)
    assert any(v["kind"] == "non-reentrant-reacquire"
               for v in san.GRAPH.report_dict()["violations"])
    # still usable afterwards
    with lk:
        pass


def test_signal_safe_lock_must_be_reentrant(san_mode):
    FLAGS.sanitizer = "locks"
    san.reset_lock_graph()
    san.InstrumentedLock("sig.bad", reentrant=False, signal_safe=True)
    vio = san.GRAPH.report_dict()["violations"]
    assert any(v["kind"] == "signal-unsafe-lock"
               and v["lock"] == "sig.bad" for v in vio)


def test_signal_reentrancy_probe(san_mode):
    """The flight.dump invariant, actively proven: a reentrant
    signal-safe lock survives the same-thread re-acquisition a
    signal-handler dump performs; the probe flags nothing for it —
    and metric locks created under the sanitizer are exactly that."""
    from paddle_tpu.observability import metrics

    FLAGS.sanitizer = "locks"
    san.reset_lock_graph()
    c = metrics.counter("sanitizer_probe_counter_%d" % os.getpid())
    assert isinstance(c._lock, san.InstrumentedLock)
    assert c._lock.signal_safe and c._lock.reentrant
    # simulate the signal: snapshot while the observe lock is held
    with c._lock:
        c.inc()           # re-entry through the same lock
        assert c.snapshot()["value"] >= 1
    assert san.probe_signal_reentrancy() == []


def test_lock_adoption_in_subsystems(san_mode):
    """FLAGS_sanitizer=locks at construction time instruments the
    adopted subsystems' locks (rpc server, kv pool, tsdb store)."""
    from paddle_tpu.distributed.rpc import VariableServer
    from paddle_tpu.observability import tsdb
    from paddle_tpu.serving.kv_cache import BlockPool

    FLAGS.sanitizer = "locks"
    san.reset_lock_graph()
    srv = VariableServer(Scope(), {"g": 0}, lambda b: None, fanin=1)
    assert isinstance(srv._ckpt_lock, san.InstrumentedLock)
    pool = BlockPool(4, 8)
    try:
        assert isinstance(pool._lock, san.InstrumentedLock)
    finally:
        pool.close()
    import tempfile
    d = tempfile.mkdtemp(prefix="san_tsdb_")
    store = tsdb.TSDB(d)
    try:
        assert isinstance(store._lock, san.InstrumentedLock)
    finally:
        store.close()
        import shutil
        shutil.rmtree(d, ignore_errors=True)


# ---------------------------------------------------------------------------
# fault plants (the fault_matrix 'sanitizer' preset drives these with
# FLAGS_sanitizer=all + a dump dir and asserts the named artifacts)
# ---------------------------------------------------------------------------

def test_fault_plant_use_after_donate(san_mode):
    if not san.buffers_on():
        FLAGS.sanitizer = "buffers"
    main, startup, loss = _build_sgd(PLANT_VAR)
    scope = Scope()
    feed = {"x": np.ones((4, 8), np.float32)}
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        prep = exe.prepare(main, feed_specs=feed, fetch_list=[loss])
        prep.run_prepared(feed)
        prep.run_prepared(feed)
        # the plant: a direct host read of the donated param
        # mid-prepared-loop, bypassing the flush protocol
        owner = scope.find_scope_of(PLANT_VAR)
        with pytest.raises(san.BufferLifetimeError) as ei:
            np.asarray(owner._vars[PLANT_VAR])
        assert ei.value.var == PLANT_VAR
        prep.sync_scope()
    if FLAGS.telemetry_dump_dir:
        arts = [p for p in os.listdir(FLAGS.telemetry_dump_dir)
                if p.startswith("flight_")]
        assert arts, "dump dir configured but no flight artifact"


def test_fault_plant_lock_inversion(san_mode, tmp_path):
    if not san.locks_on():
        FLAGS.sanitizer = "locks"
    san.reset_lock_graph()
    a = san.InstrumentedLock(PLANT_LOCKS[0])
    b = san.InstrumentedLock(PLANT_LOCKS[1])

    def t1():
        with a:
            with b:
                pass

    th = threading.Thread(target=t1)
    th.start()
    th.join()
    with b:
        with a:
            pass
    assert tuple(sorted(PLANT_LOCKS)) in san.GRAPH.inversions
    # the artifact the preset asserts: written to the dump dir when
    # configured (the inversion hook already wrote one), else here
    path = san.write_lockgraph(FLAGS.telemetry_dump_dir
                               or str(tmp_path))
    with open(path) as f:
        rec = json.load(f)
    names = {l for c in rec["cycles"] for l in c["locks"]}
    assert set(PLANT_LOCKS) <= names


# ---------------------------------------------------------------------------
# lint CLI (ISSUE 14 small fix)
# ---------------------------------------------------------------------------

def _lint_main(argv):
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                    os.pardir, "tools"))
    try:
        import lint_program
    finally:
        sys.path.pop(0)
    return lint_program, lint_program.main(argv)


def test_lint_cli_lists_lifetime_checker(capsys):
    _, rc = _lint_main(["--list-checkers"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "lifetime" in out and "def-use" in out


def test_lint_cli_warning_only_exits_zero(tmp_path, capsys):
    """A program whose only findings are WARNINGs exits 0 at the
    default --max-level error, and --json carries the fix hints."""
    prog = _prog_with(
        [O("save", {"X": ["w"]}, {}, {"file_path": "/tmp/x"}),
         O("scale", {"X": ["w"]}, {"Out": ["w"]}, {"scale": 0.9})],
        [V("w", shape=(4,), persistable=True)])
    path = str(tmp_path / "model")
    with open(path, "wb") as f:
        f.write(prog.serialize_to_string())
    lint, rc = _lint_main([path, "--checkers", "lifetime", "--json"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out and out[0]["checker"] == "lifetime"
    assert out[0]["severity"] == "warning"
    assert out[0]["suggestion"]         # the per-diagnostic fix hint
    # the same findings at --max-level warning DO fail the lint
    _, rc = _lint_main([path, "--checkers", "lifetime", "--quiet",
                        "--max-level", "warning"])
    assert rc == 1
