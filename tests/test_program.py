"""Program IR tests: serialization roundtrip, clone(for_test), prune
(cf. reference test_program.py, test_protobuf_descs.py)."""
import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.core.desc import ProgramDesc


def _build_net(main, startup):
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        h = fluid.layers.fc(x, size=8, act="relu")
        h = fluid.layers.dropout(h, dropout_prob=0.5)
        y = fluid.layers.fc(h, size=2, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(
            y, fluid.layers.data(name="label", shape=[1], dtype="int64")))
        opt = fluid.optimizer.SGD(0.1)
        opt.minimize(loss)
    return x, y, loss


def test_serialize_roundtrip():
    main, startup = fluid.Program(), fluid.Program()
    _build_net(main, startup)
    blob = main.serialize_to_string()
    restored = ProgramDesc.parse_from_string(blob)
    assert [op.type for op in restored.blocks[0].ops] == \
        [op.type for op in main.desc.blocks[0].ops]
    for name, vd in main.desc.blocks[0].vars.items():
        rd = restored.blocks[0].vars[name]
        assert rd.shape == vd.shape and rd.dtype == vd.dtype \
            and rd.persistable == vd.persistable


def test_clone_for_test_strips_backward():
    main, startup = fluid.Program(), fluid.Program()
    _build_net(main, startup)
    test_prog = main.clone(for_test=True)
    types = [op.type for op in test_prog.desc.blocks[0].ops]
    assert not any(t.endswith("_grad") for t in types)
    assert "sgd" not in types
    # dropout flips to test mode
    d_ops = [op for op in test_prog.desc.blocks[0].ops
             if op.type == "dropout"]
    assert d_ops and d_ops[0].attr("is_test") is True
    # original untouched
    orig_types = [op.type for op in main.desc.blocks[0].ops]
    assert any(t.endswith("_grad") for t in orig_types)


def test_prune_keeps_only_needed():
    main, startup = fluid.Program(), fluid.Program()
    x, y, loss = _build_net(main, startup)
    pruned = main.clone(for_test=True).prune([y])
    types = [op.type for op in pruned.desc.blocks[0].ops]
    assert "cross_entropy" not in types
    assert "mul" in types


def test_program_run_after_mutation_invalidates_cache(prog_scope, exe):
    """Compile cache keys on (uid, version): editing the program after a run
    must recompile, not reuse stale XLA."""
    main, startup, scope = prog_scope
    x = fluid.layers.data(name="x", shape=[2], dtype="float32")
    y = fluid.layers.scale(x, scale=2.0)
    exe.run(startup)
    out1, = exe.run(main, feed={"x": np.ones((1, 2), np.float32)},
                    fetch_list=[y])
    z = fluid.layers.scale(y, scale=3.0)
    out2, = exe.run(main, feed={"x": np.ones((1, 2), np.float32)},
                    fetch_list=[z])
    np.testing.assert_allclose(out1, 2 * np.ones((1, 2)))
    np.testing.assert_allclose(out2, 6 * np.ones((1, 2)))


def test_operator_introspection():
    main, startup = fluid.Program(), fluid.Program()
    _build_net(main, startup)
    op = main.global_block().ops[0]
    assert op.type == "mul"
    assert op.input("X") and op.output("Out")
    assert "x_num_col_dims" in op.attr_names
