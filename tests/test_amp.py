"""bf16 mixed precision (Float16Transpiler; TPU analog of reference
paddle/contrib/float16/float16_transpiler.py — see that module's
docstring for the design mapping)."""
import numpy as np

import paddle_tpu.fluid as fluid


def _build_convnet():
    img = fluid.layers.data(name="img", shape=[1, 16, 16], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    conv = fluid.layers.conv2d(input=img, num_filters=8, filter_size=3,
                               padding=1, act="relu")
    pool = fluid.layers.pool2d(input=conv, pool_size=2, pool_type="max",
                               pool_stride=2)
    fc = fluid.layers.fc(input=pool, size=10, act="softmax")
    loss = fluid.layers.mean(
        fluid.layers.cross_entropy(input=fc, label=label))
    return img, label, conv, loss


def _train(amp, steps=8):
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                img, label, conv, loss = _build_convnet()
                fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        if amp:
            fluid.transpiler.Float16Transpiler().transpile(main)
        main.random_seed = 5
        startup.random_seed = 5
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        rng = np.random.RandomState(0)
        x = rng.rand(16, 1, 16, 16).astype(np.float32)
        y = rng.randint(0, 10, (16, 1)).astype(np.int64)
        losses, conv_v = [], None
        for _ in range(steps):
            l, c = exe.run(main, feed={"img": x, "label": y},
                           fetch_list=[loss, conv], return_numpy=False)
            losses.append(float(np.ravel(np.asarray(l))[0]))
            conv_v = c
        params = {p.name: np.asarray(scope.find_var(p.name))
                  for p in main.all_parameters()}
    return losses, conv_v, params


def test_amp_loss_parity_and_dtypes():
    import jax.numpy as jnp

    fp_l, fp_conv, fp_params = _train(False)
    amp_l, amp_conv, amp_params = _train(True)

    # losses track fp32 closely (bf16 has ~3 decimal digits)
    np.testing.assert_allclose(amp_l, fp_l, rtol=0.1, atol=0.02)
    assert amp_l[-1] < amp_l[0]  # still learning

    # compute really happened in bf16: the fetched conv activation is
    # bfloat16 under AMP, float32 without
    assert fp_conv.dtype == jnp.float32
    assert amp_conv.dtype == jnp.bfloat16

    # master weights stay fp32 in the scope
    for name, w in amp_params.items():
        assert w.dtype == np.float32, name
    # and actually differ from the fp32 run (bf16 rounding), proving the
    # updates flowed through the bf16 path
    assert set(amp_params) == set(fp_params)
    assert any(not np.array_equal(amp_params[n], fp_params[n])
               for n in fp_params)


def test_amp_bn_bf16_passthrough():
    """FLAGS.bn_bf16: batch_norm consumes/produces bf16 under AMP
    (activation bytes halve on conv nets) while statistics stay f32 —
    loss must track the f32-BN AMP run and the BN output dtype must be
    bfloat16."""
    import jax.numpy as jnp

    from paddle_tpu.core.flags import FLAGS

    def build_bn():
        img = fluid.layers.data(name="img", shape=[1, 16, 16],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        conv = fluid.layers.conv2d(input=img, num_filters=8,
                                   filter_size=3, padding=1)
        bn = fluid.layers.batch_norm(input=conv, act="relu")
        fc = fluid.layers.fc(input=bn, size=10, act="softmax")
        loss = fluid.layers.mean(
            fluid.layers.cross_entropy(input=fc, label=label))
        return bn, loss

    def train(bn_bf16, steps=8):
        old = FLAGS.bn_bf16
        FLAGS.bn_bf16 = bn_bf16
        try:
            main, startup = fluid.Program(), fluid.Program()
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                with fluid.program_guard(main, startup):
                    with fluid.unique_name.guard():
                        bn, loss = build_bn()
                        fluid.optimizer.SGD(
                            learning_rate=0.1).minimize(loss)
                fluid.transpiler.Float16Transpiler().transpile(main)
                main.random_seed = 7
                startup.random_seed = 7
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                rng = np.random.RandomState(0)
                x = rng.rand(16, 1, 16, 16).astype(np.float32)
                y = rng.randint(0, 10, (16, 1)).astype(np.int64)
                losses, bn_v = [], None
                for _ in range(steps):
                    l, b = exe.run(main, feed={"img": x, "label": y},
                                   fetch_list=[loss, bn],
                                   return_numpy=False)
                    losses.append(float(np.ravel(np.asarray(l))[0]))
                    bn_v = b
            return losses, bn_v
        finally:
            FLAGS.bn_bf16 = old

    f32_l, f32_bn = train(False)
    b16_l, b16_bn = train(True)
    assert f32_bn.dtype == jnp.float32
    assert b16_bn.dtype == jnp.bfloat16
    np.testing.assert_allclose(b16_l, f32_l, rtol=0.15, atol=0.03)
    assert b16_l[-1] < b16_l[0]


def test_amp_with_dynamic_rnn():
    """AMP through lax.scan control flow: fp32 carries + bf16 body ops
    must not break carry dtype invariance."""
    def build_and_train(amp):
        main, startup = fluid.Program(), fluid.Program()
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            with fluid.program_guard(main, startup):
                with fluid.unique_name.guard():
                    x = fluid.layers.data(name="x", shape=[6, 4],
                                          dtype="float32")
                    y = fluid.layers.data(name="y", shape=[1],
                                          dtype="float32")
                    rnn = fluid.layers.StaticRNN()
                    with rnn.step():
                        xt = rnn.step_input(x)
                        h = rnn.memory(shape=[8], batch_ref=x)
                        nh = fluid.layers.fc(input=[xt, h], size=8,
                                             act="tanh")
                        rnn.update_memory(h, nh)
                        rnn.step_output(nh)
                    seq = rnn()
                    pred = fluid.layers.fc(
                        fluid.layers.reduce_mean(seq, dim=1), size=1)
                    loss = fluid.layers.mean(
                        fluid.layers.square_error_cost(pred, y))
                    fluid.optimizer.SGD(learning_rate=0.05).minimize(
                        loss)
            if amp:
                fluid.transpiler.Float16Transpiler().transpile(main)
            main.random_seed = startup.random_seed = 11
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            rng = np.random.RandomState(2)
            xv = rng.randn(8, 6, 4).astype(np.float32)
            yv = xv.sum(axis=(1, 2), keepdims=False)[:, None] * 0.1
            yv = yv.astype(np.float32)
            ls = []
            for _ in range(10):
                l, = exe.run(main, feed={"x": xv, "y": yv},
                             fetch_list=[loss])
                ls.append(float(np.ravel(l)[0]))
        return ls

    fp_l = build_and_train(False)
    amp_l = build_and_train(True)
    assert all(np.isfinite(amp_l))
    assert amp_l[-1] < amp_l[0]
    np.testing.assert_allclose(amp_l, fp_l, rtol=0.15, atol=0.05)


def test_amp_and_shardings_survive_serialize():
    """save/load round-trips the AMP flag and sharding annotations (a
    transpiled program must not silently revert to fp32/unsharded)."""
    from paddle_tpu.core.desc import ProgramDesc

    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            _build_convnet()
    fluid.transpiler.Float16Transpiler().transpile(main)
    main.desc.var_shardings["fc_0.w_0"] = (None, "tp")
    rt = ProgramDesc.parse_from_string(main.desc.serialize_to_string())
    assert rt.amp_bf16
    assert rt.var_shardings == {"fc_0.w_0": (None, "tp")}


def test_amp_flag_survives_clone():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            _build_convnet()
    fluid.transpiler.Float16Transpiler().transpile(main)
    test_prog = main.clone(for_test=True)
    assert test_prog.desc.amp_bf16
    fluid.transpiler.Float16Transpiler().revert(main)
    assert not main.desc.amp_bf16
    assert test_prog.desc.amp_bf16  # clone is independent


def test_amp_under_parallel_executor():
    """AMP + SPMD together: a bf16 program compiled over the data-
    parallel mesh matches its own single-device loss trajectory."""
    import jax

    if len(jax.devices("cpu")) < 8:
        import pytest
        pytest.skip("needs 8 host devices")

    def train(parallel):
        main, startup = fluid.Program(), fluid.Program()
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            with fluid.program_guard(main, startup):
                with fluid.unique_name.guard():
                    img, label, conv, loss = _build_convnet()
                    fluid.optimizer.SGD(learning_rate=0.1).minimize(
                        loss)
            fluid.transpiler.Float16Transpiler().transpile(main)
            main.random_seed = startup.random_seed = 9
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            rng = np.random.RandomState(1)
            x = rng.rand(16, 1, 16, 16).astype(np.float32)
            y = rng.randint(0, 10, (16, 1)).astype(np.int64)
            if parallel:
                pexe = fluid.ParallelExecutor(
                    use_cuda=False, loss_name=loss.name,
                    main_program=main, scope=scope)
                runner = lambda: pexe.run([loss.name],
                                          feed={"img": x, "label": y})
            else:
                runner = lambda: exe.run(main,
                                         feed={"img": x, "label": y},
                                         fetch_list=[loss])
            return [float(np.ravel(np.asarray(runner()[0]))[0])
                    for _ in range(6)]

    single = train(False)
    spmd = train(True)
    np.testing.assert_allclose(spmd, single, rtol=2e-2, atol=1e-2)
    assert spmd[-1] < spmd[0]
