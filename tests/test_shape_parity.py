"""Abstract-shape/runtime parity (verifier satellite).

The enforcement surface is the hook in op_test.py: every OpTest spec in
the suite asserts, on its CPU run, that the verifier's abstract shape
inference (registered infer_shape or the jax.eval_shape fallback)
matches its concrete output shapes/dtypes.  This file anchors the
mechanics: a meta-test proving the hook actually trips on a drifted
infer_shape, plus explicit parity anchors for representative op shapes
that must keep inferring even if their specs move around."""
import jax
import numpy as np
import pytest

import paddle_tpu.fluid as fluid  # registers all ops
from paddle_tpu.core import desc as core_desc
from paddle_tpu.core import lowering
from paddle_tpu.core.registry import has_op, register_op
from paddle_tpu.core.types import DataType

from op_test import OpTest


@pytest.fixture
def probe_op():
    """Register a throwaway op for one test and remove it afterwards —
    the registry is process-global and other suites (tpu_optest spec
    classification) sweep every registered op."""
    from paddle_tpu.core import registry

    names = []

    def _register(name, **kwargs):
        if not has_op(name):
            register_op(name, **kwargs)
            names.append(name)
        return name

    yield _register
    for name in names:
        registry._registry.pop(name, None)


def test_parity_hook_trips_on_drifted_infer_shape(probe_op):
    """Meta-test: a registered infer_shape that disagrees with the
    lowering must be caught by the OpTest parity hook — this is the
    drift the satellite exists to prevent."""
    def lying_infer(ins, attrs, op=None):
        sd = ins["X"]
        return {"Out": jax.ShapeDtypeStruct(sd.shape + (1,), sd.dtype)}

    probe_op("parity_probe_lying", grad_maker=None,
             infer_shape=lying_infer,
             lower=lambda ctx, ins, attrs, op=None: {"Out": ins["X"] * 2.0})

    x = np.ones((3, 4), np.float32)

    class T(OpTest):
        op_type = "parity_probe_lying"
        inputs = {"X": x}
        outputs = {"Out": x * 2.0}

    with pytest.raises(AssertionError, match="drifted|shape"):
        T().check_output()


def test_parity_hook_honors_correct_infer_shape(probe_op):
    def honest_infer(ins, attrs, op=None):
        sd = ins["X"]
        return {"Out": jax.ShapeDtypeStruct(sd.shape, sd.dtype)}

    probe_op("parity_probe_honest", grad_maker=None,
             infer_shape=honest_infer,
             lower=lambda ctx, ins, attrs, op=None: {"Out": ins["X"] * 3.0})

    x = np.ones((2, 5), np.float32)

    class T(OpTest):
        op_type = "parity_probe_honest"
        inputs = {"X": x}
        outputs = {"Out": x * 3.0}

    T().check_output()


# --- explicit anchors: ops whose inferred output specs must stay exact ---

ANCHORS = [
    ("mul", {"X": [("x", (4, 3), "float32")], "Y": [("y", (3, 7),
                                                     "float32")]},
     {"Out": [("o", (4, 7), "float32")]}, {}),
    ("softmax", {"X": [("x", (6, 10), "float32")]},
     {"Out": [("o", (6, 10), "float32")]}, {}),
    ("concat", {"X": [("a", (2, 3), "float32"), ("b", (2, 5),
                                                 "float32")]},
     {"Out": [("o", (2, 8), "float32")]}, {"axis": 1}),
    ("reduce_sum", {"X": [("x", (3, 4, 5), "float32")]},
     {"Out": [("o", (3, 5), "float32")]}, {"dim": [1], "keep_dim": False}),
    ("cast", {"X": [("x", (3, 3), "float32")]},
     {"Out": [("o", (3, 3), "int32")]},
     {"in_dtype": int(DataType.FP32), "out_dtype": int(DataType.INT32)}),
    ("lookup_table", {"W": [("w", (50, 8), "float32")],
                      "Ids": [("ids", (4, 1), "int32")]},
     {"Out": [("o", (4, 8), "float32")]}, {}),
    ("conv2d", {"Input": [("x", (2, 3, 8, 8), "float32")],
                "Filter": [("f", (4, 3, 3, 3), "float32")]},
     {"Output": [("o", (2, 4, 6, 6), "float32")]},
     {"strides": [1, 1], "paddings": [0, 0], "groups": 1,
      "dilations": [1, 1]}),
]


def _one_op_program(shape):
    from paddle_tpu.core.types import np_dtype_to_proto

    prog = core_desc.ProgramDesc()
    block = prog.blocks[0]
    dt = np_dtype_to_proto(np.dtype(np.float32))
    block.add_var(core_desc.VarDesc("x", shape=list(shape), dtype=dt))
    block.add_var(core_desc.VarDesc("out", shape=list(shape), dtype=dt))
    op = block.append_op(core_desc.OpDesc(
        "softmax", {"X": ["x"]}, {"Out": ["out"]}, {}))
    return prog, block, op


def test_fake_batch_sentinel_vocab_97_stays_static():
    """Regression (ISSUE 10 satellite, noted in PR 7): a REAL dim equal
    to the dynamic-dim sentinel (vocab_size=97) must survive inference
    as 97.  The old single-sentinel mapping declared every 97-sized
    output dim dynamic; the two-sentinel cross-check only maps dims
    that track BOTH substitutions."""
    prog, block, op = _one_op_program([-1, 97])
    shape, dtype = lowering.infer_op_outputs(prog, block, op)["out"]
    assert tuple(shape) == (-1, 97), shape
    assert np.dtype(dtype) == np.float32


def test_fake_batch_sentinel_inert_without_dynamic_dims():
    """A fully-static program containing a 97-sized dim has nothing to
    map back: inference must return it verbatim."""
    prog, block, op = _one_op_program([3, 97])
    shape, _ = lowering.infer_op_outputs(prog, block, op)["out"]
    assert tuple(shape) == (3, 97), shape


def test_fake_batch_sentinel_dynamic_dim_still_maps():
    """The ordinary case keeps working: the dynamic batch maps to -1."""
    prog, block, op = _one_op_program([-1, 10])
    shape, _ = lowering.infer_op_outputs(prog, block, op)["out"]
    assert tuple(shape) == (-1, 10), shape


@pytest.mark.parametrize("vocab", [97, 89],
                         ids=["primary-sentinel", "alt-sentinel"])
def test_decode_shaped_program_sentinel_dims_stay_static(vocab):
    """ISSUE 11 satellite: the generative decode step is the shape
    most likely to trip the sentinel — dynamic batch, seq-len 1, and a
    logits dim that may equal EITHER sentinel (vocab_size=97 collides
    with _FAKE_BATCH, 89 with _FAKE_BATCH_ALT).  Both must survive
    inference as static dims while the batch still maps to -1."""
    prog, block, op = _one_op_program([-1, 1, vocab])
    shape, dtype = lowering.infer_op_outputs(prog, block, op)["out"]
    assert tuple(shape) == (-1, 1, vocab), shape
    assert np.dtype(dtype) == np.float32


def test_decode_shaped_matmul_sentinel_logits_dim():
    """The decode lm_head matmul itself: [-1, d] @ [d, 97] — the
    inferred logits dim must stay 97, not decay to dynamic."""
    from paddle_tpu.core.types import np_dtype_to_proto

    prog = core_desc.ProgramDesc()
    block = prog.blocks[0]
    dt = np_dtype_to_proto(np.dtype(np.float32))
    block.add_var(core_desc.VarDesc("h", shape=[-1, 8], dtype=dt))
    block.add_var(core_desc.VarDesc("w", shape=[8, 97], dtype=dt))
    block.add_var(core_desc.VarDesc("logits", shape=[-1, 97], dtype=dt))
    op = block.append_op(core_desc.OpDesc(
        "mul", {"X": ["h"], "Y": ["w"]}, {"Out": ["logits"]}, {}))
    shape, _ = lowering.infer_op_outputs(prog, block, op)["logits"]
    assert tuple(shape) == (-1, 97), shape


@pytest.mark.parametrize("op_type,ins,outs,attrs", ANCHORS,
                         ids=[a[0] for a in ANCHORS])
def test_abstract_inference_anchor(op_type, ins, outs, attrs):
    from paddle_tpu.core.types import np_dtype_to_proto

    prog = core_desc.ProgramDesc()
    block = prog.blocks[0]
    in_map, out_map = {}, {}
    for slot, entries in ins.items():
        in_map[slot] = []
        for name, shape, dtype in entries:
            block.add_var(core_desc.VarDesc(
                name, shape=shape,
                dtype=np_dtype_to_proto(np.dtype(dtype))))
            in_map[slot].append(name)
    expected = {}
    for slot, entries in outs.items():
        out_map[slot] = []
        for name, shape, dtype in entries:
            block.add_var(core_desc.VarDesc(
                name, shape=shape,
                dtype=np_dtype_to_proto(np.dtype(dtype))))
            out_map[slot].append(name)
            expected[name] = (tuple(shape), np.dtype(dtype))
    op = block.append_op(core_desc.OpDesc(op_type, in_map, out_map, attrs))
    inferred = lowering.infer_op_outputs(prog, block, op)
    for name, (shape, dtype) in expected.items():
        got_shape, got_dtype = inferred[name]
        assert tuple(got_shape) == shape, (op_type, name, got_shape)
        assert np.dtype(got_dtype) == dtype, (op_type, name, got_dtype)
