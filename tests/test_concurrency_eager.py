"""CSP channels/Go/Select (reference python/paddle/fluid/concurrency.py,
framework/channel.h semantics) and the eager tape prototype (reference
paddle/contrib/tape/)."""
import threading
import time

import numpy as np
import pytest

import paddle_tpu.eager as eager
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.concurrency import (Channel, ChannelClosed, Go,
                                          Select, channel_recv,
                                          make_channel)


# ------------------------------ channels --------------------------------

def test_buffered_channel_fifo_and_close():
    ch = make_channel(capacity=3)
    for i in range(3):
        ch.send(i)
    ch.close()
    got = []
    while True:
        v, ok = channel_recv(ch)
        if not ok:
            break
        got.append(v)
    assert got == [0, 1, 2]
    with pytest.raises(ChannelClosed):
        ch.send(9)


def test_unbuffered_channel_rendezvous():
    ch = make_channel(capacity=0)
    order = []

    def sender():
        order.append("send-start")
        ch.send(42)
        order.append("send-done")

    g = Go(sender)
    time.sleep(0.05)
    assert "send-done" not in order  # blocked until recv
    assert ch.recv() == 42
    g.join(timeout=5)
    assert order == ["send-start", "send-done"]


def test_go_producer_consumer_pipeline():
    src = make_channel(capacity=4)
    dst = make_channel(capacity=4)

    def producer():
        for i in range(10):
            src.send(i)
        src.close()

    def worker():
        while True:
            v, ok = channel_recv(src)
            if not ok:
                break
            dst.send(v * v)
        dst.close()

    g1, g2 = Go(producer), Go(worker)
    got = []
    while True:
        v, ok = channel_recv(dst)
        if not ok:
            break
        got.append(v)
    g1.join(5)
    g2.join(5)
    assert got == [i * i for i in range(10)]


def test_go_reraises():
    def boom():
        raise ValueError("inner")

    g = Go(boom)
    with pytest.raises(ValueError, match="inner"):
        g.join(5)


def test_select_picks_ready_case():
    a = make_channel(capacity=1)
    b = make_channel(capacity=1)
    b.send("hello")
    hit = []
    Select([
        ("recv", a, lambda v: hit.append(("a", v))),
        ("recv", b, lambda v: hit.append(("b", v))),
    ]).run(timeout=2)
    assert hit == [("b", "hello")]
    # default fires when nothing is ready
    Select([
        ("recv", a, lambda v: hit.append(("a", v))),
        ("default", lambda: hit.append(("default",))),
    ]).run()
    assert hit[-1] == ("default",)
    # send case
    Select([
        ("send", a, 7, lambda: hit.append(("sent",))),
    ]).run(timeout=2)
    assert hit[-1] == ("sent",) and a.recv() == 7


def test_go_with_executor_channel_feed():
    """The intended pattern: a Go routine runs compiled steps, fed
    through a channel (reference test_concurrency-style)."""
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                x = fluid.layers.data(name="x", shape=[4],
                                      dtype="float32")
                y = fluid.layers.scale(x, scale=3.0)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        feed_ch = make_channel(capacity=2)
        out_ch = make_channel(capacity=2)

        def trainer():
            with fluid.scope_guard(scope):
                while True:
                    v, ok = channel_recv(feed_ch)
                    if not ok:
                        break
                    o, = exe.run(main, feed={"x": v}, fetch_list=[y])
                    out_ch.send(np.asarray(o))
                out_ch.close()

        g = Go(trainer)
        for i in range(3):
            feed_ch.send(np.full((1, 4), float(i), np.float32))
        feed_ch.close()
        outs = []
        while True:
            v, ok = channel_recv(out_ch)
            if not ok:
                break
            outs.append(float(v[0, 0]))
        g.join(30)
    assert outs == [0.0, 3.0, 6.0]


def test_close_releases_blocked_unbuffered_sender():
    ch = make_channel(capacity=0)
    errs = []

    def sender():
        try:
            ch.send(1)
        except ChannelClosed:
            errs.append("closed")

    g = Go(sender)
    time.sleep(0.05)
    ch.close()
    g.join(5)
    assert errs == ["closed"]


def test_select_send_on_unbuffered_with_waiting_receiver():
    ch = make_channel(capacity=0)
    got = []

    def receiver():
        got.append(ch.recv())

    g = Go(receiver)
    time.sleep(0.05)  # receiver parked in recv
    hit = []
    Select([("send", ch, 5, lambda: hit.append("sent"))]).run(timeout=2)
    g.join(5)
    assert hit == ["sent"] and got == [5]


def test_select_timeout_zero_polls_once():
    ch = make_channel(capacity=1)
    with pytest.raises(TimeoutError):
        Select([("recv", ch, lambda v: v)]).run(timeout=0)


# ------------------------------ eager tape ------------------------------

def test_eager_ops_execute_immediately():
    t = eager.Tape()
    x = eager.Variable(np.asarray([[1.0, 2.0]], np.float32))
    w = eager.Variable(np.asarray([[1.0], [1.0]], np.float32))
    out = t.run_op("mul", {"X": x, "Y": w},
                   {"x_num_col_dims": 1, "y_num_col_dims": 1})["Out"]
    np.testing.assert_allclose(out.numpy(), [[3.0]])
    assert len(t.records) == 1


def test_eager_tape_backward_matches_analytic():
    t = eager.Tape()
    rng = np.random.RandomState(0)
    xv = rng.randn(4, 3).astype(np.float32)
    wv = rng.randn(3, 2).astype(np.float32)
    bv = rng.randn(2).astype(np.float32)
    x = eager.Variable(xv)
    w = eager.Variable(wv, trainable=True)
    b = eager.Variable(bv, trainable=True)
    h = eager.fc_like(x, w, b, tape=t)
    sq = t.run_op("square", {"X": h})["Out"]
    loss = t.run_op("mean", {"X": sq})["Out"]
    t.backward(loss)
    # d mean((xw+b)^2): pin against jax.grad of the same computation
    # (matmul precision differs from numpy on some backends); the
    # analytic value 2 x^T (xw+b) / numel agrees to that precision
    import jax
    import jax.numpy as jnp

    def f(w_, b_):
        return jnp.mean(jnp.square(xv @ w_ + b_))

    gw, gb = jax.grad(f, argnums=(0, 1))(wv, bv)
    np.testing.assert_allclose(np.asarray(w.grad), np.asarray(gw),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(b.grad), np.asarray(gb),
                               rtol=1e-6)
    pre = xv @ wv + bv
    np.testing.assert_allclose(np.asarray(w.grad),
                               2 * xv.T @ pre / pre.size, rtol=2e-2,
                               atol=1e-2)


def test_eager_stochastic_ops_vary_and_stop_recording():
    t = eager.Tape(seed=3)
    x = eager.Variable(np.ones((64, 64), np.float32))
    d1 = t.run_op("dropout", {"X": x},
                  {"dropout_prob": 0.5})["Out"]
    d2 = t.run_op("dropout", {"X": x},
                  {"dropout_prob": 0.5})["Out"]
    # distinct keys per call: masks differ
    assert not np.array_equal(d1.numpy(), d2.numpy())
    with t.stop_recording():
        untaped = t.run_op("square", {"X": x})["Out"]
    assert untaped.numpy().shape == (64, 64)
    assert all(r.op_type == "dropout" for r in t.records)


def test_eager_sgd_training_loop():
    """Define-by-run training: fresh tape per step, manual sgd update."""
    rng = np.random.RandomState(1)
    w_true = rng.randn(5, 1).astype(np.float32)
    w = eager.Variable(np.zeros((5, 1), np.float32), trainable=True)
    losses = []
    for _ in range(40):
        t = eager.Tape()
        xv = rng.randn(16, 5).astype(np.float32)
        yv = xv @ w_true
        x = eager.Variable(xv)
        y = eager.Variable(yv)
        pred = eager.fc_like(x, w, tape=t)
        diff = t.run_op("elementwise_sub",
                        {"X": pred, "Y": y})["Out"]
        loss = t.run_op("mean", {"X": t.run_op(
            "square", {"X": diff})["Out"]})["Out"]
        t.backward(loss)
        w.value = w.value - 0.1 * w.grad
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 1e-3
    np.testing.assert_allclose(np.asarray(w.value), w_true, atol=0.05)
