/* C serving program for the capi test: loads a saved model dir, runs
 * one batch, prints the first output tensor as CSV on stdout.
 * Usage: capi_main <repo_path> <model_dir> <feed_name> <n> <d> [mode]
 * mode "predictor" (default) uses pd_create_predictor/pd_predictor_run;
 * mode "server" routes through the continuous-batching serving tier
 * (pd_create_server/pd_server_run) — same output contract.
 * Feeds an [n, d] float32 ramp (i*0.01). */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "paddle_capi.h"

int main(int argc, char** argv) {
  if (argc != 6 && argc != 7) {
    fprintf(stderr, "usage: %s repo model_dir feed n d [mode]\n",
            argv[0]);
    return 2;
  }
  const char* repo = argv[1];
  const char* model_dir = argv[2];
  const char* feed_name = argv[3];
  int n = atoi(argv[4]);
  int d = atoi(argv[5]);
  int use_server = argc == 7 && strcmp(argv[6], "server") == 0;

  if (pd_init(repo) != 0) {
    fprintf(stderr, "pd_init: %s\n", pd_last_error());
    return 3;
  }
  pd_predictor_t pred = NULL;
  pd_server_t server = NULL;
  if (use_server) {
    server = pd_create_server(model_dir, 0);
  } else {
    pred = pd_create_predictor(model_dir, 0);
  }
  if (pred == NULL && server == NULL) {
    fprintf(stderr, "create: %s\n", pd_last_error());
    return 4;
  }

  float* input = (float*)malloc(sizeof(float) * n * d);
  for (int i = 0; i < n * d; i++) input[i] = 0.01f * (float)i;
  int64_t shape[2];
  shape[0] = n;
  shape[1] = d;
  const char* names[1];
  const float* datas[1];
  const int64_t* shapes[1];
  int ndims[1];
  names[0] = feed_name;
  datas[0] = input;
  shapes[0] = shape;
  ndims[0] = 2;

  float* out_data[4];
  int64_t out_shapes[4][8];
  int out_ndims[4];
  int n_out = 4;
  int rc = use_server
               ? pd_server_run(server, names, datas, shapes, ndims, 1,
                               out_data, out_shapes, out_ndims, &n_out)
               : pd_predictor_run(pred, names, datas, shapes, ndims, 1,
                                  out_data, out_shapes, out_ndims,
                                  &n_out);
  if (rc != 0) {
    fprintf(stderr, "run: %s\n", pd_last_error());
    return 5;
  }
  /* second run through the same (AOT) executable — repeatability */
  float* out2[4];
  int64_t shp2[4][8];
  int nd2[4];
  int n2 = 4;
  rc = use_server
           ? pd_server_run(server, names, datas, shapes, ndims, 1, out2,
                           shp2, nd2, &n2)
           : pd_predictor_run(pred, names, datas, shapes, ndims, 1, out2,
                              shp2, nd2, &n2);
  if (rc != 0) {
    fprintf(stderr, "run2: %s\n", pd_last_error());
    return 6;
  }

  int64_t numel = 1;
  for (int i = 0; i < out_ndims[0]; i++) numel *= out_shapes[0][i];
  for (int64_t i = 0; i < numel; i++) {
    if (out_data[0][i] != out2[0][i]) {
      fprintf(stderr, "runs disagree at %lld\n", (long long)i);
      return 7;
    }
    printf(i + 1 < numel ? "%.6f," : "%.6f\n", (double)out_data[0][i]);
  }
  for (int j = 0; j < n_out; j++) pd_free(out_data[j]);
  for (int j = 0; j < n2; j++) pd_free(out2[j]);
  if (use_server) {
    pd_server_destroy(server);
  } else {
    pd_predictor_destroy(pred);
  }
  return 0;
}
