"""Disaggregated serving fleet (ISSUE 16): router placement logic,
request-id dedup, graceful drain, PredictClient reconnect-and-resend,
and the serve_fleet_bench --quick smoke — the tier-1 end-to-end drill
(Poisson load, a simulated mid-run worker kill with zero lost requests
and token parity, a torn migration named and rolled back)."""
import json
import os
import socket
import struct
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from paddle_tpu.distributed.fastwire import MAGIC
from paddle_tpu.observability import metrics
from paddle_tpu.serving.fleet import FleetWorker, LocalTransport
from paddle_tpu.serving.generative import tiny_lm
from paddle_tpu.serving.router import FleetRouter, _Member, \
    default_fleet_slos
from paddle_tpu.serving.wire import PredictClient, encode_reply

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CFG_KW = dict(vocab=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
              block_size=8, max_blocks=8, max_batch=4)


def _fleet(specs, kv_blocks=24):
    cfg, params = tiny_lm(3, **CFG_KW)
    tr = LocalTransport()
    workers = [FleetWorker(n, r, cfg, params, kv_blocks=kv_blocks,
                           warm=False, transport=tr) for n, r in specs]
    for w in workers:
        tr.register(w)
    return tr, workers


# ------------------------------------------------------- placement

def test_prefix_affinity_minimal_remap():
    """Rendezvous hashing over the token-id prefix: the same prefix
    always lands on the same prefill worker, and removing one member
    only remaps THAT member's share — every other key keeps its
    placement (no full-keyspace reshuffle on an eviction)."""
    members = [_Member("p%d" % i, "addr%d" % i, "prefill")
               for i in range(4)]
    keys = [",".join(str((7 * i + j) % 64) for j in range(8))
            for i in range(200)]
    place = {k: FleetRouter._rendezvous(k, members).name for k in keys}
    # deterministic
    assert place == {k: FleetRouter._rendezvous(k, members).name
                     for k in keys}
    survivors = members[:2] + members[3:]           # p2 evicted
    moved = 0
    for k in keys:
        now = FleetRouter._rendezvous(k, survivors).name
        if place[k] == "p2":
            assert now != "p2"
            moved += 1
        else:
            assert now == place[k], \
                "key not owned by the dead worker was remapped"
    assert moved > 0


def test_default_fleet_slos_spec():
    spec = default_fleet_slos(["d0", "d1"], ttft_p99_ms=1500.0)
    assert "serve_fleet_availability >= 1" in spec
    assert "fleet_ttft_ms_d0.p99 <= 1500" in spec
    assert "fleet_ttft_ms_d1.p99 <= 1500" in spec


# ------------------------------------------------- router behavior

def test_request_id_dedup_and_exactly_once():
    """The same req_id submitted twice returns the SAME future (one
    generation), and a fleet round-trip resolves it exactly once."""
    tr, workers = _fleet([("p0", "prefill"), ("d0", "decode")])
    router = FleetRouter(tr, [(w.name, "local:%s" % w.name, w.role)
                              for w in workers],
                         lease_s=5.0, lease_interval_s=1.0,
                         deadline_s=60.0)
    try:
        f1 = router.generate([5, 6, 7], 4, req_id="same")
        f2 = router.generate([5, 6, 7], 4, req_id="same")
        assert f1 is f2
        res = f1.result(timeout=120)
        assert len(res["tokens"]) == 4
        assert res["req_id"] == "same"
        assert metrics.counter("fleet_migrations_total").value >= 1
    finally:
        router.close()
        for w in workers:
            w.shutdown()


def test_validation_error_not_retried():
    """A non-retryable remote error (prompt token outside the vocab)
    surfaces immediately as FleetRemoteError — no burn of the attempt
    budget re-trying a request that can never succeed."""
    from paddle_tpu.serving.fleet import FleetRemoteError

    tr, workers = _fleet([("p0", "prefill"), ("d0", "decode")])
    router = FleetRouter(tr, [(w.name, "local:%s" % w.name, w.role)
                              for w in workers],
                         lease_s=5.0, lease_interval_s=1.0,
                         deadline_s=60.0)
    try:
        fut = router.generate([2, 999], 4, req_id="bad")
        with pytest.raises(FleetRemoteError, match="vocab"):
            fut.result(timeout=60)
        rec = router._recs["bad"]
        assert rec.attempts == 1, "validation error was retried"
    finally:
        router.close()
        for w in workers:
            w.shutdown()


def test_graceful_drain_stops_admission():
    """drain() removes the worker from routing and the worker refuses
    new admissions while reporting drained once quiet; requests after
    the drain run entirely on the survivor."""
    tr, workers = _fleet([("p0", "prefill"), ("d0", "decode"),
                          ("d1", "decode")])
    router = FleetRouter(tr, [(w.name, "local:%s" % w.name, w.role)
                              for w in workers],
                         lease_s=5.0, lease_interval_s=1.0,
                         deadline_s=60.0)
    try:
        ack = router.drain("d1", timeout=10.0)
        assert ack["drained"] is True
        # the drained worker refuses new admissions by name
        from paddle_tpu.serving.fleet import (M_CALL, decode_call,
                                              encode_call)
        rep = decode_call(workers[2].handle(M_CALL, memoryview(
            encode_call({"op": "generate",
                         "req": {"id": "x", "prompt": [1, 2],
                                 "max_new": 2, "eos": None}}))))
        assert rep["ok"] is False and rep["kind"] == "Draining"
        res = router.generate([4, 4, 4], 3, req_id="after").result(120)
        assert res["worker"] == "d0"
        assert len(res["tokens"]) == 3
    finally:
        router.close()
        for w in workers:
            w.shutdown()


# -------------------------------------------- wire reconnect rider

class _FlakyPredictServer:
    """Minimal fastwire Predict peer that DROPS the first connection
    right after reading a full request (torn reply), then serves
    subsequent connections properly — the reconnect-and-resend
    scenario a rolling server restart produces."""

    def __init__(self, drop_first=1):
        self._drop = drop_first
        self.requests = 0
        self._sock = socket.socket()
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(8)
        self.port = self._sock.getsockname()[1]
        threading.Thread(target=self._serve, daemon=True).start()

    def _recv(self, conn, n):
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("closed")
            buf += chunk
        return buf

    def _serve(self):
        while True:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            try:
                assert self._recv(conn, len(MAGIC)) == MAGIC
                conn.sendall(MAGIC)
                while True:
                    _, ln = struct.unpack(
                        "<BQ", self._recv(conn, 9))
                    self._recv(conn, ln)
                    self.requests += 1
                    if self._drop > 0:
                        self._drop -= 1
                        break            # close with no reply: torn
                    reply = encode_reply(
                        outputs={"y": np.arange(3, dtype=np.float32)})
                    conn.sendall(struct.pack("<Q", len(reply)) + reply)
            except (ConnectionError, OSError, AssertionError):
                pass
            finally:
                conn.close()

    def close(self):
        self._sock.close()


def test_predict_client_reconnects_and_resends():
    """A connection death mid-request is absorbed: the client backs
    off, reconnects, RESENDS, and the failure lands in the always-on
    serve_conn_failures_total counter."""
    srv = _FlakyPredictServer(drop_first=1)
    fails0 = metrics.counter("serve_conn_failures_total").value
    client = PredictClient("127.0.0.1", srv.port, timeout=10.0,
                           base_backoff=0.01, max_backoff=0.05)
    try:
        out = client.predict("m", {"x": np.zeros(2, np.float32)})
        assert list(out["y"]) == [0.0, 1.0, 2.0]
        assert srv.requests == 2, "request was not resent"
        assert metrics.counter(
            "serve_conn_failures_total").value == fails0 + 1
    finally:
        client.close()
        srv.close()


def test_predict_client_exhausts_attempts():
    """Every attempt torn -> the last socket error surfaces after
    max_attempts, with each failure counted."""
    srv = _FlakyPredictServer(drop_first=99)
    fails0 = metrics.counter("serve_conn_failures_total").value
    client = PredictClient("127.0.0.1", srv.port, timeout=10.0,
                           max_attempts=3, base_backoff=0.01,
                           max_backoff=0.02)
    try:
        with pytest.raises(OSError):
            client.predict("m", {"x": np.zeros(2, np.float32)})
        assert metrics.counter(
            "serve_conn_failures_total").value == fails0 + 3
    finally:
        client.close()
        srv.close()


# ------------------------------------------------------------ bench

def test_serve_fleet_bench_quick_smoke():
    """tools/serve_fleet_bench.py --quick must PASS outright (rc 0):
    in-process fleet, Poisson load with zero lost requests, a mid-run
    simulated kill survived with token parity + an eviction artifact +
    the availability burn alert, and a torn migration named and rolled
    back (ISSUE 16 tier-1 gate)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "serve_fleet_bench.py"),
         "--quick"],
        capture_output=True, text=True, timeout=560, env=env, cwd=REPO)
    assert proc.returncode == 0, (proc.stdout[-2000:],
                                  proc.stderr[-2000:])
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "serve_fleet_bench"
    assert rec["ok"] is True
    assert rec["kill"]["lost"] == 0
    assert rec["kill"]["parity"] is True
    assert rec["kill"]["evictions"] >= 1
    assert rec["kill"]["artifacts"], "eviction left no flight artifact"
    assert rec["slo"]["availability_alert"] is True
    assert rec["torn"]["ok"] is True
    assert rec["migrations"] > 0
