"""Control-flow front-end + lowerings: While (lax.while_loop), StaticRNN /
DynamicRNN (the scan-backed `recurrent` op), IfElse / Switch
(conditional_block -> lax.cond, split/merge_lod_tensor -> mask select),
TensorArray ops.  Reference test analogs: test_while_op.py,
test_recurrent_op.py, test_dyn_rnn.py, test_ifelse.py, test_switch.py,
test_array_read_write.py, book/test_rnn_encoder_decoder.py.
"""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
layers = fluid.layers


def test_while_sum(prog_scope, exe):
    main, startup, scope = prog_scope
    i = layers.fill_constant(shape=[1], dtype="float32", value=0.0)
    n = layers.fill_constant(shape=[1], dtype="float32", value=10.0)
    s = layers.fill_constant(shape=[1], dtype="float32", value=0.0)
    cond = layers.less_than(x=i, y=n)
    w = layers.While(cond=cond)
    with w.block():
        s2 = layers.elementwise_add(x=s, y=i)
        layers.assign(s2, s)
        layers.increment(x=i, value=1.0, in_place=True)
        layers.less_than(x=i, y=n, cond=cond)
    exe.run(startup)
    out, iv, cv = exe.run(main, fetch_list=[s, i, cond])
    assert float(out[0]) == 45.0  # 0+1+...+9
    assert float(iv[0]) == 10.0
    assert not bool(np.ravel(cv)[0])  # final cond written back


def test_while_with_array(prog_scope, exe):
    main, startup, scope = prog_scope
    i = layers.fill_constant(shape=[1], dtype="int64", value=0)
    n = layers.fill_constant(shape=[1], dtype="int64", value=5)
    x = layers.fill_constant(shape=[3], dtype="float32", value=1.0)
    arr = layers.create_array("float32", element_shape=[3], capacity=8)
    cond = layers.less_than(x=i, y=n)
    w = layers.While(cond=cond)
    with w.block():
        xi = layers.scale(x=x, scale=2.0)
        layers.array_write(xi, i, array=arr)
        layers.increment(x=i, value=1.0, in_place=True)
        layers.less_than(x=i, y=n, cond=cond)
    j = layers.fill_constant(shape=[1], dtype="int64", value=3)
    read = layers.array_read(arr, j)
    length = layers.array_length(arr)
    exe.run(startup)
    r, ln = exe.run(main, fetch_list=[read, length])
    np.testing.assert_allclose(r, np.full(3, 2.0, np.float32))
    assert int(ln[0]) == 5


def test_array_read_write_outside_loop(prog_scope, exe):
    main, startup, scope = prog_scope
    x = layers.fill_constant(shape=[2], dtype="float32", value=7.0)
    i0 = layers.fill_constant(shape=[1], dtype="int64", value=0)
    i1 = layers.fill_constant(shape=[1], dtype="int64", value=1)
    arr = layers.array_write(x, i0)
    y = layers.scale(x=x, scale=0.5)
    layers.array_write(y, i1, array=arr)
    a0 = layers.array_read(arr, i0)
    a1 = layers.array_read(arr, i1)
    exe.run(startup)
    r0, r1 = exe.run(main, fetch_list=[a0, a1])
    np.testing.assert_allclose(r0, np.full(2, 7.0, np.float32))
    np.testing.assert_allclose(r1, np.full(2, 3.5, np.float32))


def test_create_array_lazy_sizing(prog_scope, exe):
    """create_array without element_shape defers buffer sizing to the
    first out-of-loop write."""
    main, startup, scope = prog_scope
    x = layers.fill_constant(shape=[3], dtype="float32", value=4.0)
    arr = layers.create_array("float32")
    i0 = layers.fill_constant(shape=[1], dtype="int64", value=0)
    layers.array_write(x, i0, array=arr)
    r = layers.array_read(arr, i0)
    exe.run(startup)
    out, = exe.run(main, fetch_list=[r])
    np.testing.assert_allclose(out, np.full(3, 4.0, np.float32))


def test_static_rnn_accumulator(prog_scope, exe):
    """State carry without parameters: h_t = h_{t-1} + x_t."""
    main, startup, scope = prog_scope
    x = layers.data(name="x", shape=[4, 3], dtype="float32",
                    append_batch_size=True)
    rnn = layers.StaticRNN()
    with rnn.step():
        x_t = rnn.step_input(x)
        h = rnn.memory(shape=[3], batch_ref=x, init_value=0.0)
        h_new = layers.elementwise_add(x=h, y=x_t)
        rnn.update_memory(h, h_new)
        rnn.step_output(h_new)
    out = rnn()
    exe.run(startup)
    xv = np.random.RandomState(0).randn(2, 4, 3).astype(np.float32)
    o, = exe.run(main, feed={"x": xv}, fetch_list=[out])
    np.testing.assert_allclose(o, np.cumsum(xv, axis=1), rtol=1e-5)


def test_static_rnn_trains(prog_scope, exe):
    """fc-gated StaticRNN end-to-end: grads flow through scan + params."""
    main, startup, scope = prog_scope
    x = layers.data(name="x", shape=[5, 4], dtype="float32")
    y = layers.data(name="y", shape=[2], dtype="float32")
    rnn = layers.StaticRNN()
    with rnn.step():
        x_t = rnn.step_input(x)
        h = rnn.memory(shape=[8], batch_ref=x)
        h_new = layers.fc(input=[x_t, h], size=8, act="tanh",
                          bias_attr=True)
        rnn.update_memory(h, h_new)
        rnn.step_output(h_new)
    out = rnn()  # [N, T, 8]
    pred = layers.fc(input=layers.reduce_mean(out, dim=1), size=2)
    loss = layers.mean(layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe.run(startup)
    rng = np.random.RandomState(1)
    xv = rng.randn(8, 5, 4).astype(np.float32)
    yv = np.stack([xv.sum((1, 2)), xv.mean((1, 2))], 1).astype(np.float32)
    losses = []
    for _ in range(30):
        l, = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss])
        losses.append(float(np.ravel(l)[0]))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_dynamic_rnn_masked_accumulator(prog_scope, exe):
    """Ragged rows freeze past their length: final state = masked sum."""
    main, startup, scope = prog_scope
    x = layers.data(name="x", shape=[1], dtype="float32", lod_level=1)
    rnn = layers.DynamicRNN()
    with rnn.block():
        x_t = rnn.step_input(x)
        h = rnn.memory(shape=[1], batch_ref=x, init_value=0.0)
        h_new = layers.elementwise_add(x=h, y=x_t)
        rnn.update_memory(h, h_new)
        rnn.output(h_new)
    out = rnn()
    final = rnn.final_states[0]
    exe.run(startup)
    feeder = fluid.DataFeeder([x], program=main)
    rows = [[1.0, 2.0, 3.0], [4.0, 5.0], [6.0]]
    feed = feeder.feed([(r,) for r in rows])
    f, = exe.run(main, feed=feed, fetch_list=[final])
    np.testing.assert_allclose(np.ravel(f), [6.0, 9.0, 6.0], rtol=1e-6)


def test_ifelse_trains(prog_scope, exe):
    """Per-row branch + merge; gradient flows through the select."""
    main, startup, scope = prog_scope
    x = layers.data(name="x", shape=[4], dtype="float32")
    y = layers.data(name="y", shape=[1], dtype="float32")
    zero = layers.fill_constant(shape=[1], dtype="float32", value=0.0)
    row_sum = layers.reduce_sum(x, dim=1, keep_dim=True)  # [N, 1]
    cond = layers.greater_than(row_sum, zero)
    ie = layers.IfElse(cond)
    with ie.true_block():
        xt = ie.input(x)
        ie.output(layers.fc(input=xt, size=1,
                            param_attr=fluid.ParamAttr(name="w_shared")))
    with ie.false_block():
        xf = ie.input(x)
        ie.output(layers.scale(
            layers.fc(input=xf, size=1,
                      param_attr=fluid.ParamAttr(name="w_shared")),
            scale=-1.0))
    pred = ie()
    loss = layers.mean(layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    exe.run(startup)
    rng = np.random.RandomState(0)
    xv = rng.randn(32, 4).astype(np.float32)
    yv = np.abs(xv.sum(1, keepdims=True)).astype(np.float32)
    losses = []
    for _ in range(40):
        l, = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss])
        losses.append(float(np.ravel(l)[0]))
    assert losses[-1] < losses[0] * 0.3, (losses[0], losses[-1])


def test_switch_piecewise(prog_scope, exe):
    main, startup, scope = prog_scope
    step = layers.data(name="step", shape=[1], dtype="float32",
                       append_batch_size=False)
    lr = layers.fill_constant(shape=[1], dtype="float32", value=0.0)
    b1 = layers.fill_constant(shape=[1], dtype="float32", value=5.0)
    b2 = layers.fill_constant(shape=[1], dtype="float32", value=10.0)
    sw = layers.Switch()
    with sw.case(layers.less_than(step, b1)):
        v = layers.fill_constant(shape=[1], dtype="float32", value=1.0)
        layers.assign(v, lr)
    with sw.case(layers.less_than(step, b2)):
        v = layers.fill_constant(shape=[1], dtype="float32", value=0.5)
        layers.assign(v, lr)
    with sw.default():
        v = layers.fill_constant(shape=[1], dtype="float32", value=0.1)
        layers.assign(v, lr)
    exe.run(startup)
    for sv, expect in [(2.0, 1.0), (7.0, 0.5), (20.0, 0.1)]:
        out, = exe.run(main, feed={"step": np.array([sv], np.float32)},
                       fetch_list=[lr])
        np.testing.assert_allclose(float(out[0]), expect, rtol=1e-6,
                                   err_msg=str(sv))


def test_conditional_block_scalar(prog_scope, exe):
    main, startup, scope = prog_scope
    flag = layers.data(name="flag", shape=[1], dtype="float32",
                       append_batch_size=False)
    zero = layers.fill_constant(shape=[1], dtype="float32", value=0.0)
    out = layers.fill_constant(shape=[1], dtype="float32", value=-1.0)
    cond = layers.greater_than(flag, zero)
    cb = layers.ConditionalBlock([cond])
    with cb.block():
        v = layers.scale(x=flag, scale=10.0)
        layers.assign(v, out)
    exe.run(startup)
    r, = exe.run(main, feed={"flag": np.array([3.0], np.float32)},
                 fetch_list=[out])
    assert float(r[0]) == 30.0
    r, = exe.run(main, feed={"flag": np.array([-3.0], np.float32)},
                 fetch_list=[out])
    assert float(r[0]) == -1.0  # untouched prior value


def test_lod_tensor_array_round_trip(prog_scope, exe):
    main, startup, scope = prog_scope
    x = layers.data(name="x", shape=[2], dtype="float32", lod_level=1)
    table = layers.lod_rank_table(x)
    arr = layers.lod_tensor_to_array(x, table)
    back = layers.array_to_lod_tensor(arr, table)
    mlen = layers.max_sequence_len(table)
    exe.run(startup)
    feeder = fluid.DataFeeder([x], program=main)
    rows = [[[1.0, 1.5], [2.0, 2.5]], [[3.0, 3.5]]]
    feed = feeder.feed([(r,) for r in rows])
    b, m = exe.run(main, feed=feed, fetch_list=[back, mlen])
    # padded [N=2, T(padded), 2]; row values survive the round trip
    np.testing.assert_allclose(b[0, :2], [[1.0, 1.5], [2.0, 2.5]])
    np.testing.assert_allclose(b[1, :1], [[3.0, 3.5]])
    assert int(m[0]) >= 2


def test_rnn_encoder_decoder_book_model(prog_scope, exe):
    """Book model (test_rnn_encoder_decoder.py): DynamicRNN-decoder
    seq2seq trains on the copy task."""
    from paddle_tpu.models.rnn_encoder_decoder import get_model
    main, startup, scope = prog_scope
    loss, feeds, _ = get_model(src_dict_dim=40, trg_dict_dim=40,
                               emb_dim=24, hidden_dim=24,
                               learning_rate=5e-3)
    exe.run(startup)
    feeder = fluid.DataFeeder(feeds, program=main)
    rng = np.random.RandomState(0)
    ls = []
    for _ in range(60):
        batch = []
        for _ in range(8):
            L = rng.randint(3, 8)
            src = rng.randint(2, 38, L).tolist()
            # identity task (predict the current word): learnable without
            # attention, unlike the copy task, and exercises the same
            # grad path through the scanned decoder + encoder context
            batch.append((src, src, src))
        l, = exe.run(main, feed=feeder.feed(batch), fetch_list=[loss])
        ls.append(float(np.ravel(l)[0]))
    assert ls[-1] < ls[0] - 1.0, (ls[0], ls[-1])


def test_array_read_propagates_element_shape():
    """fc on a value read from a TensorArray inside a While body must
    size its parameter from the element shape — array_write/create_array
    record it on the array var and array_read copies it (shape
    inference cannot evaluate the runtime TensorArray)."""
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.core.scope import Scope

    L = fluid.layers
    main, startup = fluid.Program(), fluid.Program()
    scope = Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                counter = L.fill_constant([1], "int64", 0)
                limit = L.fill_constant([1], "int64", 3)
                x0 = L.fill_constant([2, 6], "float32", 1.0)
                arr = L.array_write(x0, i=counter, capacity=5)
                cond = L.less_than(x=counter, y=limit)
                w = L.While(cond=cond)
                with w.block():
                    cur = L.array_read(arr, i=counter)
                    assert tuple(cur.shape) == (2, 6)
                    h = L.fc(cur, size=3, bias_attr=False,
                             param_attr=fluid.ParamAttr(name="aw"))
                    L.increment(counter)
                    L.array_write(h, i=counter, array=arr)
                    L.less_than(x=counter, y=limit, cond=cond)
        # parameter sized from the ELEMENT shape, not a scalar
        assert tuple(main.global_block().var("aw").shape) == (6, 3)
        # created-with-element_shape arrays propagate too
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            with fluid.unique_name.guard():
                a2 = L.create_array("float32", element_shape=[4, 8])
                i0 = L.fill_constant([1], "int64", 0)
                r = L.array_read(a2, i=i0)
                assert tuple(r.shape) == (4, 8)
