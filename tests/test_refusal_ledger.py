"""The op-parity tail is CLOSED: every deliberate NotImplementedError
guard in the v2 layer surface (paddle_tpu/v2/layers_ext.py) must have a
justification entry in tools/tpu_optest.py's REFUSALS ledger, and every
ledger entry must still correspond to an in-tree guard.  Either direction
failing means the tail grew (new refusal without justification) or rotted
(justification for a guard that no longer exists).

The whole-symbol refusals are additionally exercised behaviorally: they
raise NotImplementedError whose message names the supported route.
"""
import ast
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LAYERS_EXT = os.path.join(REPO, "paddle_tpu", "v2", "layers_ext.py")
OPTEST = os.path.join(REPO, "tools", "tpu_optest.py")


def _load_ledger():
    """The REFUSALS dict from tools/tpu_optest.py without importing the
    module (module import builds the full op-spec table)."""
    tree = ast.parse(open(OPTEST).read())
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "REFUSALS"
                for t in node.targets):
            ns = {}
            exec(compile(ast.Module(body=[node], type_ignores=[]),
                         OPTEST, "exec"), {"dict": dict}, ns)
            return ns["REFUSALS"]
    raise AssertionError("tools/tpu_optest.py has no REFUSALS ledger")


def _raises_nie(node):
    """Does this function body (including nested defs) raise
    NotImplementedError?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Raise):
            exc = sub.exc
            if isinstance(exc, ast.Call):
                exc = exc.func
            if isinstance(exc, ast.Name) and \
                    exc.id == "NotImplementedError":
                return True
    return False


def _scan_guards():
    """Public symbols of layers_ext.py that refuse something: top-level
    defs containing a NotImplementedError raise, plus assignments built
    from the _refusal() factory."""
    tree = ast.parse(open(LAYERS_EXT).read())
    guards = set()
    for node in tree.body:
        if isinstance(node, ast.FunctionDef) and \
                not node.name.startswith("_") and _raises_nie(node):
            guards.add(node.name)
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call) and \
                isinstance(node.value.func, ast.Name) and \
                node.value.func.id == "_refusal":
            for t in node.targets:
                if isinstance(t, ast.Name):
                    guards.add(t.id)
    return guards


def test_every_guard_is_justified():
    ledger = _load_ledger()
    guards = _scan_guards()
    unjustified = guards - set(ledger)
    assert not unjustified, (
        "NotImplementedError guards in v2/layers_ext.py with no entry in "
        "tools/tpu_optest.py REFUSALS (justify them or port them): %s"
        % sorted(unjustified))


def test_every_ledger_entry_still_guards():
    ledger = _load_ledger()
    guards = _scan_guards()
    stale = set(ledger) - guards
    assert not stale, (
        "REFUSALS entries whose guard no longer exists in "
        "v2/layers_ext.py (the surface was ported — delete the ledger "
        "entry): %s" % sorted(stale))


def test_ledger_entries_are_complete():
    for name, ent in _load_ledger().items():
        assert ent.get("kind") in ("refusal", "partial"), name
        assert ent.get("reason"), "%s: missing justification" % name
        assert ent.get("use"), "%s: missing supported route" % name
        if ent["kind"] == "partial":
            assert ent.get("param"), \
                "%s: partial guard must name the refused argument" % name


def test_tail_counts():
    ledger = _load_ledger()
    refusals = [n for n, e in ledger.items() if e["kind"] == "refusal"]
    partials = [n for n, e in ledger.items() if e["kind"] == "partial"]
    assert len(refusals) == 3, refusals
    # 17 guard raise-sites grouped per symbol (multi-arg guards like
    # nce's three share one entry)
    assert len(partials) >= 13, partials


@pytest.mark.parametrize("symbol,args", [
    ("get_output", ("input", "arg")),
    ("cross_entropy_over_beam", (["beam"],)),
    ("SubsequenceInput", ("input",)),
])
def test_whole_symbol_refusals_raise_with_route(symbol, args):
    from paddle_tpu.v2 import layers_ext
    fn = getattr(layers_ext, symbol)
    with pytest.raises(NotImplementedError) as ei:
        fn(*args)
    msg = str(ei.value)
    assert "not ported" in msg
    # the message must hand the caller a supported route
    assert any(k in msg for k in ("use ", "fluid.layers", "layer.",
                                  "seq_reshape", ".state")), msg
