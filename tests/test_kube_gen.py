"""Deployment manifest generator (reference benchmark/fluid/
kube_gen_job.py + kube_templates): pserver/trainer/master manifests
carry the PADDLE_* env contract the Trainer consumes."""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(args):
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "kube_gen_job.py")]
        + args, capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    return [json.loads(doc) for doc in out.stdout.split("---") if
            doc.strip()]


def _envmap(doc):
    c = doc["spec"]["template"]["spec"]["containers"][0]
    return {e["name"]: e.get("value") for e in c["env"]}


def test_pserver_mode_manifests():
    docs = _run(["--jobname", "j1", "--pservers", "2", "--trainers", "4",
                 "--pserver-ips", "10.0.0.1,10.0.0.2", "--tpu", "4",
                 "--master"])
    kinds = [d["kind"] for d in docs]
    assert kinds == ["ReplicaSet", "Job", "ReplicaSet"]
    ps, tr, master = docs
    assert ps["spec"]["replicas"] == 2
    # ReplicaSet pod templates only allow Always
    assert ps["spec"]["template"]["spec"]["restartPolicy"] == "Always"
    assert _envmap(ps)["PADDLE_TRAINING_ROLE"] == "PSERVER"
    assert tr["spec"]["completions"] == 4
    env = _envmap(tr)
    assert env["PADDLE_TRAINING_ROLE"] == "TRAINER"
    assert env["PADDLE_PSERVER_IPS"] == "10.0.0.1,10.0.0.2"
    res = tr["spec"]["template"]["spec"]["containers"][0]["resources"]
    assert res["limits"]["google.com/tpu"] == "4"
    assert master["spec"]["replicas"] == 2  # active + standby (HA)


def test_nccl2_mode_endpoints_and_discovery():
    docs = _run(["--jobname", "j2", "--trainers", "2",
                 "--disttype", "nccl2",
                 "--discovery-root", "/shared/disc"])
    svc, tr = docs
    # headless Service + pod subdomain make the per-pod endpoint DNS
    # names actually resolvable
    assert svc["kind"] == "Service"
    assert svc["spec"]["clusterIP"] == "None"
    assert svc["metadata"]["name"] == "j2-trainer"
    assert tr["spec"]["template"]["spec"]["subdomain"] == "j2-trainer"
    assert tr["spec"]["template"]["spec"]["restartPolicy"] == "Never"
    env = _envmap(tr)
    eps = env["PADDLE_TRAINER_ENDPOINTS"].split(",")
    assert len(eps) == 2 and eps[0].startswith("j2-trainer-0.")
    assert env["PADDLE_DISCOVERY_ROOT"] == "/shared/disc"
