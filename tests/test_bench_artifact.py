"""bench.py artifact robustness (ISSUE 4 satellite, VERDICT r5 #1):
a dead accelerator tunnel must yield a FAST, explicit JSON error line
— never an rc:124 with an empty stdout — and the wall-budget machinery
that guards the stream probe / secondary bench must actually degrade
to errors instead of hanging."""
import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_wall_budget_degrades_to_timeout(tmp_path):
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)
    # point the handler's flight dump at tmp_path — with no dump dir
    # configured it falls back to the system temp dir BY DESIGN (a
    # bare hung run must still leave its who-was-waiting artifact),
    # but repeated test runs must not litter /tmp
    from paddle_tpu.core.flags import FLAGS
    old = FLAGS.telemetry_dump_dir
    FLAGS.telemetry_dump_dir = str(tmp_path)
    t0 = time.time()
    try:
        with pytest.raises(TimeoutError, match="wall budget"):
            with bench._wall_budget(1, "probe"):
                time.sleep(30)
    finally:
        FLAGS.telemetry_dump_dir = old
    assert time.time() - t0 < 5
    # and the alarm is cancelled afterwards
    with bench._wall_budget(1, "ok"):
        pass
    time.sleep(1.2)


def test_layout_bench_artifact_fields():
    """ISSUE 5: a BENCH_LAYOUT=NHWC run's headline JSON must be a
    self-describing experiment — data_format, fused_stages and xla_flags
    fields present — and the emit-immediately contract must hold (the
    partial line carries them too).  Tiny depth-8 model keeps the CPU
    compile fast."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_LAYOUT="NHWC",
               BENCH_DEPTH="8", BENCH_BATCH="4", BENCH_ITERS="2",
               BENCH_FAKE="1", BENCH_LIVENESS_TIMEOUT="30",
               BENCH_SECONDARY="0", BENCH_STREAM_PROBE="0")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [json.loads(l) for l in proc.stdout.splitlines()
             if l.strip().startswith("{")]
    assert len(lines) >= 2, proc.stdout
    partial, final = lines[0], lines[-1]
    assert partial.get("partial") is True
    for rec in (partial, final):
        assert rec["data_format"] == "NHWC", rec
        assert rec["fused_stages"] > 0, rec
        assert "xla_flags" in rec, rec
        assert rec["depth"] == 8, rec
    assert final["value"] > 0
    # ISSUE 6 satellite: per-step percentiles, sourced from the
    # telemetry histogram, ride the BENCH JSON (p50 <= p90 <= p99)
    for rec in (partial, final):
        assert rec["step_ms_p50"] > 0, rec
        assert rec["step_ms_p50"] <= rec["step_ms_p90"] \
            <= rec["step_ms_p99"], rec


def test_dead_backend_yields_fast_json_error_line(tmp_path):
    """Simulated unreachable backend: bench.py exits in seconds with a
    valid JSON line carrying an explicit ``error`` field — and (ISSUE 6)
    a flight-recorder artifact naming what was blocked, so the next
    dead tunnel is a diagnosis, not an rc:124."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_FAKE_DEAD="1",
               BENCH_LIVENESS_TIMEOUT="3",
               FLAGS_telemetry_dump_dir=str(tmp_path))
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
    elapsed = time.time() - t0
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert elapsed < 90
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert lines, "no artifact line on stdout"
    rec = json.loads(lines[-1])
    assert "error" in rec and "backend unreachable" in rec["error"]
    assert rec["metric"].endswith("_train")
    # the flight-recorder artifact exists and names the blocked op
    assert "flight_recorder" in rec, rec
    assert os.path.exists(rec["flight_recorder"])
    flight = json.loads(open(rec["flight_recorder"]).read())
    assert flight["reason"] == "backend_unreachable"
    assert flight["blocked"]["op"] == "liveness_probe"
    assert "metrics" in flight


def test_wall_budget_expiry_leaves_flight_artifact(tmp_path):
    """Simulated wall-budget expiry (the BENCH_FAKE_DEAD-style degrade
    path): the SIGALRM handler dumps a flight record BEFORE raising,
    and the TimeoutError names its path."""
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)
    from paddle_tpu.core.flags import FLAGS

    old = FLAGS.telemetry_dump_dir
    FLAGS.telemetry_dump_dir = str(tmp_path)
    try:
        with pytest.raises(TimeoutError, match="flight recorder:"):
            with bench._wall_budget(1, "probe"):
                time.sleep(30)
    finally:
        FLAGS.telemetry_dump_dir = old
    import glob
    dumps = glob.glob(str(tmp_path / "flight_*.json"))
    assert dumps, "wall-budget expiry left no flight artifact"
    rec = json.loads(open(dumps[0]).read())
    assert rec["reason"].startswith("wall_budget:")
    assert rec["blocked"]["op"] == "probe"
