"""bench.py artifact robustness (ISSUE 4 satellite, VERDICT r5 #1):
a dead accelerator tunnel must yield a FAST, explicit JSON error line
— never an rc:124 with an empty stdout — and the wall-budget machinery
that guards the stream probe / secondary bench must actually degrade
to errors instead of hanging."""
import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_wall_budget_degrades_to_timeout():
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)
    t0 = time.time()
    with pytest.raises(TimeoutError, match="wall budget"):
        with bench._wall_budget(1, "probe"):
            time.sleep(30)
    assert time.time() - t0 < 5
    # and the alarm is cancelled afterwards
    with bench._wall_budget(1, "ok"):
        pass
    time.sleep(1.2)


def test_layout_bench_artifact_fields():
    """ISSUE 5: a BENCH_LAYOUT=NHWC run's headline JSON must be a
    self-describing experiment — data_format, fused_stages and xla_flags
    fields present — and the emit-immediately contract must hold (the
    partial line carries them too).  Tiny depth-8 model keeps the CPU
    compile fast."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_LAYOUT="NHWC",
               BENCH_DEPTH="8", BENCH_BATCH="4", BENCH_ITERS="2",
               BENCH_FAKE="1", BENCH_LIVENESS_TIMEOUT="30",
               BENCH_SECONDARY="0", BENCH_STREAM_PROBE="0")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [json.loads(l) for l in proc.stdout.splitlines()
             if l.strip().startswith("{")]
    assert len(lines) >= 2, proc.stdout
    partial, final = lines[0], lines[-1]
    assert partial.get("partial") is True
    for rec in (partial, final):
        assert rec["data_format"] == "NHWC", rec
        assert rec["fused_stages"] > 0, rec
        assert "xla_flags" in rec, rec
        assert rec["depth"] == 8, rec
    assert final["value"] > 0


def test_dead_backend_yields_fast_json_error_line():
    """Simulated unreachable backend: bench.py exits in seconds with a
    valid JSON line carrying an explicit ``error`` field."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_FAKE_DEAD="1",
               BENCH_LIVENESS_TIMEOUT="3")
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
    elapsed = time.time() - t0
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert elapsed < 90
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert lines, "no artifact line on stdout"
    rec = json.loads(lines[-1])
    assert "error" in rec and "backend unreachable" in rec["error"]
    assert rec["metric"].endswith("_train")
