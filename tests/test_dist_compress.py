"""Compressed fastwire frames, bounded staleness, and hierarchical
aggregation (ISSUE 10).

In-process contracts (real VariableServer + RPCClient over real
sockets, no spawned trainers), mirroring test_pserver_dataplane.py:

- per-codec round-trip bounds (fp16 bit-exact on representables, int8
  bounded by the chunk scale, topk exact on the kept entries, rows
  exact ids);
- error-feedback convergence: N SGD steps under int8/topk track the
  uncompressed trajectory;
- wire-version negotiation: a server without WireVersion pins the
  endpoint to raw frames and training still works;
- replay/duplicate idempotence holds verbatim on compressed frames
  (the replay cache stores POST-codec values);
- bounded staleness: k=0 is bit-exact lockstep, k=1 lets the trainer
  run exactly one round ahead and drains pending rounds at shutdown;
- hierarchical aggregation: the group-local mean equals the flat sync
  mean, duplicate sparse rows merge, and the pserver sees one sender.
"""
import threading
import time

import numpy as np
import pytest

from paddle_tpu.core.scope import Scope
from paddle_tpu.core.selected_rows import SelectedRows
from paddle_tpu.distributed import compress as czip
from paddle_tpu.distributed.resilience import FLAGS, install_faults
from paddle_tpu.distributed.rpc import (RPCClient, VariableServer,
                                        _dec_tensor, _enc_tensor)


@pytest.fixture(autouse=True)
def _clean():
    install_faults("")
    prev = (FLAGS.dist_compress, FLAGS.dist_staleness,
            FLAGS.dist_hier_local, FLAGS.dist_topk_ratio)
    yield
    install_faults("")
    (FLAGS.dist_compress, FLAGS.dist_staleness,
     FLAGS.dist_hier_local, FLAGS.dist_topk_ratio) = prev
    RPCClient.reset()


# ---------------------------------------------------------------------------
# codec round-trip bounds
# ---------------------------------------------------------------------------

def test_fp16_bit_exact_on_representable_values():
    # every value below is exactly representable in fp16
    a = (np.arange(1024, dtype=np.float32) - 512) * 0.25
    c = czip.compress(a, "fp16")
    assert isinstance(c, czip.Compressed)
    assert c.nbytes == a.nbytes // 2
    np.testing.assert_array_equal(czip.decompress(c), a)


def test_int8_error_bounded_by_chunk_scale():
    rng = np.random.RandomState(3)
    a = rng.randn(3, 3000).astype(np.float32) * 5.0
    c = czip.compress(a, "int8")
    d = czip.decompress(c)
    assert d.shape == a.shape and d.dtype == a.dtype
    # per-chunk scale = absmax/127; rounding error <= scale/2
    bound = np.abs(a).max() / 127.0 * 0.5 + 1e-7
    assert float(np.abs(d - a).max()) <= bound
    assert c.nbytes < a.nbytes / 3.5   # >= 3.5x smaller


def test_topk_keeps_exactly_the_largest_entries():
    rng = np.random.RandomState(4)
    a = rng.randn(4000).astype(np.float32)
    c = czip.compress(a, "topk", topk_ratio=0.01)
    d = czip.decompress(c)
    k = max(1, int(round(0.01 * a.size)))
    kept = np.argsort(np.abs(a))[-k:]
    np.testing.assert_array_equal(d[np.sort(kept)], a[np.sort(kept)])
    mask = np.ones(a.size, bool)
    mask[kept] = False
    assert not d[mask].any()
    assert c.nbytes < a.nbytes / 10    # >= 10x smaller at 1%


def test_rows_codec_ids_exact_values_bounded():
    rng = np.random.RandomState(5)
    rows = rng.randint(0, 10**7, 700).astype(np.int64)
    vals = rng.randn(700, 8).astype(np.float32)
    sr = SelectedRows(rows, vals, 10**7)
    c = czip.compress(sr, "int8")
    d = czip.decompress(c)
    order = np.argsort(rows, kind="stable")
    np.testing.assert_array_equal(np.asarray(d.rows), rows[order])
    per_row_bound = (np.abs(vals).max(axis=1, keepdims=True) / 127.0
                     * 0.5 + 1e-7)
    assert np.all(np.abs(np.asarray(d.values) - vals[order])
                  <= per_row_bound[order])
    assert d.height == sr.height


def test_tiny_and_integer_tensors_ship_raw():
    small = np.ones(7, np.float32)
    assert czip.compress(small, "int8") is small
    ints = np.arange(4096, dtype=np.int64)
    assert czip.compress(ints, "topk") is ints


def test_wire_frame_roundtrip_compressed():
    rng = np.random.RandomState(6)
    a = rng.randn(2048).astype(np.float32)
    payload = _enc_tensor("g", czip.compress(a, "int8"), 42)
    name, val, extra = _dec_tensor(payload)
    assert name == "g" and extra == 42
    assert val.shape == a.shape
    assert float(np.abs(val - a).max()) <= np.abs(a).max() / 127.0


# ---------------------------------------------------------------------------
# live-server harness
# ---------------------------------------------------------------------------

def _sgd_server(scope, grads_to_params, fanin, lr=1.0, **kw):
    items = list(grads_to_params.items())

    def apply_block(bid):
        g, p = items[bid]
        gv = scope.find_var(g)
        pv = np.array(np.asarray(scope.find_var(p)), copy=True)
        if isinstance(gv, SelectedRows):
            np.subtract.at(pv, np.asarray(gv.rows),
                           lr * np.asarray(gv.values))
        else:
            pv -= lr * np.asarray(gv)
        scope.set(p, pv)

    srv = VariableServer(
        scope, {g: i for i, (g, _) in enumerate(items)}, apply_block,
        fanin=fanin, grad_params={g: (p,) for g, p in items}, **kw)
    port = srv.start("127.0.0.1:0")
    return srv, "127.0.0.1:%d" % port


def _quadratic_descent(mode, steps=12, lr=0.05, topk_ratio=None):
    """Minimize ||w||^2 via the real wire: grad = 2w shipped per round
    under ``mode``; returns the loss trajectory."""
    FLAGS.dist_compress = mode
    if topk_ratio is not None:
        FLAGS.dist_topk_ratio = topk_ratio
    scope = Scope()
    rng = np.random.RandomState(11)
    w0 = rng.randn(40, 40).astype(np.float32)
    scope.set("p", w0.copy())
    srv, ep = _sgd_server(scope, {"g": "p"}, fanin=1, lr=lr)
    RPCClient.reset()
    cli = RPCClient.instance()
    losses = []
    try:
        w = w0.copy()
        for r in range(steps):
            losses.append(float((w * w).sum()))
            cli.send_vars([(ep, "g", 2.0 * w)])
            cli.send_barrier([ep])
            got, = cli.get_vars([(ep, "p")])
            w = np.array(np.asarray(got), copy=True)
    finally:
        cli.send_complete([ep])
        srv.wait()
    FLAGS.dist_compress = ""
    return np.array(losses)


def test_error_feedback_convergence_parity_int8():
    """N SGD steps under int8 with error feedback must track the
    uncompressed trajectory: same monotone descent, final loss within
    15% (the EF residual cancels quantization bias — without it int8
    stalls an order of magnitude higher)."""
    ref = _quadratic_descent("")
    got = _quadratic_descent("int8")
    assert got[-1] < got[0] * 0.35          # it actually descends
    assert got[-1] <= ref[-1] * 1.15 + 1e-3  # and tracks the exact path


def test_error_feedback_convergence_parity_topk():
    """Top-k at 20% with error feedback over a longer horizon: every
    coordinate's update eventually ships (the residual carries what the
    sparsifier dropped), so the loss keeps descending toward the exact
    trajectory instead of freezing the never-selected coordinates."""
    steps = 30
    ref = _quadratic_descent("", steps=steps)
    got = _quadratic_descent("topk", steps=steps, topk_ratio=0.2)
    assert got[-1] < got[0] * 0.05          # deep descent, not a stall
    assert got[-1] <= ref[-1] * 4 + 1e-2    # within sight of exact SGD


def test_error_feedback_residual_accumulates():
    """The trainer-side residual is what cancels the bias: after a
    compressed send, the client holds exactly (grad - decoded)."""
    FLAGS.dist_compress = "topk"
    scope = Scope()
    scope.set("p", np.zeros(2048, np.float32))
    srv, ep = _sgd_server(scope, {"g": "p"}, fanin=1)
    RPCClient.reset()
    cli = RPCClient.instance()
    try:
        rng = np.random.RandomState(7)
        g = rng.randn(2048).astype(np.float32)
        cli.send_vars([(ep, "g", g)])
        res = cli._residuals[(ep, "g")]
        # residual + what the server received == the full gradient
        cli.send_barrier([ep])
        got, = cli.get_vars([(ep, "p")])
        np.testing.assert_allclose(-np.asarray(got) + res, g,
                                   rtol=1e-6, atol=1e-6)
    finally:
        cli.send_complete([ep])
        srv.wait()


def test_compressed_replay_and_duplicates_are_idempotent():
    """PR 1's dedup/replay semantics hold verbatim on compressed
    frames: a duplicated batch and a full round replay ship the SAME
    cached post-codec bytes and the sync mean counts each trainer
    once."""
    FLAGS.dist_compress = "int8"
    scope = Scope()
    scope.set("p1", np.zeros(1024, np.float32))
    srv, ep = _sgd_server(scope, {"g1": "p1"}, fanin=2)
    RPCClient.reset()
    a, b = RPCClient.instance(), RPCClient()
    try:
        ga = np.full(1024, 2.0, np.float32)
        gb = np.full(1024, 4.0, np.float32)
        a.send_vars([(ep, "g1", ga)])
        a.send_vars([(ep, "g1", ga)])     # duplicate batch
        a._replay_round(ep)               # full replay after "reconnect"
        b.send_vars([(ep, "g1", gb)])
        ts = [threading.Thread(target=c.send_barrier, args=([ep],))
              for c in (a, b)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        p1, = a.get_vars([(ep, "p1")])
        # constant grads quantize exactly: mean(2, 4) applied once
        np.testing.assert_allclose(np.asarray(p1),
                                   np.full(1024, -3.0))
    finally:
        a.send_complete([ep])
        b.send_complete([ep])
        srv.wait()


# ---------------------------------------------------------------------------
# wire-version negotiation
# ---------------------------------------------------------------------------

class _OldWireServer(VariableServer):
    """A pre-v2 server: the WireVersion method errors like an
    unimplemented handler, and a kind-2 frame would be undecodable —
    the client must pin the endpoint to raw frames."""

    def _wire_version(self, req, ctx=None):
        raise RuntimeError("Method not found!")


def test_negotiation_falls_back_to_raw_against_old_server():
    FLAGS.dist_compress = "int8"
    scope = Scope()
    scope.set("p1", np.zeros(1024, np.float32))
    items = [("g1", "p1")]

    def apply_block(bid):
        scope.set("p1", np.asarray(scope.find_var("p1"))
                  - np.asarray(scope.find_var("g1")))

    srv = _OldWireServer(scope, {"g1": 0}, apply_block, fanin=1,
                         grad_params={"g1": ("p1",)})
    ep = "127.0.0.1:%d" % srv.start("127.0.0.1:0")
    RPCClient.reset()
    cli = RPCClient.instance()
    try:
        g = np.linspace(-1, 1, 1024).astype(np.float32)
        cli.send_vars([(ep, "g1", g)])
        assert cli.wire_version(ep) == 1   # pinned to raw
        cli.send_barrier([ep])
        p1, = cli.get_vars([(ep, "p1")])
        # raw frames: BIT-exact, no quantization anywhere
        np.testing.assert_array_equal(np.asarray(p1), -g)
        # no compressed bytes were recorded for this client
        raw, seq = cli._recorded(ep, "g1", round_=0)
        assert isinstance(raw, np.ndarray)
    finally:
        cli.send_complete([ep])
        srv.wait()


def test_new_server_advertises_v2_and_codecs():
    scope = Scope()
    scope.set("p1", np.zeros(4, np.float32))
    srv, ep = _sgd_server(scope, {"g1": "p1"}, fanin=1)
    RPCClient.reset()
    cli = RPCClient.instance()
    try:
        assert cli.wire_version(ep) == 2
    finally:
        cli.send_complete([ep])
        srv.wait()


# ---------------------------------------------------------------------------
# bounded staleness
# ---------------------------------------------------------------------------

def _run_rounds(staleness, rounds=3, compress=""):
    """Two clients x N sync rounds against a 2-shard server; returns
    the fetched params per round (the test_pserver_dataplane harness
    with a staleness knob)."""
    FLAGS.dist_compress = compress
    FLAGS.dist_staleness = staleness
    scope = Scope()
    scope.set("p1", np.zeros((8, 4), np.float32))
    scope.set("p2", np.zeros((50, 8), np.float32))
    srv, ep = _sgd_server(scope, {"g1": "p1", "g2": "p2"}, fanin=2,
                          staleness=staleness)
    RPCClient.reset()
    a, b = RPCClient.instance(), RPCClient()
    fetched = []
    try:
        for r in range(rounds):
            for cli, k in ((a, 1.0), (b, 3.0)):
                rows = np.arange(0, 10, 2, dtype=np.int64) + r
                vals = np.full((5, 8), k, np.float32)
                cli.send_vars([
                    (ep, "g1", np.full((8, 4), k * (r + 1), np.float32)),
                    (ep, "g2", SelectedRows(rows, vals, 50)),
                ])
            ts = [threading.Thread(target=c.send_barrier, args=([ep],))
                  for c in (a, b)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            got = a.get_vars([(ep, "p1"), (ep, "p2")])
            fetched.append([np.array(np.asarray(x), copy=True)
                            for x in got])
    finally:
        a.send_complete([ep])
        b.send_complete([ep])
        srv.wait()
    FLAGS.dist_compress = ""
    FLAGS.dist_staleness = 0
    return fetched


def test_staleness_zero_bit_exact_with_lockstep_sync():
    """k=0 (the default) must be BIT-exact with the k-unaware PR 4
    wire — same pending/barrier bookkeeping, same aggregation order,
    compressed-off."""
    k0 = _run_rounds(0)
    # exact closed form: mean grad of round r is 2*(r+1) for p1
    expect = 0.0
    for r, (p1, _) in enumerate(k0):
        expect -= 2.0 * (r + 1)
        np.testing.assert_array_equal(p1, np.full((8, 4), expect,
                                                  np.float32))


def test_staleness_k1_runs_ahead_and_converges():
    """k=1: barrier acks stop gating on the in-flight apply, but the
    final state after the shutdown drain matches lockstep exactly (the
    same grads all applied, rounds in order)."""
    FLAGS.dist_staleness = 1
    scope = Scope()
    scope.set("p1", np.zeros(4, np.float32))
    applied = []

    def apply_block(bid):
        time.sleep(0.3)
        applied.append(time.time())
        scope.set("p1", np.asarray(scope.find_var("p1"))
                  - np.asarray(scope.find_var("g1")))

    srv = VariableServer(scope, {"g1": 0}, apply_block, fanin=1,
                         grad_params={"g1": ("p1",)}, staleness=1)
    ep = "127.0.0.1:%d" % srv.start("127.0.0.1:0")
    RPCClient.reset()
    cli = RPCClient.instance()
    try:
        cli.send_vars([(ep, "g1", np.ones(4, np.float32))])
        t0 = time.time()
        cli.send_barrier([ep])
        ahead = time.time() - t0
        cli.send_vars([(ep, "g1", np.ones(4, np.float32))])
        t0 = time.time()
        cli.send_barrier([ep])
        bounded = time.time() - t0
    finally:
        cli.send_complete([ep])
        srv.wait()
    assert ahead < 0.25, "round 0 ack should not wait for the apply"
    assert bounded > 0.2, "round 1 ack must wait for round 0 (k=1)"
    assert len(applied) == 2
    np.testing.assert_array_equal(np.asarray(scope.find_var("p1")),
                                  np.full(4, -2.0, np.float32))


def test_staleness_gap_gauge_and_status():
    from paddle_tpu.observability import metrics as obs

    FLAGS.dist_staleness = 2
    scope = Scope()
    scope.set("p1", np.zeros(4, np.float32))
    srv, ep = _sgd_server(scope, {"g1": "p1"}, fanin=2, staleness=2)
    RPCClient.reset()
    a, b = RPCClient.instance(), RPCClient()
    try:
        # a runs two rounds ahead; b stays at round 0 (no barrier)
        for _ in range(2):
            a.send_vars([(ep, "g1", np.ones(4, np.float32))])
            a.send_barrier([ep])
        b.send_vars([(ep, "g1", np.ones(4, np.float32))])
        b.send_barrier([ep])
        st = a.barrier_status(ep)
        assert st["staleness"] == 2
        # both clients share this process's label, so assert the raw
        # per-sender rounds: a is one round ahead of b
        assert sorted(srv._barrier_rounds.values()) == [0, 1]
        assert obs.snapshot()["pserver_staleness_gap"]["value"] >= 1
    finally:
        a.send_complete([ep])
        b.send_complete([ep])
        srv.wait()


def test_stale_complete_does_not_drop_slow_peers_grads():
    """Regression (review): a fast trainer's SendComplete must not let
    its persistent high-water barriers stand in for a slower LIVE
    peer — the pent-up rounds wait for the live peer's own barriers,
    and its grads count (bounded staleness delays grads <= k, never
    discards them)."""
    FLAGS.dist_staleness = 2
    scope = Scope()
    scope.set("p1", np.zeros(4, np.float32))
    srv, ep = _sgd_server(scope, {"g1": "p1"}, fanin=2, staleness=2)
    RPCClient.reset()
    a, b = RPCClient.instance(), RPCClient()
    try:
        for r in range(2):     # A runs 2 rounds ahead (k=2: acks free)
            a.send_vars([(ep, "g1", np.full(4, 2.0, np.float32))])
            a.send_barrier([ep])
        a.send_complete([ep])
        time.sleep(0.3)        # the buggy path would rush both rounds
        for r in range(2):     # B catches up; its grads must count
            b.send_vars([(ep, "g1", np.full(4, 4.0, np.float32))])
            b.send_barrier([ep])
    finally:
        b.send_complete([ep])
        srv.wait()
    # mean(2, 4) applied twice — NOT 2.0-only rounds
    np.testing.assert_allclose(np.asarray(scope.find_var("p1")),
                               np.full(4, -6.0))


def test_stale_completed_sender_never_counts_toward_live_quorum():
    """fanin=3 variant (review): with A completed and B barriered, the
    round must keep waiting for C — A's persistent high-water barrier
    plus B must NOT satisfy the 2-live quorum, or C's grads would be
    dedup-dropped when they arrive."""
    FLAGS.dist_staleness = 2
    scope = Scope()
    scope.set("p1", np.zeros(4, np.float32))
    srv, ep = _sgd_server(scope, {"g1": "p1"}, fanin=3, staleness=2)
    RPCClient.reset()
    a, b, c = RPCClient.instance(), RPCClient(), RPCClient()
    try:
        for cli, v in ((a, 3.0), (b, 6.0)):
            cli.send_vars([(ep, "g1", np.full(4, v, np.float32))])
            cli.send_barrier([ep])
        a.send_complete([ep])
        time.sleep(0.4)
        assert srv._applied_round == 0      # round 0 waits for C
        c.send_vars([(ep, "g1", np.full(4, 9.0, np.float32))])
        c.send_barrier([ep])
    finally:
        b.send_complete([ep])
        c.send_complete([ep])
        srv.wait()
    # mean(3, 6, 9) applied once — C's grads counted, nothing dropped
    np.testing.assert_allclose(np.asarray(scope.find_var("p1")),
                               np.full(4, -6.0))


def test_hier_retry_after_eager_ship_is_idempotent():
    """Regression (review): a follower frame RETRIED after the eager
    upload already shipped must not resurrect the entry — flush would
    otherwise upload a 1-contribution 'mean' over the true group
    mean."""
    from paddle_tpu.distributed import hierarchy

    shipped = []
    agg = hierarchy.HostAggregator(2, 0, upload=shipped.extend)
    try:
        g_lead = np.full(4, 2.0, np.float32)
        g_foll = np.full(4, 4.0, np.float32)
        agg.stash(0, "ep0", "g", g_lead, 100)
        agg.stash(0, "ep0", "g", g_foll, 101)   # completes -> ships
        agg._barriers[0] = {101}
        # the follower's conn dropped mid-reply and it resent BEFORE
        # the leader's barrier-time flush:
        agg.stash(0, "ep0", "g", g_foll, 101)
        stragglers = agg.flush(0, deadline=5.0)
        assert stragglers == []                 # duplicate ignored
        assert len(shipped) == 1
        np.testing.assert_allclose(shipped[0][2], np.full(4, 3.0))
    finally:
        agg.stop()


def test_staleness_compressed_matches_lockstep_compressed():
    """k=1 + int8 over constant grads (exactly representable): the
    per-round fetches may trail by one round, but the final fetched
    params of the last round match lockstep's trajectory values."""
    k0 = _run_rounds(0, compress="int8")
    k1 = _run_rounds(1, compress="int8")
    # lockstep trajectory values per round
    vals0 = [p1[0, 0] for p1, _ in k0]
    # k=1 fetches are each some prefix value of the same trajectory
    traj = [0.0] + [float(v) for v in vals0]
    for p1, _ in k1:
        assert float(p1[0, 0]) in traj


# ---------------------------------------------------------------------------
# hierarchical aggregation
# ---------------------------------------------------------------------------

@pytest.fixture
def _hier(monkeypatch):
    """Route hierarchy.role() through a thread-local so one process can
    host a leader thread and a follower thread (the real deployment
    puts them in separate processes with PADDLE_TRAINER_ID set)."""
    from paddle_tpu.distributed import hierarchy

    tl = threading.local()
    monkeypatch.setattr(hierarchy, "role",
                        lambda: hierarchy.Role(tl.tid, 2))
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    FLAGS.dist_hier_port = s.getsockname()[1]
    s.close()
    yield tl
    hierarchy.reset()
    FLAGS.dist_hier_local = 0


def test_hier_group_mean_matches_flat_sync(_hier):
    """2 trainers through the leader vs 2 trainers flat: identical
    final params (2-term mean addition is commutative, so the leader's
    local mean == the server's flat mean bit-for-bit)."""
    flat = _run_rounds(0)            # hier still off for the reference
    from paddle_tpu.distributed import hierarchy
    hierarchy.reset()
    FLAGS.dist_hier_local = 2        # now route through the leader

    scope = Scope()
    scope.set("p1", np.zeros((8, 4), np.float32))
    scope.set("p2", np.zeros((50, 8), np.float32))
    srv, ep = _sgd_server(scope, {"g1": "p1", "g2": "p2"}, fanin=1)
    RPCClient.reset()
    leader, follower = RPCClient.instance(), RPCClient()
    fetched = []
    errs = []

    def trainer(cli, tid, k):
        _hier.tid = tid
        try:
            for r in range(3):
                rows = np.arange(0, 10, 2, dtype=np.int64) + r
                vals = np.full((5, 8), k, np.float32)
                cli.send_vars([
                    (ep, "g1", np.full((8, 4), k * (r + 1),
                                       np.float32)),
                    (ep, "g2", SelectedRows(rows, vals, 50)),
                ])
                cli.send_barrier([ep])
                if tid == 0:
                    got = cli.get_vars([(ep, "p1"), (ep, "p2")])
                    fetched.append([np.array(np.asarray(x), copy=True)
                                    for x in got])
            cli.send_complete([ep])
        except Exception as e:   # pragma: no cover - surfaced below
            errs.append(e)

    ts = [threading.Thread(target=trainer, args=(leader, 0, 1.0)),
          threading.Thread(target=trainer, args=(follower, 1, 3.0))]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    srv.wait()
    assert not errs, errs
    assert len(fetched) == 3
    for (fp1, fp2), (hp1, hp2) in zip(flat, fetched):
        np.testing.assert_allclose(fp1, hp1, rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(fp2, hp2, rtol=1e-6, atol=1e-7)


def test_hier_sparse_rows_merge_duplicates(_hier):
    """Both group members touching the SAME rows: the leader's upload
    merges them (one row on the wire, summed values)."""
    from paddle_tpu.distributed import hierarchy

    agg = hierarchy.HostAggregator(2, FLAGS.dist_hier_port + 1)
    try:
        rows = np.array([3, 1, 3], np.int64)
        vals = np.ones((3, 4), np.float32)
        agg.stash(0, "ep0", "g", SelectedRows(rows, vals, 10), 100)
        agg.stash(0, "ep0", "g", SelectedRows(rows, 2 * vals, 10), 101)
        agg._barriers[0] = {101}
        (ep0, name, merged), = agg.flush(0, deadline=5.0)
        assert ep0 == "ep0" and name == "g"
        np.testing.assert_array_equal(np.asarray(merged.rows),
                                      np.array([1, 3]))
        # row 1 once per sender, row 3 twice per sender; mean over 2
        np.testing.assert_allclose(
            np.asarray(merged.values),
            np.stack([np.full(4, 1.5), np.full(4, 3.0)]))
    finally:
        agg.stop()


def test_send_merge_gates_on_duplicate_ratio():
    """Outbound SelectedRows merging is worth a sort only on
    head-heavy traffic: near-uniform ids pass through UNTOUCHED (the
    static row count keeps the pserver's jitted optimize block on one
    compiled shape — regression: unconditional merging made every
    round a recompile), duplicate-heavy ids merge by summation."""
    from paddle_tpu.ops.distributed_ops import _merge_dup_rows

    uniform = SelectedRows(np.arange(8192, dtype=np.int64),
                           np.ones((8192, 4), np.float32), 10**6)
    assert _merge_dup_rows(uniform) is uniform
    hot = SelectedRows(np.zeros(4096, np.int64) + 7,
                       np.ones((4096, 4), np.float32), 10**6)
    merged = _merge_dup_rows(hot)
    np.testing.assert_array_equal(np.asarray(merged.rows), [7])
    np.testing.assert_allclose(np.asarray(merged.values),
                               np.full((1, 4), 4096.0))


def test_bucket_sparse_grad_pads_to_power_of_two():
    """Variable-length merged grads bucket to the next power of 2 in
    the serve loop (sentinel rows == height, zero values — dropped by
    the scatter), so the jit compiles O(log K) shapes."""
    from paddle_tpu.ops.distributed_ops import _bucket_sparse_grad

    scope = Scope()
    scope.set("g", SelectedRows(np.arange(5, dtype=np.int64),
                                np.ones((5, 3), np.float32), 100))
    _bucket_sparse_grad(scope, "g")
    out = scope.find_var("g")
    assert np.asarray(out.rows).shape == (8,)
    np.testing.assert_array_equal(np.asarray(out.rows)[5:],
                                  [100, 100, 100])
    assert not np.asarray(out.values)[5:].any()
    # exact power of two: untouched
    scope.set("g2", SelectedRows(np.arange(8, dtype=np.int64),
                                 np.ones((8, 3), np.float32), 100))
    before = scope.find_var("g2")
    _bucket_sparse_grad(scope, "g2")
    assert scope.find_var("g2") is before


def test_trace_report_wire_rollup_rows():
    """export.wire_rows: the ISSUE 10 counters surface per process
    dump (compression ratio, codec time, fastwire traffic, staleness
    gap) — what `tools/trace_report.py --wire` prints."""
    from paddle_tpu.observability import export

    dump = {"label": "trainer0", "metrics": {
        "wire_bytes_raw_total": {"value": 4000},
        "wire_bytes_compressed_total": {"value": 1000},
        "compress_ms": {"p50": 1.5, "p99": 3.0, "count": 7},
        "fastwire_bytes_sent_total": {"value": 123},
        "fastwire_bytes_recv_total": {"value": 456},
        "pserver_staleness_gap": {"value": 2},
        "rpc_round_replays_total": {"value": 1},
        "pserver_dedup_drops_total": {"value": 4},
    }}
    row, = export.wire_rows([dump])
    assert row["compression_ratio"] == 4.0
    assert row["compress_ms_p99"] == 3.0
    assert row["staleness_gap"] == 2
    table = export.format_wire_table([row])
    assert "trainer0" in table and "4.00" in table


def test_transpiler_fanin_is_group_count():
    import paddle_tpu.fluid as fluid

    FLAGS.dist_hier_local = 2
    try:
        main, startup = fluid.Program(), fluid.Program()
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            with fluid.program_guard(main, startup):
                with fluid.unique_name.guard():
                    x = fluid.layers.data(name="x", shape=[4],
                                          dtype="float32")
                    y = fluid.layers.data(name="y", shape=[1],
                                          dtype="float32")
                    pred = fluid.layers.fc(input=x, size=1)
                    loss = fluid.layers.mean(
                        fluid.layers.square_error_cost(input=pred,
                                                       label=y))
                    fluid.optimizer.SGD(
                        learning_rate=0.1).minimize(loss)
        t = fluid.DistributeTranspiler()
        t.transpile(trainer_id=0, program=main, startup_program=startup,
                    pservers="127.0.0.1:0", trainers=4, sync_mode=True)
        ps = t.get_pserver_program("127.0.0.1:0")
        ls = [op for op in ps.global_block().desc.ops
              if op.type == "listen_and_serv"][0]
        assert ls.attr("Fanin") == 2      # 4 trainers / 2 per group
        assert ls.attr("staleness") == 0
        # uneven grouping is refused
        FLAGS.dist_hier_local = 3
        with pytest.raises(ValueError, match="divide"):
            fluid.DistributeTranspiler().transpile(
                trainer_id=0, program=main, startup_program=startup,
                pservers="127.0.0.1:0", trainers=4, sync_mode=True)
    finally:
        FLAGS.dist_hier_local = 0
