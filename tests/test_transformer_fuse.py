"""FuseTransformerBlockPass end to end on the transformer LM: the
fused program (fused_qkv_matmul / fused_matmul_bias_act /
fused_add_ln + their explicit grad ops) must train identically to the
unfused build — parity pinned at fp32 losses <=2e-4 / params <=4e-7
over 3 Adam steps, AMP at bf16 tolerance (ISSUE 7 acceptance)."""
import collections

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.core.flags import FLAGS
from paddle_tpu.core.scope import Scope
from paddle_tpu.models import transformer

VOCAB, SEQ, DM, HEADS, LAYERS, DFF = 101, 16, 32, 4, 2, 64


def _run(fuse, params=None, steps=3, amp=False):
    main, startup = fluid.Program(), fluid.Program()
    scope = Scope()
    with fluid.scope_guard(scope):
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                avg_cost, (src, label), _ = transformer.get_model(
                    vocab_size=VOCAB, seq_len=SEQ, d_model=DM,
                    n_head=HEADS, n_layers=LAYERS, d_ff=DFF,
                    fuse_transformer=fuse)
        if amp:
            fluid.transpiler.Float16Transpiler().transpile(main)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        if params is not None:
            for n, v in params.items():
                scope.set(n, v)
        snap = {n: np.asarray(scope.find_var(n)).copy()
                for n in scope.local_var_names()}
        rng = np.random.RandomState(0)
        feed = {src.name: rng.randint(0, VOCAB, (2, SEQ)).astype(
            np.int64),
            label.name: rng.randint(0, VOCAB, (2, SEQ, 1)).astype(
                np.int64)}
        losses = []
        for _ in range(steps):
            l, = exe.run(main, feed=feed, fetch_list=[avg_cost])
            losses.append(float(np.asarray(l).ravel()[0]))
        post = {n: np.asarray(scope.find_var(n)).copy()
                for n in scope.local_var_names()}
    ops = [o.type for o in main.desc.blocks[0].ops]
    return losses, snap, post, ops


def test_fused_transformer_training_parity():
    base_losses, params, base_post, base_ops = _run(False)
    losses, _, post, ops = _run(True, params=dict(params))
    counts = collections.Counter(ops)
    # per layer: 1 QKV triple, 3 epilogue matmuls (out-proj, mlp
    # up+act, mlp down) + the lm_head, 2 residual+LN seams
    assert counts["fused_qkv_matmul"] == LAYERS
    assert counts["fused_matmul_bias_act"] == 3 * LAYERS + 1
    assert counts["fused_add_ln"] == 2 * LAYERS
    assert counts["mul"] == 0
    # the first LN stays unfused (its input is the broadcast emb+pos
    # add, not a same-shape residual seam)
    assert counts["layer_norm"] == 1
    assert counts["fused_qkv_matmul_grad"] == LAYERS
    assert counts["fused_matmul_bias_act_grad"] == 3 * LAYERS + 1
    assert counts["fused_add_ln_grad"] == 2 * LAYERS
    # ISSUE 7 acceptance: fp32 losses <=2e-4 over 3 steps
    np.testing.assert_allclose(base_losses, losses, rtol=2e-4,
                               atol=2e-4)
    # params <=4e-7 (covers every explicit grad lowering end to end,
    # Adam state included)
    for n, v in base_post.items():
        w = post.get(n)
        if w is None or v.dtype.kind != "f" or v.shape != w.shape:
            continue
        np.testing.assert_allclose(v, w, rtol=1e-4, atol=4e-7,
                                   err_msg=n)


def test_fused_transformer_amp_parity():
    """Under the bf16 Float16Transpiler the fused ops take the same
    autocast slots as the unfused chain (AMP_WHITE matmuls, pass-through
    LN) — bf16 tolerance."""
    base_losses, params, _, _ = _run(False, amp=True)
    losses, _, _, ops = _run(True, params=dict(params), amp=True)
    assert "fused_matmul_bias_act" in ops
    np.testing.assert_allclose(base_losses, losses, rtol=2e-2,
                               atol=2e-2)


def test_flag_gating():
    """FLAGS.transformer_fuse default-off: get_model builds the unfused
    program unless the flag (or the explicit argument) says otherwise."""
    assert FLAGS.transformer_fuse is False
    _, _, _, ops = _run(None)       # None -> FLAGS (off)
    assert not any(o.startswith("fused_") for o in ops)
    FLAGS.transformer_fuse = True
    try:
        _, _, _, ops = _run(None)
        assert any(o == "fused_qkv_matmul" for o in ops)
    finally:
        FLAGS.transformer_fuse = False


def test_residual_goes_to_add_ln_not_matmul():
    """The pre-LN policy: a residual add feeding a layer_norm belongs
    to fused_add_ln (statistics from the VMEM sum); the matmul
    epilogue only absorbs residual adds that do NOT feed an LN."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            transformer.get_model(
                vocab_size=VOCAB, seq_len=SEQ, d_model=DM,
                n_head=HEADS, n_layers=LAYERS, d_ff=DFF,
                fuse_transformer=True)
    for op in main.desc.blocks[0].ops:
        if op.type == "fused_matmul_bias_act":
            assert not op.inputs.get("Residual"), (
                "residual absorbed into a matmul whose sum feeds an "
                "LN seam")
        if op.type == "fused_add_ln":
            # the residual stream reads the sum: it must stay an output
            assert op.outputs.get("Sum")


def test_fused_program_structure_survives_sum_consumers():
    """fused_add_ln's Sum output is the residual stream: the NEXT
    block's seam consumes it, so each fused_add_ln (except the final
    one) has its Sum read downstream."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            transformer.get_model(
                vocab_size=VOCAB, seq_len=SEQ, d_model=DM,
                n_head=HEADS, n_layers=LAYERS, d_ff=DFF,
                fuse_transformer=True)
    block = main.desc.blocks[0]
    sums = [op.output("Sum")[0] for op in block.ops
            if op.type == "fused_add_ln"]
    consumed = set()
    for op in block.ops:
        for n in op.input_arg_names():
            consumed.add(n)
    # all but the last seam's sum feed downstream ops (forward alone;
    # grads consume the rest)
    assert all(s in consumed for s in sums[:-1])


@pytest.mark.slow
def test_fused_transformer_cpu_step_wall():
    """ISSUE 7 acceptance: fused block stages measurably reduce the
    transformer step wall on the CPU-tier microbench vs unfused.
    Measured at the PROFILE_r07.md shape (bs4 seq256 d256 L2, ~4-6%
    on this rig); asserted with margin (best-of-3 fused must not be
    slower than best-of-3 unfused by more than 2%)."""
    import time

    def bench(fuse, iters=12):
        main, startup = fluid.Program(), fluid.Program()
        scope = Scope()
        with fluid.scope_guard(scope):
            with fluid.program_guard(main, startup):
                with fluid.unique_name.guard():
                    avg_cost, (src, label), _ = transformer.get_model(
                        vocab_size=1024, seq_len=256, d_model=256,
                        n_head=8, n_layers=2, d_ff=1024,
                        fuse_transformer=fuse)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            rng = np.random.RandomState(0)
            feed = {src.name: rng.randint(0, 1024, (4, 256)).astype(
                np.int64),
                label.name: rng.randint(0, 1024, (4, 256, 1)).astype(
                    np.int64)}
            for _ in range(2):
                exe.run(main, feed=feed, fetch_list=[avg_cost])
            t0 = time.time()
            loss = None
            for _ in range(iters):
                loss, = exe.run(main, feed=feed, fetch_list=[avg_cost],
                                return_numpy=False)
            np.asarray(loss)
            return (time.time() - t0) / iters

    unfused = min(bench(False) for _ in range(3))
    fused = min(bench(True) for _ in range(3))
    assert fused <= unfused * 1.02, (
        "fused transformer step slower than unfused on CPU: "
        "%.2f ms vs %.2f ms" % (fused * 1e3, unfused * 1e3))


def test_fused_transformer_mfu_bench_fields():
    """bench.py's transformer JSON must report fused_stages > 0 with
    per-category counts when BENCH_FUSED_TRANSFORMER=1 (acceptance) —
    checked here at the program level the bench reads them from."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            transformer.get_model(
                vocab_size=VOCAB, seq_len=SEQ, d_model=DM,
                n_head=HEADS, n_layers=LAYERS, d_ff=DFF,
                fuse_transformer=True)
    fwd_fused = [op.type for op in main.desc.blocks[0].ops
                 if op.type.startswith("fused_") and
                 not op.type.endswith("_grad")]
    assert len(fwd_fused) == 6 * LAYERS + 1
