"""Pure graph tests of the DistributeTranspiler (reference
test_dist_transpiler.py / test_simple_dist_transpiler.py: transpile, then
assert on the resulting trainer/pserver op lists — no processes)."""
import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.transpiler import slice_variable


def _build_net():
    x = fluid.layers.data(name="x", shape=[1000], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    y_predict = fluid.layers.fc(input=x, size=1000, act=None,
                                param_attr=fluid.ParamAttr(name="fc_w"),
                                bias_attr=fluid.ParamAttr(name="fc_b"))
    loss = fluid.layers.mean(
        fluid.layers.square_error_cost(input=y_predict, label=y))
    sgd = fluid.optimizer.SGD(learning_rate=0.1)
    sgd.minimize(loss)
    return loss


def test_slice_variable():
    blocks = slice_variable([("w", [1000, 100]), ("tiny", [8])],
                            slice_count=4, min_block_size=8192)
    assert len(blocks["tiny"]) == 1 and blocks["tiny"][0].block_id == -1
    ws = blocks["w"]
    assert len(ws) == 4
    assert sum(b.rows for b in ws) == 1000
    assert ws[0].name == "w.block0" and ws[0].shape == [250, 100]
    offs = [b.row_start for b in ws]
    assert offs == [0, 250, 500, 750]


def test_transpile_trainer_and_pserver_programs(prog_scope):
    main, startup, scope = prog_scope
    _build_net()
    t = fluid.DistributeTranspiler()
    t.transpile(trainer_id=0, program=main, startup_program=startup,
                pservers="127.0.0.1:6174,127.0.0.1:6175", trainers=2)

    trainer = t.get_trainer_program()
    types = [op.type for op in trainer.global_block().ops]
    # optimize ops moved out; send/recv chain appended
    assert "sgd" not in types
    assert types.count("send") == 2          # fc_w grad + fc_b grad
    assert types.count("recv") == 2
    assert types.index("send_barrier") < types.index("recv")
    assert types[-1] == "fetch_barrier"

    eps = t.pserver_endpoints
    total_opt_blocks = 0
    served = []
    for ep in eps:
        ps = t.get_pserver_program(ep)
        ps_types = [op.type for op in ps.global_block().ops]
        assert ps_types == ["listen_and_serv"]
        n_sub = len(ps.blocks) - 1
        total_opt_blocks += n_sub
        for b in ps.blocks[1:]:
            assert [op.type for op in b.ops] == ["sgd"]
        served.append(n_sub)
        # startup program initializes this server's param slices
        su = t.get_startup_program(ep, ps)
        su_types = [op.type for op in su.global_block().ops]
        assert any(tp == "slice" for tp in su_types) or n_sub == 0
    # fc_w [1000,1000] slices over both pservers; fc_b [1000] fits one
    # block; every (param block) gets exactly one optimize sub-block
    assert total_opt_blocks == sum(
        len(t.param_blocks[p]) for p, _ in t.params_grads)
    assert all(n > 0 for n in served)


def test_transpile_unsliced_small_var(prog_scope):
    main, startup, scope = prog_scope
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    p = fluid.layers.fc(input=x, size=1, act=None)
    loss = fluid.layers.mean(fluid.layers.square_error_cost(p, y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    t = fluid.DistributeTranspiler()
    t.transpile(trainer_id=0, program=main, startup_program=startup,
                pservers="127.0.0.1:6176", trainers=1)
    for blocks in t.param_blocks.values():
        assert len(blocks) == 1 and blocks[0].block_id == -1
    ps = t.get_pserver_program("127.0.0.1:6176")
    assert len(ps.blocks) == 3  # two params -> two optimize sub-blocks
