"""Weaver deterministic-schedule explorer: exhaustive clean proofs on
HEAD, planted historical races found + minimized + replayed, and the
rawlock source checker that keeps the interception layer from eroding.

Each planted race is a real bug this repo shipped and fixed:

  pserver/kstale        — PR 10 donated-params window: a trainer read
                          the param snapshot outside the apply fence.
  kv_pool/double_free   — PR 12 preemption/finish tie both freeing the
                          same KV blocks.
  kv_refcount/dropped_decref — ISSUE 19 pre-refcount prefix release:
                          two holders' read-modify-write of an external
                          holder count loses a decref and leaks the
                          shared block.
  migrate_kv/dup_migration — PR 16 MigrateKV retry double-admitting a
                          request id (check/register TOCTOU).
  router_evict/double_complete — PR 16 lease eviction completing a
                          request the original worker also completed.
"""
import json
import os
import subprocess
import sys
import threading

import pytest

from paddle_tpu.analysis import checkers, weaver
from paddle_tpu.core import sanitizer as san
from paddle_tpu.core.flags import FLAGS

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# bound 2 keeps every scenario tree in the low hundreds of schedules —
# exhaustive in a couple of seconds, comfortably inside tier-1 budget.
QUICK = dict(preemption_bound=2, max_schedules=1600)

PLANTED = [
    ("pserver", "kstale"),
    ("kv_pool", "double_free"),
    ("kv_refcount", "dropped_decref"),
    ("migrate_kv", "dup_migration"),
    ("router_evict", "double_complete"),
]


@pytest.fixture(autouse=True)
def _restore_sanitizer():
    old = FLAGS.sanitizer
    yield
    FLAGS.sanitizer = old


# ---------------------------------------------------------------------------
# registry + exhaustive clean HEAD
# ---------------------------------------------------------------------------

def test_scenario_registry():
    names = dict(weaver.list_scenarios())
    for s, p in PLANTED:
        assert s in names
        assert p in names[s]
        assert p in weaver.PLANTS[s]


@pytest.mark.parametrize("scenario", [s for s, _ in PLANTED])
def test_head_explores_clean_exhaustively(scenario):
    stats, rec = weaver.explore(scenario, plant=None, **QUICK)
    assert rec is None, (
        "HEAD %s has a schedule failure: %r sites=%s"
        % (scenario, rec and rec.failure, rec and rec.sites))
    assert stats.exhausted, (
        "%s did not exhaust within %d schedules (explored=%d)"
        % (scenario, QUICK["max_schedules"], stats.explored))
    assert stats.failures == 0
    assert stats.explored > 1           # the tree is non-trivial
    assert stats.pruned >= 0


# ---------------------------------------------------------------------------
# planted historical races: found, minimized, deterministic, clean@HEAD
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("scenario,plant", PLANTED)
def test_planted_race_found_minimized_and_replayed(scenario, plant):
    stats, rec = weaver.explore(scenario, plant=plant, **QUICK)
    assert rec is not None, "planted %s/%s not found" % (scenario, plant)
    assert rec.failure is not None

    best, runs = weaver.minimize(
        scenario, rec.trace, rec.failure_type, plant=plant,
        preemption_bound=QUICK["preemption_bound"])
    assert len(best) <= len(rec.trace)
    assert runs > 0

    # minimized trace still reproduces the same failure type...
    r1 = weaver.run_schedule(scenario, trace=best, plant=plant,
                             preemption_bound=QUICK["preemption_bound"])
    assert r1.failure_type == rec.failure_type
    assert r1.sites, "failure must name racing sites"
    # ...deterministically (bit-identical schedule + oplog)...
    r2 = weaver.run_schedule(scenario, trace=best, plant=plant,
                             preemption_bound=QUICK["preemption_bound"])
    assert r2.failure_type == r1.failure_type
    assert r2.trace == r1.trace
    assert r2.oplog == r1.oplog
    # ...while the SAME schedule on HEAD is clean (the fix holds).
    head = weaver.run_schedule(scenario, trace=best, plant=None,
                               preemption_bound=QUICK["preemption_bound"])
    assert head.failure is None, (
        "HEAD fails under the minimized %s schedule: %r"
        % (scenario, head.failure))


def test_minimized_double_free_is_one_decision():
    """Pin the canonical minimized schedule: the KV double-free needs
    exactly one non-default decision (schedule the preemptor into the
    finisher's check/free gap)."""
    stats, rec = weaver.explore("kv_pool", plant="double_free", **QUICK)
    best, _ = weaver.minimize(
        "kv_pool", rec.trace, rec.failure_type, plant="double_free",
        preemption_bound=QUICK["preemption_bound"])
    assert best == [1]
    assert rec.failure_type == "BufferLifetimeError"


def test_planted_sites_name_real_code():
    """Racing sites must point at scenario/production lines, never
    weaver internals."""
    _, rec = weaver.explore("kv_pool", plant="double_free", **QUICK)
    joined = " ".join(rec.sites)
    assert "weaver.py" not in joined
    assert "kv_cache.py" in joined or "scen.kv" in joined


def test_artifact_roundtrip(tmp_path):
    stats, rec = weaver.explore("migrate_kv", plant="dup_migration",
                                **QUICK)
    best, _ = weaver.minimize(
        "migrate_kv", rec.trace, rec.failure_type, plant="dup_migration",
        preemption_bound=QUICK["preemption_bound"])
    mrec = weaver.run_schedule("migrate_kv", trace=best,
                               plant="dup_migration",
                               preemption_bound=QUICK["preemption_bound"])
    path = weaver.write_artifact(
        str(tmp_path), "migrate_kv", "dup_migration", best, mrec,
        stats=stats, minimized_from=len(rec.trace),
        preemption_bound=QUICK["preemption_bound"])
    assert os.path.basename(path).startswith("weaver_migrate_kv_")

    with open(path) as f:
        payload = json.load(f)
    assert payload["kind"] == "weaver"
    assert payload["failure"]["sites"]
    assert payload["preemption_bound"] == QUICK["preemption_bound"]

    reproduced, rrec, rpayload = weaver.replay_artifact(path)
    assert reproduced
    assert rrec.failure_type == payload["failure"]["type"]


# ---------------------------------------------------------------------------
# sanitizer wrapper contract (make_event / make_condition / weaver mode)
# ---------------------------------------------------------------------------

def test_make_event_condition_plain_when_off():
    FLAGS.sanitizer = "off"
    ev = san.make_event("t.ev")
    assert isinstance(ev, threading.Event)
    cv = san.make_condition("t.cv")
    assert isinstance(cv, threading.Condition)


def test_weaver_mode_without_active_weaver_degrades_to_plain():
    FLAGS.sanitizer = "weaver"
    ev = san.make_event("t.ev2")
    assert isinstance(ev, threading.Event)
    lk = san.make_lock("t.lk2")
    with lk:
        pass
    cv = san.make_condition("t.cv2")
    with cv:
        cv.notify_all()


def test_instrumented_lock_backs_a_condition():
    """threading.Condition probes _is_owned()/acquire(0) on its lock —
    the locks-mode InstrumentedLock must satisfy that contract."""
    FLAGS.sanitizer = "locks"
    lk = san.make_lock("t.locks.cv")
    cv = threading.Condition(lk)
    with cv:
        assert not cv.wait(timeout=0.01)
    ev = san.make_event("t.locks.ev")     # locks mode: plain event
    assert isinstance(ev, threading.Event)


def test_adopted_modules_use_wrappers():
    """The fleet/router/batcher planes must construct through the
    sanitizer so weaver mode can intercept them."""
    FLAGS.sanitizer = "locks"
    from paddle_tpu.serving import batcher, router
    q = batcher.RequestQueue()
    assert isinstance(q._cv, threading.Condition)
    rec = router._Rec("r0", [1, 2], 4, 0)
    assert isinstance(rec.lock, san.InstrumentedLock)
    assert isinstance(rec.done_evt, threading.Event)


# ---------------------------------------------------------------------------
# rawlock source checker
# ---------------------------------------------------------------------------

def _scan_tree(tmp_path, rel, source):
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(source)
    return checkers.run_source_checkers(
        [str(tmp_path)], root=str(tmp_path), checkers=["rawlock"])


def test_rawlock_flags_raw_constructs(tmp_path):
    diags = _scan_tree(
        tmp_path, "paddle_tpu/serving/foo.py",
        "import threading\n"
        "L = threading.Lock()\n"
        "E = threading.Event()\n")
    assert len(diags) == 2
    assert all(d.checker == "rawlock" for d in diags)
    assert "make_lock" in diags[0].suggestion
    assert "make_event" in diags[1].suggestion


def test_rawlock_respects_pragma_and_scope(tmp_path):
    diags = _scan_tree(
        tmp_path, "paddle_tpu/serving/bar.py",
        "import threading\n"
        "L = threading.Lock()  # rawlock: ok - bootstrap\n")
    assert diags == []
    diags = _scan_tree(
        tmp_path, "paddle_tpu/core/baz.py",
        "import threading\nL = threading.Lock()\n")
    assert diags == []                    # out of scope


def test_rawlock_allowlist(tmp_path):
    diags = _scan_tree(
        tmp_path, "paddle_tpu/serving/kv_cache.py",
        "import threading\n_LIVE_LOCK = threading.Lock()\n")
    assert diags == []                    # serving/kv_cache.py::_LIVE_LOCK


def test_repo_distributed_and_serving_are_rawlock_clean():
    diags = checkers.run_source_checkers(
        [os.path.join(REPO, "paddle_tpu", "serving"),
         os.path.join(REPO, "paddle_tpu", "distributed")],
        root=REPO, checkers=["rawlock"])
    assert diags == [], "\n".join(d.format() for d in diags)


def test_rawlock_registered_in_source_registry():
    assert "rawlock" in checkers.SOURCE_CHECKERS
    assert "rawlock" not in checkers.CHECKERS   # IR registry untouched


# ---------------------------------------------------------------------------
# CLI smoke (tier-1 budget)
# ---------------------------------------------------------------------------

def _run_cli(*argv):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "weaver.py")]
        + list(argv),
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO)


def test_cli_quick_smoke():
    r = _run_cli("--quick")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "exhausted" in r.stdout


def test_cli_plant_writes_artifact_and_replays(tmp_path):
    r = _run_cli("--scenario", "kv_pool", "--plant", "double_free",
                 "--preemption-bound", "2", "--out-dir", str(tmp_path))
    assert r.returncode == 1, r.stdout + r.stderr
    arts = sorted(tmp_path.glob("weaver_kv_pool_*.json"))
    assert arts, r.stdout + r.stderr
    r2 = _run_cli("--replay", str(arts[0]))
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert "REPRODUCED" in r2.stdout
