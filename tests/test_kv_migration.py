"""MigrateKV edge cases (ISSUE 16 satellite): BlockPool double-free of
a migrated-away block, a migration racing an in-flight decode dispatch,
partial-migration rollback (the destination frees its half-received
pages and names the failure), migrate dedup, and end-to-end token
parity between a migrated-in decode and a local generate."""
import json
import struct
import time

import pytest

from paddle_tpu.core import sanitizer
from paddle_tpu.core.flags import FLAGS
from paddle_tpu.observability import metrics
from paddle_tpu.serving.fleet import (FleetWorker, LocalTransport,
                                      M_MIGRATE, decode_call,
                                      encode_migrate)
from paddle_tpu.serving.generative import tiny_lm

CFG_KW = dict(vocab=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
              block_size=8, max_blocks=8, max_batch=4)


@pytest.fixture
def buffers_on():
    old = FLAGS.sanitizer
    FLAGS.sanitizer = "buffers"
    try:
        yield
    finally:
        FLAGS.sanitizer = old


def _pair(kv_blocks=24):
    """One prefill + one decode worker over LocalTransport."""
    cfg, params = tiny_lm(3, **CFG_KW)
    tr = LocalTransport()
    pw = FleetWorker("mp0", "prefill", cfg, params, kv_blocks=kv_blocks,
                     warm=False, transport=tr)
    dw = FleetWorker("md0", "decode", cfg, params, kv_blocks=kv_blocks,
                     warm=False, transport=tr)
    tr.register(pw)
    tr.register(dw)
    return tr, pw, dw


def _migrate_frame(pw, rid, prompt, max_new=4, tear=False):
    """Run a real prefill+export on ``pw`` and capture the MigrateKV
    frame a prefill worker would send (optionally torn mid-payload).
    The capture SWALLOWS the delivery — the destination never sees the
    original frame, so the test controls first delivery itself."""
    rep = None

    calls = []
    orig_call = pw.transport.call

    def capture(addr, method, payload, timeout=None):
        if method != M_MIGRATE:
            return orig_call(addr, method, payload, timeout=timeout)
        calls.append((method, b"".join(payload)
                      if isinstance(payload, (list, tuple))
                      else bytes(payload)))
        from paddle_tpu.serving.fleet import encode_call
        return encode_call({"ok": True, "dup": False, "blocks": [],
                            "epoch": 1})

    pw.transport.call = capture
    try:
        rep = pw._op_prefill({"op": "prefill", "dest": "local:md0",
                              "req": {"id": rid, "prompt": prompt,
                                      "max_new": max_new, "eos": None}})
    finally:
        pw.transport.call = orig_call
    assert rep["ok"]
    (method, frame), = calls
    assert method == M_MIGRATE
    if tear:
        frame = frame[:len(frame) - len(frame) // 4]
    return frame, rep


def test_double_free_of_migrated_block(buffers_on):
    """After a block set is migrated away and freed at the source, a
    second free of the same ids (two owners both believing they
    returned the pages) must raise the NAMED error and leave the free
    list uncorrupted — the next alloc must not hand out duplicates."""
    _, pw, _ = _pair()
    pool = pw.engine.pool
    blocks = pool.alloc(3)
    pool.free(blocks)          # the migrated-away free (legitimate)
    free0 = pool.free_blocks
    with pytest.raises(sanitizer.BufferLifetimeError,
                       match="kv_block"):
        pool.free(blocks)      # the double free
    assert pool.free_blocks == free0, "free list grew on a double free"
    seen = pool.alloc(free0)
    assert len(set(seen)) == free0, "duplicate ids after double free"
    pool.free(seen)


def test_migration_racing_inflight_dispatch(buffers_on):
    """export_blocks while a decode dispatch holds the KV pool (donated
    buffers in flight) must trip the epoch guard, not copy pages that
    are being rewritten under it."""
    _, pw, _ = _pair()
    eng = pw.engine
    blocks = eng.pool.alloc(2)
    eng._kv_guard.begin("decode", step=7)     # a dispatch owns the pool
    try:
        with pytest.raises(sanitizer.BufferLifetimeError,
                           match="dispatch in flight"):
            eng.export_blocks(blocks)
    finally:
        eng._kv_guard.rebind()
        eng.pool.free(blocks)
    # quiesced: the same export now succeeds
    blocks = eng.pool.alloc(2)
    kp, vp, epoch = eng.export_blocks(blocks)
    assert kp.shape[1] == 2 and vp.shape[1] == 2
    eng.pool.free(blocks)


def test_partial_migration_rollback():
    """A MigrateKV frame torn mid-payload must (a) come back as a named
    ok=false reply — BufferLifetimeError carrying kv_migration:<rid> —
    and (b) free the destination's half-received blocks (rollback), so
    a torn wire never strands pool capacity or serves garbage pages.
    Named regardless of FLAGS_sanitizer: a torn frame is data loss."""
    _, pw, dw = _pair()
    trips0 = metrics.counter("sanitizer_trips_total").value
    frame, _ = _migrate_frame(pw, "tear1", list(range(5, 17)),
                              tear=True)
    free0 = dw.engine.pool.free_blocks
    rep = decode_call(dw.handle(M_MIGRATE, memoryview(frame)))
    assert rep["ok"] is False
    assert rep["kind"] == "BufferLifetimeError"
    assert "kv_migration:tear1" in rep["error"]
    assert "rolled back" in rep["error"]
    assert dw.engine.pool.free_blocks == free0, \
        "torn migration stranded destination blocks"
    assert metrics.counter("sanitizer_trips_total").value == trips0 + 1
    with dw._flock:
        assert "tear1" not in dw._futures, \
            "torn migration admitted a request"


def test_migrate_dedup_and_parity():
    """The same migration delivered twice (hedge/retry replay) installs
    once — the second reply is dup=true and allocates nothing — and the
    migrated-in decode finishes with tokens bit-identical to a local
    generate of the same request."""
    _, pw, dw = _pair()
    prompt = [3, 9, 27, 17, 50, 8, 8, 1, 40]
    frame, prep = _migrate_frame(pw, "dup1", prompt, max_new=6)
    rep1 = decode_call(dw.handle(M_MIGRATE, memoryview(frame)))
    assert rep1["ok"] and not rep1["dup"]
    # the epoch handshake: the reply carries the destination guard's
    # post-install epoch (0 while the sanitizer is off — rebind only
    # advances the counter when FLAGS_sanitizer=buffers)
    assert rep1["epoch"] == dw.engine._kv_guard.epoch
    dups0 = metrics.counter("fleet_migration_dups_total").value
    rep2 = decode_call(dw.handle(M_MIGRATE, memoryview(frame)))
    assert rep2["ok"] and rep2["dup"]
    assert metrics.counter("fleet_migration_dups_total").value \
        == dups0 + 1
    got = dw._op_wait({"id": "dup1", "timeout": 120.0})
    assert got["done"]
    migrated_tokens = got["result"]["tokens"]
    assert migrated_tokens[0] == prep["first"]
    # reference: the same request decoded wholly on the decode worker
    dw._op_generate({"op": "generate",
                     "req": {"id": "ref1", "prompt": prompt,
                             "max_new": 6, "eos": None}})
    ref = dw._op_wait({"id": "ref1", "timeout": 120.0})
    assert ref["done"]
    assert migrated_tokens == ref["result"]["tokens"], \
        "migrated-in decode diverged from local generate"
    # both requests done: every migrated/generated block went home
    for _ in range(200):
        if dw.engine.pool.used_blocks == 0:
            break
        time.sleep(0.01)
    assert dw.engine.pool.used_blocks == 0
    dw.shutdown()
    pw.shutdown()


def test_migrate_geometry_mismatch_rejected():
    """A frame whose kv header disagrees with the destination engine's
    geometry is refused before any allocation (same-checkpoint fleets
    are an operator invariant; silent reshape would be garbage)."""
    _, pw, dw = _pair()
    frame, _ = _migrate_frame(pw, "geo1", list(range(9)))
    view = memoryview(bytes(frame))
    (hlen,) = struct.unpack("<I", view[:4])
    head = json.loads(bytes(view[4:4 + hlen]).decode())
    head["kv"]["n_heads"] = 5
    free0 = dw.engine.pool.free_blocks
    bad = encode_migrate(head, b"", b"")
    rep = decode_call(dw.handle(
        M_MIGRATE, memoryview(b"".join(bad) + bytes(view[4 + hlen:]))))
    assert rep["ok"] is False and rep["kind"] == "ValueError"
    assert "geometry" in rep["error"]
    assert dw.engine.pool.free_blocks == free0
