"""Loss + normalization op tests (cf. reference test_cross_entropy_op.py,
test_softmax_with_cross_entropy_op.py, test_batch_norm_op.py,
test_layer_norm_op.py)."""
import numpy as np

from op_test import OpTest

rng = np.random.RandomState(11)


def _softmax(x):
    e = np.exp(x - x.max(-1, keepdims=True))
    return e / e.sum(-1, keepdims=True)


def test_cross_entropy():
    probs = _softmax(rng.randn(5, 7).astype(np.float32))
    label = rng.randint(0, 7, (5, 1)).astype(np.int64)
    expected = -np.log(probs[np.arange(5), label[:, 0]])[:, None]

    class T(OpTest):
        op_type = "cross_entropy"
        inputs = {"X": probs, "Label": label}
        outputs = {"Y": expected.astype(np.float32)}

    T().check_output()


def test_cross_entropy_soft():
    probs = _softmax(rng.randn(4, 6).astype(np.float32))
    label = _softmax(rng.randn(4, 6).astype(np.float32))
    expected = -(label * np.log(probs)).sum(-1, keepdims=True)

    class T(OpTest):
        op_type = "cross_entropy"
        inputs = {"X": probs, "Label": label}
        attrs = {"soft_label": True}
        outputs = {"Y": expected.astype(np.float32)}

    T().check_output()


def test_softmax_with_cross_entropy():
    logits = rng.randn(5, 7).astype(np.float32)
    label = rng.randint(0, 7, (5, 1)).astype(np.int64)
    sm = _softmax(logits)
    loss = -np.log(sm[np.arange(5), label[:, 0]])[:, None]

    class T(OpTest):
        op_type = "softmax_with_cross_entropy"
        inputs = {"Logits": logits, "Label": label}
        outputs = {"Softmax": sm, "Loss": loss.astype(np.float32)}

    T().check_output(atol=1e-5)
    T().check_grad(["Logits"], output_names=["Loss"],
                   max_relative_error=0.01)


def test_softmax():
    x = rng.randn(4, 9).astype(np.float32)

    class T(OpTest):
        op_type = "softmax"
        inputs = {"X": x}
        outputs = {"Out": _softmax(x)}

    T().check_output()
    T().check_grad(["X"], max_relative_error=0.01)


def test_batch_norm_train():
    x = rng.randn(4, 3, 5, 5).astype(np.float32)
    scale = rng.uniform(0.5, 1.5, 3).astype(np.float32)
    bias = rng.randn(3).astype(np.float32)
    mean_in = np.zeros(3, np.float32)
    var_in = np.ones(3, np.float32)
    eps, momentum = 1e-5, 0.9
    mu = x.mean(axis=(0, 2, 3))
    var = x.var(axis=(0, 2, 3))
    y = (x - mu[None, :, None, None]) / np.sqrt(
        var[None, :, None, None] + eps)
    y = y * scale[None, :, None, None] + bias[None, :, None, None]

    class T(OpTest):
        op_type = "batch_norm"
        inputs = {"X": x, "Scale": scale, "Bias": bias,
                  "Mean": mean_in, "Variance": var_in}
        attrs = {"epsilon": eps, "momentum": momentum, "is_test": False,
                 "data_layout": "NCHW"}
        outputs = {"Y": y.astype(np.float32),
                   "MeanOut": (mean_in * momentum + mu * (1 - momentum)),
                   "VarianceOut": (var_in * momentum + var * (1 - momentum)),
                   "SavedMean": mu, "SavedVariance": var}

    T().check_output(atol=2e-4, rtol=2e-4)


def test_batch_norm_test_mode():
    x = rng.randn(4, 3, 2, 2).astype(np.float32)
    scale = np.ones(3, np.float32)
    bias = np.zeros(3, np.float32)
    mean_in = rng.randn(3).astype(np.float32)
    var_in = np.abs(rng.randn(3).astype(np.float32)) + 0.5
    eps = 1e-5
    y = (x - mean_in[None, :, None, None]) / np.sqrt(
        var_in[None, :, None, None] + eps)

    class T(OpTest):
        op_type = "batch_norm"
        inputs = {"X": x, "Scale": scale, "Bias": bias,
                  "Mean": mean_in, "Variance": var_in}
        attrs = {"epsilon": eps, "is_test": True, "data_layout": "NCHW"}
        outputs = {"Y": y.astype(np.float32)}

    T().check_output(atol=1e-4)


def test_layer_norm():
    x = rng.randn(3, 10).astype(np.float32)
    scale = rng.uniform(0.5, 1.5, 10).astype(np.float32)
    bias = rng.randn(10).astype(np.float32)
    eps = 1e-5
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    y = (x - mu) / np.sqrt(var + eps) * scale + bias

    class T(OpTest):
        op_type = "layer_norm"
        inputs = {"X": x, "Scale": scale, "Bias": bias}
        attrs = {"epsilon": eps, "begin_norm_axis": 1}
        outputs = {"Y": y.astype(np.float32),
                   "Mean": mu.reshape(3), "Variance": var.reshape(3)}

    T().check_output(atol=1e-4)
    T().check_grad(["X", "Scale", "Bias"], output_names=["Y"],
                   max_relative_error=0.02)


def test_sigmoid_cross_entropy_with_logits():
    x = rng.randn(4, 5).astype(np.float32)
    label = rng.uniform(0, 1, (4, 5)).astype(np.float32)
    sig = 1 / (1 + np.exp(-x))
    expected = -label * np.log(sig) - (1 - label) * np.log(1 - sig)

    class T(OpTest):
        op_type = "sigmoid_cross_entropy_with_logits"
        inputs = {"X": x, "Label": label}
        outputs = {"Out": expected.astype(np.float32)}

    T().check_output(atol=1e-5)
    T().check_grad(["X"], max_relative_error=0.01)


def test_huber_loss():
    x = rng.randn(6, 1).astype(np.float32)
    y = rng.randn(6, 1).astype(np.float32)
    d = 1.0
    r = y - x
    expected = np.where(np.abs(r) <= d, 0.5 * r * r,
                        d * (np.abs(r) - 0.5 * d))

    class T(OpTest):
        op_type = "huber_loss"
        inputs = {"X": x, "Y": y}
        attrs = {"delta": d}
        outputs = {"Out": expected.astype(np.float32), "Residual": r}

    T().check_output()
