"""Loss + normalization op tests (cf. reference test_cross_entropy_op.py,
test_softmax_with_cross_entropy_op.py, test_batch_norm_op.py,
test_layer_norm_op.py)."""
import numpy as np

import paddle_tpu.fluid as fluid
from op_test import OpTest

rng = np.random.RandomState(11)


def _softmax(x):
    e = np.exp(x - x.max(-1, keepdims=True))
    return e / e.sum(-1, keepdims=True)


def test_cross_entropy():
    probs = _softmax(rng.randn(5, 7).astype(np.float32))
    label = rng.randint(0, 7, (5, 1)).astype(np.int64)
    expected = -np.log(probs[np.arange(5), label[:, 0]])[:, None]

    class T(OpTest):
        op_type = "cross_entropy"
        inputs = {"X": probs, "Label": label}
        outputs = {"Y": expected.astype(np.float32)}

    T().check_output()


def test_cross_entropy_soft():
    probs = _softmax(rng.randn(4, 6).astype(np.float32))
    label = _softmax(rng.randn(4, 6).astype(np.float32))
    expected = -(label * np.log(probs)).sum(-1, keepdims=True)

    class T(OpTest):
        op_type = "cross_entropy"
        inputs = {"X": probs, "Label": label}
        attrs = {"soft_label": True}
        outputs = {"Y": expected.astype(np.float32)}

    T().check_output()


def test_softmax_with_cross_entropy():
    logits = rng.randn(5, 7).astype(np.float32)
    label = rng.randint(0, 7, (5, 1)).astype(np.int64)
    sm = _softmax(logits)
    loss = -np.log(sm[np.arange(5), label[:, 0]])[:, None]

    class T(OpTest):
        op_type = "softmax_with_cross_entropy"
        inputs = {"Logits": logits, "Label": label}
        outputs = {"Softmax": sm, "Loss": loss.astype(np.float32)}

    T().check_output(atol=1e-5)
    T().check_grad(["Logits"], output_names=["Loss"],
                   max_relative_error=0.01)


def test_softmax():
    x = rng.randn(4, 9).astype(np.float32)

    class T(OpTest):
        op_type = "softmax"
        inputs = {"X": x}
        outputs = {"Out": _softmax(x)}

    T().check_output()
    T().check_grad(["X"], max_relative_error=0.01)


def test_batch_norm_train():
    x = rng.randn(4, 3, 5, 5).astype(np.float32)
    scale = rng.uniform(0.5, 1.5, 3).astype(np.float32)
    bias = rng.randn(3).astype(np.float32)
    mean_in = np.zeros(3, np.float32)
    var_in = np.ones(3, np.float32)
    eps, momentum = 1e-5, 0.9
    mu = x.mean(axis=(0, 2, 3))
    var = x.var(axis=(0, 2, 3))
    y = (x - mu[None, :, None, None]) / np.sqrt(
        var[None, :, None, None] + eps)
    y = y * scale[None, :, None, None] + bias[None, :, None, None]

    class T(OpTest):
        op_type = "batch_norm"
        inputs = {"X": x, "Scale": scale, "Bias": bias,
                  "Mean": mean_in, "Variance": var_in}
        attrs = {"epsilon": eps, "momentum": momentum, "is_test": False,
                 "data_layout": "NCHW"}
        outputs = {"Y": y.astype(np.float32),
                   "MeanOut": (mean_in * momentum + mu * (1 - momentum)),
                   "VarianceOut": (var_in * momentum + var * (1 - momentum)),
                   "SavedMean": mu, "SavedVariance": var}

    T().check_output(atol=2e-4, rtol=2e-4)


def test_batch_norm_test_mode():
    x = rng.randn(4, 3, 2, 2).astype(np.float32)
    scale = np.ones(3, np.float32)
    bias = np.zeros(3, np.float32)
    mean_in = rng.randn(3).astype(np.float32)
    var_in = np.abs(rng.randn(3).astype(np.float32)) + 0.5
    eps = 1e-5
    y = (x - mean_in[None, :, None, None]) / np.sqrt(
        var_in[None, :, None, None] + eps)

    class T(OpTest):
        op_type = "batch_norm"
        inputs = {"X": x, "Scale": scale, "Bias": bias,
                  "Mean": mean_in, "Variance": var_in}
        attrs = {"epsilon": eps, "is_test": True, "data_layout": "NCHW"}
        outputs = {"Y": y.astype(np.float32)}

    T().check_output(atol=1e-4)


def test_layer_norm():
    x = rng.randn(3, 10).astype(np.float32)
    scale = rng.uniform(0.5, 1.5, 10).astype(np.float32)
    bias = rng.randn(10).astype(np.float32)
    eps = 1e-5
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    y = (x - mu) / np.sqrt(var + eps) * scale + bias

    class T(OpTest):
        op_type = "layer_norm"
        inputs = {"X": x, "Scale": scale, "Bias": bias}
        attrs = {"epsilon": eps, "begin_norm_axis": 1}
        outputs = {"Y": y.astype(np.float32),
                   "Mean": mu.reshape(3), "Variance": var.reshape(3)}

    T().check_output(atol=1e-4)
    T().check_grad(["X", "Scale", "Bias"], output_names=["Y"],
                   max_relative_error=0.02)


def test_sigmoid_cross_entropy_with_logits():
    x = rng.randn(4, 5).astype(np.float32)
    label = rng.uniform(0, 1, (4, 5)).astype(np.float32)
    sig = 1 / (1 + np.exp(-x))
    expected = -label * np.log(sig) - (1 - label) * np.log(1 - sig)

    class T(OpTest):
        op_type = "sigmoid_cross_entropy_with_logits"
        inputs = {"X": x, "Label": label}
        outputs = {"Out": expected.astype(np.float32)}

    T().check_output(atol=1e-5)
    T().check_grad(["X"], max_relative_error=0.01)


def test_huber_loss():
    x = rng.randn(6, 1).astype(np.float32)
    y = rng.randn(6, 1).astype(np.float32)
    d = 1.0
    r = y - x
    expected = np.where(np.abs(r) <= d, 0.5 * r * r,
                        d * (np.abs(r) - 0.5 * d))

    class T(OpTest):
        op_type = "huber_loss"
        inputs = {"X": x, "Y": y}
        attrs = {"delta": d}
        outputs = {"Out": expected.astype(np.float32), "Residual": r}

    T().check_output()


def test_lambda_rank_vs_numpy_oracle(prog_scope, exe):
    """LambdaRank surrogate vs a direct numpy computation on ragged
    queries (reference gserver LambdaCost semantics: NDCG-truncated
    pairwise weighting, ranks by current score)."""
    from paddle_tpu.core.lod import LoDTensor
    main, startup, scope = prog_scope
    score = fluid.layers.data(name="lr_s", shape=[1], lod_level=1,
                              dtype="float32")
    label = fluid.layers.data(name="lr_l", shape=[1], lod_level=1,
                              dtype="float32")
    out, ndcg = fluid.layers.lambda_rank(score, label, ndcg_num=3,
                                         return_ndcg=True)
    exe.run(startup)

    rng = np.random.RandomState(0)
    lens = [5, 3]
    svals = [rng.randn(l).astype(np.float32) for l in lens]
    lvals = [rng.randint(0, 3, l).astype(np.float32) for l in lens]

    def lodt(parts):
        flat = np.concatenate(parts)[:, None]
        offs = np.concatenate([[0], np.cumsum(lens)]).tolist()
        return LoDTensor(flat, [offs])

    got, nv = exe.run(main, feed={"lr_s": lodt(svals),
                                  "lr_l": lodt(lvals)},
                      fetch_list=[out, ndcg])
    got = np.ravel(np.asarray(got))
    nv = np.ravel(np.asarray(nv))

    def oracle(s, l, k=3):
        """Reference CostLayer.cpp calcGrad semantics: positions by
        GOLD sort (stable desc), natural-log discounts untruncated
        for pairs, maxDCG truncated at k."""
        t = len(s)
        pos = np.argsort(np.argsort(-l, kind="stable"))
        disc = 1.0 / np.log(pos + 2.0)
        gain = 2.0 ** l
        maxdcg = max(((np.sort(2.0 ** l - 1.0)[::-1][:k]) /
                      np.log(2.0 + np.arange(min(k, t)))).sum(), 1e-6)
        c = 0.0
        for i in range(t):
            for j in range(t):
                if l[i] > l[j]:
                    w = abs((gain[i] - gain[j]) * (disc[i] - disc[j])) \
                        / maxdcg
                    c += w * np.log1p(np.exp(-(s[i] - s[j])))
        return c

    def ndcg_oracle(s, l, k=3):
        top = np.argsort(-s, kind="stable")[:k]
        dcg = ((2.0 ** l[top] - 1.0) /
               np.log(2.0 + np.arange(len(top)))).sum()
        maxdcg = max(((np.sort(2.0 ** l - 1.0)[::-1][:k]) /
                      np.log(2.0 + np.arange(min(k, len(l))))).sum(),
                     1e-6)
        return dcg / maxdcg

    for q in range(2):
        np.testing.assert_allclose(got[q], oracle(svals[q], lvals[q]),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(nv[q], ndcg_oracle(svals[q],
                                                      lvals[q]),
                                   rtol=1e-4, atol=1e-4)


def test_lambda_rank_trains(prog_scope, exe):
    """Gradient flows: scores move toward the label ordering."""
    from paddle_tpu.core.lod import LoDTensor
    main, startup, scope = prog_scope
    x = fluid.layers.data(name="lt_x", shape=[4], lod_level=1,
                          dtype="float32")
    label = fluid.layers.data(name="lt_l", shape=[1], lod_level=1,
                              dtype="float32")
    score = fluid.layers.fc(x, size=1)
    cost = fluid.layers.mean(fluid.layers.lambda_rank(score, label))
    fluid.optimizer.Adam(learning_rate=0.05).minimize(cost)
    exe.run(startup)
    rng = np.random.RandomState(1)
    lens = [6, 6]
    feats = np.concatenate([rng.randn(6, 4), rng.randn(6, 4)]).astype(
        np.float32)
    rel = (feats[:, 0] > 0).astype(np.float32)[:, None]  # learnable
    offs = [0, 6, 12]
    feed = {"lt_x": LoDTensor(feats, [offs]),
            "lt_l": LoDTensor(rel, [offs])}
    ls = []
    for _ in range(60):
        l, = exe.run(main, feed=feed, fetch_list=[cost])
        ls.append(float(np.ravel(l)[0]))
    assert ls[-1] < ls[0] * 0.3, (ls[0], ls[-1])
