"""True sparse v2 inputs (round-5 VERDICT #7).

Reference parameter/Argument.h keeps sparse input slots as row
indices end-to-end; rounds 2-4 densified them at the feeder.  Now a
``sparse_binary_vector(d)`` / ``sparse_float_vector(d)`` column feeds
as a ragged index (or (index, value)) list and ``layer.fc`` consumes
it through lookup_table + sequence_pool — the dense [N, d] matrix
never materializes, so d = 1,000,000 trains on a laptop-sized host.
"""
import numpy as np

import paddle_tpu.v2 as paddle

DIM = 1_000_000


def test_v2_million_dim_sparse_binary_trains():
    paddle.init(trainer_count=1)
    x = paddle.layer.data(
        name="ctr_x", type=paddle.data_type.sparse_binary_vector(DIM))
    y = paddle.layer.data(name="ctr_y",
                          type=paddle.data_type.dense_vector(1))
    pred = paddle.layer.fc(input=x, size=1)
    cost = paddle.layer.mse_cost(pred, y)
    params = paddle.parameters.create(cost)
    # the fc weight is the full [DIM, 1] table — created once, sparse
    # UPDATES would come from the distributed table path; what must
    # never exist is a dense [batch, DIM] activation
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=0.1))
    rng = np.random.RandomState(0)
    # the label depends only on whether feature 123 is present —
    # learnable from ~6 hot indices per sample out of 1M
    def make_sample():
        ids = rng.randint(0, DIM, size=5).tolist()
        hot = rng.randint(2)
        if hot:
            ids.append(123)
        return (sorted(set(ids)), [float(hot)])

    data = [make_sample() for _ in range(256)]

    def reader():
        for _ in range(15):
            yield data

    costs = []
    trainer.train(reader, num_passes=1,
                  event_handler=lambda e: costs.append(e.cost)
                  if isinstance(e, paddle.event.EndIteration) else None)
    assert costs[-1] < costs[0] * 0.6, (costs[0], costs[-1])


def test_v2_sparse_float_vector_value_weighting():
    """sparse_float_vector: looked-up rows scale by the fed values —
    pinned against the dense oracle on a small dim."""
    paddle.init(trainer_count=1)
    dim = 32
    x = paddle.layer.data(
        name="sfv_x", type=paddle.data_type.sparse_float_vector(dim))
    pred = paddle.layer.fc(input=x, size=3, bias_attr=False,
                           name="sfv_fc")
    params = paddle.parameters.create(pred)
    w = np.random.RandomState(1).randn(dim, 3).astype(np.float32)
    params.set("_sfv_fc.w0", w)
    rows = [([(2, 0.5), (7, -1.5)],), ([(0, 2.0)],)]
    out = paddle.infer(output_layer=pred, parameters=params, input=rows)
    dense = np.zeros((2, dim), np.float32)
    dense[0, 2], dense[0, 7], dense[1, 0] = 0.5, -1.5, 2.0
    np.testing.assert_allclose(np.asarray(out), dense @ w, atol=1e-4,
                               rtol=1e-4)
