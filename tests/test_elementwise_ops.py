"""Elementwise / broadcast op tests (cf. reference
test_elementwise_add_op.py etc.)."""
import numpy as np
import pytest

from op_test import OpTest

rng = np.random.RandomState(42)


def _mk(op_type, fn, x, y, axis=-1):
    class T(OpTest):
        pass

    T.op_type = op_type
    T.inputs = {"X": x, "Y": y}
    T.attrs = {"axis": axis}
    # compute expected with numpy broadcast on aligned axes
    yb = y
    if y.shape != x.shape:
        ax = axis if axis >= 0 else x.ndim - y.ndim
        new_shape = [1] * ax + list(y.shape) + \
            [1] * (x.ndim - ax - y.ndim)
        yb = y.reshape(new_shape)
    T.outputs = {"Out": fn(x.astype(np.float64),
                           yb.astype(np.float64)).astype(x.dtype)}
    return T()


CASES = [
    ("elementwise_add", np.add),
    ("elementwise_sub", np.subtract),
    ("elementwise_mul", np.multiply),
    ("elementwise_div", np.divide),
    ("elementwise_max", np.maximum),
    ("elementwise_min", np.minimum),
]


@pytest.mark.parametrize("op_type,fn", CASES)
def test_same_shape(op_type, fn):
    x = rng.uniform(0.5, 2, (3, 4)).astype(np.float32)
    y = rng.uniform(0.5, 2, (3, 4)).astype(np.float32)
    t = _mk(op_type, fn, x, y)
    t.check_output()
    t.check_grad(["X", "Y"])


@pytest.mark.parametrize("op_type,fn", [("elementwise_add", np.add),
                                        ("elementwise_mul", np.multiply)])
def test_broadcast_axis(op_type, fn):
    x = rng.uniform(0.5, 2, (2, 3, 4)).astype(np.float32)
    y = rng.uniform(0.5, 2, (3,)).astype(np.float32)
    t = _mk(op_type, fn, x, y, axis=1)
    t.check_output()
    t.check_grad(["X", "Y"])


def test_broadcast_trailing():
    x = rng.uniform(0.5, 2, (2, 3)).astype(np.float32)
    y = rng.uniform(0.5, 2, (3,)).astype(np.float32)
    t = _mk("elementwise_add", np.add, x, y, axis=-1)
    t.check_output()
    t.check_grad(["X", "Y"])


def test_pow():
    x = rng.uniform(0.5, 2, (3, 4)).astype(np.float32)
    y = np.full((3, 4), 2.0, np.float32)
    t = _mk("elementwise_pow", np.power, x, y)
    t.check_output()
    t.check_grad(["X"])
