"""End-to-end ragged-sequence models (reference book tests:
understand_sentiment stacked-lstm, machine_translation)."""
import numpy as np

import paddle_tpu.fluid as fluid


def test_stacked_dynamic_lstm(prog_scope, exe):
    from paddle_tpu.models.stacked_dynamic_lstm import get_model
    main, startup, scope = prog_scope
    loss, feeds, (acc,) = get_model(dict_dim=100, emb_dim=16,
                                    hidden_dim=32, stacked_num=2,
                                    learning_rate=5e-3)
    exe.run(startup)
    feeder = fluid.DataFeeder(feeds, program=main)
    rng = np.random.RandomState(0)
    ls = []
    for _ in range(40):
        batch = []
        for _ in range(16):
            y = rng.randint(0, 2)
            L = rng.randint(3, 12)
            toks = rng.randint(0, 50, L) + (50 if y else 0)
            batch.append(([int(t) for t in toks], [y]))
        l, = exe.run(main, feed=feeder.feed(batch), fetch_list=[loss])
        ls.append(float(l[0]))
    assert ls[-1] < 0.35, (ls[0], ls[-1])


def test_machine_translation_copy_task(prog_scope, exe):
    from paddle_tpu.models.machine_translation import get_model
    main, startup, scope = prog_scope
    loss, feeds, _ = get_model(src_dict_dim=60, trg_dict_dim=60,
                               emb_dim=32, hidden_dim=32,
                               learning_rate=5e-3)
    exe.run(startup)
    feeder = fluid.DataFeeder(feeds, program=main)
    rng = np.random.RandomState(0)
    ls = []
    for _ in range(60):
        batch = []
        for _ in range(8):
            L = rng.randint(3, 10)
            src = rng.randint(2, 58, L).tolist()
            trg = [1] + src[:-1]
            batch.append((src, trg, src))
        l, = exe.run(main, feed=feeder.feed(batch), fetch_list=[loss])
        ls.append(float(l[0]))
    # steady convergence on the copy task
    assert ls[-1] < ls[0] - 0.25, (ls[0], ls[-1])
